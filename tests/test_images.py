"""Images subsystem tests (model: reference ConvolverSuite/PoolerSuite/
WindowerSuite/HogExtractorSuite + golden checks vs scipy, mirroring the
reference's scipy golden-file strategy, SURVEY.md §4)."""

import numpy as np
import pytest
from scipy import signal

from keystone_tpu.data import Dataset
from keystone_tpu.ops.images import (
    CenterCornerPatcher,
    Convolver,
    DaisyExtractor,
    FisherVector,
    GrayScaler,
    HogExtractor,
    ImageVectorizer,
    LCSExtractor,
    PixelScaler,
    Pooler,
    RandomPatcher,
    SIFTExtractor,
    ScalaGMMFisherVectorEstimator,
    SymmetricRectifier,
    Windower,
)
from keystone_tpu.ops.learning.clustering import GaussianMixtureModel


def rand_image(rng, x=10, y=12, c=3):
    return rng.random((x, y, c)).astype(np.float32)


class TestConvolver:
    def test_matches_scipy_correlation(self):
        """Un-normalized, un-whitened Convolver == per-channel summed valid
        cross-correlation (the reference's scipy golden-file test)."""
        rng = np.random.default_rng(0)
        img = rand_image(rng, 8, 9, 2)
        k = 3
        filters = rng.random((4, k, k, 2)).astype(np.float32)

        conv = Convolver.build(filters, normalize_patches=False)
        out = np.asarray(conv.apply(img))

        expected = np.zeros((8 - k + 1, 9 - k + 1, 4))
        for f in range(4):
            for c in range(2):
                expected[:, :, f] += signal.correlate(
                    img[:, :, c], filters[f, :, :, c], mode="valid"
                )
        np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)

    def test_patch_normalization(self):
        """normalize_patches matches the reference Stats.normalizeRows math."""
        rng = np.random.default_rng(1)
        img = rand_image(rng, 6, 6, 1)
        k = 3
        filters = rng.random((2, k, k, 1)).astype(np.float32)
        var_constant = 10.0

        conv = Convolver.build(filters, normalize_patches=True, var_constant=var_constant)
        out = np.asarray(conv.apply(img))

        fmat = filters.reshape(2, -1)
        for ox in range(4):
            for oy in range(4):
                patch = img[ox : ox + k, oy : oy + k, 0].reshape(-1)
                centered = patch - patch.mean()
                sd = np.sqrt(centered @ centered / (len(patch) - 1) + var_constant)
                norm_patch = centered / sd
                np.testing.assert_allclose(
                    out[ox, oy], fmat @ norm_patch, rtol=1e-4, atol=1e-5
                )

    def test_batch_matches_single(self):
        rng = np.random.default_rng(2)
        imgs = rng.random((3, 7, 7, 2)).astype(np.float32)
        filters = rng.random((5, 3, 3, 2)).astype(np.float32)
        conv = Convolver.build(filters)
        batch = np.asarray(conv.batch_apply(Dataset.of(imgs)).array)
        for i in range(3):
            np.testing.assert_allclose(
                batch[i], np.asarray(conv.apply(imgs[i])), rtol=1e-4, atol=1e-5
            )


class TestPooler:
    def test_sum_pooling_reference_semantics(self):
        """Pool k covers [k·stride, k·stride+poolSize) truncated at the edge
        (Pooler.scala:39-64)."""
        rng = np.random.default_rng(3)
        img = rand_image(rng, 9, 9, 2)
        stride, pool_size = 3, 4
        out = np.asarray(Pooler(stride, pool_size).apply(img))

        start = pool_size // 2
        npools = int(np.ceil((9 - start) / stride))
        assert out.shape == (npools, npools, 2)
        for px in range(npools):
            for py in range(npools):
                xs = slice(px * stride, min(px * stride + pool_size, 9))
                ys = slice(py * stride, min(py * stride + pool_size, 9))
                np.testing.assert_allclose(
                    out[px, py], img[xs, ys, :].sum(axis=(0, 1)), rtol=1e-5
                )

    def test_max_pooling_with_pixel_function(self):
        rng = np.random.default_rng(4)
        img = rand_image(rng, 8, 8, 1) - 0.5
        out = np.asarray(Pooler(2, 2, pixel_function=abs, pool_function="max").apply(img))
        expected = np.abs(img[:8, :8, 0]).reshape(4, 2, 4, 2).max(axis=(1, 3))
        np.testing.assert_allclose(out[:, :, 0], expected, rtol=1e-5)


class TestWindowerAndRectifier:
    def test_windower_contents(self):
        rng = np.random.default_rng(5)
        img = rand_image(rng, 6, 6, 2)
        wins = np.asarray(Windower(2, 4).apply(img))
        assert wins.shape == (4, 4, 4, 2)  # 2x2 grid of windows, x-major
        np.testing.assert_allclose(wins[0], img[0:4, 0:4, :])
        np.testing.assert_allclose(wins[1], img[0:4, 2:6, :])  # y moves fastest
        np.testing.assert_allclose(wins[2], img[2:6, 0:4, :])

    def test_windower_batch_flattens(self):
        rng = np.random.default_rng(6)
        data = Dataset.of(rng.random((3, 6, 6, 1)).astype(np.float32))
        out = Windower(2, 4).batch_apply(data)
        assert out.n == 12

    def test_symmetric_rectifier(self):
        img = np.array([[[0.5, -0.3]]], dtype=np.float32)
        out = np.asarray(SymmetricRectifier(alpha=0.1).apply(img))
        np.testing.assert_allclose(out[0, 0], [0.4, 0.0, 0.0, 0.2], atol=1e-6)


class TestPlumbing:
    def test_grayscale_and_pixel_scaler(self):
        img = np.full((2, 2, 3), 255.0, dtype=np.float32)
        gray = np.asarray(GrayScaler().apply(PixelScaler().apply(img)))
        # The reference's exact MATLAB NTSC weights sum to 0.9999, not 1
        # (ImageUtils.toGrayScale: 0.2989 + 0.5870 + 0.1140).
        np.testing.assert_allclose(gray, np.full((2, 2, 1), 0.9999), rtol=1e-5)

    def test_vectorizer(self):
        rng = np.random.default_rng(7)
        img = rand_image(rng, 3, 4, 2)
        v = np.asarray(ImageVectorizer().apply(img))
        np.testing.assert_allclose(v, img.reshape(-1))

    def test_center_corner_patcher(self):
        rng = np.random.default_rng(8)
        img = rand_image(rng, 8, 8, 1)
        patches = np.asarray(CenterCornerPatcher(4, 4, horizontal_flips=False).apply(img))
        assert patches.shape == (5, 4, 4, 1)
        np.testing.assert_allclose(patches[0], img[0:4, 0:4, :])
        np.testing.assert_allclose(patches[4], img[2:6, 2:6, :])  # center

        flipped = CenterCornerPatcher(4, 4, horizontal_flips=True)
        out = flipped.batch_apply(Dataset.of(img[None]))
        assert out.n == 10

    def test_random_patcher(self):
        rng = np.random.default_rng(9)
        data = Dataset.of(rng.random((2, 10, 10, 1)).astype(np.float32))
        out = RandomPatcher(num_patches=3, patch_size_x=4, patch_size_y=4).batch_apply(data)
        assert out.n == 6
        assert np.asarray(out.array).shape == (6, 4, 4, 1)


class TestExtractors:
    def test_hog_shape_and_bounds(self):
        rng = np.random.default_rng(10)
        img = rand_image(rng, 24, 24, 3)
        feats = np.asarray(HogExtractor(bin_size=4).apply(img))
        # 6x6 cells -> 4x4 feature cells
        assert feats.shape == (16, 32)
        assert np.all(feats >= 0.0)
        assert np.all(feats[:, :18] <= 0.4 + 1e-6)  # 0.5 * 4 * clip(0.2)
        np.testing.assert_allclose(feats[:, 31], 0.0)
        assert feats.sum() > 0

    def test_hog_flat_image_is_zero(self):
        img = np.full((16, 16, 3), 0.5, dtype=np.float32)
        feats = np.asarray(HogExtractor(bin_size=4).apply(img))
        np.testing.assert_allclose(feats, 0.0, atol=1e-5)

    def test_daisy_shape_and_normalization(self):
        rng = np.random.default_rng(11)
        img = rand_image(rng, 40, 44, 1)
        d = DaisyExtractor()
        feats = np.asarray(d.apply(img))
        nx = len(range(16, 40 - 16, 4))
        ny = len(range(16, 44 - 16, 4))
        assert feats.shape == (d.H * (d.T * d.Q + 1), nx * ny)
        # Each H-block is L2-normalized (or zero).
        norms = np.linalg.norm(feats[: d.H, :], axis=0)
        assert np.all((norms < 1.0 + 1e-4))

    def test_lcs_mean_matches_box_filter(self):
        rng = np.random.default_rng(12)
        img = rand_image(rng, 32, 32, 3)
        s = 4
        lcs = LCSExtractor(stride=5, stride_start=12, sub_patch_size=s)
        feats = np.asarray(lcs.apply(img))
        xs = list(range(12, 32 - 12, 5))
        assert feats.shape[1] == len(xs) ** 2
        # First row = channel-0 mean at neighbor offset (start, start) of the
        # first keypoint.
        start = -2 * s + s // 2 - 1
        kx, ky = xs[0] + start, xs[0] + start
        pad_lo = (s - 1) // 2
        pad_hi = s - 1 - pad_lo
        region = img[kx - pad_lo : kx + pad_hi + 1, ky - pad_lo : ky + pad_hi + 1, 0]
        np.testing.assert_allclose(feats[0, 0], region.mean(), rtol=1e-4)

    def test_sift_shape_and_range(self):
        rng = np.random.default_rng(13)
        img = rand_image(rng, 48, 48, 1)
        feats = np.asarray(SIFTExtractor(step_size=4, bin_size=4, scales=2).apply(img))
        assert feats.shape[0] == 128
        assert feats.shape[1] > 0
        assert np.all(feats >= 0) and np.all(feats <= 255)

    def test_sift_batch_matches_single(self):
        rng = np.random.default_rng(14)
        imgs = rng.random((2, 32, 32, 1)).astype(np.float32)
        ext = SIFTExtractor(step_size=6, bin_size=4, scales=1)
        batch = np.asarray(ext.batch_apply(Dataset.of(imgs)).array)
        np.testing.assert_allclose(
            batch[0], np.asarray(ext.apply(imgs[0])), rtol=1e-4, atol=1e-4
        )


class TestFisherVector:
    def _gmm(self, d=4, k=3, seed=15):
        rng = np.random.default_rng(seed)
        means = rng.random((d, k))
        variances = 0.5 + rng.random((d, k))
        weights = rng.random(k)
        weights /= weights.sum()
        return GaussianMixtureModel(means, variances, weights)

    def test_fv_matches_manual(self):
        gmm = self._gmm()
        rng = np.random.default_rng(16)
        x = rng.random((4, 10)).astype(np.float32)  # d x numDescriptors

        fv = np.asarray(FisherVector(gmm).apply(x))
        assert fv.shape == (4, 6)

        q = np.asarray(gmm.posteriors(x.T))  # (n, k)
        np.testing.assert_allclose(q.sum(axis=1), 1.0, rtol=1e-4)
        means, variances = np.asarray(gmm.means), np.asarray(gmm.variances)
        weights = np.asarray(gmm.weights)
        n = x.shape[1]
        s0 = q.mean(axis=0)
        s1 = (x @ q) / n
        s2 = ((x * x) @ q) / n
        fv1 = (s1 - means * s0) / (np.sqrt(variances) * np.sqrt(weights))
        fv2 = (s2 - 2 * means * s1 + (means**2 - variances) * s0) / (
            variances * np.sqrt(2 * weights)
        )
        np.testing.assert_allclose(fv, np.hstack([fv1, fv2]), rtol=1e-4, atol=1e-5)

    def test_estimator_end_to_end(self):
        rng = np.random.default_rng(17)
        mats = [rng.random((4, 30)).astype(np.float32) for _ in range(3)]
        est = ScalaGMMFisherVectorEstimator(k=2)
        fv = est.fit(Dataset.of(mats))
        out = fv.apply(mats[0])
        assert np.asarray(out).shape == (4, 4)
