"""Loader + native data-plane tests (model: reference ImageNetLoaderSuite,
VOCLoaderSuite — which use small real archives in test resources; here the
archives are generated on the fly)."""

import io
import json
import os
import tarfile

import numpy as np
import pytest

from keystone_tpu import native
from keystone_tpu.data import Dataset
from keystone_tpu.data.loaders import (
    csv_data_loader,
    decode_image_bytes,
    load_amazon_reviews,
    load_imagenet,
    load_voc,
)


def _ppm_bytes(arr: np.ndarray) -> bytes:
    h, w, c = arr.shape
    assert c == 3
    return b"P6\n%d %d\n255\n" % (w, h) + arr.astype(np.uint8).tobytes()


def _pgm_bytes(arr: np.ndarray) -> bytes:
    h, w = arr.shape
    return b"P5\n%d %d\n255\n" % (w, h) + arr.astype(np.uint8).tobytes()


class TestNative:
    def test_csv_parse_matches_numpy(self, tmp_path):
        rng = np.random.default_rng(0)
        mat = rng.normal(size=(20, 7))
        p = tmp_path / "m.csv"
        np.savetxt(p, mat, delimiter=",")
        out = np.asarray(csv_data_loader(str(p)).array)
        np.testing.assert_allclose(out, mat, rtol=1e-6)

    def test_native_pnm_roundtrip(self):
        rng = np.random.default_rng(1)
        img = rng.integers(0, 256, size=(5, 7, 3)).astype(np.uint8)
        decoded = decode_image_bytes(_ppm_bytes(img))
        assert decoded is not None
        np.testing.assert_array_equal(decoded, img.astype(np.float32))

    def test_native_pgm(self):
        img = np.arange(12).reshape(3, 4).astype(np.uint8)
        decoded = decode_image_bytes(_pgm_bytes(img))
        assert decoded is not None
        assert decoded.shape == (3, 4, 1)
        np.testing.assert_array_equal(decoded[:, :, 0], img.astype(np.float32))

    def test_png_via_pil(self):
        from PIL import Image

        rng = np.random.default_rng(2)
        img = rng.integers(0, 256, size=(6, 6, 3)).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, format="PNG")
        decoded = decode_image_bytes(buf.getvalue())
        np.testing.assert_array_equal(decoded, img.astype(np.float32))


class TestAmazonLoader:
    def test_threshold_labels(self, tmp_path):
        p = tmp_path / "reviews.json"
        recs = [
            {"overall": 5.0, "reviewText": "great product"},
            {"overall": 1.0, "reviewText": "terrible"},
            {"overall": 4.0, "reviewText": "pretty good"},
        ]
        p.write_text("\n".join(json.dumps(r) for r in recs))
        data = load_amazon_reviews(str(p), threshold=3.5)
        assert data.data.to_list() == ["great product", "terrible", "pretty good"]
        np.testing.assert_array_equal(data.labels.to_numpy(), [1, 0, 1])


def _make_tar(path, entries):
    with tarfile.open(path, "w") as tf:
        for name, payload in entries:
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tf.addfile(info, io.BytesIO(payload))


class TestImageArchives:
    def test_imagenet_loader(self, tmp_path):
        rng = np.random.default_rng(3)
        imgs = {
            "n01/a.ppm": rng.integers(0, 256, size=(4, 4, 3)).astype(np.uint8),
            "n01/b.ppm": rng.integers(0, 256, size=(4, 4, 3)).astype(np.uint8),
            "n02/c.ppm": rng.integers(0, 256, size=(4, 4, 3)).astype(np.uint8),
        }
        tar = tmp_path / "data.tar"
        _make_tar(tar, [(k, _ppm_bytes(v)) for k, v in imgs.items()])
        labels = tmp_path / "labels.txt"
        labels.write_text("n01 0\nn02 1\n")

        out = load_imagenet(str(tar), str(labels)).to_list()
        assert len(out) == 3
        by_name = {li.filename: li for li in out}
        assert by_name["n01/a.ppm"].label == 0
        assert by_name["n02/c.ppm"].label == 1
        np.testing.assert_array_equal(by_name["n01/b.ppm"].image, imgs["n01/b.ppm"])

    def test_voc_loader_multilabel(self, tmp_path):
        rng = np.random.default_rng(4)
        img = rng.integers(0, 256, size=(6, 5, 3)).astype(np.uint8)
        tar = tmp_path / "voc.tar"
        _make_tar(tar, [("VOC2007/img1.ppm", _ppm_bytes(img))])
        csv = tmp_path / "labels.csv"
        # Filenames are full tar entry paths, as in the reference's
        # voclabels.csv (VOCLoader.scala:40 keys labelsMap by entry name).
        csv.write_text(
            "header,class,x,y,filename\n"
            'r,3,_,_,"VOC2007/img1.ppm"\n'
            'r,7,_,_,"VOC2007/img1.ppm"\n'
            'r,1,_,_,"VOC2007/other.ppm"\n'
        )
        out = load_voc(str(tar), str(csv)).to_list()
        assert len(out) == 1
        np.testing.assert_array_equal(out[0].labels, [2, 6])  # 1-based -> 0-based


class TestCsvRobustness:
    def test_ragged_csv_raises(self, tmp_path):
        p = tmp_path / "ragged.csv"
        p.write_text("1,2\n3,4,5,6\n")
        with pytest.raises(ValueError):
            csv_data_loader(str(p))

    def test_float64_precision_preserved(self, tmp_path):
        p = tmp_path / "prec.csv"
        p.write_text("1.23456789012345,2\n3,4\n")
        out = np.asarray(csv_data_loader(str(p)).array)
        assert out[0, 0] == 1.23456789012345

    def test_tab_separated(self, tmp_path):
        p = tmp_path / "tabs.csv"
        p.write_text("1\t2\t3\t4\n5\t6\t7\t8\n")
        out = np.asarray(csv_data_loader(str(p)).array)
        np.testing.assert_array_equal(out, [[1, 2, 3, 4], [5, 6, 7, 8]])

    def test_16bit_pnm_falls_back_to_pil(self):
        img = np.array([[65535, 0]], dtype=">u2")
        data = b"P5\n2 1\n65535\n" + img.tobytes()
        decoded = decode_image_bytes(data)
        # PIL handles 16-bit PGM; native decoder must not return garbage.
        if decoded is not None:
            assert decoded.shape[:2] == (1, 2)
            assert decoded.max() > 255  # 16-bit range preserved by PIL


class TestShapeBucketing:
    def test_crop_to_multiple_center(self):
        from keystone_tpu.utils.images import crop_to_multiple

        img = np.arange(13 * 18 * 3, dtype=np.float32).reshape(13, 18, 3)
        out = crop_to_multiple(img, 8)
        assert out.shape == (8, 16, 3)
        # Center crop: rows [2, 10), cols [1, 17).
        np.testing.assert_array_equal(out, img[2:10, 1:17])

    def test_exact_multiple_unchanged(self):
        from keystone_tpu.utils.images import crop_to_multiple

        img = np.zeros((16, 24, 3), dtype=np.float32)
        assert crop_to_multiple(img, 8) is img

    def test_tiny_image_unchanged(self):
        from keystone_tpu.utils.images import crop_to_multiple

        img = np.zeros((5, 6, 3), dtype=np.float32)
        assert crop_to_multiple(img, 8).shape == (5, 6, 3)

    def test_tar_loaders_bucket_shapes(self, tmp_path):
        import io, tarfile
        from keystone_tpu.data.loaders import load_imagenet

        def ppm_bytes(h, w):
            hdr = f"P6\n{w} {h}\n255\n".encode()
            return hdr + bytes(h * w * 3)

        tar = tmp_path / "n01.tar"
        with tarfile.open(tar, "w") as tf:
            for i, (h, w) in enumerate([(13, 18), (40, 40)]):
                data = ppm_bytes(h, w)
                info = tarfile.TarInfo(f"n01/img{i}.ppm")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
        labels = tmp_path / "labels.txt"
        labels.write_text("n01 3\n")
        out = load_imagenet(str(tmp_path), str(labels)).to_list()
        shapes = sorted(x.image.shape for x in out)
        # 13x18 -> 8x16; 40x40 stays (exact multiple).
        assert shapes == [(8, 16, 3), (40, 40, 3)]

    def test_one_axis_below_multiple_still_crops_other(self):
        from keystone_tpu.utils.images import crop_to_multiple

        img = np.zeros((7, 1999, 3), dtype=np.float32)
        assert crop_to_multiple(img, 8).shape == (7, 1992, 3)
