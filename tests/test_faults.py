"""Fault-injection harness + retry policy (ISSUE 5 tentpole): plans are
deterministic and replayable, site counters are exact, the env wiring
works, and the backoff schedule is a pure function of its seed.

These tests exercise the PLAN MACHINERY with synthetic site names ("s",
"a", ...) rather than the real instrumented sites, so the fault-site
registry lint is opted out for this file only.
"""
# lint: disable=fault-site

import os

import numpy as np
import pytest

from keystone_tpu.utils import faults
from keystone_tpu.utils.faults import FaultPlan, FaultRule, RetryPolicy


class TestFaultPlan:
    def test_call_indexed_rule_fires_exactly_listed_calls(self):
        plan = FaultPlan([FaultRule("s", "error", calls=[1, 3])])
        with plan:
            faults.maybe_fail("s")  # call 0
            with pytest.raises(faults.FaultError):
                faults.maybe_fail("s")  # call 1
            faults.maybe_fail("s")  # call 2
            with pytest.raises(faults.FaultError):
                faults.maybe_fail("s")  # call 3
            faults.maybe_fail("s")  # call 4
        assert plan.calls_seen("s") == 5
        assert [c for _, c, _ in plan.log] == [1, 3]

    def test_sites_count_independently(self):
        plan = FaultPlan([FaultRule("a", "error", calls=[0])])
        with plan:
            faults.maybe_fail("b")  # does not advance or trip site a
            with pytest.raises(faults.FaultError):
                faults.maybe_fail("a")
        assert plan.calls_seen("a") == 1 and plan.calls_seen("b") == 1

    def test_probabilistic_rule_replayable(self):
        def run():
            plan = FaultPlan(
                [FaultRule("s", "error", calls=None, p=0.5)], seed=7
            )
            hits = []
            with plan:
                for i in range(32):
                    try:
                        faults.maybe_fail("s")
                        hits.append(0)
                    except faults.FaultError:
                        hits.append(1)
            return hits

        first, second = run(), run()
        assert first == second  # same seed -> identical injection trace
        assert 0 < sum(first) < 32

    def test_count_bounds_probabilistic_rule(self):
        plan = FaultPlan([FaultRule("s", "error", p=1.0, count=2)])
        errors = 0
        with plan:
            for _ in range(5):
                try:
                    faults.maybe_fail("s")
                except faults.FaultError:
                    errors += 1
        assert errors == 2

    def test_latency_rule_sleeps(self):
        import time

        plan = FaultPlan(
            [FaultRule("s", "latency", calls=[0], latency_s=0.05)]
        )
        with plan:
            t0 = time.perf_counter()
            faults.maybe_fail("s")
            assert time.perf_counter() - t0 >= 0.045

    def test_corrupt_rule_flips_one_byte_deterministically(self):
        arr = np.arange(8, dtype=np.float32)
        plan = FaultPlan([FaultRule("s", "corrupt", calls=[0])])
        with plan:
            out = faults.corrupt_array("s", arr)
            clean = faults.corrupt_array("s", arr)  # call 1: no rule
        assert not np.array_equal(out, arr)
        np.testing.assert_array_equal(clean, arr)
        # The original buffer is never mutated in place.
        np.testing.assert_array_equal(arr, np.arange(8, dtype=np.float32))

    def test_error_rules_do_not_shift_corrupt_counters(self):
        # maybe_fail and corrupt_array at one site keep separate call
        # counters, so composing rules never renumbers either sequence.
        plan = FaultPlan([
            FaultRule("s", "error", calls=[0]),
            FaultRule("s", "corrupt", calls=[0]),
        ])
        arr = np.ones(4, np.float32)
        with plan:
            out = faults.corrupt_array("s", arr)  # corrupt call 0: fires
            with pytest.raises(faults.FaultError):
                faults.maybe_fail("s")  # error call 0: fires
        assert not np.array_equal(out, arr)

    def test_no_plan_hooks_are_noops(self):
        faults.uninstall()
        faults.maybe_fail("anything")
        arr = np.ones(3)
        assert faults.corrupt_array("anything", arr) is arr

    def test_nested_install_rejected(self):
        with FaultPlan([FaultRule("s", "error", calls=[0])]):
            with pytest.raises(RuntimeError, match="already installed"):
                faults.install(FaultPlan([FaultRule("t", "error",
                                                    calls=[0])]))

    def test_env_plan_roundtrip(self):
        plan = FaultPlan(
            [FaultRule("shard.load", "error", calls=[2], exc="OSError"),
             FaultRule("prefetch.read", "corrupt", calls=[1])],
            seed=3,
        )
        import json

        restored = FaultPlan.from_json(json.dumps(plan.to_dict()))
        assert restored.seed == 3
        assert restored.rules[0].site == "shard.load"
        assert restored.rules[0].calls == frozenset([2])
        assert restored.rules[1].kind == "corrupt"

    def test_env_var_activation(self, monkeypatch):
        monkeypatch.setenv(
            "KEYSTONE_FAULT_PLAN",
            '{"rules": [{"site": "s", "kind": "error", "calls": [0]}]}',
        )
        faults._reset_env_cache()
        try:
            with pytest.raises(faults.FaultError):
                faults.maybe_fail("s")
        finally:
            faults.uninstall()
            faults._reset_env_cache()

    def test_invalid_rule_rejected(self):
        with pytest.raises(ValueError, match="kind"):
            FaultRule("s", "explode", calls=[0])
        with pytest.raises(ValueError, match="calls"):
            FaultRule("s", "error")


class TestRetryPolicy:
    def test_succeeds_after_transient_failures(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        policy = RetryPolicy(attempts=3, base_delay_s=0.001)
        retried = []
        assert policy.call(
            flaky, on_retry=lambda a, d, e: retried.append((a, d))
        ) == "ok"
        assert len(calls) == 3 and len(retried) == 2

    def test_exhaustion_reraises_last_error(self):
        policy = RetryPolicy(attempts=2, base_delay_s=0.001)
        with pytest.raises(OSError, match="always"):
            policy.call(lambda: (_ for _ in ()).throw(OSError("always")))

    def test_non_transient_raises_immediately(self):
        from keystone_tpu.data.durable import ShardCorrupted

        calls = []

        def corrupt():
            calls.append(1)
            raise ShardCorrupted("bad bytes")

        policy = RetryPolicy(attempts=5, base_delay_s=0.001)
        with pytest.raises(ShardCorrupted):
            policy.call(corrupt)
        assert len(calls) == 1  # persistent failures are never retried

    def test_backoff_deterministic_and_bounded(self):
        p1 = RetryPolicy(attempts=5, base_delay_s=0.1, max_delay_s=0.5,
                         seed=11)
        p2 = RetryPolicy(attempts=5, base_delay_s=0.1, max_delay_s=0.5,
                         seed=11)
        seq1 = [p1.delay_s(a, "k") for a in range(1, 5)]
        seq2 = [p2.delay_s(a, "k") for a in range(1, 5)]
        assert seq1 == seq2  # deterministic jitter
        assert all(d <= 0.5 for d in seq1)  # capped
        assert seq1[1] > seq1[0] * 1.5  # roughly exponential
        other = RetryPolicy(attempts=5, base_delay_s=0.1, seed=12)
        assert [other.delay_s(a, "k") for a in range(1, 5)] != seq1

    def test_default_policy_env_knobs(self, monkeypatch):
        monkeypatch.setenv("KEYSTONE_RETRY_ATTEMPTS", "7")
        monkeypatch.setenv("KEYSTONE_RETRY_BASE_S", "0.5")
        policy = faults.default_retry_policy()
        assert policy.attempts == 7 and policy.base_delay_s == 0.5

    def test_attempts_must_be_positive(self):
        with pytest.raises(ValueError):
            RetryPolicy(attempts=0)

    def test_env_knobs_validated_at_parse_time(self, monkeypatch):
        """ISSUE 7 satellite: a bad retry knob must raise ONE clear
        ValueError naming the variable at policy construction — never a
        confusing failure deep inside a shard read."""
        monkeypatch.setenv("KEYSTONE_RETRY_ATTEMPTS", "banana")
        with pytest.raises(ValueError, match="KEYSTONE_RETRY_ATTEMPTS"):
            faults.default_retry_policy()
        monkeypatch.setenv("KEYSTONE_RETRY_ATTEMPTS", "-2")
        with pytest.raises(ValueError, match="KEYSTONE_RETRY_ATTEMPTS"):
            faults.default_retry_policy()
        monkeypatch.setenv("KEYSTONE_RETRY_ATTEMPTS", "0")
        with pytest.raises(ValueError, match="KEYSTONE_RETRY_ATTEMPTS"):
            faults.default_retry_policy()
        monkeypatch.delenv("KEYSTONE_RETRY_ATTEMPTS")
        monkeypatch.setenv("KEYSTONE_RETRY_BASE_S", "not-a-float")
        with pytest.raises(ValueError, match="KEYSTONE_RETRY_BASE_S"):
            faults.default_retry_policy()
        monkeypatch.setenv("KEYSTONE_RETRY_BASE_S", "-0.5")
        with pytest.raises(ValueError, match="KEYSTONE_RETRY_BASE_S"):
            faults.default_retry_policy()
        # Valid boundary values still parse: base 0 disables backoff.
        monkeypatch.setenv("KEYSTONE_RETRY_BASE_S", "0")
        assert faults.default_retry_policy().base_delay_s == 0.0
