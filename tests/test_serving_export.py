"""Serving export (ISSUE 4 tentpole): the apply-only subgraph freezes to
a bucketed pre-compiled plan — transformer-only enforced, fusion reused,
warm path never traces, padding masked off responses."""

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.data import Dataset
from keystone_tpu.serving import export_plan
from keystone_tpu.serving.export import ExportedPlan, _default_buckets
from keystone_tpu.workflow import Transformer
from keystone_tpu.workflow.graph import Graph, NodeId, SinkId, SourceId
from keystone_tpu.workflow.pipeline import FittedPipeline

from tests._serving_util import (
    TINY_D_IN,
    TraceCountingScale,
    fit_tiny_mnist,
    fitted_from_transformer,
)


class TestExportValidation:
    def test_rejects_unfitted_pipeline(self):
        t = TraceCountingScale()
        with pytest.raises(TypeError, match="FittedPipeline"):
            export_plan(t.to_pipeline(), np.zeros(4, np.float32))

    def test_rejects_graph_with_estimator_state(self):
        # A hand-built FittedPipeline smuggling an estimator operator must
        # fail at EXPORT (no fit_datasets can run at request time), not
        # mid-request.
        from keystone_tpu.workflow.operators import EstimatorOperator

        est = EstimatorOperator()
        graph = Graph(
            sources=frozenset({SourceId(0)}),
            sink_dependencies={SinkId(0): NodeId(0)},
            operators={NodeId(0): est},
            dependencies={NodeId(0): (SourceId(0),)},
        )
        fitted = FittedPipeline(graph, SourceId(0), SinkId(0))
        with pytest.raises(TypeError, match="Non-transformer"):
            export_plan(fitted, np.zeros(4, np.float32))

    def test_buckets_are_powers_of_two_up_to_max(self):
        # Bucket 1 is deliberately absent (batch-1 XLA codepaths differ
        # by a ulp — singletons pad to 2 to keep bit-identity).
        assert _default_buckets(256) == [2, 4, 8, 16, 32, 64, 128, 256]
        assert _default_buckets(1) == [1]
        assert _default_buckets(2) == [2]
        # Non-power-of-two max stays reachable as the final bucket.
        assert _default_buckets(48) == [2, 4, 8, 16, 32, 48]

    def test_batch_over_max_rejected(self):
        fitted = fitted_from_transformer(TraceCountingScale())
        plan = export_plan(fitted, np.zeros(4, np.float32), max_batch=8)
        with pytest.raises(ValueError, match="max_batch"):
            plan.apply_batch([np.zeros(4, np.float32)] * 9)


class TestWarmPathNeverTraces:
    def test_precompile_covers_every_bucket_then_zero_traces(self):
        t = TraceCountingScale()
        plan = export_plan(
            fitted_from_transformer(t), np.zeros(6, np.float32), max_batch=16
        )
        assert plan.compiled
        # Export-time traces: ONE abstract evaluation by the static plan
        # verifier (jax.eval_shape typechecks the chain against the
        # example input — workflow/verify.py) plus once per bucket shape
        # for AOT compilation. Nothing more.
        assert len(plan.buckets) == 4
        assert t.traces == len(plan.buckets) + 1
        rng = np.random.default_rng(0)
        for m in (1, 3, 4, 5, 11, 16, 2, 7):
            X = rng.normal(size=(m, 6)).astype(np.float32)
            out = plan.apply_batch(list(X))
            np.testing.assert_array_equal(out, X * 2.0)
        assert t.traces == 5, "warm-path request triggered a re-trace"
        # trace_count counts the jit's traces only (the verifier's
        # eval_shape never enters the jitted counter).
        assert plan.trace_count == 4

    def test_mnist_plan_compiles_to_one_program(self):
        fitted, _ = fit_tiny_mnist()
        plan = export_plan(
            fitted, np.zeros(TINY_D_IN, np.float32), max_batch=8
        )
        # The fusion passes collapse featurize gather + model into a
        # single-program plan (the compiled fast path, not the per-node
        # eager fallback).
        assert plan.compiled
        assert plan.pinned_bytes > 0


class TestServedOutputs:
    def test_padding_masked_and_rows_match_offline(self):
        fitted, _ = fit_tiny_mnist()
        plan = export_plan(
            fitted, np.zeros(TINY_D_IN, np.float32), max_batch=16
        )
        rng = np.random.default_rng(1)
        X = rng.normal(size=(5, TINY_D_IN)).astype(np.float32)
        out, info = plan.apply_batch_info(list(X))
        assert out.shape[0] == 5  # padding rows masked off the response
        assert info.bucket == 8 and info.batch_size == 5
        assert info.pad_fraction == pytest.approx(3 / 8)
        offline = np.asarray(fitted.apply(Dataset.of(jnp.asarray(X))).array)
        np.testing.assert_array_equal(out, offline)

    def test_eager_fallback_for_host_stage(self):
        class HostSquash(Transformer):
            """No device_fn: forces the non-composable fallback path."""

            def apply(self, x):
                return np.tanh(np.asarray(x))

            def batch_apply(self, ds):
                return Dataset(
                    jnp.asarray(np.tanh(np.asarray(ds.array))), n=ds.n
                )

        fitted = fitted_from_transformer(HostSquash())
        plan = export_plan(fitted, np.zeros(4, np.float32), max_batch=8)
        assert not plan.compiled
        X = np.random.default_rng(2).normal(size=(3, 4)).astype(np.float32)
        out = plan.apply_batch(list(X))
        np.testing.assert_allclose(out, np.tanh(X), rtol=1e-6)

    def test_singleton_request_bitwise_matches_offline(self):
        """Regression pin for the bucket-1 exclusion: a lone request —
        the case XLA's batch-1 codepath put a ulp off at FFT widths >= 32
        — now rides the 2-bucket and matches offline apply exactly."""
        fitted, _ = fit_tiny_mnist(d_in=32, block_size=32, seed=4)
        plan = export_plan(fitted, np.zeros(32, np.float32), max_batch=8)
        rng = np.random.default_rng(6)
        X = rng.normal(size=(6, 32)).astype(np.float32)
        offline = np.asarray(fitted.apply(Dataset.of(jnp.asarray(X))).array)
        for i in range(len(X)):
            out, info = plan.apply_batch_info([X[i]])
            assert info.bucket == 2 and info.pad_fraction == 0.5
            np.testing.assert_array_equal(out[0], offline[i])

    def test_single_request_measure(self):
        fitted, _ = fit_tiny_mnist()
        plan = export_plan(
            fitted, np.zeros(TINY_D_IN, np.float32), max_batch=4
        )
        s = plan.measure_single_request_s(reps=3)
        assert s > 0.0


class TestExportKnobs:
    def test_custom_buckets_must_reach_max_batch(self):
        fitted = fitted_from_transformer(TraceCountingScale())
        with pytest.raises(ValueError, match="max_batch"):
            ExportedPlan(
                fitted.transformer_graph, fitted.source, fitted.sink,
                np.zeros(4, np.float32), max_batch=16, buckets=[1, 4],
            )

    def test_bucket_for_picks_smallest_fitting(self):
        fitted = fitted_from_transformer(TraceCountingScale())
        plan = export_plan(
            fitted, np.zeros(4, np.float32), max_batch=32, precompile=False
        )
        assert plan.bucket_for(1) == 2  # singletons pad to the 2-bucket
        assert plan.bucket_for(3) == 4
        assert plan.bucket_for(17) == 32
        with pytest.raises(ValueError):
            plan.bucket_for(0)
        with pytest.raises(ValueError):
            plan.bucket_for(33)


class TestPlanFingerprint:
    def test_distinct_weights_distinct_fingerprints(self):
        f1, _ = fit_tiny_mnist(seed=0)
        f2, _ = fit_tiny_mnist(seed=1)
        example = np.zeros(TINY_D_IN, np.float32)
        p1 = export_plan(f1, example, max_batch=8, precompile=False)
        p2 = export_plan(f2, example, max_batch=8, precompile=False)
        assert p1.fingerprint != p2.fingerprint
        # Same fitted state => same identity (stable across exports).
        p1b = export_plan(f1, example, max_batch=8, precompile=False)
        assert p1b.fingerprint == p1.fingerprint

    def test_bucket_ladder_is_part_of_the_identity(self):
        """Review regression: buckets are part of the served bits — an
        explicit bucket-1 export serves singletons through XLA's batch-1
        codepath (a ulp off every other batch size, the PR 4 finding),
        so it must NOT share a fingerprint with the default-bucket
        export of the same weights."""
        f1, _ = fit_tiny_mnist(seed=0)
        example = np.zeros(TINY_D_IN, np.float32)
        default = export_plan(f1, example, max_batch=8, precompile=False)
        singleton = export_plan(f1, example, max_batch=8,
                                buckets=[1, 2, 4, 8], precompile=False)
        assert default.fingerprint != singleton.fingerprint

    def test_dict_valued_operator_state_reaches_fingerprint(self):
        """Review regression: fingerprint_token degrades a dict to its
        bare type name, so container-valued operator state (vocabulary
        maps, feature spaces) must be recursed into by plan_fingerprint
        itself — two plans differing ONLY in a dict attribute sharing a
        fingerprint would void the per-fingerprint bit-identity
        contract."""

        class VocabScale(Transformer):
            def __init__(self, vocab):
                self.vocab = vocab  # dict state, no arrays

            def apply(self, x):
                return jnp.asarray(x) * float(len(self.vocab))

            def device_fn(self):
                scale = float(len(self.vocab))
                return lambda X: X * scale

        example = np.zeros(4, np.float32)

        def fp(vocab):
            fitted = fitted_from_transformer(VocabScale(vocab))
            return export_plan(
                fitted, example, max_batch=4, precompile=False
            ).fingerprint

        base = {"a": 0, "b": 1}
        assert fp(base) != fp({"a": 0, "c": 1})
        assert fp(base) != fp({"a": 0, "b": 1, "c": 2})
        # Iteration order must NOT matter — only contents.
        assert fp(base) == fp({"b": 1, "a": 0})
        # Nested containers and sets recurse too.
        assert fp({"a": {"x", "y"}}) != fp({"a": {"x", "z"}})
        assert fp({"a": [1, {"k": 2}]}) != fp({"a": [1, {"k": 3}]})
