"""Golden word→lemma ledger for the CoreNLP-fidelity lemmatizer tier.

~220 pairs with Morpha/CoreNLP-style inflectional lemmas (bare mode, no POS:
noun-then-verb, derivational suffixes untouched), spanning every rule family:
irregular verbs (past + participle), irregular/latinate/invariant nouns,
irregular adjectives, the regular -s/-es/-ies plural families, -ed/-ied
pasts with consonant un-doubling and silent-e restoration, -ing gerunds,
and non-inflected words the cascade must leave alone.
"""

GOLDEN = [
    # irregular be/have/do and auxiliaries
    ("is", "be"), ("am", "be"), ("are", "be"), ("was", "be"), ("were", "be"),
    ("been", "be"), ("being", "be"), ("has", "have"), ("had", "have"),
    ("does", "do"), ("did", "do"), ("done", "do"),
    # irregular verb pasts
    ("went", "go"), ("gone", "go"), ("said", "say"), ("made", "make"),
    ("took", "take"), ("taken", "take"), ("came", "come"), ("saw", "see"),
    ("seen", "see"), ("got", "get"), ("knew", "know"), ("known", "know"),
    ("thought", "think"), ("gave", "give"), ("given", "give"),
    ("found", "find"), ("told", "tell"), ("became", "become"),
    ("left", "leave"), ("felt", "feel"), ("brought", "bring"),
    ("began", "begin"), ("begun", "begin"), ("kept", "keep"),
    ("held", "hold"), ("wrote", "write"), ("written", "write"),
    ("stood", "stand"), ("heard", "hear"), ("meant", "mean"),
    ("met", "meet"), ("ran", "run"), ("paid", "pay"), ("sat", "sit"),
    ("spoke", "speak"), ("spoken", "speak"), ("led", "lead"),
    ("grew", "grow"), ("grown", "grow"), ("lost", "lose"),
    ("fell", "fall"), ("fallen", "fall"), ("sent", "send"),
    ("built", "build"), ("understood", "understand"), ("drew", "draw"),
    ("broke", "break"), ("broken", "break"), ("spent", "spend"),
    ("rose", "rise"), ("risen", "rise"), ("drove", "drive"),
    ("driven", "drive"), ("bought", "buy"), ("wore", "wear"),
    ("chose", "choose"), ("chosen", "choose"), ("ate", "eat"),
    ("eaten", "eat"), ("flew", "fly"), ("flown", "fly"),
    ("forgot", "forget"), ("forgotten", "forget"), ("caught", "catch"),
    ("taught", "teach"), ("sought", "seek"), ("fought", "fight"),
    ("slept", "sleep"), ("swept", "sweep"), ("dealt", "deal"),
    ("sold", "sell"), ("threw", "throw"), ("thrown", "throw"),
    ("hid", "hide"), ("hidden", "hide"), ("sang", "sing"), ("sung", "sing"),
    ("swam", "swim"), ("drank", "drink"), ("drunk", "drink"),
    ("stole", "steal"), ("stolen", "steal"), ("froze", "freeze"),
    ("frozen", "freeze"), ("woke", "wake"), ("tore", "tear"),
    ("torn", "tear"), ("won", "win"), ("fed", "feed"), ("fled", "flee"),
    ("dug", "dig"), ("lit", "light"), ("rode", "ride"), ("ridden", "ride"),
    ("struck", "strike"), ("hung", "hang"), ("laid", "lay"),
    # invariant verbs
    ("cut", "cut"), ("put", "put"), ("set", "set"), ("let", "let"),
    ("hit", "hit"), ("cost", "cost"), ("hurt", "hurt"), ("read", "read"),
    ("spread", "spread"),
    # irregular noun plurals
    ("children", "child"), ("men", "man"), ("women", "woman"),
    ("feet", "foot"), ("teeth", "tooth"), ("geese", "goose"),
    ("mice", "mouse"), ("oxen", "ox"), ("people", "person"),
    ("lives", "life"), ("knives", "knife"), ("wives", "wife"),
    ("leaves", "leaf"), ("halves", "half"), ("shelves", "shelf"),
    ("wolves", "wolf"), ("loaves", "loaf"), ("thieves", "thief"),
    ("indices", "index"), ("matrices", "matrix"), ("vertices", "vertex"),
    ("criteria", "criterion"), ("phenomena", "phenomenon"),
    ("analyses", "analysis"), ("theses", "thesis"), ("crises", "crisis"),
    ("hypotheses", "hypothesis"), ("bases", "basis"), ("axes", "axis"),
    ("series", "series"), ("species", "species"), ("cacti", "cactus"),
    ("fungi", "fungus"), ("nuclei", "nucleus"), ("radii", "radius"),
    ("stimuli", "stimulus"), ("alumni", "alumnus"),
    # invariant nouns
    ("sheep", "sheep"), ("deer", "deer"), ("fish", "fish"),
    # irregular adjectives
    ("better", "good"), ("best", "good"), ("worse", "bad"),
    ("worst", "bad"), ("further", "far"), ("farther", "far"),
    ("less", "little"), ("least", "little"), ("more", "much"),
    ("most", "much"),
    # regular -s plurals / 3sg
    ("cats", "cat"), ("dogs", "dog"), ("cars", "car"), ("books", "book"),
    ("runs", "run"), ("walks", "walk"), ("plays", "play"),
    ("says", "say"), ("thinks", "think"), ("wants", "want"),
    ("years", "year"), ("things", "thing"), ("numbers", "number"),
    # -es families
    ("watches", "watch"), ("boxes", "box"), ("buses", "bus"),
    ("dishes", "dish"), ("classes", "class"), ("churches", "church"),
    ("foxes", "fox"), ("buzzes", "buzz"), ("potatoes", "potato"),
    ("heroes", "hero"), ("goes", "go"), ("makes", "make"),
    ("takes", "take"), ("gives", "give"), ("comes", "come"),
    ("uses", "use"), ("causes", "cause"), ("houses", "house"),
    ("pages", "page"), ("changes", "change"),
    # -ies
    ("studies", "study"), ("tries", "try"), ("flies", "fly"),
    ("cities", "city"), ("countries", "country"), ("companies", "company"),
    ("families", "family"), ("bodies", "body"), ("carries", "carry"),
    # regular -ed
    ("walked", "walk"), ("played", "play"), ("visited", "visit"),
    ("jumped", "jump"), ("wanted", "want"), ("asked", "ask"),
    ("looked", "look"), ("seemed", "seem"), ("needed", "need"),
    ("turned", "turn"), ("helped", "help"), ("talked", "talk"),
    # -ed with silent-e restoration
    ("loved", "love"), ("used", "use"), ("liked", "like"),
    ("moved", "move"), ("lived", "live"), ("hoped", "hope"),
    ("created", "create"), ("decided", "decide"), ("provided", "provide"),
    ("noticed", "notice"), ("produced", "produce"), ("argued", "argue"),
    ("continued", "continue"), ("believed", "believe"),
    # -ed with un-doubling
    ("stopped", "stop"), ("planned", "plan"), ("dropped", "drop"),
    ("grabbed", "grab"), ("hugged", "hug"), ("shipped", "ship"),
    # -eed base forms stay
    ("agreed", "agree"), ("freed", "free"), ("guaranteed", "guarantee"),
    ("studied", "study"), ("tried", "try"), ("carried", "carry"),
    ("married", "marry"), ("copied", "copy"),
    # -ing with e-restoration / un-doubling / y-keep
    ("making", "make"), ("taking", "take"), ("coming", "come"),
    ("using", "use"), ("having", "have"), ("giving", "give"),
    ("writing", "write"), ("living", "live"), ("moving", "move"),
    ("running", "run"), ("sitting", "sit"), ("getting", "get"),
    ("stopping", "stop"), ("planning", "plan"), ("swimming", "swim"),
    ("jumping", "jump"), ("studying", "study"), ("playing", "play"),
    ("saying", "say"), ("going", "go"), ("doing", "do"),
    ("working", "work"), ("looking", "look"), ("talking", "talk"),
    ("walking", "walk"), ("watching", "watch"), ("thinking", "think"),
    ("reading", "read"), ("feeling", "feel"), ("needing", "need"),
    # words the cascade must NOT touch (derivational or lemma-final forms)
    ("happiness", "happiness"), ("nation", "nation"), ("quickly", "quickly"),
    ("this", "this"), ("his", "his"), ("famous", "famous"),
    ("news", "news"), ("always", "always"), ("perhaps", "perhaps"),
    ("lens", "lens"), ("analysis", "analysis"), ("crisis", "crisis"),
    ("glass", "glass"), ("grass", "grass"), ("press", "press"),
    ("ring", "ring"), ("king", "king"), ("thing", "thing"),
    ("spring", "spring"), ("morning", "morning"), ("evening", "evening"),
    ("during", "during"), ("something", "something"),
    ("interesting", "interest"),  # bare mode (no POS): verb reading strips -ing
    ("bed", "bed"), ("red", "red"), ("hundred", "hundred"),
    ("indeed", "indeed"), ("need", "need"), ("speed", "speed"),
    ("united", "unite"), ("wednesdays", "wednesday"),
    # singular -as/-os/-ics nouns + their -es plurals (found by the
    # idempotence property test: "bias" used to lemmatize to "bia")
    ("bias", "bias"), ("alias", "alias"), ("atlas", "atlas"),
    ("canvas", "canvas"), ("chaos", "chaos"), ("cosmos", "cosmos"),
    ("physics", "physics"), ("mathematics", "mathematics"),
    ("gases", "gas"), ("biases", "bias"), ("aliases", "alias"),
    ("atlases", "atlas"), ("canvases", "canvas"),
]
