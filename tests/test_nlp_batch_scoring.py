"""Vectorized StupidBackoff batch scoring vs the dict-loop oracle.

The dict recursion (``_score_locally``, mirroring StupidBackoff.scala:62-93)
stays the semantic oracle; ``batch_score_packed`` must reproduce it exactly
over every backoff branch — observed trigram, context-observed bigram,
single backoff, double backoff to the unigram floor, and unseen words.
The reference served scoring data-parallel over the cluster
(StupidBackoff.scala:128-182); the batch path is the vectorized analog.
"""

import numpy as np
import pytest

from keystone_tpu.data import Dataset
from keystone_tpu.ops.nlp import (
    NGram,
    NGramsCounts,
    NGramsFeaturizer,
    NaiveBitPackIndexer,
    ShardedStupidBackoffModel,
    StupidBackoffEstimator,
    partition_ngram_pairs,
)


def _int_corpus(num_docs=200, vocab=50, seed=0):
    """Synthetic integer-word-id corpus (the packed indexer needs ids)."""
    rng = np.random.default_rng(seed)
    return [
        [int(w) for w in rng.integers(0, vocab, size=rng.integers(3, 12))]
        for _ in range(num_docs)
    ]


def _fit(corpus):
    data = Dataset.of(corpus)
    grams = NGramsFeaturizer([1, 2, 3]).batch_apply(data)
    counts = NGramsCounts().batch_apply(grams)
    unigrams = {
        w: c for (ng, c) in counts.to_list() if len(ng) == 1 for w in ng.words
    }
    pairs = [kv for kv in counts.to_list() if len(kv[0]) > 1]
    model = StupidBackoffEstimator(unigram_counts=unigrams).fit(
        Dataset.of(pairs)
    )
    return model, unigrams, pairs


def _queries(model, vocab=50, seed=1, extra=2000):
    """Every observed n-gram + random probes (unseen combinations hit the
    backoff and unigram-floor branches; ids >= vocab hit zero scores)."""
    rng = np.random.default_rng(seed)
    qs = list(model.ngram_counts.keys())
    for _ in range(extra):
        order = int(rng.integers(1, 4))
        qs.append(NGram(int(w) for w in rng.integers(0, vocab + 5, order)))
    return qs


class TestBatchScoring:
    def test_matches_dict_loop_on_all_branches(self):
        model, _, _ = _fit(_int_corpus())
        queries = _queries(model)
        expected = np.array([model.score(g) for g in queries])
        got = model.batch_score(queries)
        np.testing.assert_allclose(got, expected, rtol=1e-12, atol=0)
        # The probe set must actually exercise a backoff (score scaled by
        # alpha) and the zero branch, or this test proves too little.
        assert (got == 0.0).any()
        assert ((got > 0) & (got < 1)).any()

    def test_packed_entrypoint_matches(self):
        model, _, _ = _fit(_int_corpus(seed=3))
        packer = NaiveBitPackIndexer()
        queries = list(model.ngram_counts.keys())[:500]
        packed = np.array(
            [packer.pack(g.words) for g in queries], dtype=np.int64
        )
        got = model.batch_score_packed(packed)
        expected = np.array([model.score(g) for g in queries])
        np.testing.assert_allclose(got, expected, rtol=1e-12, atol=0)

    def test_sharded_batch_matches_global(self):
        model, unigrams, pairs = _fit(_int_corpus(seed=5))
        parts = partition_ngram_pairs(pairs, 4)
        est = StupidBackoffEstimator(unigrams)
        shards = [est.fit(Dataset.of(p)) for p in parts]
        sharded = ShardedStupidBackoffModel(shards)
        queries = _queries(model, extra=500)
        packer = NaiveBitPackIndexer()
        packed = np.array(
            [packer.pack(g.words) for g in queries], dtype=np.int64
        )
        np.testing.assert_allclose(
            sharded.batch_score_packed(packed),
            model.batch_score_packed(packed),
            rtol=1e-12, atol=0,
        )

    def test_inconsistent_table_raises_like_oracle(self):
        # A user-assembled table violating the context-consistency
        # invariant (observed trigram, absent bigram context) crashes the
        # dict oracle with ZeroDivisionError; the batch path must raise
        # too, not emit silent inf into downstream ranking.
        from keystone_tpu.ops.nlp import NGramIndexerImpl, StupidBackoffModel

        model = StupidBackoffModel(
            {}, {NGram((1, 2, 3)): 5}, NGramIndexerImpl(),
            {1: 2, 2: 3, 3: 4}, num_tokens=9,
        )
        with pytest.raises(ZeroDivisionError):
            model.score(NGram((1, 2, 3)))
        with pytest.raises(ZeroDivisionError):
            model.batch_score([NGram((1, 2, 3))])

    def test_throughput_exceeds_dict_loop(self):
        # Not a benchmark (bench.py owns the recorded number) — a guard
        # that the vectorized path is at least several times the dict loop
        # even at modest batch sizes.
        import time

        model, _, _ = _fit(_int_corpus(num_docs=400))
        queries = _queries(model, extra=4000)
        packer = NaiveBitPackIndexer()
        packed = np.array(
            [packer.pack(g.words) for g in queries], dtype=np.int64
        )
        model.batch_score_packed(packed)  # build tables outside the timer
        t0 = time.perf_counter()
        model.batch_score_packed(packed)
        t_vec = time.perf_counter() - t0
        t0 = time.perf_counter()
        for g in queries[:1000]:
            model.score(g)
        t_dict = (time.perf_counter() - t0) * (len(queries) / 1000)
        assert t_vec < t_dict / 3, (t_vec, t_dict)


class TestShardValidation:
    """Default construction runs the cheap sampled-key probe (O(shards² ×
    probes), O(1) memory; probabilistic), not a full set union; the
    partitioner's own path skips it — shards disjoint by construction."""

    def _shards(self, num=3):
        model, unigrams, pairs = _fit(_int_corpus(seed=9))
        parts = partition_ngram_pairs(pairs, num)
        est = StupidBackoffEstimator(unigrams)
        return [est.fit(Dataset.of(p)) for p in parts]

    def test_probe_catches_duplicated_shard(self):
        shards = self._shards()
        with pytest.raises(ValueError, match="overlap"):
            ShardedStupidBackoffModel([shards[0], shards[0]])

    def test_full_validation_still_available(self):
        shards = self._shards()
        with pytest.raises(ValueError, match="overlap"):
            ShardedStupidBackoffModel(
                [shards[0], shards[0]], validate="full"
            )
        ShardedStupidBackoffModel(shards, validate="full")  # disjoint: ok

    def test_from_partitioned_skips_validation(self):
        shards = self._shards()
        # Even a (mis)use with overlapping shards constructs — the
        # partitioner path vouches for disjointness by construction.
        ShardedStupidBackoffModel.from_partitioned(shards)
        ShardedStupidBackoffModel.from_partitioned([shards[0], shards[0]])

    def test_default_probe_passes_disjoint_shards(self):
        sharded = ShardedStupidBackoffModel(self._shards())
        assert len(sharded.shards) == 3
