"""North-star dossier tests (NORTHSTAR.md): the block-streamed mesh BCD
program that runs TIMIT at ~200k feature dims on a v5e-16.

Three claims are pinned here on the 8-device CPU mesh:
  1. Numeric parity: the block-streamed mesh sweep equals the resident
     single-device solver on the same features (scaled shapes whose
     PER-DEVICE geometry matches the v5e-16 plan's proportions).
  2. Collective schedule: the compiled HLO contains all-reduces (the
     gram+corr psums) and NO all-gather of a feature-sized operand — the
     program must never materialize or gather the feature matrix.
  3. Live-buffer bound: the compiled program's per-device peak follows the
     dossier's HBM model (raw rows + residual + one block slab + stash),
     NOT the materialized-features model.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.parallel import streaming
from keystone_tpu.parallel.linalg import bcd_least_squares_fused_flat

D_IN, K, BS = 22, 5, 64
LAM = 1e-2


def _bank(d_feat, seed=0):
    rng = np.random.default_rng(seed)
    Wrf = jnp.asarray(rng.normal(size=(d_feat, D_IN)).astype(np.float32) * 0.3)
    brf = jnp.asarray(
        rng.uniform(0, 2 * np.pi, size=(d_feat,)).astype(np.float32)
    )
    return Wrf, brf


class TestNorthstarProgram:
    def test_mesh_block_stream_matches_resident(self):
        # Scaled geometry: 8 devices, 4 blocks of 64, ragged true n.
        d_feat = 4 * BS
        Wrf, brf = _bank(d_feat)
        mesh = mesh_lib.make_mesh()
        n_true, n_pad = 700, 704  # 88 rows/device
        rng = np.random.default_rng(1)
        X = rng.normal(size=(n_true, D_IN)).astype(np.float32)
        Y = rng.normal(size=(n_true, K)).astype(np.float32)
        Xp = np.vstack(
            [X, rng.normal(size=(n_pad - n_true, D_IN)).astype(np.float32)]
        )
        Yp = np.vstack([Y, np.zeros((n_pad - n_true, K), np.float32)])

        W_mesh = streaming.streaming_block_bcd_mesh(
            mesh_lib.shard_rows(jnp.asarray(Xp), mesh),
            mesh_lib.shard_rows(jnp.asarray(Yp), mesh),
            Wrf, brf, block_size=BS, lam=LAM, num_iter=3, mesh=mesh,
            n_true=n_true,
        )
        F = jnp.cos(jnp.asarray(X) @ Wrf.T + brf)
        W_ref = bcd_least_squares_fused_flat(
            F, jnp.asarray(Y), BS, lam=LAM, num_iter=3, use_pallas=False
        )
        np.testing.assert_allclose(
            np.asarray(W_mesh), np.asarray(W_ref), atol=2e-3, rtol=2e-3
        )

    def _lowered(self, d_feat=8 * BS, n_pad=1024):
        Wrf, brf = _bank(d_feat)
        mesh = mesh_lib.make_mesh()
        X = jnp.zeros((n_pad, D_IN), jnp.float32)
        Y = jnp.zeros((n_pad, K), jnp.float32)
        Xs = mesh_lib.shard_rows(X, mesh)
        Ys = mesh_lib.shard_rows(Y, mesh)
        return jax.jit(
            lambda a, b, w, c: streaming.streaming_block_bcd_mesh(
                a, b, w, c, block_size=BS, lam=LAM, num_iter=3, mesh=mesh
            )
        ).lower(Xs, Ys, Wrf, brf)

    def test_hlo_collective_schedule(self):
        lowered = self._lowered()
        hlo = lowered.compile().as_text()
        # The gram+corr psums compile to all-reduces.
        assert "all-reduce" in hlo, "expected psum all-reduces in the HLO"
        # NOTHING feature-matrix-sized may be gathered or materialized:
        # scan for all-gather ops with a d_feat-sized operand. Block slabs
        # (ln, bs) and gram (bs, bs) are fine; (n, d_feat) or (ln, d_feat)
        # are not.
        d_feat = 8 * BS
        for m in re.finditer(r"all-gather[^=\n]*=\s*\S*f32\[([0-9,]+)\]", hlo):
            dims = [int(x) for x in m.group(1).split(",")]
            assert d_feat not in dims, f"feature-width all-gather: {m.group(0)}"

    def test_live_buffer_bound_is_streaming_not_materialized(self):
        d_feat, n_pad = 8 * BS, 1024
        lowered = self._lowered(d_feat, n_pad)
        compiled = lowered.compile()
        mem = compiled.memory_analysis()
        if mem is None:
            pytest.skip("backend exposes no memory analysis")
        ln = n_pad // 8
        # Dossier model (per device, f32 here): raw rows + residual + one
        # block slab + Gramian/factor stash + weights + bank. The
        # materialized-features alternative would hold ln*d_feat floats.
        stash = 2 * (d_feat // BS) * BS * BS
        model = (
            ln * D_IN + ln * K + ln * BS + stash
            + (d_feat // BS) * BS * K + d_feat * (D_IN + 1)
        ) * 4
        materialized = ln * d_feat * 4
        peak = getattr(mem, "temp_size_in_bytes", None)
        if peak is None:
            pytest.skip("no temp_size_in_bytes on this backend")
        # The program's temporaries must sit near the streaming model (x4
        # slack for XLA's scheduling copies), far under materialized + model.
        assert peak <= 4 * model, (peak, model)

    def test_epoch_cost_structure(self):
        # Epochs 2+ must NOT recompute Gramians/factors: the dominant
        # first-epoch cost (nb * 2*ln*bs^2 gram dots + Cholesky) is
        # epoch-invariant and stashed, so the compiled 3-epoch program's
        # FLOP estimate must be far below 3x the 1-epoch program's —
        # later epochs pay only featurize + correlation + update.
        def flops(num_iter):
            d_feat, n_pad = 8 * BS, 1024
            Wrf, brf = _bank(d_feat)
            mesh = mesh_lib.make_mesh()
            Xs = mesh_lib.shard_rows(jnp.zeros((n_pad, D_IN)), mesh)
            Ys = mesh_lib.shard_rows(jnp.zeros((n_pad, K)), mesh)
            compiled = jax.jit(
                lambda a, b, w, c: streaming.streaming_block_bcd_mesh(
                    a, b, w, c, block_size=BS, lam=LAM,
                    num_iter=num_iter, mesh=mesh,
                )
            ).lower(Xs, Ys, Wrf, brf).compile()
            ca = compiled.cost_analysis()
            if not ca or "flops" not in ca:
                pytest.skip("backend exposes no cost analysis")
            return ca["flops"]

        f1, f3 = flops(1), flops(3)
        assert f3 < 2.0 * f1, (f1, f3)


class TestNorthstar2D:
    """The 2-D (data x model) variant (VERDICT r4 directive #3): stash,
    bank and block weights shard over `model`; rows shard over both axes.
    Per-device stash = nb/model_size Gramians+factors — the d >> 200k
    lever NORTHSTAR.md §3 names."""

    def _mesh42(self):
        return mesh_lib.make_mesh(
            (4, 2), (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS)
        )

    def _shard(self, mesh, Xp, Yp, Wrf, brf):
        from jax.sharding import NamedSharding, PartitionSpec as P

        rows = P((mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS))
        return (
            jax.device_put(jnp.asarray(Xp), NamedSharding(mesh, rows)),
            jax.device_put(jnp.asarray(Yp), NamedSharding(mesh, rows)),
            jax.device_put(Wrf, NamedSharding(mesh, P(mesh_lib.MODEL_AXIS))),
            jax.device_put(brf, NamedSharding(mesh, P(mesh_lib.MODEL_AXIS))),
        )

    def test_2d_mesh_matches_resident(self):
        d_feat = 4 * BS  # nb=4 over model=2 -> 2 blocks/group
        Wrf, brf = _bank(d_feat)
        mesh = self._mesh42()
        n_true, n_pad = 700, 704  # 88 rows/device over 8 devices
        rng = np.random.default_rng(1)
        X = rng.normal(size=(n_true, D_IN)).astype(np.float32)
        Y = rng.normal(size=(n_true, K)).astype(np.float32)
        Xp = np.vstack(
            [X, rng.normal(size=(n_pad - n_true, D_IN)).astype(np.float32)]
        )
        Yp = np.vstack([Y, np.zeros((n_pad - n_true, K), np.float32)])
        Xs, Ys, Ws, bs_ = self._shard(mesh, Xp, Yp, Wrf, brf)
        W_2d = streaming.streaming_block_bcd_mesh_2d(
            Xs, Ys, Ws, bs_, block_size=BS, lam=LAM, num_iter=3, mesh=mesh,
            n_true=n_true,
        )
        F = jnp.cos(jnp.asarray(X) @ Wrf.T + brf)
        W_ref = bcd_least_squares_fused_flat(
            F, jnp.asarray(Y), BS, lam=LAM, num_iter=3, use_pallas=False
        )
        np.testing.assert_allclose(
            np.asarray(W_2d), np.asarray(W_ref), atol=2e-3, rtol=2e-3
        )

    def _lowered_2d(self, d_feat=8 * BS, n_pad=1024):
        Wrf, brf = _bank(d_feat)
        mesh = self._mesh42()
        Xs, Ys, Ws, bs_ = self._shard(
            mesh,
            np.zeros((n_pad, D_IN), np.float32),
            np.zeros((n_pad, K), np.float32),
            Wrf, brf,
        )
        return jax.jit(
            lambda a, b, w, c: streaming.streaming_block_bcd_mesh_2d(
                a, b, w, c, block_size=BS, lam=LAM, num_iter=3, mesh=mesh
            )
        ).lower(Xs, Ys, Ws, bs_)

    def test_2d_hlo_no_feature_width_gather(self):
        hlo = self._lowered_2d().compile().as_text()
        assert "all-reduce" in hlo
        d_feat = 8 * BS
        for m in re.finditer(r"all-gather[^=\n]*=\s*\S*f32\[([0-9,]+)\]", hlo):
            dims = [int(x) for x in m.group(1).split(",")]
            assert d_feat not in dims, f"feature-width all-gather: {m.group(0)}"

    def test_2d_live_buffer_shards_stash(self):
        d_feat, n_pad = 8 * BS, 1024
        compiled = self._lowered_2d(d_feat, n_pad).compile()
        mem = compiled.memory_analysis()
        if mem is None:
            pytest.skip("backend exposes no memory analysis")
        peak = getattr(mem, "temp_size_in_bytes", None)
        if peak is None:
            pytest.skip("no temp_size_in_bytes on this backend")
        ln = n_pad // 8
        nb, mc = d_feat // BS, 2
        # Per-device model: raw rows + residual + one slab + SHARDED stash
        # (nb/mc Gramians + factors) + sharded weights + sharded bank.
        stash = 2 * (nb // mc) * BS * BS
        model = (
            ln * D_IN + ln * K + ln * BS + stash
            + (nb // mc) * BS * K + (d_feat // mc) * (D_IN + 1)
        ) * 4
        assert peak <= 4 * model, (peak, model)
        # And the stash sharding is visible: the replicated-stash model of
        # the 1-D program would be ~2x larger at this geometry.
        replicated_stash_model = model + 2 * (nb - nb // mc) * BS * BS * 4
        assert model < replicated_stash_model


@pytest.mark.slow
class TestNorthstarRealisticShape:
    """VERDICT r4 directive #5: one mesh case at realistic per-device
    shapes — bs >= 1024, d_feat >= 8192, rows/device >= 8192, ragged
    n_true — the shape class where padding/raggedness/layout bugs live."""

    def test_realistic_shape_parity_and_structure(self):
        bs, d_feat, d_in, k = 1024, 8192, 64, 8
        mesh = mesh_lib.make_mesh()
        num = mesh_lib.axis_size(mesh, mesh_lib.DATA_AXIS)
        n_pad = 8192 * num
        n_true = n_pad - 1237  # ragged: boundary shard partially valid
        rng = np.random.default_rng(7)
        Wrf = jnp.asarray(
            rng.normal(size=(d_feat, d_in)).astype(np.float32) * 0.3
        )
        brf = jnp.asarray(
            rng.uniform(0, 2 * np.pi, size=(d_feat,)).astype(np.float32)
        )
        X = rng.normal(size=(n_pad, d_in)).astype(np.float32)
        Y = np.zeros((n_pad, k), np.float32)
        Y[:n_true] = rng.normal(size=(n_true, k)).astype(np.float32)

        fit = jax.jit(
            lambda a, b, w, c: streaming.streaming_block_bcd_mesh(
                a, b, w, c, block_size=bs, lam=LAM, num_iter=2, mesh=mesh,
                n_true=n_true,
            )
        )
        Xs = mesh_lib.shard_rows(jnp.asarray(X), mesh)
        Ys = mesh_lib.shard_rows(jnp.asarray(Y), mesh)

        # Structural assertions at THIS shape, not just the miniature one.
        lowered = fit.lower(Xs, Ys, Wrf, brf)
        compiled = lowered.compile()
        hlo = compiled.as_text()
        assert "all-reduce" in hlo
        for m in re.finditer(r"all-gather[^=\n]*=\s*\S*f32\[([0-9,]+)\]", hlo):
            dims = [int(x) for x in m.group(1).split(",")]
            assert d_feat not in dims, f"feature-width all-gather: {m.group(0)}"
        mem = compiled.memory_analysis()
        if mem is not None and getattr(mem, "temp_size_in_bytes", None):
            ln = n_pad // num
            nb = d_feat // bs
            model = (
                ln * d_in + ln * k + ln * bs + 2 * nb * bs * bs
                + nb * bs * k + d_feat * (d_in + 1)
            ) * 4
            materialized = ln * d_feat * 4
            assert mem.temp_size_in_bytes <= 4 * model, (
                mem.temp_size_in_bytes, model, materialized,
            )

        W_mesh = fit(Xs, Ys, Wrf, brf)

        # Parity against the resident solver on the same features.
        F = jnp.cos(jnp.asarray(X[:n_true]) @ Wrf.T + brf)
        W_ref = bcd_least_squares_fused_flat(
            F, jnp.asarray(Y[:n_true]), bs, lam=LAM, num_iter=2,
            use_pallas=False,
        )
        np.testing.assert_allclose(
            np.asarray(W_mesh), np.asarray(W_ref), atol=5e-3, rtol=5e-3
        )


class TestNorthstarCentered:
    """center=True folds BlockLeastSquares semantics into the block-streamed
    sweep (per-block feature means + label mean accumulate in the block
    steps) — the third tier's semantics parity (round 5)."""

    def test_centered_matches_streamed_centered_gram(self):
        d_feat = 4 * BS
        Wrf, brf = _bank(d_feat)
        mesh = mesh_lib.make_mesh()
        n_true, n_pad = 700, 704
        rng = np.random.default_rng(2)
        X = rng.normal(size=(n_true, D_IN)).astype(np.float32)
        Y = rng.normal(size=(n_true, K)).astype(np.float32) + 0.7
        Xp = np.vstack(
            [X, 9.0 + rng.normal(size=(n_pad - n_true, D_IN)).astype(np.float32)]
        )
        Yp = np.vstack(
            [Y, 9.0 * np.ones((n_pad - n_true, K), np.float32)]
        )
        W_b, fmean_b, ymean_b = streaming.streaming_block_bcd_mesh(
            mesh_lib.shard_rows(jnp.asarray(Xp), mesh),
            mesh_lib.shard_rows(jnp.asarray(Yp), mesh),
            Wrf, brf, block_size=BS, lam=LAM, num_iter=3, mesh=mesh,
            n_true=n_true, center=True,
        )

        def featurize(X_t):
            return jnp.cos(X_t @ Wrf.T + brf)

        W_g, fmean_g, ymean_g, _ = streaming.streaming_bcd_fit_centered(
            jnp.asarray(X), jnp.asarray(Y), featurize=featurize,
            d_feat=d_feat, tile_rows=128, block_size=BS, lam=LAM,
            num_iter=3,
        )
        np.testing.assert_allclose(
            np.asarray(fmean_b), np.asarray(fmean_g), atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(ymean_b), np.asarray(ymean_g), atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(W_b), np.asarray(W_g), atol=2e-3, rtol=2e-3
        )

    def test_block_streamed_estimator_tier(self):
        # The choice's tier decision: a budget below 8*d^2 routes
        # build_estimator to BlockStreamedLeastSquares, and its fit
        # matches BlockLeastSquaresEstimator on the same features.
        from keystone_tpu.data import Dataset
        from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
        from keystone_tpu.ops.learning.streaming_ls import (
            BlockStreamedLeastSquares,
            CosineBankFeaturize,
            StreamingLeastSquaresChoice,
        )

        d_feat = 4 * BS
        Wrf, brf = _bank(d_feat, seed=5)
        bank = CosineBankFeaturize(Wrf, brf)
        choice = StreamingLeastSquaresChoice(
            num_iter=3, lam=LAM, block_size_hint=BS
        )
        choice.budget_bytes = 4.0 * d_feat * d_feat  # below the 8d^2 stash
        est = choice.build_estimator(bank, d_feat)
        assert isinstance(est, BlockStreamedLeastSquares)
        # The stash-budget cap shrank the block size below the hint.
        assert est.block_size <= BS

        rng = np.random.default_rng(3)
        X = rng.normal(size=(512, D_IN)).astype(np.float32)
        Y = rng.normal(size=(512, K)).astype(np.float32) + 0.3
        model = est.fit(Dataset.of(X), Dataset.of(Y))
        F = np.asarray(jnp.cos(jnp.asarray(X) @ Wrf.T + brf))
        # Same block size: BCD iterate sequences are bs-dependent.
        block = BlockLeastSquaresEstimator(est.block_size, 3, lam=LAM).fit(
            Dataset.of(F), Dataset.of(Y)
        )
        p_s = np.asarray(model.batch_apply(Dataset.of(X)).array)
        p_b = np.asarray(block.batch_apply(Dataset.of(F)).array)
        np.testing.assert_allclose(p_s, p_b, atol=5e-3, rtol=5e-3)

        # Gram-feasible budget keeps the gram tier.
        choice.budget_bytes = 1e12
        from keystone_tpu.ops.learning.streaming_ls import (
            StreamingFeaturizedLeastSquares,
        )
        assert isinstance(
            choice.build_estimator(bank, d_feat),
            StreamingFeaturizedLeastSquares,
        )


class TestNorthstar2DCentered:
    def test_2d_centered_matches_1d_centered(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        d_feat = 4 * BS
        Wrf, brf = _bank(d_feat, seed=9)
        mesh2 = mesh_lib.make_mesh(
            (4, 2), (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS)
        )
        mesh1 = mesh_lib.make_mesh()
        n_true, n_pad = 700, 704
        rng = np.random.default_rng(12)
        X = rng.normal(size=(n_true, D_IN)).astype(np.float32)
        Y = rng.normal(size=(n_true, K)).astype(np.float32) + 0.5
        Xp = np.vstack(
            [X, 5.0 + rng.normal(size=(n_pad - n_true, D_IN)).astype(np.float32)]
        )
        Yp = np.vstack([Y, 5.0 * np.ones((n_pad - n_true, K), np.float32)])
        rows = P((mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS))
        W2, fm2, ym2 = streaming.streaming_block_bcd_mesh_2d(
            jax.device_put(jnp.asarray(Xp), NamedSharding(mesh2, rows)),
            jax.device_put(jnp.asarray(Yp), NamedSharding(mesh2, rows)),
            jax.device_put(Wrf, NamedSharding(mesh2, P(mesh_lib.MODEL_AXIS))),
            jax.device_put(brf, NamedSharding(mesh2, P(mesh_lib.MODEL_AXIS))),
            block_size=BS, lam=LAM, num_iter=3, mesh=mesh2, n_true=n_true,
            center=True,
        )
        W1, fm1, ym1 = streaming.streaming_block_bcd_mesh(
            mesh_lib.shard_rows(jnp.asarray(Xp), mesh1),
            mesh_lib.shard_rows(jnp.asarray(Yp), mesh1),
            Wrf, brf, block_size=BS, lam=LAM, num_iter=3, mesh=mesh1,
            n_true=n_true, center=True,
        )
        np.testing.assert_allclose(
            np.asarray(fm2).reshape(-1), np.asarray(fm1), atol=1e-5
        )
        np.testing.assert_allclose(np.asarray(ym2), np.asarray(ym1), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(W2), np.asarray(W1), atol=2e-3, rtol=2e-3
        )
