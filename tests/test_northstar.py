"""North-star dossier tests (NORTHSTAR.md): the block-streamed mesh BCD
program that runs TIMIT at ~200k feature dims on a v5e-16.

Three claims are pinned here on the 8-device CPU mesh:
  1. Numeric parity: the block-streamed mesh sweep equals the resident
     single-device solver on the same features (scaled shapes whose
     PER-DEVICE geometry matches the v5e-16 plan's proportions).
  2. Collective schedule: the compiled HLO contains all-reduces (the
     gram+corr psums) and NO all-gather of a feature-sized operand — the
     program must never materialize or gather the feature matrix.
  3. Live-buffer bound: the compiled program's per-device peak follows the
     dossier's HBM model (raw rows + residual + one block slab + stash),
     NOT the materialized-features model.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.parallel import streaming
from keystone_tpu.parallel.linalg import bcd_least_squares_fused_flat

D_IN, K, BS = 22, 5, 64
LAM = 1e-2


def _bank(d_feat, seed=0):
    rng = np.random.default_rng(seed)
    Wrf = jnp.asarray(rng.normal(size=(d_feat, D_IN)).astype(np.float32) * 0.3)
    brf = jnp.asarray(
        rng.uniform(0, 2 * np.pi, size=(d_feat,)).astype(np.float32)
    )
    return Wrf, brf


class TestNorthstarProgram:
    def test_mesh_block_stream_matches_resident(self):
        # Scaled geometry: 8 devices, 4 blocks of 64, ragged true n.
        d_feat = 4 * BS
        Wrf, brf = _bank(d_feat)
        mesh = mesh_lib.make_mesh()
        n_true, n_pad = 700, 704  # 88 rows/device
        rng = np.random.default_rng(1)
        X = rng.normal(size=(n_true, D_IN)).astype(np.float32)
        Y = rng.normal(size=(n_true, K)).astype(np.float32)
        Xp = np.vstack(
            [X, rng.normal(size=(n_pad - n_true, D_IN)).astype(np.float32)]
        )
        Yp = np.vstack([Y, np.zeros((n_pad - n_true, K), np.float32)])

        W_mesh = streaming.streaming_block_bcd_mesh(
            mesh_lib.shard_rows(jnp.asarray(Xp), mesh),
            mesh_lib.shard_rows(jnp.asarray(Yp), mesh),
            Wrf, brf, block_size=BS, lam=LAM, num_iter=3, mesh=mesh,
            n_true=n_true,
        )
        F = jnp.cos(jnp.asarray(X) @ Wrf.T + brf)
        W_ref = bcd_least_squares_fused_flat(
            F, jnp.asarray(Y), BS, lam=LAM, num_iter=3, use_pallas=False
        )
        np.testing.assert_allclose(
            np.asarray(W_mesh), np.asarray(W_ref), atol=2e-3, rtol=2e-3
        )

    def _lowered(self, d_feat=8 * BS, n_pad=1024):
        Wrf, brf = _bank(d_feat)
        mesh = mesh_lib.make_mesh()
        X = jnp.zeros((n_pad, D_IN), jnp.float32)
        Y = jnp.zeros((n_pad, K), jnp.float32)
        Xs = mesh_lib.shard_rows(X, mesh)
        Ys = mesh_lib.shard_rows(Y, mesh)
        return jax.jit(
            lambda a, b, w, c: streaming.streaming_block_bcd_mesh(
                a, b, w, c, block_size=BS, lam=LAM, num_iter=3, mesh=mesh
            )
        ).lower(Xs, Ys, Wrf, brf)

    def test_hlo_collective_schedule(self):
        lowered = self._lowered()
        hlo = lowered.compile().as_text()
        # The gram+corr psums compile to all-reduces.
        assert "all-reduce" in hlo, "expected psum all-reduces in the HLO"
        # NOTHING feature-matrix-sized may be gathered or materialized:
        # scan for all-gather ops with a d_feat-sized operand. Block slabs
        # (ln, bs) and gram (bs, bs) are fine; (n, d_feat) or (ln, d_feat)
        # are not.
        d_feat = 8 * BS
        for m in re.finditer(r"all-gather[^=\n]*=\s*\S*f32\[([0-9,]+)\]", hlo):
            dims = [int(x) for x in m.group(1).split(",")]
            assert d_feat not in dims, f"feature-width all-gather: {m.group(0)}"

    def test_live_buffer_bound_is_streaming_not_materialized(self):
        d_feat, n_pad = 8 * BS, 1024
        lowered = self._lowered(d_feat, n_pad)
        compiled = lowered.compile()
        mem = compiled.memory_analysis()
        if mem is None:
            pytest.skip("backend exposes no memory analysis")
        ln = n_pad // 8
        # Dossier model (per device, f32 here): raw rows + residual + one
        # block slab + Gramian/factor stash + weights + bank. The
        # materialized-features alternative would hold ln*d_feat floats.
        stash = 2 * (d_feat // BS) * BS * BS
        model = (
            ln * D_IN + ln * K + ln * BS + stash
            + (d_feat // BS) * BS * K + d_feat * (D_IN + 1)
        ) * 4
        materialized = ln * d_feat * 4
        peak = getattr(mem, "temp_size_in_bytes", None)
        if peak is None:
            pytest.skip("no temp_size_in_bytes on this backend")
        # The program's temporaries must sit near the streaming model (x4
        # slack for XLA's scheduling copies), far under materialized + model.
        assert peak <= 4 * model, (peak, model)

    def test_epoch_cost_structure(self):
        # Epochs 2+ must NOT recompute Gramians/factors: the dominant
        # first-epoch cost (nb * 2*ln*bs^2 gram dots + Cholesky) is
        # epoch-invariant and stashed, so the compiled 3-epoch program's
        # FLOP estimate must be far below 3x the 1-epoch program's —
        # later epochs pay only featurize + correlation + update.
        def flops(num_iter):
            d_feat, n_pad = 8 * BS, 1024
            Wrf, brf = _bank(d_feat)
            mesh = mesh_lib.make_mesh()
            Xs = mesh_lib.shard_rows(jnp.zeros((n_pad, D_IN)), mesh)
            Ys = mesh_lib.shard_rows(jnp.zeros((n_pad, K)), mesh)
            compiled = jax.jit(
                lambda a, b, w, c: streaming.streaming_block_bcd_mesh(
                    a, b, w, c, block_size=BS, lam=LAM,
                    num_iter=num_iter, mesh=mesh,
                )
            ).lower(Xs, Ys, Wrf, brf).compile()
            ca = compiled.cost_analysis()
            if not ca or "flops" not in ca:
                pytest.skip("backend exposes no cost analysis")
            return ca["flops"]

        f1, f3 = flops(1), flops(3)
        assert f3 < 2.0 * f1, (f1, f3)
