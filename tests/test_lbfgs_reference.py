"""LBFGSSuite ported: exact recovery of a hand-created linear model —
weights, intercept, and learned feature mean — through the dense LBFGS
solver (LBFGSSuite.scala 'Solve a dense linear system')."""

import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.ops.learning.lbfgs import DenseLBFGSwithL2, run_lbfgs


class TestDenseLBFGSReference:
    def test_fit_intercept_recovers_hand_model(self):
        """b = x·(a − dataMean) + extraBias: the fitted mapper must recover
        x, extraBias, and dataMean to 1e-5."""
        rng = np.random.default_rng(0)
        x = np.array([[5.0, 4.0, 3.0, 2.0, -1.0], [3.0, -1.0, 2.0, -2.0, 1.0]])
        data_mean = np.array([1.0, 0.0, 1.0, 2.0, 0.0])
        extra_bias = np.array([3.0, 4.0])

        A0 = rng.normal(size=(128, 5))
        A = A0 - A0.mean(axis=0) + data_mean  # mean exactly dataMean
        B = (A - data_mean) @ x.T + extra_bias

        mapper = DenseLBFGSwithL2(lam=0.0, num_iterations=200).fit(
            Dataset.of(A), Dataset.of(B)
        )
        preds = np.asarray(mapper.batch_apply(Dataset.of(A)).array)
        np.testing.assert_allclose(preds, B, atol=1e-5)
        np.testing.assert_allclose(np.asarray(mapper.x), x.T, atol=1e-5)
        np.testing.assert_allclose(np.asarray(mapper.b_opt), extra_bias, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(mapper.feature_scaler.mean), data_mean, atol=1e-5
        )

    def test_no_intercept_recovers_weights(self):
        """'no fit intercept': b = A xᵀ solved by the raw core."""
        rng = np.random.default_rng(1)
        x = np.array([[5.0, 4.0, 3.0, 2.0, -1.0], [3.0, -1.0, 2.0, -2.0, 1.0]])
        A = rng.normal(size=(128, 5))
        B = A @ x.T

        W = np.asarray(run_lbfgs(A, B, lam=0.0, num_iterations=200))
        np.testing.assert_allclose(W, x.T, atol=1e-5)
        np.testing.assert_allclose(A @ W, B, atol=1e-5)
