"""Serving-fleet chaos suite (ISSUE 20 acceptance): SIGKILL a plane
process mid-Poisson-storm and the fleet books still balance EXACTLY
(``offered == completed + rejected + failed`` at the router — the
zero-drop contract at PROCESS scope), the watchdog respawns the dead
plane through the ``fleet.plane.spawn`` fault site within its restart
budget, and the merged fleet p99 stays computable through the degraded
window (the dead plane's last-scraped histogram stays in the merge).
Spawn-fault exhaustion ("fleet.plane.spawn" error rules burning the
budget) evicts the plane LOUDLY with the surviving fleet intact; a
fingerprint-corrupted plan ship QUARANTINES the receiving plane (the
"fleet.rpc.send" corrupt site models wire corruption of a shipped
weight plane, caught by the split-plane CRCs).

The Poisson storm legs are marked ``slow`` so the tier-1 wall is
unchanged; run the full suite with ``bin/fleet-chaos`` (or
``pytest -m chaos``).
"""

import copy
import json
import os
import signal
import time

import numpy as np
import pytest

from keystone_tpu.serving.export import export_plan
from keystone_tpu.serving.fleet import (
    FleetPlaneDied,
    FleetRouter,
    FleetSaturated,
)
from keystone_tpu.serving.fleet_plane import (
    ShipRejected,
    decode_plan_ship,
    encode_plan_ship,
)
from keystone_tpu.serving.loadgen import run_multi_tenant_open_loop
from keystone_tpu.utils.faults import FaultPlan, FaultRule

from tests._serving_util import TINY_D_IN, fit_tiny_mnist

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def shipment():
    """One fitted pipeline + its encoded plan ship, shared across the
    module (the fit dominates setup cost)."""
    fitted, X = fit_tiny_mnist()
    plan = export_plan(
        fitted, np.zeros(TINY_D_IN, np.float32), max_batch=8
    )
    return fitted, plan, X, encode_plan_ship(fitted, plan)


def _fleet(ship, num_planes=2, **kw):
    kw.setdefault("replicas_per_plane", 1)
    kw.setdefault("heartbeat_interval_s", 0.1)
    kw.setdefault("heartbeat_timeout_s", 3.0)
    kw.setdefault("restart_budget", 2)
    kw.setdefault("spawn_retry_delay_s", 0.01)
    return FleetRouter(ship, num_planes=num_planes, **kw)


def _books_balance(stats):
    return stats["aggregate_offered"] == (
        stats["completed"] + stats["rejected"] + stats["failed"]
    )


class TestShipIntegrity:
    def test_round_trip_reproduces_fingerprint(self, shipment):
        _fitted, plan, _X, ship = shipment
        rebuilt = decode_plan_ship(copy.deepcopy(ship))
        assert rebuilt.fingerprint == plan.fingerprint

    def test_tampered_weight_plane_rejected(self, shipment):
        """Flip one bit in a shipped split-plane tensor: the per-tensor
        CRC must reject the ship — wrong bits never become a plan."""
        _fitted, _plan, _X, ship = shipment
        bad = copy.deepcopy(ship)
        t = bad.tensors[0]
        plane = t.raw if t.raw is not None else t.hi
        plane.flat[0] ^= 1
        with pytest.raises(ShipRejected, match="CRC"):
            decode_plan_ship(bad)

    def test_wire_corruption_rule_rejected(self, shipment):
        """The chaos-plan form of the same contract: a corrupt rule at
        "fleet.rpc.send" flips bytes inside the decode path and the
        CRC catches it."""
        _fitted, _plan, _X, ship = shipment
        plan = FaultPlan([
            FaultRule("fleet.rpc.send", "corrupt", p=1.0),
        ])
        with plan:
            with pytest.raises(ShipRejected, match="CRC"):
                decode_plan_ship(copy.deepcopy(ship))

    def test_claimed_fingerprint_mismatch_rejected(self, shipment):
        _fitted, _plan, _X, ship = shipment
        bad = copy.deepcopy(ship)
        bad.fingerprint = "0" * len(bad.fingerprint)
        with pytest.raises(ShipRejected, match="fingerprint"):
            decode_plan_ship(bad)


class TestFleetKill:
    def test_sigkill_respawn_books_balance(self, shipment):
        """The tier-1 core of the tentpole: SIGKILL one plane under
        traffic — its in-flight requests fail with the NAMED
        FleetPlaneDied, the watchdog respawns it (new pid), the books
        balance exactly across the kill, and the merged fleet
        histogram keeps the dead plane's observations."""
        _fitted, _plan, X, ship = shipment
        fleet = _fleet(ship, num_planes=2)
        try:
            for i in range(20):
                fleet.submit(X[i % len(X)]).result(timeout=30)
            time.sleep(0.3)  # let the watchdog scrape the histograms
            pre_count = fleet.stats()["fleet_latency_count"]
            assert pre_count >= 20

            victim = fleet.plane_pids()["plane0"]
            os.kill(victim, signal.SIGKILL)
            named = 0
            for i in range(40):
                try:
                    fleet.submit(X[i % len(X)]).result(timeout=30)
                except FleetPlaneDied:
                    named += 1
                time.sleep(0.01)

            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                s = fleet.stats()
                if s["restarts_total"] >= 1 and s["healthy_planes"] == 2:
                    break
                time.sleep(0.1)
            s = fleet.stats()
            assert s["restarts_total"] >= 1
            assert s["healthy_planes"] == 2
            assert s["evicted_planes"] == []
            assert fleet.plane_pids()["plane0"] != victim
            # Books: exact, with every kill-window failure NAMED.
            assert _books_balance(s), s
            assert s["failed"] == named
            # The dead plane's scraped observations survive the kill in
            # the fleet merge.
            assert s["fleet_latency_count"] >= pre_count
            assert s["fleet_p99_latency_s"] is not None
            # Post-respawn the fleet serves normally.
            fleet.submit(X[0]).result(timeout=30)
        finally:
            fleet.close()
        assert fleet.accounting_ok()

    @pytest.mark.slow
    def test_sigkill_mid_poisson_storm(self, shipment):
        """The full acceptance storm: 8 tenants of open-loop Poisson
        arrivals against a 4-plane fleet; one plane SIGKILLed
        mid-storm. The loadgen's books and the router's books must
        BOTH balance, the watchdog must respawn, and the merged p99
        must stay computable through the degraded window."""
        _fitted, _plan, X, ship = shipment
        fleet = _fleet(ship, num_planes=4, replicas_per_plane=1,
                       heartbeat_interval_s=0.05)
        killed = {}
        try:
            def submit(tenant, x, deadline_ms=None):
                return fleet.submit_tenant(tenant, x,
                                           deadline_ms=deadline_ms)

            import threading

            def killer():
                time.sleep(1.2)
                killed["pid"] = fleet.plane_pids()["plane1"]
                os.kill(killed["pid"], signal.SIGKILL)

            kt = threading.Thread(target=killer)
            kt.start()
            report = run_multi_tenant_open_loop(
                submit,
                lambda tenant, i: X[i % len(X)],
                rates_hz={f"t{k}": 30.0 for k in range(8)},
                duration_s=3.0,
                seed=20,
                result_timeout_s=60.0,
            )
            kt.join(timeout=10.0)
            # Loadgen-side books (per tenant) and router-side books
            # must BOTH balance — nothing silently dropped anywhere.
            assert report.accounting_ok()
            s = fleet.stats()
            assert _books_balance(s), s
            agg = sum(r.num_offered for r in report.tenants.values())
            assert s["aggregate_offered"] == agg
            # The kill actually happened and was recovered within the
            # restart budget.
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                s = fleet.stats()
                if s["restarts_total"] >= 1 and s["healthy_planes"] == 4:
                    break
                time.sleep(0.1)
            assert s["restarts_total"] >= 1
            assert s["healthy_planes"] == 4
            assert fleet.plane_pids()["plane1"] != killed["pid"]
            # Merged p99 through the degraded window.
            assert s["fleet_latency_count"] > 0
            assert s["fleet_p99_latency_s"] is not None
            # The storm actually spread: every plane completed work.
            assert all(p["completed"] > 0
                       for p in s["planes"].values())
        finally:
            fleet.close()
        assert fleet.accounting_ok()


class TestSpawnBudget:
    @pytest.mark.slow
    def test_spawn_fault_exhaustion_evicts_loudly(self, shipment):
        """Every respawn attempt fails (injected error rule at
        "fleet.plane.spawn"): the restart budget burns down to a LOUD
        permanent eviction while the surviving plane keeps serving and
        the books stay exact."""
        _fitted, _plan, X, ship = shipment
        fleet = _fleet(ship, num_planes=2, restart_budget=2,
                       heartbeat_interval_s=0.05)
        chaos = FaultPlan([
            FaultRule("fleet.plane.spawn", "error", p=1.0),
        ])
        try:
            fleet.submit(X[0]).result(timeout=30)
            with chaos:
                os.kill(fleet.plane_pids()["plane0"], signal.SIGKILL)
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline:
                    s = fleet.stats()
                    if s["evicted_planes"]:
                        break
                    time.sleep(0.1)
            s = fleet.stats()
            assert s["evicted_planes"] == ["plane0"]
            assert s["healthy_planes"] == 1
            assert s["planes"]["plane0"]["restart_budget_left"] == 0
            # Both budgeted attempts fired through the fault site.
            assert chaos.calls_seen("fleet.plane.spawn") >= 2
            # The survivor still serves; the books still balance.
            fleet.submit(X[0]).result(timeout=30)
            assert _books_balance(fleet.stats())
        finally:
            fleet.close()
        assert fleet.accounting_ok()


class TestQuarantine:
    @pytest.mark.slow
    def test_corrupted_ship_quarantines_plane(self, shipment):
        """Ship a plan whose weight plane is corrupted in transit (the
        "fleet.rpc.send" corrupt rule, installed in the CHILD via
        KEYSTONE_FAULT_PLAN): the plane boots QUARANTINED — it
        heartbeats, refuses traffic with a named error, and never
        serves wrong bits."""
        _fitted, _plan, X, ship = shipment
        spec = json.dumps({
            "rules": [{"site": "fleet.rpc.send", "kind": "corrupt",
                       "p": 1.0}],
            "seed": 0,
        })
        os.environ["KEYSTONE_FAULT_PLAN"] = spec
        try:
            fleet = _fleet(ship, num_planes=1)
        finally:
            os.environ.pop("KEYSTONE_FAULT_PLAN", None)
        try:
            s = fleet.stats()
            assert s["quarantined_planes"] == ["plane0"]
            assert s["healthy_planes"] == 0  # quarantined != eligible
            # The plane process is alive and heartbeating...
            assert fleet.plane_pids()["plane0"] is not None
            # ...but the fleet refuses to route to it, loudly.
            with pytest.raises(FleetPlaneDied, match="quarantined"):
                fleet.submit(X[0])
            s = fleet.stats()
            assert _books_balance(s)
            assert s["failed"] == 1
        finally:
            fleet.close()


class TestCanaryRoll:
    @pytest.mark.slow
    def test_offer_canary_rolls_across_fleet(self, shipment):
        """A candidate ships to every surviving plane and runs each
        plane's OWN lifecycle gate → canary → promotion; the fleet
        reports the new fingerprint everywhere afterwards."""
        _fitted, _plan, X, ship = shipment
        fitted2, _X2 = fit_tiny_mnist(seed=3)
        plan2 = export_plan(
            fitted2, np.zeros(TINY_D_IN, np.float32), max_batch=8
        )
        assert plan2.fingerprint != ship.fingerprint
        ship2 = encode_plan_ship(fitted2, plan2)
        fleet = _fleet(ship, num_planes=2, replicas_per_plane=2)
        try:
            for i in range(10):
                fleet.submit(X[i % len(X)]).result(timeout=30)
            results = fleet.offer_canary(ship2)
            assert set(results) == {"plane0", "plane1"}
            for name, r in results.items():
                assert r["ok"], (name, r)
                assert r["result"]["published"], (name, r)
                assert r["result"]["fingerprint"] == plan2.fingerprint
            # Post-roll traffic serves under the NEW fingerprint.
            y = fleet.submit(X[0])
            y.result(timeout=30)
            stats = fleet.stats()
            assert _books_balance(stats)
        finally:
            fleet.close()

    @pytest.mark.slow
    def test_corrupt_candidate_rejected_fleet_unharmed(self, shipment):
        """A tampered CANDIDATE ship is rejected per-plane by the same
        CRC verification as boot; the incumbent keeps serving."""
        _fitted, _plan, X, ship = shipment
        bad = copy.deepcopy(ship)
        t = bad.tensors[0]
        plane = t.raw if t.raw is not None else t.hi
        plane.flat[0] ^= 1
        fleet = _fleet(ship, num_planes=1)
        try:
            results = fleet.offer_canary(bad)
            assert results["plane0"]["ok"] is False
            assert results["plane0"]["error"] == "ship_rejected"
            fleet.submit(X[0]).result(timeout=30)  # incumbent intact
        finally:
            fleet.close()


class TestAdmission:
    def test_router_bound_sheds_with_named_rejection(self, shipment):
        """The router's own admission bound: past ``max_outstanding``
        submissions shed synchronously with FleetSaturated (a NAMED
        rejection, counted in the books)."""
        _fitted, _plan, X, ship = shipment
        fleet = _fleet(ship, num_planes=1, max_outstanding=4,
                       dispatchers=1)
        try:
            futs, rejected = [], 0
            for i in range(64):
                try:
                    futs.append(fleet.submit(X[i % len(X)]))
                except FleetSaturated:
                    rejected += 1
            for f in futs:
                f.exception(timeout=30)
            assert rejected >= 1
            s = fleet.stats()
            assert s["rejected"] >= rejected
            assert _books_balance(s)
        finally:
            fleet.close()
        assert fleet.accounting_ok()
