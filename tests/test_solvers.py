"""Solver-node tests: parity vs closed forms (contract from the reference's
BlockLinearMapperSuite / LinearMapperSuite)."""

import numpy as np
import pytest

from keystone_tpu.data import Dataset
from keystone_tpu.data.loaders import synthetic_classification
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator, BlockLinearMapper
from keystone_tpu.ops.learning.linear import (
    LinearMapEstimator,
    LinearMapper,
    LocalLeastSquaresEstimator,
)
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels, MaxClassifier


@pytest.fixture
def regression_problem():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 16)) + 1.5  # nonzero mean exercises centering
    W = rng.normal(size=(16, 3))
    Y = X @ W + 0.5 + 0.01 * rng.normal(size=(200, 3))
    return X, Y


def centered_ridge(X, Y, lam):
    xm, ym = X.mean(0), Y.mean(0)
    Xc, Yc = X - xm, Y - ym
    W = np.linalg.solve(Xc.T @ Xc + lam * np.eye(X.shape[1]), Xc.T @ Yc)
    return W, xm, ym


class TestLinearMapEstimator:
    def test_matches_centered_ridge(self, regression_problem):
        X, Y = regression_problem
        lam = 0.3
        model = LinearMapEstimator(lam).fit(Dataset.of(X), Dataset.of(Y))
        W, xm, ym = centered_ridge(X, Y, lam)
        preds = model.batch_apply(Dataset.of(X)).to_numpy()
        expected = (X - xm) @ W + ym
        np.testing.assert_allclose(preds, expected, atol=1e-7)

    def test_matches_local_solver(self, regression_problem):
        X, Y = regression_problem
        dist = LinearMapEstimator(None).fit(Dataset.of(X), Dataset.of(Y))
        local = LocalLeastSquaresEstimator(0.0).fit(Dataset.of(X), Dataset.of(Y))
        p1 = dist.batch_apply(Dataset.of(X)).to_numpy()
        p2 = local.batch_apply(Dataset.of(X)).to_numpy()
        np.testing.assert_allclose(p1, p2, atol=1e-5)


class TestBlockLeastSquares:
    def test_block_model_matches_full_model(self, regression_problem):
        """A BlockLinearMapper over a split model equals the unsplit LinearMapper
        (BlockLinearMapperSuite.scala:18-56)."""
        X, Y = regression_problem
        rng = np.random.default_rng(1)
        W = rng.normal(size=(16, 3))
        full = LinearMapper(W)
        block = BlockLinearMapper([W[:6], W[6:12], W[12:]], block_size=6)
        p_full = full.batch_apply(Dataset.of(X)).to_numpy()
        p_block = block.batch_apply(Dataset.of(X)).to_numpy()
        np.testing.assert_allclose(p_block, p_full, atol=1e-9)

    def test_many_iters_converges_to_exact(self, regression_problem):
        X, Y = regression_problem
        lam = 0.5
        est = BlockLeastSquaresEstimator(block_size=6, num_iter=60, lam=lam)
        model = est.fit(Dataset.of(X), Dataset.of(Y))
        W, xm, ym = centered_ridge(X, Y, lam)
        preds = model.batch_apply(Dataset.of(X)).to_numpy()
        expected = (X - xm) @ W + ym
        np.testing.assert_allclose(preds, expected, atol=1e-5)

    @pytest.mark.slow
    def test_sharded_matches_unsharded(self, regression_problem, mesh8):
        X, Y = regression_problem
        est = BlockLeastSquaresEstimator(block_size=8, num_iter=3, lam=0.1)
        m1 = est.fit(Dataset.of(X), Dataset.of(Y))
        m2 = est.fit(Dataset.of(X).shard(mesh8), Dataset.of(Y).shard(mesh8))
        p1 = m1.batch_apply(Dataset.of(X)).to_numpy()
        p2 = m2.batch_apply(Dataset.of(X).shard(mesh8)).to_numpy()
        np.testing.assert_allclose(p1, p2, atol=1e-7)

    def test_weight(self):
        assert BlockLeastSquaresEstimator(10, 5, 0.0).weight == 16

    def test_apply_and_evaluate_streams_partials(self, regression_problem):
        X, _ = regression_problem
        rng = np.random.default_rng(2)
        W = rng.normal(size=(16, 3))
        block = BlockLinearMapper([W[:8], W[8:]], block_size=8)
        seen = []
        block.apply_and_evaluate(Dataset.of(X), lambda ds: seen.append(ds.to_numpy()))
        assert len(seen) == 2
        np.testing.assert_allclose(seen[-1], X @ W, atol=1e-9)


class TestStandardScaler:
    def test_mean_std(self):
        rng = np.random.default_rng(3)
        X = rng.normal(loc=2.0, scale=3.0, size=(500, 5))
        model = StandardScaler().fit(Dataset.of(X))
        np.testing.assert_allclose(np.asarray(model.mean), X.mean(0), atol=1e-9)
        np.testing.assert_allclose(
            np.asarray(model.std), X.std(0, ddof=1), atol=1e-9)
        out = model.batch_apply(Dataset.of(X)).to_numpy()
        np.testing.assert_allclose(out.mean(0), 0, atol=1e-9)
        np.testing.assert_allclose(out.std(0, ddof=1), 1, atol=1e-9)

    def test_sharded_padding_correct(self, mesh8):
        """Stats over a padded sharded dataset match the unpadded host stats."""
        rng = np.random.default_rng(4)
        X = rng.normal(size=(101, 5))  # 101 % 8 != 0 -> padding
        ds = Dataset.of(X).shard(mesh8)
        model = StandardScaler().fit(ds)
        np.testing.assert_allclose(np.asarray(model.mean), X.mean(0), atol=1e-9)
        np.testing.assert_allclose(np.asarray(model.std), X.std(0, ddof=1), atol=1e-9)

    def test_zero_std_guard(self):
        X = np.ones((10, 3))
        model = StandardScaler().fit(Dataset.of(X))
        np.testing.assert_allclose(np.asarray(model.std), 1.0)


class TestEndToEndClassification:
    def test_block_ls_classifier(self):
        train = synthetic_classification(512, 20, 4, seed=0)
        test = synthetic_classification(256, 20, 4, seed=1)
        labels = ClassLabelIndicatorsFromIntLabels(4)(train.labels)
        est = BlockLeastSquaresEstimator(block_size=10, num_iter=3, lam=1.0)
        model = est.fit(train.data, labels)
        preds = MaxClassifier()(model.batch_apply(test.data))
        metrics = MulticlassClassifierEvaluator(4).evaluate(preds, test.labels)
        assert metrics.accuracy > 0.9
        assert "Accuracy" in metrics.summary()


class TestSketchedLeastSquares:
    @pytest.mark.slow
    def test_recovers_solution_with_refinement(self):
        from keystone_tpu.ops.learning.linear import (
            LinearMapEstimator,
            SketchedLeastSquaresEstimator,
        )

        rng = np.random.default_rng(0)
        n, d, k = 2048, 32, 3
        X = rng.normal(size=(n, d)).astype(np.float64)
        W = rng.normal(size=(d, k))
        Y = X @ W + 0.01 * rng.normal(size=(n, k))

        exact = LinearMapEstimator(lam=1e-3).fit(Dataset.of(X), Dataset.of(Y))
        sk = SketchedLeastSquaresEstimator(
            lam=1e-3, sketch_factor=8, refine_iters=3
        ).fit(Dataset.of(X), Dataset.of(Y))

        pe = np.asarray(exact.batch_apply(Dataset.of(X)).to_numpy())
        ps = np.asarray(sk.batch_apply(Dataset.of(X)).to_numpy())
        # Hessian-sketch refinement closes the gap to the exact solve.
        rel = np.abs(ps - pe).max() / np.abs(pe).max()
        assert rel < 1e-2, rel

    def test_sketch_only_residual_bound(self):
        from keystone_tpu.ops.learning.linear import SketchedLeastSquaresEstimator

        rng = np.random.default_rng(1)
        n, d, k = 4096, 16, 2
        X = rng.normal(size=(n, d)).astype(np.float64)
        Y = X @ rng.normal(size=(d, k)) + 0.5 * rng.normal(size=(n, k))

        sk = SketchedLeastSquaresEstimator(
            lam=0.0, sketch_factor=8, refine_iters=0
        ).fit(Dataset.of(X), Dataset.of(Y))
        preds = np.asarray(sk.batch_apply(Dataset.of(X)).to_numpy())
        res_sk = np.linalg.norm(preds - Y)
        # Optimal residual from lstsq on centered data.
        Xc, Yc = X - X.mean(0), Y - Y.mean(0)
        W_opt, *_ = np.linalg.lstsq(Xc, Yc, rcond=None)
        res_opt = np.linalg.norm(Xc @ W_opt - Yc)
        assert res_sk <= 1.5 * res_opt, (res_sk, res_opt)

    def test_sharded_matches_unsharded(self, mesh8):
        from keystone_tpu.ops.learning.linear import SketchedLeastSquaresEstimator

        rng = np.random.default_rng(2)
        X = rng.normal(size=(128, 8)).astype(np.float64)
        Y = rng.normal(size=(128, 2)).astype(np.float64)
        est = lambda: SketchedLeastSquaresEstimator(lam=1e-2, refine_iters=2)
        m1 = est().fit(Dataset.of(X), Dataset.of(Y))
        m2 = est().fit(Dataset.of(X).shard(mesh8), Dataset.of(Y).shard(mesh8))
        p1 = np.asarray(m1.batch_apply(Dataset.of(X)).to_numpy())
        p2 = np.asarray(m2.batch_apply(Dataset.of(X).shard(mesh8)).to_numpy())
        np.testing.assert_allclose(p1, p2, atol=1e-5)

    def test_approximate_candidate_is_opt_in(self):
        from keystone_tpu.ops.learning.cost import LeastSquaresEstimator
        from keystone_tpu.ops.learning.linear import SketchedLeastSquaresEstimator

        def has_sketched(est):
            return any(
                isinstance(opt, SketchedLeastSquaresEstimator)
                for opt, _ in est.options
            )

        assert not has_sketched(LeastSquaresEstimator(lam=0.1))
        assert has_sketched(LeastSquaresEstimator(lam=0.1, allow_approximate=True))


class TestRankDeficientBlocks:
    def test_wide_block_f32_lam_zero_stays_finite(self):
        """block_size > n with λ=0 in f32: the rank-deficient Gramian defeats
        Cholesky; the scale-relative LU rescue must keep the solve finite and
        near the minimum-norm fit (the TimitPipeline demo shape that returned
        99% NaN-error before round 2)."""
        rng = np.random.default_rng(0)
        n, d, k = 48, 128, 3
        F = rng.normal(size=(n, d)).astype(np.float32)
        Y = rng.normal(size=(n, k)).astype(np.float32)
        est = BlockLeastSquaresEstimator(block_size=d, num_iter=2, lam=0.0)
        model = est.fit(Dataset.of(F), Dataset.of(Y))
        preds = np.asarray(model.batch_apply(Dataset.of(F)).array)
        assert np.isfinite(preds).all()
        # d > n: the (jittered) interpolating fit should be near-exact.
        assert np.abs(preds - Y).max() < 0.05


class TestNystromKernelRidge:
    def _problem(self):
        rng = np.random.default_rng(3)
        X = rng.normal(size=(256, 6)).astype(np.float32)
        y = (np.sin(X[:, 0]) + 0.5 * X[:, 1] ** 2 > 0.5).astype(np.int64)
        Y = (2.0 * np.eye(2)[y] - 1.0).astype(np.float32)
        return X, Y, y

    def test_close_to_exact_krr(self):
        from keystone_tpu.ops.learning.kernel import (
            GaussianKernelGenerator,
            KernelRidgeRegression,
            NystromKernelRidge,
        )

        X, Y, y = self._problem()
        gen = GaussianKernelGenerator(gamma=0.5)
        exact = KernelRidgeRegression(gen, 1e-3, 64, 4).fit(
            Dataset.of(X), Dataset.of(Y)
        )
        nystrom = NystromKernelRidge(gen, 1e-3, num_landmarks=64).fit(
            Dataset.of(X), Dataset.of(Y)
        )
        pe = np.asarray(exact.batch_apply(Dataset.of(X)).to_numpy()).argmax(1)
        pn = np.asarray(nystrom.batch_apply(Dataset.of(X)).to_numpy()).argmax(1)
        # Both should classify the training set nearly identically.
        assert (pe == y).mean() > 0.95
        assert (pn == y).mean() > 0.92

    def test_uniform_landmarks(self):
        from keystone_tpu.ops.learning.kernel import (
            GaussianKernelGenerator,
            NystromKernelRidge,
        )

        X, Y, y = self._problem()
        m = NystromKernelRidge(
            GaussianKernelGenerator(0.5), 1e-3, 48, kmeans_landmarks=False
        ).fit(Dataset.of(X), Dataset.of(Y))
        pn = np.asarray(m.batch_apply(Dataset.of(X)).to_numpy()).argmax(1)
        assert (pn == y).mean() > 0.9

    def test_landmarks_capped_at_n(self):
        from keystone_tpu.ops.learning.kernel import (
            GaussianKernelGenerator,
            NystromKernelRidge,
        )

        X, Y, _ = self._problem()
        m = NystromKernelRidge(
            GaussianKernelGenerator(0.5), 1e-3, num_landmarks=10_000,
            kmeans_landmarks=False,
        ).fit(Dataset.of(X[:32]), Dataset.of(Y[:32]))
        assert m.landmarks.shape[0] == 32

    def test_sharded_data_unpadded_labels(self, mesh8):
        """Nystrom fit aligns differing physical paddings (mesh-padded data
        vs unpadded labels)."""
        from keystone_tpu.ops.learning.kernel import (
            GaussianKernelGenerator,
            NystromKernelRidge,
        )

        rng = np.random.default_rng(9)
        X = rng.normal(size=(30, 4)).astype(np.float32)  # pads to 32 on mesh8
        Y = rng.normal(size=(30, 2)).astype(np.float32)
        m = NystromKernelRidge(
            GaussianKernelGenerator(0.3), 1e-3, 16, kmeans_landmarks=False
        ).fit(Dataset.of(X).shard(mesh8), Dataset.of(Y))
        out = m.batch_apply(Dataset.of(X)).to_numpy()
        assert out.shape == (30, 2) and np.isfinite(out).all()
