"""Streamed ZCA whitening: batch-estimator parity and the kill→resume
bit-identity contract on the existing CheckpointSpec machinery (ISSUE 18
tentpole). The kill/resume case is chaos-marked but fast (tiny d, six
segments) so the contract is exercised in tier-1."""

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.data import Dataset
from keystone_tpu.data.durable import CheckpointSpec
from keystone_tpu.data.prefetch import ShardSource
from keystone_tpu.data.shards import DiskDenseShards
from keystone_tpu.ops.learning.pca import (
    StreamedZCAWhitenerEstimator,
    ZCAWhitenerEstimator,
)
from keystone_tpu.utils.faults import FaultPlan, FaultRule


def _problem(tmp_path, n=700, d=12, tile=64, tps=2, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32) * 2.0 + 0.5
    Y = np.zeros((n, 1), dtype=np.float32)
    shards = DiskDenseShards.write(
        str(tmp_path / "dense"), X, Y, tile_rows=tile, tiles_per_segment=tps
    )
    return X, shards


class TestStreamedParity:
    def test_matches_batch_estimator(self, tmp_path):
        X, shards = _problem(tmp_path)
        batch = ZCAWhitenerEstimator(eps=0.1).fit_single(X)
        streamed = StreamedZCAWhitenerEstimator(eps=0.1).fit_source(
            shards.as_source()
        )
        np.testing.assert_allclose(
            np.asarray(streamed.means), np.asarray(batch.means),
            rtol=1e-5, atol=1e-5,
        )
        # Covariance-eigh route vs centered SVD: same algebra, different
        # factorization — whitener parity to f32 eigensolve tolerance.
        np.testing.assert_allclose(
            np.asarray(streamed.whitener), np.asarray(batch.whitener),
            rtol=5e-3, atol=5e-3,
        )
        xw_s = np.asarray(streamed.apply(X[:50]))
        xw_b = np.asarray(batch.apply(X[:50]))
        np.testing.assert_allclose(xw_s, xw_b, rtol=5e-3, atol=5e-3)

    def test_resident_dataset_falls_back_to_batch_path(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(80, 6)).astype(np.float32)
        got = StreamedZCAWhitenerEstimator(eps=0.2).fit(Dataset(X))
        want = ZCAWhitenerEstimator(eps=0.2).fit_single(X)
        np.testing.assert_array_equal(
            np.asarray(got.whitener), np.asarray(want.whitener)
        )

    def test_too_few_rows_raises(self):
        est = StreamedZCAWhitenerEstimator()
        with pytest.raises(ValueError, match="n >= 2"):
            est._finalize(jnp.zeros((3,)), jnp.zeros((3, 3)), 1)

    def test_shard_backed_dataset_view_ignores_pad_rows(self, tmp_path):
        # A shard-backed Dataset's row view (DenseShardView) zero-pads
        # its tail segment to the fixed segment shape. Pad rows are zero
        # in the (Σx, XᵀX) fold, but counting them as true rows shrinks
        # the mean/covariance — fit() must produce the same whitener the
        # batch estimator gets from the true rows.
        X, shards = _problem(tmp_path, n=700, d=12, tile=64, tps=2)
        labeled = shards.as_labeled_data()
        view = labeled.data.shard_source
        padded = sum(
            view.load(s).shape[0] for s in range(view.num_segments)
        )
        assert padded > view.n_true  # the fixture really has pad rows
        got = StreamedZCAWhitenerEstimator(eps=0.1).fit(labeled.data)
        want = ZCAWhitenerEstimator(eps=0.1).fit_single(X)
        np.testing.assert_allclose(
            np.asarray(got.means), np.asarray(want.means),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(got.whitener), np.asarray(want.whitener),
            rtol=5e-3, atol=5e-3,
        )

    def test_fit_source_loads_each_segment_exactly_once(self, tmp_path):
        # The row width comes from the source's shape metadata, not an
        # extra load(0) — on an image source that probe would decode a
        # whole segment (and fire its fault sites) twice.
        X, shards = _problem(tmp_path, n=200, d=6, tile=32, tps=2)
        inner = shards.as_source()
        calls = []

        class Counting(ShardSource):
            num_segments = inner.num_segments
            n_true = inner.n_true
            d_in = inner.d_in

            def load(self, s):
                calls.append(s)
                return inner.load(s)

        got = StreamedZCAWhitenerEstimator(
            eps=0.1, prefetch_depth=0
        ).fit_source(Counting())
        assert sorted(calls) == list(range(inner.num_segments))
        want = ZCAWhitenerEstimator(eps=0.1).fit_single(X)
        np.testing.assert_allclose(
            np.asarray(got.whitener), np.asarray(want.whitener),
            rtol=5e-3, atol=5e-3,
        )

    def test_fit_source_falls_back_to_load0_without_metadata(
        self, tmp_path
    ):
        X, shards = _problem(tmp_path, n=150, d=5, tile=32, tps=2)
        inner = shards.as_source()

        class Bare(ShardSource):
            num_segments = inner.num_segments
            n_true = inner.n_true

            def load(self, s):
                return inner.load(s)

        got = StreamedZCAWhitenerEstimator(
            eps=0.1, prefetch_depth=0
        ).fit_source(Bare())
        want = ZCAWhitenerEstimator(eps=0.1).fit_single(X)
        np.testing.assert_allclose(
            np.asarray(got.whitener), np.asarray(want.whitener),
            rtol=5e-3, atol=5e-3,
        )


@pytest.mark.chaos
class TestZCAKillResume:
    def test_killed_and_resumed_bit_identical(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KEYSTONE_RETRY_BASE_S", "0.001")
        X, shards = _problem(tmp_path)
        assert shards.num_segments >= 5

        def fit(**kw):
            est = StreamedZCAWhitenerEstimator(eps=0.1, **kw)
            return est.fit_source(shards.as_source())

        ref = fit()  # uninterrupted reference

        ck = CheckpointSpec(str(tmp_path / "ck"), every_segments=2)
        # Exhaust the 3-attempt retry budget on a mid-run segment load.
        kill = FaultPlan([FaultRule("prefetch.read", "error",
                                    calls=[4, 5, 6])])
        with kill:
            with pytest.raises(OSError):
                fit(checkpoint=ck)
        assert ck.has_snapshot(), (
            "the killed ZCA fit left no snapshot to resume from"
        )

        resumed = fit(checkpoint=ck)  # resume, no faults
        np.testing.assert_array_equal(
            np.asarray(ref.means), np.asarray(resumed.means)
        )
        np.testing.assert_array_equal(
            np.asarray(ref.whitener), np.asarray(resumed.whitener)
        )
        # Completion cleared the snapshot: the next fit starts fresh.
        assert not ck.has_snapshot()
