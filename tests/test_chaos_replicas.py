"""Replicated-plane chaos suite (ISSUE 7 acceptance): replica kill under
open-loop Poisson load recovers within the restart budget with zero
silently-dropped requests (every submitted future resolves with a result
or a NAMED error), spawn faults burn the budget to loud permanent
eviction, and hot-swap under sustained load drops nothing while every
response stays bit-identical to offline apply under the plan fingerprint
recorded on it.

Driven by the deterministic fault harness's ``serving.replica.execute``
(loop-level — kills the whole replica worker, not one batch) and
``serving.replica.spawn`` (burns restart budget) sites. The Poisson
storm legs are marked ``slow`` so the tier-1 wall is unchanged; run the
full suite with ``pytest -m chaos``.
"""

import threading
import time

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.data import Dataset
from keystone_tpu.serving import (
    ReplicatedServer,
    ServerDegraded,
    export_plan,
    run_open_loop,
)
from keystone_tpu.utils.faults import FaultPlan, FaultRule

from tests._serving_util import TINY_D_IN, fit_tiny_mnist

pytestmark = pytest.mark.chaos


def _plane(num_replicas=3, seed=0, **kw):
    fitted, X = fit_tiny_mnist(seed=seed)
    plan = export_plan(fitted, np.zeros(TINY_D_IN, np.float32), max_batch=8)
    kw.setdefault("max_wait_ms", 0.5)
    kw.setdefault("watchdog_interval_s", 0.01)
    return fitted, plan, X, ReplicatedServer(
        plan, num_replicas=num_replicas, **kw
    )


class TestReplicaKill:
    def test_kill_restart_full_health(self):
        """An injected loop-level error kills one replica worker; its
        in-flight request fails with the NAMED ServerDegraded; the
        watchdog restarts it from the exported plan and the plane
        returns to full health with exactly one budget unit burned."""
        _, plan, X, srv = _plane(num_replicas=3)
        kill = FaultPlan([FaultRule("serving.replica.execute", "error",
                                    calls=[0])])
        named_errors = 0
        try:
            with kill:
                for i in range(30):
                    try:
                        srv.submit(X[i % len(X)]).result(timeout=30)
                    except (ServerDegraded, OSError):
                        named_errors += 1
                    time.sleep(0.01)
            stats = srv.stats()
            assert named_errors >= 1  # the killed worker's in-flight
            assert stats["restarts_total"] == 1
            assert stats["healthy_replicas"] == 3
            assert not stats["degraded"]
            assert stats["evicted_replicas"] == []
            # Post-recovery the plane serves normally again.
            srv.submit(X[0]).result(timeout=30)
        finally:
            srv.close()

    def test_spawn_faults_exhaust_budget_to_loud_eviction(self):
        """Every respawn attempt fails (injected at
        serving.replica.spawn): the budget burns down and the replica is
        PERMANENTLY evicted — visible in degraded stats — while the
        surviving replica keeps serving."""
        _, plan, X, srv = _plane(num_replicas=2, restart_budget=2)
        chaos = FaultPlan([
            FaultRule("serving.replica.execute", "error", calls=[0]),
            FaultRule("serving.replica.spawn", "error", p=1.0),
        ])
        try:
            with chaos:
                try:
                    srv.submit(X[0]).result(timeout=30)
                except (ServerDegraded, OSError):
                    pass
                deadline = time.perf_counter() + 10.0
                while (not srv.stats()["evicted_replicas"]
                       and time.perf_counter() < deadline):
                    time.sleep(0.02)
            stats = srv.stats()
            assert len(stats["evicted_replicas"]) == 1
            assert stats["degraded"]
            assert stats["healthy_replicas"] == 1
            evicted = stats["evicted_replicas"][0]
            assert stats["per_replica"][evicted]["restarts"] == 2
            # The survivor still serves.
            out = srv.submit(X[0])
            out.result(timeout=30)
            assert out.replica_index != evicted
        finally:
            srv.close()

    def test_zero_restart_budget_evicts_on_first_death(self):
        _, plan, X, srv = _plane(num_replicas=2, restart_budget=0)
        kill = FaultPlan([FaultRule("serving.replica.execute", "error",
                                    calls=[0])])
        try:
            with kill:
                try:
                    srv.submit(X[0]).result(timeout=30)
                except (ServerDegraded, OSError):
                    pass
                deadline = time.perf_counter() + 10.0
                while (not srv.stats()["evicted_replicas"]
                       and time.perf_counter() < deadline):
                    time.sleep(0.02)
            stats = srv.stats()
            assert len(stats["evicted_replicas"]) == 1
            assert stats["restarts_total"] == 0
        finally:
            srv.close()

    @pytest.mark.slow
    def test_kill_under_poisson_storm_recovers_with_zero_silent_drops(self):
        """The acceptance drill: a replica dies mid-Poisson-storm. Every
        offered request is accounted for (completed + rejected + failed
        == offered — run_open_loop resolves every future), the handful
        of failures are the killed worker's in-flight (named errors,
        bounded), the watchdog restores full health, and the post-storm
        plane's latency is back at steady state."""
        _, plan, X, srv = _plane(num_replicas=3, max_queue_depth=4096)
        # Kill whichever replica executes the ~40th batch of the storm.
        kill = FaultPlan([FaultRule("serving.replica.execute", "error",
                                    calls=[40])])
        try:
            with kill:
                report = run_open_loop(
                    srv.submit, lambda i: X[i % len(X)],
                    rate_hz=300.0, duration_s=3.0, seed=11,
                )
            stats = srv.stats()
            # ZERO silent drops: every future resolved one way.
            assert (report.completed + report.rejected + report.failed
                    == report.num_offered)
            assert report.completed > 0.9 * report.num_offered
            assert 1 <= report.failed <= 64  # the dead worker's in-flight
            # Per-replica attribution covers every completion.
            assert sum(report.per_replica_completed.values()) \
                == report.completed
            assert set(report.per_replica_completed) == {0, 1, 2}
            # Recovered: restart happened, full health, nobody evicted.
            assert stats["restarts_total"] >= 1
            assert stats["healthy_replicas"] == 3
            assert stats["evicted_replicas"] == []
            # p99 degrades gracefully, not catastrophically: the storm's
            # tail stays within the coalescing-window regime rather than
            # the multi-second restart window.
            assert report.p99_latency_s < 1.0
        finally:
            srv.close()


class TestHotSwapUnderLoad:
    @pytest.mark.slow
    def test_swap_under_sustained_load_zero_drop_bit_identical(self):
        """The acceptance drill: swap_plan under sustained submissions.
        ZERO requests dropped (no errors of any kind), both plan
        versions appear, and EVERY response is bit-identical to offline
        apply under the fingerprint recorded on it — no mixed-plan
        batches, by construction."""
        fitted1, X = fit_tiny_mnist(seed=0)
        fitted2, _ = fit_tiny_mnist(seed=42)
        plan1 = export_plan(fitted1, np.zeros(TINY_D_IN, np.float32),
                            max_batch=8)
        plan2 = export_plan(fitted2, np.zeros(TINY_D_IN, np.float32),
                            max_batch=8)
        by_fp = {plan1.fingerprint: fitted1, plan2.fingerprint: fitted2}
        assert plan1.fingerprint != plan2.fingerprint

        srv = ReplicatedServer(plan1, num_replicas=3, max_wait_ms=0.5,
                               drain_timeout_s=30.0)
        swap_err = []

        def _swap():
            try:
                srv.swap_plan(plan2)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                swap_err.append(e)

        swapper = threading.Thread(target=_swap)
        records = []  # (x, future)
        try:
            n = 400
            for i in range(n):
                x = X[i % len(X)]
                records.append((x, srv.submit(x)))
                if i == n // 3:
                    swapper.start()  # swap rolls while load continues
                time.sleep(0.002)
            swapper.join(timeout=60)
            assert not swapper.is_alive()
            assert not swap_err, swap_err
            outs = [f.result(timeout=30) for _, f in records]  # no errors
        finally:
            if swapper.ident is not None:
                swapper.join(timeout=60)
            srv.close()

        fps = {f.plan_fingerprint for _, f in records}
        assert fps == set(by_fp), fps  # both versions actually served
        # Bit-identity per fingerprint: group responses by the version
        # stamped on them, compare against THAT version's offline apply.
        for fp, fitted in by_fp.items():
            idx = [i for i, (_, f) in enumerate(records)
                   if f.plan_fingerprint == fp]
            served = np.stack([np.asarray(outs[i]) for i in idx])
            batch = np.stack([records[i][0] for i in idx])
            offline = np.asarray(
                fitted.apply(Dataset.of(jnp.asarray(batch))).array
            )
            np.testing.assert_array_equal(served, offline)
        stats = srv.stats()
        assert stats["swaps_completed"] == 1
        assert stats["failed"] == 0 and stats["rejected"] == 0
        assert stats["completed"] == len(records)
