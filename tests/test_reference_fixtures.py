"""Golden-contract tests on the reference's own committed fixture data.

The reference ships real fixture matrices (src/test/resources/{aMat,bMat}.csv
et al.) and asserts solver contracts on them in
BlockWeightedLeastSquaresSuite.scala:
  - the BWLS solution has ~zero gradient of the weighted objective
    (":143-167", tol 1e-2 on the gradient norm);
  - the PerClass solver matches the BlockWeighted solver to 1e-6
    (":115-140");
  - degenerate fixtures (single class, block size not dividing d) fit.

These tests run OUR solvers against the SAME fixture data (read directly
from the read-only reference checkout) and the same assertions, with the
gradient computed by an independent numpy implementation of the weighted
objective — external evidence the mixture algebra matches, not just
self-consistency.
"""

import os

import numpy as np
import pytest

from keystone_tpu.data import Dataset
from keystone_tpu.ops.learning.bwls import BlockWeightedLeastSquaresEstimator
from keystone_tpu.ops.learning.rwls import PerClassWeightedLeastSquaresEstimator

from _reference import RESOURCES as _RES, needs_reference_fixtures

pytestmark = needs_reference_fixtures


def _load(name):
    return np.loadtxt(os.path.join(_RES, name), delimiter=",")


def _weighted_gradient(A, B, lam, mw, X, b):
    """Gradient of the class-weighted objective, independently in numpy
    (the formula of BlockWeightedLeastSquaresSuite.computeGradient):
    W[i, j] = (1−mw)/n (+ mw/n_class(i) on the row's own class column);
    grad = Aᵀ((A X + b − B) ∘ W) + λX."""
    n, k = B.shape
    cls = B.argmax(axis=1)
    counts = np.bincount(cls, minlength=k)
    neg = (1.0 - mw) / n
    W = np.full((n, k), neg)
    W[np.arange(n), cls] += mw / counts[cls]
    P = A @ X + b[None, :] - B
    return A.T @ (P * W) + lam * X


def _model_of(mapper):
    return np.concatenate([np.asarray(x) for x in mapper.xs], axis=0)


class TestBWLSOnReferenceFixtures:
    @pytest.mark.slow
    def test_solution_has_zero_gradient(self):
        A, B = _load("aMat.csv"), _load("bMat.csv")
        est = BlockWeightedLeastSquaresEstimator(4, 10, 0.1, 0.3)
        m = est.fit(Dataset.of(A), Dataset.of(B))
        grad = _weighted_gradient(
            A, B, 0.1, 0.3, _model_of(m), np.asarray(m.b_opt)
        )
        # Reference: Stats.aboutEq(norm(gradient), 0, 1e-2).
        assert np.linalg.norm(grad) < 1e-2

    @pytest.mark.slow
    def test_per_class_matches_block_weighted(self):
        A, B = _load("aMat.csv"), _load("bMat.csv")
        wsq = BlockWeightedLeastSquaresEstimator(4, 5, 0.1, 0.3).fit(
            Dataset.of(A), Dataset.of(B)
        )
        pcs = PerClassWeightedLeastSquaresEstimator(4, 5, 0.1, 0.3).fit(
            Dataset.of(A), Dataset.of(B)
        )
        diff = np.linalg.norm(_model_of(wsq) - _model_of(pcs))
        assert diff < 1e-6
        assert abs(
            np.linalg.norm(np.asarray(wsq.b_opt))
            - np.linalg.norm(np.asarray(pcs.b_opt))
        ) < 1e-6

    def test_single_class_fixture_fits(self):
        A, B = _load("aMat-1class.csv"), _load("bMat-1class.csv")
        if B.ndim == 1:
            B = B[:, None]
        m = BlockWeightedLeastSquaresEstimator(4, 10, 0.1, 0.3).fit(
            Dataset.of(A), Dataset.of(B)
        )
        assert np.isfinite(_model_of(m)).all()

    @pytest.mark.slow
    def test_block_size_not_dividing_num_features(self):
        A, B = _load("aMat.csv"), _load("bMat.csv")  # d=12, bs=5
        m = BlockWeightedLeastSquaresEstimator(5, 10, 0.1, 0.3).fit(
            Dataset.of(A), Dataset.of(B)
        )
        grad = _weighted_gradient(
            A, B, 0.1, 0.3, _model_of(m), np.asarray(m.b_opt)
        )
        # Reference tolerance for the ragged-block case is 1e-1
        # (BlockWeightedLeastSquaresSuite "nFeatures not divisible").
        assert np.linalg.norm(grad) < 1e-1

        pcs = PerClassWeightedLeastSquaresEstimator(5, 10, 0.1, 0.3).fit(
            Dataset.of(A), Dataset.of(B)
        )
        pcs_grad = _weighted_gradient(
            A, B, 0.1, 0.3, _model_of(pcs), np.asarray(pcs.b_opt)
        )
        assert np.linalg.norm(pcs_grad) < 1e-1

    def test_shuffled_rows_same_solution(self):
        """Row order must not matter (the shuffled fixture pair exists for
        exactly this: the class-sort replaces the hash partitioner)."""
        A, B = _load("aMat.csv"), _load("bMat.csv")
        As, Bs = _load("aMatShuffled.csv"), _load("bMatShuffled.csv")
        est = BlockWeightedLeastSquaresEstimator(4, 5, 0.1, 0.3)
        m1 = est.fit(Dataset.of(A), Dataset.of(B))
        m2 = est.fit(Dataset.of(As), Dataset.of(Bs))
        np.testing.assert_allclose(
            _model_of(m1), _model_of(m2), atol=1e-8
        )
