"""Ports of the last three un-mirrored reference suites:
NaiveBayesModelSuite.scala (parameter recovery from generated multinomial
data), ZCAWhiteningSuite.scala (identity covariance incl. the negative
large-epsilon assertion), LogisticRegressionModelSuite.scala (binary
slope/accuracy recovery and the multinomial fit against R-computed golden
weights — an external golden committed upstream in the suite source).
"""

import numpy as np
import pytest

from keystone_tpu.data import Dataset
from keystone_tpu.ops.learning.classifiers import (
    LogisticRegressionEstimator,
    NaiveBayesEstimator,
)
from keystone_tpu.ops.learning.pca import ZCAWhitenerEstimator


# ---------------------------------------------------------------------------
# NaiveBayesModelSuite.scala
# ---------------------------------------------------------------------------


def _generate_nb_input(log_pi, log_theta, n, seed, sample=10):
    """Reference generator (NaiveBayesModelSuite.scala:23-57): class drawn
    from exp(log_pi), features are counts of `sample` multinomial draws from
    exp(log_theta[class])."""
    rng = np.random.default_rng(seed)
    pi = np.exp(log_pi)
    theta = np.exp(log_theta)
    ys, xs = [], []
    for _ in range(n):
        y = int(rng.choice(len(pi), p=pi / pi.sum()))
        counts = rng.multinomial(sample, theta[y] / theta[y].sum())
        ys.append(y)
        xs.append(counts.astype(np.float64))
    return np.asarray(xs), np.asarray(ys)


class TestNaiveBayesReference:
    def test_multinomial_parameter_recovery(self):
        # NaiveBayesModelSuite.scala:95-117 ("Naive Bayes Multinomial").
        log_pi = np.log([0.5, 0.1, 0.4])
        log_theta = np.log(
            [
                [0.70, 0.10, 0.10, 0.10],
                [0.10, 0.70, 0.10, 0.10],
                [0.10, 0.10, 0.70, 0.10],
            ]
        )
        X, y = _generate_nb_input(log_pi, log_theta, 1000, seed=42)
        model = NaiveBayesEstimator(3, lam=1.0).fit(
            Dataset.of(X), Dataset.of(y)
        )
        # validateModelFit: recovered exp(pi)/exp(theta) within 0.05
        np.testing.assert_allclose(
            np.exp(np.asarray(model.pi)), np.exp(log_pi), atol=0.05
        )
        np.testing.assert_allclose(
            np.exp(np.asarray(model.theta)), np.exp(log_theta), atol=0.05
        )
        # validatePrediction on fresh data: < 20% wrong
        Xv, yv = _generate_nb_input(log_pi, log_theta, 1000, seed=17)
        preds = np.asarray(model.batch_apply(Dataset.of(Xv)).array).argmax(1)
        assert (preds != yv).mean() < 0.2


# ---------------------------------------------------------------------------
# ZCAWhiteningSuite.scala
# ---------------------------------------------------------------------------


class TestZCAWhiteningReference:
    NROWS, NDIM = 10000, 10

    @classmethod
    def _cov_deviation(cls, eps):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(cls.NROWS, cls.NDIM))
        wx = np.asarray(
            ZCAWhitenerEstimator(eps=eps).fit_single(X).apply(X),
            dtype=np.float64,
        )
        cov = np.cov(wx, rowvar=False)
        return np.abs(cov - np.eye(cls.NDIM)).max()

    def test_whitening_with_small_epsilon(self):
        # ZCAWhiteningSuite.scala:26-29
        assert self._cov_deviation(1e-12) < 1e-4

    def test_whitening_with_large_epsilon(self):
        # ZCAWhiteningSuite.scala:31-37: still roughly white at 0.1, but a
        # large epsilon must be measurably noisy (the negative assertion).
        dev = self._cov_deviation(0.1)
        assert dev < 0.1
        assert dev >= 1e-4


# ---------------------------------------------------------------------------
# LogisticRegressionModelSuite.scala
# ---------------------------------------------------------------------------


def _generate_logistic_input(offset, scale, n, seed):
    """Reference generator: y ~ Bernoulli(logistic(offset + scale*x))."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=n)
    p = 1.0 / (1.0 + np.exp(-(offset + scale * x)))
    y = (rng.random(n) < p).astype(np.int64)
    return x[:, None], y


class TestLogisticRegressionReference:
    def test_binary_recovers_slope(self):
        # "logistic regression with LBFGS": A=0, B=-0.8, n=10000; the
        # learned slope within 0.03 of B and validation accuracy > 0.65.
        # (Our model is softmax-parameterized; the MLlib pivot slope is
        # W[:,1] - W[:,0].)
        A, B = 0.0, -0.8
        # n=50000 (reference: 10000): our RNG stream differs from Scala's,
        # so the slope must be compared to the POPULATION value; at n=10000
        # the slope's sampling SE (~0.025) alone can exceed the reference's
        # 0.03 tolerance. 5x the rows keeps the same tolerance honest.
        X, y = _generate_logistic_input(A, B, 50000, seed=42)
        model = LogisticRegressionEstimator(2, num_iters=200).fit(
            Dataset.of(X), Dataset.of(y)
        )
        W = np.asarray(model.weights)
        slope = float(W[0, 1] - W[0, 0])
        assert abs(slope - B) < 0.03, slope

        Xv, yv = _generate_logistic_input(A, B, 10000, seed=17)
        preds = np.asarray(model.batch_apply(Dataset.of(Xv)).array)
        acc = (preds.reshape(-1) == yv).mean()
        assert acc > 0.65, acc

    @pytest.mark.slow
    def test_multinomial_matches_r_golden_weights(self):
        # "multinomial logistic regression with LBFGS": data drawn from the
        # iris-fitted model (intercept layout, stride d+1 — the Spark
        # original these constants come from); the fitted pivot weights
        # must match the R-computed goldens committed in the reference
        # suite source (LogisticRegressionModelSuite.scala:199-203) at the
        # reference's own 0.05 tolerance. weights_r is the first 8 entries
        # of the stride-5 pivot layout (2 classes x [4 features,
        # intercept]). n=100000 (reference: 10000) because our RNG stream
        # differs from Scala's — the golden only reproduces at a sample
        # large enough that sampling noise sits inside the tolerance.
        weights = [
            -0.57997, 0.912083, -0.371077, -0.819866, 2.688191,
            -0.16624, -0.84355, -0.048509, -0.301789, 4.170682,
        ]
        x_mean = np.array([5.843, 3.057, 3.758, 1.199])
        x_var = np.array([0.6856, 0.1899, 3.116, 0.581])
        weights_r = np.array([
            -0.5837166, 0.9285260, -0.3783612, -0.8123411, 2.6228269,
            -0.1691865, -0.811048, -0.0646380,
        ])

        d, k, n = 4, 3, 100_000
        Wgen = np.asarray(weights).reshape(k - 1, d + 1)
        rng = np.random.default_rng(42)

        def draw(n, rng):
            X = rng.normal(size=(n, d)) * np.sqrt(x_var) + x_mean
            margins = np.concatenate(
                [np.zeros((n, 1)), X @ Wgen[:, :d].T + Wgen[:, d]], axis=1
            )
            margins -= margins.max(axis=1, keepdims=True)
            probs = np.exp(margins)
            probs /= probs.sum(axis=1, keepdims=True)
            u = rng.random(n)
            y = (u[:, None] > probs.cumsum(axis=1)).sum(axis=1)
            return X, y

        X, y = draw(n, rng)
        # Our softmax estimator has no intercept term; the reference-
        # faithful form is the append-ones trick (the same one our sparse
        # LBFGS uses), with the pivot = columns minus the reference class.
        Xa = np.concatenate([X, np.ones((n, 1))], axis=1)
        model = LogisticRegressionEstimator(
            3, num_iters=400, convergence_tol=1e-15
        ).fit(Dataset.of(Xa), Dataset.of(y))
        W = np.asarray(model.weights, dtype=np.float64)  # (d+1, k)
        pivot = (W[:, 1:] - W[:, :1]).T.reshape(-1)  # stride-5 layout
        np.testing.assert_allclose(pivot[:8], weights_r, atol=0.05)

        # Prediction on fresh data beats the reference's 0.47 floor (the
        # generating curve is shallow by design).
        Xv, yv = draw(10_000, rng)
        Xva = np.concatenate([Xv, np.ones((len(Xv), 1))], axis=1)
        preds = np.asarray(model.batch_apply(Dataset.of(Xva)).array)
        assert (preds.reshape(-1) == yv).mean() > 0.47
