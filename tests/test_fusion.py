"""Stage fusion (workflow/fusion.py): chains of row-local device
transformers compile into ONE XLA program via the whole-pipeline optimizer's
final batch — the TPU-specific optimizer transform (one dispatch per chain,
XLA fusing across old node boundaries, vs the reference's one Spark stage
per node)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.data import Dataset
from keystone_tpu.ops.stats import (
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomSignNode,
    SignedHellingerMapper,
)
from keystone_tpu.ops.util import Cacher, MaxClassifier
from keystone_tpu.workflow import Pipeline
from keystone_tpu.workflow.fusion import (
    FusedBatchTransformer,
    StageFusionRule,
    fusable,
)

rng = np.random.default_rng(0)


def _chain_pipeline():
    return (
        RandomSignNode.create(64, seed=3)
        .to_pipeline()
        .and_then(PaddedFFT())
        .and_then(LinearRectifier(0.0))
    )


def _unfused_result(X):
    out = Dataset.of(X)
    for t in (
        RandomSignNode.create(64, seed=3),
        PaddedFFT(),
        LinearRectifier(0.0),
    ):
        out = t.batch_apply(out)
    return np.asarray(out.array)


class TestFusedBatchTransformer:
    def test_composed_matches_sequential(self):
        X = rng.normal(size=(16, 64)).astype(np.float32)
        members = [RandomSignNode.create(64, seed=3), PaddedFFT(), LinearRectifier(0.0)]
        fused = FusedBatchTransformer(members)
        out = np.asarray(fused.batch_apply(Dataset.of(X)).array)
        np.testing.assert_allclose(out, _unfused_result(X), atol=1e-5)

    def test_fitted_pipeline_with_fused_chain_pickles(self, tmp_path):
        # FittedPipeline.save() pickles the optimized transformer graph; the
        # fused node must survive the round trip and rebuild its jitted
        # composition on load (regression: the jitted local closure used to
        # make every fused fitted pipeline unpicklable).
        X = rng.normal(size=(12, 64)).astype(np.float32)
        fitted = _chain_pipeline().fit()
        before = np.asarray(fitted.apply(Dataset.of(X)).array)
        path = str(tmp_path / "fused.pkl")
        fitted.save(path)

        from keystone_tpu.workflow.pipeline import FittedPipeline

        loaded = FittedPipeline.load(path)
        after = np.asarray(loaded.apply(Dataset.of(X)).array)
        np.testing.assert_allclose(after, before, atol=1e-6)

    def test_single_datum_apply(self):
        x = rng.normal(size=(64,)).astype(np.float32)
        members = [RandomSignNode.create(64, seed=3), PaddedFFT(), LinearRectifier(0.0)]
        fused = FusedBatchTransformer(members)
        seq = x
        for m in members:
            seq = m.apply(seq)
        np.testing.assert_allclose(np.asarray(fused.apply(x)), np.asarray(seq), atol=1e-5)

    def test_rejects_non_fusable(self):
        from keystone_tpu.ops.nlp import Tokenizer

        with pytest.raises(ValueError):
            FusedBatchTransformer([NormalizeRows(), Tokenizer()])

    def test_padded_dataset_matches_unfused(self):
        """Mesh zero-padding: one trailing rezero (fused) must equal the
        per-stage rezeroing of the sequential chain — the row-local
        contract. Exercises a stage mapping 0 -> nonzero mid-chain
        (LinearRectifier with negative alpha)."""
        from keystone_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.make_mesh()
        X = rng.normal(size=(13, 8)).astype(np.float32)  # pads
        members = [LinearRectifier(0.0, -0.5), NormalizeRows()]
        fused = FusedBatchTransformer(members)
        ds = Dataset.of(X).shard(mesh)
        out = fused.batch_apply(ds)
        seq = ds
        for m in members:
            seq = m.batch_apply(seq)
        np.testing.assert_allclose(
            np.asarray(out.array)[:13], np.asarray(seq.array)[:13], atol=1e-6
        )
        assert out.n == 13
        np.testing.assert_allclose(np.asarray(out.array)[13:], 0.0, atol=0)


class TestStageFusionRule:
    def test_pipeline_chain_fuses_to_one_node(self):
        pipe = _chain_pipeline()
        X = rng.normal(size=(12, 64)).astype(np.float32)
        handle = pipe.apply(Dataset.of(X))
        out = np.asarray(handle.get().array)
        np.testing.assert_allclose(out, _unfused_result(X), atol=1e-5)

        # The executed (optimized) graph is the applied data source plus
        # exactly one fused node — the three originals are gone.
        graph = handle.executor.optimized_graph
        labels = sorted(graph.get_operator(n).label for n in graph.nodes)
        assert sum(l.startswith("Fused[") for l in labels) == 1, labels
        assert len(labels) == 2, labels

    def test_cacher_is_a_fusion_barrier(self):
        # Cacher marks a prefix-published materialization point; chains must
        # not fuse across (or swallow) it.
        pipe = (
            SignedHellingerMapper()
            .to_pipeline()
            .and_then(Cacher())
            .and_then(NormalizeRows())
        )
        X = rng.normal(size=(10, 8)).astype(np.float32)
        handle = pipe.apply(Dataset.of(X))
        ref = NormalizeRows().batch_apply(
            SignedHellingerMapper().batch_apply(Dataset.of(X))
        )
        np.testing.assert_allclose(
            np.asarray(handle.get().array), np.asarray(ref.array), atol=1e-6
        )
        graph = handle.executor.optimized_graph
        labels = [graph.get_operator(n).label for n in graph.nodes]
        assert not any(l.startswith("Fused[") for l in labels), labels

    def test_branch_consumers_prevent_fusion(self):
        # A node consumed by two branches must stay materialized.
        from keystone_tpu.ops.util import VectorCombiner

        base = SignedHellingerMapper().to_pipeline()
        b1 = base.and_then(NormalizeRows())
        b2 = base.and_then(LinearRectifier(0.0))
        pipe = Pipeline.gather([b1, b2]).and_then(VectorCombiner())
        X = rng.normal(size=(6, 8)).astype(np.float32)
        out = np.asarray(pipe.apply(Dataset.of(X)).get().array)
        h = SignedHellingerMapper().batch_apply(Dataset.of(X))
        ref = np.concatenate(
            [
                np.asarray(NormalizeRows().batch_apply(h).array),
                np.asarray(LinearRectifier(0.0).batch_apply(h).array),
            ],
            axis=-1,
        )
        np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_mnist_fft_branches_fuse(self):
        """The MnistRandomFFT featurizer's per-branch RandomSign -> PaddedFFT
        -> LinearRectifier chains first collapse into one fused node per
        branch (StageFusionRule), then the whole gather tree + combiner
        collapses into a single FusedGather program (GatherFusionRule) —
        the entire featurizer is ONE dispatch."""
        from keystone_tpu.pipelines.mnist_random_fft import (
            MnistRandomFFTConfig,
            build_featurizer,
        )

        cfg = MnistRandomFFTConfig(num_ffts=3, block_size=32, image_size=48)
        pipe = build_featurizer(cfg)
        X = rng.normal(size=(8, 48)).astype(np.float32)
        handle = pipe.apply(Dataset.of(X))
        out = np.asarray(handle.get().array)
        assert out.shape == (8, 3 * 32)  # 3 branches x (64-pad FFT)/2
        graph = handle.executor.optimized_graph
        labels = [graph.get_operator(n).label for n in graph.nodes]
        gathered = [l for l in labels if l.startswith("FusedGather[")]
        assert len(gathered) == 1, labels
        # Each branch's chain is visible inside the fused label.
        assert gathered[0].count(" | ") == 2, gathered

    def test_fusable_predicate(self):
        assert fusable(NormalizeRows())
        assert fusable(MaxClassifier())
        assert not fusable(Cacher())


class TestPackedFFTGather:
    """ISSUE 3: the packed-pair FFT lowering must be equality-tested
    against the per-branch composition it silently replaces, and its
    ENGAGEMENT on the MNIST shape must be pinned (the bench row states
    the packed program's flop/traffic model)."""

    def _branches(self, nb, d_in, alphas=None):
        from keystone_tpu.ops.stats import (
            LinearRectifier,
            PaddedFFT,
            RandomSignNode,
        )

        return [
            [
                RandomSignNode.create(d_in, seed=i),
                PaddedFFT(),
                LinearRectifier(0.0, alpha=(alphas[i] if alphas else 0.0)),
            ]
            for i in range(nb)
        ]

    @pytest.mark.parametrize("nb,d_in", [(2, 100), (3, 48), (4, 784)])
    def test_packed_matches_per_branch_composition(self, nb, d_in):
        from keystone_tpu.ops.stats import packed_fft_gather_fn
        from keystone_tpu.ops.util import VectorCombiner

        branches = self._branches(nb, d_in, alphas=[0.1 * i for i in range(nb)])
        fn = packed_fft_gather_fn(branches, VectorCombiner())
        assert fn is not None
        X = rng.normal(size=(16, d_in)).astype(np.float32)
        out = np.asarray(fn(jnp.asarray(X)))
        refs = []
        for br in branches:
            b = jnp.asarray(X)
            for m in br:
                b = m.device_fn()(b)
            refs.append(np.asarray(b))
        ref = np.concatenate(refs, axis=-1)
        assert out.shape == ref.shape
        np.testing.assert_allclose(out, ref, atol=1e-4)

    def test_fused_gather_engages_packed_path(self):
        from keystone_tpu.ops.util import VectorCombiner
        from keystone_tpu.workflow.fusion import FusedGatherTransformer

        fg = FusedGatherTransformer(
            self._branches(4, 64), VectorCombiner()
        )
        assert fg.uses_packed_fft
        # And the engaged program still matches the per-branch math
        # through the transformer's own batch path.
        X = rng.normal(size=(8, 64)).astype(np.float32)
        out = np.asarray(fg.batch_apply(Dataset.of(jnp.asarray(X))).array)
        refs = []
        for br in self._branches(4, 64):
            b = jnp.asarray(X)
            for m in br:
                b = m.device_fn()(b)
            refs.append(np.asarray(b))
        np.testing.assert_allclose(
            out, np.concatenate(refs, axis=-1), atol=1e-4
        )

    def test_non_matching_gather_falls_back(self):
        from keystone_tpu.ops.stats import packed_fft_gather_fn
        from keystone_tpu.ops.util import VectorCombiner
        from keystone_tpu.workflow.fusion import FusedGatherTransformer

        # Branch shape differs (no rectifier): recognizer must decline
        # and the generic composition must serve.
        branches = [
            [m for m in br[:2]] for br in self._branches(2, 32)
        ]
        assert packed_fft_gather_fn(branches, VectorCombiner()) is None
        fg = FusedGatherTransformer(branches, VectorCombiner())
        assert not fg.uses_packed_fft
        X = rng.normal(size=(4, 32)).astype(np.float32)
        out = np.asarray(fg.batch_apply(Dataset.of(jnp.asarray(X))).array)
        assert out.shape == (4, 2 * 16)  # two branches x (32-pad FFT)/2

    def test_mnist_pipeline_gather_is_packed(self):
        from keystone_tpu.pipelines.mnist_random_fft import (
            MnistRandomFFTConfig,
            build_featurizer,
        )
        from keystone_tpu.workflow.fusion import FusedGatherTransformer

        cfg = MnistRandomFFTConfig(num_ffts=4, block_size=32, image_size=48)
        pipe = build_featurizer(cfg)
        X = rng.normal(size=(8, 48)).astype(np.float32)
        handle = pipe.apply(Dataset.of(jnp.asarray(X)))
        handle.get()
        graph = handle.executor.optimized_graph
        fgs = [
            graph.get_operator(n) for n in graph.nodes
            if isinstance(graph.get_operator(n), FusedGatherTransformer)
        ]
        assert fgs and all(fg.uses_packed_fft for fg in fgs)
