"""NLP node tests (model: reference nodes/nlp test suites: TokenizerSuite,
NGramSuite, NGramsFeaturizerSuite, NGramsHashingTFSuite, WordFrequencyEncoderSuite,
NaiveBitPackIndexerSuite, StupidBackoffSuite)."""

import numpy as np
import pytest

from keystone_tpu.data import Dataset
from keystone_tpu.ops.nlp import (
    CoreNLPFeatureExtractor,
    HashingTF,
    LowerCase,
    NaiveBitPackIndexer,
    NGram,
    NGramIndexerImpl,
    NGramsCounts,
    NGramsFeaturizer,
    NGramsHashingTF,
    StupidBackoffEstimator,
    Tokenizer,
    Trim,
    WordFrequencyEncoder,
    initial_bigram_partition,
)


class TestStringNodes:
    def test_tokenizer(self):
        assert Tokenizer().apply("Hello, world  foo") == ["Hello", "world", "foo"]

    def test_trim_lowercase_chain(self):
        pipe = Trim().and_then(LowerCase()).and_then(Tokenizer())
        out = pipe.apply("  Hello World ").get()
        assert out == ["hello", "world"]


class TestNGrams:
    def test_featurizer_reference_order(self):
        grams = NGramsFeaturizer([1, 2]).apply(["a", "b", "c"])
        assert grams == [("a",), ("a", "b"), ("b",), ("b", "c"), ("c",)]

    def test_featurizer_validation(self):
        with pytest.raises(ValueError):
            NGramsFeaturizer([0, 1])
        with pytest.raises(ValueError):
            NGramsFeaturizer([1, 3])

    def test_ngram_equality_hash(self):
        assert NGram(["a", "b"]) == NGram(("a", "b"))
        assert hash(NGram([1, 2])) == hash(NGram((1, 2)))
        assert NGram(["a"]) != NGram(["a", "a"])

    def test_counts_sorted_desc(self):
        data = Dataset.of([[("a",), ("b",), ("a",)], [("a",), ("c",)]])
        out = NGramsCounts().batch_apply(data).to_list()
        assert out[0] == (NGram(("a",)), 3)
        assert set(dict(out).values()) == {3, 1}

    def test_counts_no_add(self):
        data = Dataset.of([[("a",), ("a",)], [("a",)]])
        out = NGramsCounts(mode="no_add").batch_apply(data).to_list()
        assert dict(out[0])[NGram(("a",))] == 2
        assert dict(out[1])[NGram(("a",))] == 1


class TestHashing:
    def test_hashing_tf_counts(self):
        tf = HashingTF(64).apply(["x", "y", "x"])
        assert sum(tf.values()) == 3.0
        assert max(tf.values()) == 2.0

    def test_ngrams_hashing_tf_matches_composition(self):
        """Rolling-hash fusion must equal HashingTF ∘ NGramsFeaturizer
        (NGramsHashingTF.scala contract)."""
        rng = np.random.default_rng(0)
        vocab = ["alpha", "beta", "gamma", "delta", "eps"]
        for trial in range(5):
            tokens = [vocab[i] for i in rng.integers(0, len(vocab), size=12)]
            for orders in ([1, 2], [2, 3], [1, 2, 3]):
                fused = NGramsHashingTF(orders, 128).apply(tokens)
                grams = NGramsFeaturizer(orders).apply(tokens)
                composed = HashingTF(128).apply(grams)
                assert fused == composed


class TestWordFrequencyEncoder:
    def test_rank_and_oov(self):
        data = Dataset.of([["a", "b", "a"], ["a", "c", "b"]])
        enc = WordFrequencyEncoder().fit(data)
        assert enc.apply(["a", "b", "c", "zzz"]) == [0, 1, 2, -1]
        # unigram counts keyed by rank
        assert enc.unigram_counts[0] == 3
        assert enc.unigram_counts[1] == 2


class TestIndexers:
    def test_bitpack_roundtrip(self):
        idx = NaiveBitPackIndexer()
        for gram in ([5], [5, 9], [5, 9, 13]):
            packed = idx.pack(gram)
            assert idx.ngram_order(packed) == len(gram)
            for pos, w in enumerate(gram):
                assert idx.unpack(packed, pos) == w

    def test_bitpack_remove_words(self):
        idx = NaiveBitPackIndexer()
        tri = idx.pack([5, 9, 13])
        no_far = idx.remove_farthest_word(tri)
        assert idx.ngram_order(no_far) == 2
        assert idx.unpack(no_far, 0) == 9 and idx.unpack(no_far, 1) == 13
        no_cur = idx.remove_current_word(tri)
        assert idx.ngram_order(no_cur) == 2
        assert idx.unpack(no_cur, 0) == 5 and idx.unpack(no_cur, 1) == 9

    def test_bitpack_vocab_limit(self):
        with pytest.raises(ValueError):
            NaiveBitPackIndexer().pack([1 << 20])

    def test_ngram_indexer_impl(self):
        idx = NGramIndexerImpl()
        g = idx.pack(["x", "y", "z"])
        assert idx.remove_farthest_word(g) == NGram(["y", "z"])
        assert idx.remove_current_word(g) == NGram(["x", "y"])
        assert idx.ngram_order(g) == 3

    def test_initial_bigram_partition_groups_shared_context(self):
        idx = NGramIndexerImpl()
        a = initial_bigram_partition(NGram(["u", "v", "w"]), 7, idx)
        b = initial_bigram_partition(NGram(["u", "v", "x"]), 7, idx)
        assert a == b
        assert initial_bigram_partition(NGram(["u"]), 7, idx) == 0


class TestStupidBackoff:
    def _fit(self):
        corpus = [["the", "cat", "sat"], ["the", "cat", "ran"], ["the", "dog", "sat"]]
        data = Dataset.of(corpus)
        grams = NGramsFeaturizer([1, 2, 3]).batch_apply(data)
        counts = NGramsCounts().batch_apply(grams)
        unigrams = {w: c for (ng, c) in counts.to_list() if len(ng) == 1 for w in ng.words}
        model = StupidBackoffEstimator(unigram_counts=unigrams).fit(
            Dataset.of([kv for kv in counts.to_list() if len(kv[0]) > 1])
        )
        return model, unigrams

    def test_seen_bigram_score(self):
        model, unigrams = self._fit()
        # S(cat | the) = freq(the cat)/freq(the) = 2/3
        assert model.score(NGram(["the", "cat"])) == pytest.approx(2 / 3)

    def test_seen_trigram_score(self):
        model, _ = self._fit()
        # S(sat | the cat) = freq(the cat sat)/freq(the cat) = 1/2
        assert model.score(NGram(["the", "cat", "sat"])) == pytest.approx(1 / 2)

    def test_unseen_backs_off_to_unigram(self):
        model, unigrams = self._fit()
        n_tokens = sum(unigrams.values())
        # "dog ran" unseen -> alpha * S(ran) = 0.4 * freq(ran)/N
        expected = 0.4 * unigrams_count("ran", unigrams) / n_tokens
        assert model.score(NGram(["dog", "ran"])) == pytest.approx(expected)

    def test_scores_in_unit_interval(self):
        model, _ = self._fit()
        for g, s in model.scores.items():
            assert 0.0 <= s <= 1.0

    def test_partitioned_fit_matches_global(self):
        """InitialBigramPartitioner semantics (StupidBackoff.scala:25-58,
        152-176): per-partition fits score identically to the global fit,
        partitions tile the table, and the sharded model routes queries."""
        import numpy as np

        from keystone_tpu.ops.nlp import (
            ShardedStupidBackoffModel,
            pack_ngram_pairs,
            partition_ngram_pairs,
            unpack_ngram_pairs,
        )

        rng = np.random.default_rng(3)
        sents = [rng.integers(1, 30, size=10).tolist() for _ in range(20)]
        feats = NGramsFeaturizer([2, 3])
        pairs, unigrams = [], {}
        for s in sents:
            for w in s:
                unigrams[w] = unigrams.get(w, 0) + 1
            for g in feats.apply(s):
                pairs.append((NGram(g), 1))

        # Wire-format roundtrip (the multi-host exchange format).
        rt = unpack_ngram_pairs(pack_ngram_pairs(pairs))
        assert [(a.words, b) for a, b in rt] == [(a.words, b) for a, b in pairs]

        est = StupidBackoffEstimator(unigrams)
        full = est.fit(Dataset.of(pairs))
        parts = partition_ngram_pairs(pairs, 3)
        shard_models = [est.fit(Dataset.of(p)) for p in parts]

        assert sum(len(m.scores) for m in shard_models) == len(full.scores)
        for m in shard_models:
            for g, s in m.scores.items():
                assert s == pytest.approx(full.scores[g], abs=1e-15)

        sharded = ShardedStupidBackoffModel(shard_models)
        for g in list(full.scores)[:40]:
            assert sharded.score(g) == pytest.approx(full.score(g), abs=1e-15)

        # UNOBSERVED n-grams exercise the backoff chain, whose lookups hop
        # partitions (dropping the first word changes the initial bigram):
        # per-lookup routing must still match the single-host model.
        checked = 0
        for a in range(1, 30):
            for b in range(1, 30):
                g = NGram((a, b, a))
                if g in full.scores:
                    continue
                assert sharded.score(g) == pytest.approx(
                    full.score(g), abs=1e-15
                ), g
                checked += 1
                if checked >= 60:
                    break
            if checked >= 60:
                break
        assert checked >= 60


def unigrams_count(w, unigrams):
    return unigrams[w]


class TestCoreNLP:
    def test_lemmatized_ngrams(self):
        out = CoreNLPFeatureExtractor([1, 2]).apply("The cats running")
        assert ("cat",) in out
        assert ("runn",) in out or ("run",) in out
