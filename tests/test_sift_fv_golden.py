"""Quantitative validation of the native-tier featurizers (SIFT, FisherVector).

The reference validates its JNI SIFT against MATLAB ``vl_phow`` output on the
real ``000012.jpg`` test image (VLFeatSuite.scala:12-40, tolerance: <0.5% of
entries may differ by more than 1 on the 0..255 short scale) and its
FisherVector against the committed real VOC codebook (EncEvalSuite.scala).
The MATLAB golden CSV (feats128.csv) is not in the reference checkout (it was
fetched at build time) and vlfeat itself is not installable offline, so the
external yardstick here is an INDEPENDENT literal implementation:

  - SIFT: a plain-numpy dense-SIFT written directly from the vl_dsift
    specification (gradient orientation histograms, flat-window box pooling,
    4x4x8 layout, 0.2-clip renormalization, 512-scale), evaluated on the
    real reference image and compared entry-by-entry at the reference
    suite's own tolerance.
  - FisherVector: a plain-numpy posterior + FV-moment implementation
    (Sanchez et al. formulas, the reference's thresholded-posterior
    semantics) evaluated against the REAL committed VOC codebook
    (voc_codebook/{means,variances,priors}).
"""

import os

import numpy as np
import pytest

from _reference import RESOURCES as _RES, needs_reference_fixtures

pytestmark = [needs_reference_fixtures, pytest.mark.slow]


# ---------------------------------------------------------------------------
# Independent numpy dense SIFT (vl_dsift spec, flat window)
# ---------------------------------------------------------------------------


def _np_gaussian_blur(img, sigma):
    """Edge-replicated separable Gaussian, radius ceil(3σ) (the smoothing
    spec of the extractor; implemented here with numpy correlate loops)."""
    radius = max(1, int(np.ceil(3.0 * sigma)))
    xs = np.arange(-radius, radius + 1, dtype=np.float64)
    k = np.exp(-0.5 * (xs / sigma) ** 2)
    k /= k.sum()

    def along_axis0(a):
        padded = np.pad(a, ((radius, radius), (0, 0)), mode="edge")
        out = np.zeros_like(a)
        for i, w in enumerate(k):
            out += w * padded[i : i + a.shape[0], :]
        return out

    return along_axis0(along_axis0(img).T).T


def _np_box_sum(a, size):
    """Zero-padded box sum matching 'same' conv alignment: output i sums
    input [i-(size-1)//2, i + size - 1 - (size-1)//2]."""
    lo = (size - 1) // 2
    hi = size - 1 - lo

    def axis0(x):
        padded = np.pad(x, ((lo, hi), (0, 0)))
        c = np.cumsum(padded, axis=0)
        c = np.vstack([np.zeros((1, x.shape[1])), c])
        return c[size:, :] - c[:-size, :]

    return axis0(axis0(a).T).T


def numpy_dsift(image, bin_size, step):
    """Literal dense SIFT for one scale; image (X, Y) grayscale in [0, 1]."""
    X, Y = image.shape
    smoothed = _np_gaussian_blur(image.astype(np.float64), bin_size / 6.0)

    dx = np.zeros_like(smoothed)
    dx[1:-1, :] = (smoothed[2:, :] - smoothed[:-2, :]) * 0.5
    dy = np.zeros_like(smoothed)
    dy[:, 1:-1] = (smoothed[:, 2:] - smoothed[:, :-2]) * 0.5
    mag = np.sqrt(dx * dx + dy * dy)
    angle = np.arctan2(dy, dx)

    t = np.mod(angle / (2 * np.pi) * 8.0, 8.0)
    lo = np.floor(t)
    frac = t - lo
    lo_i = lo.astype(np.int64) % 8
    hi_i = (lo_i + 1) % 8
    planes = np.zeros((8, X, Y))
    xi, yi = np.meshgrid(np.arange(X), np.arange(Y), indexing="ij")
    np.add.at(planes, (lo_i, xi, yi), mag * (1.0 - frac))
    np.add.at(planes, (hi_i, xi, yi), mag * frac)

    pooled = np.stack([_np_box_sum(p, bin_size) for p in planes])

    extent = 3 * bin_size + bin_size // 2
    anchors_x = np.arange(0, X - extent, step)
    anchors_y = np.arange(0, Y - extent, step)
    centers = np.arange(4) * bin_size + bin_size // 2

    descs = []
    for ax in anchors_x:
        for ay in anchors_y:
            d = np.zeros((4, 4, 8))
            for bx in range(4):
                for by in range(4):
                    d[bx, by, :] = pooled[:, ax + centers[bx], ay + centers[by]]
            descs.append(d.reshape(128))
    desc = np.asarray(descs)

    norm = np.sqrt(np.sum(desc * desc, axis=1, keepdims=True))
    d1 = desc / np.maximum(norm, 1e-12)
    d1 = np.minimum(d1, 0.2)
    norm2 = np.sqrt(np.sum(d1 * d1, axis=1, keepdims=True))
    d2 = d1 / np.maximum(norm2, 1e-12)
    d2 = np.where(norm > 0.005, d2, 0.0)
    return np.minimum(np.floor(512.0 * d2), 255.0).T  # (128, n)


def _load_real_image(max_side=180):
    from _reference import load_reference_image_gray

    return load_reference_image_gray(max_side)


class TestSIFTAgainstIndependentImplementation:
    @pytest.mark.parametrize("bin_size,step", [(4, 3), (6, 4)])
    def test_single_scale_matches_literal_numpy(self, bin_size, step):
        from keystone_tpu.ops.images.sift import _scale_descriptors

        image = _load_real_image()
        ours = np.asarray(
            _scale_descriptors(
                np.asarray(image, np.float32), bin_size=bin_size, step=step
            )
        )
        ref = numpy_dsift(image, bin_size, step)
        assert ours.shape == ref.shape and ours.shape[1] > 100

        # The reference suite's own gate (VLFeatSuite.scala:47-52): fewer
        # than 0.5% of entries may differ by more than 1.
        frac_off = float(np.mean(np.abs(ours - ref) > 1.0))
        assert frac_off < 0.005, f"{frac_off:.4%} of entries off by > 1"

    def test_multi_scale_extractor_on_real_image(self):
        from keystone_tpu.ops.images.sift import SIFTExtractor

        image = _load_real_image()
        ext = SIFTExtractor(step_size=3, bin_size=4, scales=2, scale_step=1)
        descs = np.asarray(ext.apply(np.asarray(image, np.float32)))
        assert descs.shape[0] == 128
        # Real-image content: descriptors span the short range and are not
        # degenerate.
        assert descs.max() > 100
        assert (descs.sum(axis=0) > 0).mean() > 0.9


# ---------------------------------------------------------------------------
# FisherVector against the real VOC codebook
# ---------------------------------------------------------------------------


def _np_posteriors(X, means, variances, weights, thr=1e-4):
    """Literal numpy port of the reference posterior math
    (GaussianMixtureModel.scala:47-83): Mahalanobis via the three-term
    expansion, shift-exp-normalize, aggressive thresholding, renormalize."""
    mu = means.T  # (k, d)
    var = variances.T
    sq = (
        (X * X) @ (0.5 / var).T
        - X @ (mu / var).T
        + 0.5 * np.sum(mu * mu / var, axis=1)[None, :]
    )
    llh = (
        -0.5 * X.shape[1] * np.log(2 * np.pi)
        - 0.5 * np.sum(np.log(var), axis=1)[None, :]
        + np.log(weights)[None, :]
        - sq
    )
    llh -= llh.max(axis=1, keepdims=True)
    p = np.exp(llh)
    p /= p.sum(axis=1, keepdims=True)
    p = np.where(p > thr, p, 0.0)
    return p / p.sum(axis=1, keepdims=True)


def _np_fisher(x, means, variances, weights):
    """Sanchez et al. FV from moments (FisherVector.scala:38-50)."""
    n = x.shape[1]
    q = _np_posteriors(x.T, means, variances, weights)
    s0 = q.mean(axis=0)
    s1 = (x @ q) / n
    s2 = ((x * x) @ q) / n
    fv1 = (s1 - means * s0[None, :]) / (
        np.sqrt(variances) * np.sqrt(weights)[None, :]
    )
    fv2 = (s2 - 2.0 * means * s1 + (means * means - variances) * s0[None, :]) / (
        variances * np.sqrt(2.0 * weights)[None, :]
    )
    return np.concatenate([fv1, fv2], axis=1)


class TestFisherVectorAgainstRealCodebook:
    def _codebook(self):
        from keystone_tpu.ops.learning.clustering import GaussianMixtureModel

        base = os.path.join(_RES, "images/voc_codebook")
        return GaussianMixtureModel.load(
            os.path.join(base, "means.csv"),
            os.path.join(base, "variances.csv"),
            os.path.join(base, "priors"),
        )

    def test_codebook_loads_with_reference_geometry(self):
        gmm = self._codebook()
        assert np.asarray(gmm.means).shape == (80, 256)
        assert np.asarray(gmm.variances).shape == (80, 256)
        w = np.asarray(gmm.weights)
        assert w.shape == (256,) and abs(w.sum() - 1.0) < 1e-3

    def test_fv_matches_independent_numpy_on_real_codebook(self):
        from keystone_tpu.ops.images.fisher import FisherVector

        gmm = self._codebook()
        rng = np.random.default_rng(0)
        # Descriptor-like inputs drawn around real codebook centers so the
        # posteriors exercise the thresholding path non-trivially.
        means = np.asarray(gmm.means, dtype=np.float64)  # (80, 256)
        pick = rng.integers(0, 256, size=300)
        x = (
            means[:, pick]
            + rng.normal(size=(80, 300))
            * np.sqrt(np.asarray(gmm.variances))[:, pick]
        )

        ours = np.asarray(FisherVector(gmm).apply(x.astype(np.float32)))
        ref = _np_fisher(
            x,
            means,
            np.asarray(gmm.variances, dtype=np.float64),
            np.asarray(gmm.weights, dtype=np.float64),
        )
        assert ours.shape == ref.shape == (80, 512)
        # f32 pipeline vs f64 literal: relative agreement on the FV scale.
        denom = np.maximum(np.abs(ref).max(), 1e-9)
        assert np.abs(ours - ref).max() / denom < 5e-3
        # The EncEval suite asserts on the FV sum (EncEvalSuite.scala:38-41);
        # check ours against the independent implementation the same way.
        assert abs(ours.sum() - ref.sum()) < 1e-2 * max(1.0, abs(ref.sum()))
