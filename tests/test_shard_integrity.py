"""Durable shard formats (ISSUE 5 tentpole): metadata is atomic and
written LAST, per-tile/chunk checksums catch torn or bit-flipped bytes as
:class:`ShardCorrupted` (never silent wrong data), and a clean directory
round-trips byte-identically to the pre-reliability format semantics.
"""

import json
import os

import numpy as np
import pytest

from keystone_tpu.data.durable import (
    CheckpointSpec,
    ShardCorrupted,
    atomic_write_json,
    checksum_algo,
    crc_of_array,
)
from keystone_tpu.data.shards import (
    DiskCOOShards,
    DiskDenseShards,
    DiskDenseShardWriter,
)


def _dense(tmp_path, n=500, d_in=8, k=2, tile=64, tps=2, name="d"):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d_in)).astype(np.float32)
    Y = rng.normal(size=(n, k)).astype(np.float32)
    return (
        DiskDenseShards.write(
            str(tmp_path / name), X, Y, tile_rows=tile, tiles_per_segment=tps
        ),
        X,
        Y,
    )


def _flip_byte(path, offset):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


class TestAtomicMeta:
    def test_atomic_write_json_no_torn_partial(self, tmp_path):
        """A failed write (simulated by an os.replace that never ran —
        the temp file is all that exists) must leave the destination
        untouched: either the old content or nothing, never a torn
        half-JSON that parses as a short dataset."""
        path = str(tmp_path / "meta.json")
        atomic_write_json(path, {"v": 1})
        # Mid-write kill: a temp file exists, the target still holds v=1.
        with open(path + ".tmp.dead", "w") as f:
            f.write('{"v": 2, "trunc')  # torn JSON under the temp name
        with open(path) as f:
            assert json.load(f) == {"v": 1}
        atomic_write_json(path, {"v": 3})
        with open(path) as f:
            assert json.load(f) == {"v": 3}

    def test_dense_write_meta_is_last(self, tmp_path, monkeypatch):
        """Kill between array writes and meta write (satellite
        regression): the directory must refuse to load rather than
        parse as valid-but-short."""
        directory = str(tmp_path / "killed")
        real = DiskDenseShards._final_meta

        def boom(*a, **kw):
            raise KeyboardInterrupt("kill -9 between arrays and meta")

        monkeypatch.setattr(DiskDenseShards, "_final_meta", staticmethod(boom))
        rng = np.random.default_rng(1)
        with pytest.raises(KeyboardInterrupt):
            DiskDenseShards.write(
                directory,
                rng.normal(size=(100, 4)).astype(np.float32),
                rng.normal(size=(100, 2)).astype(np.float32),
                tile_rows=32, tiles_per_segment=2,
            )
        assert os.path.exists(os.path.join(directory, "x.npy"))
        with pytest.raises(FileNotFoundError):
            DiskDenseShards(directory)  # no meta -> loud, not short
        monkeypatch.setattr(
            DiskDenseShards, "_final_meta", staticmethod(real)
        )

    def test_rewrite_over_old_directory_drops_stale_meta(self, tmp_path):
        """Re-ingesting into a directory holding a COMPLETE previous
        build, killed mid-array-write, must not load the old meta
        against the new partial arrays."""
        directory = str(tmp_path / "re")
        _dense(tmp_path, name="re")  # complete previous build
        rng = np.random.default_rng(2)

        class Kill(Exception):
            pass

        # Start a new build and kill it after the arrays are allocated:
        # DiskDenseShardWriter deletes the stale meta at open.
        w = DiskDenseShardWriter(directory, 100, 8, 2, tile_rows=32)
        w.append(rng.normal(size=(10, 8)).astype(np.float32),
                 rng.normal(size=(10, 2)).astype(np.float32))
        # never closed == killed
        with pytest.raises(FileNotFoundError):
            DiskDenseShards(directory)

    def test_coo_unsealed_directory_refuses_to_load(self, tmp_path):
        DiskCOOShards.create(str(tmp_path / "u"), 2, 64, 4, 2,
                             n_true=100, d=32)
        with pytest.raises(ShardCorrupted, match="sealed"):
            DiskCOOShards(str(tmp_path / "u"))
        shards = DiskCOOShards.seal(str(tmp_path / "u"))
        assert shards.num_chunks == 2 and shards.is_checksummed


class TestChecksums:
    def test_clean_roundtrip_verified(self, tmp_path):
        shards, X, Y = _dense(tmp_path)
        assert shards.is_checksummed
        X_seg, Y_seg, valid = shards.segment_source(0)
        np.testing.assert_array_equal(
            X_seg.reshape(-1, X.shape[1])[:valid][: 2 * 64], X[: 2 * 64]
        )

    def test_bit_flip_raises_shard_corrupted(self, tmp_path):
        shards, _, _ = _dense(tmp_path)
        # Flip one byte well inside tile 0's data region of x.npy.
        _flip_byte(os.path.join(shards.directory, "x.npy"), 400)
        reopened = DiskDenseShards(shards.directory)
        with pytest.raises(ShardCorrupted, match="checksum mismatch"):
            reopened.segment_source(0)
        # Label reads of an uncorrupted file still work.
        reopened.segment_source_y(0)

    def test_coo_bit_flip_raises(self, tmp_path):
        rng = np.random.default_rng(3)
        idx = rng.integers(0, 32, size=(300, 4)).astype(np.int32)
        val = rng.normal(size=(300, 4)).astype(np.float32)
        y = rng.normal(size=(300, 2)).astype(np.float32)
        shards = DiskCOOShards.write(
            str(tmp_path / "c"), idx, val, y, chunk_rows=128,
            n_true=300, d=32,
        )
        _flip_byte(os.path.join(shards.directory, "values.npy"), 300)
        reopened = DiskCOOShards(shards.directory)
        with pytest.raises(ShardCorrupted, match="checksum mismatch"):
            reopened.segment_source(0, 2)

    def test_corruption_not_retried_into_silence(self, tmp_path):
        """ShardCorrupted must NOT be transient: the retry layer
        re-reading the same bad bytes and 'succeeding' would be the
        worst possible outcome. It is not an OSError by construction."""
        assert not issubclass(ShardCorrupted, OSError)
        shards, _, _ = _dense(tmp_path, name="nr")
        _flip_byte(os.path.join(shards.directory, "x.npy"), 400)
        reopened = DiskDenseShards(shards.directory)
        with pytest.raises(ShardCorrupted):
            reopened.segment_source(0)

    def test_legacy_meta_without_checksums_loads(self, tmp_path):
        shards, _, _ = _dense(tmp_path, name="leg")
        meta_path = os.path.join(shards.directory, "dense_shards.json")
        with open(meta_path) as f:
            meta = json.load(f)
        meta.pop("checksums")
        meta.pop("checksum_algo")
        with open(meta_path, "w") as f:
            json.dump(meta, f)
        legacy = DiskDenseShards(shards.directory)
        assert not legacy.is_checksummed
        legacy.segment_source(0)  # loads, unverified (pre-PR behavior)

    def test_writer_close_checksums_only_written_tiles(self, tmp_path):
        rng = np.random.default_rng(4)
        w = DiskDenseShardWriter(
            str(tmp_path / "w"), capacity_rows=1000, d_in=8, k=2,
            tile_rows=64,
        )
        w.append(rng.normal(size=(100, 8)).astype(np.float32),
                 rng.normal(size=(100, 2)).astype(np.float32))
        shards = w.close()
        assert shards.is_checksummed and shards.num_tiles == 2
        with open(os.path.join(shards.directory,
                               "dense_shards.json")) as f:
            meta = json.load(f)
        assert len(meta["checksums"]["x"]) == 2  # not capacity tiles
        shards.segment_source(0)


class TestCheckpointDurability:
    def test_roundtrip_bit_exact(self, tmp_path):
        ck = CheckpointSpec(str(tmp_path / "ck"), every_segments=4)
        rng = np.random.default_rng(5)
        arrays = [
            rng.normal(size=(16, 16)).astype(np.float32),
            rng.normal(size=(16, 3)).astype(np.float32),
            np.float32(3.25).reshape(()),
        ]
        fp = {"kind": "t", "num_segments": 9}
        ck.save(arrays, cursor=6, fingerprint=fp)
        got, cursor = ck.load(fp)
        assert cursor == 6
        for a, b in zip(arrays, got):
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_array_equal(a, b)

    def test_fingerprint_mismatch_returns_none(self, tmp_path):
        ck = CheckpointSpec(str(tmp_path / "ck"))
        ck.save([np.zeros(3, np.float32)], 1, {"kind": "a"})
        assert ck.load({"kind": "b"}) is None

    def test_corrupt_checkpoint_raises(self, tmp_path):
        import glob

        ck = CheckpointSpec(str(tmp_path / "ck"))
        ck.save([np.arange(64, dtype=np.float32)], 2, {"kind": "a"})
        (carry_path,) = glob.glob(
            str(tmp_path / "ck" / "fit-*" / "carry-*.bin")
        )
        _flip_byte(carry_path, 16)
        with pytest.raises(ShardCorrupted, match="checkpoint"):
            ck.load({"kind": "a"})

    def test_kill_between_data_and_meta_keeps_previous_snapshot(self, tmp_path):
        """The snapshot data file is versioned per cursor and the meta
        (written last) names it: a kill after the new data lands but
        before the new meta does must leave the PREVIOUS snapshot fully
        resumable — never old meta over new bytes (-> ShardCorrupted)."""
        import glob

        ck = CheckpointSpec(str(tmp_path / "ck"))
        fp = {"kind": "a"}
        ck.save([np.full(4, 1.0, np.float32)], 2, fp)

        # Simulate the kill window: cursor-4 data written, meta never.
        (fit_dir,) = glob.glob(str(tmp_path / "ck" / "fit-*"))
        with open(os.path.join(fit_dir, "carry-4.bin"), "wb") as f:
            f.write(np.full(4, 9.0, np.float32).tobytes())

        arrays, cursor = ck.load(fp)
        assert cursor == 2 and float(arrays[0][0]) == 1.0  # old snapshot
        # The next successful save reclaims the orphaned data file.
        ck.save([np.full(4, 3.0, np.float32)], 6, fp)
        remaining = sorted(
            os.path.basename(p)
            for p in glob.glob(os.path.join(fit_dir, "carry-*.bin"))
        )
        assert remaining == ["carry-6.bin"]

    def test_clear_removes_snapshot(self, tmp_path):
        ck = CheckpointSpec(str(tmp_path / "ck"))
        ck.save([np.zeros(3, np.float32)], 1, {"kind": "a"})
        assert ck.has_snapshot() and ck.has_snapshot({"kind": "a"})
        ck.clear()
        assert ck.load({"kind": "a"}) is None
        assert not ck.has_snapshot()

    def test_shared_directory_namespaces_fits(self, tmp_path):
        """One --checkpoint-dir serving several segmented fits: each
        fit's snapshot and clear() are isolated — fit A completing must
        not delete fit B's resume point."""
        ck = CheckpointSpec(str(tmp_path / "ck"))
        fp_a, fp_b = {"kind": "a", "d": 8}, {"kind": "b", "d": 16}
        ck.save([np.full(3, 1.0, np.float32)], 1, fp_a)
        ck.save([np.full(3, 2.0, np.float32)], 5, fp_b)
        arrays_a, cur_a = ck.load(fp_a)
        arrays_b, cur_b = ck.load(fp_b)
        assert cur_a == 1 and float(arrays_a[0][0]) == 1.0
        assert cur_b == 5 and float(arrays_b[0][0]) == 2.0
        ck.clear(fp_a)  # fit A finished
        assert ck.load(fp_a) is None
        assert ck.load(fp_b) is not None  # fit B's resume point survives

    def test_source_fingerprint_resolves_bound_method(self, tmp_path):
        """The legacy callable segment_source form (a bound method like
        shards.segment_source) must carry the same source identity as
        the ShardSource forms — a stale snapshot over a re-ingested
        directory has to miss on every documented input shape."""
        from keystone_tpu.data.durable import source_fingerprint

        shards, _, _ = _dense(tmp_path, name="fpr")
        via_source = source_fingerprint(shards.as_source())
        via_method = source_fingerprint(shards.segment_source)
        via_object = source_fingerprint(shards)
        assert via_source is not None
        assert via_source == via_method == via_object
        assert via_source["directory"] == shards.directory
        assert via_source["checksums_crc"] is not None
        assert source_fingerprint(lambda s: s) is None  # plain callable

    def test_algo_recorded_and_used(self, tmp_path):
        shards, _, _ = _dense(tmp_path, name="alg")
        with open(os.path.join(shards.directory,
                               "dense_shards.json")) as f:
            meta = json.load(f)
        assert meta["checksum_algo"] == checksum_algo()
        # Digest re-derivable from the file exactly as recorded.
        x = np.load(os.path.join(shards.directory, "x.npy"), mmap_mode="r")
        assert meta["checksums"]["x"][0] == crc_of_array(
            np.asarray(x[0]), meta["checksum_algo"]
        )
