"""Micro-batcher behavior (ISSUE 4 tentpole + satellites): bit-identity
of the served path vs offline apply under any bucket interleaving,
explicit overload shedding, clean shutdown mid-load (mirrors
tests/test_prefetch.py's shutdown coverage), and error re-raise to the
submitter."""

import threading
import time

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.data import Dataset
from keystone_tpu.serving import (
    MicroBatchServer,
    ServerClosed,
    ServerOverloaded,
    export_plan,
    run_open_loop,
)
from keystone_tpu.workflow import Transformer

from tests._serving_util import (
    TINY_D_IN,
    fit_tiny_mnist,
    fitted_from_transformer,
)


class GatedScale(Transformer):
    """Device-less x -> 3x whose batch path blocks on an Event — gives
    the tests deterministic control over when the worker is busy."""

    def __init__(self):
        self.gate = threading.Event()
        self.gate.set()
        self.batches = 0

    def apply(self, x):
        return jnp.asarray(x) * 3.0

    def batch_apply(self, ds):
        self.gate.wait(timeout=10.0)
        self.batches += 1
        return Dataset(jnp.asarray(ds.array) * 3.0, n=ds.n)


def _gated_server(**kw):
    op = GatedScale()
    plan = export_plan(
        fitted_from_transformer(op), np.zeros(4, np.float32), max_batch=8
    )
    assert not plan.compiled  # the gated op keeps the eager path
    return op, MicroBatchServer(plan, **kw)


class TestBitIdentity:
    def test_served_equals_offline_any_interleaving(self):
        """For a fixed request set, served outputs — whatever bucket sizes
        the batcher happened to coalesce, padding masked — equal offline
        FittedPipeline.apply on the concatenated batch, bit for bit."""
        fitted, _ = fit_tiny_mnist()
        plan = export_plan(
            fitted, np.zeros(TINY_D_IN, np.float32), max_batch=8
        )
        rng = np.random.default_rng(3)
        X = rng.normal(size=(37, TINY_D_IN)).astype(np.float32)
        offline = np.asarray(fitted.apply(Dataset.of(jnp.asarray(X))).array)

        server = MicroBatchServer(plan, max_batch=8, max_wait_ms=1.0)
        try:
            futures = []
            for i in range(len(X)):
                futures.append(server.submit(X[i]))
                if i % 7 == 3:
                    time.sleep(0.003)  # stagger arrivals: varied buckets
            served = np.stack([f.result(timeout=30) for f in futures])
        finally:
            server.close()
        np.testing.assert_array_equal(served, offline)
        # The interleaving genuinely exercised more than one bucket.
        buckets = {s.bucket for s in server.span_log.snapshot()}
        assert len(buckets) >= 2, buckets

    def test_spans_and_stats_populated(self):
        fitted, _ = fit_tiny_mnist()
        plan = export_plan(
            fitted, np.zeros(TINY_D_IN, np.float32), max_batch=4
        )
        with MicroBatchServer(plan, max_wait_ms=1.0) as server:
            futs = [server.submit(np.zeros(TINY_D_IN, np.float32))
                    for _ in range(9)]
            for f in futs:
                f.result(timeout=30)
            stats = server.stats()
        assert stats["completed"] == 9
        assert stats["num_latency_samples"] == 9
        assert stats["p99_latency_s"] >= stats["p50_latency_s"] > 0.0
        assert 0.0 <= stats["mean_pad_fraction"] < 1.0
        span = server.span_log.snapshot()[0]
        assert span.queue_wait_s >= 0.0 and span.exec_s > 0.0
        assert span.bucket >= span.batch_size
        assert span.replica is None  # standalone server: no attribution

    def test_stats_split_queue_wait_from_exec(self):
        """ISSUE 7 satellite: end-to-end latency reported SPLIT into its
        queue-wait and execute sides, so admission-control tuning can
        see which side of the SLO is burning budget. The two sides must
        (approximately) compose back into the end-to-end number."""
        fitted, _ = fit_tiny_mnist()
        plan = export_plan(
            fitted, np.zeros(TINY_D_IN, np.float32), max_batch=8
        )
        # Fewer requests than max_batch: the fill trigger never fires,
        # so the 20ms coalescing window is what every request pays —
        # the split must pin that on the queue side.
        with MicroBatchServer(plan, max_wait_ms=20.0) as server:
            futs = [server.submit(np.zeros(TINY_D_IN, np.float32))
                    for _ in range(6)]
            for f in futs:
                f.result(timeout=30)
            stats = server.stats()
        for side in ("queue_wait", "exec"):
            assert stats[f"p99_{side}_s"] >= stats[f"p50_{side}_s"] >= 0.0
        assert stats["p50_exec_s"] > 0.0
        # The 20ms coalescing wait dominates this idle-arrival workload:
        # the split must ATTRIBUTE the latency to the queue side.
        assert stats["p50_queue_wait_s"] > stats["p50_exec_s"]
        # Wait + exec compose to roughly the end-to-end percentile.
        # Since ISSUE 10 the end-to-end number comes from the
        # log-BUCKETED histogram (whole-run percentiles at ~8%/bucket
        # resolution) while the split stays on the exact span window —
        # the comparison tolerates one bucket width.
        from keystone_tpu.obs.metrics import BucketedHistogram

        assert (
            stats["p99_queue_wait_s"] + stats["p99_exec_s"]
        ) * BucketedHistogram._GROWTH >= stats["p50_latency_s"]


class TestOverload:
    def test_bounded_queue_sheds_explicitly_and_inflight_completes(self):
        op, server = _gated_server(
            max_batch=4, max_wait_ms=0.0, max_queue_depth=4
        )
        op.gate.clear()  # worker blocks inside the first batch
        try:
            first = server.submit(np.ones(4, np.float32))
            time.sleep(0.05)  # let the worker pick it up
            futs = [server.submit(np.ones(4, np.float32) * i)
                    for i in range(12)]
            op.gate.set()
            outcomes = {"ok": 0, "shed": 0}
            for f in [first] + futs:
                try:
                    f.result(timeout=10)
                    outcomes["ok"] += 1
                except ServerOverloaded:
                    outcomes["shed"] += 1
        finally:
            server.close()
        # Nothing silently dropped: every future resolved one way.
        assert outcomes["ok"] + outcomes["shed"] == 13
        assert outcomes["shed"] > 0  # the bounded queue genuinely shed
        assert outcomes["ok"] >= 5  # in-flight + queue-depth worth served
        assert server.stats()["rejected"] == outcomes["shed"]

    def test_earliest_deadline_is_the_shedding_victim(self):
        op, server = _gated_server(
            max_batch=2, max_wait_ms=0.0, max_queue_depth=2
        )
        op.gate.clear()
        try:
            blocker = server.submit(np.ones(4, np.float32))
            time.sleep(0.05)  # worker now busy; queue empty
            f_tight = server.submit(np.ones(4, np.float32), deadline_ms=50.0)
            f_loose = server.submit(np.ones(4, np.float32), deadline_ms=1e6)
            # Queue full; a new tighter-deadline request is itself the
            # earliest-deadline victim -> rejected synchronously.
            with pytest.raises(ServerOverloaded):
                server.submit(np.ones(4, np.float32), deadline_ms=1.0)
            # A new LOOSER-deadline request evicts the tightest queued one.
            f_new = server.submit(np.ones(4, np.float32))
            with pytest.raises(ServerOverloaded):
                f_tight.result(timeout=5)
            op.gate.set()
            blocker.result(timeout=10)
            f_loose.result(timeout=10)
            f_new.result(timeout=10)
        finally:
            server.close()
        assert server.stats()["rejected"] == 2


    def test_edf_shedding_is_deterministic_on_replay(self):
        """ISSUE 7 satellite: for a fixed submission sequence against a
        blocked worker, earliest-deadline-first shedding picks the SAME
        victims on replay — overload behavior is part of the
        deterministic-replay contract, not thread-timing luck."""
        def run_once():
            op, server = _gated_server(
                max_batch=4, max_wait_ms=0.0, max_queue_depth=3
            )
            op.gate.clear()
            outcomes = []
            try:
                blocker = server.submit(np.ones(4, np.float32))
                time.sleep(0.05)  # worker blocked inside the batch
                # Deadlines differ by >= 10ms; submission jitter is
                # microseconds, so the EDF order is fixed by the values.
                deadlines = [500.0, 40.0, None, 120.0, 15.0,
                             800.0, None, 60.0, 25.0, 300.0]
                futs = []
                for d in deadlines:
                    try:
                        futs.append(server.submit(
                            np.ones(4, np.float32), deadline_ms=d
                        ))
                    except ServerOverloaded:
                        futs.append(None)
                op.gate.set()
                for f in futs:
                    if f is None:
                        outcomes.append("sync_shed")
                        continue
                    try:
                        f.result(timeout=10)
                        outcomes.append("ok")
                    except ServerOverloaded:
                        outcomes.append("shed")
                blocker.result(timeout=10)
            finally:
                op.gate.set()
                server.close()
            return outcomes

        first = run_once()
        assert "ok" in first and "shed" in first and "sync_shed" in first
        assert run_once() == first


class TestShutdown:
    def test_shutdown_midload_no_deadlock_no_thread_leak(self):
        op, server = _gated_server(
            max_batch=4, max_wait_ms=0.0, max_queue_depth=64
        )
        op.gate.clear()
        inflight = server.submit(np.ones(4, np.float32))
        time.sleep(0.05)
        queued = [server.submit(np.ones(4, np.float32) * i) for i in range(10)]
        op.gate.set()
        t0 = time.perf_counter()
        server.close(timeout=10.0)
        assert time.perf_counter() - t0 < 10.0
        assert not server.is_alive
        assert not any(
            t.name == "keystone-serving-batcher" for t in threading.enumerate()
        )
        # In-flight completed; queued-but-unstarted failed EXPLICITLY.
        np.testing.assert_array_equal(
            np.asarray(inflight.result(timeout=1)), np.ones(4) * 3.0
        )
        for f in queued:
            with pytest.raises(ServerClosed):
                f.result(timeout=1)

    def test_submit_after_close_raises(self):
        _, server = _gated_server()
        server.close()
        with pytest.raises(ServerClosed):
            server.submit(np.zeros(4, np.float32))

    def test_close_is_idempotent(self):
        _, server = _gated_server()
        server.close()
        server.close()
        assert not server.is_alive


class TestRobustness:
    def test_client_cancelled_future_does_not_kill_worker(self):
        # A cancelled future rejects set_result with InvalidStateError;
        # unguarded, that would kill the worker and hang every later
        # request forever.
        op, server = _gated_server(max_batch=4, max_wait_ms=0.0)
        op.gate.clear()
        try:
            blocker = server.submit(np.ones(4, np.float32))
            time.sleep(0.05)
            doomed = server.submit(np.ones(4, np.float32))
            assert doomed.cancel()
            op.gate.set()
            blocker.result(timeout=10)
            # The worker survived the cancelled future: new requests serve.
            out = server.submit(np.ones(4, np.float32)).result(timeout=10)
            np.testing.assert_array_equal(np.asarray(out), np.ones(4) * 3.0)
            assert server.is_alive
        finally:
            server.close()

    def test_nonpositive_max_batch_rejected_at_build(self):
        op = GatedScale()
        plan = export_plan(
            fitted_from_transformer(op), np.zeros(4, np.float32), max_batch=8
        )
        with pytest.raises(ValueError, match="max_batch"):
            MicroBatchServer(plan, max_batch=0)
        with pytest.raises(ValueError, match="max_batch"):
            MicroBatchServer(plan, max_batch=-1)


class TestErrors:
    def test_plan_error_reraises_in_submitter_and_server_survives(self):
        class Exploding(Transformer):
            def __init__(self):
                self.arm = True

            def apply(self, x):
                return x

            def batch_apply(self, ds):
                if self.arm:
                    raise ValueError("kernel went sideways")
                return ds

        op = Exploding()
        plan = export_plan(
            fitted_from_transformer(op), np.zeros(4, np.float32), max_batch=4
        )
        server = MicroBatchServer(plan, max_wait_ms=0.0)
        try:
            with pytest.raises(ValueError, match="sideways"):
                server.submit(np.zeros(4, np.float32)).result(timeout=10)
            assert server.is_alive  # a batch failure never kills the worker
            op.arm = False
            server.submit(np.zeros(4, np.float32)).result(timeout=10)
            assert server.stats()["failed"] == 1
        finally:
            server.close()


class TestDegradation:
    """ISSUE 5: explicit degradation — breaker states surface in stats,
    defaults never trip on a healthy server, and a dying worker fails
    futures instead of hanging submitters (the chaos drills in
    tests/test_chaos.py exercise the injected-fault forms)."""

    def _exploding_server(self, **kw):
        class Exploding(Transformer):
            def __init__(self):
                self.arm = True

            def apply(self, x):
                return x

            def batch_apply(self, ds):
                if self.arm:
                    raise ValueError("plan down")
                return ds

        op = Exploding()
        plan = export_plan(
            fitted_from_transformer(op), np.zeros(4, np.float32), max_batch=4
        )
        return op, MicroBatchServer(plan, max_wait_ms=0.0, **kw)

    def test_healthy_server_reports_closed_breaker(self):
        _, server = _gated_server()
        try:
            server.submit(np.ones(4, np.float32)).result(timeout=10)
            stats = server.stats()
            assert stats["breaker_state"] == "closed"
            assert stats["breaker_opens"] == 0
            assert stats["degraded_rejected"] == 0
            assert stats["consecutive_failures"] == 0
        finally:
            server.close()

    def test_breaker_opens_and_recovers_via_half_open_probe(self):
        from keystone_tpu.serving import ServerDegraded

        op, server = self._exploding_server(
            breaker_threshold=2, breaker_reset_s=0.2
        )
        try:
            for _ in range(2):
                with pytest.raises(ValueError, match="plan down"):
                    server.submit(np.zeros(4, np.float32)).result(timeout=10)
            deadline = time.perf_counter() + 5.0
            while (server.breaker_state != "open"
                   and time.perf_counter() < deadline):
                time.sleep(0.005)
            with pytest.raises(ServerDegraded):
                server.submit(np.zeros(4, np.float32))
            op.arm = False  # plan healthy again
            time.sleep(0.25)  # cooldown elapses -> half-open
            server.submit(np.zeros(4, np.float32)).result(timeout=10)
            assert server.breaker_state == "closed"
            assert server.stats()["breaker_opens"] == 1
        finally:
            server.close()

    def test_default_threshold_absorbs_isolated_failures(self):
        # One failed batch out of many must NOT trip the default
        # breaker: isolated errors re-raise submitter-side, stream
        # continues (pre-reliability behavior).
        op, server = self._exploding_server()
        try:
            with pytest.raises(ValueError):
                server.submit(np.zeros(4, np.float32)).result(timeout=10)
            op.arm = False
            server.submit(np.zeros(4, np.float32)).result(timeout=10)
            assert server.breaker_state == "closed"
        finally:
            server.close()

    def test_close_racing_half_open_probe_resolves_server_closed(self):
        """ISSUE 7 satellite regression: a half-open probe submitted but
        not yet executed when close() runs must resolve with
        ServerClosed — never hang on the probe slot, never stall
        close()."""
        class Exploding(Transformer):
            def apply(self, x):
                return x

            def batch_apply(self, ds):
                raise ValueError("plan down")

        plan = export_plan(
            fitted_from_transformer(Exploding()), np.zeros(4, np.float32),
            max_batch=4,
        )
        # The long coalescing wait keeps the admitted probe QUEUED while
        # close() races it.
        server = MicroBatchServer(
            plan, max_wait_ms=500.0, breaker_threshold=1,
            breaker_reset_s=0.05,
        )
        try:
            with pytest.raises(ValueError, match="plan down"):
                server.submit(np.zeros(4, np.float32)).result(timeout=10)
            deadline = time.perf_counter() + 5.0
            while (server.breaker_state == "closed"
                   and time.perf_counter() < deadline):
                time.sleep(0.005)
            time.sleep(0.08)  # cooldown elapses -> next submit is a probe
            assert server.breaker_state == "half_open"
            probe = server.submit(np.zeros(4, np.float32))
            t0 = time.perf_counter()
            server.close(timeout=10.0)
            assert time.perf_counter() - t0 < 5.0  # close never stalls
            with pytest.raises(ServerClosed):
                probe.result(timeout=2)
            assert not server.is_alive
        finally:
            server.close()

    def test_worker_death_never_hangs_submitters(self):
        from keystone_tpu.serving import ServerDegraded

        _, server = _gated_server(max_wait_ms=100.0)
        server.submit(np.ones(4, np.float32)).result(timeout=10)
        server._execute = None  # loop-level failure, outside the guard
        fut = server.submit(np.ones(4, np.float32))
        with pytest.raises(ServerDegraded, match="worker thread died"):
            fut.result(timeout=10)
        with pytest.raises(ServerDegraded):
            server.submit(np.ones(4, np.float32))
        assert server.stats()["breaker_state"] == "dead"
        server.close()  # must not hang on the dead worker


@pytest.mark.slow
class TestOpenLoopPoisson:
    """Poisson load smoke (slow tier: real sleeps over a multi-second
    window — tier-1 wall time must not pay for it)."""

    def test_open_loop_report_fields_and_batching_wins(self):
        fitted, _ = fit_tiny_mnist()
        plan = export_plan(
            fitted, np.zeros(TINY_D_IN, np.float32), max_batch=32
        )
        rng = np.random.default_rng(5)
        pool = rng.normal(size=(64, TINY_D_IN)).astype(np.float32)
        server = MicroBatchServer(plan, max_batch=32, max_wait_ms=2.0,
                                  max_queue_depth=4096)
        try:
            report = run_open_loop(
                server.submit, lambda i: pool[i % 64],
                rate_hz=300.0, duration_s=2.0, seed=7,
            )
            stats = server.stats()
        finally:
            server.close()
        assert report.completed > 100
        assert report.failed == 0
        assert report.p99_latency_s >= report.p50_latency_s > 0.0
        assert report.achieved_qps > 0.0
        d = report.to_row_dict()
        assert d["num_samples"] == report.completed
        assert d["offered_rate_hz"] == 300.0
        # Under offered load the batcher genuinely coalesced.
        assert stats["mean_batch_size"] > 1.0
