"""Live serving observability plane (ISSUE 10): mergeable log-bucketed
histograms (exact cross-replica merge, empty/single-sample contract),
the SLO burn-rate state machine + error-budget ledger, tail-sampled
request tracing with bucket exemplars, the live exporter (Prometheus +
atomic JSON snapshots, thread-join discipline), concurrent flight-dump
uniqueness, the registry snapshot-vs-observe race, and the ``bin/slo``
renderer.
"""

import json
import math
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from keystone_tpu import obs
from keystone_tpu.obs import flight as flight_mod
from keystone_tpu.obs import tracer as tracer_mod
from keystone_tpu.obs.metrics import (
    METRIC_PREFETCH_LOAD_S,
    METRIC_RUNTIME_LANE_TASKS,
    METRIC_SERVING_LATENCY_S,
    METRIC_SLO_STATE,
    BucketedHistogram,
)


@pytest.fixture(autouse=True)
def _no_leaked_tracer_or_dump_dir():
    """Tests that die inside obs.tracing / with a flight dump dir set
    must not leak process state into the rest of the suite."""
    yield
    tracer_mod._ACTIVE = None
    flight_mod.set_dump_dir(None)


# ---------------------------------------------------------------------------
# BucketedHistogram: the mergeable latency store
# ---------------------------------------------------------------------------


class TestBucketedHistogram:
    def test_empty_and_single_sample_contract(self):
        """PR-9 conventions, pinned for the bucketed form: empty ->
        None (never a fabricated zero), a single sample IS every
        percentile (returned exactly via the min/max clamp), and an
        out-of-range q raises naming the bound."""
        h = BucketedHistogram()
        assert h.percentile(50.0) is None
        assert h.percentile(99.0) is None
        snap = h.stats_snapshot()
        assert snap == {"count": 0, "sum": 0.0, "p50": None, "p99": None}
        h.observe(0.7)
        for q in (0.0, 50.0, 99.0, 100.0):
            assert h.percentile(q) == 0.7
        with pytest.raises(ValueError, match=r"\[0, 100\]"):
            h.percentile(101.0)
        with pytest.raises(ValueError, match=r"\[0, 100\]"):
            h.percentile(-1.0)

    def test_non_finite_is_rejected_loudly(self):
        h = BucketedHistogram()
        for bad in (float("nan"), float("inf"), float("-inf")):
            with pytest.raises(ValueError, match="finite"):
                h.observe(bad)
        assert h.count == 0

    def test_underflow_bucket_and_zero(self):
        h = BucketedHistogram()
        h.observe(0.0)
        assert h.percentile(50.0) == 0.0  # clamped to observed min/max
        h.observe(1e-9)
        assert 0.0 <= h.percentile(99.0) <= BucketedHistogram._LO

    def test_count_sum_snapshot(self):
        h = BucketedHistogram()
        for v in (0.1, 0.2, 0.3):
            h.observe(v)
        snap = h.stats_snapshot()
        assert snap["count"] == 3
        assert snap["sum"] == pytest.approx(0.6)

    def test_merge_is_exact_and_matches_concatenated_stream(self):
        """The acceptance property: merged p50/p99 over a pair of
        replica histograms (1) EXACTLY equals a histogram built from
        the concatenated stream (bucket counts add — no resampling),
        and (2) is within one bucket width of the true nearest-rank
        percentile of the raw concatenated values."""
        rng = np.random.default_rng(0)
        a_vals = rng.lognormal(mean=-4.0, sigma=1.0, size=700)
        b_vals = rng.lognormal(mean=-2.5, sigma=0.6, size=300)

        ha, hb, hcat = (
            BucketedHistogram(), BucketedHistogram(), BucketedHistogram()
        )
        for v in a_vals:
            ha.observe(v)
            hcat.observe(v)
        for v in b_vals:
            hb.observe(v)
            hcat.observe(v)
        merged = BucketedHistogram()
        merged.merge(ha).merge(hb)

        both = np.sort(np.concatenate([a_vals, b_vals]))
        assert merged.count == hcat.count == len(both)
        assert merged.total == pytest.approx(hcat.total)
        growth = BucketedHistogram._GROWTH
        for q in (10.0, 50.0, 90.0, 99.0):
            est = merged.percentile(q)
            # (1) exact merge: identical to the concatenated histogram.
            assert est == hcat.percentile(q), q
            # (2) within one bucket width of the true percentile.
            rank = max(int(math.ceil((q / 100.0) * len(both))), 1)
            true = both[rank - 1]
            assert true / (growth * 1.001) <= est <= true * growth * 1.001, (
                q, est, true
            )

    def test_merge_carries_min_max_and_exemplars(self):
        a, b = BucketedHistogram(), BucketedHistogram()
        a.observe(0.001, exemplar="run/1")
        b.observe(1.0, exemplar="run/2")
        a.merge(b)
        assert a.percentile(0.0) >= 0.001 * (1 / a._GROWTH)
        # p100 lands in the merged max's bucket (min/max merged too).
        assert 1.0 / a._GROWTH <= a.percentile(100.0) <= 1.0
        assert "run/2" in a.exemplars_at_or_above(99.0)

    def test_exemplars_link_tail_buckets_worst_first(self):
        h = BucketedHistogram()
        for i in range(100):
            h.observe(0.001, exemplar=f"run/fast{i}")
        h.observe(5.0, exemplar="run/slow")
        tail = h.exemplars_at_or_above(99.0)
        assert tail[0] == "run/slow"
        assert h.exemplars_at_or_above(99.0, limit=1) == ["run/slow"]
        assert BucketedHistogram().exemplars_at_or_above(99.0) == []

    def test_registry_form_and_snapshot_surface(self):
        """`snapshot()` keeps the `.count/.sum/.p50/.p99` sub-key
        surface for the bucketed form — dashboards don't care which
        store backs a latency metric."""
        r = obs.MetricsRegistry()
        h = r.bucketed_histogram(METRIC_SERVING_LATENCY_S)
        assert r.bucketed_histogram(METRIC_SERVING_LATENCY_S) is h
        for v in (0.1, 0.2, 0.4):
            h.observe(v)
        snap = r.snapshot()
        assert snap["serving.latency_s.count"] == 3
        assert snap["serving.latency_s.p50"] == pytest.approx(0.2, rel=0.09)
        with pytest.raises(TypeError, match="already registered"):
            r.histogram(METRIC_SERVING_LATENCY_S)


# ---------------------------------------------------------------------------
# Registry snapshot raced against concurrent observe()/add()
# ---------------------------------------------------------------------------


class TestRegistrySnapshotRace:
    def test_snapshot_never_throws_and_counters_never_regress(self):
        """ISSUE 10 satellite: lane workers hammer observe()/add()
        while an exporter thread snapshots — every snapshot must
        succeed, counters and histogram counts must read monotonically
        across successive snapshots, and each histogram's four sub-keys
        must be mutually consistent (one lock acquisition)."""
        r = obs.MetricsRegistry()
        stop = threading.Event()
        errors = []

        def worker(site):
            c = r.counter(METRIC_RUNTIME_LANE_TASKS, site=site)
            ring = r.histogram(METRIC_PREFETCH_LOAD_S)
            bucketed = r.bucketed_histogram(METRIC_SERVING_LATENCY_S)
            i = 0
            try:
                while not stop.is_set():
                    c.add(1)
                    ring.observe(0.001 * (i % 7 + 1))
                    bucketed.observe(0.001 * (i % 5 + 1))
                    i += 1
            except Exception as e:  # noqa: BLE001 — reported below
                errors.append(e)

        threads = [
            threading.Thread(target=worker, args=(s,), daemon=True)
            for s in ("read", "verify", "checkpoint")
        ]
        for th in threads:
            th.start()
        try:
            prev_counter = 0.0
            prev_ring = prev_bucketed = 0
            for _ in range(300):
                snap = r.snapshot()  # must never throw
                total = sum(
                    v for k, v in snap.items()
                    if k.startswith("runtime.lane.tasks{")
                )
                assert total >= prev_counter
                prev_counter = total
                ring_count = snap.get("prefetch.load_s.count", 0)
                assert ring_count >= prev_ring
                prev_ring = ring_count
                b_count = snap.get("serving.latency_s.count", 0)
                assert b_count >= prev_bucketed
                prev_bucketed = b_count
                if b_count:
                    assert snap["serving.latency_s.p99"] is not None
        finally:
            stop.set()
            for th in threads:
                th.join(timeout=10)
        assert not errors


# ---------------------------------------------------------------------------
# SLO objectives, burn rates, the state machine, the budget ledger
# ---------------------------------------------------------------------------


def _fake_clock():
    now = [0.0]

    def clock():
        return now[0]

    return now, clock


def _latency_objective(**kw):
    kw.setdefault("threshold_s", 0.1)
    kw.setdefault("target", 0.9)
    kw.setdefault("fast_window_s", 1.0)
    kw.setdefault("slow_window_s", 4.0)
    kw.setdefault("warn_burn", 1.0)
    kw.setdefault("breach_burn", 5.0)
    return obs.SLOObjective("latency", kind="latency", **kw)


class TestSLOObjectiveValidation:
    def test_kind_threshold_target_window_and_burn_order(self):
        with pytest.raises(ValueError, match="kind"):
            obs.SLOObjective("x", kind="throughput")
        with pytest.raises(ValueError, match="threshold_s"):
            obs.SLOObjective("x", kind="latency", threshold_s=None)
        with pytest.raises(ValueError, match="target"):
            obs.SLOObjective("x", kind="availability", target=1.0)
        with pytest.raises(ValueError, match="fast_window_s"):
            obs.SLOObjective(
                "x", kind="availability", fast_window_s=10.0,
                slow_window_s=5.0,
            )
        with pytest.raises(ValueError, match="breach_burn"):
            obs.SLOObjective(
                "x", kind="availability", warn_burn=3.0, breach_burn=1.0,
            )
        with pytest.raises(ValueError, match="min_events"):
            obs.SLOObjective("x", kind="availability", min_events=0)

    def test_tracker_rejects_empty_and_duplicate_objectives(self):
        with pytest.raises(ValueError, match="at least one"):
            obs.SLOTracker([])
        with pytest.raises(ValueError, match="duplicate"):
            obs.SLOTracker([
                obs.SLOObjective("a", kind="availability"),
                obs.SLOObjective("a", kind="availability"),
            ])


class TestSLOStateMachine:
    def test_breach_and_recovery_with_budget_ledger(self):
        """The acceptance sequence, deterministic under a fake clock:
        healthy traffic -> OK; a failure storm -> BREACH (fast-window
        burn over the page threshold); the storm ages out of the slow
        window -> recovery to OK — with the error-budget ledger
        attributing the bad events to the degraded interval."""
        now, clock = _fake_clock()
        tr = obs.SLOTracker([_latency_objective()], clock=clock)

        for _ in range(50):
            tr.observe(latency_s=0.01)
        assert tr.states() == {"latency": "OK"}

        now[0] = 1.0  # the fast window ages the healthy phase out
        for _ in range(20):
            tr.observe(latency_s=2.0)  # way past threshold_s
        assert tr.states() == {"latency": "BREACH"}
        assert tr.worst_state() == "BREACH"

        # Recovery: healthy traffic after both windows pass the storm.
        now[0] = 6.0
        for _ in range(50):
            tr.observe(latency_s=0.01)
        assert tr.states() == {"latency": "OK"}

        v = tr.verdict()
        assert v["state"] == "OK"
        o = v["objectives"]["latency"]
        # The storm escalates (possibly via WARN as the slow window
        # dilutes) to exactly one BREACH, and the run ends recovered.
        tos = [t["to"] for t in o["transitions"]]
        assert tos[-2:] == ["BREACH", "OK"]
        assert tos.count("BREACH") == 1
        assert o["good_total"] == 100
        assert o["bad_total"] == 20
        # Budget: 20 bad / 120 total against a 10% budget.
        assert o["budget_spent_fraction"] == pytest.approx(
            (20 / 120) / 0.1, abs=1e-3
        )
        # The ledger attributes the storm to the degraded intervals:
        # escalation fires on the min_events-th bad observation (which
        # is charged to the interval it arrived in), and every bad
        # event after the BREACH transition lands on the breach entry.
        states = [e["state"] for e in o["ledger"]]
        assert states[0] == "OK" and states[-1] == "OK"
        breach = [e for e in o["ledger"] if e["state"] == "BREACH"]
        assert len(breach) == 1
        assert breach[0]["bad"] == 10 and breach[0]["good"] <= 1
        assert breach[0]["t_end"] is not None
        assert o["ledger"][-1]["t_end"] is None  # the open interval

    def test_min_events_gates_escalation_not_decay(self):
        """Regression (seen on the chaos bench's first cold batch): ONE
        slow request in an otherwise-empty fast window is a 100% bad
        fraction — burn = 1/budget — and must NOT page. Escalation
        waits for min_events; de-escalation never does."""
        now, clock = _fake_clock()
        tr = obs.SLOTracker(
            [_latency_objective(min_events=10)], clock=clock
        )
        tr.observe(latency_s=2.0)  # the cold first request, slow
        assert tr.states() == {"latency": "OK"}
        for _ in range(8):
            tr.observe(latency_s=2.0)
        assert tr.states() == {"latency": "OK"}  # 9 events: still gated
        tr.observe(latency_s=2.0)
        assert tr.states() == {"latency": "BREACH"}  # 10th: real storm

    def test_idle_decay_via_evaluate(self):
        """A breach with NO follow-up traffic must still clear: the
        exporter's periodic evaluate() re-runs the windows on the
        current clock."""
        now, clock = _fake_clock()
        tr = obs.SLOTracker([_latency_objective()], clock=clock)
        for _ in range(10):
            tr.observe(latency_s=2.0)
        assert tr.states() == {"latency": "BREACH"}
        now[0] = 10.0
        assert tr.evaluate() == {"latency": "OK"}

    def test_warn_between_ok_and_breach_and_hysteresis(self):
        """A slow-window burn above warn_burn WARNs without paging; a
        breach only clears when the fast burn is back under warn_burn
        (not merely under breach_burn — no flapping)."""
        now, clock = _fake_clock()
        tr = obs.SLOTracker(
            [_latency_objective(warn_burn=1.0, breach_burn=8.0)],
            clock=clock,
        )
        # 3 bad / 20 total in both windows: burn = 0.15/0.1 = 1.5 —
        # above warn, far below breach.
        for _ in range(17):
            tr.observe(latency_s=0.01)
        for _ in range(3):
            tr.observe(latency_s=2.0)
        assert tr.states() == {"latency": "WARN"}

        # Storm to BREACH (83 bad / 100 total -> burn 8.3), then dilute
        # the fast window to burn ~2 (>= warn, < breach): hysteresis
        # holds the breach.
        for _ in range(80):
            tr.observe(latency_s=2.0)
        assert tr.states() == {"latency": "BREACH"}
        now[0] = 1.0
        for _ in range(8):
            tr.observe(latency_s=2.0)
        for _ in range(32):
            tr.observe(latency_s=0.01)
        # fast window (0,1]: 8/40 bad -> burn 2.0: under breach_burn but
        # over warn_burn -> still BREACH (hysteresis).
        assert tr.states() == {"latency": "BREACH"}
        now[0] = 6.0
        tr.observe(latency_s=0.01)
        assert tr.states() == {"latency": "OK"}

    def test_availability_objective_counts_rejects(self):
        now, clock = _fake_clock()
        tr = obs.SLOTracker([
            obs.SLOObjective(
                "availability", kind="availability", target=0.5,
                fast_window_s=1.0, slow_window_s=2.0, breach_burn=1.9,
            ),
        ], clock=clock)
        tr.observe(latency_s=0.01)  # good
        tr.observe(ok=False)        # shed/reject/failure
        v = tr.verdict()["objectives"]["availability"]
        assert v["good_total"] == 1 and v["bad_total"] == 1

    def test_latency_objective_ignores_ok_without_latency(self):
        """ok=True with no measured latency is not a latency SLI (but
        still a good availability event)."""
        now, clock = _fake_clock()
        tr = obs.SLOTracker([
            _latency_objective(),
            obs.SLOObjective("availability", kind="availability"),
        ], clock=clock)
        tr.observe(ok=True)
        v = tr.verdict()["objectives"]
        assert v["latency"]["good_total"] == 0
        assert v["availability"]["good_total"] == 1

    def test_transitions_are_traced_and_breach_dumps_flight(self, caplog):
        import logging

        now, clock = _fake_clock()
        tr = obs.SLOTracker([_latency_objective()], clock=clock)
        with caplog.at_level(
            logging.WARNING, logger="keystone_tpu.obs.flight"
        ):
            with obs.tracing() as t:
                for _ in range(10):
                    tr.observe(latency_s=2.0)
        evs = [r for r in t.events if r.get("name") == "slo.transition"]
        assert len(evs) == 1
        assert evs[0]["args"]["to"] == "BREACH"
        assert any("SLO BREACH" in r.message for r in caplog.records)

    def test_states_publish_into_registry(self):
        now, clock = _fake_clock()
        reg = obs.MetricsRegistry()
        tr = obs.SLOTracker(
            [_latency_objective()], metrics=reg, clock=clock
        )
        for _ in range(10):
            tr.observe(latency_s=2.0)
        # Gauges refresh on evaluate() (the exporter tick), not on the
        # per-request hot path — the transition counter is the
        # exception (transitions are rare and must never be missed).
        assert reg.snapshot()["slo.state{objective=latency}"] == 0.0
        tr.evaluate()
        snap = reg.snapshot()
        assert snap["slo.state{objective=latency}"] == 2.0  # BREACH
        assert snap["slo.burn_rate_fast{objective=latency}"] >= 5.0
        assert snap["slo.transitions{objective=latency}"] == 1.0


# ---------------------------------------------------------------------------
# Tail-sampled request tracing + exemplars
# ---------------------------------------------------------------------------


class TestTailSampler:
    def test_validation(self):
        with pytest.raises(ValueError, match=r"\[0, 1\]"):
            obs.TailSampler(head_rate=1.5)
        with pytest.raises(ValueError, match="slow_s"):
            obs.TailSampler(slow_s=0.0)

    def test_flagged_and_slow_always_kept(self):
        s = obs.TailSampler(head_rate=0.0, slow_s=0.5)
        assert s.keep(0.001, flagged=True) == (True, "flagged")
        assert s.keep(0.9) == (True, "slow")
        assert s.keep(0.001) == (False, None)
        st = s.stats()
        assert st["kept"] == {"flagged": 1, "slow": 1}
        assert st["kept_total"] == 2 and st["sampled_out"] == 1

    def test_head_rate_keeps_every_nth_deterministically(self):
        s = obs.TailSampler(head_rate=0.25)
        kept = [s.keep(0.001)[0] for _ in range(20)]
        assert sum(kept) == 5
        assert kept == ([False, False, False, True] * 5)

    def test_rate_one_keeps_everything(self):
        s = obs.TailSampler(head_rate=1.0)
        assert all(s.keep(0.0)[0] for _ in range(10))
        assert s.stats()["sampled_out"] == 0

    def test_tracer_applies_sampler_to_serving_spans_only(self):
        sampler = obs.TailSampler(head_rate=0.0, slow_s=0.5)
        with obs.tracing(serving_sampler=sampler) as t:
            t0 = time.perf_counter()
            # Healthy fast span: sampled out.
            assert t.add_serving_span("serving.request", t0, t0 + 0.01) \
                is None
            # Error span: always kept, reason stamped.
            sid = t.add_serving_span(
                "serving.request", t0, t0 + 0.01, flagged=True,
                outcome="error",
            )
            assert sid is not None
            # Slow span: always kept.
            assert t.add_serving_span(
                "serving.request", t0, t0 + 0.9
            ) is not None
            # Fit-path spans are never sampled.
            assert t.add_span("fold.chunk", t0, t0 + 0.001) is not None
        kept = t.spans("serving.request")
        assert len(kept) == 2
        assert {s["args"].get("keep") for s in kept} == {"flagged", "slow"}

    def test_no_sampler_keeps_everything(self):
        with obs.tracing() as t:
            t0 = time.perf_counter()
            assert t.add_serving_span("serving.request", t0, t0 + 0.001) \
                is not None

    def test_sampler_from_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KEYSTONE_TRACE", str(tmp_path / "tr"))
        monkeypatch.setenv("KEYSTONE_TRACE_SAMPLE", "0.5")
        monkeypatch.setenv("KEYSTONE_TRACE_SLOW_MS", "250")
        with obs.tracing_from_env():
            t = obs.active_tracer()
            assert t.serving_sampler is not None
            assert t.serving_sampler.head_rate == 0.5
            assert t.serving_sampler.slow_s == pytest.approx(0.25)

    def test_env_knob_parse_errors_name_the_variable(self, tmp_path,
                                                     monkeypatch):
        monkeypatch.setenv("KEYSTONE_TRACE", str(tmp_path / "tr"))
        monkeypatch.setenv("KEYSTONE_TRACE_SAMPLE", "1%")
        with pytest.raises(ValueError, match="KEYSTONE_TRACE_SAMPLE"):
            obs.tracing_from_env()
        monkeypatch.setenv("KEYSTONE_TRACE_SAMPLE", "2")
        with pytest.raises(ValueError, match="KEYSTONE_TRACE_SAMPLE"):
            obs.tracing_from_env()


# ---------------------------------------------------------------------------
# The live exporter
# ---------------------------------------------------------------------------


def _sources():
    reg = obs.MetricsRegistry()
    reg.counter(METRIC_RUNTIME_LANE_TASKS, site="read").add(3)
    reg.bucketed_histogram(METRIC_SERVING_LATENCY_S).observe(0.02)
    return reg


class TestLiveExporter:
    def test_publish_collects_renders_and_snapshots(self, tmp_path):
        reg = _sources()
        now, clock = _fake_clock()
        tr = obs.SLOTracker([_latency_objective()], clock=clock)
        tr.observe(latency_s=0.01)
        ex = obs.LiveExporter(
            sources={"metrics": reg, "serving": lambda: {"completed": 7}},
            slo=tr, snapshot_dir=str(tmp_path), interval_s=60.0,
        )
        try:
            doc = ex.publish_now()
            assert doc["serving"]["completed"] == 7
            assert doc["slo"]["state"] == "OK"
            assert doc["metrics"]["serving.latency_s.count"] == 1
            assert doc["exporter"]["exporter.publishes"] >= 0
            # Atomic JSON snapshot on disk, loadable.
            with open(tmp_path / "live_metrics.json") as f:
                on_disk = json.load(f)
            assert on_disk["serving"]["completed"] == 7
            # Prometheus text: labeled registry keys + flattened dicts.
            text = ex.last_prometheus()
            assert 'keystone_metrics_runtime_lane_tasks{site="read"} 3' \
                in text
            assert "keystone_serving_completed 7" in text
            assert 'keystone_slo_objectives_latency_burn_fast' in text
            # The ALERTABLE numeric projection: the string state is
            # JSON-only, state_level is what a Prometheus alert reads.
            assert "keystone_slo_state_level 0" in text
            assert "keystone_slo_objectives_latency_state_level 0" in text
        finally:
            ex.close()

    def test_http_endpoints(self):
        reg = _sources()
        ex = obs.LiveExporter(
            sources={"metrics": reg}, port=0, interval_s=60.0,
        )
        try:
            ex.publish_now()
            base = f"http://127.0.0.1:{ex.port}"
            with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
                assert r.read() == b"ok\n"
            with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
                assert b"keystone_metrics_runtime_lane_tasks" in r.read()
            with urllib.request.urlopen(
                base + "/snapshot.json", timeout=5
            ) as r:
                doc = json.loads(r.read())
            assert doc["metrics"]["runtime.lane.tasks{site=read}"] == 3.0
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(base + "/nope", timeout=5)
        finally:
            ex.close()

    def test_close_joins_both_threads_and_is_idempotent(self):
        ex = obs.LiveExporter(sources={}, port=0, interval_s=60.0)
        ex.close()
        ex.close()
        assert not ex._thread.is_alive()
        assert not ex._http_thread.is_alive()

    def test_final_publish_on_close(self, tmp_path):
        calls = []
        ex = obs.LiveExporter(
            sources={"s": lambda: calls.append(1) or {"n": len(calls)}},
            snapshot_dir=str(tmp_path), interval_s=60.0,
        )
        ex.close()
        assert calls  # close() publishes once even if no tick elapsed
        with open(tmp_path / "live_metrics.json") as f:
            assert json.load(f)["s"]["n"] == len(calls)

    def test_publisher_loop_ticks(self):
        reg = _sources()
        ex = obs.LiveExporter(sources={"metrics": reg}, interval_s=0.02)
        try:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if ex.last_snapshot().get("metrics"):
                    break
                time.sleep(0.01)
            assert ex.last_snapshot()["metrics"][
                "runtime.lane.tasks{site=read}"
            ] == 3.0
        finally:
            ex.close()

    def test_collector_error_is_counted_never_fatal(self):
        def boom():
            raise RuntimeError("collector down")

        ex = obs.LiveExporter(
            sources={"bad": boom, "good": lambda: {"v": 1}},
            interval_s=60.0,
        )
        try:
            doc = ex.publish_now()
            assert doc["good"]["v"] == 1
            assert "bad" not in doc
            assert ex.metrics.snapshot()["exporter.errors"] >= 1
        finally:
            ex.close()

    def test_validation(self):
        with pytest.raises(ValueError, match="interval_s"):
            obs.LiveExporter(interval_s=0.0)
        with pytest.raises(TypeError, match="callable"):
            obs.LiveExporter(sources={"x": 42})

    def test_render_prometheus_skips_non_numeric_and_sequences(self):
        text = obs.render_prometheus({
            "serving": {
                "state": "OK",            # string: JSON-only
                "ledger": [1, 2, 3],      # sequence: JSON-only
                "ok": True,               # bool: skipped
                "p99_latency_s": 0.25,
            },
        })
        assert text == "keystone_serving_p99_latency_s 0.25\n"


# ---------------------------------------------------------------------------
# Flight recorder: concurrent dumps must not clobber each other
# ---------------------------------------------------------------------------


class TestConcurrentFlightDumps:
    def test_concurrent_dumps_get_unique_files(self, tmp_path):
        """ISSUE 10 satellite regression: two replicas dying in the
        same tick dump concurrently — every dump must land in its OWN
        file (O_EXCL + per-process sequence), none clobbered."""
        flight_mod.set_dump_dir(str(tmp_path))
        n = 16
        barrier = threading.Barrier(n)

        def die(i):
            barrier.wait()
            flight_mod.dump_flight_record(f"replica {i} died")

        threads = [
            threading.Thread(target=die, args=(i,)) for i in range(n)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=30)
        files = sorted(tmp_path.glob("flight-*.txt"))
        assert len(files) == n
        contexts = set()
        for f in files:
            body = f.read_text()
            assert "flight record" in body
            contexts.add(body.splitlines()[0])
        assert contexts == {f"context: replica {i} died" for i in range(n)}

    def test_unwritable_dump_dir_keeps_the_loud_log(self, caplog):
        """Regression: the on-disk dump is an augmentation — an
        unwritable dump dir (bad env, full disk) must neither raise
        nor swallow the warning log the dump exists to emit."""
        import logging

        flight_mod.set_dump_dir("/proc/definitely/not/writable")
        with caplog.at_level(
            logging.WARNING, logger="keystone_tpu.obs.flight"
        ):
            block = flight_mod.dump_flight_record("replica died")
        assert "flight record" in block
        assert any("replica died" in r.message for r in caplog.records)

    def test_env_knob_and_no_dir_writes_nothing(self, tmp_path,
                                                monkeypatch):
        sub = tmp_path / "envdumps"
        monkeypatch.setenv(flight_mod.DUMP_DIR_ENV, str(sub))
        flight_mod.dump_flight_record("env-configured death")
        assert len(list(sub.glob("flight-*.txt"))) == 1
        monkeypatch.delenv(flight_mod.DUMP_DIR_ENV)
        flight_mod.set_dump_dir(None)
        flight_mod.dump_flight_record("no dir configured")  # must not raise
        assert len(list(sub.glob("flight-*.txt"))) == 1


# ---------------------------------------------------------------------------
# bin/slo: the snapshot renderer
# ---------------------------------------------------------------------------


class TestSLOCli:
    def _snapshot_dir(self, tmp_path):
        now, clock = _fake_clock()
        tr = obs.SLOTracker([_latency_objective()], clock=clock)
        for _ in range(20):
            tr.observe(latency_s=0.01)
        now[0] = 1.0
        for _ in range(10):
            tr.observe(latency_s=2.0)  # BREACH, on the record
        now[0] = 6.0
        tr.evaluate()  # recovery
        ex = obs.LiveExporter(
            sources={"serving": lambda: {
                "completed": 30, "rejected": 0, "failed": 10,
                "p99_latency_s": 0.02,
            }},
            slo=tr, snapshot_dir=str(tmp_path), interval_s=60.0,
        )
        ex.close()  # close() publishes the final snapshot
        return tmp_path

    def test_renders_verdict_transitions_and_ledger(self, tmp_path,
                                                    capsys):
        from keystone_tpu.tools import slo as slo_cli

        assert slo_cli.main([str(self._snapshot_dir(tmp_path))]) == 0
        out = capsys.readouterr().out
        assert "SLO verdict: OK" in out
        assert "latency" in out
        assert "BREACH" in out          # the transition log
        assert "budget ledger" in out
        assert "completed=30" in out    # the serving summary line

    def test_errors_on_missing_or_empty_snapshot(self, tmp_path, capsys):
        from keystone_tpu.tools import slo as slo_cli

        assert slo_cli.main([str(tmp_path / "nope")]) == 1
        assert "cannot read" in capsys.readouterr().err
        empty = tmp_path / "live_metrics.json"
        empty.write_text("{}")
        assert slo_cli.main([str(empty)]) == 1

    def test_bin_wrapper_exists_and_is_executable(self):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = os.path.join(root, "bin", "slo")
        assert os.access(path, os.X_OK)
        with open(path) as f:
            assert "keystone_tpu.tools.slo" in f.read()


# ---------------------------------------------------------------------------
# Serving integration: the plane feeds the SLO tracker; exemplars flow
# ---------------------------------------------------------------------------


class TestServingIntegration:
    def _server(self, slo=None, **kw):
        from keystone_tpu.serving.export import export_plan
        from keystone_tpu.serving.batcher import MicroBatchServer
        from keystone_tpu.workflow import Transformer
        from tests._serving_util import fitted_from_transformer

        class Scale2(Transformer):
            def apply(self, x):
                import jax.numpy as jnp

                return jnp.asarray(x) * 2.0

        plan = export_plan(
            fitted_from_transformer(Scale2()), np.zeros(4, np.float32),
            max_batch=8,
        )
        kw.setdefault("max_wait_ms", 0.5)
        return MicroBatchServer(plan, slo=slo, **kw)

    @pytest.mark.chaos
    def test_served_breach_and_recovery_sequence(self):
        """The acceptance chaos sequence at unit scale, deterministic
        under a fake tracker clock: a healthy served window is OK, an
        injected execute-failure storm drives BREACH, post-storm
        healthy traffic recovers to OK — and the error-budget ledger
        attributes the failures to the degraded interval."""
        from keystone_tpu.serving.batcher import ServerClosed  # noqa: F401
        from keystone_tpu.utils.faults import FaultPlan, FaultRule

        now, clock = _fake_clock()
        tr = obs.SLOTracker([
            obs.SLOObjective(
                "availability", kind="availability", target=0.9,
                fast_window_s=1.0, slow_window_s=4.0, breach_burn=4.0,
            ),
        ], clock=clock)
        srv = self._server(slo=tr, breaker_threshold=0)
        x = np.zeros(4, np.float32)
        try:
            for _ in range(20):
                srv.submit(x).result(timeout=30)
            assert tr.states() == {"availability": "OK"}

            now[0] = 1.0
            storm = FaultPlan([FaultRule(
                "serving.execute", "error", calls=list(range(64)),
            )])
            with storm:
                for _ in range(20):
                    with pytest.raises(Exception):
                        srv.submit(x).result(timeout=30)
            assert tr.states() == {"availability": "BREACH"}

            now[0] = 6.0
            for _ in range(20):
                srv.submit(x).result(timeout=30)
            tr.evaluate()
            assert tr.states() == {"availability": "OK"}
        finally:
            srv.close()
        o = tr.verdict()["objectives"]["availability"]
        tos = [t["to"] for t in o["transitions"]]
        assert tos[-2:] == ["BREACH", "OK"]
        assert tos.count("BREACH") == 1
        assert o["good_total"] == 40 and o["bad_total"] == 20
        breach = [e for e in o["ledger"] if e["state"] == "BREACH"]
        # Escalation fires on the min_events-th failure (charged to the
        # preceding interval); the rest of the storm lands on the
        # breach entry.
        assert len(breach) == 1 and breach[0]["bad"] == 10

    def test_shed_feeds_slo_as_bad_event(self):
        """Admission control spends error budget visibly: a shed
        victim is a bad availability event."""
        from keystone_tpu.serving.batcher import ServerOverloaded

        now, clock = _fake_clock()
        tr = obs.SLOTracker([
            obs.SLOObjective("availability", kind="availability"),
        ], clock=clock)
        srv = self._server(
            slo=tr, max_queue_depth=1, max_wait_ms=200.0,
        )
        x = np.zeros(4, np.float32)
        futures = []
        try:
            # Queue depth 1 + a 200ms batching window: each new submit
            # sheds the previously queued request (earliest deadline
            # first — the VICTIM's future carries the overload, the
            # incoming submit does not raise).
            for _ in range(8):
                futures.append(srv.submit(x, deadline_ms=1e6))
        finally:
            srv.close()
        sheds = 0
        for f in futures:
            try:
                f.result(timeout=30)
            except ServerOverloaded:
                sheds += 1
            except Exception:  # noqa: BLE001 — the last queued request
                pass           # resolves ServerClosed on close()
        assert sheds >= 1
        assert tr.verdict()["objectives"]["availability"]["bad_total"] \
            >= sheds

    def test_completed_requests_attach_trace_exemplars(self):
        """Under tracing, a kept serving span's run_id/span_id lands as
        an exemplar on its latency bucket — the p99-breach→trace
        link."""
        with obs.tracing() as t:
            srv = self._server()
            x = np.zeros(4, np.float32)
            try:
                for _ in range(5):
                    srv.submit(x).result(timeout=30)
            finally:
                srv.close()
            hist = srv.metrics.bucketed_histogram(METRIC_SERVING_LATENCY_S)
            refs = hist.exemplars_at_or_above(0.0, limit=8)
            assert refs
            for ref in refs:
                run_id, sid = ref.split("/")
                assert run_id == t.run_id
                assert any(
                    r.get("span_id") == int(sid)
                    for r in t.spans("serving.request")
                )

    def test_loadgen_report_carries_slo_verdict(self):
        from keystone_tpu.serving.loadgen import run_open_loop

        now, clock = _fake_clock()
        tr = obs.SLOTracker([
            obs.SLOObjective("availability", kind="availability"),
        ], clock=clock)
        srv = self._server(slo=tr)
        try:
            report = run_open_loop(
                srv.submit, lambda i: np.zeros(4, np.float32),
                rate_hz=200.0, duration_s=0.2, seed=0, slo=tr,
            )
        finally:
            srv.close()
        assert report.slo is not None
        assert report.slo["state"] == "OK"
        row = report.to_row_dict()
        assert row["slo"]["objectives"]["availability"]["state"] == "OK"
        assert "ledger" not in row["slo"]["objectives"]["availability"]
