"""Pallas kernels composed with multi-device meshes via shard_map.

Round-1 gated every Pallas kernel to single-device processes; these tests pin
the round-2 contract: each kernel runs *per shard* inside shard_map (interpret
mode on the forced-CPU mesh, real Mosaic on TPU) and the collectives around it
reproduce the XLA-path numbers.

Reference behaviors under test: BCD Gramian+correlation reductions (mlmatrix
NormalEquations / BlockCoordinateDescent), blocked Gaussian kernel generation
(KernelGenerator.scala:121-205), CosineRandomFeatures (CosineRandomFeatures.scala:19-61).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.data import Dataset
from keystone_tpu.parallel import linalg, ring
from keystone_tpu.parallel import mesh as mesh_lib


@pytest.fixture
def force_pallas(monkeypatch):
    """Force the Pallas kernels on (interpret mode off-TPU)."""
    monkeypatch.delenv("KEYSTONE_NO_PALLAS", raising=False)
    monkeypatch.setenv("KEYSTONE_PALLAS", "1")


def _mesh():
    return mesh_lib.make_mesh()


class TestShardedBCDPallas:
    def test_mesh_bcd_pallas_matches_xla(self, force_pallas):
        rng = np.random.default_rng(0)
        n, db, k = 64, 16, 3
        blocks = [
            rng.normal(size=(n, db)).astype(np.float32) for _ in range(2)
        ]
        B = rng.normal(size=(n, k)).astype(np.float32)
        mesh = _mesh()
        sharded = [mesh_lib.shard_rows(b, mesh) for b in blocks]
        B_sh = mesh_lib.shard_rows(B, mesh)

        Ws_pallas = linalg.bcd_least_squares(
            sharded, B_sh, lam=1e-3, num_iter=2, mesh=mesh, use_pallas=True
        )
        Ws_xla = linalg.bcd_least_squares(
            [jnp.asarray(b) for b in blocks], jnp.asarray(B),
            lam=1e-3, num_iter=2,
        )
        for wp, wx in zip(Ws_pallas, Ws_xla):
            np.testing.assert_allclose(
                np.asarray(wp), np.asarray(wx), rtol=0, atol=2e-4
            )

    def test_mesh_bcd_xla_shardmap_matches_unsharded(self):
        # The shard_map XLA body (use_pallas=False) must match the plain
        # GSPMD path bit-for-bit-ish in f64.
        rng = np.random.default_rng(1)
        n, db, k = 48, 8, 2
        blocks = [rng.normal(size=(n, db)) for _ in range(3)]
        B = rng.normal(size=(n, k))
        mesh = _mesh()
        Ws_mesh = linalg.bcd_least_squares(
            [mesh_lib.shard_rows(b, mesh) for b in blocks],
            mesh_lib.shard_rows(B, mesh),
            lam=1e-2, num_iter=2, mesh=mesh, use_pallas=False,
        )
        Ws_ref = linalg.bcd_least_squares(
            [jnp.asarray(b) for b in blocks], jnp.asarray(B),
            lam=1e-2, num_iter=2,
        )
        for wm, wr in zip(Ws_mesh, Ws_ref):
            np.testing.assert_allclose(
                np.asarray(wm), np.asarray(wr), rtol=0, atol=1e-9
            )


class TestRingPallas:
    def test_ring_gaussian_pallas_matches_xla(self, force_pallas):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(64, 12)).astype(np.float32)
        mesh = _mesh()
        Xs = mesh_lib.shard_rows(X, mesh)
        K_pallas = np.asarray(ring.ring_pairwise_gaussian(Xs, 0.3, mesh))
        K_ref = np.asarray(ring._gaussian_xla(jnp.asarray(X), jnp.asarray(X), 0.3))
        np.testing.assert_allclose(K_pallas, K_ref, rtol=0, atol=5e-6)

    def test_ring_kernel_apply_pallas(self, force_pallas):
        rng = np.random.default_rng(3)
        Xtr = rng.normal(size=(64, 10)).astype(np.float32)
        Xte = rng.normal(size=(32, 10)).astype(np.float32)
        W = rng.normal(size=(64, 4)).astype(np.float32)
        mesh = _mesh()
        preds = np.asarray(
            ring.ring_kernel_apply(
                mesh_lib.shard_rows(Xte, mesh),
                mesh_lib.shard_rows(Xtr, mesh),
                mesh_lib.shard_rows(W, mesh),
                0.2,
                mesh,
            )
        )
        K = np.asarray(
            ring._gaussian_xla(jnp.asarray(Xte), jnp.asarray(Xtr), 0.2)
        )
        np.testing.assert_allclose(preds, K @ W, rtol=0, atol=5e-5)

    def test_ring_f64_keeps_xla_path(self, force_pallas):
        # x64 operands must not silently drop to the f32 Pallas kernel.
        rng = np.random.default_rng(4)
        X = rng.normal(size=(32, 6))  # float64 under the tests' x64 config
        mesh = _mesh()
        K = np.asarray(
            ring.ring_pairwise_gaussian(mesh_lib.shard_rows(X, mesh), 0.5, mesh)
        )
        assert K.dtype == np.float64
        K_ref = np.asarray(
            ring._gaussian_xla(jnp.asarray(X), jnp.asarray(X), 0.5)
        )
        np.testing.assert_allclose(K, K_ref, rtol=0, atol=1e-12)


class TestRingAttention:
    """Ring attention (Liu et al. 2023 schedule): queries resident, KV
    circulating via ppermute with online-softmax folding. Must equal the
    unsharded softmax(QKᵀ/√d)V exactly, including global-position causal
    masking across shard boundaries."""

    def _ref(self, Q, K, V, causal):
        s = (Q @ K.T) / np.sqrt(Q.shape[1])
        if causal:
            n = Q.shape[0]
            s = np.where(np.tril(np.ones((n, n), bool)), s, -np.inf)
        p = np.exp(s - s.max(axis=1, keepdims=True))
        p /= p.sum(axis=1, keepdims=True)
        return p @ V

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_unsharded_attention(self, causal):
        rng = np.random.default_rng(8)
        n, d = 64, 16
        Q = rng.normal(size=(n, d))
        K = rng.normal(size=(n, d))
        V = rng.normal(size=(n, d))
        mesh = _mesh()
        out = np.asarray(
            ring.ring_attention(
                mesh_lib.shard_rows(Q, mesh),
                mesh_lib.shard_rows(K, mesh),
                mesh_lib.shard_rows(V, mesh),
                mesh=mesh,
                causal=causal,
            )
        )
        np.testing.assert_allclose(out, self._ref(Q, K, V, causal), atol=1e-10)

    def test_padded_rows_masked_by_n_valid(self):
        """pad_rows' zero-padding invariant does NOT hold under softmax
        (a zero key still gets weight); n_valid masks both the ghost keys
        and the padded query rows."""
        rng = np.random.default_rng(10)
        n, d = 500, 8  # pads to 504 over 8 shards
        Q = rng.normal(size=(n, d))
        K = rng.normal(size=(n, d))
        V = rng.normal(size=(n, d))
        mesh = _mesh()
        Qp, _ = mesh_lib.pad_rows(Q, 8)
        Kp, _ = mesh_lib.pad_rows(K, 8)
        Vp, _ = mesh_lib.pad_rows(V, 8)
        out = np.asarray(
            ring.ring_attention(
                mesh_lib.shard_rows(Qp, mesh),
                mesh_lib.shard_rows(Kp, mesh),
                mesh_lib.shard_rows(Vp, mesh),
                mesh=mesh,
                n_valid=n,
            )
        )
        np.testing.assert_allclose(
            out[:n], self._ref(Q, K, V, False), atol=1e-10
        )
        np.testing.assert_allclose(out[n:], 0.0, atol=0)

    def test_bf16_operands_f32_state(self):
        """bf16 layouts keep the online-softmax state in f32: error stays at
        the bf16 output-quantization floor, not accumulation-driven."""
        import jax.numpy as jnp

        rng = np.random.default_rng(11)
        n, d = 512, 16
        Q = rng.normal(size=(n, d)).astype(np.float32)
        mesh = _mesh()
        out = np.asarray(
            ring.ring_attention(
                mesh_lib.shard_rows(jnp.asarray(Q, jnp.bfloat16), mesh),
                mesh_lib.shard_rows(jnp.asarray(Q, jnp.bfloat16), mesh),
                mesh_lib.shard_rows(jnp.asarray(Q, jnp.bfloat16), mesh),
                mesh=mesh,
            ).astype(jnp.float32)
        )
        ref = self._ref(
            np.asarray(jnp.asarray(Q, jnp.bfloat16).astype(jnp.float32)),
            np.asarray(jnp.asarray(Q, jnp.bfloat16).astype(jnp.float32)),
            np.asarray(jnp.asarray(Q, jnp.bfloat16).astype(jnp.float32)),
            False,
        )
        assert np.abs(out - ref).max() < 8e-3  # bf16 ulp at O(1) values

    def test_mixed_dtypes_do_not_crash(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(12)
        n, d = 64, 8
        mesh = _mesh()
        out = ring.ring_attention(
            mesh_lib.shard_rows(jnp.asarray(rng.normal(size=(n, d)), jnp.bfloat16), mesh),
            mesh_lib.shard_rows(jnp.asarray(rng.normal(size=(n, d)), jnp.float32), mesh),
            mesh_lib.shard_rows(jnp.asarray(rng.normal(size=(n, d)), jnp.float32), mesh),
            mesh=mesh,
        )
        assert np.isfinite(np.asarray(out, dtype=np.float32)).all()

    def test_dataset_wrapper_threads_n_valid(self):
        """ring_attention_dataset wires Dataset.n through as n_valid, so a
        mesh-padded Dataset caller cannot silently softmax-weight ghost
        keys (the ADVICE finding's failure mode)."""
        from keystone_tpu.data import Dataset

        rng = np.random.default_rng(13)
        n, d = 500, 8  # pads to 504 over 8 shards
        Q = rng.normal(size=(n, d))
        mesh = _mesh()
        ds = Dataset.of(Q).shard(mesh)
        assert ds.array.shape[0] > n  # actually padded
        out = ring.ring_attention_dataset(ds, mesh=mesh)
        arr = np.asarray(out.array)
        assert out.n == n
        np.testing.assert_allclose(arr[:n], self._ref(Q, Q, Q, False), atol=1e-10)
        np.testing.assert_allclose(arr[n:], 0.0, atol=0)

    def test_dataset_wrapper_rejects_mismatched_counts(self):
        from keystone_tpu.data import Dataset

        rng = np.random.default_rng(14)
        mesh = _mesh()
        q = Dataset.of(rng.normal(size=(16, 4))).shard(mesh)
        k = Dataset.of(rng.normal(size=(24, 4))).shard(mesh)
        with pytest.raises(ValueError, match="matching true row counts"):
            ring.ring_attention_dataset(q, k, mesh=mesh)

    def test_long_sequence_memory_shape(self):
        # 8 shards of 128 rows: per-device score blocks are (128, 128) even
        # though the full matrix would be (1024, 1024).
        rng = np.random.default_rng(9)
        n, d = 1024, 8
        Q = rng.normal(size=(n, d)).astype(np.float32)
        mesh = _mesh()
        out = np.asarray(
            ring.ring_attention(
                mesh_lib.shard_rows(Q, mesh),
                mesh_lib.shard_rows(Q, mesh),
                mesh_lib.shard_rows(Q, mesh),
                mesh=mesh,
                causal=True,
            )
        )
        assert out.shape == (n, d) and np.isfinite(out).all()


class TestCosineFeaturesSharded:
    def test_sharded_batch_apply_uses_pallas_and_matches(self, force_pallas):
        from keystone_tpu.ops.stats import CosineRandomFeatures

        rng = np.random.default_rng(5)
        X = rng.normal(size=(64, 20)).astype(np.float32)
        model = CosineRandomFeatures(20, 32, gamma=0.1, seed=7)
        mesh = _mesh()
        ds = Dataset.of(X).shard(mesh)
        out = np.asarray(model.batch_apply(ds).array)[:64]
        ref = np.cos(X @ np.asarray(model.W).T + np.asarray(model.b))
        np.testing.assert_allclose(out, ref, rtol=0, atol=5e-6)


class TestBlockLSEndToEndOnMesh:
    def test_block_ls_mesh_pallas_matches_unsharded(self, force_pallas):
        from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator

        rng = np.random.default_rng(6)
        n, d, k = 64, 32, 3
        X = rng.normal(size=(n, d)).astype(np.float32)
        Y = rng.normal(size=(n, k)).astype(np.float32)
        mesh = _mesh()

        est = BlockLeastSquaresEstimator(16, 2, lam=1e-3)
        m_sharded = est.fit(Dataset.of(X).shard(mesh), Dataset.of(Y).shard(mesh))
        m_local = est.fit(Dataset.of(X), Dataset.of(Y))

        p_sharded = np.asarray(
            m_sharded.batch_apply(Dataset.of(X).shard(mesh)).array
        )[:n]
        p_local = np.asarray(m_local.batch_apply(Dataset.of(X)).array)
        np.testing.assert_allclose(p_sharded, p_local, rtol=0, atol=5e-4)
