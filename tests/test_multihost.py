"""Multi-host (multi-process) bring-up exercised for real.

Two OS processes join one JAX distributed runtime over localhost (the DCN
analog of the reference's driver/executor bring-up, bin/run-pipeline.sh) and
run a sharded normal-equations solve whose Gramian reduction crosses the
process boundary. Each process forces 2 CPU devices, so the global mesh is
2 hosts × 2 devices = 4 — the smallest topology where `make_hybrid_mesh`'s
ICI-within/DCN-across layout is distinguishable.
"""

import os
import socket
import subprocess
import sys
import textwrap

_WORKER = textwrap.dedent(
    """
    import sys
    import numpy as np
    import jax

    jax.config.update("jax_platforms", "cpu")  # axon overrides JAX_PLATFORMS
    jax.config.update("jax_num_cpu_devices", 2)
    jax.config.update("jax_enable_x64", True)

    coord, pid = sys.argv[1], int(sys.argv[2])

    from keystone_tpu.parallel import linalg
    from keystone_tpu.parallel import mesh as mesh_lib

    mesh_lib.init_distributed(
        coordinator_address=coord, num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, len(jax.devices())

    # data axis across hosts (DCN), model axis within a host (ICI).
    mesh = mesh_lib.make_hybrid_mesh(
        ici_shape=(1, 2), dcn_shape=(2, 1),
        axis_names=(mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS),
    )
    assert dict(mesh.shape) == {"data": 2, "model": 2}, dict(mesh.shape)

    # Deterministic data on every process; rows sharded over `data`.
    rng = np.random.default_rng(0)
    A = rng.normal(size=(32, 6))
    B = rng.normal(size=(32, 3))
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    # Build the global sharded array from per-process local shards (the
    # multi-host ingestion path: each host holds its own rows).
    sharding = NamedSharding(mesh, P("data", None))
    def put(x):
        return jax.make_array_from_process_local_data(sharding, x[pid * 16 : (pid + 1) * 16])
    A_sh, B_sh = put(A), put(B)

    W = linalg.normal_equations_solve(A_sh, B_sh, lam=1e-3)
    W_local = np.linalg.solve(A.T @ A + 1e-3 * np.eye(6), A.T @ B)
    # Replicated solve: every process's copy must equal the local solve.
    np.testing.assert_allclose(
        np.asarray(W.addressable_data(0)), W_local, atol=1e-9
    )
    print(f"proc {pid} OK")
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_distributed_solve(tmp_path):
    coord = f"localhost:{_free_port()}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker configures its own device count
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coord, str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            cwd="/root/repo",
        )
        for pid in range(2)
    ]
    outputs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(out.decode())
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} OK" in out
