"""Multi-host (multi-process) bring-up exercised for real.

Two OS processes join one JAX distributed runtime over localhost (the DCN
analog of the reference's driver/executor bring-up, bin/run-pipeline.sh) and
run a sharded normal-equations solve whose Gramian reduction crosses the
process boundary. Each process forces 2 CPU devices, so the global mesh is
2 hosts × 2 devices = 4 — the smallest topology where `make_hybrid_mesh`'s
ICI-within/DCN-across layout is distinguishable.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow  # golden/e2e/multihost tier

_WORKER = textwrap.dedent(
    """
    import sys
    import numpy as np
    import jax

    jax.config.update("jax_platforms", "cpu")  # axon overrides JAX_PLATFORMS
    jax.config.update("jax_num_cpu_devices", 2)
    jax.config.update("jax_enable_x64", True)

    coord, pid = sys.argv[1], int(sys.argv[2])

    from keystone_tpu.parallel import linalg
    from keystone_tpu.parallel import mesh as mesh_lib

    mesh_lib.init_distributed(
        coordinator_address=coord, num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, len(jax.devices())

    # data axis across hosts (DCN), model axis within a host (ICI).
    mesh = mesh_lib.make_hybrid_mesh(
        ici_shape=(1, 2), dcn_shape=(2, 1),
        axis_names=(mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS),
    )
    assert dict(mesh.shape) == {"data": 2, "model": 2}, dict(mesh.shape)

    # Deterministic data on every process; rows sharded over `data`.
    rng = np.random.default_rng(0)
    A = rng.normal(size=(32, 6))
    B = rng.normal(size=(32, 3))
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    # Build the global sharded array from per-process local shards (the
    # multi-host ingestion path: each host holds its own rows).
    sharding = NamedSharding(mesh, P("data", None))
    def put(x):
        return jax.make_array_from_process_local_data(sharding, x[pid * 16 : (pid + 1) * 16])
    A_sh, B_sh = put(A), put(B)

    W = linalg.normal_equations_solve(A_sh, B_sh, lam=1e-3)
    W_local = np.linalg.solve(A.T @ A + 1e-3 * np.eye(6), A.T @ B)
    # Replicated solve: every process's copy must equal the local solve.
    np.testing.assert_allclose(
        np.asarray(W.addressable_data(0)), W_local, atol=1e-9
    )
    print(f"proc {pid} OK")
    """
)


_LM_WORKER = textwrap.dedent(
    """
    import sys
    import numpy as np
    import jax

    jax.config.update("jax_platforms", "cpu")  # axon overrides JAX_PLATFORMS
    jax.config.update("jax_num_cpu_devices", 2)
    # The packed n-gram ids use up to 62 bits: without x64 the device
    # all_gather would silently truncate them to int32 garbage.
    jax.config.update("jax_enable_x64", True)

    coord, pid = sys.argv[1], int(sys.argv[2])

    from keystone_tpu.parallel import mesh as mesh_lib

    mesh_lib.init_distributed(
        coordinator_address=coord, num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2

    from jax.experimental import multihost_utils

    from keystone_tpu.data import Dataset
    from keystone_tpu.ops.nlp import (
        NGram,
        NGramsFeaturizer,
        StupidBackoffEstimator,
        initial_bigram_partition,
        pack_ngram_pairs,
        partition_ngram_pairs,
        unpack_ngram_pairs,
        ShardedStupidBackoffModel,
    )

    # Deterministic corpus of int word-ids; each process HOLDS only half of
    # the raw (ngram, count) stream (the per-host data slice).
    rng = np.random.default_rng(7)
    sents = [rng.integers(1, 40, size=12).tolist() for _ in range(30)]
    feats = NGramsFeaturizer([2, 3])
    all_pairs = []
    unigrams = {}
    for s in sents:
        for w in s:
            unigrams[w] = unigrams.get(w, 0) + 1
        for g in feats.apply(s):
            all_pairs.append((NGram(g), 1))
    local_pairs = all_pairs[pid::2]

    # Exchange: pack local counts into ONE int64 device array and
    # all_gather across the two processes (counts ride DCN as arrays, not
    # pickled host objects).
    packed = pack_ngram_pairs(local_pairs)
    # Ragged halves: pad to a common length with an invalid row (count 0).
    m = (len(all_pairs) + 1) // 2
    if packed.shape[0] < m:
        pad = np.zeros((m - packed.shape[0], 2), dtype=np.int64)
        packed = np.vstack([packed, pad])
    gathered = multihost_utils.process_allgather(packed)  # (2, m, 2)
    pairs_all = []
    for part in gathered:
        part = part[part[:, 1] > 0]
        pairs_all.extend(unpack_ngram_pairs(part))

    # reduceByKey + InitialBigramPartitioner; this process fits ONLY its
    # own partition (StupidBackoff.scala:152-176 mapPartitions analog).
    parts = partition_ngram_pairs(pairs_all, 2)
    est = StupidBackoffEstimator(unigrams)
    my_model = est.fit(Dataset.of(parts[pid]))

    # Single-host reference fit over the full data: the partition-local
    # scores must EQUAL the global fit's scores on this partition.
    full_model = est.fit(Dataset.of(all_pairs))
    assert len(my_model.scores) == len(parts[pid])
    for ngram, score in my_model.scores.items():
        ref = full_model.scores[ngram]
        assert abs(score - ref) < 1e-12, (ngram, score, ref)

    # Coverage: the two partitions tile the global table exactly.
    sizes = multihost_utils.process_allgather(
        np.array([len(my_model.scores)])
    )
    assert int(sizes.sum()) == len(full_model.scores), (
        sizes, len(full_model.scores)
    )

    # Serving side: a sharded model routing by the partitioner agrees with
    # the single-host model on every observed ngram.
    shards = [est.fit(Dataset.of(p)) for p in parts]
    sharded = ShardedStupidBackoffModel(shards)
    for ngram in list(full_model.scores)[:50]:
        assert abs(sharded.score(ngram) - full_model.score(ngram)) < 1e-12

    print(f"lm proc {pid} OK: partition size {len(my_model.scores)}")
    """
)


_KRR_WORKER = textwrap.dedent(
    """
    import sys
    import numpy as np
    import jax

    jax.config.update("jax_platforms", "cpu")  # axon overrides JAX_PLATFORMS
    jax.config.update("jax_num_cpu_devices", 2)

    coord, pid = sys.argv[1], int(sys.argv[2])

    from keystone_tpu.parallel import mesh as mesh_lib

    mesh_lib.init_distributed(
        coordinator_address=coord, num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.data import Dataset
    from keystone_tpu.ops.learning.kernel import (
        GaussianKernelGenerator,
        KernelRidgeRegression,
        _krr_fit_fused,
    )

    # data axis spans 2 hosts x 2 devices: the fused shard_map sweep's
    # all_gather(X) and psum(residual) must cross the process (DCN)
    # boundary, not just ICI.
    mesh = mesh_lib.make_hybrid_mesh(
        ici_shape=(2,), dcn_shape=(2,), axis_names=(mesh_lib.DATA_AXIS,)
    )
    assert dict(mesh.shape) == {"data": 4}

    n, d, k, bs, epochs = 256, 8, 3, 64, 2
    rng = np.random.default_rng(3)
    X = rng.normal(size=(n, d)).astype(np.float32)
    Y = rng.normal(size=(n, k)).astype(np.float32)

    sharding = NamedSharding(mesh, P("data", None))
    def put(x):
        return jax.make_array_from_process_local_data(
            sharding, x[pid * (n // 2) : (pid + 1) * (n // 2)]
        )

    data = Dataset(put(X), n=n, mesh=mesh)
    labels = Dataset(put(Y), n=n, mesh=mesh)
    krr = KernelRidgeRegression(GaussianKernelGenerator(0.05), 0.2, bs, epochs)
    model = krr.fit(data, labels)

    # Reference: the single-device fused sweep on the full local copy.
    order = jnp.asarray(np.tile(np.arange(n // bs, dtype=np.int32), epochs))
    _, ref_stack = _krr_fit_fused(
        jnp.asarray(X), jnp.asarray(Y), order, 0.05, 0.2, bs, n, n // bs,
        False,
    )
    for b in range(n // bs):
        got = np.asarray(model.w_locals[b].addressable_data(0))
        want = np.asarray(ref_stack[b])
        np.testing.assert_allclose(got, want, atol=2e-4)
    print(f"krr proc {pid} OK: {n // bs} blocks match single-device fit")
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_two_workers(tmp_path, source: str, ok_marker: str):
    coord = f"localhost:{_free_port()}"
    script = tmp_path / "worker.py"
    script.write_text(source)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker configures its own device count
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coord, str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            cwd="/root/repo",
        )
        for pid in range(2)
    ]
    outputs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(out.decode())
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert ok_marker.format(pid=pid) in out


def test_two_process_distributed_solve(tmp_path):
    _run_two_workers(tmp_path, _WORKER, "proc {pid} OK")


def test_two_process_stupid_backoff_counts(tmp_path):
    """The LM count/score tables shard by initial_bigram_partition across
    two OS processes: counts exchanged as packed int64 device arrays, each
    process fits only its partition, scores equal the single-host fit, the
    partitions tile the table, and the sharded model serves correctly."""
    _run_two_workers(tmp_path, _LM_WORKER, "lm proc {pid} OK")


def test_two_process_fused_krr_fit(tmp_path):
    """The fused KRR shard_map sweep runs with its data axis spanning two
    OS processes (all_gather + psum over the DCN boundary) and matches the
    single-device fused fit block for block."""
    _run_two_workers(tmp_path, _KRR_WORKER, "krr proc {pid} OK")
