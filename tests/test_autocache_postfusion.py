"""Fusion-aware autocache: cache placement on the post-fusion plan.

Round 5 measured the pre-fusion world model failing: whole-chain fusion
made recompute nearly free while inserted Cachers broke the fused program,
so greedy LOST to no-cache on the reuse bench. These tests pin the round-6
contract:

  - AutoCacheRule DECLINES to insert a Cacher inside a region the fusion
    rules would compile into one program (chain links, estimator featurize
    inputs), whatever the phase order;
  - it STILL caches fused-stage boundaries: multi-consumer intermediates
    and host-loader/decode stages;
  - AutoCachingOptimizer runs cache placement after fusion, so a fully
    device-fusable chain stays ONE fused program under the caching
    optimizer, and a cached host boundary is served from the prefix state
    table on later fits (the cross-fit reuse that makes caching win);
  - the executor records observed (full-scale, post-fusion) profiles that
    greedy prefers over sampled extrapolation.
"""

import numpy as np
import pytest

from keystone_tpu.data import Dataset
from keystone_tpu.ops.util import Cacher
from keystone_tpu.workflow import Estimator, PipelineEnv, Transformer
from keystone_tpu.workflow.autocache import (
    AggressiveCache,
    AutoCacheRule,
    GreedyCache,
    clear_observed_profiles,
    get_observed_profile,
    observed_profile_key,
)
from keystone_tpu.workflow.executor import GraphExecutor
from keystone_tpu.workflow.fusion import (
    cache_would_split_fusion,
    fused_members,
    fusion_splitting_nodes,
)
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.operators import DatasetOperator
from keystone_tpu.workflow.optimizer import AutoCachingOptimizer


class DeviceScale(Transformer):
    """Row-local device-pure transformer (participates in stage fusion)."""

    def __init__(self, c: float, weight: int = 1):
        self.c = float(c)
        self.weight = weight

    def device_fn(self):
        c = self.c
        return lambda X: X * c

    def apply(self, x):
        return x * self.c


class HostDecode(Transformer):
    """Host-side stage: NOT device-fusable; counts batch executions."""

    def __init__(self, weight: int = 1):
        self.weight = weight
        self.batch_ns = []  # (n,) per batch_apply call

    def apply(self, x):
        return np.sqrt(np.abs(np.asarray(x))).astype(np.float32)

    def batch_apply(self, data: Dataset) -> Dataset:
        self.batch_ns.append(data.n)
        X = np.asarray(data.array)
        return Dataset.of(np.sqrt(np.abs(X)).astype(np.float32))


class WeightedSumEstimator(Estimator):
    """Plain (non-traceable) fit making ``weight`` passes over its input."""

    weight = 4

    def fit(self, data: Dataset) -> Transformer:
        total = float(np.sum(np.asarray(data.array)))
        return DeviceScale(1.0 + 0.0 * total)


def _cachers(graph: Graph):
    return [n for n in graph.nodes if isinstance(graph.get_operator(n), Cacher)]


class TestFusionPreservingPlacement:
    """AutoCacheRule never splits a fusable region, whatever the order."""

    def _chain_graph(self):
        ds = Dataset.of(np.arange(32.0, dtype=np.float32).reshape(8, 4))
        g = Graph()
        g, d = g.add_node(DatasetOperator(ds), [])
        g, a = g.add_node(DeviceScale(2.0), [d])
        g, b = g.add_node(DeviceScale(3.0, weight=4), [a])
        g, sink = g.add_sink(b)
        return g, d, a, b

    def test_aggressive_declines_cacher_inside_fusable_chain(self):
        # a's only consumer b is weight-4 (4 weighted accesses) — the
        # pre-fusion rule would cache a, severing the a->b chain edge
        # StageFusionRule compiles into one program.
        g, d, a, b = self._chain_graph()
        assert cache_would_split_fusion(g, a, {})
        new_graph, _ = AutoCacheRule(AggressiveCache()).apply(g, {})
        assert not _cachers(new_graph)

    def test_greedy_declines_and_skips_profiling_inside_chain(self, monkeypatch):
        from keystone_tpu.workflow import autocache

        calls = []
        monkeypatch.setattr(
            autocache,
            "profile_nodes",
            lambda *a, **k: calls.append(a) or {},
        )
        g, d, a, b = self._chain_graph()
        rule = AutoCacheRule(GreedyCache(max_mem_bytes=1 << 30))
        new_graph, _ = rule.apply(g, {})
        # No Cacher inside the fusable region (after a); the raw dataset
        # node d is a boundary and may legitimately be cached.
        for c in _cachers(new_graph):
            assert new_graph.get_dependencies(c) != (a,)
        # The chain-interior node is not even profiled: its recompute is
        # absorbed by the fused program, so sampling it would price a plan
        # that never runs.
        for (graph_arg, nodes, *_rest) in calls:
            assert a not in nodes

    def test_declines_cacher_on_estimator_featurize_input(self):
        # f's single consumer is a traceable fit: EstimatorFusionRule
        # would absorb f INTO the fit program — caching f splits it.
        class TraceableFit(Estimator):
            weight = 4
            streamed_fit_fusable = True

            def fit(self, data):
                return DeviceScale(1.0)

        ds = Dataset.of(np.ones((8, 4), np.float32))
        lab = Dataset.of(np.ones((8, 2), np.float32))
        g = Graph()
        g, d = g.add_node(DatasetOperator(ds), [])
        g, dl = g.add_node(DatasetOperator(lab), [])
        g, f = g.add_node(DeviceScale(2.0), [d])
        g, est = g.add_node(TraceableFit(), [f, dl])
        g, sink = g.add_sink(est)
        assert cache_would_split_fusion(g, f, {})
        new_graph, _ = AutoCacheRule(AggressiveCache()).apply(g, {})
        # No Cacher on the featurize input (the labels input dl is a
        # boundary the weight-4 fit legitimately caches).
        for c in _cachers(new_graph):
            assert new_graph.get_dependencies(c) != (f,)

    def test_still_caches_multi_consumer_boundary(self):
        # a feeds TWO branches: it is a materialization point of the fused
        # plan (chains never fuse across multi-consumer nodes), so the
        # cache lands.
        ds = Dataset.of(np.arange(32.0, dtype=np.float32).reshape(8, 4))
        g = Graph()
        g, d = g.add_node(DatasetOperator(ds), [])
        g, a = g.add_node(DeviceScale(2.0), [d])
        g, b = g.add_node(DeviceScale(3.0, weight=3), [a])
        g, c = g.add_node(DeviceScale(4.0, weight=3), [a])
        g, s1 = g.add_sink(b)
        g, s2 = g.add_sink(c)
        assert not cache_would_split_fusion(g, a, {})
        new_graph, _ = AutoCacheRule(AggressiveCache()).apply(g, {})
        cachers = _cachers(new_graph)
        assert len(cachers) == 1
        assert new_graph.get_dependencies(cachers[0]) == (a,)

    def test_still_caches_host_loader_boundary(self):
        # A host decode is not device-fusable: fusion cannot absorb it, so
        # its recompute cost is real and the cache lands right after it.
        ds = Dataset.of(np.arange(32.0, dtype=np.float32).reshape(8, 4))
        g = Graph()
        g, d = g.add_node(DatasetOperator(ds), [])
        g, h = g.add_node(HostDecode(), [d])
        g, b = g.add_node(DeviceScale(3.0, weight=4), [h])
        g, sink = g.add_sink(b)
        assert not cache_would_split_fusion(g, h, {})
        assert h not in fusion_splitting_nodes(g, {})
        new_graph, _ = AutoCacheRule(AggressiveCache()).apply(g, {})
        cachers = _cachers(new_graph)
        assert len(cachers) == 1
        assert new_graph.get_dependencies(cachers[0]) == (h,)


class TestPostFusionPhaseOrder:
    def test_greedy_keeps_whole_chain_fused(self):
        """Under AutoCachingOptimizer the device-pure chain compiles into
        ONE fused program — no Cacher lands inside it (round 5's measured
        defect: pre-fusion placement split the chain into per-stage
        dispatches)."""
        env = PipelineEnv.get_or_create()
        env.reset()
        clear_observed_profiles()
        env.set_optimizer(AutoCachingOptimizer(GreedyCache(max_mem_bytes=1 << 30)))
        try:
            f1, f2, f3 = DeviceScale(2.0), DeviceScale(0.5), DeviceScale(3.0)
            est = WeightedSumEstimator()
            X = np.arange(64.0, dtype=np.float32).reshape(16, 4)
            data = Dataset.of(X)
            pipe = (
                f1.to_pipeline().and_then(f2).and_then(f3).and_then(est, data)
            )
            res = pipe.apply(Dataset.of(X[:4]))
            out = np.asarray(res.get().to_numpy())
            g = res.executor.optimized_graph
            fused_ops = [
                g.get_operator(n)
                for n in g.nodes
                if str(getattr(g.get_operator(n), "label", "")).startswith("Fused[")
            ]
            # The full 3-stage chain fused as one program (train side and
            # apply side each collapse; membership query sees all stages).
            assert fused_ops, [
                getattr(g.get_operator(n), "label", "") for n in g.nodes
            ]
            assert any(len(fused_members(op)) == 3 for op in fused_ops)
            # Any Cacher sits at a boundary, never between fused members:
            # its dependency must not be a node the fusion rules would
            # chain through.
            for c in _cachers(g):
                (dep,) = g.get_dependencies(c)
                assert not cache_would_split_fusion(g, dep, {})
            np.testing.assert_allclose(out, X[:4] * 3.0, rtol=1e-5)
        finally:
            env.reset()

    def test_host_boundary_cached_and_reused_across_fits(self):
        """The cross-fit win caching still owns post-fusion: a host decode
        executes at FULL scale once; later fits load the published cache
        from the prefix state table instead of recomputing the stage."""
        env = PipelineEnv.get_or_create()
        env.reset()
        clear_observed_profiles()
        env.set_optimizer(AutoCachingOptimizer(GreedyCache(max_mem_bytes=1 << 30)))
        try:
            host = HostDecode()
            f = DeviceScale(2.0)
            n_full = 64
            X = np.abs(
                np.random.default_rng(0).normal(size=(n_full, 4))
            ).astype(np.float32)
            data = Dataset.of(X)
            for _ in range(3):  # a sweep refitting the same prefix
                est = WeightedSumEstimator()  # fresh fit per iteration
                pipe = host.to_pipeline().and_then(f).and_then(est, data)
                out = pipe.apply(Dataset.of(X[:4]))
                np.asarray(out.get().to_numpy())
            full_runs = [n for n in host.batch_ns if n == n_full]
            assert len(full_runs) == 1, host.batch_ns
        finally:
            env.reset()

    def test_pre_fusion_order_still_available_for_ab(self):
        post = AutoCachingOptimizer(GreedyCache())
        pre = AutoCachingOptimizer(GreedyCache(), cache_before_fusion=True)
        post_names = [b.name for b in post.batches]
        pre_names = [b.name for b in pre.batches]
        assert post_names.index("Auto Cache (post-fusion)") > post_names.index(
            "Tree & Fit Fusion"
        )
        assert pre_names.index("Auto Cache") < pre_names.index("Stage Fusion")


class TestObservedProfiles:
    def test_executor_records_full_scale_profiles(self):
        clear_observed_profiles()
        ds = Dataset.of(np.ones((8, 4), np.float32))
        g = Graph()
        g, d = g.add_node(DatasetOperator(ds), [])
        g, h = g.add_node(HostDecode(), [d])
        g, sink = g.add_sink(h)
        ex = GraphExecutor(g, optimize=False)
        ex.execute(sink).get()
        key = observed_profile_key(g, h)
        prof = get_observed_profile(key)
        assert prof is not None and prof.ns > 0
        assert prof.mem_bytes > 0

    def test_greedy_prefers_observed_over_sampling(self, monkeypatch):
        from keystone_tpu.workflow import autocache

        clear_observed_profiles()
        ds = Dataset.of(np.ones((8, 4), np.float32))
        g = Graph()
        g, d = g.add_node(DatasetOperator(ds), [])
        g, h = g.add_node(HostDecode(), [d])
        g, b = g.add_node(DeviceScale(1.0, weight=4), [h])
        g, sink = g.add_sink(b)
        # Real execution first: full-scale profiles land in the table.
        ex = GraphExecutor(g, optimize=False)
        ex.execute(sink).get()
        sampled = []
        monkeypatch.setattr(
            autocache,
            "profile_nodes",
            lambda graph, nodes, *a, **k: sampled.append(set(nodes)) or {},
        )
        rule = AutoCacheRule(GreedyCache(max_mem_bytes=1 << 30))
        rule.apply(g, {})
        # Every candidate (d and h) was observed by the executor — greedy
        # pays zero sampled profiling passes.
        assert not sampled or all(
            h not in nodes and d not in nodes for nodes in sampled
        )
