"""Full VOCSIFTFisher end-to-end on the reference's real committed archive:
load voctest.tar (real JPEG decode) → SIFT → PCA → GMM Fisher vectors →
block least squares → mean average precision.

This is the best offline-feasible real-data integration of the whole image
stack (VOCSIFTFisher.scala:23-105 composition; VOCLoaderSuite fixtures).
With train == test == the 10 committed images, a correct pipeline must rank
its own training images perfectly for every class that appears in the data:
9 distinct classes → 9 APs of 1.0 → MAP = 9/20 = 0.45 (absent classes
score AP 0 by the evaluator's convention, matching the reference's
MeanAveragePrecisionEvaluator on empty actuals).
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # golden/e2e/multihost tier


from _reference import RESOURCES, needs_reference_fixtures

IMAGES = os.path.join(RESOURCES, "images")


@needs_reference_fixtures
def test_voc_sift_fisher_on_real_archive():
    if not os.path.exists(os.path.join(IMAGES, "voc/voctest.tar")):
        pytest.skip("voctest.tar not available")

    from keystone_tpu.pipelines.voc_sift_fisher import VOCConfig, run

    cfg = VOCConfig(
        train_location=os.path.join(IMAGES, "voc"),
        train_labels=os.path.join(IMAGES, "voclabels.csv"),
        test_location=os.path.join(IMAGES, "voc"),
        test_labels=os.path.join(IMAGES, "voclabels.csv"),
        # Mini config: enough capacity to separate 10 images, small enough
        # to run in CI (full reference config: descDim=80, vocab=64).
        descriptor_dim=32,
        vocab_size=4,
        sift_scale_step=2,
        lam=0.5,
    )
    _, aps, mean_ap = run(cfg)
    aps = np.asarray(aps)

    assert aps.shape == (20,)
    # The 9 classes present among the 10 images must all rank (near-)
    # perfectly on their own training data; absent classes score 0.
    assert (aps > 0.99).sum() >= 8
    assert mean_ap >= 0.4
