"""Unit coverage of the obs plane (ISSUE 9): tracer semantics, the
zero-cost-when-disabled contract, the metrics registry, Chrome-trace
export + schema validation, and the flight recorder."""

import json
import threading
import time

import numpy as np
import pytest

from keystone_tpu import obs
from keystone_tpu.obs import tracer as tracer_mod
from keystone_tpu.obs.metrics import (
    METRIC_RUNTIME_LANE_TASKS,
    METRIC_SERVING_LATENCY_S,
)


@pytest.fixture(autouse=True)
def _no_leaked_tracer():
    """A test that dies inside obs.tracing must not leave the process
    tracer active for the rest of the suite."""
    yield
    tracer_mod._ACTIVE = None


class TestTracerSpans:
    def test_nesting_and_parent_links(self):
        with obs.tracing() as t:
            with obs.span("outer", a=1):
                with obs.span("inner"):
                    pass
            with obs.span("sibling"):
                pass
        outer = t.spans("outer")[0]
        inner = t.spans("inner")[0]
        sibling = t.spans("sibling")[0]
        assert inner["parent_id"] == outer["span_id"]
        assert outer["parent_id"] is None
        assert sibling["parent_id"] is None
        assert outer["args"] == {"a": 1}

    def test_one_run_id_stamps_every_record(self):
        with obs.tracing() as t:
            with obs.span("s"):
                pass
            obs.event("e", x=1)
            obs.counter_track("c", 2.0)
        assert {r["run_id"] for r in t.events if "run_id" in r} == {
            t.run_id
        }

    def test_thread_spans_record_own_thread_and_no_cross_parent(self):
        with obs.tracing() as t:
            with obs.span("main.outer"):
                th = threading.Thread(
                    target=lambda: obs.span("worker.task").__enter__()
                    .__exit__(None, None, None),
                    name="obs-test-worker",
                )
                th.start()
                th.join()
        worker = t.spans("worker.task")[0]
        assert worker["thread"] == "obs-test-worker"
        # A worker-thread span does NOT parent onto another thread's
        # open span — nesting is per thread.
        assert worker["parent_id"] is None

    def test_span_set_and_error_capture(self):
        with obs.tracing() as t:
            with pytest.raises(ValueError):
                with obs.span("failing") as sp:
                    sp.set(extra=7)
                    raise ValueError("boom")
        rec = t.spans("failing")[0]
        assert rec["args"]["extra"] == 7
        assert "ValueError: boom" in rec["error"]

    def test_inflight_names_open_spans(self):
        with obs.tracing() as t:
            with obs.span("held"):
                names = [s["name"] for s in t.inflight()]
                assert names == ["held"]
            assert t.inflight() == []

    def test_add_span_retroactive(self):
        with obs.tracing() as t:
            t0 = time.perf_counter()
            t.add_span("served", t0, t0 + 0.25, bucket=4)
        rec = t.spans("served")[0]
        assert 240_000 <= rec["dur_us"] <= 260_000
        assert rec["args"]["bucket"] == 4

    def test_bounded_records_roll_off_oldest_and_count(self):
        # A traced long-lived process (serve under load) must not grow
        # memory without bound: at capacity the OLDEST records roll off
        # and the drop is counted, never silent.
        t = tracer_mod.Tracer(max_records=4)
        tracer_mod._ACTIVE = t
        try:
            for i in range(7):
                with obs.span(f"s{i}"):
                    pass
        finally:
            tracer_mod._ACTIVE = None
        names = [r["name"] for r in t.events]
        assert names == ["s3", "s4", "s5", "s6"]
        assert t.dropped == 3

    def test_nested_activation_raises(self):
        with obs.tracing():
            with pytest.raises(RuntimeError, match="already active"):
                with obs.tracing():
                    pass

    def test_tracing_from_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KEYSTONE_TRACE", str(tmp_path / "tr"))
        with obs.tracing_from_env():
            with obs.span("env.span"):
                pass
        events = obs.load_events(str(tmp_path / "tr"))
        assert [e["name"] for e in events] == ["env.span"]
        monkeypatch.delenv("KEYSTONE_TRACE")
        with obs.tracing_from_env():
            assert not obs.enabled()  # unset env -> no-op context


class TestDisabledIsFree:
    def test_disabled_span_is_the_shared_noop(self):
        # One branch, one shared object: the disabled hook allocates no
        # span, no timestamps, takes no lock.
        assert not obs.enabled()
        assert obs.span("a") is obs.span("b", attr=1)
        obs.event("nothing")   # no tracer: swallowed
        obs.counter_track("nothing", 1.0)
        assert obs.active_tracer() is None

    def test_disabled_hook_cost_is_sub_microsecond_scale(self):
        """The streamed-fold regression leg: a fold step's hook budget.
        The disabled path must cost so little per call that the fold
        loop (ms-scale dispatches) cannot measure it. Bound generously
        for a noisy CI box — the contract is 'no measurable overhead',
        pinned here as a per-hook ceiling of 20µs min-of-5 over 20k
        calls (two orders of magnitude below one fold dispatch)."""
        assert not obs.enabled()

        def hooked_loop(reps):
            t0 = time.perf_counter()
            for i in range(reps):
                with obs.span("fold.segment", chunk0=i):
                    pass
            return time.perf_counter() - t0

        best = min(hooked_loop(20_000) for _ in range(5))
        assert best / 20_000 < 20e-6, f"{best / 20_000 * 1e6:.2f}us/hook"

    def test_disabled_fold_matches_hookless_fold(self):
        """Tracing OFF adds no measurable overhead to the streamed-fold
        regression path: the same tiny segment fold with the obs hooks
        live (disabled) vs monkey-bypassed entirely, min-of-5 each,
        within generous CI noise tolerance."""
        from keystone_tpu.data.prefetch import (
            PrefetchStats,
            ResidentDenseSource,
        )
        from keystone_tpu.ops.learning.streaming_ls import (
            CosineBankFeaturize,
        )
        from keystone_tpu.parallel import streaming

        rng = np.random.default_rng(0)
        n, d_in, d_feat, k = 2048, 16, 128, 3
        X = rng.normal(size=(n, d_in)).astype(np.float32)
        Y = rng.normal(size=(n, k)).astype(np.float32)
        src = ResidentDenseSource(X, Y, tile_rows=256, tiles_per_segment=2)
        bank = CosineBankFeaturize(
            rng.normal(size=(d_feat, d_in)).astype(np.float32) * 0.3,
            rng.uniform(0, 6, d_feat).astype(np.float32),
        )

        def fit():
            W, _, _, loss = streaming.streaming_bcd_fit_segments(
                src, bank=bank, d_feat=d_feat, block_size=32, lam=1e-3,
                num_iter=1, center=False, prefetch_depth=0,
                prefetch_stats=PrefetchStats(),
            )
            return float(loss)

        fit()  # compile + warm
        with_hooks = min(self._timed(fit) for _ in range(5))
        # Bypass every hook: span() returns the noop without even the
        # one branch — "a build without the hooks". Patch the PACKAGE
        # attribute (`obs.span`), because that is what every
        # instrumented seam resolves at call time (`from keystone_tpu
        # import obs; obs.span(...)`) — patching tracer_mod.span would
        # leave the hooks live and compare two identical runs.
        real_span = obs.span
        assert obs.span is tracer_mod.span  # the seam we bypass below
        try:
            obs.span = lambda *a, **kw: tracer_mod._NOOP
            fit()
            without = min(self._timed(fit) for _ in range(5))
        finally:
            obs.span = real_span
        # Generous bound: CI wall noise on a ~10ms fit dwarfs the ns of
        # branch cost; the assertion exists to catch an accidental
        # always-on allocation (which shows up as 2x+, not 1.5x).
        assert with_hooks < without * 1.5 + 0.01, (with_hooks, without)

    @staticmethod
    def _timed(fn):
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0


class TestMetricsRegistry:
    def test_counter_gauge_histogram_snapshot(self):
        r = obs.MetricsRegistry()
        c = r.counter(METRIC_RUNTIME_LANE_TASKS, site="read")
        c.add(2)
        c.add()
        r.gauge("runtime.lane.queued", site="read").set(5)
        h = r.histogram(METRIC_SERVING_LATENCY_S)
        for v in (0.1, 0.2, 0.3):
            h.observe(v)
        snap = r.snapshot()
        assert snap["runtime.lane.tasks{site=read}"] == 3.0
        assert snap["runtime.lane.queued{site=read}"] == 5.0
        assert snap["serving.latency_s.count"] == 3
        assert snap["serving.latency_s.p50"] == pytest.approx(0.2)

    def test_get_or_create_is_lookup(self):
        r = obs.MetricsRegistry()
        assert r.counter("prefetch.retries") is r.counter("prefetch.retries")
        assert r.counter("prefetch.retries", site="a") is not r.counter(
            "prefetch.retries", site="b"
        )

    def test_type_conflict_raises(self):
        r = obs.MetricsRegistry()
        r.counter("prefetch.retries")
        with pytest.raises(TypeError, match="already registered"):
            r.gauge("prefetch.retries")

    def test_values_by_label(self):
        r = obs.MetricsRegistry()
        r.counter("overlap.site_busy_s", site="read").add(1.5)
        r.counter("overlap.site_busy_s", site="compute").add(2.5)
        assert r.values_by_label("overlap.site_busy_s", "site") == {
            "read": 1.5, "compute": 2.5,
        }

    def test_histogram_edges(self):
        h = obs.MetricsRegistry().histogram("serving.latency_s")
        assert h.percentile(99.0) is None  # empty -> None, no warning
        h.observe(0.7)
        assert h.percentile(50.0) == 0.7  # single sample IS every pct
        assert h.percentile(99.0) == 0.7
        with pytest.raises(ValueError, match=r"\[0, 100\]"):
            h.percentile(101.0)

    def test_thread_safety_smoke(self):
        r = obs.MetricsRegistry()

        def work():
            for _ in range(1000):
                r.counter("prefetch.retries").add(1)

        threads = [threading.Thread(target=work) for _ in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert r.snapshot()["prefetch.retries"] == 4000.0


class TestChromeTraceExport:
    def _traced(self):
        with obs.tracing() as t:
            with obs.span("outer"):
                with obs.span("inner", k=1):
                    pass
            obs.event("cost.decision", winner="x")
            obs.counter_track("runtime.read.queued", 2)
        return t

    def test_valid_document(self):
        t = self._traced()
        doc = obs.to_chrome_trace(t.events)
        assert obs.validate_chrome_trace(doc) == []
        phases = {e["ph"] for e in doc["traceEvents"]}
        assert {"M", "X", "i", "C"} <= phases
        assert doc["otherData"]["run_id"] == t.run_id

    def test_span_event_carries_ids_and_args(self):
        t = self._traced()
        doc = obs.to_chrome_trace(t.events)
        inner = [e for e in doc["traceEvents"]
                 if e.get("name") == "inner"][0]
        assert inner["args"]["k"] == 1
        assert inner["args"]["run_id"] == t.run_id
        assert "parent_id" in inner["args"]

    def test_validator_rejects_malformed(self):
        assert obs.validate_chrome_trace([]) != []
        assert obs.validate_chrome_trace({"traceEvents": "nope"}) != []
        bad_phase = {"traceEvents": [
            {"name": "x", "ph": "Z", "pid": 1, "tid": 1, "ts": 0}
        ]}
        assert any("phase" in v for v in
                   obs.validate_chrome_trace(bad_phase))
        no_dur = {"traceEvents": [
            {"name": "x", "ph": "X", "pid": 1, "tid": 1, "ts": 0}
        ]}
        assert any("dur" in v for v in obs.validate_chrome_trace(no_dur))
        bad_counter = {"traceEvents": [
            {"name": "c", "ph": "C", "pid": 1, "tid": 0, "ts": 0,
             "args": {"value": "high"}}
        ]}
        assert any("numeric" in v for v in
                   obs.validate_chrome_trace(bad_counter))

    def test_write_and_load_roundtrip(self, tmp_path):
        d = str(tmp_path / "trace")
        with obs.tracing(d) as t:
            with obs.span("s", n=3):
                pass
        events = obs.load_events(d)
        assert [e["name"] for e in events] == ["s"]
        assert events[0]["run_id"] == t.run_id
        doc = json.loads((tmp_path / "trace" / "trace.json").read_text())
        assert obs.validate_chrome_trace(doc) == []
        meta = json.loads((tmp_path / "trace" / "meta.json").read_text())
        assert meta["run_id"] == t.run_id
        assert meta["counts"]["span"] == 1


class TestCostDecisionEvents:
    def test_recorded_under_tracing(self):
        with obs.tracing() as t:
            obs.record_cost_decision(obs.CostDecision(
                decision="least_squares_solver",
                winner="BlockLeastSquaresEstimator",
                candidates=[
                    {"label": "BlockLeastSquaresEstimator",
                     "cost_s": 0.3, "feasible": True},
                    {"label": "DenseLBFGSwithL2", "cost_s": 2.0,
                     "feasible": True},
                ],
                reason="argmin",
                context={"n": 10, "d": 4},
            ))
        evs = [e for e in t.events if e["name"] == "cost.decision"]
        assert len(evs) == 1
        args = evs[0]["args"]
        assert args["winner"] == "BlockLeastSquaresEstimator"
        assert args["n"] == 10
        assert len(args["candidates"]) == 2

    def test_noop_when_disabled(self):
        obs.record_cost_decision(obs.CostDecision(
            decision="d", winner="w", candidates=[],
        ))  # must not raise with no tracer active


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        fr = obs.FlightRecorder(maxlen=4)
        for i in range(10):
            fr.note("fault", f"site{i}")
        names = [r["name"] for r in fr.snapshot()]
        assert names == ["site6", "site7", "site8", "site9"]

    def test_render_includes_inflight_spans(self):
        with obs.tracing():
            with obs.span("long.running"):
                rendered = obs.render_flight_record()
                assert "IN FLIGHT: long.running" in rendered

    def test_dump_logs_and_returns_block(self, caplog):
        import logging

        obs.flight_note("fault", "unit.test", detail="x")
        with caplog.at_level(logging.WARNING,
                             logger="keystone_tpu.obs.flight"):
            block = obs.flight.dump_flight_record(
                "unit-test death", ValueError("boom")
            )
        assert "unit.test" in block
        assert any("unit-test death" in r.message for r in caplog.records)

    def test_shard_corruption_dumps_flight_record(self, caplog):
        import logging

        from keystone_tpu.data.durable import ShardCorrupted, verify_array

        with caplog.at_level(logging.WARNING,
                             logger="keystone_tpu.obs.flight"):
            with pytest.raises(ShardCorrupted):
                verify_array(np.zeros(4, np.float32), expected=1,
                             algo="crc32", what="tile 3")
        assert any("ShardCorrupted" in r.message for r in caplog.records)

    def test_shard_corrupted_construction_is_pure(self, caplog):
        # Re-wrapping / unpickling a ShardCorrupted must NOT fire a
        # second postmortem dump — only the raise-site factory dumps.
        import logging

        from keystone_tpu.data.durable import ShardCorrupted

        with caplog.at_level(logging.WARNING,
                             logger="keystone_tpu.obs.flight"):
            ShardCorrupted("constructed, not raised")
        assert not caplog.records

    def test_worker_death_dumps_flight_record(self, caplog):
        import logging

        from keystone_tpu.serving.batcher import MicroBatchServer
        from keystone_tpu.serving.export import export_plan
        from tests._serving_util import fitted_from_transformer
        from keystone_tpu.workflow import Transformer

        class Scale3(Transformer):
            def apply(self, x):
                import jax.numpy as jnp

                return jnp.asarray(x) * 3.0

        plan = export_plan(
            fitted_from_transformer(Scale3()), np.zeros(4, np.float32),
            max_batch=8,
        )
        srv = MicroBatchServer(plan, max_wait_ms=1.0)
        try:
            with caplog.at_level(logging.WARNING,
                                 logger="keystone_tpu.obs.flight"):
                # Kill the worker loop itself (not a plan error): poison
                # _take_batch so the NEXT loop pass raises outside
                # _execute. The first submit may still be served by the
                # in-flight _take_batch call; the death lands right
                # after it.
                srv._take_batch = None  # worker loop TypeErrors
                srv.submit(np.zeros(4, np.float32))
                srv._thread.join(timeout=5.0)
                assert srv._worker_dead
        finally:
            srv.close()
        assert any("worker thread died" in r.message
                   for r in caplog.records)
