"""Pipeline semantics tests (contract from reference PipelineSuite.scala:28-520):
chaining, estimators fit exactly once, prefix state reuse across applications,
gather, fit() producing transformer-only serializable pipelines.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu import Dataset, Pipeline, PipelineEnv, Transformer
from keystone_tpu.workflow import Estimator, Identity, LabelEstimator, transformer
from keystone_tpu.ops.util import Cacher


class Double(Transformer):
    def apply(self, x):
        return x * 2


class AddOne(Transformer):
    def apply(self, x):
        return x + 1


class AddConst(Transformer):
    def __init__(self, c):
        self.c = float(c)

    def apply(self, x):
        return x + self.c


class CountingEstimator(Estimator):
    """Estimator that counts fits and produces a transformer adding the dataset mean."""

    def __init__(self):
        self.fit_count = 0

    def fit(self, data: Dataset):
        self.fit_count += 1
        return AddConst(jnp.mean(data.array[: data.n]))


class CountingLabelEstimator(LabelEstimator):
    def __init__(self):
        self.fit_count = 0

    def fit(self, data: Dataset, labels: Dataset):
        self.fit_count += 1
        shift = jnp.mean(data.array[: data.n]) + jnp.mean(labels.array[: labels.n])

        class Shift(Transformer):
            def apply(self, x, _s=shift):
                return x + _s

        return Shift()


def dataset(values):
    return Dataset.of(np.asarray(values, dtype=np.float64))


class TestChaining:
    def test_transformer_chain_datum(self):
        pipe = Double().and_then(AddOne())
        assert float(pipe.apply(3.0).get()) == 7.0

    def test_transformer_chain_dataset(self):
        pipe = Double().and_then(AddOne())
        out = pipe.apply(dataset([1.0, 2.0, 3.0])).get()
        np.testing.assert_allclose(out.to_numpy(), [3.0, 5.0, 7.0])

    def test_or_sugar(self):
        pipe = Double() | AddOne() | Double()
        assert float(pipe.apply(1.0).get()) == 6.0

    def test_identity(self):
        pipe = Identity().and_then(Double())
        assert float(pipe.apply(2.0).get()) == 4.0

    def test_result_memoized(self):
        calls = []

        class Tracking(Transformer):
            def apply(self, x):
                calls.append(x)
                return x

        pipe = Tracking().to_pipeline()
        res = pipe.apply(1.0)
        res.get()
        res.get()
        assert len(calls) == 1


class TestEstimators:
    def test_estimator_fit_and_apply(self):
        est = CountingEstimator()
        data = dataset([0.0, 2.0, 4.0])  # mean 2
        pipe = Double().and_then(est, data)
        # train data passes through Double -> mean 4
        assert float(pipe.apply(1.0).get()) == pytest.approx(6.0)  # 1*2 + 4

    def test_estimator_fits_only_once(self):
        est = CountingEstimator()
        data = dataset([1.0, 2.0, 3.0])
        pipe = Double().and_then(est, data)
        pipe.apply(1.0).get()
        pipe.apply(2.0).get()
        pipe.apply(dataset([1.0, 4.0])).get()
        assert est.fit_count == 1

    def test_label_estimator(self):
        est = CountingLabelEstimator()
        data = dataset([0.0, 2.0])  # doubled: mean 2
        labels = dataset([10.0, 20.0])  # mean 15
        pipe = Double().and_then(est, data, labels)
        assert float(pipe.apply(0.0).get()) == pytest.approx(17.0)
        assert est.fit_count == 1

    def test_state_reuse_across_pipeline_applications(self):
        """Fitted state is reused via the prefix table across separately
        constructed pipelines over the same data (PipelineSuite.scala:115-326)."""
        data = dataset([1.0, 2.0, 3.0])
        est = CountingEstimator()
        dbl = Double()
        pipe1 = dbl.and_then(est, data)
        pipe1.apply(1.0).get()
        assert est.fit_count == 1
        # A second pipeline with identical (operator, data) prefix structure:
        pipe2 = dbl.and_then(est, data)
        pipe2.apply(5.0).get()
        assert est.fit_count == 1  # loaded from PipelineEnv.state, not refit


class TestGather:
    def test_gather_datum(self):
        pipe = Pipeline.gather([Double().to_pipeline(), AddOne().to_pipeline()])
        out = pipe.apply(3.0).get()
        assert [float(x) for x in out] == [6.0, 4.0]

    def test_gather_dataset(self):
        pipe = Pipeline.gather([Double().to_pipeline(), AddOne().to_pipeline()])
        out = pipe.apply(dataset([1.0, 2.0])).get()
        items = out.to_list()
        assert len(items) == 2
        assert [float(v) for v in items[0]] == [2.0, 2.0]
        assert [float(v) for v in items[1]] == [4.0, 3.0]


class TestFit:
    def test_fit_produces_transformer_only_pipeline(self):
        est = CountingEstimator()
        data = dataset([0.0, 4.0])  # doubled: mean 4
        pipe = Double().and_then(est, data)
        fitted = pipe.fit()
        assert est.fit_count == 1
        assert float(fitted.apply(1.0)) == pytest.approx(6.0)
        # Applying fitted pipeline does not refit
        fitted.apply(2.0)
        assert est.fit_count == 1

    def test_fitted_pipeline_on_dataset(self):
        est = CountingEstimator()
        data = dataset([0.0, 4.0])
        fitted = Double().and_then(est, data).fit()
        out = fitted.apply(dataset([0.0, 1.0]))
        np.testing.assert_allclose(out.to_numpy(), [4.0, 6.0])

    def test_fit_publishes_prefix_state(self):
        """fit() publishes fitted estimators to the prefix table so later
        pipelines over the same logical prefix don't refit."""
        est = CountingEstimator()
        data = dataset([1.0, 2.0])
        dbl = Double()
        dbl.and_then(est, data).fit()
        assert est.fit_count == 1
        pipe2 = dbl.and_then(est, data)
        pipe2.apply(5.0).get()
        assert est.fit_count == 1

    def test_fitted_pipeline_save_load(self, tmp_path):
        est = CountingEstimator()
        data = dataset([0.0, 4.0])
        fitted = Double().and_then(est, data).fit()
        path = str(tmp_path / "pipe.pkl")
        fitted.save(path)
        loaded = type(fitted).load(path)
        assert float(loaded.apply(1.0)) == pytest.approx(6.0)


class TestCacher:
    def test_cacher_prefix_state_saved(self):
        data = dataset([1.0, 2.0])
        pipe = Double().and_then(Cacher())
        out = pipe.apply(data)
        out.get()
        # The Cacher node's prefix should now be in the global state table.
        assert len(PipelineEnv.get_or_create().state) >= 1


class TestLambdaAndCSE:
    def test_lambda_transformer(self):
        pipe = transformer(lambda x: x * 3).to_pipeline()
        assert float(pipe.apply(2.0).get()) == 6.0

    def test_equal_transformers_merge(self):
        """Structurally equal dataclass transformers trigger CSE."""
        from dataclasses import dataclass

        calls = []

        @dataclass(frozen=True)
        class Stamp(Transformer):
            tag: int

            def apply(self, x):
                calls.append(self.tag)
                return x + self.tag

        branch = Stamp(5).to_pipeline()
        pipe = Pipeline.gather([branch, Stamp(5).to_pipeline()])
        out = pipe.apply(1.0).get()
        assert [float(v) for v in out] == [6.0, 6.0]
        # CSE merged the two equal nodes: only one execution.
        assert len(calls) == 1


class TestBatchApplyDefault:
    """Transformer.batch_apply derives from device_fn: batched on device
    datasets AND on rectangular host collections (one dispatch, not one per
    item); ragged host items fall back to per-item apply."""

    def test_rectangular_host_list_takes_batched_path(self):
        from keystone_tpu.ops.util import FloatToDouble

        items = [np.full(3, i, dtype=np.float32) for i in range(4)]
        # Direct construction keeps the list (host form) — Dataset.of would
        # eagerly stack a rectangular list, bypassing the branch under test.
        ds = Dataset(list(items))
        assert ds.is_host
        calls = []
        t = FloatToDouble()
        orig = t._batch_fn
        object.__setattr__(t, "_batch_fn", lambda X: calls.append(X.shape) or orig(X))
        out = t.batch_apply(ds)
        assert calls == [(4, 3)]  # one batched call over the stacked array
        assert not out.is_host
        assert out.n == 4
        np.testing.assert_allclose(np.asarray(out.array), np.stack(items))

    def test_ragged_host_items_fall_back_per_item(self):
        from keystone_tpu.ops.images.core import GrayScaler

        rng = np.random.default_rng(0)
        imgs = [rng.random((5 + i, 4, 3)).astype(np.float32) for i in range(3)]
        out = GrayScaler().batch_apply(Dataset.of(imgs))
        shapes = [np.asarray(a).shape for a in out.to_list()]
        assert shapes == [(5, 4, 1), (6, 4, 1), (7, 4, 1)]

    def test_no_device_fn_maps_apply(self):
        class PlusOne(Transformer):
            def apply(self, x):
                return x + 1

        out = PlusOne().batch_apply(Dataset.of([1.0, 2.0]))
        assert [float(v) for v in out.to_list()] == [2.0, 3.0]


class TestDatumApplyCompileCache:
    """ISSUE 4 satellite: repeated single-datum FittedPipeline.apply calls
    with the same shape reuse ONE compiled executable — the trace-counter
    fixture pins the compile count."""

    def _fitted_chain(self, counter):
        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            TransformerGraph,
        )

        pipe = counter.to_pipeline()
        return FittedPipeline(
            TransformerGraph.from_graph(pipe.executor.graph),
            pipe.source,
            pipe.sink,
        )

    def test_same_shape_compiles_once(self):
        from tests._serving_util import TraceCountingScale

        t = TraceCountingScale()
        fitted = self._fitted_chain(t)
        x = np.arange(6, dtype=np.float32)
        outs = [np.asarray(fitted.apply(x + i)) for i in range(4)]
        assert t.traces == 1, "same-shape datum applies re-traced"
        for i, o in enumerate(outs):
            np.testing.assert_array_equal(o, (x + i) * 2.0)

    def test_new_shape_compiles_again_and_caps(self):
        from tests._serving_util import TraceCountingScale

        t = TraceCountingScale()
        fitted = self._fitted_chain(t)
        fitted.apply(np.zeros(3, np.float32))
        fitted.apply(np.zeros(5, np.float32))
        fitted.apply(np.zeros(3, np.float32))  # cache hit
        assert t.traces == 2

    def test_non_traceable_pipeline_keeps_per_node_path(self):
        class HostOnly(Transformer):
            def apply(self, x):
                return np.asarray(x) + 1.0

        fitted = self._fitted_chain(HostOnly())
        out = fitted.apply(np.zeros(4, np.float32))
        np.testing.assert_array_equal(np.asarray(out), np.ones(4))

    def test_save_load_drops_and_rebuilds_datum_cache(self, tmp_path):
        from tests._serving_util import TraceCountingScale

        t = TraceCountingScale()
        fitted = self._fitted_chain(t)
        fitted.apply(np.zeros(4, np.float32))
        path = str(tmp_path / "fitted.pkl")
        fitted.save(path)
        from keystone_tpu.workflow.pipeline import FittedPipeline

        loaded = FittedPipeline.load(path)
        out = loaded.apply(np.ones(4, np.float32))
        np.testing.assert_array_equal(np.asarray(out), np.ones(4) * 2.0)
