"""Gather-tree and estimator-fit fusion (workflow/fusion.py round 4).

GatherFusionRule collapses gather(branches...) -> VectorCombiner trees into
one program; EstimatorFusionRule then compiles the featurize program INTO a
trailing BlockLeastSquares fit (DeviceFit contract) — the pipeline-level
form of the bench's hand-fused featurize+solve region. Together they take
MnistRandomFFT's fit to ONE dispatch and its apply to one more.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.data import Dataset
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.ops.stats import LinearRectifier, PaddedFFT, RandomSignNode
from keystone_tpu.ops.util import (
    ClassLabelIndicatorsFromIntLabels,
    MaxClassifier,
    VectorCombiner,
)
from keystone_tpu.pipelines.mnist_random_fft import (
    MnistRandomFFTConfig,
    build_featurizer,
)
from keystone_tpu.workflow import Pipeline
from keystone_tpu.workflow.fusion import (
    EstimatorFusionRule,
    FusedFitEstimator,
    FusedGatherTransformer,
    GatherFusionRule,
)

rng = np.random.default_rng(0)
D_IN = 48


def _featurizer(num_ffts=3, block=32):
    cfg = MnistRandomFFTConfig(
        num_ffts=num_ffts, block_size=block, image_size=D_IN
    )
    return build_featurizer(cfg), cfg


class TestGatherFusion:
    def test_gather_tree_fuses_to_one_node(self):
        pipe, cfg = _featurizer()
        X = rng.normal(size=(10, D_IN)).astype(np.float32)
        handle = pipe.apply(Dataset.of(X))
        out = np.asarray(handle.get().array)

        graph = handle.executor.optimized_graph
        labels = [graph.get_operator(n).label for n in graph.nodes]
        assert any(l.startswith("FusedGather[") for l in labels), labels
        # The whole featurizer is ONE node now (branch chains + gather +
        # combiner all collapsed).
        assert len(labels) == 2, labels  # fused gather + the data source

        # Numeric parity with the unoptimized execution.
        from keystone_tpu.workflow.executor import GraphExecutor

        raw = GraphExecutor(pipe.executor.graph, optimize=False)
        sink_dep = pipe.executor.graph.get_sink_dependency(pipe.sink)
        # Re-wire the source by building via apply on a fresh unoptimized
        # pipeline instead:
        pipe2, _ = _featurizer()
        handle2 = pipe2.apply(Dataset.of(X))
        out2 = np.asarray(handle2.get().array)
        np.testing.assert_allclose(out, out2, atol=1e-5)

    def test_fused_gather_apply_matches_members(self):
        branches = [
            [RandomSignNode.create(D_IN, seed=i), PaddedFFT(),
             LinearRectifier(0.0)]
            for i in range(2)
        ]
        fused = FusedGatherTransformer(branches, VectorCombiner())
        X = rng.normal(size=(6, D_IN)).astype(np.float32)
        got = np.asarray(fused.batch_apply(Dataset.of(X)).array)
        parts = []
        for br in branches:
            d = Dataset.of(X)
            for m in br:
                d = m.batch_apply(d)
            parts.append(np.asarray(d.array))
        np.testing.assert_allclose(got, np.concatenate(parts, -1), atol=1e-5)


class TestEstimatorFitFusion:
    def _fit_pipeline(self, optimize=True):
        pipe, cfg = _featurizer(num_ffts=2, block=32)
        n = 64
        X = rng.normal(size=(n, D_IN)).astype(np.float32)
        y = rng.integers(0, 10, size=n)
        Y_ind = ClassLabelIndicatorsFromIntLabels(10)(Dataset.of(y))
        labels = Dataset.of(jnp.asarray(np.asarray(Y_ind.array)))
        data = Dataset.of(jnp.asarray(X))
        est = BlockLeastSquaresEstimator(cfg.block_size, 2, 1e-3)
        fitted = pipe.and_then(est, data, labels).fit()
        return fitted, data, y

    def test_fit_fuses_and_matches_unfused(self):
        fitted, data, y = self._fit_pipeline()
        # The fit graph rewrote the estimator into a FusedFitEstimator.
        # (Transformer graphs only keep fitted transformers, so inspect via
        # prediction parity against a manual unfused fit instead.)
        preds = np.asarray(fitted.apply(data).to_numpy())

        pipe, cfg = _featurizer(num_ffts=2, block=32)
        feats = pipe.apply(data).get()
        est = BlockLeastSquaresEstimator(cfg.block_size, 2, 1e-3)
        y_ind = Dataset.of(
            jnp.asarray(
                np.asarray(
                    ClassLabelIndicatorsFromIntLabels(10)(
                        Dataset.of(y)
                    ).array
                )
            )
        )
        mapper = est.fit(feats, y_ind)
        ref = np.asarray(mapper.batch_apply(feats).array)
        np.testing.assert_allclose(preds, ref, atol=2e-3, rtol=2e-3)

    def test_device_fit_fn_matches_fit(self):
        # The DeviceFit contract alone (no graph): fused-fit params give
        # the same model as the estimator's materialized-features fit.
        n, d, bs, k = 96, 64, 16, 3
        F = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        Y = jnp.asarray(rng.normal(size=(n, k)).astype(np.float32))
        est = BlockLeastSquaresEstimator(bs, 2, 1e-3)
        dev = est.device_fit_fn()
        assert dev.supports(d) and not dev.supports(d + 1)
        import jax

        params = jax.jit(dev.fit, static_argnums=2)(F, Y, n, *dev.operands)
        fused_model = dev.build(params)
        ref_model = est.fit(Dataset.of(F), Dataset.of(Y))
        got = np.asarray(fused_model.batch_apply(Dataset.of(F)).array)
        ref = np.asarray(ref_model.batch_apply(Dataset.of(F)).array)
        np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)

    def test_device_fit_fn_with_padding_rows(self):
        # Padding rows must not perturb means or solve. Inside a FUSED
        # program the padding rows of F are featurize(0) — NONZERO — so
        # the padded fixture uses garbage rows, not zeros (a zero-padded
        # fixture would mask the unmasked-mean bias this test exists for).
        n, pad, d, bs, k = 90, 38, 64, 16, 3
        F = rng.normal(size=(n, d)).astype(np.float32)
        Y = rng.normal(size=(n, k)).astype(np.float32)
        Fp = jnp.asarray(
            np.vstack([F, 7.0 + rng.normal(size=(pad, d)).astype(np.float32)])
        )
        Yp = jnp.asarray(
            np.vstack([Y, rng.normal(size=(pad, k)).astype(np.float32)])
        )
        est = BlockLeastSquaresEstimator(bs, 2, 1e-3)
        dev = est.device_fit_fn()
        import jax

        params_p = jax.jit(dev.fit, static_argnums=2)(Fp, Yp, n, *dev.operands)
        params = jax.jit(dev.fit, static_argnums=2)(
            jnp.asarray(F), jnp.asarray(Y), n, *dev.operands
        )
        for a, b in zip(params_p, params):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4
            )

    def test_fused_fit_estimator_fallback_on_unsupported_geometry(self):
        # d_feat not divisible by block -> falls back to the sequential
        # path and still produces a working model. Either way the fitted
        # model consumes FEATURIZED rows (the estimator's own output
        # contract), so both sides apply to NormalizeRows(X).
        n, d = 50, 40
        X = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        Y = jnp.asarray(rng.normal(size=(n, 2)).astype(np.float32))
        est = BlockLeastSquaresEstimator(16, 1, 1e-3)  # 40 % 16 != 0
        from keystone_tpu.ops.stats import NormalizeRows

        fe = FusedFitEstimator([NormalizeRows()], est)
        model = fe.fit(Dataset.of(X), Dataset.of(Y))
        feats = NormalizeRows().batch_apply(Dataset.of(X))
        ref = est.fit(feats, Dataset.of(Y))
        np.testing.assert_allclose(
            np.asarray(model.batch_apply(feats).array),
            np.asarray(ref.batch_apply(feats).array),
            atol=1e-5,
        )


class TestLinearMapEstimatorDeviceFit:
    def test_device_fit_matches_fit_with_garbage_padding(self):
        from keystone_tpu.ops.learning.linear import LinearMapEstimator

        n, pad, d, k = 120, 40, 32, 3
        F = rng.normal(size=(n, d)).astype(np.float32)
        Y = rng.normal(size=(n, k)).astype(np.float32)
        Fp = jnp.asarray(
            np.vstack([F, 5.0 + rng.normal(size=(pad, d)).astype(np.float32)])
        )
        Yp = jnp.asarray(
            np.vstack([Y, rng.normal(size=(pad, k)).astype(np.float32)])
        )
        est = LinearMapEstimator(lam=1e-3)
        dev = est.device_fit_fn()
        import jax

        params = jax.jit(dev.fit, static_argnums=2)(Fp, Yp, n, *dev.operands)
        fused_model = dev.build(params)
        ref_model = est.fit(
            Dataset.of(jnp.asarray(F)), Dataset.of(jnp.asarray(Y))
        )
        probe = Dataset.of(jnp.asarray(F[:32]))
        np.testing.assert_allclose(
            np.asarray(fused_model.batch_apply(probe).array),
            np.asarray(ref_model.batch_apply(probe).array),
            atol=2e-4, rtol=2e-4,
        )

    def test_pipeline_fit_fuses_linear_estimator(self):
        from keystone_tpu.ops.learning.linear import LinearMapEstimator
        from keystone_tpu.ops.stats import NormalizeRows

        n, d, k = 80, 24, 2
        X = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        Y = jnp.asarray(rng.normal(size=(n, k)).astype(np.float32))
        fitted = NormalizeRows().to_pipeline().and_then(
            LinearMapEstimator(lam=1e-2), Dataset.of(X), Dataset.of(Y)
        ).fit()
        preds = np.asarray(fitted.apply(Dataset.of(X)).to_numpy())
        feats = NormalizeRows().batch_apply(Dataset.of(X))
        ref = np.asarray(
            LinearMapEstimator(lam=1e-2)
            .fit(feats, Dataset.of(Y))
            .batch_apply(feats)
            .array
        )
        np.testing.assert_allclose(preds, ref, atol=2e-4, rtol=2e-4)


class TestMoreFamilyFitFusion:
    """Fit fusion for DenseLBFGSwithL2 and StreamingFeaturizedLeastSquares
    (VERDICT r4 directive #10): pipeline-level fits of those families also
    compile to one dispatch, matching their unfused fits."""

    def test_dense_lbfgs_device_fit_matches_fit(self):
        import jax

        from keystone_tpu.ops.learning.lbfgs import DenseLBFGSwithL2

        n, d, k = 96, 32, 3
        F = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        Y = jnp.asarray(rng.normal(size=(n, k)).astype(np.float32))
        est = DenseLBFGSwithL2(lam=1e-2, num_iterations=30)
        dev = est.device_fit_fn()
        params = jax.jit(dev.fit, static_argnums=2)(F, Y, n, *dev.operands)
        fused_model = dev.build(params)
        ref_model = est.fit(Dataset.of(F), Dataset.of(Y))
        got = np.asarray(fused_model.batch_apply(Dataset.of(F)).array)
        ref = np.asarray(ref_model.batch_apply(Dataset.of(F)).array)
        np.testing.assert_allclose(got, ref, atol=2e-3, rtol=2e-3)

    def test_dense_lbfgs_pipeline_fit_fuses(self):
        from keystone_tpu.ops.learning.lbfgs import DenseLBFGSwithL2
        from keystone_tpu.workflow.env import PipelineEnv

        PipelineEnv.get_or_create().reset()
        pipe, cfg = _featurizer(num_ffts=2, block=32)
        n = 64
        X = rng.normal(size=(n, D_IN)).astype(np.float32)
        Y = rng.normal(size=(n, 3)).astype(np.float32)
        est = DenseLBFGSwithL2(lam=1e-2, num_iterations=25)
        data, labels = Dataset.of(jnp.asarray(X)), Dataset.of(jnp.asarray(Y))
        p = pipe.and_then(est, data, labels)
        # Held-out apply: applying to the training data would CSE-merge the
        # train/apply featurize chains, which blocks estimator fusion (the
        # featurized result is genuinely consumed twice there).
        X2 = rng.normal(size=(16, D_IN)).astype(np.float32)
        handle = p.apply(Dataset.of(jnp.asarray(X2)))
        preds_held = np.asarray(handle.get().array)
        data2 = Dataset.of(jnp.asarray(X2))
        preds = np.asarray(p.apply(data).get().array)
        graph = handle.executor.optimized_graph
        labels_g = [
            str(getattr(graph.get_operator(nid), "label", ""))
            for nid in graph.nodes
        ]
        assert any(l.startswith("FusedFit[") for l in labels_g), labels_g

        featurizer = _featurizer(num_ffts=2, block=32)[0]
        feats = featurizer.apply(data).get()
        ref_model = est.fit(feats, labels)
        ref = np.asarray(ref_model.batch_apply(feats).array)
        np.testing.assert_allclose(preds, ref, atol=2e-3, rtol=2e-3)
        feats2 = featurizer.apply(data2).get()
        ref2 = np.asarray(ref_model.batch_apply(feats2).array)
        np.testing.assert_allclose(preds_held, ref2, atol=2e-3, rtol=2e-3)

    def test_streaming_estimator_device_fit_matches_fit(self):
        import jax

        from keystone_tpu.ops.learning.streaming_ls import (
            CosineBankFeaturize,
            StreamingFeaturizedLeastSquares,
        )

        n, d_in, d_feat, bs, k = 200, 16, 128, 32, 3
        rloc = np.random.default_rng(5)
        bank = CosineBankFeaturize(
            rloc.normal(size=(d_feat, d_in)).astype(np.float32),
            rloc.uniform(0, 6, size=(d_feat,)).astype(np.float32),
        )
        X = jnp.asarray(rloc.normal(size=(n, d_in)).astype(np.float32))
        Y = jnp.asarray(rloc.normal(size=(n, k)).astype(np.float32))
        est = StreamingFeaturizedLeastSquares(
            bank, d_feat=d_feat, block_size=bs, num_iter=2, lam=1e-2,
            tile_rows=64,
        )
        dev = est.device_fit_fn()
        # The bank rides as TRACED operands (DeviceFit.operands) so it
        # never embeds as an HLO constant in the fused program.
        assert len(dev.operands) == 3  # lam + Wrf + brf as traced operands
        params = jax.jit(dev.fit, static_argnums=2)(X, Y, n, *dev.operands)
        fused_model = dev.build(params)
        ref_model = est.fit(Dataset.of(X), Dataset.of(Y))
        got = np.asarray(fused_model.batch_apply(Dataset.of(X)).array)
        ref = np.asarray(ref_model.batch_apply(Dataset.of(X)).array)
        np.testing.assert_allclose(got, ref, atol=2e-3, rtol=2e-3)


class TestSharedFitPrograms:
    def test_lambda_sweep_with_fresh_estimators_compiles_once(self):
        """A λ-sweep whose driver builds a NEW estimator object per λ (the
        autocache bench pattern) must share ONE fused featurize+fit
        program: λ is a DeviceFit operand and the program cache keys on
        (members, program_key), not estimator identity. Regression test
        for the round-5 recompile-per-λ slowdown the CRF device_fn
        introduced."""
        from keystone_tpu.workflow import fusion
        from keystone_tpu.workflow.env import PipelineEnv

        PipelineEnv.get_or_create().reset()
        pipe, cfg = _featurizer(num_ffts=2, block=32)
        n = 64
        X = rng.normal(size=(n, D_IN)).astype(np.float32)
        Y = rng.normal(size=(n, 3)).astype(np.float32)
        data = Dataset.of(jnp.asarray(X))
        labels = Dataset.of(jnp.asarray(Y))

        before_keys = set(fusion._SHARED_FIT_PROGRAMS)
        preds = []
        for lam in (1e-4, 1e-3, 1e-2):
            # One optimizer across the sweep (the bench pattern): the
            # fusion memos then hand every λ the SAME fused members, and
            # the shared-program cache must collapse the sweep to one
            # compile. (Estimator prefix state would make later fits
            # no-ops, so clear just the state table, not the optimizer.)
            PipelineEnv.get_or_create().state.clear()
            est = BlockLeastSquaresEstimator(cfg.block_size, 2, lam)
            p = pipe.and_then(est, data, labels)
            X2 = Dataset.of(jnp.asarray(X[:16]))
            preds.append(np.asarray(p.apply(X2).get().array))
        # One shared program for the whole sweep (same members + same
        # BlockLS program_key; λ rides as an operand). Key-set delta, not
        # length delta: the insert-time purge may drop entries whose
        # owners died in earlier tests.
        new_keys = set(fusion._SHARED_FIT_PROGRAMS) - before_keys
        assert len(new_keys) == 1, new_keys
        # And λ genuinely differed: heavier ridge shrinks predictions.
        assert not np.allclose(preds[0], preds[2])
