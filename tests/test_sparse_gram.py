"""Sparse gram-engine LBFGS vs the gather-path oracle.

The gram engine folds G = AᵀA once over densified row chunks and runs the
SAME L-BFGS iterates against G (hvp = GP/n + λP ≡ Aᵀ(AP)/n + λP), so the
two solvers must agree to summation-order noise. Also pins the
compressed-COO resident format (int16 indices + bf16 values — 4 bytes/nnz)
through the same fit.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.data import Dataset
from keystone_tpu.ops.learning.lbfgs import (
    SparseLBFGSwithL2,
    run_lbfgs_gram_streamed,
)
from keystone_tpu.ops.sparse import gram_pad_dim, sparse_gram_stream

N, D, W_NNZ, K = 3000, 200, 12, 3


def _problem(seed=0, idx_dtype=np.int32, val_dtype=np.float32):
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, D, size=(N, W_NNZ)).astype(idx_dtype)
    vals = rng.normal(size=(N, W_NNZ)).astype(val_dtype)
    labels = rng.integers(0, K, size=N)
    Y = (2.0 * np.eye(K)[labels] - 1.0).astype(np.float32)
    ds = Dataset(
        {"indices": jnp.asarray(idx), "values": jnp.asarray(vals)}, n=N
    )
    return ds, Dataset.of(jnp.asarray(Y)), idx, vals, Y


class TestSparseGramStream:
    def test_gram_matches_dense_oracle(self):
        _, _, idx, vals, Y = _problem()
        dense = np.zeros((N, D), np.float64)
        np.add.at(dense, (np.arange(N)[:, None], idx), vals)

        c = 512
        nchunks = -(-N // c)
        pad = nchunks * c - N
        idx_t = jnp.asarray(
            np.pad(idx, ((0, pad), (0, 0)), constant_values=-1)
        ).reshape(nchunks, c, W_NNZ)
        val_t = jnp.asarray(np.pad(vals, ((0, pad), (0, 0)))).reshape(
            nchunks, c, W_NNZ
        )
        Y_t = jnp.asarray(np.pad(Y, ((0, pad), (0, 0)))).reshape(
            nchunks, c, K
        )
        import jax

        G, AtY, yty = jax.jit(
            lambda a, b, y: sparse_gram_stream(
                lambda cid: (a[cid], b[cid], y[cid]), nchunks, D, K
            )
        )(idx_t, val_t, Y_t)
        d_pad = gram_pad_dim(D, jnp.float32)
        assert G.shape == (d_pad, d_pad)
        np.testing.assert_allclose(
            np.asarray(G)[:D, :D], dense.T @ dense, rtol=2e-4, atol=2e-3
        )
        # Padding rows/cols of G and AtY are exactly zero.
        assert np.all(np.asarray(G)[D:, :] == 0)
        assert np.all(np.asarray(AtY)[D:, :] == 0)
        np.testing.assert_allclose(
            np.asarray(AtY)[:D], dense.T @ Y, rtol=2e-4, atol=2e-3
        )
        np.testing.assert_allclose(float(yty), (Y * Y).sum(), rtol=1e-6)

    def test_duplicate_indices_accumulate(self):
        # COO rows may repeat a column; densify must add, not overwrite.
        idx = jnp.asarray([[1, 1, 3]], dtype=jnp.int32)
        vals = jnp.asarray([[2.0, 3.0, 4.0]], dtype=jnp.float32)
        Y = jnp.asarray([[1.0]], dtype=jnp.float32)
        import jax

        G, AtY, _ = jax.jit(
            lambda a, b, y: sparse_gram_stream(
                lambda cid: (a, b, y), 1, 8, 1
            )
        )(idx, vals, Y)
        dense = np.zeros(8)
        dense[1], dense[3] = 5.0, 4.0
        np.testing.assert_allclose(
            np.asarray(G)[:8, :8], np.outer(dense, dense), atol=1e-5
        )
        np.testing.assert_allclose(np.asarray(AtY)[:8, 0], dense, atol=1e-5)


class TestGramSolverMatchesGather:
    @pytest.mark.slow
    def test_same_model_as_gather_path(self):
        ds, ys, *_ = _problem()
        m_gather = SparseLBFGSwithL2(
            lam=1e-3, num_iterations=25, num_features=D
        ).fit(ds, ys)
        m_gram = SparseLBFGSwithL2(
            lam=1e-3, num_iterations=25, num_features=D, solver="gram",
            gram_chunk_rows=512,
        ).fit(ds, ys)
        np.testing.assert_allclose(
            np.asarray(m_gram.x), np.asarray(m_gather.x), rtol=5e-3,
            atol=5e-4,
        )
        np.testing.assert_allclose(
            np.asarray(m_gram.b_opt), np.asarray(m_gather.b_opt),
            rtol=5e-3, atol=5e-4,
        )
        # Predictions agree tightly on held-out rows too (the model
        # difference is fp noise, not a train-set artifact).
        ds_test = _problem(seed=5)[0]
        for probe in (ds, ds_test):
            p1 = np.asarray(m_gather.batch_apply(probe).array)
            p2 = np.asarray(m_gram.batch_apply(probe).array)
            np.testing.assert_allclose(p2, p1, rtol=1e-2, atol=1e-3)

    def test_compressed_int16_bf16_storage(self):
        # 4-bytes-per-nnz resident format: int16 indices + bf16 values.
        ds16, ys, idx, vals, Y = _problem(
            idx_dtype=np.int16, val_dtype=np.float32
        )
        ds16 = Dataset(
            {
                "indices": jnp.asarray(idx.astype(np.int16)),
                "values": jnp.asarray(vals).astype(jnp.bfloat16),
            },
            n=N,
        )
        m16 = SparseLBFGSwithL2(
            lam=1e-3, num_iterations=25, num_features=D, solver="gram",
            gram_chunk_rows=512,
        ).fit(ds16, ys)
        ds32, _, _, _, _ = _problem()
        m32 = SparseLBFGSwithL2(
            lam=1e-3, num_iterations=25, num_features=D
        ).fit(ds32, ys)
        # bf16 values quantize the data itself (~0.4% relative), so the
        # tolerance is bf16-resolution, not fp32-noise.
        np.testing.assert_allclose(
            np.asarray(m16.x), np.asarray(m32.x), rtol=0.05, atol=0.02
        )

    def test_segmented_dispatch_equals_single(self):
        # The dispatch-bounded fold (phantom-padded final segment, donated
        # carry, traced cid0) must reproduce the one-dispatch fit exactly.
        _, _, idx, vals, Y = _problem()
        c = 500
        nchunks = N // c  # 6 chunks -> segments of 4 = [4, phantom-padded 4]
        idx_t = jnp.asarray(idx).reshape(nchunks, c, W_NNZ)
        val_t = jnp.asarray(vals).reshape(nchunks, c, W_NNZ)
        Y_t = jnp.asarray(Y).reshape(nchunks, c, K)

        def cf(cid, it, vt, yt):
            cid = jnp.minimum(cid, nchunks - 1)  # phantom ids slice safely
            return it[cid], vt[cid], yt[cid]

        kw = dict(lam=1e-3, num_iterations=25, n=N,
                  operands=(idx_t, val_t, Y_t))
        W_one, loss_one = run_lbfgs_gram_streamed(
            cf, nchunks, D, K, **kw
        )
        W_seg, loss_seg = run_lbfgs_gram_streamed(
            cf, nchunks, D, K, max_chunks_per_dispatch=4, **kw
        )
        np.testing.assert_allclose(
            np.asarray(W_seg), np.asarray(W_one), atol=1e-5, rtol=1e-5
        )
        np.testing.assert_allclose(float(loss_seg), float(loss_one), rtol=1e-6)

    def test_streamed_regenerated_chunks(self):
        # Chunks produced by a generator (nothing resident) must equal the
        # resident fit on the same data.
        import jax

        ds, ys, idx, vals, Y = _problem()
        c = 500
        nchunks = N // c

        idx_t = jnp.asarray(idx).reshape(nchunks, c, W_NNZ)
        val_t = jnp.asarray(vals).reshape(nchunks, c, W_NNZ)
        Y_t = jnp.asarray(Y).reshape(nchunks, c, K)

        W_s, loss = run_lbfgs_gram_streamed(
            lambda cid, it, vt, yt: (it[cid], vt[cid], yt[cid]),
            nchunks, D, K, lam=1e-3, num_iterations=25, n=N,
            operands=(idx_t, val_t, Y_t),
        )
        m_gather = SparseLBFGSwithL2(
            lam=1e-3, num_iterations=25, num_features=D
        ).fit(ds, ys)
        # No intercept lane in this direct call: compare to gather WITHOUT
        # intercept by refitting through run_lbfgs on the raw COO.
        from keystone_tpu.ops.learning.lbfgs import run_lbfgs

        W_ref = run_lbfgs(
            {"indices": jnp.asarray(idx), "values": jnp.asarray(vals)},
            jnp.asarray(Y), lam=1e-3, num_iterations=25, n=N,
            W_init=jnp.zeros((D, K), jnp.float32),
        )
        assert np.isfinite(float(loss))
        np.testing.assert_allclose(
            np.asarray(W_s), np.asarray(W_ref), rtol=5e-3, atol=5e-4
        )
