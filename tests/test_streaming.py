"""Streaming (out-of-core) fit path: tiled Gramian accumulation + BCD on
the normal equations must reproduce the resident residual-form solver.

This is the memory-wall tier (VERDICT r3 Missing #1): the feature matrix
is generated per row tile and never materialized; correctness here means
the streamed solve is the SAME algorithm as ``bcd_least_squares_fused_flat``
— identical iterates up to f32 summation-order noise — plus exact padding /
masking semantics (a zero input row featurizes to cos(b) ≠ 0, so padding
must be excluded after featurization, not before).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.parallel import streaming
from keystone_tpu.parallel.linalg import bcd_least_squares_fused_flat

D_IN, D_FEAT, BLOCK, K = 24, 128, 32, 3
LAM = 1e-2


def _featurizer(seed=0):
    rng = np.random.default_rng(seed)
    Wr = jnp.asarray(rng.normal(size=(D_FEAT, D_IN)).astype(np.float32) * 0.3)
    br = jnp.asarray(
        rng.uniform(0, 2 * np.pi, size=(D_FEAT,)).astype(np.float32)
    )

    def featurize(X_t):
        return jnp.cos(X_t @ Wr.T + br)

    return featurize


def _problem(n, seed=1):
    rng = np.random.default_rng(seed)
    X = jnp.asarray(rng.normal(size=(n, D_IN)).astype(np.float32))
    Y = jnp.asarray(rng.normal(size=(n, K)).astype(np.float32))
    return X, Y


class TestStreamingMatchesResident:
    @pytest.mark.parametrize("epochs", [1, 3])
    @pytest.mark.parametrize("n,tile", [(512, 128), (529, 128), (100, 256)])
    def test_matches_fused_flat(self, n, tile, epochs):
        # n=529: ragged remainder; n=100 < tile: remainder-only path.
        featurize = _featurizer()
        X, Y = _problem(n)
        W_s, loss, _ = streaming.streaming_bcd_fit(
            X, Y, featurize=featurize, d_feat=D_FEAT, tile_rows=tile,
            block_size=BLOCK, lam=LAM, num_iter=epochs,
        )
        F = featurize(X)
        W_ref = bcd_least_squares_fused_flat(
            F, Y, BLOCK, lam=LAM, num_iter=epochs, use_pallas=False
        )
        np.testing.assert_allclose(
            np.asarray(W_s), np.asarray(W_ref), atol=2e-3, rtol=2e-3
        )
        # The algebraic loss (from G/FY/yty) equals the explicit residual.
        Wf = np.asarray(W_s).reshape(D_FEAT, K)
        R = np.asarray(Y) - np.asarray(F, np.float64) @ Wf
        np.testing.assert_allclose(
            float(loss), float((R * R).sum() / n), rtol=2e-3
        )

    def test_streaming_predict(self):
        featurize = _featurizer()
        X, Y = _problem(300)
        W, _, _ = streaming.streaming_bcd_fit(
            X, Y, featurize=featurize, d_feat=D_FEAT, tile_rows=128,
            block_size=BLOCK, lam=LAM, num_iter=2,
        )
        preds = streaming.streaming_predict(X, W, featurize, tile_rows=128)
        expected = featurize(X) @ np.asarray(W).reshape(D_FEAT, K)
        np.testing.assert_allclose(
            np.asarray(preds), np.asarray(expected), atol=1e-4
        )

    def test_pretiled_static_valid_labelize_matches_flat(self):
        # The large-fit calling convention: pre-tiled 3-D X, int labels
        # turned into ±1 one-hot targets per tile, static valid masking
        # the boundary tile. Must equal the flat-X dense-Y fit on the true
        # rows.
        featurize = _featurizer()
        n_true, tile = 450, 128
        rng = np.random.default_rng(8)
        X, _ = _problem(n_true, seed=2)
        y = rng.integers(0, K, size=n_true)
        Y = jnp.asarray(2.0 * np.eye(K, dtype=np.float32)[y] - 1.0)

        T = -(-n_true // tile)
        pad = T * tile - n_true
        Xp = jnp.concatenate(
            [X, jnp.asarray(rng.normal(size=(pad, D_IN)).astype(np.float32))]
        ).reshape(T, tile, D_IN)
        yp = jnp.asarray(
            np.concatenate([y, rng.integers(0, K, size=pad)])
        ).reshape(T, tile)

        def labelize(y_t):
            return 2.0 * jax.nn.one_hot(y_t, K, dtype=jnp.float32) - 1.0

        W_t, loss_t, _ = streaming.streaming_bcd_fit(
            Xp, yp, featurize=featurize, d_feat=D_FEAT, tile_rows=tile,
            block_size=BLOCK, lam=LAM, num_iter=2, valid=n_true,
            labelize=labelize,
        )
        W_f, loss_f, _ = streaming.streaming_bcd_fit(
            X, Y, featurize=featurize, d_feat=D_FEAT, tile_rows=tile,
            block_size=BLOCK, lam=LAM, num_iter=2,
        )
        np.testing.assert_allclose(
            np.asarray(W_t), np.asarray(W_f), atol=1e-4, rtol=1e-4
        )
        np.testing.assert_allclose(float(loss_t), float(loss_f), rtol=1e-5)
        # Pre-tiled predict path flattens back to (T*tile, k).
        preds = streaming.streaming_predict(Xp, W_t, featurize, tile)
        preds_flat = streaming.streaming_predict(X, W_t, featurize, tile)
        np.testing.assert_allclose(
            np.asarray(preds)[:n_true], np.asarray(preds_flat), atol=1e-4
        )

    def test_valid_masks_garbage_padding(self):
        # Garbage (NOT zero) padding rows with valid= must give the exact
        # result of fitting the true rows only.
        featurize = _featurizer()
        X, Y = _problem(200)
        rng = np.random.default_rng(9)
        Xp = jnp.concatenate(
            [X, jnp.asarray(rng.normal(size=(56, D_IN)).astype(np.float32))]
        )
        Yp = jnp.concatenate(
            [Y, jnp.asarray(rng.normal(size=(56, K)).astype(np.float32))]
        )
        G_p, FY_p, yty_p = jax.jit(
            lambda a, b: streaming.gram_stats(
                a, b, featurize, D_FEAT, 128,
                valid=jnp.asarray(200, jnp.int32),
            )
        )(Xp, Yp)
        G, FY, yty = jax.jit(
            lambda a, b: streaming.gram_stats(a, b, featurize, D_FEAT, 128)
        )(X, Y)
        np.testing.assert_allclose(np.asarray(G_p), np.asarray(G), atol=1e-4)
        np.testing.assert_allclose(np.asarray(FY_p), np.asarray(FY), atol=1e-5)
        np.testing.assert_allclose(float(yty_p), float(yty), rtol=1e-6)


class TestStreamingEstimatorAPI:
    def test_estimator_matches_solver(self):
        from keystone_tpu.data import Dataset
        from keystone_tpu.ops.learning.streaming_ls import (
            StreamingFeaturizedLeastSquares,
        )

        featurize = _featurizer()
        X, Y = _problem(500)
        est = StreamingFeaturizedLeastSquares(
            featurize, d_feat=D_FEAT, block_size=BLOCK, num_iter=2,
            lam=LAM, tile_rows=128, center=False,  # raw-BCD reference below
        )
        model = est.fit(Dataset.of(X), Dataset.of(Y))
        preds = np.asarray(model.batch_apply(Dataset.of(X)).array)
        F = featurize(X)
        W_ref = bcd_least_squares_fused_flat(
            F, Y, BLOCK, lam=LAM, num_iter=2, use_pallas=False
        )
        ref = np.asarray(F @ np.asarray(W_ref).reshape(D_FEAT, K))
        np.testing.assert_allclose(preds, ref, atol=5e-3, rtol=5e-3)
        # Single-item apply agrees with the batch path.
        one = np.asarray(model.apply(np.asarray(X)[0]))
        np.testing.assert_allclose(one, preds[0], atol=1e-4)

    def test_estimator_mesh_branch_matches_single_device(self):
        from keystone_tpu.data import Dataset
        from keystone_tpu.ops.learning.streaming_ls import (
            StreamingFeaturizedLeastSquares,
        )

        featurize = _featurizer()
        X, Y = _problem(512, seed=9)
        mesh = mesh_lib.make_mesh()
        est = StreamingFeaturizedLeastSquares(
            featurize, d_feat=D_FEAT, block_size=BLOCK, num_iter=2,
            lam=LAM, tile_rows=64,
        )
        m_one = est.fit(Dataset.of(X), Dataset.of(Y))
        m_mesh = est.fit(
            Dataset.of(X).shard(mesh), Dataset.of(Y).shard(mesh)
        )
        # Same tolerance as the sibling mesh-parity test: f32 psum/fold
        # summation-order noise, BCD-amplified.
        np.testing.assert_allclose(
            np.asarray(m_mesh.W_stack), np.asarray(m_one.W_stack),
            atol=2e-3, rtol=2e-3,
        )

    def test_timit_pipeline_streaming_mode(self):
        from keystone_tpu.pipelines.timit import TimitConfig, run

        cfg = TimitConfig(
            num_cosines=2, block_size=64, num_epochs=2, lam=1e-3,
            synthetic_n=512, streaming=True,
        )
        _, train_eval, _ = run(cfg)
        # Synthetic TIMIT is learnable: the streamed fit must actually fit.
        assert train_eval.total_error < 0.5, train_eval.total_error


class TestStreamingCentered:
    """Centered streamed fits must match BlockLeastSquaresEstimator — the
    solver whose semantics (per-block feature centering + label centering +
    intercept, BlockLinearMapper.scala:224-243) the streaming tier claims
    (VERDICT r4 Missing #2)."""

    def test_matches_block_least_squares(self):
        from keystone_tpu.data import Dataset
        from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
        from keystone_tpu.ops.learning.streaming_ls import (
            StreamingFeaturizedLeastSquares,
        )

        featurize = _featurizer()
        X, Y = _problem(500)
        est = StreamingFeaturizedLeastSquares(
            featurize, d_feat=D_FEAT, block_size=BLOCK, num_iter=2,
            lam=LAM, tile_rows=128,  # center=True default
        )
        model = est.fit(Dataset.of(X), Dataset.of(Y))

        F = featurize(X)
        block = BlockLeastSquaresEstimator(BLOCK, 2, lam=LAM).fit(
            Dataset.of(np.asarray(F)), Dataset.of(Y)
        )
        Xt, _ = _problem(100, seed=3)
        preds = np.asarray(model.batch_apply(Dataset.of(Xt)).array)
        ref = np.asarray(
            block.batch_apply(Dataset.of(np.asarray(featurize(Xt)))).array
        )
        np.testing.assert_allclose(preds, ref, atol=5e-3, rtol=5e-3)

    def test_centered_solver_matches_masked_center_reference(self):
        # Rank-1 gram-space centering == explicit center-then-solve, with
        # ragged padding rows holding GARBAGE (they must not leak into the
        # means: a zero row featurizes to cos(b) != 0, a garbage row to
        # anything).
        featurize = _featurizer()
        n_true = 437
        X, Y = _problem(n_true)
        rng = np.random.default_rng(21)
        pad = 75
        Xp = jnp.concatenate(
            [X, jnp.asarray(rng.normal(size=(pad, D_IN)).astype(np.float32) * 50)]
        )
        Yp = jnp.concatenate(
            [Y, jnp.asarray(rng.normal(size=(pad, K)).astype(np.float32) * 50)]
        )
        W, fmean, ymean, loss = streaming.streaming_bcd_fit_centered(
            Xp, Yp, featurize=featurize, d_feat=D_FEAT, tile_rows=128,
            block_size=BLOCK, lam=LAM, num_iter=2, valid=n_true,
        )
        F = np.asarray(featurize(X)).astype(np.float64)
        Yd = np.asarray(Y, dtype=np.float64)
        mu, ybar = F.mean(axis=0), Yd.mean(axis=0)
        W_ref = bcd_least_squares_fused_flat(
            jnp.asarray((F - mu).astype(np.float32)),
            jnp.asarray((Yd - ybar).astype(np.float32)),
            BLOCK, lam=LAM, num_iter=2, use_pallas=False,
        )
        np.testing.assert_allclose(np.asarray(fmean), mu, atol=1e-4)
        np.testing.assert_allclose(np.asarray(ymean), ybar, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(W), np.asarray(W_ref), atol=2e-3, rtol=2e-3
        )
        assert np.isfinite(float(loss)) and float(loss) >= 0

    def test_centered_mesh_matches_single_device(self):
        featurize = _featurizer()
        n_true = 700
        X, Y = _problem(n_true, seed=7)
        mesh = mesh_lib.make_mesh()
        num = mesh_lib.axis_size(mesh, mesh_lib.DATA_AXIS)
        pad = (-n_true) % (num * 64)
        rng = np.random.default_rng(11)
        Xp = jnp.concatenate(
            [X, jnp.asarray(rng.normal(size=(pad, D_IN)).astype(np.float32))]
        )
        Yp = jnp.concatenate(
            [Y, jnp.asarray(rng.normal(size=(pad, K)).astype(np.float32))]
        )
        W_mesh, fm_m, ym_m = streaming.streaming_bcd_fit_mesh_centered(
            mesh_lib.shard_rows(Xp, mesh), mesh_lib.shard_rows(Yp, mesh),
            featurize=featurize, d_feat=D_FEAT, tile_rows=64,
            block_size=BLOCK, lam=LAM, num_iter=2, mesh=mesh, n_true=n_true,
        )
        W_one, fm_1, ym_1, _ = streaming.streaming_bcd_fit_centered(
            X, Y, featurize=featurize, d_feat=D_FEAT, tile_rows=64,
            block_size=BLOCK, lam=LAM, num_iter=2,
        )
        np.testing.assert_allclose(
            np.asarray(fm_m), np.asarray(fm_1), atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(ym_m), np.asarray(ym_1), atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(W_mesh), np.asarray(W_one), atol=2e-3, rtol=2e-3
        )

    def test_lambda_sweep_is_one_compile(self):
        # λ is a traced operand (VERDICT r4 Weak #3): a 3-λ sweep over one
        # geometry must add exactly ONE entry to the jit cache.
        featurize = _featurizer(seed=33)
        X, Y = _problem(320, seed=13)
        kw = dict(
            featurize=featurize, d_feat=D_FEAT, tile_rows=128,
            block_size=BLOCK, num_iter=2,
        )
        before = streaming._streaming_fit_closure._cache_size()
        sols = [
            np.asarray(
                streaming.streaming_bcd_fit_centered(X, Y, lam=lam, **kw)[0]
            )
            for lam in (1e-3, 1e-2, 1e-1)
        ]
        assert streaming._streaming_fit_closure._cache_size() - before == 1
        # λ actually took effect: heavier ridge shrinks the weights.
        norms = [float(np.linalg.norm(s)) for s in sols]
        assert norms[0] > norms[1] > norms[2]


class TestStreamingPallasKernel:
    def test_gram_sym_acc_interpret_matches_xla(self):
        # Aligned shapes so the accumulating syrk path engages (interpret
        # mode on CPU); upper triangle must match G0 + FᵀF.
        from keystone_tpu.ops import pallas_ops

        rng = np.random.default_rng(3)
        F = jnp.asarray(rng.normal(size=(1024, 256)).astype(np.float32))
        G0 = jnp.asarray(rng.normal(size=(256, 256)).astype(np.float32))
        assert pallas_ops.gram_acc_ok(F)
        out = pallas_ops.gram_sym_acc(G0, F, interpret=True)
        expected = np.asarray(G0) + np.asarray(F).T @ np.asarray(F)
        np.testing.assert_allclose(
            np.triu(np.asarray(out)), np.triu(expected), atol=1e-3
        )

    def test_streaming_fit_pallas_interpret_matches_xla(self):
        # The full streamed fit with the Pallas accumulation on (interpret)
        # must match the XLA accumulation path.
        rng = np.random.default_rng(4)
        Wr = jnp.asarray(rng.normal(size=(256, D_IN)).astype(np.float32) * 0.3)
        br = jnp.asarray(rng.uniform(0, 6.0, size=(256,)).astype(np.float32))

        def featurize(X_t):
            return jnp.cos(X_t @ Wr.T + br)

        X, Y = _problem(1024, seed=5)
        kw = dict(
            featurize=featurize, d_feat=256, tile_rows=512, block_size=128,
            lam=LAM, num_iter=2,
        )
        import os
        os.environ["KEYSTONE_PALLAS"] = "1"
        try:
            W_p, _, _ = streaming.streaming_bcd_fit(X, Y, use_pallas=True, **kw)
        finally:
            os.environ.pop("KEYSTONE_PALLAS", None)
        W_x, _, _ = streaming.streaming_bcd_fit(X, Y, use_pallas=False, **kw)
        np.testing.assert_allclose(
            np.asarray(W_p), np.asarray(W_x), atol=2e-3, rtol=2e-3
        )


class TestStreamingMesh:
    def test_mesh_matches_single_device(self):
        # Rows padded to shard over 8 devices; n_true masks the padding.
        featurize = _featurizer()
        n_true = 700
        X, Y = _problem(n_true, seed=7)
        mesh = mesh_lib.make_mesh()
        num = mesh_lib.axis_size(mesh, mesh_lib.DATA_AXIS)
        pad = (-n_true) % (num * 64)
        rng = np.random.default_rng(11)
        Xp = jnp.concatenate(
            [X, jnp.asarray(rng.normal(size=(pad, D_IN)).astype(np.float32))]
        )
        Yp = jnp.concatenate(
            [Y, jnp.asarray(rng.normal(size=(pad, K)).astype(np.float32))]
        )
        Xs = mesh_lib.shard_rows(Xp, mesh)
        Ys = mesh_lib.shard_rows(Yp, mesh)
        W_mesh = streaming.streaming_bcd_fit_mesh(
            Xs, Ys, featurize=featurize, d_feat=D_FEAT, tile_rows=64,
            block_size=BLOCK, lam=LAM, num_iter=2, mesh=mesh, n_true=n_true,
        )
        W_one, _, _ = streaming.streaming_bcd_fit(
            X, Y, featurize=featurize, d_feat=D_FEAT, tile_rows=64,
            block_size=BLOCK, lam=LAM, num_iter=2,
        )
        np.testing.assert_allclose(
            np.asarray(W_mesh), np.asarray(W_one), atol=2e-3, rtol=2e-3
        )
