"""Out-of-core ingestion wired into the typed Pipeline API (ISSUE 2):
loaders spill to disk shards instead of a resident array, a shard-backed
Dataset flows through ``Pipeline.fit``, and the capacity selector routes
past-host-RAM datasets through the disk tier with NO manual flag —
matching the resident path within existing streaming parity tolerances.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.data import Dataset, LabeledData
from keystone_tpu.data.loaders import csv_to_disk_shards
from keystone_tpu.data.shards import DiskDenseShards, DiskDenseShardWriter
from keystone_tpu.ops.learning.cost import LeastSquaresEstimator
from keystone_tpu.ops.learning.streaming_ls import (
    BlockStreamedLeastSquares,
    CosineBankFeaturize,
    StreamingLeastSquaresChoice,
)
from keystone_tpu.ops.stats import CosineRandomFeatures
from keystone_tpu.workflow.env import PipelineEnv


def _spilled_problem(tmp_path, n=1000, d=24, k=3, shard_rows=128, seed=0):
    """shard_rows does NOT divide n: ragged final shard by construction."""
    assert n % shard_rows != 0
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    Y = rng.normal(size=(n, k)).astype(np.float32) + 0.3
    sld = LabeledData(X, Y).to_disk_shards(
        str(tmp_path / "shards"), shard_rows=shard_rows,
        tiles_per_segment=2,
    )
    return X, Y, sld


class TestSpillPath:
    def test_loader_spill_roundtrips_rows(self, tmp_path):
        X, Y, sld = _spilled_problem(tmp_path)
        assert sld.data.is_shard_backed and sld.labels.is_shard_backed
        assert sld.data.n == X.shape[0]
        np.testing.assert_array_equal(sld.data.to_numpy(), X)
        np.testing.assert_array_equal(sld.labels.to_numpy(), Y)

    def test_csv_dir_to_disk_shards_roundtrip_fit(self, tmp_path):
        # CSV directory -> disk shards ONE FILE AT A TIME -> streamed fit,
        # with a shard_rows that divides neither any file nor the total.
        rng = np.random.default_rng(1)
        n, d, num_classes = 541, 12, 4
        X = rng.normal(size=(n, d))
        labels = rng.integers(0, num_classes, size=n)
        csv_dir = tmp_path / "csv"
        csv_dir.mkdir()
        splits = [0, 200, 437, n]  # ragged files
        for i in range(3):
            lo, hi = splits[i], splits[i + 1]
            with open(csv_dir / f"part{i}.csv", "w") as f:
                for r in range(lo, hi):
                    f.write(
                        ",".join([str(labels[r])]
                                 + [f"{v:.6f}" for v in X[r]]) + "\n"
                    )
        (csv_dir / "part3_empty.csv").touch()  # _SUCCESS-marker semantics

        sld = csv_to_disk_shards(
            str(csv_dir), str(tmp_path / "spill"), shard_rows=128,
            tiles_per_segment=2, num_classes=num_classes,
        )
        assert sld.data.n == n
        X_back = sld.data.to_numpy()
        np.testing.assert_allclose(X_back, X.astype(np.float32), atol=1e-5)
        Y_expect = 2.0 * np.eye(num_classes, dtype=np.float32)[labels] - 1.0
        np.testing.assert_array_equal(sld.labels.to_numpy(), Y_expect)

        # Round trip THROUGH a fit: disk-tier solve equals resident solve.
        choice = StreamingLeastSquaresChoice(
            num_iter=2, lam=1e-2, block_size_hint=12
        )
        m_disk = choice.fit(sld.data, sld.labels)
        m_res = choice.fit(
            Dataset.of(X.astype(np.float32)), Dataset.of(Y_expect)
        )
        p_d = np.asarray(
            m_disk.batch_apply(Dataset.of(X.astype(np.float32))).array
        )
        p_r = np.asarray(
            m_res.batch_apply(Dataset.of(X.astype(np.float32))).array
        )
        np.testing.assert_allclose(p_d, p_r, atol=5e-4, rtol=5e-4)

    def test_csv_spill_preserves_float_labels(self, tmp_path):
        # num_classes=None: continuous targets must survive the spill as
        # floats (truncating to int would corrupt every downstream fit).
        rng = np.random.default_rng(5)
        n, d = 40, 3
        X = rng.normal(size=(n, d))
        y = rng.uniform(0.1, 2.0, size=n)
        csv = tmp_path / "reg.csv"
        with open(csv, "w") as f:
            for r in range(n):
                f.write(
                    ",".join([f"{y[r]:.6f}"] + [f"{v:.6f}" for v in X[r]])
                    + "\n"
                )
        sld = csv_to_disk_shards(
            str(csv), str(tmp_path / "regspill"), shard_rows=16
        )
        np.testing.assert_allclose(
            sld.labels.to_numpy().ravel(), y.astype(np.float32), atol=1e-5
        )

    def test_writer_overshoot_capacity_records_true_rows(self, tmp_path):
        w = DiskDenseShardWriter(
            str(tmp_path / "w"), capacity_rows=1000, d_in=4, k=1,
            tile_rows=64,
        )
        rng = np.random.default_rng(2)
        blocks = [rng.normal(size=(m, 4)).astype(np.float32)
                  for m in (100, 37, 240)]
        for b in blocks:
            w.append(b, np.ones((b.shape[0], 1), np.float32))
        shards = w.close()
        assert shards.n_true == 377
        assert shards.num_tiles == -(-377 // 64)
        np.testing.assert_allclose(
            shards.as_source().materialize()[0], np.concatenate(blocks)
        )


class TestCapacitySelection:
    def _sample(self, tmp_path, n=1000, d=24, k=3):
        X, Y, sld = _spilled_problem(tmp_path, n=n, d=d, k=k)
        return X, Y, sld

    def test_over_host_budget_routes_to_disk_tier(self, tmp_path):
        X, Y, sld = self._sample(tmp_path)
        # Host budget below the raw dataset: every resident candidate
        # (including non-shard streaming) is host-infeasible; only the
        # disk tier survives.
        est = LeastSquaresEstimator(lam=0.1, host_budget_bytes=16 << 10)
        from keystone_tpu.workflow.rules import _collect_samples
        from keystone_tpu.workflow.graph import Graph
        from keystone_tpu.workflow.operators import DatasetOperator

        g = Graph()
        g, dn = g.add_node(DatasetOperator(sld.data), [])
        g, ln = g.add_node(DatasetOperator(sld.labels), [])
        g, en = g.add_node(est, [dn, ln])
        g, _ = g.add_sink(en)
        samples = _collect_samples(g, [en], samples_per_shard=3)
        s, ls = samples[en]
        assert getattr(s, "shard_backed", False)
        assert s.total_n == X.shape[0]
        chosen = est.optimize(s, ls)
        assert isinstance(chosen, StreamingLeastSquaresChoice)
        assert chosen.data_is_shard_backed

    def test_under_host_budget_keeps_resident_solver(self, tmp_path):
        X, Y, sld = self._sample(tmp_path)
        est = LeastSquaresEstimator(lam=0.1, host_budget_bytes=1 << 30)
        from keystone_tpu.workflow.rules import _collect_samples
        from keystone_tpu.workflow.graph import Graph
        from keystone_tpu.workflow.operators import DatasetOperator

        g = Graph()
        g, dn = g.add_node(DatasetOperator(sld.data), [])
        g, ln = g.add_node(DatasetOperator(sld.labels), [])
        g, en = g.add_node(est, [dn, ln])
        g, _ = g.add_sink(en)
        samples = _collect_samples(g, [en], samples_per_shard=3)
        s, ls = samples[en]
        chosen = est.optimize(s, ls)
        assert not isinstance(chosen, StreamingLeastSquaresChoice)

    def test_shard_backed_pricing_matches_gram_fold_execution(self):
        # The shard-backed fit ALWAYS runs the gram fold (fit_source), so
        # its capacity model must carry the 8d^2 Gramian stash even where
        # _gram_tier_ok would pick the block tier — otherwise the
        # selector admits a fold that OOMs allocating G.
        choice = StreamingLeastSquaresChoice(num_iter=2, lam=1e-2)
        choice.data_is_shard_backed = True
        choice.shard_segment_bytes = 1 << 20
        choice.budget_bytes = 1 << 30  # 8d^2 at d=60k >> budget
        d = 60_000
        rb = choice.resident_bytes(10_000_000, d, 4, 1.0, 1)
        assert rb >= 8.0 * d * d
        # ...and no term scales with n: disk-tier residency is n-free.
        assert rb == choice.resident_bytes(10, d, 4, 1.0, 1)

    def test_host_cut_applies_to_plain_resident_data_too(self):
        # A NON-shard-backed dataset past the host budget has no disk
        # path: nothing is host-feasible and the selector falls back to
        # least-resident rather than pretending a resident solve fits.
        rng = np.random.default_rng(3)
        est = LeastSquaresEstimator(
            lam=0.1, hbm_bytes=8 << 30, host_budget_bytes=1 << 20
        )
        s = Dataset.of(rng.normal(size=(24, 512)).astype(np.float32))
        s.total_n = 10_000_000
        s.source_row_bytes = 2048.0
        ls = Dataset.of(rng.normal(size=(24, 4)).astype(np.float32))
        chosen = est.optimize(s, ls)  # warning path, still returns a plan
        assert chosen is not None


class TestOutOfCorePipelineFit:
    def test_pipeline_fit_over_host_budget_no_flag(self, tmp_path):
        """The acceptance path: Pipeline.fit on a shard-backed dataset
        whose resident size exceeds the (forced) host budget — the
        selector picks the streaming tier, the optimizer binds the
        featurizer, and the fit folds prefetched disk segments; result
        matches the explicit resident bank fit within streaming parity
        tolerances."""
        PipelineEnv.get_or_create().reset()
        rng = np.random.default_rng(0)
        n, d_in, d_feat, k = 4096, 16, 256, 4
        X = rng.normal(size=(n, d_in)).astype(np.float32)
        Y = rng.normal(size=(n, k)).astype(np.float32)
        sld = LabeledData(X, Y).to_disk_shards(
            str(tmp_path / "sh"), shard_rows=384, tiles_per_segment=2
        )

        crf = CosineRandomFeatures(d_in, d_feat, 0.2, seed=1)
        auto = LeastSquaresEstimator(lam=0.1, host_budget_bytes=64 << 10)
        p = crf.to_pipeline().and_then(auto, sld.data, sld.labels)
        res = p.apply(Dataset.of(X[:256]))
        preds = np.asarray(res.get().array)

        og = res.executor.optimized_graph
        labels_g = [
            str(getattr(op, "label", type(op).__name__))
            for op in og.operators.values()
        ]
        assert any("StreamedFit" in l for l in labels_g), labels_g

        choice = auto._streaming_choice
        assert choice.data_is_shard_backed
        ref = choice.build_estimator(
            CosineBankFeaturize(crf.W, crf.b), d_feat
        ).fit(Dataset.of(X), Dataset.of(Y))
        ref_preds = np.asarray(ref.batch_apply(Dataset.of(X[:256])).array)
        np.testing.assert_allclose(preds, ref_preds, atol=2e-3, rtol=2e-3)

        # fit() (the serializable-pipeline route) works on the same graph.
        fitted = p.fit()
        preds2 = np.asarray(fitted.apply(Dataset.of(X[:256])).array)
        np.testing.assert_allclose(preds2, ref_preds, atol=2e-3, rtol=2e-3)

    def test_direct_choice_fit_from_shards_matches_resident(self, tmp_path):
        X, Y, sld = _spilled_problem(tmp_path, n=900, d=32, k=3)
        choice = StreamingLeastSquaresChoice(
            num_iter=2, lam=1e-2, block_size_hint=16
        )
        m_disk = choice.fit(sld.data, sld.labels)
        m_res = choice.fit(Dataset.of(X), Dataset.of(Y))
        p_d = np.asarray(m_disk.batch_apply(Dataset.of(X)).array)
        p_r = np.asarray(m_res.batch_apply(Dataset.of(X)).array)
        np.testing.assert_allclose(p_d, p_r, atol=5e-4, rtol=5e-4)

    def test_mismatched_labels_against_paired_source_raise(self, tmp_path):
        # A triple-delivering source embeds its own labels: unrelated
        # labels must raise, not be silently ignored (the model would
        # otherwise train on the embedded Y with no error).
        from keystone_tpu.data.shards import DiskDenseShards

        X, Y, sld = _spilled_problem(tmp_path, n=500, d=8, k=2)
        paired = DiskDenseShards(
            str(tmp_path / "shards")
        ).as_source()
        data = Dataset.from_shards(paired)
        other = np.zeros((500, 2), np.float32)
        choice = StreamingLeastSquaresChoice(num_iter=1, lam=1e-2)
        with pytest.raises(ValueError, match="embeds its own labels"):
            choice.fit(data, Dataset.of(other))
        # The matching view of the same shards is accepted.
        m = choice.fit(data, sld.labels)
        assert m is not None

    def test_label_view_loads_only_labels(self, tmp_path, monkeypatch):
        # The cost-model sampler loads label segments: the label view
        # must never pay the (much wider) row read.
        X, Y, sld = _spilled_problem(tmp_path, n=500, d=8, k=2)
        view = sld.labels.shard_source
        monkeypatch.setattr(
            type(view.paired.shards), "segment_source_x",
            lambda self, s: (_ for _ in ()).throw(
                AssertionError("label view read the row file")
            ),
        )
        seg = view.load(0)
        assert seg.shape[-1] == 2
        np.testing.assert_array_equal(view.materialize(), Y)

    def test_resident_labels_pair_with_shard_backed_rows(self, tmp_path):
        # Labels usually fit host RAM even when rows don't: a resident
        # labels Dataset slices per segment against shard-backed rows.
        X, Y, sld = _spilled_problem(tmp_path, n=700, d=16, k=2)
        choice = StreamingLeastSquaresChoice(
            num_iter=2, lam=1e-2, block_size_hint=16
        )
        m_mix = choice.fit(sld.data, Dataset.of(Y))
        m_disk = choice.fit(sld.data, sld.labels)
        p_m = np.asarray(m_mix.batch_apply(Dataset.of(X)).array)
        p_d = np.asarray(m_disk.batch_apply(Dataset.of(X)).array)
        np.testing.assert_array_equal(p_m, p_d)

    def test_block_streamed_accepts_shard_backed(self, tmp_path, monkeypatch):
        # BlockStreamedLeastSquares accepts a ShardSource by materializing
        # (its residual sweep re-featurizes X every block step, so raw
        # rows must be device-resident). The mesh program itself is
        # exercised by the mesh suite; here we pin that the shard-backed
        # path hands it EXACTLY the rows the resident path gets.
        from keystone_tpu.ops.learning import streaming_ls
        from keystone_tpu.parallel import streaming as streaming_mod

        X, Y, sld = _spilled_problem(tmp_path, n=700, d=16, k=2)
        rng = np.random.default_rng(4)
        d_feat = 64
        bank = CosineBankFeaturize(
            rng.normal(size=(d_feat, 16)).astype(np.float32) * 0.3,
            rng.uniform(0, 6, d_feat).astype(np.float32),
        )
        est = BlockStreamedLeastSquares(
            bank, d_feat=d_feat, block_size=16, num_iter=2, lam=1e-2
        )
        seen = []

        def spy(X_in, Y_in, Wrf, brf, **kw):
            seen.append((np.asarray(X_in), np.asarray(Y_in)))
            return (
                jnp.zeros((4, 16, 2)), jnp.zeros(d_feat), jnp.zeros(2)
            )

        monkeypatch.setattr(
            streaming_mod, "streaming_block_bcd_mesh", spy
        )
        est.fit(sld.data, sld.labels)
        est.fit(Dataset.of(X), Dataset.of(Y))
        np.testing.assert_array_equal(seen[0][0], seen[1][0])
        np.testing.assert_array_equal(seen[0][1], seen[1][1])
