"""Static plan verifier (ISSUE 6 tentpole): every seeded violation class
is caught with a report naming the offending node; the bundled pipelines
dry-run with ZERO findings; fit / optimizer / export all run the
verifier by default and the env knob disables it; runtime node failures
carry the same coordinates as verifier reports."""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.data import Dataset
from keystone_tpu.ops.stats import CosineRandomFeatures, LinearRectifier, RandomSignNode
from keystone_tpu.ops.util import Cacher, MaxClassifier
from keystone_tpu.workflow import (
    Graph,
    LambdaTransformer,
    PipelineDataset,
    PlanVerificationError,
    SourceId,
    Transformer,
    verify_graph,
)
from keystone_tpu.workflow.pipeline import Estimator, LabelEstimator
from keystone_tpu.workflow.operators import DatasetOperator
from keystone_tpu.workflow.verify import (
    CACHE_SPLITS_FUSION,
    DTYPE_DRIFT,
    ESTIMATOR_IN_APPLY,
    GATHER_MISMATCH,
    HOST_SIGNATURE_MISMATCH,
    SHAPE_MISMATCH,
    UNDECLARED_SIGNATURE,
    ArraySig,
    HostSig,
    verification_mode,
)


class _IdentityFit(Transformer):
    def apply(self, x):
        return x

    def _batch_fn(self, X):
        return X

    def device_fn(self):
        return self._batch_fn


class _MeanEstimator(LabelEstimator):
    """Minimal estimator: fits a bias, applies identity+bias."""

    def fit(self, data, labels):
        return _IdentityFit()


class _UnaryMeanEstimator(Estimator):
    def fit(self, data):
        return _IdentityFit()


class _CastsToBf16(Transformer):
    """Seeded dtype-drift violation: silently narrows f32 -> bf16."""

    def apply(self, x):
        return jnp.asarray(x, jnp.bfloat16)

    def _batch_fn(self, X):
        return X.astype(jnp.bfloat16)

    def device_fn(self):
        return self._batch_fn


def _data(n=4, d=5, dtype=np.float32):
    return Dataset(np.zeros((n, d), dtype))


def _labels(n=4, k=3):
    return Dataset(np.zeros((n, k), np.float32))


class TestSeededViolations:
    def test_shape_mismatch_names_node(self):
        # 16 random features over an 8-wide input, fed a d=5 dataset.
        rf = CosineRandomFeatures(8, 16, 1.0, seed=0)
        applied = rf.to_pipeline().apply(PipelineDataset.of(_data(d=5)))
        report = verify_graph(applied.executor.graph)
        findings = report.by_code(SHAPE_MISMATCH)
        assert len(findings) == 1
        f = findings[0]
        assert f.operator == "CosineRandomFeaturesModel"
        assert f.node in applied.executor.graph.nodes
        assert f.severity == "error"

    def test_dtype_drift_is_reported(self):
        chain = RandomSignNode.create(5).and_then(_CastsToBf16()).and_then(
            LinearRectifier()
        )
        applied = chain.apply(PipelineDataset.of(_data(d=5)))
        report = verify_graph(applied.executor.graph)
        drift = report.by_code(DTYPE_DRIFT)
        assert len(drift) == 1
        assert drift[0].operator == "_CastsToBf16"
        assert "bfloat16" in drift[0].message
        # Drift is warning-severity: it reports, it does not reject.
        assert not report.errors

    def test_declared_dtype_change_is_silent(self):
        class Declared(_CastsToBf16):
            declares_dtype_change = True

        applied = (
            RandomSignNode.create(5).and_then(Declared())
        ).apply(PipelineDataset.of(_data(d=5)))
        assert not verify_graph(applied.executor.graph).by_code(DTYPE_DRIFT)

    def test_estimator_output_consumed_as_data(self):
        g = Graph()
        g, data = g.add_node(DatasetOperator(_data()), [])
        g, est = g.add_node(_UnaryMeanEstimator(), [data])
        # A transformer eating the ESTIMATOR output as if it were data.
        g, bad = g.add_node(MaxClassifier(), [est])
        g, _ = g.add_sink(bad)
        report = verify_graph(g)
        leaks = report.by_code(ESTIMATOR_IN_APPLY)
        assert len(leaks) == 1
        assert leaks[0].node == bad
        assert leaks[0].severity == "error"

    def test_cache_cut_splitting_fusable_chain(self):
        chain = (
            RandomSignNode.create(5)
            .and_then(Cacher())
            .and_then(LinearRectifier())
        )
        applied = chain.apply(PipelineDataset.of(_data(d=5)))
        report = verify_graph(applied.executor.graph)
        cuts = report.by_code(CACHE_SPLITS_FUSION)
        assert len(cuts) == 1
        assert cuts[0].operator == "Cacher"
        assert "RandomSignNode" in cuts[0].message
        assert "LinearRectifier" in cuts[0].message

    def test_cache_after_multi_consumer_node_is_clean(self):
        """The dependency feeds a SECOND consumer besides the cacher: it
        is a materialization point in the fused plan already
        (StageFusionRule only chains single-consumer links), so the
        cache cut is legitimate — the check must agree with the
        authoritative fusion.cache_would_split_fusion predicate."""
        g = Graph()
        g, data = g.add_node(DatasetOperator(_data(d=5)), [])
        g, d = g.add_node(RandomSignNode.create(5), [data])
        g, cache = g.add_node(Cacher(), [d])
        g, b = g.add_node(LinearRectifier(), [cache])
        g, other = g.add_node(MaxClassifier(), [d])  # second consumer of d
        g, _ = g.add_sink(b)
        g, _ = g.add_sink(other)
        assert not verify_graph(g).by_code(CACHE_SPLITS_FUSION)

    def test_cache_on_fusion_boundary_is_clean(self):
        # A cache AFTER the full device chain (feeding only the sink)
        # sits on a materialization boundary — no finding.
        chain = RandomSignNode.create(5).and_then(LinearRectifier()).and_then(
            Cacher()
        )
        applied = chain.apply(PipelineDataset.of(_data(d=5)))
        assert not verify_graph(applied.executor.graph).by_code(
            CACHE_SPLITS_FUSION
        )

    def test_undeclared_host_op_strict(self):
        host_data = Dataset(["a b", "c d"])
        chain = LambdaTransformer(lambda s: s.split())
        applied = chain.to_pipeline().apply(PipelineDataset.of(host_data))
        strict = verify_graph(applied.executor.graph, strict=True)
        undeclared = strict.by_code(UNDECLARED_SIGNATURE)
        assert len(undeclared) == 1
        assert undeclared[0].operator.startswith("Lambda")
        # Default mode: unknown propagation, no finding.
        assert not verify_graph(applied.executor.graph).findings

    def test_host_kind_mismatch(self):
        from keystone_tpu.ops.nlp import NGramsFeaturizer, Trim

        chain = Trim().and_then(NGramsFeaturizer([1, 2]))
        applied = chain.apply(PipelineDataset.of(Dataset(["doc one"])))
        report = verify_graph(applied.executor.graph)
        bad = report.by_code(HOST_SIGNATURE_MISMATCH)
        assert len(bad) == 1
        assert "tokens" in bad[0].message

    def test_estimator_input_size_mismatch(self):
        pipe = _MeanEstimator().with_data(_data(n=4), _labels(n=6))
        report = verify_graph(pipe.executor.graph)
        sizes = report.by_code(GATHER_MISMATCH)
        assert len(sizes) == 1
        assert "4" in sizes[0].message and "6" in sizes[0].message


class TestDryRunNoFalsePositives:
    def test_all_bundled_pipelines_verify_clean_strict(self):
        from keystone_tpu.tools.dryrun import BUILDERS, dryrun

        reports = dryrun(strict=True)
        assert set(reports) == set(BUILDERS) and len(reports) == 5
        for name, report in reports.items():
            assert not report.findings, (
                f"{name}: false positives: "
                + "; ".join(str(f) for f in report.findings)
            )
            # The interpretation actually propagated signatures (the
            # clean report is not an everything-was-unknown vacuity).
            assert len(report.sigs) > 5, name


def _bad_fit_pipeline():
    """16 cosine features over 8 inputs, composed on d=5 training data:
    the estimator fit would crash mid-GEMM at runtime."""
    from keystone_tpu.ops.learning.linear import LinearMapEstimator

    rf = CosineRandomFeatures(8, 16, 1.0, seed=0)
    return rf.and_then(LinearMapEstimator(lam=1.0), _data(d=5), _labels())


class TestPrepassIntegration:
    def test_fit_rejects_invalid_plan(self):
        with pytest.raises(PlanVerificationError) as exc:
            _bad_fit_pipeline().fit()
        assert "shape-mismatch" in str(exc.value)
        assert "CosineRandomFeaturesModel" in str(exc.value)

    def test_optimizer_rejects_invalid_plan(self):
        from keystone_tpu.workflow.optimizer import DefaultOptimizer

        pipe = _bad_fit_pipeline()
        with pytest.raises(PlanVerificationError):
            DefaultOptimizer().execute(pipe.executor.graph, {})

    def test_apply_rejects_invalid_plan(self):
        rf = CosineRandomFeatures(8, 16, 1.0, seed=0)
        result = rf.to_pipeline().apply(PipelineDataset.of(_data(d=5)))
        with pytest.raises(PlanVerificationError):
            result.get()

    def test_env_knob_disables(self, monkeypatch):
        monkeypatch.setenv("KEYSTONE_VERIFY", "off")
        assert verification_mode() == "off"
        # The invalid plan now sails past the pre-pass and fails at
        # RUNTIME instead (some shape error from the actual execution).
        with pytest.raises(Exception) as exc:
            _bad_fit_pipeline().fit()
        assert not isinstance(exc.value, PlanVerificationError)

    def test_env_knob_strict(self, monkeypatch):
        monkeypatch.setenv("KEYSTONE_VERIFY", "strict")
        assert verification_mode() == "strict"
        monkeypatch.setenv("KEYSTONE_VERIFY", "on")
        assert verification_mode() == "on"

    def test_export_rejects_wrong_example_shape(self):
        rf = CosineRandomFeatures(8, 16, 1.0, seed=0)
        fitted = rf.to_pipeline().fit()
        from keystone_tpu.serving.export import export_plan

        with pytest.raises(PlanVerificationError):
            export_plan(fitted, np.zeros(5, np.float32), precompile=False)
        # Correct example shape exports fine.
        plan = export_plan(fitted, np.zeros(8, np.float32), precompile=False)
        assert plan.compiled

    def test_export_estimator_leak_reported(self):
        from keystone_tpu.workflow.verify import verify_apply_graph

        g = Graph()
        g, data = g.add_node(DatasetOperator(_data()), [])
        g, est = g.add_node(_UnaryMeanEstimator(), [data])
        g, sink = g.add_sink(est)
        g, src = g.add_source()
        with pytest.raises(PlanVerificationError) as exc:
            verify_apply_graph(g, src, sink)
        assert "estimator-in-apply" in str(exc.value)


class _Boom(Transformer):
    def apply(self, x):
        raise ValueError("boom inside node")

    def batch_apply(self, data):
        raise ValueError("boom inside node")


class TestRuntimeErrorCoordinates:
    def test_executor_failure_names_node_and_inputs(self):
        chain = RandomSignNode.create(5).and_then(_Boom())
        result = chain.apply(PipelineDataset.of(_data(d=5)))
        with pytest.raises(ValueError) as exc:
            result.get()
        msg = str(exc.value)
        assert "boom inside node" in msg
        assert "keystone node" in msg
        assert "_Boom" in msg
        assert "Node(" in msg
        # Inferred input signature of the failing node's dep is cited.
        assert "f[4,5]" in msg

    def test_annotation_applies_once_at_deepest_node(self):
        chain = RandomSignNode.create(5).and_then(_Boom()).and_then(
            LinearRectifier()
        )
        result = chain.apply(PipelineDataset.of(_data(d=5)))
        with pytest.raises(ValueError) as exc:
            result.get()
        assert str(exc.value).count("keystone node") == 1

    def test_fitted_pipeline_failure_names_node(self):
        fitted = _Boom().to_pipeline().fit()
        with pytest.raises(ValueError) as exc:
            fitted.apply(_data(d=5))
        assert "keystone node" in str(exc.value)
        assert "_Boom" in str(exc.value)

    def test_exception_type_is_preserved(self):
        class Custom(Exception):
            pass

        class RaisesCustom(Transformer):
            def batch_apply(self, data):
                raise Custom("custom")

            def apply(self, x):
                raise Custom("custom")

        result = RaisesCustom().to_pipeline().apply(
            PipelineDataset.of(_data(d=5))
        )
        with pytest.raises(Custom):
            result.get()


class TestSignatureHelpers:
    def test_array_sig_describe(self):
        assert ArraySig((None, 4), "float32").describe() == "batch f[?,4]:float32"
        assert HostSig("tokens").describe() == "host[tokens]"

    def test_signature_of_dataset_forms(self):
        from keystone_tpu.workflow.verify import signature_of_value

        s = signature_of_value(_data(n=3, d=7))
        assert isinstance(s, ArraySig) and s.shape == (3, 7) and s.n == 3
        h = signature_of_value(Dataset(["a", "b"]))
        assert isinstance(h, HostSig) and h.kind == "str" and h.n == 2
        sp = signature_of_value(Dataset(
            {"indices": np.zeros((2, 3), np.int32),
             "values": np.zeros((2, 3), np.float32)}, n=2
        ))
        assert isinstance(sp, HostSig) and sp.kind == "sparse"
