"""More exact reference-suite ports: ClassLabelIndicatorsSuite,
MaxClassifierSuite, RandomSignNodeSuite, PaddedFFTSuite (R-derived goldens),
TermFrequencySuite, CoreNLPFeatureExtractorSuite (lemmatization + n-gram
structure; the NER test is out of scope — our extractor lemmatizes tokens
but does not run a named-entity recognizer)."""

import numpy as np
import pytest

from keystone_tpu.ops.nlp import CoreNLPFeatureExtractor
from keystone_tpu.ops.stats import PaddedFFT, RandomSignNode, TermFrequency
from keystone_tpu.ops.util import (
    ClassLabelIndicatorsFromIntArrayLabels,
    ClassLabelIndicatorsFromIntLabels,
    MaxClassifier,
)


class TestClassLabelIndicators:
    def test_single_label_indicators(self):
        """ClassLabelIndicatorsSuite 'single label indicators'."""
        with pytest.raises(ValueError):
            ClassLabelIndicatorsFromIntLabels(0)
        with pytest.raises(ValueError):
            ClassLabelIndicatorsFromIntLabels(1)
        five = ClassLabelIndicatorsFromIntLabels(5)
        np.testing.assert_array_equal(
            np.asarray(five.apply(2)), [-1.0, -1.0, 1.0, -1.0, -1.0]
        )

    def test_multi_label_indicators(self):
        """'multiple label indicators without validation'."""
        with pytest.raises(ValueError):
            ClassLabelIndicatorsFromIntArrayLabels(0)
        with pytest.raises(ValueError):
            ClassLabelIndicatorsFromIntArrayLabels(1)
        five = ClassLabelIndicatorsFromIntArrayLabels(5)
        np.testing.assert_array_equal(
            np.asarray(five.apply([2, 1])), [-1.0, 1.0, 1.0, -1.0, -1.0]
        )
        with pytest.raises(ValueError):
            five.apply([4, 6])
        # Unchecked mode: negative indices wrap — the reference's documented
        # "weird behavior" for out-of-contract input.
        unchecked = ClassLabelIndicatorsFromIntArrayLabels(5, valid_check=False)
        np.testing.assert_array_equal(
            np.asarray(unchecked.apply([-1, 2])), [-1.0, -1.0, 1.0, -1.0, 1.0]
        )


class TestMaxClassifier:
    def test_exact_argmax(self):
        """MaxClassifierSuite."""
        assert int(MaxClassifier().apply(np.array([-10.0, 42.4, 335.23, -43.0]))) == 2
        assert int(MaxClassifier().apply(np.array([-1.7976931348623157e308]))) == 0
        assert int(MaxClassifier().apply(np.array([3.0, -23.2, 2.99]))) == 0


class TestRandomSignNode:
    def test_fixed_signs(self):
        """RandomSignNodeSuite 'RandomSignNode'."""
        node = RandomSignNode(np.array([1.0, -1.0, 1.0]))
        np.testing.assert_array_equal(
            np.asarray(node.apply(np.array([1.0, 2.0, 3.0]))), [1.0, -2.0, 3.0]
        )

    def test_create_draws_signs(self):
        """'RandomSignNode.create': every element is ±1."""
        node = RandomSignNode.create(1000, seed=0)
        signs = np.asarray(node.signs)
        assert np.all((signs == 1.0) | (signs == -1.0))


class TestPaddedFFT:
    def test_r_golden_values(self):
        """PaddedFFTSuite: length-100 inputs pad to 128; expected real parts
        from R (Re(fft(...))) — the reference's external golden."""
        ones = np.zeros(100)
        twos = np.zeros(100)
        ones[0] = 1.0
        twos[2] = 1.0

        fft = PaddedFFT()
        twosout = np.asarray(fft.apply(twos))
        onesout = np.asarray(fft.apply(ones))

        assert twosout.shape == (64,)
        # Re(fft(c(0, 0, 1, rep(0, 125))))
        assert abs(twosout[0] - 1.0) < 1e-8
        assert abs(twosout[16] - 0.0) < 1e-8
        assert abs(twosout[32] - (-1.0)) < 1e-8
        assert abs(twosout[48] - 0.0) < 1e-8
        # Re(fft(c(1, rep(0, 127)))) == 1 everywhere
        np.testing.assert_allclose(onesout, np.ones(64), atol=1e-8)


class TestTermFrequency:
    def test_simple_strings(self):
        out = dict(TermFrequency().apply(["b", "a", "c", "b", "b", "a", "b"]))
        assert out == {"a": 2, "b": 4, "c": 1}

    def test_varying_types(self):
        items = ["b", "a", "c", ("b", "b"), ("b", "b"), 12, 12, "a", "b", 12]
        out = dict(TermFrequency().apply(items))
        assert out == {"a": 2, "b": 2, "c": 1, ("b", "b"): 2, 12: 3}

    def test_log_weighting(self):
        out = dict(
            TermFrequency(lambda x: np.log(x + 1)).apply(
                ["b", "a", "c", "b", "b", "a", "b"]
            )
        )
        assert abs(out["a"] - np.log(3)) < 1e-12
        assert abs(out["b"] - np.log(5)) < 1e-12
        assert abs(out["c"] - np.log(2)) < 1e-12


class TestCoreNLPFeatureExtractor:
    def test_lemmatization(self):
        """CoreNLPFeatureExtractorSuite 'lemmatization': the exact CoreNLP
        outputs the reference asserts."""
        grams = CoreNLPFeatureExtractor([1, 2, 3]).apply(
            "jumping snakes lakes oceans hunted"
        )
        unigrams = {g[0] for g in grams if len(g) == 1}
        for lemma in ("jump", "snake", "lake", "ocean", "hunt"):
            assert lemma in unigrams, lemma
        for raw in ("jumping", "snakes", "lakes", "oceans", "hunted"):
            assert raw not in unigrams, raw

    def test_one_two_three_grams(self):
        """'1-2-3-grams' structural contract."""
        grams = set(
            tuple(g) for g in CoreNLPFeatureExtractor([1, 2, 3]).apply("a b c d")
        )
        for expected in [
            ("a",), ("b",), ("c",), ("d",),
            ("a", "b"), ("b", "c"), ("c", "d"),
            ("a", "b", "c"), ("b", "c", "d"),
        ]:
            assert expected in grams, expected
