"""Full ImageNetSiftLcsFV end-to-end on real committed JPEGs.

The companion of tests/test_voc_end_to_end_real.py for the reference's
largest pipeline (ImageNetSiftLcsFV.scala:33-135): real JPEG decode → two
featurization branches (dense SIFT + LCS), each PCA → GMM Fisher vector →
normalize → gather/combine → block *weighted* least squares → top-k.

Offline-feasible real data: a two-synset ImageNet-layout dataset assembled
from the reference's committed archives — the real `n15075141.tar` synset
(5 JPEGs) plus a second synset re-tarred from `voctest.tar`'s real VOC
JPEGs (raw bytes unchanged, entries renamed into synset-directory layout,
as ImageNetLoader only cares about the `classdir/file` convention,
ImageNetLoader.scala:12-39). Two visually distinct photo sources → a real
two-class separation problem through the full image stack.
"""

import os
import tarfile

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # golden/e2e/multihost tier


from _reference import RESOURCES, needs_reference_fixtures

IMAGES = os.path.join(RESOURCES, "images")


def _build_two_synset_dir(tmp_path):
    data_dir = tmp_path / "imagenet2"
    data_dir.mkdir()
    # Synset 1: the committed archive, verbatim.
    src_tar = os.path.join(IMAGES, "imagenet/n15075141.tar")
    (data_dir / "n15075141.tar").write_bytes(open(src_tar, "rb").read())

    # Synset 2: real VOC JPEGs re-tarred under a synset directory.
    voc_tar = os.path.join(IMAGES, "voc/voctest.tar")
    out_tar = data_dir / "nvoc000000.tar"
    with tarfile.open(voc_tar) as src, tarfile.open(out_tar, "w") as dst:
        for member in src:
            if not member.name.lower().endswith((".jpg", ".jpeg")):
                continue
            blob = src.extractfile(member).read()
            info = tarfile.TarInfo(
                "nvoc000000/" + os.path.basename(member.name)
            )
            info.size = len(blob)
            import io

            dst.addfile(info, io.BytesIO(blob))

    labels = tmp_path / "labels"
    labels.write_text("n15075141 0\nnvoc000000 1\n")
    return str(data_dir), str(labels)


@needs_reference_fixtures
def test_imagenet_sift_lcs_fv_on_real_jpegs(tmp_path):
    for need in ("imagenet/n15075141.tar", "voc/voctest.tar"):
        if not os.path.exists(os.path.join(IMAGES, need)):
            pytest.skip(f"{need} not available")

    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        ImageNetConfig,
        run,
    )

    data_dir, labels_path = _build_two_synset_dir(tmp_path)
    cfg = ImageNetConfig(
        train_location=data_dir,
        train_labels=labels_path,
        test_location=data_dir,
        test_labels=labels_path,
        num_classes=2,
        # Mini capacity: enough to separate 15 real photos in two classes,
        # small enough for CI (full config: pca 64, vocab 16).
        sift_pca_dim=32,
        lcs_pca_dim=32,
        vocab_size=4,
        block_size=1024,
        lam=1e-3,
    )
    _, top1_eval, top5_err = run(cfg)

    # 15 real images (5 ImageNet + 10 VOC), train == test: the full stack
    # must rank its own training images correctly. With 2 classes top-5 is
    # degenerate (always 0); top-1 is the meaningful check.
    assert top5_err == 0.0
    assert top1_eval.total_error <= 0.2, top1_eval.total_error
    cm = np.asarray(top1_eval.confusion)
    assert cm.sum() == 15  # every committed JPEG decoded and classified
