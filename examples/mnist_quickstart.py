"""Quickstart: compose, fit, save, and reload a pipeline end to end.

The analog of the reference's examples/ walkthrough (README.md:14-24 runs
MnistRandomFFT): build the MNIST random-FFT featurizer + block least squares
classifier against synthetic data, evaluate, then round-trip the fitted
pipeline through disk.

Run:  python examples/mnist_quickstart.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.data.loaders import synthetic_mnist
from keystone_tpu.evaluation.metrics import MulticlassClassifierEvaluator
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.ops.stats import LinearRectifier, PaddedFFT, RandomSignNode
from keystone_tpu.ops.util import (
    ClassLabelIndicatorsFromIntLabels,
    MaxClassifier,
    VectorCombiner,
)
from keystone_tpu.workflow import FittedPipeline, Pipeline


def main():
    num_classes, num_ffts = 10, 3
    train = synthetic_mnist(n=2048, seed=0)
    test = synthetic_mnist(n=512, seed=1)
    labels = ClassLabelIndicatorsFromIntLabels(num_classes)(train.labels)

    # numFFTs random-sign FFT branches, gathered and concatenated —
    # the MnistRandomFFT composition (reference: MnistRandomFFT.scala:21-70).
    d = np.asarray(train.data.array).shape[1]
    branches = [
        RandomSignNode.create(d, seed=i).and_then(PaddedFFT()).and_then(LinearRectifier())
        for i in range(num_ffts)
    ]
    pipeline = (
        Pipeline.gather(branches)
        .and_then(VectorCombiner())
        .and_then(
            BlockLeastSquaresEstimator(block_size=512, num_iter=1, lam=1e-3),
            train.data,
            labels,
        )
        .and_then(MaxClassifier())
    )

    evaluator = MulticlassClassifierEvaluator(num_classes)
    test_preds = pipeline.apply(test.data)  # lazy handle, memoized on .get()
    train_eval = evaluator.evaluate(pipeline.apply(train.data), train.labels)
    test_eval = evaluator.evaluate(test_preds, test.labels)
    print(f"train error {100 * train_eval.total_error:.2f}%  "
          f"test error {100 * test_eval.total_error:.2f}%")

    # Fit -> serializable transformer-only pipeline -> disk round trip.
    fitted = pipeline.fit()
    path = os.path.join(tempfile.mkdtemp(), "mnist.pipeline")
    fitted.save(path)
    reloaded = FittedPipeline.load(path)
    preds = reloaded.apply(test.data).to_numpy()
    agree = (preds == test_preds.get().to_numpy()).mean()
    print(f"reloaded pipeline agreement: {100 * agree:.1f}%  (saved to {path})")


if __name__ == "__main__":
    main()
