"""Preemption-safe kernel ridge regression: checkpoint, kill, resume.

TPU pods get preempted; the reference's answer was Spark recomputing from
scratch (its only concession: lineage truncation every 25 blocks,
KernelRidgeRegression.scala:199-203). Here the fused Gauss-Seidel sweep
checkpoints (position, block-weight stack) atomically between compiled
segments, and a fit restarted with the same data and hyperparameters
resumes from the last completed segment — ending in exactly the model an
uninterrupted fit produces.

Run:  python examples/krr_preemption.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.ops.learning.kernel import (
    GaussianKernelGenerator,
    KernelRidgeRegression,
)


def main():
    rng = np.random.default_rng(0)
    n, d, k = 2048, 32, 5
    X = Dataset.of(rng.normal(size=(n, d)).astype(np.float32))
    Y = Dataset.of(rng.normal(size=(n, k)).astype(np.float32))

    ckpt = os.path.join(tempfile.mkdtemp(), "krr.ckpt")

    def make_est():
        return KernelRidgeRegression(
            GaussianKernelGenerator(gamma=0.01),
            lam=0.3,
            block_size=512,
            num_epochs=3,
            checkpoint_path=ckpt,      # <- opt in to mid-solver resume
            checkpoint_every_blocks=4,  # save cadence (block updates)
        )

    # --- simulate a preemption: die right after the first checkpoint save
    real_replace, saves = os.replace, [0]

    def preempting_replace(src, dst):
        real_replace(src, dst)
        # Only count the checkpoint's own saves — other machinery (e.g. the
        # JAX compilation cache) also uses os.replace.
        if str(dst) == ckpt:
            saves[0] += 1
            if saves[0] == 1:
                raise KeyboardInterrupt("simulated preemption")

    os.replace = preempting_replace
    try:
        make_est().fit(X, Y)
    except KeyboardInterrupt:
        print(f"preempted; checkpoint on disk: {os.path.exists(ckpt)}")
    finally:
        os.replace = real_replace

    # --- a fresh process would do exactly this: same config, same data
    model = make_est().fit(X, Y)   # resumes from the checkpoint
    print(f"resumed fit complete; checkpoint removed: {not os.path.exists(ckpt)}")

    # --- the resumed model equals an uninterrupted fit
    reference = KernelRidgeRegression(
        GaussianKernelGenerator(gamma=0.01), lam=0.3, block_size=512,
        num_epochs=3,
    ).fit(X, Y)
    diff = max(
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(model.w_locals, reference.w_locals)
    )
    print(f"max |resumed - uninterrupted| = {diff:.2e}")
    assert diff < 1e-5


if __name__ == "__main__":
    main()
