"""Multichip mesh runner: ``python -m keystone_tpu.tools.multichip``
(wrapped by ``bin/multichip``).

Runs one synthetic padded-COO streamed gram fit TWICE — on a single
device and on a data-parallel mesh (``run_lbfgs_gram_streamed``'s
``mesh=`` path: per-device local folds, ONE psum tree-reduction per
fit) — and reports parity and walls. Two deployment forms:

- **Forced host devices** (``--force-host-devices 8``): the tier-1-safe
  leg — XLA splits the host CPU into N devices, so the mesh *program*
  (sharding, liveness masking, the psum) is exercised with no chips.
  Walls measured this way are NOT device evidence (N ways of one CPU);
  the runner says so rather than printing a fake speedup.
- **Real chips** (default on a TPU backend): the measurement leg — the
  walls are real, the layout decision (``cost.choose_mesh_layout``) is
  recorded as a ``mesh_layout`` CostDecision and stamped with the
  measured mesh wall, so ``bin/calibrate`` joins predicted-vs-measured
  for layouts exactly like solver decisions.

Exit code: 0 when the mesh fit matches the single-device fit within
``--tol``, 1 otherwise (or on setup errors).
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List, Optional, Sequence

__all__ = ["main", "run", "run_scaling"]

# Max |dW| between the 1-device and mesh fits. The forced-host leg is
# the SAME arithmetic scheduled differently (per-device partial folds +
# one tree reduction), so the bound is float-reassociation noise — the
# MULTICHIP_r05 dry-run pinned 3.43e-07 for the streaming leg; the
# default keeps headroom over it for bigger geometries.
DEFAULT_TOL = 5e-5


def _parse_layout(spec: str):
    try:
        p, q = spec.lower().split("x")
        return max(int(p), 1), max(int(q), 1)
    except ValueError:
        raise SystemExit(
            f"--layout {spec!r}: expected '<data>x<model>', e.g. 8x1"
        )


def _synth_coo(args):
    """The runner's synthetic padded-COO problem (ragged rows via dead
    lanes) chunked for the streamed fold."""
    import numpy as np

    n, d, w, k, c = args.n, args.d, args.nnz, args.k, args.chunk
    rng = np.random.default_rng(args.seed)
    idx = rng.integers(0, d, size=(n, w)).astype(np.int32)
    idx[rng.random((n, w)) < 0.2] = -1  # ragged rows: dead lanes
    val = rng.normal(size=(n, w)).astype(np.float32)
    Y = rng.normal(size=(n, k)).astype(np.float32)
    nchunks = -(-n // c)
    pad = nchunks * c - n
    idx_t = np.pad(idx, ((0, pad), (0, 0)), constant_values=-1)
    val_t = np.pad(val, ((0, pad), (0, 0)))
    y_t = np.pad(Y, ((0, pad), (0, 0)))
    return nchunks, (
        idx_t.reshape(nchunks, c, w),
        val_t.reshape(nchunks, c, w),
        y_t.reshape(nchunks, c, k),
    )


def run(args) -> int:
    import jax
    import jax.numpy as jnp

    from keystone_tpu import obs
    from keystone_tpu.ops.learning import cost as cost_mod
    from keystone_tpu.ops.learning.lbfgs import (
        _resident_chunk_fn,
        run_lbfgs_gram_streamed,
    )
    from keystone_tpu.parallel import mesh as mesh_lib

    backend = jax.default_backend()
    avail = len(jax.devices())
    n, d, w, k, c = args.n, args.d, args.nnz, args.k, args.chunk

    if args.layout == "auto":
        (p, q), ref = cost_mod.choose_mesh_layout(
            n, d, k, nnz_per_row=w, num_devices=avail,
        )
        layout_src = "cost.choose_mesh_layout"
    else:
        p, q = _parse_layout(args.layout)
        ref = None
        layout_src = "forced"
    if p * q > avail:
        print(
            f"multichip: layout {p}x{q} needs {p * q} devices, "
            f"{avail} available ({backend})", file=sys.stderr,
        )
        return 1

    nchunks, operands = _synth_coo(args)

    kw = dict(
        lam=args.lam, num_iterations=args.iters, convergence_tol=1e-8,
        n=n, val_dtype=jnp.float32,
    )

    t0 = time.perf_counter()
    W1, loss1 = run_lbfgs_gram_streamed(
        _resident_chunk_fn, nchunks, d, k, operands=operands,
        max_chunks_per_dispatch=args.seg, **kw,
    )
    W1.block_until_ready()
    single_s = time.perf_counter() - t0

    if q > 1:
        mesh = mesh_lib.make_mesh(
            (p, q), (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS),
            devices=jax.devices()[: p * q],
        )
    else:
        mesh = mesh_lib.make_mesh(
            (p,), (mesh_lib.DATA_AXIS,), devices=jax.devices()[:p],
        )
    t0 = time.perf_counter()
    Wm, lossm = run_lbfgs_gram_streamed(
        _resident_chunk_fn, nchunks, d, k, operands=operands,
        max_chunks_per_dispatch=args.seg, mesh=mesh, **kw,
    )
    Wm.block_until_ready()
    mesh_s = time.perf_counter() - t0
    if ref is not None:
        ref.stamp(mesh_s, timing="wall")

    parity = float(jnp.max(jnp.abs(W1 - Wm)))
    ok = parity <= args.tol
    print(f"backend={backend} devices={avail} layout={p}x{q} "
          f"({layout_src})")
    print(f"geometry: n={n} d={d} nnz/row={w} k={k} chunk={c} "
          f"seg={args.seg} iters={args.iters}")
    print(f"single-device wall: {single_s:.3f}s (loss {float(loss1):.6f})")
    print(f"mesh wall:          {mesh_s:.3f}s (loss {float(lossm):.6f})")
    if backend == "cpu":
        # N forced host devices share ONE CPU's cycles: the mesh wall is
        # program-correctness evidence, never a speedup claim.
        print("note: cpu backend — walls are not device evidence "
              "(forced host devices share one CPU); parity is the "
              "result here")
    else:
        print(f"speedup: {single_s / mesh_s:.2f}x "
              f"(num_devices={p * q}, "
              f"single_device_baseline_s={single_s:.3f})")
    print(f"parity max|dW|: {parity:.3e} "
          f"({'OK' if ok else 'FAIL'}, tol {args.tol:.1e})")
    if obs.enabled():
        print("trace: mesh_layout decision + fold.segment device spans "
              "recorded")
    return 0 if ok else 1


def run_scaling(args) -> int:
    """``--scaling``: the same fit at 1/2/4/8 devices (data-parallel
    meshes over device prefixes), each leg warmed then min-of-``--reps``.
    Per-leg walls are split into the fold phase (sum of ``fold.segment``
    span time — the parallel part) and the solve remainder (the ONE psum
    + the replicated L-BFGS-on-G solve — the Amdahl term that bends the
    scaling curve), so the bend is ATTRIBUTED, not guessed. Emits one
    machine-readable ``scaling: {json}`` line (bench.py's
    multichip_timit_scaling row parses it); exit code is the parity
    verdict of every leg against the 1-device fit."""
    import json as _json

    import jax
    import jax.numpy as jnp

    from keystone_tpu import obs
    from keystone_tpu.ops.learning.lbfgs import (
        _resident_chunk_fn,
        run_lbfgs_gram_streamed,
    )
    from keystone_tpu.parallel import mesh as mesh_lib

    backend = jax.default_backend()
    avail = len(jax.devices())
    legs_m = [m for m in (1, 2, 4, 8) if m <= avail]
    nchunks, operands = _synth_coo(args)
    n, d, k = args.n, args.d, args.k
    kw = dict(
        lam=args.lam, num_iterations=args.iters, convergence_tol=1e-8,
        n=n, val_dtype=jnp.float32,
    )
    print(f"backend={backend} devices={avail} scaling legs={legs_m}")
    print(f"geometry: n={n} d={d} nnz/row={args.nnz} k={k} "
          f"chunk={args.chunk} seg={args.seg} iters={args.iters}")

    legs = []
    W_ref = None
    worst_parity = 0.0
    for m in legs_m:
        mesh = None
        if m > 1:
            mesh = mesh_lib.make_mesh(
                (m,), (mesh_lib.DATA_AXIS,), devices=jax.devices()[:m],
            )

        def fit():
            return run_lbfgs_gram_streamed(
                _resident_chunk_fn, nchunks, d, k, operands=operands,
                max_chunks_per_dispatch=args.seg, mesh=mesh, **kw,
            )

        W, _ = fit()  # warm: compile + first execute, untimed
        W.block_until_ready()
        wall = float("inf")
        fold_s = None
        for _ in range(max(args.reps, 1)):
            # In-memory trace per rep (only when the caller isn't already
            # tracing) splits the wall into fold vs solve phases.
            tr = None if obs.enabled() else obs.tracing()
            t0 = time.perf_counter()
            if tr is not None:
                with tr as t:
                    W, _ = fit()
                    W.block_until_ready()
            else:
                W, _ = fit()
                W.block_until_ready()
            rep_wall = time.perf_counter() - t0
            if rep_wall < wall:
                wall = rep_wall
                if tr is not None:
                    fold_s = sum(
                        e.get("dur_us", 0) for e in t.events
                        if e.get("type") == "span"
                        and e.get("name") == "fold.segment"
                    ) / 1e6
        if W_ref is None:
            W_ref = W
        parity = float(jnp.max(jnp.abs(W - W_ref)))
        worst_parity = max(worst_parity, parity)
        leg = {"num_devices": m, "wall_s": round(wall, 4),
               "parity_max_dw": parity}
        if fold_s is not None:
            leg["fold_s"] = round(min(fold_s, wall), 4)
            leg["solve_s"] = round(max(wall - fold_s, 0.0), 4)
        legs.append(leg)
        print(f"  m={m}: wall {wall:.3f}s"
              + (f" (fold {leg['fold_s']:.3f}s, solve+psum "
                 f"{leg['solve_s']:.3f}s)" if fold_s is not None else ""))

    t1 = legs[0]["wall_s"]
    for leg in legs:
        # The scaling-claim audit rule (bench.py _scaling_violations):
        # every speedup/scaling_efficiency claim carries its numeric
        # num_devices and single_device_baseline_s in the SAME dict.
        leg["speedup_vs_single_device"] = round(t1 / leg["wall_s"], 4)
        leg["scaling_efficiency"] = round(
            t1 / leg["wall_s"] / leg["num_devices"], 4,
        )
        leg["single_device_baseline_s"] = t1

    have_phases = all("fold_s" in leg for leg in legs)
    if have_phases:
        bend = {
            "phase": "gram_solve+psum",
            "note": (
                "the fold phase shards across devices; the one psum and "
                "the replicated L-BFGS-on-G solve do not — their share "
                f"grows from {legs[0]['solve_s'] / max(t1, 1e-9):.0%} of "
                f"the 1-device wall to "
                f"{legs[-1]['solve_s'] / max(legs[-1]['wall_s'], 1e-9):.0%}"
                f" at {legs[-1]['num_devices']} devices (Amdahl term)"
            ),
        }
    else:
        bend = {"phase": "unattributed",
                "note": "phase split unavailable (outer tracing active)"}

    device_evidence = backend != "cpu"
    if not device_evidence:
        print("note: cpu backend — walls are not device evidence "
              "(forced host devices share one CPU); parity and the "
              "phase decomposition are the result here")
    ok = worst_parity <= args.tol
    print(f"parity max|dW| (worst leg): {worst_parity:.3e} "
          f"({'OK' if ok else 'FAIL'}, tol {args.tol:.1e})")
    print("scaling: " + _json.dumps({
        "backend": backend, "device_evidence": device_evidence,
        "legs": legs, "bend": bend,
        "geometry": {"n": n, "d": d, "nnz_per_row": args.nnz, "k": k,
                     "chunk": args.chunk, "seg": args.seg,
                     "iters": args.iters},
        "parity_worst_max_dw": worst_parity, "parity_tol": args.tol,
    }))
    return 0 if ok else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        "keystone-multichip", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--layout", default="auto",
                        help="'<data>x<model>' mesh shape, or 'auto' "
                             "(cost.choose_mesh_layout picks and the "
                             "decision is recorded)")
    parser.add_argument("--force-host-devices", type=int, default=0,
                        help="split the host CPU into N XLA devices "
                             "(must run before jax initializes; the "
                             "tier-1-safe parity leg)")
    parser.add_argument("--scaling", action="store_true",
                        help="run the 1/2/4/8-device scaling legs and "
                             "emit a machine-readable 'scaling:' JSON "
                             "line (bench multichip_timit_scaling row)")
    parser.add_argument("--reps", type=int, default=2,
                        help="warm reps per scaling leg (min taken)")
    parser.add_argument("--n", type=int, default=20_000)
    parser.add_argument("--d", type=int, default=256)
    parser.add_argument("--nnz", type=int, default=16,
                        help="active lanes per padded-COO row")
    parser.add_argument("--k", type=int, default=2)
    parser.add_argument("--chunk", type=int, default=512,
                        help="rows per fold chunk")
    parser.add_argument("--seg", type=int, default=4,
                        help="chunks per dispatched fold segment")
    parser.add_argument("--iters", type=int, default=30)
    parser.add_argument("--lam", type=float, default=0.1)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--tol", type=float, default=DEFAULT_TOL)
    parser.add_argument("--trace", default="",
                        help="write a trace directory (mesh_layout "
                             "decision, per-device spans)")
    args = parser.parse_args(list(argv) if argv is not None else None)

    if args.force_host_devices:
        # XLA reads the flag at BACKEND initialization, not at module
        # import — setting it here works as long as nothing has queried
        # jax.devices() yet; the count check below catches the too-late
        # case (an already-initialized single-device backend).
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                f"--xla_force_host_platform_device_count="
                f"{args.force_host_devices} "
                + os.environ.get("XLA_FLAGS", "")
            )
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax

        if len(jax.devices()) < args.force_host_devices:
            print(
                f"multichip: wanted {args.force_host_devices} forced "
                f"host devices but the backend initialized with "
                f"{len(jax.devices())} — set XLA_FLAGS="
                "--xla_force_host_platform_device_count=N before any "
                "jax.devices() call (bin/multichip does)",
                file=sys.stderr,
            )
            return 1

    entry = run_scaling if args.scaling else run
    if args.trace:
        from keystone_tpu import obs

        with obs.tracing(args.trace):
            rc = entry(args)
        print(f"trace written: {args.trace}")
        return rc
    return entry(args)


if __name__ == "__main__":
    sys.exit(main())
