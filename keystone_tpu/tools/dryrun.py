"""Static-verifier dry-runs over every bundled pipeline.

Builds each of the bundled example pipelines (TIMIT, Amazon reviews,
MNIST random-FFT, CIFAR-KRR, newsgroups) at a tiny synthetic geometry —
graph construction only, NOTHING is fitted or compiled — and runs the
plan verifier (:mod:`keystone_tpu.workflow.verify`) in strict mode over
each fit graph. This is the zero-false-positive contract: a verifier
change that starts flagging a known-good pipeline fails here before it
can reject real plans.

Runnable two ways:

  - ``python -m keystone_tpu.tools.dryrun`` (or ``bin/verify-pipelines``)
    prints one line per pipeline and exits non-zero on any finding;
  - ``tests/test_verify.py`` imports :func:`build_pipelines` and asserts
    every report is empty in tier-1.
"""

from __future__ import annotations

import sys
from typing import Callable, Dict, List, Tuple

from keystone_tpu.workflow import Pipeline
from keystone_tpu.workflow.verify import VerifyReport, verify_graph


def _mnist() -> Pipeline:
    from keystone_tpu.data.loaders import synthetic_mnist
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
    from keystone_tpu.ops.util import (
        ClassLabelIndicatorsFromIntLabels,
        MaxClassifier,
    )
    from keystone_tpu.pipelines.mnist_random_fft import (
        NUM_CLASSES,
        MnistRandomFFTConfig,
        build_featurizer,
    )

    config = MnistRandomFFTConfig(synthetic_n=128, num_ffts=2, block_size=512)
    train = synthetic_mnist(config.synthetic_n, seed=0)
    labels = ClassLabelIndicatorsFromIntLabels(NUM_CLASSES)(train.labels)
    return (
        build_featurizer(config)
        .and_then(
            BlockLeastSquaresEstimator(config.block_size, 1, 0.0),
            train.data,
            labels,
        )
        .and_then(MaxClassifier())
    )


def _timit() -> Pipeline:
    from keystone_tpu.data.loaders import synthetic_timit
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
    from keystone_tpu.ops.util import (
        ClassLabelIndicatorsFromIntLabels,
        MaxClassifier,
    )
    from keystone_tpu.pipelines.timit import (
        NUM_CLASSES,
        TimitConfig,
        build_featurizer,
    )

    config = TimitConfig(synthetic_n=128, num_cosines=2, block_size=256,
                         num_epochs=1)
    train = synthetic_timit(config.synthetic_n, seed=0)
    labels = ClassLabelIndicatorsFromIntLabels(NUM_CLASSES)(train.labels)
    return (
        build_featurizer(config)
        .and_then(
            BlockLeastSquaresEstimator(config.block_size, 1, 0.0),
            train.data,
            labels,
        )
        .and_then(MaxClassifier())
    )


def _amazon() -> Pipeline:
    from keystone_tpu.data.loaders import synthetic_documents
    from keystone_tpu.ops.learning.classifiers import (
        LogisticRegressionEstimator,
    )
    from keystone_tpu.ops.sparse import CommonSparseFeatures
    from keystone_tpu.pipelines.amazon_reviews import (
        AmazonReviewsConfig,
        build_featurizer,
    )

    config = AmazonReviewsConfig(synthetic_n=48)
    train = synthetic_documents(config.synthetic_n, 2, seed=0)
    return build_featurizer(config).and_then(
        CommonSparseFeatures(64), train.data
    ).and_then(
        LogisticRegressionEstimator(2, num_iters=2),
        train.data,
        train.labels,
    )


def _newsgroups() -> Pipeline:
    from keystone_tpu.data.loaders import synthetic_documents
    from keystone_tpu.ops.learning.classifiers import NaiveBayesEstimator
    from keystone_tpu.ops.sparse import AllSparseFeatures
    from keystone_tpu.ops.util import MaxClassifier
    from keystone_tpu.pipelines.newsgroups import (
        NewsgroupsConfig,
        build_featurizer,
    )

    config = NewsgroupsConfig(synthetic_n=48, synthetic_classes=4)
    train = synthetic_documents(config.synthetic_n, 4, seed=0)
    return (
        build_featurizer(config)
        .and_then(AllSparseFeatures(), train.data)
        .and_then(NaiveBayesEstimator(4), train.data, train.labels)
        .and_then(MaxClassifier())
    )


def _cifar_krr() -> Pipeline:
    from keystone_tpu.data.loaders import synthetic_cifar
    from keystone_tpu.ops.learning.kernel import (
        GaussianKernelGenerator,
        KernelRidgeRegression,
    )
    from keystone_tpu.ops.stats import StandardScaler
    from keystone_tpu.ops.util import (
        ClassLabelIndicatorsFromIntLabels,
        MaxClassifier,
    )
    from keystone_tpu.pipelines.cifar import (
        NUM_CLASSES,
        CifarConfig,
        _conv_featurizer,
        _sample_whitened_filters,
    )

    config = CifarConfig(synthetic_n=32, num_filters=8, whitener_size=64)
    train = synthetic_cifar(config.synthetic_n, seed=0)
    from keystone_tpu.data import LabeledData

    labeled = LabeledData(train.data, train.labels)
    filters, whitener = _sample_whitened_filters(labeled, config)
    labels = ClassLabelIndicatorsFromIntLabels(NUM_CLASSES)(train.labels)
    featurizer = _conv_featurizer(filters, whitener, config).and_then(
        StandardScaler(), train.data
    )
    return featurizer.and_then(
        KernelRidgeRegression(
            GaussianKernelGenerator(config.kernel_gamma),
            config.lam,
            config.block_size,
            1,
        ),
        train.data,
        labels,
    ).and_then(MaxClassifier())


BUILDERS: Dict[str, Callable[[], Pipeline]] = {
    "timit": _timit,
    "amazon": _amazon,
    "mnist_random_fft": _mnist,
    "cifar_krr": _cifar_krr,
    "newsgroups": _newsgroups,
}


def build_pipelines() -> List[Tuple[str, Pipeline]]:
    """Construct every bundled pipeline at dry-run geometry."""
    return [(name, build()) for name, build in BUILDERS.items()]


def dryrun(strict: bool = True) -> Dict[str, VerifyReport]:
    """Verify every bundled pipeline's fit graph. Returns name→report."""
    return {
        name: verify_graph(pipe.executor.graph, strict=strict)
        for name, pipe in build_pipelines()
    }


def main(argv=None) -> int:
    reports = dryrun(strict=True)
    failed = False
    for name, report in sorted(reports.items()):
        if report.findings:
            failed = True
            print(f"{name}: {len(report.findings)} finding(s)")
            for f in report.findings:
                print(f"  {f}")
        else:
            print(f"{name}: ok ({len(report.sigs)} signatures propagated)")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
