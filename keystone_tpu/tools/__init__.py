"""Developer tooling: the discipline linter (:mod:`.lint`) and the
static-verifier dry-run over the bundled pipelines (:mod:`.dryrun`)."""
