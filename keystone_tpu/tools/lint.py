"""Discipline linter: AST checks encoding the repo's written invariants.

PRs 2–5 introduced hand-rolled thread and fault disciplines that, until
now, lived only in comments and docs — nothing enforced them
mechanically. This module turns each one into an AST rule, runnable as a
CLI (``python -m keystone_tpu.tools.lint [paths...]``) and as a tier-1
test over the whole package (``tests/test_lint.py``):

``jax-off-thread``
    No ``jax``/``jnp`` usage reachable from a background-thread target —
    the ``data/prefetch.py`` / ``serving/batcher.py`` discipline: reader
    threads own disk+numpy ONLY; exactly one thread owns JAX. Covers
    BOTH spawn forms: ``threading.Thread(target=...)`` AND tasks
    submitted to the data-plane runtime's worker pool
    (``data/runtime.py`` — any ``x.submit("<site>", fn, ...)`` whose
    first argument is a string lane name walks ``fn`` exactly like a
    Thread target; a lambda is walked in place). Reachability is
    per-module and depth-limited: the target function plus the local
    / same-class helpers it calls. A function that IS the designated JAX
    owner opts out with a ``# lint: jax-owner-thread`` marker on its
    ``def`` line — there is exactly ONE such designation per worker
    pool (the serving batcher's worker).

``thread-join``
    Every scope (class or function) that ``.start()``s a
    ``threading.Thread`` must also ``.join()`` one on its shutdown path —
    the "close() joins the worker" contract Prefetcher,
    MicroBatchServer, the data-plane runtime's lane pool
    (``data/runtime.py`` — every pooled worker joins on ``close()``),
    and the obs live exporter's publisher + HTTP threads
    (``obs/live.py``) document and test.

``retry-transient``
    ``RetryPolicy(transient=...)`` tuples must never include
    ``ShardCorrupted``: a checksum mismatch is persistent state and
    retrying it re-reads the same bad bytes while hiding the corruption
    (the data/durable.py invariant — ShardCorrupted is deliberately NOT
    an OSError for exactly this reason).

``fault-site``
    Fault-injection site names (``faults.maybe_fail(...)``,
    ``faults.corrupt_array(...)``, ``FaultRule(site=...)``) must exist in
    the ``SITE_*`` registry of :mod:`keystone_tpu.utils.faults` — a typo
    in a site name silently turns a chaos drill into a no-op.

``bench-row``
    Bench result rows must be built through ``make_row`` (which validates
    the timing convention and the roofline-auditability rules); a raw
    ``{"metric": ..., "value": ..., "detail": ...}`` dict literal bypasses
    every convention check.

``metric-name``
    Every :class:`~keystone_tpu.obs.metrics.MetricsRegistry`
    register/lookup site (``*.counter(...)`` / ``*.gauge(...)`` /
    ``*.histogram(...)`` / ``*.bucketed_histogram(...)``) must use a
    dotted name present in the ``METRIC_*`` catalogue of
    :mod:`keystone_tpu.obs.metrics` — parsed, never imported, exactly
    like the fault-site registry. A metric name invented at a call site
    silently forks the dashboard namespace; the catalogue is the one
    place names exist. Covers the live-plane names (``slo.*``,
    ``exporter.*``) the ISSUE-10 exporter publishes.

``mesh-axis-name``
    Mesh axis names at collective / sharding call sites (``psum(...)``,
    ``axis_index(...)``, ``all_gather(...)``, ``PartitionSpec``/``P``
    literals — the strings ``shard_map`` programs shard by) must come
    from the ``DATA_AXIS``/``MODEL_AXIS`` registry of
    :mod:`keystone_tpu.parallel.mesh` — parsed, never imported, exactly
    like the fault-site registry. A literal ``"data"`` typo'd to
    ``"date"`` produces a mesh program that fails at trace time at best,
    or silently reduces over the wrong axis on a 2-D mesh at worst; the
    registry constants are the one place axis names exist.

``decision-event``
    Every ``*.decision`` event emitted inside ``keystone_tpu/``
    (``tracer.event("cost.decision", ...)``, ``obs.event(
    "zoo.decision", **rec)``, the placement engine's unified stream)
    must carry the audit schema ``candidates`` / ``winner`` /
    ``reason`` — the keys :mod:`keystone_tpu.obs.calibrate` joins on
    and :mod:`keystone_tpu.placement.planner` replays. Keys may arrive
    as literal kwargs or through a resolvable ``**spread`` (a dict
    literal assigned in the enclosing function, or a ``*.to_args()``
    call — resolved against the union of the module's ``to_args``
    key sets, parsed, never imported). A spread the linter cannot
    resolve statically makes no claim. A decision event missing its
    candidate table is an audit stream the planner cannot replay.
    Benches, ``scripts/`` and the test suite fabricate synthetic
    decision payloads on purpose and are exempt.

``jax-clean-module``
    A module carrying a ``# lint: jax-clean-module`` marker (in its
    first 40 lines) must not import jax ANYWHERE — no module-level
    ``import jax`` / ``from jax import ...``, and no function-local
    ones either. This is the serving-fleet router discipline
    (``serving/fleet.py`` / ``serving/fleet_rpc.py``): the front-door
    process owns no device work and must run on hosts with no
    accelerator stack, so the modules it is built from never name jax
    at any scope. The check is per-module AST (the package root
    imports jax, so transitive cleanliness is a process-architecture
    property — the fleet plane boots jax only inside the spawned
    child); the marker makes the contract explicit and greppable.

``explicit-seed``
    Randomized LIBRARY code must take an explicit integer seed: inside
    ``keystone_tpu/``, an argless ``jax.random.key()`` /
    ``jax.random.PRNGKey()``, a hardcoded integer-literal seed at those
    call sites, or a ``seed`` parameter whose default is anything but
    an int literal (``seed=None`` pushes the draw to an implicit
    source) is flagged. The package convention (module docstring of
    ``ops/stats.py``): every random draw derives from a caller-visible
    integer, so fitted models are reproducible and the sketched solver
    tier's per-chunk ``fold_in`` streams are replayable. Benches,
    ``scripts/`` and the test suite legitimately pin literal demo
    seeds and are exempt.

Findings are ``path:line: [rule] message``; the CLI exits 1 on any.
"""

from __future__ import annotations

import ast
import sys
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "lint_file", "lint_paths", "main", "RULES"]

RULES = (
    "jax-off-thread",
    "thread-join",
    "retry-transient",
    "fault-site",
    "bench-row",
    "metric-name",
    "mesh-axis-name",
    "explicit-seed",
    "decision-event",
    "jax-clean-module",
)

_JAX_NAMES = {"jax", "jnp"}
_OWNER_MARK = "lint: jax-owner-thread"
_CALL_DEPTH = 6  # transitive same-scope helper expansion bound


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


# ---------------------------------------------------------------------------
# Site registry (parsed from utils/faults.py, never imported — the linter
# must work on a broken tree)
# ---------------------------------------------------------------------------


def _faults_module_path() -> Path:
    return Path(__file__).resolve().parent.parent / "utils" / "faults.py"


def _metrics_module_path() -> Path:
    return Path(__file__).resolve().parent.parent / "obs" / "metrics.py"


def _parse_prefixed_constants(path: Path, prefix: str) -> Dict[str, str]:
    """``{ATTR_NAME: "string value"}`` for top-level ``PREFIX_* = "..."``
    assignments — the shared not-imported parsing both registries
    (fault sites, metric names) use, so the linter works on a broken
    tree."""
    tree = ast.parse(path.read_text())
    registry: Dict[str, str] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id.startswith(prefix)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            registry[node.targets[0].id] = node.value.value
    return registry


def fault_site_registry(path: Optional[Path] = None) -> Dict[str, str]:
    """``{SITE_ATTR_NAME: "site.string"}`` parsed from faults.py."""
    return _parse_prefixed_constants(
        path or _faults_module_path(), "SITE_"
    )


def metric_name_registry(path: Optional[Path] = None) -> Dict[str, str]:
    """``{METRIC_ATTR_NAME: "dotted.name"}`` parsed from
    obs/metrics.py — never imported, exactly like the fault sites."""
    return _parse_prefixed_constants(
        path or _metrics_module_path(), "METRIC_"
    )


def _mesh_module_path() -> Path:
    return Path(__file__).resolve().parent.parent / "parallel" / "mesh.py"


def mesh_axis_registry(path: Optional[Path] = None) -> Dict[str, str]:
    """``{AXIS_CONST_NAME: "axis"}`` parsed (never imported) from
    parallel/mesh.py: the top-level ``*_AXIS = "..."`` assignments
    (``DATA_AXIS``, ``MODEL_AXIS``) — the one place axis names exist."""
    tree = ast.parse((path or _mesh_module_path()).read_text())
    registry: Dict[str, str] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id.endswith("_AXIS")
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            registry[node.targets[0].id] = node.value.value
    return registry


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------


def _call_name(func: ast.AST) -> str:
    """Trailing name of a call target: ``faults.maybe_fail`` → maybe_fail."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _uses_jax(node: ast.AST) -> Optional[ast.AST]:
    """First descendant that reads a name bound to jax/jnp, or None."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in _JAX_NAMES:
            return sub
        if (
            isinstance(sub, (ast.Import, ast.ImportFrom))
            and any(
                (alias.asname or alias.name).split(".")[0] in _JAX_NAMES
                or alias.name.split(".")[0] == "jax"
                for alias in sub.names
            )
        ):
            return sub
    return None


def _called_local_names(fn: ast.AST) -> Set[str]:
    """Names of functions/methods this function calls that could resolve
    in the same scope: bare ``helper(...)`` and ``self._helper(...)``."""
    out: Set[str] = set()
    for sub in ast.walk(fn):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        if isinstance(f, ast.Name):
            out.add(f.id)
        elif (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id in ("self", "cls")
        ):
            out.add(f.attr)
    return out


def _is_owner_marked(fn: ast.AST, source_lines: Sequence[str]) -> bool:
    """``# lint: jax-owner-thread`` on the def line (or the line above)."""
    line = fn.lineno - 1
    for i in (line, line - 1):
        if 0 <= i < len(source_lines) and _OWNER_MARK in source_lines[i]:
            return True
    return False


# ---------------------------------------------------------------------------
# Rule: jax-off-thread + thread-join
# ---------------------------------------------------------------------------


def _thread_targets(scope: ast.AST) -> List[Tuple[ast.Call, Optional[str]]]:
    """``threading.Thread(...)`` calls in a scope, with the local name of
    their ``target=`` when resolvable (``self._reader`` / ``reader``)."""
    out = []
    for sub in ast.walk(scope):
        if not isinstance(sub, ast.Call):
            continue
        if _call_name(sub.func) != "Thread":
            continue
        target_name: Optional[str] = None
        for kw in sub.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            if isinstance(v, ast.Name):
                target_name = v.id
            elif isinstance(v, ast.Attribute) and isinstance(
                v.value, ast.Name
            ) and v.value.id in ("self", "cls"):
                target_name = v.attr
        out.append((sub, target_name))
    return out


def _runtime_submit_targets(
    scope: ast.AST,
) -> List[Tuple[ast.Call, Optional[str], Optional[ast.Lambda]]]:
    """``x.submit("<site>", fn, ...)`` calls — the data-plane runtime's
    task submission (``data/runtime.py``): the callable runs on a pooled
    IO worker, so the jax-off-thread rule walks it exactly like a Thread
    target. Matched only when the FIRST argument names a lane — a string
    literal or a ``LANE_*`` constant (``rt.submit(runtime.LANE_READ,
    fn, ...)`` is the production prefetcher's form) — so the serving
    batcher's ``submit(request)`` — data, not a task — never
    false-positives. Returns (call, local name of the submitted fn when
    resolvable, the lambda node when the task is a lambda)."""

    def _is_lane_arg(site: ast.AST) -> bool:
        if isinstance(site, ast.Constant) and isinstance(site.value, str):
            return True
        name = (
            site.id if isinstance(site, ast.Name)
            else site.attr if isinstance(site, ast.Attribute)
            else None
        )
        return name is not None and name.startswith("LANE_")

    out: List[Tuple[ast.Call, Optional[str], Optional[ast.Lambda]]] = []
    for sub in ast.walk(scope):
        if not isinstance(sub, ast.Call) or _call_name(sub.func) != "submit":
            continue
        if len(sub.args) < 2:
            continue
        if not _is_lane_arg(sub.args[0]):
            continue
        tgt = sub.args[1]
        name: Optional[str] = None
        lam: Optional[ast.Lambda] = None
        if isinstance(tgt, ast.Name):
            name = tgt.id
        elif isinstance(tgt, ast.Attribute) and isinstance(
            tgt.value, ast.Name
        ) and tgt.value.id in ("self", "cls"):
            name = tgt.attr
        elif isinstance(tgt, ast.Lambda):
            lam = tgt
        out.append((sub, name, lam))
    return out


def _thread_binding_names(members: Sequence[ast.AST]) -> Set[str]:
    """Names a ``threading.Thread(...)`` result is bound to within a
    scope's members: ``self._thread = Thread(...)`` → ``_thread``,
    ``t = Thread(...)`` → ``t``."""
    out: Set[str] = set()
    for m in members:
        for sub in ast.walk(m):
            if not isinstance(sub, ast.Assign):
                continue
            value = sub.value
            if not (
                isinstance(value, ast.Call)
                and _call_name(value.func) == "Thread"
            ):
                continue
            for target in sub.targets:
                if isinstance(target, ast.Name):
                    out.add(target.id)
                elif isinstance(target, ast.Attribute):
                    out.add(target.attr)
    return out


def _scope_functions(scope: ast.AST) -> Dict[str, ast.AST]:
    """Directly-nested function/method defs of a class or module."""
    body = getattr(scope, "body", [])
    return {
        n.name: n
        for n in body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _check_thread_rules(
    tree: ast.Module, path: str, source_lines: Sequence[str]
) -> List[Finding]:
    findings: List[Finding] = []
    scopes: List[ast.AST] = [tree] + [
        n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
    ]
    for scope in scopes:
        in_class = isinstance(scope, ast.ClassDef)
        fns = _scope_functions(scope)
        # Class methods' bodies belong to the class scope; the module
        # scope must not double-report what a class scope owns.
        if not in_class:
            members = [
                n for n in tree.body
                if not isinstance(n, ast.ClassDef)
            ]
        else:
            members = scope.body
        threads = []
        submits: List[
            Tuple[ast.Call, Optional[str], Optional[ast.Lambda]]
        ] = []
        for m in members:
            threads.extend(_thread_targets(m))
            submits.extend(_runtime_submit_targets(m))
        if not threads and not submits:
            continue

        # Names threads are bound to in this scope (``self._thread =
        # threading.Thread(...)`` / ``t = Thread(...)``) — a join only
        # counts when called on one of them (or, when no binding is
        # resolvable, on SOME name — never on a string literal:
        # ``", ".join(...)`` must not satisfy the thread contract).
        thread_names = _thread_binding_names(members)

        def _join_receiver_ok(call: ast.Call) -> bool:
            recv = call.func.value if isinstance(
                call.func, ast.Attribute
            ) else None
            if recv is None or isinstance(recv, ast.Constant):
                return False
            name = None
            if isinstance(recv, ast.Name):
                name = recv.id
            elif isinstance(recv, ast.Attribute):
                name = recv.attr
            if thread_names:
                return name in thread_names
            return name is not None

        if threads:
            started = any(
                isinstance(sub, ast.Call)
                and _call_name(sub.func) == "start"
                for m in members
                for sub in ast.walk(m)
            )
            joined = any(
                isinstance(sub, ast.Call)
                and _call_name(sub.func) == "join"
                and _join_receiver_ok(sub)
                for m in members
                for sub in ast.walk(m)
            )
            if started and not joined:
                line = threads[0][0].lineno
                where = (
                    f"class {scope.name}" if in_class else "module scope"
                )
                findings.append(Finding(
                    path, line, "thread-join",
                    f"{where} starts a threading.Thread but never joins "
                    "it — every started thread needs a join on the "
                    "close()/shutdown path (the Prefetcher/"
                    "MicroBatchServer/runtime-lane contract)",
                ))

        # jax-off-thread: walk each resolvable worker target (Thread
        # target OR runtime-submitted task) transitively through
        # same-scope helpers.
        targets = [
            (call, name, None) for call, name in threads
        ] + submits
        for call, target_name, lam in targets:
            seen: Set[str] = set()
            if lam is not None:
                if _is_owner_marked(lam, source_lines):
                    continue
                hit = _uses_jax(lam)
                if hit is not None:
                    findings.append(Finding(
                        path, getattr(hit, "lineno", lam.lineno),
                        "jax-off-thread",
                        f"lambda submitted to an IO worker (submit at "
                        f"line {call.lineno}) touches jax/jnp — runtime "
                        "workers own disk+numpy only; one thread owns "
                        "JAX (data/runtime.py discipline). Mark the "
                        "designated owner with "
                        f"`# {_OWNER_MARK}` if intended",
                    ))
                    continue
                frontier = list(_called_local_names(lam))
            elif target_name is not None and target_name in fns:
                frontier = [target_name]
            else:
                continue
            depth = 0
            while frontier and depth < _CALL_DEPTH:
                nxt: List[str] = []
                for name in frontier:
                    if name in seen or name not in fns:
                        continue
                    seen.add(name)
                    fn = fns[name]
                    if _is_owner_marked(fn, source_lines):
                        # The designated JAX-owner thread (e.g. a serving
                        # worker that owns ALL device interaction).
                        seen.clear()
                        frontier = []
                        nxt = []
                        break
                    hit = _uses_jax(fn)
                    if hit is not None:
                        findings.append(Finding(
                            path, getattr(hit, "lineno", fn.lineno),
                            "jax-off-thread",
                            f"function {name!r} runs on a background "
                            f"worker (target at line {call.lineno}) "
                            "but touches jax/jnp — background threads "
                            "and runtime IO workers own disk+numpy "
                            "only; one thread owns JAX "
                            "(data/prefetch.py + data/runtime.py "
                            "discipline). Mark the designated owner "
                            f"with `# {_OWNER_MARK}` if intended",
                        ))
                        continue
                    nxt.extend(_called_local_names(fn))
                frontier = nxt
                depth += 1
    return findings


# ---------------------------------------------------------------------------
# Rule: retry-transient
# ---------------------------------------------------------------------------


def _check_retry_rule(tree: ast.Module, path: str) -> List[Finding]:
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _call_name(node.func) != "RetryPolicy":
            continue
        for kw in node.keywords:
            if kw.arg != "transient":
                continue
            for sub in ast.walk(kw.value):
                name = None
                if isinstance(sub, ast.Name):
                    name = sub.id
                elif isinstance(sub, ast.Attribute):
                    name = sub.attr
                if name == "ShardCorrupted":
                    findings.append(Finding(
                        path, node.lineno, "retry-transient",
                        "RetryPolicy transient tuple includes "
                        "ShardCorrupted — checksum corruption is "
                        "persistent state; retrying re-reads the same bad "
                        "bytes and hides the failure (data/durable.py "
                        "invariant)",
                    ))
    return findings


# ---------------------------------------------------------------------------
# Rule: fault-site
# ---------------------------------------------------------------------------


def _check_fault_sites(
    tree: ast.Module, path: str, registry: Dict[str, str]
) -> List[Finding]:
    findings = []
    site_values = set(registry.values())
    site_names = set(registry)

    def check_site_expr(expr: ast.AST, call: ast.Call) -> None:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            if expr.value not in site_values:
                findings.append(Finding(
                    path, call.lineno, "fault-site",
                    f"fault site {expr.value!r} is not in the faults.py "
                    f"registry {sorted(site_values)} — a typo'd site makes "
                    "the chaos drill a silent no-op",
                ))
        elif isinstance(expr, ast.Attribute) and expr.attr.startswith(
            "SITE_"
        ):
            if expr.attr not in site_names:
                findings.append(Finding(
                    path, call.lineno, "fault-site",
                    f"faults.{expr.attr} is not defined in faults.py "
                    f"(known: {sorted(site_names)})",
                ))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name in ("maybe_fail", "corrupt_array") and node.args:
            check_site_expr(node.args[0], node)
        elif name == "FaultRule":
            if node.args:
                check_site_expr(node.args[0], node)
            for kw in node.keywords:
                if kw.arg == "site":
                    check_site_expr(kw.value, node)
    return findings


# ---------------------------------------------------------------------------
# Rule: metric-name
# ---------------------------------------------------------------------------

# Every registry register/lookup door, including the ISSUE-10 mergeable
# bucketed form (the live serving plane's latency store) — a name
# invented at a bucketed_histogram site forks the dashboard namespace
# exactly like the ring form would.
_REGISTRY_METHODS = ("counter", "gauge", "histogram", "bucketed_histogram")


def _check_metric_names(
    tree: ast.Module, path: str, registry: Dict[str, str]
) -> List[Finding]:
    """Every ``*.counter(name, ...)`` / ``*.gauge(...)`` /
    ``*.histogram(...)`` whose first argument is a string literal or a
    ``METRIC_*`` reference must resolve into the parsed catalogue. A
    first argument that is neither (a variable, an f-string) is left
    alone — only literal names can be checked statically, and those are
    the overwhelming call-site form."""
    findings: List[Finding] = []
    names = set(registry)
    values = set(registry.values())
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        # Only attribute calls: bare ``counter(...)`` (e.g. a local
        # helper, itertools.count-style factories) is not a registry
        # lookup; every registry site reads ``<registry>.counter``.
        if not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr not in _REGISTRY_METHODS:
            continue
        if not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value not in values:
                findings.append(Finding(
                    path, node.lineno, "metric-name",
                    f"metric name {arg.value!r} is not in the METRIC_* "
                    "catalogue of keystone_tpu/obs/metrics.py — register "
                    "it there (one place names exist) instead of "
                    "inventing it at the call site",
                ))
        else:
            ref = (
                arg.attr if isinstance(arg, ast.Attribute)
                else arg.id if isinstance(arg, ast.Name)
                else None
            )
            if ref is not None and ref.startswith("METRIC_") \
                    and ref not in names:
                findings.append(Finding(
                    path, node.lineno, "metric-name",
                    f"{ref} is not defined in keystone_tpu/obs/"
                    f"metrics.py (known: {len(names)} catalogue "
                    "entries)",
                ))
    return findings


# ---------------------------------------------------------------------------
# Rule: mesh-axis-name
# ---------------------------------------------------------------------------

# Collectives whose axis-name argument is the SECOND positional (first
# is the operand) — jax.lax signatures — and those where it is the
# first (axis_index takes only the axis).
_AXIS_ARG1_COLLECTIVES = (
    "psum", "psum_scatter", "pmean", "pmax", "pmin",
    "all_gather", "ppermute", "all_to_all",
)
_AXIS_ARG0_COLLECTIVES = ("axis_index",)
# Sharding-spec constructors whose every string argument is an axis
# name: the ``in_specs``/``out_specs`` literals shard_map programs (and
# NamedSharding placements) are built from.
_SPEC_CONSTRUCTORS = ("PartitionSpec", "P")


def _check_mesh_axis_names(
    tree: ast.Module, path: str, registry: Dict[str, str]
) -> List[Finding]:
    """Every string-literal axis name at a collective call site or
    inside a ``PartitionSpec``/``P`` literal must be one of the parsed
    registry's values; an ``*_AXIS`` constant reference must be defined
    there. Variables and f-strings are left alone — only literals can
    be checked statically, and the rule exists precisely so call sites
    use the constants instead of literals."""
    findings: List[Finding] = []
    values = set(registry.values())
    names = set(registry)

    def check_axis_expr(expr: ast.AST, call: ast.Call) -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                if sub.value not in values:
                    findings.append(Finding(
                        path, call.lineno, "mesh-axis-name",
                        f"mesh axis name {sub.value!r} is not in the "
                        f"parallel/mesh.py registry {sorted(values)} — "
                        "use the DATA_AXIS/MODEL_AXIS constants; a "
                        "typo'd axis reduces over the wrong mesh "
                        "dimension",
                    ))
            elif isinstance(sub, (ast.Name, ast.Attribute)):
                ref = sub.id if isinstance(sub, ast.Name) else sub.attr
                if ref.endswith("_AXIS") and ref not in names:
                    findings.append(Finding(
                        path, call.lineno, "mesh-axis-name",
                        f"{ref} is not defined in parallel/mesh.py "
                        f"(known: {sorted(names)})",
                    ))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name in _AXIS_ARG1_COLLECTIVES:
            if len(node.args) >= 2:
                check_axis_expr(node.args[1], node)
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    check_axis_expr(kw.value, node)
        elif name in _AXIS_ARG0_COLLECTIVES:
            if node.args:
                check_axis_expr(node.args[0], node)
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    check_axis_expr(kw.value, node)
        elif name in _SPEC_CONSTRUCTORS:
            for arg in node.args:
                check_axis_expr(arg, node)
    return findings


# ---------------------------------------------------------------------------
# Rule: explicit-seed
# ---------------------------------------------------------------------------

_PRNG_CONSTRUCTORS = ("key", "PRNGKey")


def _is_prng_constructor(func: ast.AST) -> bool:
    """``jax.random.key`` / ``random.key`` / ``jax.random.PRNGKey`` as an
    attribute of a ``random`` module, or a bare ``PRNGKey`` name (the
    ``from jax.random import PRNGKey`` form). A bare ``key(...)`` name is
    NOT matched — too generic to attribute to the PRNG."""
    if isinstance(func, ast.Attribute) and func.attr in _PRNG_CONSTRUCTORS:
        base = func.value
        if isinstance(base, ast.Name):
            return base.id == "random"
        if isinstance(base, ast.Attribute):
            return base.attr == "random"
        return False
    return isinstance(func, ast.Name) and func.id == "PRNGKey"


def _is_int_literal(node: Optional[ast.AST]) -> bool:
    # bool is an int subclass; ``seed=True`` is not an explicit seed.
    return (
        isinstance(node, ast.Constant)
        and type(node.value) is int
    )


def _check_explicit_seed(tree: ast.Module, path: str) -> List[Finding]:
    """Randomized library code must take an explicit integer seed: no
    argless PRNG-key constructors, no hardcoded integer-literal seeds at
    those call sites, and every ``seed`` parameter's default (if any)
    must be an int literal — ``seed=None`` defers the draw to an
    implicit source the caller cannot replay."""
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_prng_constructor(node.func):
            if not node.args and not node.keywords:
                findings.append(Finding(
                    path, node.lineno, "explicit-seed",
                    "argless PRNG key constructor — library code must "
                    "derive every key from an explicit integer seed "
                    "parameter",
                ))
            elif node.args and _is_int_literal(node.args[0]):
                findings.append(Finding(
                    path, node.lineno, "explicit-seed",
                    f"hardcoded seed literal "
                    f"{ast.literal_eval(node.args[0])!r} at a PRNG key "
                    "constructor — thread a caller-visible seed "
                    "parameter instead",
                ))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            pos = list(node.args.posonlyargs) + list(node.args.args)
            defaults = list(node.args.defaults)
            for arg, default in zip(pos[len(pos) - len(defaults):], defaults):
                if arg.arg == "seed" and not _is_int_literal(default):
                    findings.append(Finding(
                        path, node.lineno, "explicit-seed",
                        f"parameter 'seed' of {node.name}() defaults to "
                        "a non-integer — default it to an int literal "
                        "so the draw is replayable",
                    ))
            for arg, default in zip(node.args.kwonlyargs,
                                    node.args.kw_defaults):
                if arg.arg == "seed" and default is not None \
                        and not _is_int_literal(default):
                    findings.append(Finding(
                        path, node.lineno, "explicit-seed",
                        f"parameter 'seed' of {node.name}() defaults to "
                        "a non-integer — default it to an int literal "
                        "so the draw is replayable",
                    ))
    # ast.walk is breadth-first; report in source order.
    return sorted(findings, key=lambda f: f.line)


# ---------------------------------------------------------------------------
# Rule: bench-row
# ---------------------------------------------------------------------------

_ROW_KEYS = {"metric", "value", "detail"}


def _check_bench_rows(tree: ast.Module, path: str) -> List[Finding]:
    findings = []
    # Dict literals inside make_row itself are the one legitimate site.
    allowed: Set[int] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == "make_row"
        ):
            allowed.update(id(sub) for sub in ast.walk(node))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict) or id(node) in allowed:
            continue
        keys = {
            k.value for k in node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        }
        if _ROW_KEYS <= keys:
            findings.append(Finding(
                path, node.lineno, "bench-row",
                "raw bench-row dict literal (metric/value/detail) — build "
                "rows through make_row so the timing convention and "
                "roofline-auditability rules are enforced",
            ))
    return findings


# ---------------------------------------------------------------------------
# decision-event: every *.decision event carries the audit schema
# ---------------------------------------------------------------------------

_DECISION_REQUIRED = ("candidates", "reason", "winner")


def _module_string_constants(tree: ast.Module) -> Dict[str, str]:
    """Top-level ``NAME = "string"`` assignments — how the placement
    engine names its event (``PLACEMENT_EVENT = "placement.decision"``)
    without the linter importing anything."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            out[node.targets[0].id] = node.value.value
    return out


def _to_args_key_union(tree: ast.Module) -> Set[str]:
    """Union of the string keys any ``to_args`` method in the module
    emits: constant keys of its dict literals plus ``out["k"] = ...``
    subscript stores — the two forms every decision dataclass uses."""
    keys: Set[str] = set()
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.FunctionDef) and node.name == "to_args"
        ):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict):
                keys.update(
                    k.value for k in sub.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                )
            elif (
                isinstance(sub, ast.Assign)
                and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Subscript)
                and isinstance(sub.targets[0].slice, ast.Constant)
                and isinstance(sub.targets[0].slice.value, str)
            ):
                keys.add(sub.targets[0].slice.value)
    return keys


def _check_decision_events(
    tree: ast.Module, path: str
) -> List[Finding]:
    findings: List[Finding] = []
    consts = _module_string_constants(tree)
    to_args_keys = _to_args_key_union(tree)

    def _event_name(call: ast.Call) -> Optional[str]:
        if _call_name(call.func) != "event" or not call.args:
            return None
        arg = call.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
        elif isinstance(arg, ast.Name):
            name = consts.get(arg.id)
        else:
            name = None
        if name is None or not name.endswith(".decision"):
            return None
        return name

    def _check_call(call: ast.Call, assigns: Dict[str, ast.AST]) -> None:
        name = _event_name(call)
        if name is None:
            return
        provided: Set[str] = set()
        unresolvable = False
        for kw in call.keywords:
            if kw.arg is not None:
                provided.add(kw.arg)
                continue
            v = kw.value  # a **spread
            if isinstance(v, ast.Call) \
                    and _call_name(v.func) == "to_args":
                provided |= to_args_keys
                continue
            src = assigns.get(v.id) if isinstance(v, ast.Name) else None
            if isinstance(src, ast.Dict) and all(
                isinstance(k, ast.Constant) for k in src.keys
            ):
                provided |= {k.value for k in src.keys}
            elif isinstance(src, ast.Call) \
                    and _call_name(src.func) == "to_args":
                provided |= to_args_keys
            else:
                # A spread the linter cannot see through (e.g. the
                # engine's **context passthrough) could provide
                # anything — static analysis makes no claim.
                unresolvable = True
        missing = [k for k in _DECISION_REQUIRED if k not in provided]
        if missing and not unresolvable:
            findings.append(Finding(
                path, call.lineno, "decision-event",
                f"decision event {name!r} is missing required schema "
                f"key(s) {', '.join(missing)} — every *.decision event "
                "must record its full candidate table, winner and "
                "reason (the audit schema obs/calibrate.py joins and "
                "placement/planner.py replays)",
            ))

    seen: Set[int] = set()
    # Innermost scopes first (ast.walk yields outer before inner), so
    # every emit call is checked against its tightest enclosing
    # function's assignments; the module scope sweeps up the rest.
    fns = [
        node for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    scopes: List[Tuple[ast.AST, Dict[str, ast.AST]]] = [
        (fn, {}) for fn in reversed(fns)
    ] + [(tree, {})]
    for scope, assigns in scopes:
        for sub in ast.walk(scope):
            if (
                isinstance(sub, ast.Assign)
                and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Name)
            ):
                # Innermost-scope walk runs last and wins, matching
                # Python's name resolution closely enough for the
                # ``rec = decision.to_args()`` emit idiom.
                assigns[sub.targets[0].id] = sub.value
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Call) and id(sub) not in seen:
                if _event_name(sub) is not None:
                    seen.add(id(sub))
                    _check_call(sub, assigns)
    return findings


# ---------------------------------------------------------------------------
# jax-clean-module rule
# ---------------------------------------------------------------------------

_CLEAN_MARK = "lint: jax-clean-module"


def _has_clean_marker(src: str) -> bool:
    return any(
        _CLEAN_MARK in line for line in src.splitlines()[:40]
    )


def _check_jax_clean_module(tree: ast.Module, path: str) -> List[Finding]:
    """Flag EVERY jax import (any scope) in a marked module — see the
    module docstring's ``jax-clean-module`` entry."""
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                root = alias.name.split(".")[0]
                if root in _JAX_NAMES:
                    findings.append(Finding(
                        path, node.lineno, "jax-clean-module",
                        f"import {alias.name!r} in a jax-clean module "
                        "— the fleet router process must run without "
                        "jax; move device work into the plane process",
                    ))
        elif isinstance(node, ast.ImportFrom):
            root = (node.module or "").split(".")[0]
            if root in _JAX_NAMES:
                findings.append(Finding(
                    path, node.lineno, "jax-clean-module",
                    f"from {node.module!r} import ... in a jax-clean "
                    "module — the fleet router process must run "
                    "without jax; move device work into the plane "
                    "process",
                ))
    return findings


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

_DISABLE_MARK = "# lint: disable="


def _file_disabled_rules(src: str) -> Set[str]:
    """File-level opt-out: a ``# lint: disable=rule1,rule2`` comment
    anywhere in the file's first 40 lines disables those rules for the
    file. The opt-out is explicit and greppable — e.g. the fault-harness
    unit tests fabricate synthetic site names on purpose."""
    out: Set[str] = set()
    for line in src.splitlines()[:40]:
        idx = line.find(_DISABLE_MARK)
        if idx >= 0:
            spec = line[idx + len(_DISABLE_MARK):].strip()
            out.update(r.strip() for r in spec.split(",") if r.strip())
    return out


def lint_file(
    path: Path,
    registry: Optional[Dict[str, str]] = None,
    rules: Optional[Sequence[str]] = None,
    metric_registry: Optional[Dict[str, str]] = None,
    mesh_registry: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    """Lint one file; returns findings (parse failures are findings too —
    a file the linter cannot read is a file nothing checks)."""
    if registry is None:
        registry = fault_site_registry()
    if metric_registry is None:
        metric_registry = metric_name_registry()
    if mesh_registry is None:
        mesh_registry = mesh_axis_registry()
    src = path.read_text()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(str(path), e.lineno or 0, "parse",
                        f"cannot parse: {e.msg}")]
    enabled = set(rules or RULES) - _file_disabled_rules(src)
    lines = src.splitlines()
    findings: List[Finding] = []
    sp = str(path)
    if {"jax-off-thread", "thread-join"} & enabled:
        thread_findings = _check_thread_rules(tree, sp, lines)
        findings.extend(f for f in thread_findings if f.rule in enabled)
    if "retry-transient" in enabled:
        findings.extend(_check_retry_rule(tree, sp))
    if "fault-site" in enabled:
        # faults.py itself defines the registry (and uses site strings in
        # docstrings/constants); skip it.
        if path.name != "faults.py":
            findings.extend(_check_fault_sites(tree, sp, registry))
    if "bench-row" in enabled:
        findings.extend(_check_bench_rows(tree, sp))
    if "metric-name" in enabled:
        # obs/metrics.py itself defines the catalogue; skip it (parity
        # with the faults.py exemption above).
        if not (path.name == "metrics.py" and path.parent.name == "obs"):
            findings.extend(
                _check_metric_names(tree, sp, metric_registry)
            )
    if "mesh-axis-name" in enabled:
        # parallel/mesh.py itself defines the axis registry; skip it
        # (parity with the faults.py / metrics.py exemptions above).
        if not (path.name == "mesh.py" and path.parent.name == "parallel"):
            findings.extend(
                _check_mesh_axis_names(tree, sp, mesh_registry)
            )
    if "explicit-seed" in enabled:
        # Library scope only: benches, measurement scripts and the test
        # suite legitimately pin literal demo seeds.
        parts = set(path.parts)
        exempt = (
            "tests" in parts or "scripts" in parts
            or path.name == "bench.py"
            or path.name.startswith("test_")
            or path.name == "conftest.py"
        )
        if not exempt:
            findings.extend(_check_explicit_seed(tree, sp))
    if "decision-event" in enabled:
        # Library scope only: the test suite and benches fabricate
        # synthetic decision payloads on purpose (same exemption shape
        # as explicit-seed).
        parts = set(path.parts)
        exempt = (
            "tests" in parts or "scripts" in parts
            or path.name == "bench.py"
            or path.name.startswith("test_")
            or path.name == "conftest.py"
        )
        if not exempt:
            findings.extend(_check_decision_events(tree, sp))
    if "jax-clean-module" in enabled and _has_clean_marker(src):
        findings.extend(_check_jax_clean_module(tree, sp))
    return findings


def _iter_py(paths: Iterable[Path]) -> Iterable[Path]:
    for p in paths:
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def lint_paths(
    paths: Sequence[Path],
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    registry = fault_site_registry()
    metric_registry = metric_name_registry()
    mesh_registry = mesh_axis_registry()
    findings: List[Finding] = []
    for f in _iter_py(paths):
        if "__pycache__" in f.parts:
            continue
        findings.extend(lint_file(
            f, registry=registry, rules=rules,
            metric_registry=metric_registry,
            mesh_registry=mesh_registry,
        ))
    return findings


def default_paths() -> List[Path]:
    """The enforced surface: the package itself, the test suite, the
    bench driver, and the measurement scripts."""
    root = Path(__file__).resolve().parent.parent.parent
    out = [root / "keystone_tpu", root / "tests"]
    for extra in (root / "bench.py", root / "scripts"):
        if extra.exists():
            out.append(extra)
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    paths = [Path(a) for a in args] or default_paths()
    findings = lint_paths(paths)
    for f in findings:
        print(f)
    if findings:
        print(f"{len(findings)} finding(s)")
        return 1
    print("lint clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
