"""Trace-dir summarizer CLI: ``python -m keystone_tpu.tools.trace <dir>``
(wrapped by ``bin/trace``).

Reads the compact ``events.jsonl`` a traced run wrote
(``KEYSTONE_TRACE=dir`` / ``run.py --trace=dir`` / ``obs.tracing(dir)``)
and prints the three views a postmortem starts from:

  - **Top spans by self-time**: per span name, total wall minus the wall
    of same-thread children — where time actually went, not where it
    was merely enclosed.
  - **Per-lane occupancy**: busy fraction of each IO lane
    (``runtime.task`` spans grouped by their ``lane`` attr) over the
    trace's wall — the overlap picture at a glance.
  - **Cost-decision table**: every ``cost.decision`` event — decision
    kind, winner, reason, the feasible/infeasible candidate split, and
    (when the executor back-annotated the decision with its measured
    outcome) predicted vs measured seconds with the log error per row,
    plus a drift WARNING when the median |log error| exceeds the
    calibration threshold — the audit trail for "why did the optimizer
    run THIS engine" and "was the model right". ``bin/calibrate``
    renders the full per-engine/mis-route analysis and refits.

``--decisions`` prints the merged chronological decision log instead:
every ``*.decision`` event across all six streams (cost, placement,
autoscale, zoo, lifecycle) in timestamp order with stream, kind,
winner, reason, and the weight family it was priced under — the
one-command answer to "what did every resource decider choose, in what
order, under which weights" (docs/placement.md).

``--perfetto OUT.json`` (re-)emits the Chrome-trace projection from the
JSONL rows (e.g. after post-processing, or when only the event log was
shipped off-box). Exits non-zero on an unreadable/invalid trace dir.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import statistics
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Sequence

from keystone_tpu.obs.calibrate import DEFAULT_DRIFT_THRESHOLD as \
    DRIFT_THRESHOLD
from keystone_tpu.obs.export import (
    device_of_span_args,
    load_events,
    to_chrome_trace,
    validate_chrome_trace,
)

__all__ = ["main", "summarize"]


def _self_times(spans: List[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """Per span NAME: count, total wall, total SELF wall (dur minus
    same-thread children's dur)."""
    child_dur: Dict[Any, int] = defaultdict(int)
    for s in spans:
        if s.get("parent_id") is not None:
            child_dur[s["parent_id"]] += s.get("dur_us", 0)
    agg: Dict[str, Dict[str, float]] = defaultdict(
        lambda: {"count": 0, "total_s": 0.0, "self_s": 0.0}
    )
    for s in spans:
        dur = s.get("dur_us", 0)
        row = agg[s["name"]]
        row["count"] += 1
        row["total_s"] += dur / 1e6
        row["self_s"] += max(dur - child_dur.get(s["span_id"], 0), 0) / 1e6
    return dict(agg)


def _lane_occupancy(
    spans: List[Dict[str, Any]], wall_s: float
) -> Dict[str, Dict[str, float]]:
    lanes: Dict[str, Dict[str, float]] = defaultdict(
        lambda: {"busy_s": 0.0, "tasks": 0}
    )
    for s in spans:
        if s["name"] != "runtime.task":
            continue
        lane = (s.get("args") or {}).get("lane", "?")
        lanes[lane]["busy_s"] += s.get("dur_us", 0) / 1e6
        lanes[lane]["tasks"] += 1
    for row in lanes.values():
        row["occupancy"] = (row["busy_s"] / wall_s) if wall_s > 0 else 0.0
    return dict(lanes)


def _device_occupancy(
    spans: List[Dict[str, Any]], wall_s: float
) -> Dict[str, Dict[str, float]]:
    """Busy seconds per DEVICE: spans carrying a ``device=`` attr (the
    mesh fold dispatches) plus the per-device ``read.d<k>`` ingestion
    lanes — the table that shows whether an 8-chip run actually kept 8
    chips busy, or one."""
    devs: Dict[str, Dict[str, float]] = defaultdict(
        lambda: {"busy_s": 0.0, "spans": 0}
    )
    for s in spans:
        dev = device_of_span_args(s.get("args") or {})
        if dev is None:
            continue
        row = devs[dev]
        row["busy_s"] += s.get("dur_us", 0) / 1e6
        row["spans"] += 1
    for row in devs.values():
        row["occupancy"] = (row["busy_s"] / wall_s) if wall_s > 0 else 0.0
    return dict(devs)


def summarize(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The structured summary the CLI renders (and tests assert on)."""
    spans = [r for r in records if r.get("type") == "span"]
    events = [r for r in records if r.get("type") == "event"]
    run_ids = sorted({r["run_id"] for r in records if r.get("run_id")})
    if spans:
        t0 = min(s["ts_us"] for s in spans)
        t1 = max(s["ts_us"] + s.get("dur_us", 0) for s in spans)
        wall_s = (t1 - t0) / 1e6
    else:
        wall_s = 0.0
    return {
        "run_ids": run_ids,
        "wall_s": wall_s,
        "num_spans": len(spans),
        "num_events": len(events),
        "self_times": _self_times(spans),
        "lanes": _lane_occupancy(spans, wall_s),
        "devices": _device_occupancy(spans, wall_s),
        "cost_decisions": [
            e.get("args", {}) for e in events
            if e.get("name") == "cost.decision"
        ],
    }


def _render(summary: Dict[str, Any], top: int) -> str:
    lines: List[str] = []
    lines.append(
        f"run {', '.join(summary['run_ids']) or '?'}: "
        f"{summary['num_spans']} spans, {summary['num_events']} events, "
        f"wall {summary['wall_s']:.3f}s"
    )
    lines.append("")
    lines.append(f"top {top} spans by self-time:")
    lines.append(f"  {'name':<32} {'count':>6} {'total_s':>9} {'self_s':>9}")
    ranked = sorted(
        summary["self_times"].items(),
        key=lambda kv: kv[1]["self_s"], reverse=True,
    )[:top]
    for name, row in ranked:
        lines.append(
            f"  {name:<32} {row['count']:>6} {row['total_s']:>9.3f} "
            f"{row['self_s']:>9.3f}"
        )
    if summary["lanes"]:
        lines.append("")
        lines.append("per-lane occupancy (runtime.task):")
        for lane, row in sorted(summary["lanes"].items()):
            lines.append(
                f"  {lane:<12} tasks={int(row['tasks']):>5} "
                f"busy={row['busy_s']:.3f}s "
                f"occupancy={row['occupancy']:.1%}"
            )
    if summary.get("devices"):
        lines.append("")
        lines.append("per-device occupancy (device= spans + read.d<k> lanes):")
        devs = summary["devices"]

        def _dev_key(item):
            name = item[0]
            return (0, int(name)) if name.isdigit() else (1, name)

        for dev, row in sorted(devs.items(), key=_dev_key):
            lines.append(
                f"  device-{dev:<10} spans={int(row['spans']):>5} "
                f"busy={row['busy_s']:.3f}s "
                f"occupancy={row['occupancy']:.1%}"
            )
    decisions = summary["cost_decisions"]
    if decisions:
        lines.append("")
        lines.append("cost decisions (predicted vs measured via the "
                     "back-annotated outcome — obs/calibrate.py):")
        errors = []
        for d in decisions:
            cands = d.get("candidates", [])
            feas = sum(1 for c in cands if c.get("feasible"))
            winner = d.get("winner", "?")
            row = (
                f"  {d.get('decision', '?'):<24} winner={winner} "
                f"reason={d.get('reason', '?')} "
                f"({feas}/{len(cands)} candidates feasible)"
            )
            predicted = next(
                (c.get("cost_s") for c in cands
                 if c.get("label") == winner), None,
            )
            measured = (d.get("outcome") or {}).get("measured_s")
            if measured is not None:
                # Same scoreability guard as DecisionOutcome.log_error:
                # a zero/negative wall (an external stamp) renders as
                # measured-only, never a math domain error.
                err = (
                    math.log(measured / predicted)
                    if predicted and predicted > 0 and measured > 0
                    else None
                )
                if err is not None:
                    errors.append(abs(err))
                err_s = f" log_err={err:+.3f}" if err is not None else ""
                pred_s = (
                    f"{predicted:.4g}s" if predicted is not None
                    else "inf"
                )
                row += (
                    f" predicted={pred_s} measured={measured:.4g}s"
                    f"{err_s}"
                )
            lines.append(row)
        if errors:
            # statistics.median — the same median CONVENTION as
            # drift_gate. (bin/calibrate scores a broader row set —
            # span-window joins, re-prediction — so its verdict is the
            # authoritative one; this warning is the inline tripwire.)
            med = statistics.median(errors)
            if med > DRIFT_THRESHOLD:
                lines.append(
                    f"  WARNING: cost-model drift — median |log error| "
                    f"{med:.3f} > {DRIFT_THRESHOLD} across "
                    f"{len(errors)} measured decisions; audit with "
                    "bin/calibrate (and --refit to re-estimate the "
                    "weights from this trace)"
                )
    return "\n".join(lines)


def _render_decisions(records: List[Dict[str, Any]]) -> str:
    """The merged chronological decision log across every stream."""
    from keystone_tpu.placement.planner import decision_rows

    rows = decision_rows(records)
    lines: List[str] = []
    streams = sorted({r["stream"] for r in rows})
    lines.append(
        f"{len(rows)} decisions across {len(streams)} streams "
        f"({', '.join(streams) or 'none'}):"
    )
    if not rows:
        return "\n".join(lines)
    t0 = rows[0]["ts_us"]
    lines.append(
        f"  {'t_s':>9} {'stream':<20} {'kind':<26} {'winner':<28} "
        f"{'reason':<24} family"
    )
    for r in rows:
        lines.append(
            f"  {(r['ts_us'] - t0) / 1e6:>9.3f} {r['stream']:<20} "
            f"{str(r['kind']):<26} {str(r['winner']):<28} "
            f"{str(r['reason'] or '?'):<24} "
            f"{r['weights_family'] or '?'}"
        )
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        "keystone-trace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("trace_dir", help="directory a traced run wrote")
    parser.add_argument("--top", type=int, default=12,
                        help="span names in the self-time table")
    parser.add_argument("--perfetto", default="",
                        help="also (re-)emit the Chrome-trace JSON here")
    parser.add_argument("--decisions", action="store_true",
                        help="print the merged chronological decision "
                             "log (all *.decision streams) instead of "
                             "the span summary")
    args = parser.parse_args(list(argv) if argv is not None else None)
    try:
        records = load_events(args.trace_dir)
    except OSError as e:
        print(f"trace: cannot read {args.trace_dir!r}: {e}",
              file=sys.stderr)
        return 1
    if not records:
        print(f"trace: {args.trace_dir!r} holds no events",
              file=sys.stderr)
        return 1
    if args.decisions:
        print(_render_decisions(records))
        return 0
    print(_render(summarize(records), args.top))
    if args.perfetto:
        doc = to_chrome_trace(records)
        problems = validate_chrome_trace(doc)
        if problems:
            print("trace: refusing to emit an invalid Chrome trace:",
                  file=sys.stderr)
            for p in problems[:10]:
                print(f"  {p}", file=sys.stderr)
            return 1
        out_dir = os.path.dirname(os.path.abspath(args.perfetto))
        os.makedirs(out_dir, exist_ok=True)
        with open(args.perfetto, "w") as f:
            json.dump(doc, f)
        print(f"\nperfetto trace written: {args.perfetto} "
              f"(load at https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # Piping the summary through `head` is the normal postmortem
        # workflow; a closed pipe is not an error worth a traceback.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
