"""SLO snapshot renderer CLI: ``python -m keystone_tpu.tools.slo <dir>``
(wrapped by ``bin/slo``).

Reads the atomic ``live_metrics.json`` snapshot the live exporter
writes (``obs/live.py`` — ``run.py serve --metrics-dir=DIR``, or any
:class:`~keystone_tpu.obs.live.LiveExporter` with a ``snapshot_dir``)
and renders the operator view of the live plane:

  - per-objective SLO table: state, fast/slow burn rates, budget
    spent/remaining, good/bad totals;
  - the transition log (when a breach happened and at what burn);
  - the error-budget ledger (which state interval spent what);
  - the autoscale decision log beside the verdict table, when the
    snapshot carries an ``autoscale`` section (``run.py serve
    --autoscale``): replica count/bounds, scale counters, brownout
    state, and the audited decisions — action, reason, inputs;
  - the lifecycle publication summary + decision log when the snapshot
    carries a ``lifecycle`` section (``run.py learn``): candidates
    published/rejected/rolled back, canary promotions, the current
    model staleness beside the incumbent fingerprint, and the audited
    publication decisions — plus the trainer's fold/resume counters
    from the ``trainer`` section;
  - the per-tenant verdict table when the snapshot carries a ``zoo``
    section (``run.py serve --tenants N``): per tenant — SLO state,
    burn rates, budget spent, admission shares, residency and the
    front-door accounting — beside the zoo paging summary and its
    decision log;
  - a one-line serving summary when the snapshot carries a
    ``serving`` section (completed/rejected/failed + p99).

Scrape-less by design: no HTTP, no server — a file read, so it works
over ssh/cron exactly like ``bin/trace`` works on a trace dir. Exits
non-zero on an unreadable/empty snapshot.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Sequence

from keystone_tpu.obs.live import SNAPSHOT_FILE

__all__ = ["main", "render"]


def load_snapshot(path: str) -> Dict[str, Any]:
    """Accept the snapshot file itself or the directory holding it."""
    if os.path.isdir(path):
        path = os.path.join(path, SNAPSHOT_FILE)
    with open(path) as f:
        return json.load(f)


def _fmt_burn(v: Any) -> str:
    return f"{v:.2f}x" if isinstance(v, (int, float)) else "?"


def render(doc: Dict[str, Any]) -> str:
    lines: List[str] = []
    ts = doc.get("ts")
    age = f", {time.time() - ts:.1f}s old" if isinstance(ts, (int, float)) \
        else ""
    lines.append(f"live snapshot seq={doc.get('seq', '?')}{age}")
    slo = doc.get("slo") or {}
    objectives: Dict[str, Dict[str, Any]] = slo.get("objectives") or {}
    if objectives:
        lines.append("")
        lines.append(f"SLO verdict: {slo.get('state', '?')}")
        lines.append(
            f"  {'objective':<16} {'state':<7} {'burn_fast':>9} "
            f"{'burn_slow':>9} {'budget_spent':>12} {'remaining':>10} "
            f"{'good':>8} {'bad':>6}"
        )
        for name, o in sorted(objectives.items()):
            spent = o.get("budget_spent_fraction")
            remaining = o.get("budget_remaining_fraction")
            spent_s = f"{spent:.1%}" if isinstance(spent, (int, float)) \
                else "?"
            rem_s = f"{remaining:.1%}" \
                if isinstance(remaining, (int, float)) else "?"
            lines.append(
                f"  {name:<16} {o.get('state', '?'):<7} "
                f"{_fmt_burn(o.get('burn_fast')):>9} "
                f"{_fmt_burn(o.get('burn_slow')):>9} "
                f"{spent_s:>12} {rem_s:>10} "
                f"{o.get('good_total', 0):>8} {o.get('bad_total', 0):>6}"
            )
        for name, o in sorted(objectives.items()):
            transitions = o.get("transitions") or []
            if transitions:
                lines.append("")
                lines.append(f"  {name} transitions:")
                for t in transitions:
                    lines.append(
                        f"    t+{t.get('t_s', 0):.3f}s "
                        f"{t.get('from', '?')} -> {t.get('to', '?')} "
                        f"(burn_fast {_fmt_burn(t.get('burn_fast'))}, "
                        f"budget {t.get('budget_spent_fraction', 0):.1%} "
                        f"spent)"
                    )
            ledger = o.get("ledger") or []
            if len(ledger) > 1:
                lines.append(f"  {name} budget ledger:")
                for e in ledger:
                    t_end = e.get("t_end")
                    end_s = f"{t_end:.3f}s" if isinstance(
                        t_end, (int, float)) else "now"
                    lines.append(
                        f"    [{e.get('state', '?'):<7}] "
                        f"t+{e.get('t_start', 0):.3f}s..{end_s}  "
                        f"good={e.get('good', 0)} bad={e.get('bad', 0)}"
                    )
    else:
        lines.append("(no SLO objectives in this snapshot)")
    autoscale = doc.get("autoscale") or {}
    if autoscale:
        lines.append("")
        lines.append(
            f"autoscale: replicas={autoscale.get('replicas', '?')} "
            f"(bounds {autoscale.get('min_replicas', '?')}.."
            f"{autoscale.get('max_replicas', '?')}, observed "
            f"{autoscale.get('replicas_low', '?')}.."
            f"{autoscale.get('replicas_high', '?')}) "
            f"scale_ups={autoscale.get('scale_ups', 0)} "
            f"scale_downs={autoscale.get('scale_downs', 0)} "
            f"brownout_level={autoscale.get('brownout_level', 0)}"
            + (f" steps={autoscale['brownout_steps']}"
               if autoscale.get("brownout_steps") else "")
        )
        decisions = autoscale.get("decisions") or []
        if decisions:
            lines.append("  decision log:")
            for d in decisions:
                inputs = d.get("inputs") or {}
                step = f":{d['step']}" if d.get("step") else ""
                ok = "" if d.get("ok", True) else " FAILED"
                lines.append(
                    f"    t+{d.get('t_s', 0):.3f}s "
                    f"{d.get('action', '?')}{step}{ok} "
                    f"(state={inputs.get('state', '?')} "
                    f"burn_fast={_fmt_burn(inputs.get('burn_fast'))} "
                    f"replicas={inputs.get('replicas', '?')} "
                    f"queue={inputs.get('queue_depth', '?')}) — "
                    f"{d.get('reason', '')}"
                )
    lifecycle = doc.get("lifecycle") or {}
    if lifecycle:
        stale = lifecycle.get("staleness_s")
        stale_s = f"{stale:.3f}s" if isinstance(stale, (int, float)) \
            else "-"
        med = lifecycle.get("staleness_median_s")
        med_s = f"{med:.3f}s" if isinstance(med, (int, float)) else "-"
        lines.append("")
        lines.append(
            f"lifecycle: published={lifecycle.get('published', 0)} "
            f"rejected={lifecycle.get('rejected', 0)} "
            f"rollbacks={lifecycle.get('rollbacks', 0)} "
            f"canary_promotions={lifecycle.get('canary_promotions', 0)} "
            f"staleness={stale_s} (median {med_s}, "
            f"n={lifecycle.get('staleness_num_samples', 0)}) "
            f"incumbent={lifecycle.get('incumbent_fingerprint', '?')}"
            + (" [attribution window OPEN]"
               if lifecycle.get("attribution_open") else "")
        )
        decisions = lifecycle.get("decisions") or []
        if decisions:
            lines.append("  publication decision log:")
            for d in decisions:
                ok = "" if d.get("ok", True) else " FAILED"
                lines.append(
                    f"    t+{d.get('t_s', 0):.3f}s "
                    f"{d.get('action', '?')}:"
                    f"{d.get('fingerprint') or '<unexported>'}{ok} "
                    f"— {d.get('reason', '')}"
                )
    trainer = doc.get("trainer") or {}
    if trainer:
        lines.append(
            f"trainer: segments_fit={trainer.get('segments_fit', 0)}/"
            f"{trainer.get('num_segments', '?')} "
            f"resumes={trainer.get('resumes', 0)} "
            f"publishes={trainer.get('publishes', 0)}"
            + (f" ERROR={trainer['error']}"
               if trainer.get("error") else "")
        )
    zoo = doc.get("zoo") or {}
    if zoo.get("tenants"):
        lines.append("")
        lines.append(
            f"zoo: tenants={zoo.get('num_tenants', '?')} "
            f"residents={zoo.get('residents', '?')} "
            f"resident_bytes={zoo.get('resident_bytes', '?')}/"
            f"{zoo.get('budget_bytes', '?')} "
            f"page_ins={zoo.get('page_ins', 0)} "
            f"page_outs={zoo.get('page_outs', 0)} "
            f"quarantined={zoo.get('quarantined', 0)} "
            f"coldstart_failfast={zoo.get('coldstart_failfast', 0)} "
            f"accounting_ok={zoo.get('accounting_ok', '?')}"
        )
        lines.append(
            f"  {'tenant':<12} {'state':<7} {'burn_fast':>9} "
            f"{'burn_slow':>9} {'budget_spent':>12} {'share':>6} "
            f"{'offered':>8} {'done':>8} {'rej':>6} {'fail':>5} "
            f"{'residency':<10}"
        )
        for name, t in sorted(zoo["tenants"].items()):
            slo_t = t.get("slo") or {}
            objectives = slo_t.get("objectives") or {}
            burn_fast = burn_slow = spent = None
            for o in objectives.values():
                if burn_fast is None or (o.get("burn_fast") or 0) > burn_fast:
                    burn_fast = o.get("burn_fast")
                    burn_slow = o.get("burn_slow")
                    spent = o.get("budget_spent_fraction")
            spent_s = f"{spent:.1%}" if isinstance(spent, (int, float)) \
                else "?"
            residency = (
                "QUARANTINE" if t.get("quarantined")
                else "resident" if t.get("resident") else "paged"
            )
            lines.append(
                f"  {name:<12} {slo_t.get('state', '-'):<7} "
                f"{_fmt_burn(burn_fast):>9} {_fmt_burn(burn_slow):>9} "
                f"{spent_s:>12} "
                f"{t.get('admission_share', 0):>6.2f} "
                f"{t.get('offered', 0):>8} {t.get('completed', 0):>8} "
                f"{t.get('rejected', 0):>6} {t.get('failed', 0):>5} "
                f"{residency:<10}"
            )
        decisions = zoo.get("decisions") or []
        if decisions:
            lines.append("  paging decision log:")
            for d in decisions:
                ok = "" if d.get("ok", True) else " FAILED"
                lines.append(
                    f"    t+{d.get('t_s', 0):.3f}s "
                    f"{d.get('action', '?')}:{d.get('tenant', '?')}{ok} "
                    f"— {d.get('reason', '')}"
                )
    serving = doc.get("serving") or {}
    if serving:
        p99 = serving.get("p99_latency_s")
        p99_s = f"{p99 * 1e3:.2f}ms" if isinstance(p99, (int, float)) \
            else "?"
        lines.append("")
        lines.append(
            f"serving: completed={serving.get('completed', '?')} "
            f"rejected={serving.get('rejected', '?')} "
            f"failed={serving.get('failed', '?')} p99={p99_s}"
            + (f" healthy_replicas={serving['healthy_replicas']}"
               if "healthy_replicas" in serving else "")
        )
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        "keystone-slo", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "snapshot",
        help=f"snapshot dir (holding {SNAPSHOT_FILE}) or the file itself",
    )
    args = parser.parse_args(list(argv) if argv is not None else None)
    try:
        doc = load_snapshot(args.snapshot)
    except (OSError, json.JSONDecodeError) as e:
        print(f"slo: cannot read {args.snapshot!r}: {e}", file=sys.stderr)
        return 1
    if not doc:
        print(f"slo: {args.snapshot!r} holds an empty snapshot",
              file=sys.stderr)
        return 1
    print(render(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
