"""Cost-model calibration CLI: ``python -m keystone_tpu.tools.calibrate
TRACE_DIR [TRACE_DIR ...]`` (wrapped by ``bin/calibrate``).

Reads the ``events.jsonl`` of one or more traced runs
(``KEYSTONE_TRACE=dir`` / ``run.py --trace=dir`` / ``obs.tracing(dir)``)
and renders the predicted-vs-measured audit of every ``cost.decision``
the traces carry (``obs/calibrate.py``):

  - **per-engine error table**: decisions joined with the measured
    seconds of the work they priced (back-annotated outcome or
    span-window join), summarized per engine as median predicted /
    measured / signed and absolute log error;
  - **mis-route table**: decisions where a measured-faster feasible
    candidate lost, with the regret in seconds and the evidence class
    (a measured same-geometry outcome, or the loser's calibrated
    estimate);
  - **drift verdict**: OK or DRIFT against the stated threshold —
    DRIFT exits 2, so a mis-predicting cost model fails a scripted
    calibration check the way a failing test fails CI. NO-DATA (no
    decision could be joined with a measurement — tracing was off, or
    the trace holds no cost decisions) exits 3: a gate with zero
    evidence fails closed, it does not pass vacuously.

``--refit OUT.json`` re-estimates the weight family from the traces and
writes the versioned, provenance-stamped calibration artifact that
``KEYSTONE_COST_WEIGHTS=calibrated:OUT.json`` activates, printing the
before/after residuals. ``--weights tpu|ec2|calibrated:<path>``
evaluates the traces under a family other than the active one (the
drift A/B). Exits non-zero on an unreadable trace dir (1), a DRIFT
verdict (2), or NO-DATA (3).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

from keystone_tpu.obs import calibrate as cal
from keystone_tpu.obs.export import load_events

__all__ = ["main", "render_report"]


def _fmt_s(v: Any) -> str:
    return f"{v:.4g}s" if isinstance(v, (int, float)) else "?"


def _fmt_err(v: Any) -> str:
    return f"{v:+.3f}" if isinstance(v, (int, float)) else "?"


def render_report(report: Dict[str, Any], verdict: Dict[str, Any],
                  top_misroutes: int = 10) -> str:
    """The operator view the CLI prints (and tests assert on)."""
    lines: List[str] = []
    lines.append(
        f"calibration: {report['num_decisions']} decisions "
        f"({report['num_measured']} measured, {report['num_scored']} "
        f"scored) under the {report['weights_family']!r} weights, "
        f"runs {', '.join(report['run_ids']) or '?'}"
    )
    if report["skipped_unknown_engine"]:
        lines.append(
            f"  NOTE: {report['skipped_unknown_engine']} measured "
            "decision(s) skipped — engine label unknown to the "
            "candidate registry"
        )
    spans = report.get("span_counts") or {}
    if spans:
        lines.append(
            "  joined spans: " + ", ".join(
                f"{name}={count}" for name, count in sorted(spans.items())
            )
        )
    per_engine = report.get("per_engine") or {}
    if per_engine:
        lines.append("")
        lines.append("per-engine predicted vs measured (log error = "
                     "ln(measured/predicted)):")
        lines.append(
            f"  {'engine':<40} {'n':>4} {'med_pred':>10} {'med_meas':>10} "
            f"{'med_err':>8} {'med|err|':>9} {'max|err|':>9}"
        )
        ranked = sorted(
            per_engine.items(),
            key=lambda kv: kv[1]["median_abs_log_error"], reverse=True,
        )
        for label, eng in ranked:
            lines.append(
                f"  {label:<40} {eng['count']:>4} "
                f"{_fmt_s(eng['median_predicted_s']):>10} "
                f"{_fmt_s(eng['median_measured_s']):>10} "
                f"{_fmt_err(eng['median_log_error']):>8} "
                f"{eng['median_abs_log_error']:>9.3f} "
                f"{eng['max_abs_log_error']:>9.3f}"
            )
    misroutes = report.get("misroutes") or []
    if misroutes:
        lines.append("")
        lines.append(
            f"mis-routes ({len(misroutes)} total, "
            f"{report['total_regret_s']:.3f}s total regret):"
        )
        lines.append(
            f"  {'winner':<36} {'measured':>10} "
            f"{'faster candidate':<36} {'estimate':>10} {'regret':>9} "
            f"evidence"
        )
        for m in misroutes[:top_misroutes]:
            lines.append(
                f"  {m['winner']:<36} {_fmt_s(m['winner_measured_s']):>10} "
                f"{m['faster_candidate']:<36} "
                f"{_fmt_s(m['faster_estimate_s']):>10} "
                f"{m['regret_s']:>8.3f}s {m['evidence']}"
            )
        if len(misroutes) > top_misroutes:
            lines.append(
                f"  ... {len(misroutes) - top_misroutes} more "
                "(--json for the full table)"
            )
    lines.append("")
    if verdict["num_scored"] == 0:
        lines.append(
            "drift verdict: NO-DATA — no decision could be joined with "
            "a measured outcome (trace the fit with KEYSTONE_TRACE=dir)"
        )
    elif verdict["drifted"]:
        lines.append(
            f"drift verdict: DRIFT — median |log error| "
            f"{verdict['median_abs_log_error']:.3f} > threshold "
            f"{verdict['threshold']:.3f} under the "
            f"{verdict['weights_family']!r} weights (worst engine: "
            f"{verdict['worst_engine']} at "
            f"{verdict['worst_engine_median_abs_log_error']:.3f}). "
            "The active cost model is mis-predicting this workload — "
            "refit with --refit OUT.json and activate "
            "KEYSTONE_COST_WEIGHTS=calibrated:OUT.json"
        )
    else:
        lines.append(
            f"drift verdict: OK — median |log error| "
            f"{verdict['median_abs_log_error']:.3f} <= threshold "
            f"{verdict['threshold']:.3f} under the "
            f"{verdict['weights_family']!r} weights"
        )
    return "\n".join(lines)


def _render_refit(result: Dict[str, Any]) -> str:
    w = result["weights"]
    before = result["before"]["median_abs_log_error"]
    after = result["after"]["median_abs_log_error"]
    refitted = ", ".join(w["fitted"]) or "nothing — no fit-capable rows"
    lines = [
        "",
        f"trace-driven refit (re-estimated: {refitted}; "
        f"rows: {w['num_rows']['sequential']} sequential, "
        f"{w['num_rows']['gather']} gather):",
        f"  cpu = {w['cpu']:.3e}",
        f"  mem = {w['mem']:.3e}",
        f"  network = {w['network']:.3e}  # pinned, not fit",
    ]
    if w["sparse_gather_overhead"] is not None:
        lines.append(
            f"  sparse_gather_overhead = {w['sparse_gather_overhead']:.1f}"
        )
    b = f"{before:.3f}" if before is not None else "?"
    a = f"{after:.3f}" if after is not None else "?"
    lines.append(
        f"  median |log error|: {b} (before) -> {a} (refit)"
    )
    if result["artifact_path"]:
        lines.append(
            f"  artifact: {result['artifact_path']} — activate with "
            f"KEYSTONE_COST_WEIGHTS=calibrated:"
            f"{result['artifact_path']}"
        )
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        "keystone-calibrate", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "trace_dirs", nargs="+",
        help="trace directories written by traced runs",
    )
    parser.add_argument(
        "--weights", default="active",
        help="weight family to score predictions under: active "
             "(default), tpu, ec2, or calibrated:<artifact.json>",
    )
    parser.add_argument(
        "--threshold", type=float, default=cal.DEFAULT_DRIFT_THRESHOLD,
        help="drift gate: median |log error| past this exits 2 "
             f"(default {cal.DEFAULT_DRIFT_THRESHOLD})",
    )
    parser.add_argument(
        "--refit", default="", metavar="OUT.json",
        help="re-estimate the weight family from these traces and "
             "write the calibration artifact here",
    )
    parser.add_argument(
        "--top-misroutes", type=int, default=10,
        help="mis-route rows to print (the JSON form is unabridged)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit the full report + verdict (+ refit) as JSON",
    )
    args = parser.parse_args(list(argv) if argv is not None else None)

    records: List[Dict[str, Any]] = []
    for d in args.trace_dirs:
        try:
            records.extend(load_events(d))
        except (OSError, ValueError) as e:
            # ValueError covers json.JSONDecodeError — a truncated
            # events.jsonl (run killed mid-write) gets the same named
            # diagnostic as a missing dir, not a raw traceback.
            print(f"calibrate: cannot read {d!r}: {e}", file=sys.stderr)
            return 1
    if not records:
        print("calibrate: the trace dirs hold no events", file=sys.stderr)
        return 1

    try:
        weights = cal.family_weights(args.weights)
    except ValueError as e:
        print(f"calibrate: {e}", file=sys.stderr)
        return 1

    report = cal.calibration_report(records, weights=weights)
    verdict = cal.drift_gate(report, threshold=args.threshold)
    refit_result = None
    if args.refit:
        if report["num_measured"] == 0:
            # Fail closed here too: an artifact "fit" from zero
            # measured decisions would just re-package the base family
            # as calibrated-looking provenance.
            print(
                "calibrate: refusing --refit — no decision could be "
                "joined with a measured outcome",
                file=sys.stderr,
            )
        else:
            out_dir = os.path.dirname(os.path.abspath(args.refit))
            os.makedirs(out_dir, exist_ok=True)
            refit_result = cal.refit(records, out_path=args.refit,
                                     base=weights)

    if args.json:
        doc = {"report": report, "verdict": verdict}
        if refit_result is not None:
            doc["refit"] = {
                "weights": refit_result["weights"],
                "artifact_path": refit_result["artifact_path"],
                "median_abs_log_error_before": (
                    refit_result["before"]["median_abs_log_error"]
                ),
                "median_abs_log_error_after": (
                    refit_result["after"]["median_abs_log_error"]
                ),
            }
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        print(render_report(report, verdict,
                            top_misroutes=args.top_misroutes))
        if refit_result is not None:
            print(_render_refit(refit_result))
    if verdict["drifted"]:
        return 2
    if verdict["num_scored"] == 0:
        return 3  # NO-DATA fails closed — zero evidence is not a pass
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
