"""Capacity-planner CLI: ``python -m keystone_tpu.tools.plan <dir>...``
(wrapped by ``bin/plan``).

Feeds one or more trace dirs (``KEYSTONE_TRACE=dir`` /
``run.py --trace=dir`` / ``with obs.tracing(dir):``) to
:class:`keystone_tpu.placement.planner.CapacityPlanner` and renders:

  - **Baseline**: the measured record — decision count, the weight
    family they were priced under, batch count, p50/p99, the peak
    replica/queue/outstanding occupancy the autoscale stream saw.
  - **1x fidelity**: the admission ticket — every recorded argmin
    decision replayed over its RECORDED candidates must reproduce its
    winner, and every stamped outcome is scored predicted-vs-measured
    on the calibration plane's ``|ln|`` yardstick. Exit 2 when replay
    mismatches or the worst outcome error exceeds the drift threshold:
    a planner that cannot reproduce the past must not predict the
    future.
  - **What-if rows** (one per ``--whatif``): ``traffic=2x`` |
    ``hbm=0.5x`` | ``tenants=+1`` | ``mesh=8x1``, each self-auditing
    (prediction + measured baseline + provenance + assumptions in the
    same dict — the shape bench.py's ``_whatif_violations`` enforces).

``--json`` emits the full plan dict instead (the scriptable surface).
See docs/placement.md (planner cookbook).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Sequence

from keystone_tpu.obs.export import load_events
from keystone_tpu.placement.planner import (
    CapacityPlanner,
    DEFAULT_DRIFT_THRESHOLD,
    parse_whatif,
)

__all__ = ["main"]


def _fmt_s(v: Optional[float]) -> str:
    return f"{v:.4g}s" if v is not None else "?"


def _render(plan: Dict[str, Any], drift_threshold: float) -> List[str]:
    lines: List[str] = []
    base = plan["baseline"]
    lines.append(
        f"baseline: {base['num_decisions']} decisions "
        f"(family={base['weights_family']}), "
        f"{base['num_batches']} batches, "
        f"p50={_fmt_s(base['measured_p50_s'])} "
        f"p99={_fmt_s(base['measured_p99_s'])}, "
        f"peaks: replicas={base['replicas_peak']} "
        f"queue={base['queue_peak']:g} "
        f"outstanding={base['outstanding_peak']:g}"
    )
    fid = plan["fidelity"]
    ok = fid["num_reproduced"] == fid["num_replayed"]
    worst = fid["max_abs_log_error"]
    drifted = worst is not None and worst > drift_threshold
    lines.append(
        f"1x fidelity: {fid['num_reproduced']}/{fid['num_replayed']} "
        f"argmin winners reproduced, {fid['num_outcomes']} stamped "
        f"outcomes, worst |log error| "
        f"{worst if worst is None else round(worst, 3)} "
        f"(threshold {drift_threshold}) — "
        f"{'OK' if ok and not drifted else 'FAILED'}"
    )
    for m in fid["mismatches"]:
        lines.append(
            f"  MISMATCH {m['kind']}: recorded={m['recorded']} "
            f"replayed={m['replayed']}"
        )
    for row in plan["whatifs"]:
        lines.append("")
        lines.append(f"what-if {row['whatif']}:")
        for key in (
            "predicted_p99_s", "predicted_p99_1x_s", "measured_p99_s",
            "abs_log_error_1x", "whatif_changed_winners",
            "whatif_added_page_seconds", "predicted_page_in_s",
            "measured_page_in_p50_s", "whatif_slowdown_x",
            "recorded_winner", "num_mesh_decisions",
            "measured_num_replayed", "num_page_ins", "note",
        ):
            if key in row and row[key] is not None:
                v = row[key]
                lines.append(
                    f"  {key} = "
                    f"{round(v, 6) if isinstance(v, float) else v}"
                )
        for ch in row.get("changed", []):
            lines.append(
                f"  FLIP {ch['kind']}: {ch['recorded']} -> "
                f"{ch['predicted']}"
            )
        for a in row.get("assumptions", []):
            lines.append(f"  (assumes: {a})")
    return lines


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        "keystone-plan", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("trace_dirs", nargs="+",
                        help="trace directories recorded runs wrote")
    parser.add_argument("--whatif", action="append", default=[],
                        metavar="KEY=VALUE",
                        help="traffic=2x | hbm=0.5x | tenants=+1 | "
                             "mesh=8x1 (repeatable)")
    parser.add_argument("--drift-threshold", type=float,
                        default=DEFAULT_DRIFT_THRESHOLD,
                        help="1x fidelity bound on |ln(pred/measured)| "
                             "(the calibration plane's default)")
    parser.add_argument("--json", action="store_true",
                        help="emit the plan dict as JSON")
    args = parser.parse_args(list(argv) if argv is not None else None)

    try:
        whatifs = [parse_whatif(s) for s in args.whatif]
    except ValueError as e:
        print(f"plan: {e}", file=sys.stderr)
        return 1
    records: List[Dict[str, Any]] = []
    for d in args.trace_dirs:
        try:
            records.extend(load_events(d))
        except OSError as e:
            print(f"plan: cannot read {d!r}: {e}", file=sys.stderr)
            return 1
    if not records:
        print("plan: no events in "
              f"{', '.join(repr(d) for d in args.trace_dirs)}",
              file=sys.stderr)
        return 1

    planner = CapacityPlanner(records,
                              drift_threshold=args.drift_threshold)
    plan = planner.plan(whatifs)
    if args.json:
        print(json.dumps(plan, indent=2, sort_keys=True))
    else:
        print("\n".join(_render(plan, args.drift_threshold)))
    fid = plan["fidelity"]
    worst = fid["max_abs_log_error"]
    if fid["num_reproduced"] != fid["num_replayed"] or (
        worst is not None and worst > args.drift_threshold
    ):
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
