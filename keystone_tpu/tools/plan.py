"""Capacity-planner CLI: ``python -m keystone_tpu.tools.plan <dir>...``
(wrapped by ``bin/plan``).

Feeds one or more trace dirs (``KEYSTONE_TRACE=dir`` /
``run.py --trace=dir`` / ``with obs.tracing(dir):``) to
:class:`keystone_tpu.placement.planner.CapacityPlanner` and renders:

  - **Baseline**: the measured record — decision count, the weight
    family they were priced under, batch count, p50/p99, the peak
    replica/queue/outstanding occupancy the autoscale stream saw.
  - **1x fidelity**: the admission ticket — every recorded argmin
    decision replayed over its RECORDED candidates must reproduce its
    winner, and every stamped outcome is scored predicted-vs-measured
    on the calibration plane's ``|ln|`` yardstick. Exit 2 when replay
    mismatches or the worst outcome error exceeds the drift threshold:
    a planner that cannot reproduce the past must not predict the
    future.
  - **What-if rows** (one per ``--whatif``): ``traffic=2x`` |
    ``hbm=0.5x`` | ``tenants=+1`` | ``mesh=8x1``, each self-auditing
    (prediction + measured baseline + provenance + assumptions in the
    same dict — the shape bench.py's ``_whatif_violations`` enforces).

``--json`` emits the full plan dict instead (the scriptable surface).

``--apply PATH`` closes ROADMAP item 3's loop: when (and ONLY when)
the 1x fidelity gate passes, write an auditable serving-defaults
artifact — replica count / queue depth / admission bound sized off the
measured occupancy peaks, an SLO p99 bound calibrated off the measured
tail — that ``run.py serve --from-plan PATH`` consumes, so planner
verdicts reach the serving plane without an operator retyping them.
A planner that cannot reproduce the past must not configure the
future: a failed fidelity gate refuses to write (exit 2).

See docs/placement.md (planner cookbook).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
from typing import Any, Dict, List, Optional, Sequence

from keystone_tpu.obs.export import load_events
from keystone_tpu.placement.planner import (
    CapacityPlanner,
    DEFAULT_DRIFT_THRESHOLD,
    parse_whatif,
)

__all__ = ["main"]


def _fmt_s(v: Optional[float]) -> str:
    return f"{v:.4g}s" if v is not None else "?"


def _render(plan: Dict[str, Any], drift_threshold: float) -> List[str]:
    lines: List[str] = []
    base = plan["baseline"]
    lines.append(
        f"baseline: {base['num_decisions']} decisions "
        f"(family={base['weights_family']}), "
        f"{base['num_batches']} batches, "
        f"p50={_fmt_s(base['measured_p50_s'])} "
        f"p99={_fmt_s(base['measured_p99_s'])}, "
        f"peaks: replicas={base['replicas_peak']} "
        f"queue={base['queue_peak']:g} "
        f"outstanding={base['outstanding_peak']:g}"
    )
    fid = plan["fidelity"]
    ok = fid["num_reproduced"] == fid["num_replayed"]
    worst = fid["max_abs_log_error"]
    drifted = worst is not None and worst > drift_threshold
    lines.append(
        f"1x fidelity: {fid['num_reproduced']}/{fid['num_replayed']} "
        f"argmin winners reproduced, {fid['num_outcomes']} stamped "
        f"outcomes, worst |log error| "
        f"{worst if worst is None else round(worst, 3)} "
        f"(threshold {drift_threshold}) — "
        f"{'OK' if ok and not drifted else 'FAILED'}"
    )
    for m in fid["mismatches"]:
        lines.append(
            f"  MISMATCH {m['kind']}: recorded={m['recorded']} "
            f"replayed={m['replayed']}"
        )
    for row in plan["whatifs"]:
        lines.append("")
        lines.append(f"what-if {row['whatif']}:")
        for key in (
            "predicted_p99_s", "predicted_p99_1x_s", "measured_p99_s",
            "abs_log_error_1x", "whatif_changed_winners",
            "whatif_added_page_seconds", "predicted_page_in_s",
            "measured_page_in_p50_s", "whatif_slowdown_x",
            "recorded_winner", "num_mesh_decisions",
            "measured_num_replayed", "num_page_ins", "note",
        ):
            if key in row and row[key] is not None:
                v = row[key]
                lines.append(
                    f"  {key} = "
                    f"{round(v, 6) if isinstance(v, float) else v}"
                )
        for ch in row.get("changed", []):
            lines.append(
                f"  FLIP {ch['kind']}: {ch['recorded']} -> "
                f"{ch['predicted']}"
            )
        for a in row.get("assumptions", []):
            lines.append(f"  (assumes: {a})")
    return lines


PLAN_ARTIFACT_KIND = "keystone-plan-defaults"


def serve_defaults_from_plan(plan: Dict[str, Any]) -> Dict[str, Any]:
    """Derive the serving-defaults block from a planner verdict: every
    knob is a function of a MEASURED baseline quantity (the occupancy
    peaks the autoscale stream recorded, the batch-latency tail), never
    a guess — the same measured-over-assumed discipline the what-if
    rows follow."""
    base = plan["baseline"]
    replicas_peak = max(1, int(base.get("replicas_peak") or 1))
    # Admission knobs: headroom of 2x over the RECORDED backlog peaks,
    # floored so a quiet trace still yields a servable door.
    occ_peak = max(
        float(base.get("queue_peak") or 0.0),
        float(base.get("outstanding_peak") or 0.0),
        1.0,
    )
    queue_depth = max(64, 1 << math.ceil(math.log2(2.0 * occ_peak)))
    defaults: Dict[str, Any] = {
        "replicas": replicas_peak,
        "queue_depth": queue_depth,
        "min_replicas": 1,
        # Brownout threshold: the ladder engages past the ceiling, set
        # one doubling above the storm's recorded replica peak.
        "max_replicas": 2 * replicas_peak,
    }
    p99_s = base.get("measured_p99_s")
    if p99_s:
        # The SLO bound the brownout/autoscale loop pages on: 3x the
        # measured tail (the calibrated-bound convention bench.py's
        # chaos rows use), floored at 1 ms so a microbenchmark trace
        # cannot write an unservable objective.
        defaults["slo_p99_ms"] = round(max(3e3 * float(p99_s), 1.0), 3)
        defaults["slo_target"] = 0.99
    return defaults


def write_apply_artifact(path: str, plan: Dict[str, Any],
                         trace_dirs: Sequence[str],
                         drift_threshold: float) -> Dict[str, Any]:
    """Write the ``--apply`` artifact atomically (tmp + rename) and
    return it. The artifact carries its own provenance: the source
    traces, the fidelity verdict it was gated on, and the measured
    baseline each default was derived from."""
    fid = plan["fidelity"]
    doc = {
        "artifact": PLAN_ARTIFACT_KIND,
        "version": 1,
        "written_at_unix_s": round(time.time(), 3),
        "source_traces": [os.path.abspath(d) for d in trace_dirs],
        "fidelity": {
            "num_reproduced": fid["num_reproduced"],
            "num_replayed": fid["num_replayed"],
            "num_outcomes": fid["num_outcomes"],
            "max_abs_log_error": fid["max_abs_log_error"],
            "drift_threshold": drift_threshold,
        },
        "baseline": {
            k: plan["baseline"].get(k)
            for k in ("num_decisions", "weights_family", "num_batches",
                      "measured_p50_s", "measured_p99_s",
                      "replicas_peak", "queue_peak", "outstanding_peak")
        },
        "serve_defaults": serve_defaults_from_plan(plan),
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return doc


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        "keystone-plan", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("trace_dirs", nargs="+",
                        help="trace directories recorded runs wrote")
    parser.add_argument("--whatif", action="append", default=[],
                        metavar="KEY=VALUE",
                        help="traffic=2x | hbm=0.5x | tenants=+1 | "
                             "mesh=8x1 (repeatable)")
    parser.add_argument("--drift-threshold", type=float,
                        default=DEFAULT_DRIFT_THRESHOLD,
                        help="1x fidelity bound on |ln(pred/measured)| "
                             "(the calibration plane's default)")
    parser.add_argument("--json", action="store_true",
                        help="emit the plan dict as JSON")
    parser.add_argument("--apply", default="", metavar="PATH",
                        help="write the serving-defaults artifact here "
                             "(replicas / queue depth / SLO bound sized "
                             "off the measured baseline) for run.py "
                             "serve --from-plan; REFUSED (exit 2) when "
                             "the fidelity gate fails")
    args = parser.parse_args(list(argv) if argv is not None else None)

    try:
        whatifs = [parse_whatif(s) for s in args.whatif]
    except ValueError as e:
        print(f"plan: {e}", file=sys.stderr)
        return 1
    records: List[Dict[str, Any]] = []
    for d in args.trace_dirs:
        try:
            records.extend(load_events(d))
        except OSError as e:
            print(f"plan: cannot read {d!r}: {e}", file=sys.stderr)
            return 1
    if not records:
        print("plan: no events in "
              f"{', '.join(repr(d) for d in args.trace_dirs)}",
              file=sys.stderr)
        return 1

    planner = CapacityPlanner(records,
                              drift_threshold=args.drift_threshold)
    plan = planner.plan(whatifs)
    if args.json:
        print(json.dumps(plan, indent=2, sort_keys=True))
    else:
        print("\n".join(_render(plan, args.drift_threshold)))
    fid = plan["fidelity"]
    worst = fid["max_abs_log_error"]
    fidelity_ok = fid["num_reproduced"] == fid["num_replayed"] and not (
        worst is not None and worst > args.drift_threshold
    )
    if args.apply:
        if not fidelity_ok:
            # The apply gate: a planner that cannot reproduce the past
            # must not configure the future.
            print(
                f"plan: --apply REFUSED: the 1x fidelity gate failed "
                f"({fid['num_reproduced']}/{fid['num_replayed']} "
                f"reproduced, worst |log error| {worst}) — no defaults "
                "written",
                file=sys.stderr,
            )
            return 2
        doc = write_apply_artifact(args.apply, plan, args.trace_dirs,
                                   args.drift_threshold)
        d = doc["serve_defaults"]
        print(
            f"apply: wrote {args.apply} ("
            + ", ".join(f"{k}={d[k]}" for k in sorted(d))
            + ")"
        )
    if not fidelity_ok:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
