"""One-command fleet chaos drill: ``python -m
keystone_tpu.tools.fleet_chaos`` (wrapped by ``bin/fleet-chaos``).

Quick-fits a small mnist_random_fft pipeline, ships it (split-plane
encoded, fingerprint-verified on arrival) to a multi-process serving
fleet behind the :class:`~keystone_tpu.serving.fleet.FleetRouter`,
drives a multi-tenant open-loop Poisson storm, SIGKILLs one whole
plane PROCESS mid-storm, waits for the watchdog respawn, and prints
the accounting verdict as JSON:

  - ``books_balance`` — the fleet invariant ``offered == completed +
    rejected + failed`` with zero in flight, held EXACTLY across the
    process kill (in-flight requests on the dead plane fail loudly,
    never silently).
  - ``respawn_fired`` — the watchdog declared the plane dead off
    missed heartbeats and respawned it from the shipped plan (new
    pid) within the restart budget.
  - the per-plane books and the fleet-merged latency tail (the exact
    cross-process histogram merge).

Exit status: 0 when both hold, 1 otherwise — the drill IS the check,
mirroring ``bin/chaos``'s run-the-contract discipline. See
docs/serving.md (fleet section) and docs/reliability.md
(process-death contract).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Any, Dict, Optional, Sequence

__all__ = ["main"]


def _fit_and_ship(d_in: int, num_ffts: int, block_size: int, n: int,
                  max_batch: int, seed: int):
    """Quick-fit at drill scale and encode the plan ship. ONE padding
    bucket: cross-bucket outputs are not bit-identical for the FFT
    plan on CPU, and the plane lifecycle gate enforces bit-identity."""
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.data import Dataset
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
    from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels
    from keystone_tpu.pipelines.mnist_random_fft import (
        MnistRandomFFTConfig,
        build_featurizer,
    )
    from keystone_tpu.serving import export_plan
    from keystone_tpu.serving.fleet_plane import encode_plan_ship

    rng = np.random.default_rng(seed)
    X = jnp.asarray(rng.normal(size=(n, d_in)).astype(np.float32))
    y = rng.integers(0, 10, size=n)
    labels = ClassLabelIndicatorsFromIntLabels(10)(
        Dataset.of(jnp.asarray(y))
    )
    cfg = MnistRandomFFTConfig(
        num_ffts=num_ffts, block_size=block_size, image_size=d_in
    )
    fitted = build_featurizer(cfg).and_then(
        BlockLeastSquaresEstimator(block_size, 1, 1e-3),
        Dataset.of(X), labels,
    ).fit()
    plan = export_plan(fitted, np.zeros(d_in, np.float32),
                       max_batch=max_batch, buckets=[max_batch])
    return plan, encode_plan_ship(fitted, plan)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        "keystone-fleet-chaos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--planes", type=int, default=2,
                        help="plane processes in the fleet")
    parser.add_argument("--replicas", type=int, default=1,
                        help="replicas inside each plane")
    parser.add_argument("--tenants", type=int, default=4,
                        help="independent Poisson tenants")
    parser.add_argument("--rate", type=float, default=0.0,
                        help="aggregate offered rate in Hz (0 = "
                             "calibrate to --rate-x planes' worth of "
                             "measured single-request throughput)")
    parser.add_argument("--rate-x", type=float, default=1.0,
                        help="with --rate 0: aggregate rate as a "
                             "multiple of ONE plane's naive throughput")
    parser.add_argument("--duration-s", type=float, default=3.0,
                        help="storm window; the kill lands halfway in")
    parser.add_argument("--input-dim", type=int, default=16)
    parser.add_argument("--fit-n", type=int, default=96)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(list(argv) if argv is not None else None)

    from keystone_tpu.serving.fleet import FleetRouter
    from keystone_tpu.serving.loadgen import run_multi_tenant_open_loop

    plan, ship = _fit_and_ship(
        d_in=args.input_dim, num_ffts=2, block_size=args.input_dim,
        n=args.fit_n, max_batch=32, seed=args.seed,
    )
    single_s = plan.measure_single_request_s(reps=3)
    rate_hz = args.rate or (
        args.rate_x * max(1, args.replicas) / single_s
    )
    rates = {f"t{i}": rate_hz / args.tenants
             for i in range(args.tenants)}
    import numpy as np

    rng = np.random.default_rng(args.seed + 1)
    pool = rng.normal(size=(128, args.input_dim)).astype(np.float32)

    victim: Dict[str, Any] = {}

    fleet = FleetRouter(
        ship, num_planes=args.planes,
        replicas_per_plane=max(1, args.replicas),
        heartbeat_interval_s=0.1, heartbeat_timeout_s=3.0,
        restart_budget=2,
    )

    def kill_one_plane() -> None:
        pids = fleet.plane_pids()
        name = sorted(pids)[0]
        victim["name"], victim["pid"] = name, pids[name]
        os.kill(pids[name], signal.SIGKILL)

    try:
        timer = threading.Timer(args.duration_s / 2.0, kill_one_plane)
        timer.start()
        try:
            report = run_multi_tenant_open_loop(
                fleet.submit_tenant,
                lambda tenant, i: pool[i % len(pool)],
                rates, duration_s=args.duration_s, seed=args.seed,
            )
        finally:
            timer.cancel()
            timer.join()
        # The respawn races the storm's tail — give the watchdog a
        # bounded window to finish its work before reading the books.
        deadline = time.monotonic() + 30.0
        respawn_fired = False
        while time.monotonic() < deadline:
            s = fleet.stats()
            if (s["restarts_total"] >= 1
                    and s["healthy_planes"] == args.planes):
                respawn_fired = True
                break
            time.sleep(0.05)
        drain_deadline = time.monotonic() + 15.0
        while (not fleet.accounting_ok()
               and time.monotonic() < drain_deadline):
            time.sleep(0.05)
        stats = fleet.stats()
        books_balance = fleet.accounting_ok()
        respawned_pid = fleet.plane_pids().get(victim.get("name"))
    finally:
        fleet.close()

    verdict = {
        "books_balance": books_balance,
        "respawn_fired": respawn_fired,
        "loadgen_books_balance": report.accounting_ok(),
        "victim": victim.get("name"),
        "victim_pid": victim.get("pid"),
        "respawned_pid": respawned_pid,
        "offered": stats["aggregate_offered"],
        "completed": stats["completed"],
        "rejected": stats["rejected"],
        "failed": stats["failed"],
        "inflight": stats["inflight"],
        "num_planes": stats["num_planes"],
        "healthy_planes": stats["healthy_planes"],
        "restarts_total": stats["restarts_total"],
        "offered_rate_hz": round(rate_hz, 2),
        "num_tenants": args.tenants,
        "fleet_p50_latency_s": stats["fleet_p50_latency_s"],
        "fleet_p99_latency_s": stats["fleet_p99_latency_s"],
        "planes": stats["planes"],
    }
    print(json.dumps(verdict, indent=2, sort_keys=True))
    ok = (books_balance and respawn_fired
          and report.accounting_ok()
          and victim.get("pid") is not None
          and respawned_pid != victim.get("pid"))
    if not ok:
        print("fleet-chaos: VERDICT FAILED (books_balance="
              f"{books_balance}, respawn_fired={respawn_fired}, "
              f"loadgen_books={report.accounting_ok()})",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
