"""Continuous trainer: incremental re-fit over arriving shard segments,
checkpoint-resumable, publishing through the serving lifecycle gate.

KeystoneML's premise (PAPER.md layers 5-7) is pipelines that are
*re-fit* as data arrives; the production-scale version needs the
trainer process to be as chaos-proven as shard reads and replica deaths
already are. :class:`ContinuousTrainer` composes the existing
ingredients rather than inventing new ones:

  - **The fold is a plain normal-equations accumulation** —
    ``G += XᵀX``, ``C += Xᵀy`` per segment, solved every K segments for
    a fresh ``LinearMapper`` candidate. Host numpy in float64: the fold
    is deterministic by construction, so the bit-identity resume
    contract below is a property of the carry snapshot, not of
    careful device bookkeeping. (The trainer deliberately does NO jax
    work itself — candidate export/compile happens inside the
    lifecycle controller's gate, and the data-plane discipline of one
    module owning its thread's device work holds.)
  - **Checkpoint/resume rides PR 5's CheckpointSpec verbatim**: the
    carry (G, C, n) snapshots every ``CheckpointSpec.every_segments``
    through the same write-behind lane, fingerprint-guarded, atomic,
    versioned. A trainer killed mid-fit (the ``trainer.fit`` fault
    site fires once per segment fold) restores the carry and cursor and
    refolds the remaining segments in the same order — the resumed
    carry is BIT-IDENTICAL to the uninterrupted one, so the candidate
    it publishes has the SAME plan fingerprint
    (tests/test_chaos_lifecycle.py pins this end to end).
  - **Publication goes through the lifecycle controller** — never
    straight to the plane: every candidate passes the validation gate
    (finite weights, bucket bit-identity, held-out quality) and the
    canary window before any replica serves it. The trainer also hands
    the controller ``data_time`` — the arrival stamp of the newest
    segment the candidate covers — which is the start of the
    model-staleness clock.

:class:`TimedSegmentFeed` models "arriving shards" deterministically:
segments carry arrival offsets on an injectable clock, are
index-addressable (a resumed trainer re-reads exactly the segments an
uninterrupted one would have), and block the trainer until their
arrival time — no feeder thread, so the arrival schedule is replayable
by construction, the same discipline as ``utils/faults.py``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from keystone_tpu.data.durable import resolve_checkpoint
from keystone_tpu.obs.metrics import (
    METRIC_TRAINER_RESUMES,
    METRIC_TRAINER_SEGMENTS_FIT,
)
from keystone_tpu.utils import faults

__all__ = ["ContinuousTrainer", "TimedSegmentFeed"]

logger = logging.getLogger("keystone_tpu.learning")


class TimedSegmentFeed:
    """Arriving (X, y) segments with deterministic arrival stamps.

    ``segments`` is a sequence of ``(X, y)`` numpy pairs;
    ``arrival_offsets`` gives each segment's arrival time in seconds
    from :meth:`start` (non-decreasing; default 0 for every segment —
    everything already arrived, the unit-test shape). The feed is
    INDEX-ADDRESSABLE (:meth:`load`), which is what makes trainer
    resume bit-identical: segment i is segment i on every run.
    """

    def __init__(
        self,
        segments: Sequence[Tuple[Any, Any]],
        arrival_offsets: Optional[Sequence[float]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._segments = [
            (np.asarray(X), np.asarray(y)) for X, y in segments
        ]
        if not self._segments:
            raise ValueError("TimedSegmentFeed needs >= 1 segment")
        if arrival_offsets is None:
            offsets = [0.0] * len(self._segments)
        else:
            offsets = [float(t) for t in arrival_offsets]
        if len(offsets) != len(self._segments):
            raise ValueError(
                f"{len(offsets)} arrival offsets for "
                f"{len(self._segments)} segments"
            )
        if any(b < a for a, b in zip(offsets, offsets[1:])):
            raise ValueError("arrival_offsets must be non-decreasing")
        self._offsets = offsets
        self._clock = clock
        self._t0: Optional[float] = None

    def start(self) -> "TimedSegmentFeed":
        """Stamp the feed's epoch (idempotent): offsets are relative to
        the FIRST start, so a resumed trainer sees the original arrival
        stamps, not re-aged ones."""
        if self._t0 is None:
            self._t0 = self._clock()
        return self

    @property
    def num_segments(self) -> int:
        return len(self._segments)

    def available(self) -> int:
        """How many leading segments have arrived by now."""
        if self._t0 is None:
            return 0
        now = self._clock() - self._t0
        n = 0
        for off in self._offsets:
            if off <= now:
                n += 1
            else:
                break
        return n

    def arrival_time(self, i: int) -> float:
        """ABSOLUTE (clock-domain) arrival stamp of segment ``i`` — the
        staleness clock's start. Raises until :meth:`start`."""
        if self._t0 is None:
            raise RuntimeError("feed not started")
        return self._t0 + self._offsets[i]

    def load(self, i: int) -> Tuple[np.ndarray, np.ndarray]:
        return self._segments[i]

    def wait_for(self, i: int, stop: threading.Event,
                 poll_s: float = 0.01) -> bool:
        """Block until segment ``i`` has arrived (True) or ``stop`` is
        set (False). The wait is clock-driven, not event-driven, so a
        fake clock advances it deterministically under test."""
        if self._t0 is None:
            self.start()
        while self.available() <= i:
            if stop.wait(poll_s):
                return False
        return True


class ContinuousTrainer:
    """Incrementally re-fit a linear pipeline over arriving segments and
    publish every K segments through a lifecycle controller (module
    docstring).

    Knobs:

      - ``feed``: a :class:`TimedSegmentFeed` (or anything with its
        ``num_segments/load/arrival_time/wait_for`` surface).
      - ``controller``: the
        :class:`~keystone_tpu.serving.lifecycle.LifecycleController`
        publications go through. ``None`` collects candidates on
        ``self.candidates`` instead (the unit-test shape) — a real
        deployment ALWAYS publishes through the gate.
      - ``publish_every_k``: candidate cadence in segments (the final
        segment always publishes, so a feed tail shorter than K is
        never silently unfitted).
      - ``lam``: ridge regularizer of the incremental solve.
      - ``checkpoint``: CheckpointSpec | directory | None (None
        consults ``KEYSTONE_CHECKPOINT_DIR`` — the ``run.py
        --checkpoint-dir`` wiring, same as the streamed solvers).
      - ``metrics``: registry for ``trainer.segments_fit`` /
        ``trainer.resumes`` (defaults to the controller plane's).

    Thread contract: :meth:`run` does host-only numpy work plus calls
    into the controller (whose gate owns any device work); it may run
    inline (tests) or on the :meth:`start` thread. A crash mid-fit is
    recorded on ``self.error`` and logged loudly — the recovery story
    is a NEW trainer over the same feed + checkpoint directory, which
    resumes from the snapshot bit-identically.
    """

    def __init__(
        self,
        feed: TimedSegmentFeed,
        controller=None,
        publish_every_k: int = 4,
        lam: float = 1e-3,
        checkpoint=None,
        source_id: str = "continuous",
        metrics=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if publish_every_k < 1:
            raise ValueError("publish_every_k must be >= 1")
        self.feed = feed
        self.controller = controller
        self.publish_every_k = int(publish_every_k)
        self.lam = float(lam)
        self.checkpoint = checkpoint
        self.source_id = str(source_id)
        self._clock = clock

        self._lock = threading.Lock()
        self.segments_fit = 0
        self.resumes = 0
        self.publishes = 0
        self.error: Optional[BaseException] = None
        self.results: List[Dict[str, Any]] = []
        self.candidates: List[Any] = []  # controller=None collection

        reg = metrics
        if reg is None and controller is not None:
            reg = getattr(getattr(controller, "plane", None),
                          "metrics", None)
        self._metrics = reg
        if reg is not None:
            self._c_segments = reg.counter(METRIC_TRAINER_SEGMENTS_FIT)
            self._c_resumes = reg.counter(METRIC_TRAINER_RESUMES)

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ContinuousTrainer":
        """Run :meth:`run` on a background thread (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return self
            self._thread = threading.Thread(
                target=self._run_guarded,
                name="keystone-continuous-trainer", daemon=True,
            )
            self._thread.start()
        return self

    def _run_guarded(self) -> None:
        try:
            self.run()
        except BaseException as e:  # noqa: BLE001 — recorded, loud
            self.error = e
            logger.warning(
                "continuous trainer DIED mid-fit: %r — restart it over "
                "the same feed and checkpoint directory to resume "
                "bit-identically", e,
            )

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: float = 30.0) -> None:
        """Join the trainer thread (the shutdown path — a trainer that
        finished its feed has already exited)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    # -- the fit loop ------------------------------------------------------

    def _fingerprint(self, d: int, k: int) -> Dict[str, Any]:
        """The checkpoint identity: fit kind + geometry + regularizer +
        source — a snapshot from a different feed or λ can never seed
        this carry (CheckpointSpec contract)."""
        return {
            "fit": "continuous_linear",
            "d": int(d), "k": int(k), "lam": self.lam,
            "source": self.source_id,
            "num_segments": self.feed.num_segments,
        }

    def run(self) -> Dict[str, Any]:
        """Fold the feed to completion, publishing every K segments.
        Returns the final stats block."""
        feed = self.feed.start()
        X0, y0 = feed.load(0)
        d = int(X0.shape[-1])
        k = int(y0.shape[-1]) if y0.ndim > 1 else 1
        fingerprint = self._fingerprint(d, k)
        ckpt = resolve_checkpoint(self.checkpoint)

        G = np.zeros((d, d), np.float64)
        C = np.zeros((d, k), np.float64)
        n = np.zeros((1,), np.float64)
        start = 0
        if ckpt is not None:
            arrays, start = ckpt.restore(fingerprint)
            if arrays is not None:
                G, C, n = arrays
                # Restored buffers are read-only views of the snapshot
                # blob; the fold mutates in place.
                G = np.array(G, copy=True)
                C = np.array(C, copy=True)
                n = np.array(n, copy=True)
                with self._lock:
                    self.resumes += 1
                if self._metrics is not None:
                    self._c_resumes.add(1)
                logger.warning(
                    "continuous trainer RESUMED from checkpoint at "
                    "segment %d (%s)", start, self.source_id,
                )

        num = feed.num_segments
        for i in range(start, num):
            if not feed.wait_for(i, self._stop):
                break  # stopped while waiting for an arrival
            # The chaos hook: one fire per segment fold — an injected
            # error here IS the killed-trainer scenario.
            faults.maybe_fail(faults.SITE_TRAINER_FIT)
            X, y = feed.load(i)
            Xf = X.astype(np.float64, copy=False)
            yf = y.reshape(len(y), -1).astype(np.float64, copy=False)
            G += Xf.T @ Xf
            C += Xf.T @ yf
            n[0] += len(Xf)
            with self._lock:
                self.segments_fit += 1
            if self._metrics is not None:
                self._c_segments.add(1)
            if ckpt is not None:
                ckpt.maybe_save([G, C, n], i, num, fingerprint)
            if (i + 1) % self.publish_every_k == 0 or (i + 1) == num:
                self._publish(G, C, i)
        if ckpt is not None and not self._stop.is_set():
            # Completed: the snapshot is spent (same contract as the
            # streamed solvers — a later identical fit starts fresh).
            ckpt.clear(fingerprint)
        return self.stats()

    def _solve(self, G: np.ndarray, C: np.ndarray) -> np.ndarray:
        d = G.shape[0]
        return np.linalg.solve(
            G + self.lam * np.eye(d, dtype=np.float64), C
        ).astype(np.float32)

    def _candidate(self, G: np.ndarray, C: np.ndarray):
        """Solve the current carry into a transformer-only
        FittedPipeline candidate (the gate exports/compiles it — this
        module stays host-only)."""
        from keystone_tpu.ops.learning.linear import LinearMapper
        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            TransformerGraph,
        )

        pipe = LinearMapper(self._solve(G, C)).to_pipeline()
        return FittedPipeline(
            TransformerGraph.from_graph(pipe.executor.graph),
            pipe.source, pipe.sink,
        )

    def _publish(self, G: np.ndarray, C: np.ndarray,
                 segment: int) -> None:
        candidate = self._candidate(G, C)
        with self._lock:
            self.publishes += 1
        if self.controller is None:
            self.candidates.append(candidate)
            return
        result = self.controller.offer(
            candidate,
            data_time=self.feed.arrival_time(segment),
            context={"segments_covered": segment + 1},
        )
        with self._lock:
            self.results.append(result)

    # -- reading -----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            results = list(self.results)
            out = {
                "segments_fit": self.segments_fit,
                "resumes": self.resumes,
                "publishes": self.publishes,
                "published": sum(
                    1 for r in results if r.get("published")
                ),
                # NOT "gate_rejected": a canary rollback or a publish
                # failure also lands here — the controller's stats()
                # holds the per-reason books; this is just the
                # trainer's view of its own offers.
                "not_published": sum(
                    1 for r in results
                    if not r.get("published")
                ),
                "num_segments": self.feed.num_segments,
                "publish_every_k": self.publish_every_k,
                "error": repr(self.error) if self.error else None,
            }
        return out
