"""Continuous-learning layer: trainers that re-fit pipelines as data
arrives and publish them through the serving lifecycle controller
(ROADMAP item 4, docs/reliability.md's model-publication contract).

  - :class:`TimedSegmentFeed` — arriving (X, y) shard segments with
    arrival stamps, index-addressable so a resumed trainer re-reads
    exactly the segments an uninterrupted one would have.
  - :class:`ContinuousTrainer` — incrementally folds normal equations
    over arriving segments on the PR-5 checkpoint/resume machinery
    (a killed trainer resumes BIT-IDENTICALLY and republishes), and
    every K segments hands a candidate ``FittedPipeline`` to a
    :class:`~keystone_tpu.serving.lifecycle.LifecycleController` for
    validation-gated, canaried publication.
"""

from .continuous import ContinuousTrainer, TimedSegmentFeed

__all__ = ["ContinuousTrainer", "TimedSegmentFeed"]
