"""Device mesh management: the substrate that replaces the Spark cluster.

The reference distributes work as RDD partitions over Spark executors; here the
substrate is a `jax.sharding.Mesh` over TPU chips (ICI) or forced-CPU devices
in tests. Axis conventions:

  - ``data``  — examples (rows). The analog of RDD row-partitioning.
  - ``model`` — features/columns. The analog of VectorSplitter feature blocks
    (reference: nodes/util/VectorSplitter.scala:10-36).

All collectives are XLA collectives inserted by the compiler from sharding
annotations (or explicit psums inside shard_map kernels); nothing here talks to
NCCL/MPI.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"

_default_mesh: Optional[Mesh] = None


def make_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh over the available devices.

    Default: a 1-D ``data`` mesh over all devices. Pass ``shape`` +
    ``axis_names`` for 2-D data×model meshes.
    """
    devs = np.array(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (devs.size,)
    return Mesh(devs.reshape(shape), tuple(axis_names))


def default_mesh() -> Mesh:
    """Process-wide default mesh (1-D over all devices), created on demand."""
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_mesh()
    return _default_mesh


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh
    _default_mesh = mesh


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Temporarily install `mesh` as the process default."""
    global _default_mesh
    prev = _default_mesh
    _default_mesh = mesh
    try:
        yield mesh
    finally:
        _default_mesh = prev


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.shape else 1


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host JAX runtime (one process per host over DCN).

    The analog of the Spark driver/executor bring-up in bin/run-pipeline.sh:
    after this, ``jax.devices()`` spans every host's chips and meshes built
    from it produce programs whose collectives ride ICI within a slice and
    DCN across slices. No-op when already initialized or single-process with
    no coordinator configured.
    """
    # NOTE: must not touch jax.devices()/process_count() here — querying the
    # backend initializes it, after which jax.distributed.initialize refuses
    # to run. Check the distributed client state directly instead.
    from jax._src import distributed as _distributed

    if getattr(_distributed.global_state, "client", None) is not None:
        return  # already initialized
    if coordinator_address is None and "JAX_COORDINATOR_ADDRESS" not in os.environ:
        return  # single-process run
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_hybrid_mesh(
    ici_shape: Tuple[int, ...],
    dcn_shape: Tuple[int, ...],
    axis_names: Sequence[str],
) -> Mesh:
    """Mesh over a multi-slice topology: ``ici_shape`` axes map within a
    slice (fast ICI), ``dcn_shape`` axes across slices (DCN). Put the
    data-parallel axis on DCN and model/feature axes on ICI — the layout that
    keeps Gramian all-reduces and block broadcasts on the fast interconnect.

    Degenerates to a plain mesh when there is a single slice.
    """
    if int(np.prod(dcn_shape)) == 1:
        full = tuple(d * i for d, i in zip(dcn_shape, ici_shape))
        return make_mesh(full, axis_names)
    from jax.experimental import mesh_utils

    # TPU slices carry a slice_index; hosts without one (multi-process CPU,
    # single-slice-per-host topologies) group by process instead, so the DCN
    # axes land across processes.
    slice_ids = {getattr(d, "slice_index", None) for d in jax.devices()}
    process_is_granule = len(slice_ids) <= 1
    devices = mesh_utils.create_hybrid_device_mesh(
        ici_shape, dcn_shape, devices=jax.devices(),
        process_is_granule=process_is_granule,
    )
    return Mesh(devices, tuple(axis_names))


def pad_rows(x: np.ndarray, multiple: int):
    """Zero-pad the leading axis up to a multiple; returns (padded, n_valid).

    Zero padding is the invariant the solvers rely on: padded rows contribute
    nothing to Gramians (AtA), moment sums, or gradient accumulations, so only
    divisions by n need the true count.
    """
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_width), n


def shard_rows(x, mesh: Optional[Mesh] = None, axis: str = DATA_AXIS):
    """Place an array on the mesh, sharded along its leading (example) axis."""
    mesh = mesh or default_mesh()
    spec = P(axis, *([None] * (np.ndim(x) - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def replicate(x, mesh: Optional[Mesh] = None):
    """Fully replicate an array over the mesh (the `broadcast` analog)."""
    mesh = mesh or default_mesh()
    return jax.device_put(x, NamedSharding(mesh, P()))


def shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map`` entry point.

    Newer JAX exposes ``jax.shard_map`` (replication checking named
    ``check_vma``); the 0.4 line only has the experimental entry point
    whose equivalent flag is ``check_rep``. Every shard_map program in
    this package routes through here so one import site owns the
    difference — call it exactly like ``jax.shard_map``.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def sync_if_cpu(x) -> None:
    """Barrier after a dispatched step — on the CPU backend only.

    The forced-host multi-device CPU backend deadlocks when many collective
    programs are queued asynchronously, so host-driven solver loops call
    this after each dispatched step. On TPU it is a no-op: the loop keeps
    async dispatch and step b+1's GEMMs overlap step b's solve.
    """
    if jax.default_backend() == "cpu":
        jax.block_until_ready(x)
