"""Ring collectives over the mesh ``data`` axis: sequence-parallel kernel
computation.

The reference's long-context analog is the n×n kernel matrix that is never
materialized on one machine (KernelMatrix.scala:50-90 generates column blocks
on demand; KernelGenerator.scala:121-205 collects a block of rows to the
driver and broadcasts it). On a TPU mesh the idiomatic replacement is a
**ring**: training rows stay sharded over the ``data`` axis, and each step
every device computes the kernel block between its resident rows and a
*visiting* shard that circulates neighbor-to-neighbor via ``lax.ppermute`` —
the same block-rotation schedule as ring attention, riding ICI with no
gather, no driver, and O(n/P) peak memory per device.

Primitives:
  - ``ring_pairwise_gaussian``: full row-sharded n×n Gaussian kernel.
  - ``ring_kernel_apply``: K(test, train) @ W with train rows *and* the dual
    model W sharded — the distributed KernelBlockLinearMapper apply
    (reference: KernelBlockLinearMapper.scala:28-115) without ever gathering
    either operand.
  - ``ring_gram``: AᵀA with the reduction ring-scattered over devices
    (psum_scatter), the collective form of mlmatrix's treeReduce Gramians.

All primitives are shard_map programs: explicit per-shard compute + explicit
collectives, compiled once over the whole mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import mesh as mesh_lib


def _gaussian_xla(x, y, gamma, precision=jax.lax.Precision.HIGHEST):
    xn = jnp.sum(x * x, axis=1)
    yn = jnp.sum(y * y, axis=1)
    sq = xn[:, None] + yn[None, :] - 2.0 * jnp.dot(x, y.T, precision=precision)
    return jnp.exp(-gamma * jnp.maximum(sq, 0.0))


def _gaussian(x, y, gamma):
    """Local (per-shard) Gaussian kernel block inside the ring bodies.

    Operands here are already unsharded (shard_map-local), so the fused
    Pallas kernel composes directly — on TPU meshes each ring step's block
    is one fused matmul+exp with no HBM round-trip for the distance matrix.
    The kernel computes in f32; x64 callers keep the XLA path so ring
    results stay double-precision on the CPU test backend.
    """
    from keystone_tpu.ops import pallas_ops

    if pallas_ops.pallas_enabled() and x.dtype != jnp.float64:
        xn = jnp.sum(x * x, axis=1)
        yn = jnp.sum(y * y, axis=1)
        return pallas_ops.gaussian_kernel_block(x, y, xn, yn, gamma).astype(
            x.dtype
        )
    return _gaussian_xla(x, y, gamma)


def _ring_perm(p: int):
    return [(i, (i + 1) % p) for i in range(p)]


def ring_pairwise_gaussian(X, gamma: float, mesh: Optional[Mesh] = None):
    """Full n×n Gaussian kernel over row-sharded X, output row-sharded.

    Each of the P ring steps computes one (n/P, n/P) block per device while
    the visiting shard hops to the next neighbor, so peak per-device memory
    is the local output stripe — the n×n matrix only ever exists sharded.
    """
    mesh = mesh or mesh_lib.default_mesh()
    axis = mesh_lib.DATA_AXIS
    p = mesh.shape[axis]
    X = jnp.asarray(X)

    def body(x_local):
        n_local = x_local.shape[0]
        me = jax.lax.axis_index(axis)

        def step(s, carry):
            visiting, cols = carry
            # After s forward hops, the shard visiting device `me` is the one
            # that started at (me - s) mod p.
            src = (me - s) % p
            block = _gaussian(x_local, visiting, gamma)
            start = jnp.asarray(src * n_local)
            cols = jax.lax.dynamic_update_slice(
                cols, block, (jnp.zeros((), dtype=start.dtype), start)
            )
            visiting = jax.lax.ppermute(visiting, axis, _ring_perm(p))
            return visiting, cols

        cols0 = jnp.zeros((n_local, n_local * p), dtype=x_local.dtype)
        # The carry becomes device-varying after the first update; mark the
        # initial value as varying over the mesh axis for shard_map's types.
        cols0 = jax.lax.pcast(cols0, (axis,), to="varying")
        _, cols = jax.lax.fori_loop(0, p, step, (x_local, cols0))
        return cols

    return mesh_lib.shard_map(
        body, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None),
        check_vma=False,
    )(X)


def ring_kernel_apply(
    X_test,
    X_train,
    W,
    gamma: float,
    mesh: Optional[Mesh] = None,
):
    """predictions = K(test, train) @ W with train rows and W row-sharded.

    The kernel-space analog of ring attention's KV circulation: the (train
    shard, model shard) pair circulates the ring; each device accumulates the
    partial product for its resident test rows. Nothing is gathered; each
    K(test_local, train_shard) block is consumed immediately and freed.

    X_test: (m, d) row-sharded over ``data``; X_train: (n, d) row-sharded;
    W: (n, k) row-sharded identically to X_train. Returns (m, k) row-sharded.
    """
    mesh = mesh or mesh_lib.default_mesh()
    axis = mesh_lib.DATA_AXIS
    p = mesh.shape[axis]
    X_test = jnp.asarray(X_test)
    X_train = jnp.asarray(X_train)
    W = jnp.asarray(W)

    def body(xt_local, xtr_local, w_local):
        def step(_, carry):
            xtr, w, acc = carry
            acc = acc + jnp.dot(
                _gaussian(xt_local, xtr, gamma).astype(w.dtype),
                w,
                precision=jax.lax.Precision.HIGHEST,
            )
            xtr = jax.lax.ppermute(xtr, axis, _ring_perm(p))
            w = jax.lax.ppermute(w, axis, _ring_perm(p))
            return xtr, w, acc

        acc0 = jnp.zeros((xt_local.shape[0], w_local.shape[1]), dtype=w_local.dtype)
        acc0 = jax.lax.pcast(acc0, (axis,), to="varying")
        _, _, acc = jax.lax.fori_loop(0, p, step, (xtr_local, w_local, acc0))
        return acc

    return mesh_lib.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis, None)),
        out_specs=P(axis, None),
        check_vma=False,
    )(X_test, X_train, W)


def ring_attention(
    Q,
    K,
    V,
    mesh: Optional[Mesh] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    n_valid: Optional[int] = None,
):
    """Exact softmax attention over a sequence sharded across the mesh.

    The general form of this module's kernel-matrix rings (and the direct
    TPU analog of Ring Attention, Liu et al. 2023): queries stay resident,
    the (K, V) shard pair circulates neighbor-to-neighbor via ``ppermute``,
    and each step folds one block of scores into an **online softmax**
    running state (row max ``m``, normalizer ``l``, weighted accumulator) —
    so neither the n×n score matrix nor the full K/V ever exist on one
    device, peak memory is O(n/P · d), and the P hops ride ICI.

    Q, K, V: (n, d) row-sharded over the ``data`` axis (same sharding).
    ``causal=True`` masks with GLOBAL sequence positions (query i attends
    to keys j ≤ i across shard boundaries). Rows padded on by
    ``mesh.pad_rows`` must be masked via ``n_valid`` — zero key rows are
    NOT no-ops under softmax (score 0 still gets weight), unlike the
    Gramian/moment reductions the zero-padding invariant covers. The
    softmax state (m, l, acc) runs in f32 regardless of the input layout
    dtype — bf16 operands, f32 accumulation — with one cast at the end.
    Returns (n, d) row-sharded, equal to ``softmax(QKᵀ·scale [+mask]) V``.
    """
    mesh = mesh or mesh_lib.default_mesh()
    axis = mesh_lib.DATA_AXIS
    p = mesh.shape[axis]
    Q = jnp.asarray(Q)
    K = jnp.asarray(K)
    V = jnp.asarray(V)
    d = Q.shape[1]
    sc = (1.0 / d**0.5) if scale is None else float(scale)
    out_dtype = jnp.result_type(Q.dtype, K.dtype, V.dtype)
    acc_dtype = jnp.promote_types(out_dtype, jnp.float32)
    neg = jnp.asarray(-1e30, dtype=acc_dtype)
    # QKᵀ: bf16 operands hit the MXU natively (one pass, f32 accumulation
    # via preferred_element_type); f32 operands need HIGHEST, as everywhere.
    qk_kwargs = dict(
        precision=(
            jax.lax.Precision.DEFAULT
            if out_dtype == jnp.bfloat16
            else jax.lax.Precision.HIGHEST
        ),
        preferred_element_type=acc_dtype,
    )
    # P·V: p_blk is an f32 softmax weight (part of the documented f32
    # state), so this dot always runs at full f32 precision — DEFAULT here
    # would silently demote the weights to bf16 and mis-normalize against
    # the f32 normalizer l.
    pv_kwargs = dict(
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=acc_dtype,
    )

    def body(q_local, k_local, v_local):
        n_loc = q_local.shape[0]
        me = jax.lax.axis_index(axis)
        q_pos = me * n_loc + jnp.arange(n_loc)

        def step(s, carry):
            k_blk, v_blk, m, l, acc = carry
            src = (me - s) % p  # origin shard of the visiting block
            scores = jnp.dot(q_local, k_blk.T, **qk_kwargs) * sc
            k_pos = src * n_loc + jnp.arange(n_loc)
            if causal:
                scores = jnp.where(
                    q_pos[:, None] >= k_pos[None, :], scores, neg
                )
            if n_valid is not None:
                scores = jnp.where(k_pos[None, :] < n_valid, scores, neg)
            m_new = jnp.maximum(m, jnp.max(scores, axis=1))
            # A fully-masked visiting block with m still at the -1e30 init
            # would make exp(scores - m_new) = 1 spuriously. That cannot
            # happen under this schedule: step 0 visits the SELF block,
            # where every VALID query's own diagonal key is unmasked, so m
            # is finite before any all-masked block arrives. (Padded query
            # rows can see all-masked blocks; their garbage output is
            # zeroed below.)
            alpha = jnp.exp(m - m_new)
            p_blk = jnp.exp(scores - m_new[:, None])
            l = l * alpha + jnp.sum(p_blk, axis=1)
            acc = acc * alpha[:, None] + jnp.dot(
                p_blk, v_blk.astype(acc_dtype), **pv_kwargs
            )
            k_blk = jax.lax.ppermute(k_blk, axis, _ring_perm(p))
            v_blk = jax.lax.ppermute(v_blk, axis, _ring_perm(p))
            return k_blk, v_blk, m_new, l, acc

        m0 = jnp.full((n_loc,), neg, dtype=acc_dtype)
        l0 = jnp.zeros((n_loc,), dtype=acc_dtype)
        acc0 = jnp.zeros((n_loc, V.shape[1]), dtype=acc_dtype)
        m0, l0, acc0 = (
            jax.lax.pcast(x, (axis,), to="varying") for x in (m0, l0, acc0)
        )
        _, _, _, l, acc = jax.lax.fori_loop(
            0, p, step, (k_local, v_local, m0, l0, acc0)
        )
        out = acc / jnp.maximum(l, 1e-30)[:, None]
        if n_valid is not None:
            out = out * (q_pos < n_valid)[:, None].astype(out.dtype)
        return out.astype(out_dtype)

    return mesh_lib.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis, None)),
        out_specs=P(axis, None),
        check_vma=False,
    )(Q, K, V)


def ring_attention_dataset(
    q_data,
    k_data=None,
    v_data=None,
    mesh: Optional[Mesh] = None,
    causal: bool = False,
    scale: Optional[float] = None,
):
    """Dataset-aware :func:`ring_attention`: threads ``Dataset.n`` through as
    ``n_valid`` so mesh zero-padding can never be silently softmax-weighted
    (a zero-padded key row scores 0, and score 0 still gets weight — the one
    padding case the zero-row invariant does NOT cover). ``k_data`` defaults
    to ``q_data`` (self-attention) and ``v_data`` to ``k_data``; all inputs
    must share one padded length and true row count. The mesh defaults to
    the one ``q_data`` is sharded over."""
    k_data = q_data if k_data is None else k_data
    v_data = k_data if v_data is None else v_data
    if not (q_data.n == k_data.n == v_data.n):
        raise ValueError(
            f"ring_attention_dataset needs matching true row counts, got "
            f"{q_data.n}, {k_data.n}, {v_data.n}"
        )
    from keystone_tpu.data import Dataset

    mesh = mesh or q_data.mesh
    out = ring_attention(
        q_data.array,
        k_data.array,
        v_data.array,
        mesh=mesh,
        causal=causal,
        scale=scale,
        n_valid=q_data.n,
    )
    return Dataset(out, n=q_data.n, mesh=mesh)


def ring_gram(A, mesh: Optional[Mesh] = None):
    """AᵀA over row-sharded A, with the (d, d) result scattered over the
    mesh: each device ends with a (d/P, d) row stripe via ``psum_scatter``
    (ICI ring reduce-scatter) instead of every device holding the full
    Gramian — the collective replacement for mlmatrix treeReduce + driver
    collect. Returns the result row-sharded over ``data``.

    Requires d to be divisible by the mesh size.
    """
    mesh = mesh or mesh_lib.default_mesh()
    axis = mesh_lib.DATA_AXIS
    p = mesh.shape[axis]
    A = jnp.asarray(A)
    d = A.shape[1]
    if d % p != 0:
        raise ValueError(f"feature dim {d} not divisible by mesh size {p}")

    def body(a_local):
        local = jax.lax.dot_general(
            a_local, a_local, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        return jax.lax.psum_scatter(local, axis, scatter_dimension=0, tiled=True)

    return mesh_lib.shard_map(
        body, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None),
        check_vma=False,
    )(A)
