"""Ring collectives over the mesh ``data`` axis: sequence-parallel kernel
computation.

The reference's long-context analog is the n×n kernel matrix that is never
materialized on one machine (KernelMatrix.scala:50-90 generates column blocks
on demand; KernelGenerator.scala:121-205 collects a block of rows to the
driver and broadcasts it). On a TPU mesh the idiomatic replacement is a
**ring**: training rows stay sharded over the ``data`` axis, and each step
every device computes the kernel block between its resident rows and a
*visiting* shard that circulates neighbor-to-neighbor via ``lax.ppermute`` —
the same block-rotation schedule as ring attention, riding ICI with no
gather, no driver, and O(n/P) peak memory per device.

Primitives:
  - ``ring_pairwise_gaussian``: full row-sharded n×n Gaussian kernel.
  - ``ring_kernel_apply``: K(test, train) @ W with train rows *and* the dual
    model W sharded — the distributed KernelBlockLinearMapper apply
    (reference: KernelBlockLinearMapper.scala:28-115) without ever gathering
    either operand.
  - ``ring_gram``: AᵀA with the reduction ring-scattered over devices
    (psum_scatter), the collective form of mlmatrix's treeReduce Gramians.

All primitives are shard_map programs: explicit per-shard compute + explicit
collectives, compiled once over the whole mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import mesh as mesh_lib


def _gaussian_xla(x, y, gamma, precision=jax.lax.Precision.HIGHEST):
    xn = jnp.sum(x * x, axis=1)
    yn = jnp.sum(y * y, axis=1)
    sq = xn[:, None] + yn[None, :] - 2.0 * jnp.dot(x, y.T, precision=precision)
    return jnp.exp(-gamma * jnp.maximum(sq, 0.0))


def _gaussian(x, y, gamma):
    """Local (per-shard) Gaussian kernel block inside the ring bodies.

    Operands here are already unsharded (shard_map-local), so the fused
    Pallas kernel composes directly — on TPU meshes each ring step's block
    is one fused matmul+exp with no HBM round-trip for the distance matrix.
    The kernel computes in f32; x64 callers keep the XLA path so ring
    results stay double-precision on the CPU test backend.
    """
    from keystone_tpu.ops import pallas_ops

    if pallas_ops.pallas_enabled() and x.dtype != jnp.float64:
        xn = jnp.sum(x * x, axis=1)
        yn = jnp.sum(y * y, axis=1)
        return pallas_ops.gaussian_kernel_block(x, y, xn, yn, gamma).astype(
            x.dtype
        )
    return _gaussian_xla(x, y, gamma)


def _ring_perm(p: int):
    return [(i, (i + 1) % p) for i in range(p)]


def ring_pairwise_gaussian(X, gamma: float, mesh: Optional[Mesh] = None):
    """Full n×n Gaussian kernel over row-sharded X, output row-sharded.

    Each of the P ring steps computes one (n/P, n/P) block per device while
    the visiting shard hops to the next neighbor, so peak per-device memory
    is the local output stripe — the n×n matrix only ever exists sharded.
    """
    mesh = mesh or mesh_lib.default_mesh()
    axis = mesh_lib.DATA_AXIS
    p = mesh.shape[axis]
    X = jnp.asarray(X)

    def body(x_local):
        n_local = x_local.shape[0]
        me = jax.lax.axis_index(axis)

        def step(s, carry):
            visiting, cols = carry
            # After s forward hops, the shard visiting device `me` is the one
            # that started at (me - s) mod p.
            src = (me - s) % p
            block = _gaussian(x_local, visiting, gamma)
            start = jnp.asarray(src * n_local)
            cols = jax.lax.dynamic_update_slice(
                cols, block, (jnp.zeros((), dtype=start.dtype), start)
            )
            visiting = jax.lax.ppermute(visiting, axis, _ring_perm(p))
            return visiting, cols

        cols0 = jnp.zeros((n_local, n_local * p), dtype=x_local.dtype)
        # The carry becomes device-varying after the first update; mark the
        # initial value as varying over the mesh axis for shard_map's types.
        cols0 = jax.lax.pcast(cols0, (axis,), to="varying")
        _, cols = jax.lax.fori_loop(0, p, step, (x_local, cols0))
        return cols

    return jax.shard_map(
        body, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None),
        check_vma=False,
    )(X)


def ring_kernel_apply(
    X_test,
    X_train,
    W,
    gamma: float,
    mesh: Optional[Mesh] = None,
):
    """predictions = K(test, train) @ W with train rows and W row-sharded.

    The kernel-space analog of ring attention's KV circulation: the (train
    shard, model shard) pair circulates the ring; each device accumulates the
    partial product for its resident test rows. Nothing is gathered; each
    K(test_local, train_shard) block is consumed immediately and freed.

    X_test: (m, d) row-sharded over ``data``; X_train: (n, d) row-sharded;
    W: (n, k) row-sharded identically to X_train. Returns (m, k) row-sharded.
    """
    mesh = mesh or mesh_lib.default_mesh()
    axis = mesh_lib.DATA_AXIS
    p = mesh.shape[axis]
    X_test = jnp.asarray(X_test)
    X_train = jnp.asarray(X_train)
    W = jnp.asarray(W)

    def body(xt_local, xtr_local, w_local):
        def step(_, carry):
            xtr, w, acc = carry
            acc = acc + jnp.dot(
                _gaussian(xt_local, xtr, gamma).astype(w.dtype),
                w,
                precision=jax.lax.Precision.HIGHEST,
            )
            xtr = jax.lax.ppermute(xtr, axis, _ring_perm(p))
            w = jax.lax.ppermute(w, axis, _ring_perm(p))
            return xtr, w, acc

        acc0 = jnp.zeros((xt_local.shape[0], w_local.shape[1]), dtype=w_local.dtype)
        acc0 = jax.lax.pcast(acc0, (axis,), to="varying")
        _, _, acc = jax.lax.fori_loop(0, p, step, (xtr_local, w_local, acc0))
        return acc

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis, None)),
        out_specs=P(axis, None),
        check_vma=False,
    )(X_test, X_train, W)


def ring_gram(A, mesh: Optional[Mesh] = None):
    """AᵀA over row-sharded A, with the (d, d) result scattered over the
    mesh: each device ends with a (d/P, d) row stripe via ``psum_scatter``
    (ICI ring reduce-scatter) instead of every device holding the full
    Gramian — the collective replacement for mlmatrix treeReduce + driver
    collect. Returns the result row-sharded over ``data``.

    Requires d to be divisible by the mesh size.
    """
    mesh = mesh or mesh_lib.default_mesh()
    axis = mesh_lib.DATA_AXIS
    p = mesh.shape[axis]
    A = jnp.asarray(A)
    d = A.shape[1]
    if d % p != 0:
        raise ValueError(f"feature dim {d} not divisible by mesh size {p}")

    def body(a_local):
        local = jax.lax.dot_general(
            a_local, a_local, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        return jax.lax.psum_scatter(local, axis, scatter_dimension=0, tiled=True)

    return jax.shard_map(
        body, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None),
        check_vma=False,
    )(A)
