"""Distributed linear algebra: the in-tree replacement for mlmatrix.

The reference leans on the out-of-tree `edu.berkeley.cs.amplab.mlmatrix`
package for distributed solves (RowPartitionedMatrix, NormalEquations, TSQR,
BlockCoordinateDescent, treeReduce). Here those become sharded-array
computations: rows live sharded over the mesh ``data`` axis, Gramian/correlation
reductions are XLA all-reduces inserted by the compiler from sharding
annotations, and the small per-block solves are replicated Cholesky factorizations.

Conventions (matching the reference solvers):
  - ridge solve is ``(AᵀA + λI) x = AᵀB`` with *raw* λ (not scaled by n)
    (reference: nodes/learning/LinearMapper.scala:80-98 via mlmatrix
    NormalEquations; BlockWeightedLeastSquares.scala:270-276).
  - block coordinate descent is Gauss-Seidel over feature blocks maintaining
    the residual ``R = B - Σ_b A_b W_b`` (the in-tree pattern at
    BlockWeightedLeastSquares.scala:177-313, subsuming mlmatrix
    BlockCoordinateDescent.solveLeastSquaresWithL2 / solveOnePassL2).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_lib


def _corr(a, r):
    """AᵀR with at-least-f32 accumulation and the f32-operand precision
    pin — the correlation contraction shared by every BCD path."""
    acc = jnp.promote_types(a.dtype, jnp.float32)
    return jax.lax.dot_general(
        a, r.astype(a.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=acc, **_hi_kwargs(a.dtype),
    )


def _psd_factor(gram, lam):
    """Cholesky factor of (gram + lam I) — loop-invariant across BCD epochs
    for a fixed block, so multi-epoch sweeps stash it next to the Gramian
    and later epochs pay only the two triangular solves."""
    eye = jnp.eye(gram.shape[0], dtype=gram.dtype)
    return jax.scipy.linalg.cholesky(gram + lam * eye, lower=True)


def _solve_psd(gram, rhs, lam, chol=None):
    """Solve (gram + lam I) x = rhs via Cholesky (gram PSD).

    Rank-deficient Gramians (fewer rows than block columns — demo-scale fits
    of wide blocks) with zero/tiny lam defeat the f32 Cholesky (negative
    pivots from rounding -> NaN factor). Those solves rescue through a
    second Cholesky with a strong scale-relative jitter (TPU's LU kernel
    cannot compile at d=16384 — scoped-VMEM overflow — so the rescue stays
    Cholesky-shaped); healthy Gramians keep the exact path bit for bit.
    (The reference inherits robustness from Breeze's `\\`, which LU-solves.)

    Pass ``chol`` (from :func:`_psd_factor` on the same gram/lam) to skip
    the factorization; acceptance is still checked per solve, so a stale or
    unhealthy factor falls into the same rescue path.
    """
    d = gram.shape[0]
    eye = jnp.eye(d, dtype=gram.dtype)
    if chol is None:
        chol = _psd_factor(gram, lam)
    sol = jax.scipy.linalg.cho_solve((chol, True), rhs)

    def rescue(_):
        # 1e-3·(tr/d) keeps the condition number within f32 Cholesky's
        # reliable range (~1e6) while shrinking the fit by ~0.1%. Should a
        # concentrated spectrum defeat even the jittered factorization, the
        # last resort is a diagonal-preconditioned step — always finite, and
        # still a descent direction for the BCD sweep.
        mean_diag = jnp.trace(gram) / d
        jitter = mean_diag * jnp.asarray(1e-3, gram.dtype) + lam
        chol_j = jax.scipy.linalg.cholesky(gram + jitter * eye, lower=True)
        sol_j = jax.scipy.linalg.cho_solve((chol_j, True), rhs)
        fallback = rhs / (mean_diag + lam + jnp.asarray(1e-30, gram.dtype))
        return jnp.where(jnp.all(jnp.isfinite(sol_j)), sol_j, fallback)

    # Acceptance is by the linear system's relative residual, not factor
    # finiteness: a failed f32 Cholesky can also produce finite-but-garbage
    # factors (observed on TPU) whose solutions blow up the BCD sweep. The
    # check costs one (d,d)@(d,k) GEMM — noise next to the Gramian build.
    lin_res = gram @ sol + lam * sol - rhs
    ok = jnp.all(jnp.isfinite(sol)) & (
        jnp.linalg.norm(lin_res)
        <= jnp.asarray(1e-2, gram.dtype) * (jnp.linalg.norm(rhs) + 1e-30)
    )
    return jax.lax.cond(ok, lambda _: sol, rescue, None)


@functools.partial(jax.jit, static_argnames=("lam",))
def _normal_equations_kernel(A, B, lam: float):
    gram = A.T @ A
    corr = A.T @ B
    return _solve_psd(gram, corr, jnp.asarray(lam, dtype=A.dtype))


def normal_equations_solve(A, B, lam: float = 0.0):
    """Exact least-squares / ridge solve via normal equations.

    A: (n, d) rows (may be sharded over the mesh data axis; zero-padding rows
    are harmless). B: (n, k). Returns (d, k) replicated.

    The AᵀA / AᵀB contractions over the sharded n axis compile to per-shard
    GEMMs + an all-reduce — the direct analog of the reference's per-partition
    Gramians + treeReduce (mlmatrix NormalEquations).
    """
    return _normal_equations_kernel(jnp.asarray(A), jnp.asarray(B), float(lam))


# ---------------------------------------------------------------------------
# Block coordinate descent least squares
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("lam",), donate_argnums=(2,))
def _bcd_block_step(Ab, Wb, R, lam: float):
    """One Gauss-Seidel block update.

    Solves (AbᵀAb + λI) Wb' = Abᵀ(R + Ab Wb), returns (Wb', R', AbᵀAb) with
    R' = R - Ab (Wb' - Wb). R is donated (updated in place on device).
    """
    gram = Ab.T @ Ab
    rhs = Ab.T @ R + gram @ Wb
    Wb_new = _solve_psd(gram, rhs, jnp.asarray(lam, dtype=Ab.dtype))
    R_new = R - Ab @ (Wb_new - Wb)
    return Wb_new, R_new, gram


@functools.partial(jax.jit, static_argnames=("lam",), donate_argnums=(2,))
def _bcd_block_step_cached(Ab, Wb, R, lam: float, gram):
    """Later-epoch block update reusing a stashed Gramian: only the
    correlation re-reads the data, and the pass-through gram is not a jit
    output (which would copy it every step)."""
    rhs = Ab.T @ R + gram @ Wb
    Wb_new = _solve_psd(gram, rhs, jnp.asarray(lam, dtype=Ab.dtype))
    return Wb_new, R - Ab @ (Wb_new - Wb)


def _gram_cache_ok(num_iter: int, gram_bytes: int) -> bool:
    """Stash per-block Gramians across epochs only when the stash is small
    beside HBM (shared policy of the stepwise and fused flat paths)."""
    return num_iter > 1 and gram_bytes <= (1 << 30)


@functools.lru_cache(maxsize=8)  # bounded: cached meshes pin compiled executables
def _mesh_bcd_step(mesh, lam: float, use_pallas: bool):
    """Compiled per-block BCD step for a row-sharded design matrix.

    The Gramian + correlation are computed per shard — through the fused
    Pallas ``gram_corr_sym`` kernel when enabled (each shard's tile is
    unsharded inside shard_map, so ``pallas_call`` composes with the mesh)
    — then psum'd over the ``data`` axis: the explicit-collective form of
    the reference's per-partition Gramians + treeReduce (mlmatrix
    NormalEquations). Solve and weight update are replicated; the residual
    update partitions as a plain sharded GEMM.
    """
    axis = mesh_lib.DATA_AXIS

    def gram_corr_body(a, r):
        if use_pallas:
            from keystone_tpu.ops import pallas_ops

            gram, corr = pallas_ops.gram_corr_sym(a, r)
        else:
            acc = jnp.promote_types(a.dtype, jnp.float32)
            gram = jax.lax.dot_general(
                a, a, (((0,), (0,)), ((), ())), preferred_element_type=acc,
                **_hi_kwargs(a.dtype),
            )
            corr = _corr(a, r)
        return jax.lax.psum(gram, axis), jax.lax.psum(corr, axis)

    # check_vma=False: pallas_call outputs carry no varying-mesh-axes info,
    # so the static replication checker cannot see through them; the psums
    # above establish the replicated out_specs regardless.
    sharded_gram_corr = mesh_lib.shard_map(
        gram_corr_body,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=(P(), P()),
        check_vma=False,
    )

    sharded_corr = mesh_lib.shard_map(
        lambda a, r: jax.lax.psum(_corr(a, r), axis),
        mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=P(),
        check_vma=False,
    )

    def finish(Ab, Wb, R, gram, corr):
        Wb = Wb.astype(gram.dtype)
        rhs = corr + gram @ Wb
        Wb_new = _solve_psd(gram, rhs, jnp.asarray(lam, dtype=gram.dtype))
        delta = (Ab @ (Wb_new - Wb).astype(Ab.dtype)).astype(R.dtype)
        return Wb_new, R - delta

    @functools.partial(jax.jit, donate_argnums=(2,))
    def step(Ab, Wb, R):
        gram, corr = sharded_gram_corr(Ab, R)
        Wb_new, R_new = finish(Ab, Wb, R, gram, corr)
        return Wb_new, R_new, gram

    @functools.partial(jax.jit, donate_argnums=(2,))
    def step_cached(Ab, Wb, R, gram):
        """Later epochs: the Gramian is loop-invariant — only the
        correlation re-reads the sharded rows."""
        corr = sharded_corr(Ab, R)
        Wb_new, R_new = finish(Ab, Wb, R, gram, corr)
        return Wb_new, R_new

    return step, step_cached


def bcd_least_squares(
    A_blocks: Sequence,
    B,
    lam: float = 0.0,
    num_iter: int = 1,
    W_init: Optional[List] = None,
    mesh=None,
    use_pallas: Optional[bool] = None,
) -> List:
    """Block coordinate descent ridge regression over feature blocks.

    A_blocks: list of (n, d_b) arrays (feature-axis blocks of the design
    matrix, rows sharded over the data axis). B: (n, k). Returns the list of
    per-block weights W_b, each (d_b, k), minimizing
    ``||B - Σ_b A_b W_b||² + λ Σ_b ||W_b||²``.

    Host Python drives the (epoch × block) loop — the analog of the Spark
    driver — while each block step is one compiled sharded computation. All
    equally-shaped blocks share a single compiled executable. Pass ``mesh``
    (multi-device) to run each step's Gramian+correlation as an explicit
    shard_map program — with the fused Pallas kernels inside when enabled.
    """
    from keystone_tpu.ops import pallas_ops

    B = jnp.asarray(B)
    k = B.shape[1]
    Ws = (
        list(W_init)
        if W_init is not None
        else [jnp.zeros((Ab.shape[1], k), dtype=B.dtype) for Ab in A_blocks]
    )
    if W_init is not None:
        R = B - sum(Ab @ Wb for Ab, Wb in zip(A_blocks, Ws))
    else:
        # Fresh buffer: the block step donates R, and aliasing the caller's B
        # would delete it out from under them.
        R = jnp.array(B, copy=True)

    multi = mesh is not None and mesh_lib.axis_size(mesh, mesh_lib.DATA_AXIS) > 1
    if multi:
        if use_pallas is None:
            use_pallas = pallas_ops.pallas_enabled()
        step, step_cached = _mesh_bcd_step(mesh, float(lam), bool(use_pallas))
    else:
        step = step_cached = None

    # Stash loop-invariant per-block Gramians across epochs when the stash
    # is small beside HBM (shared policy with the fused flat path).
    # jnp.result_type reads the dtype without transferring host blocks.
    gram_bytes = sum(
        int(a.shape[1]) ** 2
        * jnp.promote_types(jnp.result_type(a), jnp.float32).itemsize
        for a in A_blocks
    )
    cache_grams = _gram_cache_ok(max(num_iter, 1), gram_bytes)
    grams: List = [None] * len(A_blocks)

    for _ in range(max(num_iter, 1)):
        for b, Ab in enumerate(A_blocks):
            Ab = jnp.asarray(Ab)
            if grams[b] is not None:
                if step_cached is not None:
                    Ws[b], R = step_cached(Ab, Ws[b], R, grams[b])
                else:
                    Ws[b], R = _bcd_block_step_cached(
                        Ab, Ws[b], R, float(lam), grams[b]
                    )
            else:
                if step is not None:
                    Ws[b], R, gram = step(Ab, Ws[b], R)
                else:
                    Ws[b], R, gram = _bcd_block_step(
                        Ab, Ws[b], R, float(lam)
                    )
                if cache_grams:
                    grams[b] = gram
            mesh_lib.sync_if_cpu(R)
    return Ws


# ---------------------------------------------------------------------------
# Fused (single-dispatch) block coordinate descent
# ---------------------------------------------------------------------------


# ``lam`` is a TRACED operand: λ-sweeps over one geometry reuse one
# compiled sweep (it reaches the solves as a numeric jitter only).
@functools.partial(
    jax.jit,
    static_argnames=("num_iter", "use_pallas", "sym", "cache_stash"),
)
def _bcd_fused_kernel(A_stack, B, W0, lam, num_iter: int,
                      use_pallas: bool, sym: bool, cache_stash: bool = True):
    def first_epoch_step(R, xs):
        """First sweep: compute (and, when caching, stash) each block's
        Gramian + Cholesky factor. Single-epoch runs — and models past the
        _gram_cache_ok budget (the stash is 2x nb*db^2 f32, ~536 MB at
        bench shapes) — skip the stash."""
        Ab, Wb = xs
        R, Wb_new, gram, chol = _bcd_block_update(Ab, R, Wb, lam, use_pallas, sym)
        empty = jnp.zeros((0,))
        stash = (
            (Wb_new, gram, chol)
            if (num_iter > 1 and cache_stash)
            else (Wb_new, empty, empty)
        )
        return R, stash

    def later_epoch_step(R, xs):
        """Later sweeps reuse the loop-invariant Gramians and factors —
        only the correlation AᵀR depends on the evolving residual."""
        Ab, Wb, gram, chol = xs
        R, Wb_new, _, _ = _bcd_block_update(
            Ab, R, Wb, lam, use_pallas, sym, gram=gram, chol=chol
        )
        return R, Wb_new

    R, (W, grams, chols) = jax.lax.scan(first_epoch_step, B, (A_stack, W0))
    if num_iter == 1:
        return W, R

    if cache_stash:
        def epoch(carry, _):
            R, W = carry
            R, W = jax.lax.scan(
                later_epoch_step, R, (A_stack, W, grams, chols)
            )
            return (R, W), None
    else:
        # Over-budget stash: later epochs recompute Gramian + factor
        # (rematerialization economics — the same policy as the flat path).
        def epoch(carry, _):
            R, W = carry
            R, (W, _, _) = jax.lax.scan(first_epoch_step, R, (A_stack, W))
            return (R, W), None

    (R, W), _ = jax.lax.scan(epoch, (R, W), None, length=num_iter - 1)
    return W, R


def _residual_dtype(feat_dtype, label_dtype):
    """Residual/solve dtype: at least f32 (bf16 features still accumulate in
    f32), promoted to f64 when either operand is double so fused results
    match the stepwise solver bit for bit."""
    acc = jnp.promote_types(feat_dtype, jnp.float32)
    return jnp.promote_types(acc, jnp.promote_types(label_dtype, jnp.float32))


def _hi_kwargs(feat_dtype):
    """f32 operands force HIGHEST precision (the TPU default is a single
    lossy bf16 pass); bf16 operands hit the MXU natively."""
    if feat_dtype == jnp.float32:
        return dict(precision=jax.lax.Precision.HIGHEST)
    return {}


def _bcd_block_update(Ab, R, Wb, lam: float, use_pallas: bool, sym: bool,
                      gram=None, chol=None):
    """One Gauss-Seidel block update shared by the fused solvers.

    Solves (AbᵀAb + λI) Wb' = AbᵀR + (AbᵀAb) Wb and returns
    (R - Ab (Wb' - Wb), Wb', AbᵀAb, cholesky). The residual delta is
    accumulated in f32 regardless of the feature layout dtype
    (preferred_element_type) so bf16 GEMM inputs never quantize the running
    residual. Pass ``gram`` (and ``chol``) to reuse the precomputed,
    loop-invariant Gramian/factor — only the correlation then recomputes.
    """
    from keystone_tpu.ops import pallas_ops

    feat_dtype = Ab.dtype
    # Accumulate in at least f32; f64 inputs keep f64 (a preferred type of
    # plain f32 would silently downcast double-precision accumulations).
    acc_dtype = jnp.promote_types(feat_dtype, jnp.float32)
    hi = _hi_kwargs(feat_dtype)
    if gram is None and use_pallas and acc_dtype == jnp.float32:
        # The Pallas kernels accumulate in f32; f64 inputs keep the XLA path
        # so the double-precision promotion below is honored.
        fn = pallas_ops.gram_corr_sym if sym else pallas_ops.gram_corr
        gram, corr = fn(Ab, R)
    else:
        if gram is None:
            gram = jax.lax.dot_general(
                Ab, Ab, (((0,), (0,)), ((), ())),
                preferred_element_type=acc_dtype, **hi,
            )
        corr = _corr(Ab, R)
    lam_t = jnp.asarray(lam, dtype=gram.dtype)
    if chol is None:
        chol = _psd_factor(gram, lam_t)
    rhs = corr + gram @ Wb
    Wb_new = _solve_psd(gram, rhs, lam_t, chol=chol)
    delta = jax.lax.dot_general(
        Ab, (Wb_new - Wb).astype(feat_dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=acc_dtype, **hi,
    )
    return R - delta, Wb_new, gram, chol


@functools.partial(
    jax.jit,
    static_argnames=("block", "num_iter", "use_pallas", "sym",
                     "cache_grams", "strided"),
)
def _bcd_fused_flat_kernel(F, B, W0, block: int, lam, num_iter: int,
                           use_pallas: bool, sym: bool,
                           cache_grams: bool = False, strided: bool = False):
    nb = F.shape[1] // block
    acc_dtype = jnp.promote_types(F.dtype, jnp.float32)

    def slice_block(F, W, bi):
        Ab = jax.lax.dynamic_slice_in_dim(F, bi * block, block, axis=1)
        Wb = jax.lax.dynamic_index_in_dim(W, bi, axis=0, keepdims=False)
        return Ab, Wb

    from keystone_tpu.ops import pallas_ops

    def strided_update(bi, R, Wb, gram=None, chol=None):
        """Block update where every F access streams the column window
        straight out of the flat buffer (scalar-prefetched base index) —
        no 2 GB dynamic_slice copy per block, which is pure HBM traffic
        the MXU never sees."""
        if gram is None:
            gram = pallas_ops.block_gram_sym(F, bi * block, block)
        corr = pallas_ops.block_corr(F, bi * block, block, R)
        lam_b = jnp.asarray(lam, dtype=gram.dtype)
        if chol is None:
            chol = _psd_factor(gram, lam_b)
        rhs = corr + gram @ Wb
        Wb_new = _solve_psd(gram, rhs, lam_b, chol=chol)
        R_new = pallas_ops.block_residual_update(
            F, bi * block, block, (Wb_new - Wb).astype(F.dtype), R
        )
        return R_new, Wb_new, gram, chol

    def first_block(bi, carry):
        """First sweep: compute (and, when caching, stash) each block's
        Gramian AND its Cholesky factor — both are loop-invariant across
        epochs; the Gramian recompute is the dominant per-epoch GEMM cost
        (n·d_b² vs the correlation's n·d_b·k) and the factorization is the
        dominant per-epoch non-GEMM cost."""
        R, W, G, C = carry
        if strided:
            Wb = jax.lax.dynamic_index_in_dim(W, bi, axis=0, keepdims=False)
            R, Wb_new, gram, chol = strided_update(bi, R, Wb)
        else:
            Ab, Wb = slice_block(F, W, bi)
            R, Wb_new, gram, chol = _bcd_block_update(
                Ab, R, Wb, lam, use_pallas, sym
            )
        W = jax.lax.dynamic_update_index_in_dim(W, Wb_new, bi, 0)
        if cache_grams:
            G = jax.lax.dynamic_update_index_in_dim(
                G, gram.astype(acc_dtype), bi, 0
            )
            C = jax.lax.dynamic_update_index_in_dim(
                C, chol.astype(acc_dtype), bi, 0
            )
        return R, W, G, C

    def later_block(bi, carry):
        R, W, G, C = carry
        gram = jax.lax.dynamic_index_in_dim(G, bi, axis=0, keepdims=False)
        chol = jax.lax.dynamic_index_in_dim(C, bi, axis=0, keepdims=False)
        if strided:
            Wb = jax.lax.dynamic_index_in_dim(W, bi, axis=0, keepdims=False)
            R, Wb_new, _, _ = strided_update(bi, R, Wb, gram=gram, chol=chol)
        else:
            Ab, Wb = slice_block(F, W, bi)
            R, Wb_new, _, _ = _bcd_block_update(
                Ab, R, Wb, lam, use_pallas, sym, gram=gram, chol=chol
            )
        return R, jax.lax.dynamic_update_index_in_dim(W, Wb_new, bi, 0), G, C

    stash_shape = (nb, block, block) if cache_grams else (0, 0, 0)
    G0 = jnp.zeros(stash_shape, dtype=acc_dtype)
    C0 = jnp.zeros(stash_shape, dtype=acc_dtype)
    R, W, G, C = jax.lax.fori_loop(0, nb, first_block, (B, W0, G0, C0))

    if num_iter > 1:
        body = later_block if cache_grams else first_block

        def epoch(_, carry):
            return jax.lax.fori_loop(0, nb, body, carry)

        R, W, G, C = jax.lax.fori_loop(0, num_iter - 1, epoch, (R, W, G, C))
    return W, R


def bcd_least_squares_fused_flat(
    F,
    B,
    block_size: int,
    lam: float = 0.0,
    num_iter: int = 1,
    use_pallas: Optional[bool] = None,
    return_residual: bool = False,
):
    """Single-dispatch BCD over a *flat* (n, d) feature matrix.

    Functionally identical to ``bcd_least_squares_fused`` on the column
    blocks ``F[:, i*block : (i+1)*block]``, but the features live in one
    contiguous buffer — at large n the stacked layout cannot be produced
    without a second full-size copy (stack of independently-computed block
    buffers), which is the difference between fitting in HBM and not.
    Multi-epoch runs stash the loop-invariant per-block Gramians when the
    (nb, d_b, d_b) buffer is small next to HBM (≤1 GB), making epochs 2+
    pay only the correlation + solve + residual update; larger models fall
    back to recomputation (rematerialization economics).
    """
    from keystone_tpu.ops import pallas_ops

    F = jnp.asarray(F)
    B = jnp.asarray(B)
    B = B.astype(_residual_dtype(F.dtype, B.dtype))
    if F.dtype != jnp.bfloat16:
        F = F.astype(B.dtype)
    n, d = F.shape
    if d % block_size != 0:
        raise ValueError(f"feature dim {d} not divisible by block {block_size}")
    nb = d // block_size
    if use_pallas is None:
        use_pallas = pallas_ops.pallas_direct_ok(F)
    W0 = jnp.zeros((nb, block_size, B.shape[1]), dtype=B.dtype)
    acc_itemsize = jnp.promote_types(F.dtype, jnp.float32).itemsize
    # x2: the stash holds Gramians AND their Cholesky factors.
    cache_grams = _gram_cache_ok(
        int(num_iter), 2 * nb * block_size * block_size * acc_itemsize
    )
    # Strided column-window kernels (no per-block dynamic_slice copy of F)
    # need tile-aligned shapes and an f32 accumulation dtype; everything in
    # the update then runs lane-padded to a 128 multiple, so pad the labels
    # once up front and slice the model on the way out (the padded label
    # columns are zero, and stay zero through every solve).
    strided = (
        bool(use_pallas)
        and jnp.promote_types(F.dtype, jnp.float32) == jnp.float32
        and pallas_ops.strided_gram_ok(F, block_size)
    )
    k_orig = B.shape[1]
    if strided and k_orig % 128:
        tr = ((k_orig + 127) // 128) * 128
        B = jnp.pad(B, ((0, 0), (0, tr - k_orig)))
        W0 = jnp.zeros((nb, block_size, tr), dtype=B.dtype)
    W, R = _bcd_fused_flat_kernel(
        F, B, W0, int(block_size), lam, max(int(num_iter), 1),
        bool(use_pallas), True, cache_grams, strided,
    )
    if W.shape[2] != k_orig:
        W = W[:, :, :k_orig]
        R = R[:, :k_orig]
    return (W, R) if return_residual else W


def bcd_least_squares_fused(
    A_stack,
    B,
    lam: float = 0.0,
    num_iter: int = 1,
    W_init=None,
    use_pallas: Optional[bool] = None,
    return_residual: bool = False,
):
    """Single-dispatch block coordinate descent over equal-sized blocks.

    A_stack: (num_blocks, n, d_b) stacked feature blocks — may be bfloat16,
    in which case GEMMs run natively on the MXU with float32 accumulation
    (the solve and residual stay float32). The entire (epochs × blocks)
    Gauss-Seidel sweep is one compiled program: ``lax.scan`` over blocks
    inside ``lax.scan`` over epochs, with the Gramian+correlation computed by
    the fused Pallas ``gram_corr_sym`` kernel on TPU (upper-triangle blocks
    only — the BLAS ``syrk`` trick) and plain XLA contractions elsewhere.

    Against the per-block host-driven loop (``bcd_least_squares``), this
    removes every intermediate host dispatch — the analog of replacing the
    reference's per-block Spark job waves (mlmatrix BlockCoordinateDescent)
    with one compiled program over the mesh.
    """
    from keystone_tpu.ops import pallas_ops

    A_stack = jnp.asarray(A_stack)
    B = jnp.asarray(B)
    B = B.astype(_residual_dtype(A_stack.dtype, B.dtype))
    if A_stack.dtype != jnp.bfloat16:
        # Unify operand dtypes up front (except the intentional bf16 feature
        # layout) so the block updates run entirely in the residual dtype —
        # e.g. f32 features with f64 labels solve in f64.
        A_stack = A_stack.astype(B.dtype)
    nb, n, db = A_stack.shape
    k = B.shape[1]
    if use_pallas is None:
        use_pallas = pallas_ops.pallas_direct_ok(A_stack)
    W0 = (
        jnp.asarray(W_init, dtype=B.dtype)
        if W_init is not None
        else jnp.zeros((nb, db, k), dtype=B.dtype)
    )
    if W_init is not None:
        # A_stack is already unified with B's dtype (bf16 features upcast
        # here so the warm-start residual keeps full precision).
        B = B - sum(
            jnp.dot(
                A_stack[i].astype(B.dtype), W0[i],
                precision=jax.lax.Precision.HIGHEST,
            )
            for i in range(nb)
        )
    acc_itemsize = jnp.promote_types(A_stack.dtype, jnp.float32).itemsize
    # x2: the stash holds Gramians AND their Cholesky factors (same budget
    # policy as the flat path).
    cache_stash = _gram_cache_ok(
        int(num_iter), 2 * nb * db * db * acc_itemsize
    )
    W, R = _bcd_fused_kernel(
        A_stack, B, W0, lam, max(int(num_iter), 1),
        bool(use_pallas), True, cache_stash,
    )
    return (W, R) if return_residual else W


# ---------------------------------------------------------------------------
# TSQR
# ---------------------------------------------------------------------------


def tsqr_r(A, mesh=None) -> jax.Array:
    """R factor of a tall-skinny QR, computed shard-locally then combined.

    The analog of mlmatrix ``TSQR().qrR``: each data shard computes a local
    (d, d) R; the stacked Rs get a final QR. Sign convention: R has
    non-negative diagonal. Falls back to a direct QR when unsharded.
    """
    A = jnp.asarray(A)
    d = A.shape[1]
    sharding = getattr(A, "sharding", None)
    mesh = mesh or (getattr(sharding, "mesh", None) if sharding is not None else None)

    if mesh is None or mesh_lib.DATA_AXIS not in getattr(mesh, "shape", {}):
        r = jnp.linalg.qr(A, mode="r")
    else:
        num = mesh.shape[mesh_lib.DATA_AXIS]

        def local_qr(a_shard):
            r_local = jnp.linalg.qr(a_shard, mode="r")
            # (1, d, d) leaf per shard -> stacked on the data axis
            return r_local[None]

        stacked = mesh_lib.shard_map(
            local_qr,
            mesh=mesh,
            in_specs=P(mesh_lib.DATA_AXIS),
            out_specs=P(mesh_lib.DATA_AXIS),
        )(A)
        stacked = stacked.reshape(num * d, d)
        r = jnp.linalg.qr(stacked, mode="r")

    # Fix signs so the diagonal is non-negative (deterministic convention).
    signs = jnp.sign(jnp.diagonal(r))
    signs = jnp.where(signs == 0, 1.0, signs)
    return r * signs[:, None]


def distributed_gram(A):
    """AᵀA over sharded rows (per-shard GEMM + all-reduce)."""
    A = jnp.asarray(A)
    return A.T @ A


def column_means(A, n: Optional[int] = None):
    """Column means over the true row count (padding rows are zero)."""
    A = jnp.asarray(A)
    count = A.shape[0] if n is None else n
    return jnp.sum(A, axis=0) / count
