"""Parallel substrate: device meshes, sharding helpers, distributed linear algebra."""

from . import mesh
from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    default_mesh,
    make_mesh,
    pad_rows,
    replicate,
    set_default_mesh,
    shard_rows,
    use_mesh,
)
