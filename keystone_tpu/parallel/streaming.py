"""Out-of-core (streaming / tiled) least-squares: the memory-wall crosser.

The reference's substrate streams by construction: ``CsvDataLoader`` is a
lazy ``textFile`` (CsvDataLoader.scala:10-31), and the block solvers
accumulate per-partition Gramians + correlations into a ``treeReduce``
(BlockWeightedLeastSquares.scala:177-313) — the full feature matrix never
exists on any machine. This module is the TPU-native analog: features are
*generated per row tile* inside a scanned sweep (fused featurize kernel),
each tile contributes

    G  += FₜᵀFₜ          (accumulating symmetric Pallas kernel — syrk)
    FY += FₜᵀYₜ
    yty += ΣYₜ²

and the (tile_rows, d) feature slab is the only feature storage that ever
exists. At TIMIT's real scale (n=2.2e6, d=16384) the materialized feature
matrix would be 72 GB of bf16 against 16 GB of HBM; the streamed state is
G (1.07 GB f32) + one slab (~2 GB bf16) + the raw input (3.9 GB f32).

The solve then runs block Gauss-Seidel directly on the normal equations:

    W_b ← (G_bb + λI)⁻¹ (FY_b − Σ_{j≠b} G_bj W_j)

which is algebraically the SAME iterate sequence as residual-maintaining
BCD (``linalg.bcd_least_squares_fused_flat``) — the residual is simply
eliminated through R = Y − F W. Extra epochs cost only (d, block)×(block,
k) GEMMs on the cached Gramian — no data pass — where the residual form
pays a full re-featurize per block per epoch.

Mesh story: rows shard over the ``data`` axis; each device folds its local
tiles, then ONE psum of (G, FY, yty) per fit crosses the interconnect —
the explicit-collective form of the reference's treeReduce, and the
minimum possible communication for this algorithm.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_lib
from .linalg import _psd_factor, _solve_psd

Array = jax.Array

# Default HBM budget for one feature slab (the streamed working set).
_DEFAULT_SLAB_BYTES = 2 << 30
# Row alignment the Pallas accumulating-syrk kernel needs (its k-tile).
_ROW_ALIGN = 512


def pick_tile_rows(
    d_feat: int,
    feat_itemsize: int = 2,
    slab_bytes: int = _DEFAULT_SLAB_BYTES,
) -> int:
    """Largest _ROW_ALIGN-multiple tile whose feature slab fits the budget."""
    rows = max(slab_bytes // max(d_feat * feat_itemsize, 1), _ROW_ALIGN)
    return max((rows // _ROW_ALIGN) * _ROW_ALIGN, _ROW_ALIGN)


class BoundedInflight:
    """Bound the device dispatch queue of a host-driven segment loop.

    ``admit(x)`` enqueues a tiny NON-donated probe derived from the
    segment's carry (the ``+ 0.0`` keeps it off the donated buffers) and
    blocks on the oldest once more than ``limit`` are in flight — the
    next segment's host load/transfer overlaps device compute while the
    queue (and the tunnel watchdog's view of it) stays bounded. Shared
    by the dense and sparse segmented folds.
    """

    def __init__(self, limit: int):
        from collections import deque

        self._limit = max(int(limit), 1)
        self._probes = deque()

    def admit(self, scalar) -> None:
        self._probes.append(scalar + 0.0)
        while len(self._probes) > self._limit:
            float(self._probes.popleft())


def _row_mask(M, valid):
    """Zero rows at index >= valid (padding rows must not touch G/FY)."""
    idx = jax.lax.broadcasted_iota(jnp.int32, (M.shape[0], 1), 0)
    return jnp.where(idx < valid, M, jnp.zeros((), M.dtype))


def _tile_update(G, FY, yty, fsum, ysum, X_t, Y_t, featurize, use_pallas,
                 valid: Optional[Array]):
    """Fold one row tile into (G, FY, yty, fsum, ysum). ``valid`` (traced
    scalar) masks rows >= valid; None means the whole tile is valid (no
    mask pass).

    Masking zeroes the *feature* rows, not just X rows: a zero input row
    still featurizes to cos(b) — a nonzero constant — so padding must be
    excluded after featurization.

    The column sums (fsum, ysum) ride the same pass so the centered
    solvers get their means for free — two vector reductions per tile,
    ~1/d_feat of the syrk's work.
    """
    from keystone_tpu.ops import pallas_ops

    F_t = featurize(X_t)
    if valid is not None:
        F_t = _row_mask(F_t, valid)
        Y_t = _row_mask(Y_t, valid)
    acc = jnp.promote_types(F_t.dtype, jnp.float32)
    if use_pallas and pallas_ops.gram_acc_ok(F_t):
        G = pallas_ops.gram_sym_acc(G, F_t)
    else:
        G = G + jax.lax.dot_general(
            F_t, F_t, (((0,), (0,)), ((), ())), preferred_element_type=acc,
        ).astype(jnp.float32)
    FY = FY + jax.lax.dot_general(
        F_t, Y_t.astype(F_t.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=acc,
    ).astype(jnp.float32)
    Yf = Y_t.astype(jnp.float32)
    # dtype=f32 so bf16 feature slabs accumulate their column sums at the
    # same precision as the G/FY folds (a bf16 reduction would bias the
    # centered solve: cos features have near-zero means, all cancellation).
    fsum = fsum + jnp.sum(F_t, axis=0, dtype=jnp.float32)
    ysum = ysum + jnp.sum(Yf, axis=0)
    return G, FY, yty + jnp.sum(Yf * Yf), fsum, ysum


def gram_stats(
    X: Array,
    Y: Array,
    featurize: Callable[[Array], Array],
    d_feat: int,
    tile_rows: int,
    use_pallas: bool = False,
    valid=None,
    labelize: Optional[Callable[[Array], Array]] = None,
    moments: bool = False,
) -> Tuple[Array, ...]:
    """Accumulate (G = FᵀF, FY = FᵀY, yty = ΣY²) over row tiles of X.

    With ``moments=True`` also returns the per-column sums
    (fsum = Σᵢ fᵢ, ysum = Σᵢ yᵢ) accumulated in the SAME pass — the
    centered solvers' means, so mean-centering costs no extra data pass
    (the streamed analog of BlockLinearMapper.scala:224-243's per-block
    StandardScalers). Returns (G, FY, yty) or (G, FY, yty, fsum, ysum).

    Traceable (call under jit). X: (n, d_in) — or PRE-TILED (T, tile_rows,
    d_in), which large fits should prefer: handing the program already-
    tiled operands removes the in-program reshape, which XLA materializes
    as a second full-size (lane-padded) copy of X — ~5 GB at the TIMIT
    geometry. Y: (n, k) / (T, tile_rows, k), or raw per-row labels of any
    trailing shape when ``labelize`` is given (e.g. int class ids;
    ``labelize`` maps a (tile_rows, ...) label slice to the (tile_rows, k)
    regression target per tile — a one-hot target then never exists at
    full n).

    The feature matrix F = featurize(X) — (n, d_feat), conceptually — is
    produced one (tile_rows, d_feat) slab at a time and never
    materialized. Full tiles run through a ``lax.scan``; a ragged
    remainder is padded to the kernel's row alignment and masked.

    ``valid`` excludes trailing padding rows (their FEATURE rows are
    zeroed — a zero input row still featurizes to cos(b) ≠ 0). A static
    int masks only the boundary tile (full tiles before it run unmasked,
    tiles past it are skipped at trace time); a traced scalar masks every
    tile — mesh callers with per-shard counts use that form. Returns G
    with BOTH triangles valid.
    """
    pre_tiled = X.ndim == 3
    if pre_tiled:
        num_full, tile_rows = int(X.shape[0]), int(X.shape[1])
        rem = 0
        Xs, Ys = X, Y
    else:
        n = X.shape[0]
        num_full = n // tile_rows
        rem = n - num_full * tile_rows
        if num_full:
            Xs = X[: num_full * tile_rows].reshape(
                (num_full, tile_rows) + X.shape[1:]
            )
            Ys = Y[: num_full * tile_rows].reshape(
                (num_full, tile_rows) + Y.shape[1:]
            )
        else:
            Xs = Ys = None

    if labelize is None:
        labelize = lambda y_t: y_t  # noqa: E731 — identity target map
        k = int(Y.shape[-1])
    else:
        y_slice = jax.eval_shape(lambda a: a[0], Ys) if num_full else Y
        k = int(jax.eval_shape(labelize, y_slice).shape[-1])

    static_valid = valid is not None and not isinstance(valid, jax.core.Tracer)
    if static_valid:
        valid = int(valid)
        # Full tiles entirely inside `valid` run unmasked; the boundary
        # tile masks once; tiles entirely past `valid` never execute.
        num_unmasked = min(valid // tile_rows, num_full)
    else:
        num_unmasked = num_full if valid is None else 0

    carry = (
        jnp.zeros((d_feat, d_feat), jnp.float32),
        jnp.zeros((d_feat, k), jnp.float32),
        jnp.zeros((), jnp.float32),
        jnp.zeros((d_feat,), jnp.float32),
        jnp.zeros((k,), jnp.float32),
    )

    def fold(carry, X_t, y_t, tile_valid):
        return _tile_update(
            *carry, X_t, labelize(y_t), featurize, use_pallas, tile_valid
        )

    if num_unmasked:

        def body(carry, xs):
            X_t, y_t = xs
            return fold(carry, X_t, y_t, None), None

        carry, _ = jax.lax.scan(
            body, carry, (Xs[:num_unmasked], Ys[:num_unmasked])
        )

    if static_valid:
        for t in range(num_unmasked, num_full):
            tile_valid = min(max(valid - t * tile_rows, 0), tile_rows)
            if tile_valid == 0:
                break
            carry = fold(
                carry, Xs[t], Ys[t], jnp.asarray(tile_valid, jnp.int32)
            )
    elif valid is not None and num_full:

        def body(carry, xs):
            X_t, y_t, t = xs
            tile_valid = jnp.clip(valid - t * tile_rows, 0, tile_rows)
            return fold(carry, X_t, y_t, tile_valid.astype(jnp.int32)), None

        carry, _ = jax.lax.scan(body, carry, (Xs, Ys, jnp.arange(num_full)))

    if rem:
        pad = (-rem) % _ROW_ALIGN
        X_r = jnp.pad(X[num_full * tile_rows :], ((0, pad), (0, 0)))
        y_r = jnp.pad(
            Y[num_full * tile_rows :],
            ((0, pad),) + ((0, 0),) * (Y.ndim - 1),
        )
        rem_valid = rem
        if static_valid:
            rem_valid = min(max(valid - num_full * tile_rows, 0), rem)
        if rem_valid:
            rv = jnp.asarray(rem_valid, jnp.int32)
            if valid is not None and not static_valid:
                rv = jnp.minimum(
                    rv, jnp.clip(valid - num_full * tile_rows, 0, rem)
                ).astype(jnp.int32)
            carry = fold(carry, X_r, y_r, rv)

    G, FY, yty, fsum, ysum = carry
    # The Pallas accumulation writes upper-triangle blocks only; mirroring
    # from triu is also exact for the XLA path (G symmetric).
    G = jnp.triu(G) + jnp.triu(G, 1).T
    if moments:
        return G, FY, yty, fsum, ysum
    return G, FY, yty


def bcd_from_gram(
    G: Array,
    FY: Array,
    block_size: int,
    lam: float,
    num_iter: int,
) -> Array:
    """Block Gauss-Seidel ridge solve on accumulated normal equations.

    Returns W as (nb, block_size, k) — the same iterate sequence as
    residual-form BCD (the residual is eliminated algebraically; see module
    docstring). Per-block Cholesky factors are computed once; every epoch
    costs nb (d, block)×(block, k) GEMMs against the cached G — no data.
    """
    d, k = FY.shape
    if num_iter < 1:
        raise ValueError(f"num_iter must be >= 1, got {num_iter}")
    if d % block_size:
        raise ValueError(f"feature dim {d} not divisible by {block_size}")
    nb = d // block_size
    lam_t = jnp.asarray(lam, G.dtype)

    # (nb, bs, bs) stack of diagonal blocks + factors (loop-invariant).
    diag = jnp.stack(
        [
            G[b * block_size : (b + 1) * block_size,
              b * block_size : (b + 1) * block_size]
            for b in range(nb)
        ]
    )
    chols = jax.vmap(lambda g: _psd_factor(g, lam_t))(diag)

    W0 = jnp.zeros((nb, block_size, k), G.dtype)
    S0 = jnp.zeros((d, k), G.dtype)  # S = G @ W_flat, maintained

    def block_step(b, carry):
        W, S = carry
        Wb = jax.lax.dynamic_index_in_dim(W, b, 0, keepdims=False)
        Gbb = jax.lax.dynamic_index_in_dim(diag, b, 0, keepdims=False)
        ch = jax.lax.dynamic_index_in_dim(chols, b, 0, keepdims=False)
        Sb = jax.lax.dynamic_slice_in_dim(S, b * block_size, block_size, 0)
        FYb = jax.lax.dynamic_slice_in_dim(FY, b * block_size, block_size, 0)
        # S_b = Σ_j G_bj W_j includes j = b; add G_bb W_b back to exclude it.
        rhs = FYb - Sb + Gbb @ Wb
        Wb_new = _solve_psd(Gbb, rhs, lam_t, chol=ch)
        # Column block of G via transposed row slice (G symmetric) — the
        # row slice is contiguous; a column slice is a strided gather.
        Gcol = jax.lax.dynamic_slice_in_dim(
            G, b * block_size, block_size, 0
        ).T
        S = S + Gcol @ (Wb_new - Wb)
        return jax.lax.dynamic_update_index_in_dim(W, Wb_new, b, 0), S

    def epoch(_, carry):
        return jax.lax.fori_loop(0, nb, block_step, carry)

    W, _ = jax.lax.fori_loop(0, num_iter, epoch, (W0, S0))
    return W


class BankFeaturize:
    """Featurize whose array parameters ride as jit OPERANDS, not trace
    constants.

    The closure-based fit programs key their compile cache on the
    featurize CALLABLE's identity and embed any captured arrays as HLO
    constants — so rebuilding a logically-equal bank (λ-sweeps, pipeline
    re-optimization) recompiles the whole tile scan, and a TIMIT-scale
    bank (~360 MB) becomes a constant the remote-compile transport
    rejects. Subclasses instead expose

      - ``params``: pytree of arrays (passed as traced operands),
      - ``static_key()``: hashable non-array config,
      - classmethod ``apply_bank(static_key, params, X_t)``: the traceable
        featurize, resolved through the CLASS (stable identity),

    and the fit dispatchers key the program on (class, static_key, operand
    shapes) — one executable per geometry, shared across bank instances.
    ``__call__`` keeps instances usable as plain featurize callables
    (predict path, gram_stats, tests).
    """

    @property
    def params(self):
        raise NotImplementedError

    def static_key(self) -> tuple:
        return ()

    @classmethod
    def apply_bank(cls, static_key, params, X_t):
        raise NotImplementedError

    def __call__(self, X_t):
        return type(self).apply_bank(self.static_key(), self.params, X_t)


class CallableBank(BankFeaturize):
    """Any traceable featurize callable through the BankFeaturize
    contract: no operand arrays; the callable itself is the static key,
    so the segmented folds' jit cache keys on its identity exactly like
    the closure-path fits (one executable per callable per geometry).
    Lets ``streaming_bcd_fit_segments`` — whose fold is bank-keyed —
    drive composed/fused featurize programs and the identity path."""

    def __init__(self, fn: Callable):
        self.fn = fn

    @property
    def params(self):
        return ()

    def static_key(self) -> tuple:
        return (self.fn,)

    @classmethod
    def apply_bank(cls, static_key, params, X_t):
        return static_key[0](X_t)


def as_bank(featurize) -> BankFeaturize:
    """Normalize a featurize to the BankFeaturize contract."""
    if isinstance(featurize, BankFeaturize):
        return featurize
    return CallableBank(featurize)


def _fit_core(X, Y, featurize, d_feat, tile_rows, block_size, lam,
              num_iter, use_pallas, valid, labelize, center):
    """Shared traceable fit body: tile folds → (optional rank-1 centering)
    → BCD on the normal equations. Returns (W, loss, yty, fmean, ymean);
    fmean/ymean are None when ``center`` is False (static branch)."""
    n_true = valid if valid is not None else (
        X.shape[0] if X.ndim == 2 else X.shape[0] * X.shape[1]
    )
    if center:
        G, FY, yty, fsum, ysum = gram_stats(
            X, Y, featurize, d_feat, tile_rows, use_pallas=use_pallas,
            valid=valid, labelize=labelize, moments=True,
        )
    else:
        G, FY, yty = gram_stats(
            X, Y, featurize, d_feat, tile_rows, use_pallas=use_pallas,
            valid=valid, labelize=labelize,
        )
        fsum = ysum = None
    # W blocks are laid out [b*block : (b+1)*block] along d, so Wf rows
    # align with G/FY rows (shared solve tail).
    W, loss, fmean, ymean = _solve_from_stats_core(
        G, FY, yty, fsum, ysum, n_true, lam, block_size, num_iter, center
    )
    return W, loss, yty, fmean, ymean


@functools.partial(
    jax.jit,
    static_argnames=(
        "featurize", "d_feat", "tile_rows", "block_size", "num_iter",
        "use_pallas", "valid", "labelize", "center",
    ),
)
def _streaming_fit_closure(X, Y, *, featurize, d_feat, tile_rows,
                           block_size, lam, num_iter, use_pallas, valid,
                           labelize, center):
    return _fit_core(X, Y, featurize, d_feat, tile_rows, block_size, lam,
                     num_iter, use_pallas, valid, labelize, center)


@functools.partial(
    jax.jit,
    static_argnames=(
        "bank_type", "bank_key", "d_feat", "tile_rows", "block_size",
        "num_iter", "use_pallas", "valid", "labelize", "center",
    ),
)
def _streaming_fit_bank(X, Y, bank_params, *, bank_type, bank_key, d_feat,
                        tile_rows, block_size, lam, num_iter, use_pallas,
                        valid, labelize, center):
    featurize = lambda X_t: bank_type.apply_bank(bank_key, bank_params, X_t)  # noqa: E731
    return _fit_core(X, Y, featurize, d_feat, tile_rows, block_size, lam,
                     num_iter, use_pallas, valid, labelize, center)


def _dispatch_fit(X, Y, featurize, center, kw):
    if isinstance(featurize, BankFeaturize):
        return _streaming_fit_bank(
            X, Y, featurize.params, bank_type=type(featurize),
            bank_key=featurize.static_key(), center=center, **kw,
        )
    return _streaming_fit_closure(
        X, Y, featurize=featurize, center=center, **kw,
    )


# ``lam`` is a TRACED operand (not static): a λ-sweep over one geometry
# reuses one compiled program instead of recompiling the whole tile scan
# per λ (VERDICT r4 Weak #3). A :class:`BankFeaturize` featurize further
# keys the program on bank SHAPES rather than callable identity.
def streaming_bcd_fit(
    X: Array,
    Y: Array,
    *,
    featurize: Callable[[Array], Array],
    d_feat: int,
    tile_rows: int,
    block_size: int,
    lam: float,
    num_iter: int,
    use_pallas: bool = False,
    valid: Optional[int] = None,
    labelize: Optional[Callable[[Array], Array]] = None,
    mesh=None,
) -> Tuple[Array, Array, Array]:
    """One-dispatch streamed fit: tiles → (G, FY, yty) → BCD epochs.

    X may be (n, d_in) or pre-tiled (T, tile_rows, d_in) — see
    :func:`gram_stats` for why large fits should pre-tile (and for the
    ``valid`` / ``labelize`` contracts; both must be static here).
    Returns (W, train_loss, yty) with W: (nb, block_size, k). The train
    loss ||Y − FW||²/n comes algebraically from the accumulated stats —
    (yty − 2·tr(Wᵀ FY) + tr(Wᵀ G W))/n — two small GEMMs, no data pass.

    ``mesh`` (ISSUE 16): shard the tile folds over the mesh's data axis
    (each device folds its row shard locally; ONE psum of the stats
    crosses the ICI — :func:`gram_stats_mesh`) with a replicated solve —
    the same iterates as the 1-device fit up to reduction order. X rows
    must divide evenly over the axis (pad and pass ``valid``);
    ``labelize`` is not supported on this path (pre-apply it to Y).
    """
    if mesh is not None:
        if labelize is not None:
            raise ValueError(
                "labelize is not supported with mesh=; pre-apply it to Y "
                "(the mesh fold shards Y rows alongside X)"
            )
        n_true = valid if valid is not None else (
            X.shape[0] if X.ndim == 2 else X.shape[0] * X.shape[1]
        )
        G, FY, yty = gram_stats_mesh(
            X, Y, featurize, d_feat, tile_rows, mesh,
            use_pallas=use_pallas, n_true=valid,
        )
        W, loss, _, _ = _solve_from_stats_core(
            G, FY, yty, None, None, n_true, lam, block_size, num_iter,
            False,
        )
        return W, loss, yty
    W, loss, yty, _, _ = _dispatch_fit(
        X, Y, featurize, False,
        dict(d_feat=d_feat, tile_rows=tile_rows, block_size=block_size,
             lam=lam, num_iter=num_iter, use_pallas=use_pallas,
             valid=valid, labelize=labelize),
    )
    return W, loss, yty


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=(
    "bank_type", "bank_key", "tile_rows", "use_pallas",
))
def _dense_segment_fold(carry, X_seg, Y_seg, valid_rows, bank_params, *,
                        bank_type, bank_key, tile_rows, use_pallas):
    """Fold one SEGMENT of pre-tiled rows into the (G, FY, yty, fsum,
    ysum) carry — the dense analog of the sparse segmented fold: segments
    may be loaded from disk one at a time, so neither HBM nor host RAM
    ever holds the dataset. The carry is donated (G dominates);
    ``valid_rows`` (traced) masks the ragged tail of the LAST segment.
    The featurize bank rides as traced operands (BankFeaturize contract:
    one compiled fold for every segment and every logically-equal bank).
    """
    featurize = lambda X_t: bank_type.apply_bank(bank_key, bank_params, X_t)  # noqa: E731
    G, FY, yty, fsum, ysum = carry

    def body(c, xs):
        X_t, Y_t, t0 = xs
        tile_valid = jnp.clip(valid_rows - t0, 0, tile_rows).astype(jnp.int32)
        return _tile_update(
            *c, X_t, Y_t, featurize, use_pallas, tile_valid
        ), None

    starts = jnp.arange(X_seg.shape[0]) * tile_rows
    (G, FY, yty, fsum, ysum), _ = jax.lax.scan(
        body, (G, FY, yty, fsum, ysum), (X_seg, Y_seg, starts)
    )
    return G, FY, yty, fsum, ysum


def streaming_bcd_fit_segments(
    segment_source,
    num_segments: Optional[int] = None,
    n_true: Optional[int] = None,
    bank=None,
    d_feat: int = None,
    tile_rows: int = None,
    block_size: int = None,
    lam=0.0,
    num_iter: int = 1,
    use_pallas: bool = False,
    center: bool = True,
    inflight: int = 2,
    prefetch_depth: int = 2,
    prefetch_stats=None,
    checkpoint=None,
):
    """Disk-bounded dense streamed fit: fold (G, FY, moments) over
    segments delivered one at a time (e.g.
    :class:`keystone_tpu.data.shards.DiskDenseShards.segment_source` over
    memory-mapped tiles), then solve with (optionally centered) BCD on
    the normal equations. The dense analog of
    ``run_lbfgs_gram_streamed(segment_source=...)``: n is bounded by
    DISK, not host RAM or HBM.

    ``segment_source``: either a :class:`keystone_tpu.data.prefetch.
    ShardSource` (then ``num_segments``/``n_true`` default from it and a
    background reader thread prefetches segment k+1 while segment k's
    H2D transfer + fold are in flight — ``prefetch_depth`` bounds the
    staged-host-buffer depth; 0 loads serially, byte-identical results),
    or the legacy callable ``segment_source(s) -> (X_seg (T, tile_rows,
    d_in), Y_seg (T, tile_rows, k), valid_rows)`` — valid_rows counts the
    segment's true rows (phantom/padding tiles past it are masked); the
    callable form loads serially (a callable makes no thread-safety
    promise). ``bank`` may be any featurize callable (wrapped via
    :class:`CallableBank` when not already a BankFeaturize). Returns
    (W, fmean, ymean, loss) when centered, else (W, None, None, loss).

    ``checkpoint``: a :class:`keystone_tpu.data.durable.CheckpointSpec`
    (or directory path; None consults ``KEYSTONE_CHECKPOINT_DIR``) that
    atomically snapshots the fold carry — the (G, FY, yty, fsum, ysum)
    accumulators plus the segment cursor — every ``every_segments``
    segments. A fit killed mid-stream and re-run with the same spec
    resumes at the last snapshot and produces BIT-IDENTICAL results to
    the uninterrupted run (the carry round-trips as raw f32 bytes and
    the remaining segments fold through the same compiled program —
    proven under injected kills in tests/test_chaos.py). The snapshot is
    cleared on successful completion.
    """
    from keystone_tpu.data.durable import (
        fingerprint_token,
        resolve_checkpoint,
        source_fingerprint,
    )
    from keystone_tpu.data.prefetch import is_shard_source, iter_segments

    checkpoint = resolve_checkpoint(checkpoint)

    if is_shard_source(segment_source):
        if num_segments is None:
            num_segments = segment_source.num_segments
        if n_true is None:
            n_true = segment_source.n_true
        if tile_rows is None:
            tile_rows = segment_source.tile_rows
    else:
        prefetch_depth = 0  # plain callables make no thread-safety promise
    if num_segments is None or n_true is None:
        raise ValueError(
            "callable segment sources need explicit num_segments and n_true"
        )
    if bank is None or d_feat is None or tile_rows is None or block_size is None:
        # Fail here, not as a cryptic NoneType error mid-trace: only
        # tile_rows defaults (from a ShardSource) — the rest are required.
        raise ValueError(
            "streamed segment fit needs bank, d_feat, block_size, and "
            "tile_rows (tile_rows defaults only from a ShardSource)"
        )
    bank = as_bank(bank)
    bank_type, bank_key = type(bank), bank.static_key()
    bank_params = bank.params  # raw pytree — the BankFeaturize contract
    carry = None
    start = 0
    fingerprint = None
    if checkpoint is not None:
        # Geometry + featurizer identity (type, static key, parameter
        # digests) + source identity: a stale snapshot from a different
        # bank or a re-ingested shard directory must never seed this
        # fold's accumulators.
        fingerprint = {
            "kind": "dense_bcd_segments",
            "num_segments": int(num_segments), "n_true": int(n_true),
            "d_feat": int(d_feat), "tile_rows": int(tile_rows),
            "bank": {
                "type": bank_type.__name__,
                "key": fingerprint_token(bank_key),
                "params": fingerprint_token(
                    tuple(jax.tree_util.tree_leaves(bank_params))
                ),
            },
            "source": source_fingerprint(segment_source),
        }
        arrays, start = checkpoint.restore(fingerprint)
        if arrays is not None:
            carry = tuple(jnp.asarray(a) for a in arrays)
    throttle = BoundedInflight(inflight)
    import time as _time

    from keystone_tpu import obs as _obs

    for s, (X_seg, Y_seg, valid_rows) in iter_segments(
        segment_source, num_segments=num_segments,
        prefetch_depth=prefetch_depth, stats=prefetch_stats, start=start,
    ):
        if carry is None:
            k = int(Y_seg.shape[-1])
            carry = (
                jnp.zeros((d_feat, d_feat), jnp.float32),
                jnp.zeros((d_feat, k), jnp.float32),
                jnp.zeros((), jnp.float32),
                jnp.zeros((d_feat,), jnp.float32),
                jnp.zeros((k,), jnp.float32),
            )
        t0 = _time.perf_counter()
        # Fold chunk span (obs plane): same region as the `compute` busy
        # counter below, so the trace audits the fold floor per segment.
        with _obs.span("fold.segment", segment=int(s)):
            carry = _dense_segment_fold(
                carry, jnp.asarray(X_seg), jnp.asarray(Y_seg),
                jnp.asarray(int(valid_rows), jnp.int32), bank_params,
                bank_type=bank_type, bank_key=bank_key, tile_rows=tile_rows,
                use_pallas=use_pallas,
            )
            throttle.admit(carry[2])
        if prefetch_stats is not None:
            # The `compute` site: transfer + fold dispatch + the inflight
            # throttle's blocking — the denominator phase of the per-site
            # overlap report (utils.profiling.overlap_report).
            prefetch_stats.add_busy(
                "compute", _time.perf_counter() - t0
            )
        if checkpoint is not None:
            checkpoint.maybe_save(carry, s, num_segments, fingerprint,
                                  stats=prefetch_stats)
    G, FY, yty, fsum, ysum = carry
    G = jnp.triu(G) + jnp.triu(G, 1).T
    # The accumulated moments ride into the shared jitted solve either
    # way; the static ``center`` branch simply ignores them when False.
    W, loss, fmean, ymean = _solve_from_stats(
        G, FY, yty, fsum, ysum,
        jnp.asarray(n_true, jnp.float32), jnp.asarray(lam, jnp.float32),
        block_size=block_size, num_iter=num_iter, center=center,
    )
    if checkpoint is not None:
        # The fit completed: a later fit with this fingerprint must
        # start fresh, not resume a finished run's final carry. Only
        # THIS fit's snapshot — other fits sharing the directory keep
        # theirs.
        checkpoint.clear(fingerprint)
    return W, fmean, ymean, loss


def _solve_from_stats_core(G, FY, yty, fsum, ysum, n_true, lam,
                           block_size, num_iter, center):
    """Traceable solve tail shared by every gram-stats fit entry point:
    (optional rank-1 centering) -> BCD on the normal equations -> loss.
    ``G`` must have BOTH triangles valid. Returns
    (W, loss, fmean, ymean) — fmean/ymean None when not centering."""
    fmean = ymean = None
    if center:
        G, FY, yty, fmean, ymean = center_gram_stats(
            G, FY, yty, fsum, ysum, n_true
        )
    W = bcd_from_gram(G, FY, block_size, lam, num_iter)
    Wf = W.reshape(G.shape[0], W.shape[2])
    loss = (yty - 2.0 * jnp.vdot(Wf, FY) + jnp.vdot(Wf, G @ Wf)) / n_true
    return W, loss, fmean, ymean


@functools.partial(
    jax.jit, static_argnames=("block_size", "num_iter", "center")
)
def _solve_from_stats(G, FY, yty, fsum, ysum, n_true, lam, *,
                      block_size, num_iter, center):
    return _solve_from_stats_core(
        G, FY, yty, fsum, ysum, n_true, lam, block_size, num_iter, center
    )


def center_gram_stats(G, FY, yty, fsum, ysum, n):
    """Rank-1-correct accumulated stats to their mean-centered form.

    With μ = fsum/n and ȳ = ysum/n over the n VALID rows (padding rows
    contribute zero to every accumulator):

        Gc   = Σ(fᵢ−μ)(fᵢ−μ)ᵀ = G  − fsum·fsumᵀ/n
        FYc  = Σ(fᵢ−μ)(yᵢ−ȳ)ᵀ = FY − fsum·ysumᵀ/n
        ytyc = Σ‖yᵢ−ȳ‖²        = yty − ysum·ysum/n

    exactly — centering costs two rank-1 updates instead of a second data
    pass. Returns (Gc, FYc, ytyc, fmean, ymean).
    """
    n = jnp.asarray(n, G.dtype)
    fmean = fsum / n
    ymean = ysum / n
    Gc = G - jnp.outer(fsum, fmean)
    FYc = FY - jnp.outer(fsum, ymean)
    ytyc = yty - jnp.dot(ysum, ymean)
    return Gc, FYc, ytyc, fmean, ymean


def streaming_bcd_fit_centered(
    X: Array,
    Y: Array,
    *,
    featurize: Callable[[Array], Array],
    d_feat: int,
    tile_rows: int,
    block_size: int,
    lam,
    num_iter: int,
    use_pallas: bool = False,
    valid: Optional[int] = None,
    labelize: Optional[Callable[[Array], Array]] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Mean-centered one-dispatch streamed fit — the streamed form of
    ``BlockLeastSquaresEstimator`` semantics (per-block feature centering +
    label centering + intercept, BlockLinearMapper.scala:224-243): column
    sums accumulate in the same tile pass as G/FY, the normal equations
    get rank-1 centering corrections, and BCD runs on the centered system.

    Returns (W, fmean, ymean, loss): predictions are
    (F − fmean) @ W_flat + ymean — the same affine model BlockLinearMapper
    applies. ``lam`` is traced (λ-sweeps share one executable).
    """
    W, loss, _, fmean, ymean = _dispatch_fit(
        X, Y, featurize, True,
        dict(d_feat=d_feat, tile_rows=tile_rows, block_size=block_size,
             lam=lam, num_iter=num_iter, use_pallas=use_pallas,
             valid=valid, labelize=labelize),
    )
    return W, fmean, ymean, loss


def streaming_predict(
    X: Array,
    W: Array,
    featurize: Callable[[Array], Array],
    tile_rows: int,
) -> Array:
    """Predictions F @ W_flat computed tile-wise (F never materialized).

    W: (nb, block, k) from the fit. X may be (n, d_in) or pre-tiled
    (T, tile_rows, d_in) — predictions come back flattened to (n, k)
    either way. Traceable; pads a ragged remainder internally
    (predictions for padding rows are dropped).
    """
    Wf = W.reshape(-1, W.shape[2])

    def tile_preds(X_t):
        F_t = featurize(X_t)
        return (F_t @ Wf.astype(F_t.dtype)).astype(jnp.float32)

    if X.ndim == 3:
        _, P_full = jax.lax.scan(lambda _, X_t: (None, tile_preds(X_t)), None, X)
        return P_full.reshape(X.shape[0] * X.shape[1], -1)

    n = X.shape[0]
    num_full = n // tile_rows
    rem = n - num_full * tile_rows
    outs = []
    if num_full:
        Xs = X[: num_full * tile_rows].reshape(num_full, tile_rows, -1)
        _, P_full = jax.lax.scan(
            lambda _, X_t: (None, tile_preds(X_t)), None, Xs
        )
        outs.append(P_full.reshape(num_full * tile_rows, -1))
    if rem:
        outs.append(tile_preds(X[num_full * tile_rows :]))
    return jnp.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]


# ``lam`` is a TRACED operand (λ-sweeps share one compiled sweep).
@functools.partial(
    jax.jit,
    static_argnames=(
        "block_size", "num_iter", "mesh", "n_true", "feat_dtype",
        "center",
    ),
)
def streaming_block_bcd_mesh(
    X: Array,
    Y: Array,
    Wrf: Array,
    brf: Array,
    *,
    block_size: int,
    lam: float,
    num_iter: int,
    mesh,
    n_true: Optional[int] = None,
    feat_dtype=jnp.float32,
    center: bool = False,
):
    """The north-star program: cosine-featurize + block coordinate descent
    where feature BLOCKS are generated per step and discarded — the plan
    that runs TIMIT at ~200k feature dims on a v5e-16 (NORTHSTAR.md).

    Rows of X (n_pad, d_in) and Y (n_pad, k) shard over the mesh ``data``
    axis; the random-feature bank Wrf (d_feat, d_in) / brf (d_feat,)
    replicates (352 MB at the full 200k×440 — small beside HBM). The whole
    (epochs × blocks) sweep is ONE shard_map program:

      per block b:  F_b = cos(X_local Wrf_bᵀ + brf_b)   local slab, freed
                    gram, corr = psum(F_bᵀF_b), psum(F_bᵀR)   ← the ONLY
                        per-step collective: bs² + bs·k floats over ICI
                    W_b ← replicated Cholesky solve
                    R_local ← R_local − F_b ΔW_b

    so the (n × d_feat) feature matrix — 880 GB of bf16 at the full
    geometry — never exists; the resident working set per device is the
    raw rows, the residual, one block slab and the epoch-invariant
    Gramian/factor stash (HBM table in NORTHSTAR.md). Epochs 2+ reuse the
    stashed factors and pay only featurize + correlation + update.

    Padding rows (``n_true``) are masked AFTER featurization (a zero row
    featurizes to cos(b) ≠ 0). Returns the (nb, bs, k) block weights,
    replicated — or, with ``center=True``, (W, fmean, ymean):
    per-block feature means and the label mean accumulate in the same
    block steps (one extra bs-vector in the epoch-1 psum and a k-vector
    per correlation psum), the per-block systems solve on their CENTERED
    Gramians, and the model is the BlockLeastSquares affine form
    (F − fmean) @ W + ymean — full semantics parity with the resident
    Block solver at geometries where only this tier runs.
    """
    axis = mesh_lib.DATA_AXIS
    d_feat = Wrf.shape[0]
    d_in = X.shape[1]
    k = Y.shape[1]
    if d_feat % block_size:
        raise ValueError(f"d_feat {d_feat} not divisible by {block_size}")
    nb = d_feat // block_size
    n_pad = X.shape[0]
    num = mesh_lib.axis_size(mesh, axis)
    ln = n_pad // num
    n_eff = n_true if n_true is not None else n_pad

    def body(x_local, y_local, Wrf, brf):
        lam_t = jnp.asarray(lam, jnp.float32)
        if n_true is not None and n_true != n_pad:
            start = jax.lax.axis_index(axis) * ln
            valid = (
                (start + jnp.arange(ln)) < n_true
            ).astype(jnp.float32)[:, None]
        else:
            valid = None

        def featurize_block(b):
            Wb = jax.lax.dynamic_slice(
                Wrf, (b * block_size, 0), (block_size, d_in)
            )
            bb = jax.lax.dynamic_slice(brf, (b * block_size,), (block_size,))
            F = jnp.cos(x_local @ Wb.T + bb).astype(feat_dtype)
            if valid is not None:
                F = F * valid.astype(F.dtype)
            return F

        def update(b, R, Wst, gram, chol, mu):
            """One block solve + residual update. ``gram``/``chol`` are the
            (centered, when ``center``) block system; ``mu`` is the block's
            feature mean (None when not centering)."""
            acc = jnp.promote_types(feat_dtype, jnp.float32)
            F = featurize_block(b)
            local = jax.lax.dot_general(
                F, R.astype(F.dtype), (((0,), (0,)), ((), ())),
                preferred_element_type=acc,
            ).astype(jnp.float32)
            if mu is not None:
                # Centered correlation: FcᵀR = FᵀR − μ·(Σᵢ Rᵢ)ᵀ. The row
                # sum rides the SAME psum as the correlation (stacked as
                # one extra row) — one collective per block step, as the
                # dossier's cost model states.
                stacked = jax.lax.psum(
                    jnp.concatenate(
                        [local, jnp.sum(R, axis=0)[None, :]], axis=0
                    ),
                    axis,
                )
                corr = stacked[:-1] - jnp.outer(mu, stacked[-1])
            else:
                corr = jax.lax.psum(local, axis)
            w_old = jax.lax.dynamic_index_in_dim(Wst, b, 0, keepdims=False)
            rhs = corr + gram @ w_old
            w_new = _solve_psd(gram, rhs, lam_t, chol=chol)
            dw = w_new - w_old
            delta = jax.lax.dot_general(
                F, dw.astype(F.dtype), (((1,), (0,)), ((), ())),
                preferred_element_type=acc,
            ).astype(R.dtype)
            if mu is not None:
                # R ← R − Fc·Δw = R − F·Δw + 1·(μᵀΔw); the constant term
                # must not leak into padding rows.
                const = (mu @ dw).astype(R.dtype)
                corr_term = (
                    const[None, :] if valid is None
                    else const[None, :] * valid.astype(R.dtype)
                )
                delta = delta - corr_term
            R = R - delta
            return R, jax.lax.dynamic_update_index_in_dim(Wst, w_new, b, 0)

        def first_step(carry, b):
            R, Wst, G, C, M = carry
            acc = jnp.promote_types(feat_dtype, jnp.float32)
            F = featurize_block(b)
            gram = jax.lax.psum(
                jax.lax.dot_general(
                    F, F, (((0,), (0,)), ((), ())),
                    preferred_element_type=acc,
                ),
                axis,
            )
            if center:
                fsum = jax.lax.psum(
                    jnp.sum(F, axis=0, dtype=jnp.float32), axis
                )
                mu = fsum / n_eff
                gram = gram - jnp.outer(fsum, mu)  # = G − n μμᵀ, exact
                M = jax.lax.dynamic_update_index_in_dim(M, mu, b, 0)
            else:
                mu = None
            chol = _psd_factor(gram, lam_t)
            R, Wst = update(b, R, Wst, gram, chol, mu)
            G = jax.lax.dynamic_update_index_in_dim(G, gram, b, 0)
            C = jax.lax.dynamic_update_index_in_dim(C, chol, b, 0)
            return (R, Wst, G, C, M), None

        def later_step(carry, b):
            R, Wst, G, C, M = carry
            gram = jax.lax.dynamic_index_in_dim(G, b, 0, keepdims=False)
            chol = jax.lax.dynamic_index_in_dim(C, b, 0, keepdims=False)
            mu = (
                jax.lax.dynamic_index_in_dim(M, b, 0, keepdims=False)
                if center else None
            )
            R, Wst = update(b, R, Wst, gram, chol, mu)
            return (R, Wst, G, C, M), None

        R0 = y_local.astype(jnp.float32)
        if valid is not None:
            R0 = R0 * valid
        if center:
            ysum = jax.lax.psum(jnp.sum(R0, axis=0), axis)
            ymean = ysum / n_eff
            R0 = R0 - (
                ymean[None, :] if valid is None
                else ymean[None, :] * valid
            )
        Wst0 = jnp.zeros((nb, block_size, k), jnp.float32)
        G0 = jnp.zeros((nb, block_size, block_size), jnp.float32)
        C0 = jnp.zeros((nb, block_size, block_size), jnp.float32)
        M0 = jnp.zeros((nb, block_size), jnp.float32)
        order = jnp.arange(nb)
        carry, _ = jax.lax.scan(first_step, (R0, Wst0, G0, C0, M0), order)
        if num_iter > 1:
            def epoch(carry, _):
                carry, _ = jax.lax.scan(later_step, carry, order)
                return carry, None
            carry, _ = jax.lax.scan(epoch, carry, None, length=num_iter - 1)
        if center:
            return carry[1], carry[4].reshape(d_feat), ymean
        return carry[1]

    out_specs = (P(), P(), P()) if center else P()
    return mesh_lib.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P()),
        out_specs=out_specs,
        check_vma=False,
    )(X, Y, Wrf, brf)


@functools.partial(
    jax.jit,
    static_argnames=(
        "block_size", "num_iter", "mesh", "n_true", "feat_dtype",
        "center",
    ),
)
def streaming_block_bcd_mesh_2d(
    X: Array,
    Y: Array,
    Wrf: Array,
    brf: Array,
    *,
    block_size: int,
    lam: float,
    num_iter: int,
    mesh,
    n_true: Optional[int] = None,
    feat_dtype=jnp.float32,
    center: bool = False,
):
    """2-D (data × model) form of the north-star program: the Gramian/
    factor stash, the block weights AND the feature bank shard over the
    ``model`` axis (reference analog: VectorSplitter.scala:10-36 feature
    blocks over workers), while rows shard over BOTH axes so every device
    computes on every block step.

    Per-device stash drops from nb·bs² to (nb/model_size)·bs² — the lever
    NORTHSTAR.md §3 names for d ≫ 200k: at d_feat = 409,600 (100 blocks
    of 4096) the replicated stash would be 13.4 GB (Gramian + factor);
    over model=4 it is 3.4 GB.

    Block b's owner is model index b // (nb/model_size) (contiguous
    assignment matches the bank's natural sharding). Per block step:

      bank slice  psum over model (bs·d_in floats — owner broadcasts)
      F           local cos slab over the device's rows, freed per step
      gram/corr   psum over BOTH axes (epoch 1) / corr only (later)
      solve       epoch 1: replicated (gram is replicated post-psum);
                  later: the OWNER computes gram@w_old and the Cholesky
                  solve from its stash, then broadcasts w_new/w_old
                  (2·bs·k floats) — the stash itself never crosses the
                  interconnect
      R update    local rows

    Returns (nb, bs, k) block weights sharded over ``model`` on axis 0.
    X/Y rows must be sharded over (data, model) flattened (data-major).
    With ``center=True`` (same semantics as the 1-D form): returns
    (W, fmean (nb, bs) sharded over model, ymean replicated); per-block
    means live in the owner's stash and are owner-broadcast (bs floats)
    in later epochs alongside w_new/w_old.
    """
    data_ax = mesh_lib.DATA_AXIS
    model_ax = mesh_lib.MODEL_AXIS
    d_feat = Wrf.shape[0]
    d_in = X.shape[1]
    k = Y.shape[1]
    if d_feat % block_size:
        raise ValueError(f"d_feat {d_feat} not divisible by {block_size}")
    nb = d_feat // block_size
    mc = mesh_lib.axis_size(mesh, model_ax)
    dr = mesh_lib.axis_size(mesh, data_ax)
    if nb % mc:
        raise ValueError(f"nb {nb} not divisible by model axis {mc}")
    nb_local = nb // mc
    n_pad = X.shape[0]
    ln = n_pad // (dr * mc)
    bs = block_size
    n_eff = n_true if n_true is not None else n_pad

    def body(x_local, y_local, wrf_local, brf_local):
        lam_t = jnp.asarray(lam, jnp.float32)
        mi = jax.lax.axis_index(model_ax)
        if n_true is not None and n_true != n_pad:
            # P((data, model)) splits rows data-major.
            start = (jax.lax.axis_index(data_ax) * mc + mi) * ln
            valid = (
                (start + jnp.arange(ln)) < n_true
            ).astype(jnp.float32)[:, None]
        else:
            valid = None

        def bank_block(b):
            slot = jnp.mod(b, nb_local)
            owner = b // nb_local
            is_owner = (mi == owner)
            sl = jax.lax.dynamic_slice(
                wrf_local, (slot * bs, 0), (bs, d_in)
            )
            bb = jax.lax.dynamic_slice(brf_local, (slot * bs,), (bs,))
            own_f = is_owner.astype(sl.dtype)
            Wb = jax.lax.psum(sl * own_f, model_ax)
            bv = jax.lax.psum(bb * own_f, model_ax)
            return Wb, bv, is_owner, slot

        def featurize(x, Wb, bv):
            F = jnp.cos(x @ Wb.T + bv).astype(feat_dtype)
            if valid is not None:
                F = F * valid.astype(F.dtype)
            return F

        acc = jnp.promote_types(feat_dtype, jnp.float32)

        def psum2(v):
            return jax.lax.psum(jax.lax.psum(v, data_ax), model_ax)

        def corr_of(F, R, mu):
            local = jax.lax.dot_general(
                F, R.astype(F.dtype), (((0,), (0,)), ((), ())),
                preferred_element_type=acc,
            ).astype(jnp.float32)
            if mu is None:
                return psum2(local)
            # Centered correlation: FcᵀR = FᵀR − μ·(Σᵢ Rᵢ)ᵀ. The row sum
            # rides the SAME psum2 as the correlation (one extra stacked
            # row) — the per-step collective count stays at one pair.
            stacked = psum2(
                jnp.concatenate([local, jnp.sum(R, axis=0)[None, :]], axis=0)
            )
            return stacked[:-1] - jnp.outer(mu, stacked[-1])

        def apply_delta(R, F, w_new, w_old, mu):
            dw = w_new - w_old
            delta = jax.lax.dot_general(
                F, dw.astype(F.dtype),
                (((1,), (0,)), ((), ())), preferred_element_type=acc,
            ).astype(R.dtype)
            if mu is not None:
                # R ← R − Fc·Δw = R − F·Δw + 1·(μᵀΔw), padding-masked.
                const = (mu @ dw).astype(R.dtype)
                term = (
                    const[None, :] if valid is None
                    else const[None, :] * valid.astype(R.dtype)
                )
                delta = delta - term
            return R - delta

        def mask_store(stash, slot, value, is_owner):
            old = jax.lax.dynamic_index_in_dim(stash, slot, 0, keepdims=False)
            new = jnp.where(is_owner, value, old)
            return jax.lax.dynamic_update_index_in_dim(stash, new, slot, 0)

        def first_step(carry, b):
            R, Wst, G, C, M = carry
            Wb, bv, is_owner, slot = bank_block(b)
            F = featurize(x_local, Wb, bv)
            gram = psum2(
                jax.lax.dot_general(
                    F, F, (((0,), (0,)), ((), ())),
                    preferred_element_type=acc,
                )
            )
            if center:
                fsum = psum2(jnp.sum(F, axis=0, dtype=jnp.float32))
                mu = fsum / n_eff
                gram = gram - jnp.outer(fsum, mu)  # = G − n μμᵀ, exact
                M = mask_store(M, slot, mu, is_owner)
            else:
                mu = None
            chol = _psd_factor(gram, lam_t)
            corr = corr_of(F, R, mu)
            # w_old is zero in epoch 1 (fresh W) — rhs is just corr.
            w_new = _solve_psd(gram, corr, lam_t, chol=chol)
            R = apply_delta(R, F, w_new, jnp.zeros_like(w_new), mu)
            G = mask_store(G, slot, gram, is_owner)
            C = mask_store(C, slot, chol, is_owner)
            Wst = mask_store(Wst, slot, w_new, is_owner)
            return (R, Wst, G, C, M), None

        def later_step(carry, b):
            R, Wst, G, C, M = carry
            Wb, bv, is_owner, slot = bank_block(b)
            F = featurize(x_local, Wb, bv)
            own_f = is_owner.astype(jnp.float32)
            if center:
                # Owner broadcasts the block's mean (bs floats).
                mu_l = jax.lax.dynamic_index_in_dim(
                    M, slot, 0, keepdims=False
                )
                mu = jax.lax.psum(mu_l * own_f, model_ax)
            else:
                mu = None
            corr = corr_of(F, R, mu)
            gram_l = jax.lax.dynamic_index_in_dim(G, slot, 0, keepdims=False)
            chol_l = jax.lax.dynamic_index_in_dim(C, slot, 0, keepdims=False)
            w_old_l = jax.lax.dynamic_index_in_dim(
                Wst, slot, 0, keepdims=False
            )
            # Non-owners hold garbage stash slots; guard the factor with I
            # so their (masked-out) solves stay finite — NaN·0 would leak.
            chol_safe = jnp.where(
                is_owner, chol_l, jnp.eye(bs, dtype=chol_l.dtype)
            )
            rhs = corr + gram_l @ w_old_l
            w_new_l = _solve_psd(gram_l, rhs, lam_t, chol=chol_safe)
            w_new = jax.lax.psum(w_new_l * own_f, model_ax)
            w_old = jax.lax.psum(w_old_l * own_f, model_ax)
            R = apply_delta(R, F, w_new, w_old, mu)
            Wst = mask_store(Wst, slot, w_new, is_owner)
            return (R, Wst, G, C, M), None

        R0 = y_local.astype(jnp.float32)
        if valid is not None:
            R0 = R0 * valid
        if center:
            ymean = psum2(jnp.sum(R0, axis=0)) / n_eff
            R0 = R0 - (
                ymean[None, :] if valid is None
                else ymean[None, :] * valid
            )
        Wst0 = jnp.zeros((nb_local, bs, k), jnp.float32)
        G0 = jnp.zeros((nb_local, bs, bs), jnp.float32)
        C0 = jnp.zeros((nb_local, bs, bs), jnp.float32)
        M0 = jnp.zeros((nb_local, bs), jnp.float32)
        order = jnp.arange(nb)
        carry, _ = jax.lax.scan(first_step, (R0, Wst0, G0, C0, M0), order)
        if num_iter > 1:
            def epoch(carry, _):
                carry, _ = jax.lax.scan(later_step, carry, order)
                return carry, None
            carry, _ = jax.lax.scan(epoch, carry, None, length=num_iter - 1)
        if center:
            return carry[1], carry[4], ymean
        return carry[1]

    out_specs = (
        (P(model_ax), P(model_ax), P()) if center else P(model_ax)
    )
    return mesh_lib.shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P((data_ax, model_ax)), P((data_ax, model_ax)),
            P(model_ax), P(model_ax),
        ),
        out_specs=out_specs,
        check_vma=False,
    )(X, Y, Wrf, brf)


def gram_stats_mesh(
    X: Array,
    Y: Array,
    featurize: Callable[[Array], Array],
    d_feat: int,
    tile_rows: int,
    mesh,
    use_pallas: bool = False,
    n_true: Optional[int] = None,
    moments: bool = False,
) -> Tuple[Array, ...]:
    """Mesh-parallel gram_stats: rows sharded over ``data``; each device
    folds its local tiles, then ONE psum of (G, FY, yty) crosses the
    interconnect — the treeReduce analog, one collective per fit.

    ``n_true`` (static): the true global row count when X was padded to
    shard evenly — trailing padding rows are masked out per shard.
    ``moments=True`` additionally psums the column sums (see
    :func:`gram_stats`) for the centered solvers.
    """
    axis = mesh_lib.DATA_AXIS
    n_padded = X.shape[0]
    num = mesh_lib.axis_size(mesh, axis)
    local_rows = n_padded // num

    def local(xs, ys):
        if n_true is not None and n_true != n_padded:
            start = jax.lax.axis_index(axis) * local_rows
            valid = jnp.clip(n_true - start, 0, local_rows)
        else:
            valid = None
        stats = gram_stats(
            xs, ys, featurize, d_feat, tile_rows, use_pallas=use_pallas,
            valid=valid, moments=moments,
        )
        return tuple(jax.lax.psum(s, axis) for s in stats)

    n_out = 5 if moments else 3
    return mesh_lib.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=tuple(P() for _ in range(n_out)),
        check_vma=False,
    )(X, Y)


@functools.partial(
    jax.jit,
    static_argnames=(
        "featurize", "d_feat", "tile_rows", "block_size", "num_iter",
        "mesh", "use_pallas", "n_true",
    ),
)
def streaming_bcd_fit_mesh(
    X: Array,
    Y: Array,
    *,
    featurize: Callable[[Array], Array],
    d_feat: int,
    tile_rows: int,
    block_size: int,
    lam: float,
    num_iter: int,
    mesh,
    use_pallas: bool = False,
    n_true: Optional[int] = None,
) -> Array:
    """Mesh streamed fit: sharded tile folds + one psum + replicated solve.

    X/Y rows sharded (or shardable) over the mesh's data axis; when padded
    to shard evenly, pass the true global row count as ``n_true`` and the
    trailing padding is masked per shard (padding rows in X may hold any
    value — their feature rows are zeroed after featurization).
    """
    G, FY, _ = gram_stats_mesh(
        X, Y, featurize, d_feat, tile_rows, mesh, use_pallas=use_pallas,
        n_true=n_true,
    )
    return bcd_from_gram(G, FY, block_size, lam, num_iter)


@functools.partial(
    jax.jit,
    static_argnames=(
        "featurize", "d_feat", "tile_rows", "block_size", "num_iter",
        "mesh", "use_pallas", "n_true",
    ),
)
def streaming_bcd_fit_mesh_centered(
    X: Array,
    Y: Array,
    *,
    featurize: Callable[[Array], Array],
    d_feat: int,
    tile_rows: int,
    block_size: int,
    lam,
    num_iter: int,
    mesh,
    use_pallas: bool = False,
    n_true: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    """Mesh form of :func:`streaming_bcd_fit_centered`: sharded tile folds
    (column sums psum'd alongside G/FY — still ONE collective round per
    fit), rank-1 centering corrections, replicated solve. Returns
    (W, fmean, ymean)."""
    G, FY, yty, fsum, ysum = gram_stats_mesh(
        X, Y, featurize, d_feat, tile_rows, mesh, use_pallas=use_pallas,
        n_true=n_true, moments=True,
    )
    n = n_true if n_true is not None else X.shape[0]
    Gc, FYc, _, fmean, ymean = center_gram_stats(G, FY, yty, fsum, ysum, n)
    W = bcd_from_gram(Gc, FYc, block_size, lam, num_iter)
    return W, fmean, ymean
