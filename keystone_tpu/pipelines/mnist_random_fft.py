"""MnistRandomFFT: random-FFT featurization + block least squares on MNIST
(reference: pipelines/images/mnist/MnistRandomFFT.scala:21-115).

Composition: gather(numFFTs × [RandomSignNode → PaddedFFT → LinearRectifier])
→ VectorCombiner → BlockLeastSquares(blockSize, 1, λ) → MaxClassifier.
"""

from __future__ import annotations

import argparse
import logging
import time
from dataclasses import dataclass
from typing import Optional

from keystone_tpu.data.loaders import load_labeled_csv, synthetic_mnist
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.ops.stats import LinearRectifier, PaddedFFT, RandomSignNode
from keystone_tpu.ops.util import (
    ClassLabelIndicatorsFromIntLabels,
    MaxClassifier,
    VectorCombiner,
)
from keystone_tpu.workflow import Pipeline

logger = logging.getLogger("keystone_tpu.pipelines.mnist")

NUM_CLASSES = 10
MNIST_IMAGE_SIZE = 784


@dataclass
class MnistRandomFFTConfig:
    train_location: str = ""
    test_location: str = ""
    num_ffts: int = 4
    block_size: int = 2048
    lam: Optional[float] = None
    seed: int = 0
    synthetic_n: int = 4096  # used when no train_location given
    image_size: int = MNIST_IMAGE_SIZE  # input dims (64 for the real
    # sklearn digits data used by parity.py; 784 for MNIST CSVs)
    use_digits: bool = False  # real UCI digits instead of synthetic


def build_featurizer(config: MnistRandomFFTConfig) -> Pipeline:
    branches = [
        RandomSignNode.create(config.image_size, seed=config.seed + i)
        .and_then(PaddedFFT())
        .and_then(LinearRectifier(0.0))
        for i in range(config.num_ffts)
    ]
    return Pipeline.gather(branches).and_then(VectorCombiner())


def run(config: MnistRandomFFTConfig):
    """Build, train, and evaluate; returns (pipeline, train_metrics, test_metrics)."""
    start = time.time()
    if config.train_location:
        # File labels are 1-indexed (MnistRandomFFT.scala:34-37).
        train = load_labeled_csv(config.train_location, label_offset=-1)
        test = load_labeled_csv(config.test_location, label_offset=-1)
    elif config.use_digits:
        from dataclasses import replace

        from keystone_tpu.data.loaders import load_digits_real

        train, test = load_digits_real(seed=config.seed)
        dim = int(train.data.array.shape[1])
        if config.image_size != dim:
            # Derive the featurizer width from the loaded data (64 for the
            # UCI digits) rather than crashing on the 784 MNIST default.
            config = replace(config, image_size=dim)
    else:
        train = synthetic_mnist(config.synthetic_n, seed=config.seed)
        test = synthetic_mnist(max(config.synthetic_n // 4, 256), seed=config.seed + 1)

    labels = ClassLabelIndicatorsFromIntLabels(NUM_CLASSES)(train.labels)

    featurizer = build_featurizer(config)
    pipeline = featurizer.and_then(
        BlockLeastSquaresEstimator(config.block_size, 1, config.lam or 0.0),
        train.data,
        labels,
    ).and_then(MaxClassifier())

    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    train_eval = evaluator.evaluate(pipeline.apply(train.data), train.labels)
    logger.info("TRAIN Error is %.2f%%", 100 * train_eval.total_error)
    test_eval = evaluator.evaluate(pipeline.apply(test.data), test.labels)
    logger.info("TEST Error is %.2f%%", 100 * test_eval.total_error)
    logger.info("Pipeline took %.1f s", time.time() - start)
    return pipeline, train_eval, test_eval


def main(argv=None):
    parser = argparse.ArgumentParser("MnistRandomFFT")
    parser.add_argument("--trainLocation", default="")
    parser.add_argument("--testLocation", default="")
    parser.add_argument("--numFFTs", type=int, default=4)
    parser.add_argument("--blockSize", type=int, default=2048)
    parser.add_argument("--lambda", dest="lam", type=float, default=None)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    config = MnistRandomFFTConfig(
        train_location=args.trainLocation,
        test_location=args.testLocation,
        num_ffts=args.numFFTs,
        block_size=args.blockSize,
        lam=args.lam,
        seed=args.seed,
    )
    _, train_eval, test_eval = run(config)
    print(f"TRAIN Error is {100 * train_eval.total_error:.2f}%")
    print(f"TEST Error is {100 * test_eval.total_error:.2f}%")


if __name__ == "__main__":
    main()
