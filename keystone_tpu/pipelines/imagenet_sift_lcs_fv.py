"""ImageNetSiftLcsFV: two featurization branches (dense SIFT + LCS), each
PCA → GMM Fisher vector → normalize; gathered, combined, and solved with
block weighted least squares; top-5 evaluation
(reference: pipelines/images/imagenet/ImageNetSiftLcsFV.scala:33-135).
"""

from __future__ import annotations

import argparse
import logging
import time
from dataclasses import dataclass

import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.data.loaders import LabeledImage, load_imagenet
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.ops.images.core import (
    GrayScaler,
    ImageExtractor,
    LabelExtractor,
    PixelScaler,
)
from keystone_tpu.ops.images.fisher import GMMFisherVectorEstimator
from keystone_tpu.ops.images.lcs import LCSExtractor
from keystone_tpu.ops.images.sift import SIFTExtractor
from keystone_tpu.ops.learning.bwls import BlockWeightedLeastSquaresEstimator
from keystone_tpu.ops.learning.pca import ColumnPCAEstimator
from keystone_tpu.ops.stats import NormalizeRows, SignedHellingerMapper
from keystone_tpu.ops.util import (
    Cacher,
    ClassLabelIndicatorsFromIntLabels,
    FloatToDouble,
    MatrixVectorizer,
    TopKClassifier,
    VectorCombiner,
)
from keystone_tpu.workflow import Pipeline

logger = logging.getLogger("keystone_tpu.pipelines.imagenet")


@dataclass
class ImageNetConfig:
    train_location: str = ""
    train_labels: str = ""
    test_location: str = ""
    test_labels: str = ""
    num_classes: int = 1000
    lam: float = 6e-5
    mixture_weight: float = 0.25
    sift_pca_dim: int = 64  # ImageNetSiftLcsFV.scala:41
    lcs_pca_dim: int = 64
    lcs_stride: int = 4
    lcs_border: int = 16
    lcs_patch: int = 6
    vocab_size: int = 16
    block_size: int = 4096
    num_iters: int = 1
    seed: int = 0
    synthetic_n: int = 24
    synthetic_classes: int = 5
    synthetic_image_size: int = 48


def synthetic_imagenet(
    n: int, num_classes: int, seed: int, image_size: int = 48
) -> Dataset:
    rng = np.random.default_rng(seed)
    pat_rng = np.random.default_rng(7)
    freqs = pat_rng.uniform(0.2, 1.5, size=(num_classes, 2))
    yy, xx = np.meshgrid(np.arange(image_size), np.arange(image_size), indexing="ij")
    items = []
    for i in range(n):
        c = int(rng.integers(0, num_classes))
        img = np.stack(
            [np.sin(freqs[c, 0] * xx + freqs[c, 1] * yy)] * 3, axis=-1
        )
        img = 127.5 + 70.0 * img + rng.normal(scale=20.0, size=img.shape)
        items.append(LabeledImage(np.clip(img, 0, 255), c, f"img{i}"))
    return Dataset.of(items)


def _fv_suffix() -> list:
    """FloatToDouble → MatrixVectorizer → NormalizeRows → SignedHellinger →
    NormalizeRows (ImageNetSiftLcsFV.scala:60-72)."""
    return [
        FloatToDouble(),
        MatrixVectorizer(),
        NormalizeRows(),
        SignedHellingerMapper(),
        NormalizeRows(),
    ]


def build_featurizer(train_images: Dataset, config: ImageNetConfig) -> Pipeline:
    sift_branch = (
        PixelScaler()
        .to_pipeline()
        .and_then(GrayScaler())
        .and_then(SIFTExtractor(scale_step=1))
        .and_then(ColumnPCAEstimator(config.sift_pca_dim), train_images)
        .and_then(
            GMMFisherVectorEstimator(config.vocab_size, gmm_seed=config.seed),
            train_images,
        )
    )
    lcs_branch = (
        PixelScaler()
        .to_pipeline()
        .and_then(
            LCSExtractor(config.lcs_stride, config.lcs_border, config.lcs_patch)
        )
        .and_then(ColumnPCAEstimator(config.lcs_pca_dim), train_images)
        .and_then(
            GMMFisherVectorEstimator(config.vocab_size, gmm_seed=config.seed + 1),
            train_images,
        )
    )
    for node in _fv_suffix():
        sift_branch = sift_branch.and_then(node)
        lcs_branch = lcs_branch.and_then(node)
    return (
        Pipeline.gather([sift_branch, lcs_branch])
        .and_then(VectorCombiner())
        .and_then(Cacher())
    )


def run(config: ImageNetConfig):
    start = time.time()
    if config.train_location:
        train = load_imagenet(config.train_location, config.train_labels)
        test = load_imagenet(config.test_location, config.test_labels)
        num_classes = config.num_classes
    else:
        num_classes = config.synthetic_classes
        train = synthetic_imagenet(
            config.synthetic_n, num_classes, config.seed, config.synthetic_image_size
        )
        test = synthetic_imagenet(
            max(config.synthetic_n // 2, 8),
            num_classes,
            config.seed + 1,
            config.synthetic_image_size,
        )

    train_images = ImageExtractor().batch_apply(train)
    test_images = ImageExtractor().batch_apply(test)
    train_label_ints = LabelExtractor().batch_apply(train)
    test_label_ints = LabelExtractor().batch_apply(test)

    labels = ClassLabelIndicatorsFromIntLabels(num_classes).batch_apply(
        train_label_ints
    )

    featurizer = build_featurizer(train_images, config)
    top_k = min(5, num_classes)
    pipeline = featurizer.and_then(
        BlockWeightedLeastSquaresEstimator(
            config.block_size, config.num_iters, config.lam, config.mixture_weight
        ),
        train_images,
        labels,
    ).and_then(TopKClassifier(top_k))

    test_preds = pipeline.apply(test_images).get()
    top5 = np.asarray(Dataset.of(test_preds).to_numpy())
    actual = np.asarray(test_label_ints.to_numpy()).reshape(-1)
    top5_err = 1.0 - np.mean([actual[i] in top5[i] for i in range(len(actual))])
    top1 = top5[:, 0]
    evaluator = MulticlassClassifierEvaluator(num_classes)
    top1_eval = evaluator.evaluate(
        Dataset.of(top1), Dataset.of(actual)
    )
    logger.info("TEST top-1 error %.2f%%", 100 * top1_eval.total_error)
    logger.info("TEST top-5 error %.2f%%", 100 * top5_err)
    logger.info("Pipeline took %.1f s", time.time() - start)
    return pipeline, top1_eval, top5_err


def main(argv=None):
    parser = argparse.ArgumentParser("ImageNetSiftLcsFV")
    parser.add_argument("--trainLocation", default="")
    parser.add_argument("--trainLabels", default="")
    parser.add_argument("--testLocation", default="")
    parser.add_argument("--testLabels", default="")
    parser.add_argument("--numClasses", type=int, default=1000)
    parser.add_argument("--lambda", dest="lam", type=float, default=6e-5)
    parser.add_argument("--mixtureWeight", type=float, default=0.25)
    parser.add_argument("--vocabSize", type=int, default=16)
    parser.add_argument("--blockSize", type=int, default=4096)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    config = ImageNetConfig(
        train_location=args.trainLocation,
        train_labels=args.trainLabels,
        test_location=args.testLocation,
        test_labels=args.testLabels,
        num_classes=args.numClasses,
        lam=args.lam,
        mixture_weight=args.mixtureWeight,
        vocab_size=args.vocabSize,
        block_size=args.blockSize,
        seed=args.seed,
    )
    _, top1_eval, top5_err = run(config)
    print(f"TEST top-5 error is {100 * top5_err:.2f}%")


if __name__ == "__main__":
    main()
