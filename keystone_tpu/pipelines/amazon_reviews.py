"""AmazonReviewsPipeline: ngram term-frequency features + logistic regression
for binary sentiment (reference: pipelines/text/AmazonReviewsPipeline.scala:27-79).

Composition: Trim → LowerCase → Tokenizer → NGramsFeaturizer(1..n) →
TermFrequency(binary) → CommonSparseFeatures(topK) → LogisticRegression.
"""

from __future__ import annotations

import argparse
import logging
import time
from dataclasses import dataclass

import numpy as np

from keystone_tpu.data.loaders import load_amazon_reviews, synthetic_documents
from keystone_tpu.evaluation import BinaryClassifierEvaluator
from keystone_tpu.ops.learning.classifiers import LogisticRegressionEstimator
from keystone_tpu.ops.nlp import LowerCase, NGramsFeaturizer, Tokenizer, Trim
from keystone_tpu.ops.sparse import CommonSparseFeatures
from keystone_tpu.ops.stats import TermFrequency
from keystone_tpu.workflow import Pipeline

logger = logging.getLogger("keystone_tpu.pipelines.amazon")


@dataclass
class AmazonReviewsConfig:
    train_location: str = ""
    test_location: str = ""
    threshold: float = 3.5
    n_grams: int = 2
    common_features: int = 1000
    num_iters: int = 20
    seed: int = 0
    synthetic_n: int = 256


def build_featurizer(config: AmazonReviewsConfig) -> Pipeline:
    return (
        Trim()
        .to_pipeline()
        .and_then(LowerCase())
        .and_then(Tokenizer())
        .and_then(NGramsFeaturizer(range(1, config.n_grams + 1)))
        .and_then(TermFrequency(weighting=lambda x: 1))
    )


def run(config: AmazonReviewsConfig):
    start = time.time()
    if config.train_location:
        train = load_amazon_reviews(config.train_location, config.threshold)
        test = load_amazon_reviews(config.test_location, config.threshold)
    else:
        train = synthetic_documents(config.synthetic_n, 2, seed=config.seed)
        test = synthetic_documents(
            max(config.synthetic_n // 4, 64), 2, seed=config.seed + 1
        )

    featurizer = build_featurizer(config)
    pipeline = featurizer.and_then(
        CommonSparseFeatures(config.common_features), train.data
    ).and_then(
        LogisticRegressionEstimator(2, num_iters=config.num_iters),
        train.data,
        train.labels,
    )

    evaluator = BinaryClassifierEvaluator()
    train_preds = pipeline.apply(train.data)
    train_eval = evaluator.evaluate(train_preds, train.labels)
    test_eval = evaluator.evaluate(pipeline.apply(test.data), test.labels)
    logger.info("TRAIN accuracy %.4f", train_eval.accuracy)
    logger.info("TEST accuracy %.4f", test_eval.accuracy)
    logger.info("Pipeline took %.1f s", time.time() - start)
    return pipeline, train_eval, test_eval


def main(argv=None):
    parser = argparse.ArgumentParser("AmazonReviewsPipeline")
    parser.add_argument("--trainLocation", default="")
    parser.add_argument("--testLocation", default="")
    parser.add_argument("--threshold", type=float, default=3.5)
    parser.add_argument("--nGrams", type=int, default=2)
    parser.add_argument("--commonFeatures", type=int, default=1000)
    parser.add_argument("--numIters", type=int, default=20)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    config = AmazonReviewsConfig(
        train_location=args.trainLocation,
        test_location=args.testLocation,
        threshold=args.threshold,
        n_grams=args.nGrams,
        common_features=args.commonFeatures,
        num_iters=args.numIters,
    )
    _, train_eval, test_eval = run(config)
    print(f"TRAIN accuracy is {train_eval.accuracy:.4f}")
    print(f"TEST accuracy is {test_eval.accuracy:.4f}")


if __name__ == "__main__":
    main()
