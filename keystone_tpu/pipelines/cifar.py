"""CIFAR-10 pipelines (reference: pipelines/images/cifar/).

- LinearPixels: grayscale pixels → exact least squares
  (LinearPixels.scala:18-56).
- RandomCifar: random gaussian conv filters → rectify → pool → least squares
  (RandomCifar.scala:20-77).
- RandomPatchCifar: ZCA-whitened random training patches as conv filters →
  rectify → pool → standardize → block least squares
  (RandomPatchCifar.scala:21-86).
- RandomPatchCifarKernel: same featurization → Gaussian-kernel ridge
  regression (RandomPatchCifarKernel.scala:33-76).
- RandomPatchCifarAugmented: random train crops + center/corner test crops,
  vote over augmented copies (RandomPatchCifarAugmented.scala:27-90).
"""

from __future__ import annotations

import argparse
import logging
import time
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset, LabeledData
from keystone_tpu.data.loaders import load_cifar_binary, synthetic_cifar
from keystone_tpu.evaluation import (
    AugmentedExamplesEvaluator,
    MulticlassClassifierEvaluator,
)
from keystone_tpu.ops.images.conv import Convolver, Pooler, SymmetricRectifier
from keystone_tpu.ops.images.core import (
    CenterCornerPatcher,
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
    RandomPatcher,
)
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.ops.learning.kernel import (
    GaussianKernelGenerator,
    KernelRidgeRegression,
)
from keystone_tpu.ops.learning.linear import LinearMapEstimator
from keystone_tpu.ops.learning.pca import ZCAWhitenerEstimator
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.ops.util import (
    Cacher,
    ClassLabelIndicatorsFromIntLabels,
    MaxClassifier,
)
from keystone_tpu.workflow import Pipeline

logger = logging.getLogger("keystone_tpu.pipelines.cifar")

NUM_CLASSES = 10


@dataclass
class CifarConfig:
    train_location: str = ""
    test_location: str = ""
    num_filters: int = 100
    whitener_size: int = 1000  # patches sampled for the ZCA fit
    patch_size: int = 6
    pool_size: int = 10
    pool_stride: int = 9
    alpha: float = 0.25
    lam: float = 10.0
    # Kernel variant (RandomPatchCifarKernel.scala:33-76)
    kernel_gamma: float = 5e-4
    block_size: int = 512
    num_epochs: int = 1
    # Preemption-safe KRR fits: segment the fused sweep and persist
    # (position, stack) here; a rerun with the same config+data resumes.
    checkpoint_path: str = ""
    checkpoint_every_blocks: int = 25
    # Augmented variant (RandomPatchCifarAugmented.scala:27-90).
    # horizontal_flips=None auto-selects: flips on real data (the reference
    # behavior) and off for the synthetic demo, whose phase-sensitive
    # sinusoid classes are not flip-invariant like real photos.
    augment_patch_size: int = 24
    augment_patches: int = 8
    horizontal_flips: "bool | None" = None
    seed: int = 0
    synthetic_n: int = 512


def _load(config: CifarConfig):
    """Returns (train, test, is_synthetic) — the one place that decides the
    data source, so policies keyed on it (flip augmentation) cannot drift."""
    if config.train_location:
        train = load_cifar_binary(config.train_location)
        test = load_cifar_binary(config.test_location)
        return train, test, False
    train = synthetic_cifar(config.synthetic_n, seed=config.seed)
    test = synthetic_cifar(max(config.synthetic_n // 4, 128), seed=config.seed + 1)
    return train, test, True


def _sample_whitened_filters(train: LabeledData, config: CifarConfig):
    """Random training patches, row-normalized, ZCA-whitened, subsampled to a
    conv filter bank (RandomPatchCifar.scala:36-58)."""
    images = np.asarray(train.data.array, dtype=np.float64)[: train.data.n]
    per_image = max(1, config.whitener_size // images.shape[0] + 1)
    patcher = RandomPatcher(
        per_image, config.patch_size, config.patch_size, seed=config.seed + 7
    )
    patches = np.asarray(patcher.batch_apply(train.data).array)
    patches = patches.reshape(patches.shape[0], -1)[: config.whitener_size]
    # Row normalization with the reference's variance floor (Stats.normalizeRows)
    norms = np.sqrt(np.maximum(np.var(patches, axis=1) * patches.shape[1], 10.0))
    patches = (patches - patches.mean(axis=1, keepdims=True)) / norms[:, None]
    whitener = ZCAWhitenerEstimator(eps=0.1).fit_single(jnp.asarray(patches))
    rng = np.random.default_rng(config.seed + 13)
    idx = rng.choice(patches.shape[0], size=config.num_filters, replace=False)
    sampled = np.array(whitener.apply(jnp.asarray(patches[idx])))
    # Renormalize whitened filters (RandomPatchCifar.scala:52-57).
    sampled /= np.linalg.norm(sampled, axis=1, keepdims=True) + 1e-12
    filters = sampled.reshape(
        config.num_filters, config.patch_size, config.patch_size, 3
    )
    return filters, whitener


def _conv_featurizer(filters, whitener, config: CifarConfig) -> Pipeline:
    """Convolver → SymmetricRectifier → Pooler(sum) → vectorize."""
    conv = Convolver(
        jnp.asarray(filters, jnp.float32).reshape(len(filters), -1),
        img_x=32,
        img_y=32,
        img_channels=3,
        whitener=whitener,
        normalize_patches=True,
    )
    return (
        conv.to_pipeline()
        .and_then(SymmetricRectifier(alpha=config.alpha))
        .and_then(
            Pooler(config.pool_stride, config.pool_size, pool_function="sum")
        )
        .and_then(ImageVectorizer())
        .and_then(Cacher())
    )


def run_linear_pixels(config: CifarConfig):
    """GrayScaler → vectorize → exact least squares → argmax
    (LinearPixels.scala:18-56)."""
    start = time.time()
    train, test, _ = _load(config)
    labels = ClassLabelIndicatorsFromIntLabels(NUM_CLASSES)(train.labels)
    pipeline = (
        PixelScaler()
        .to_pipeline()
        .and_then(GrayScaler())
        .and_then(ImageVectorizer())
        .and_then(LinearMapEstimator(lam=None), train.data, labels)
        .and_then(MaxClassifier())
    )
    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    train_eval = evaluator.evaluate(pipeline.apply(train.data), train.labels)
    test_eval = evaluator.evaluate(pipeline.apply(test.data), test.labels)
    logger.info(
        "LinearPixels train %.2f%% test %.2f%% (%.1fs)",
        100 * train_eval.total_error,
        100 * test_eval.total_error,
        time.time() - start,
    )
    return pipeline, train_eval, test_eval


def run_random_cifar(config: CifarConfig):
    """Random (unwhitened) gaussian filters (RandomCifar.scala:20-77)."""
    start = time.time()
    train, test, _ = _load(config)
    rng = np.random.default_rng(config.seed)
    filters = rng.normal(
        size=(config.num_filters, config.patch_size, config.patch_size, 3)
    )
    filters /= np.linalg.norm(filters.reshape(config.num_filters, -1), axis=1)[
        :, None, None, None
    ]
    labels = ClassLabelIndicatorsFromIntLabels(NUM_CLASSES)(train.labels)
    pipeline = (
        _conv_featurizer(filters, None, config)
        .and_then(StandardScaler(), train.data)
        .and_then(
            BlockLeastSquaresEstimator(config.block_size, 1, config.lam),
            train.data,
            labels,
        )
        .and_then(MaxClassifier())
    )
    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    train_eval = evaluator.evaluate(pipeline.apply(train.data), train.labels)
    test_eval = evaluator.evaluate(pipeline.apply(test.data), test.labels)
    logger.info(
        "RandomCifar train %.2f%% test %.2f%% (%.1fs)",
        100 * train_eval.total_error,
        100 * test_eval.total_error,
        time.time() - start,
    )
    return pipeline, train_eval, test_eval


def run_random_patch_cifar(config: CifarConfig):
    """Whitened random-patch filters + block least squares
    (RandomPatchCifar.scala:21-86)."""
    start = time.time()
    train, test, _ = _load(config)
    filters, whitener = _sample_whitened_filters(train, config)
    labels = ClassLabelIndicatorsFromIntLabels(NUM_CLASSES)(train.labels)
    pipeline = (
        _conv_featurizer(filters, whitener, config)
        .and_then(StandardScaler(), train.data)
        .and_then(
            BlockLeastSquaresEstimator(config.block_size, 1, config.lam),
            train.data,
            labels,
        )
        .and_then(MaxClassifier())
    )
    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    train_eval = evaluator.evaluate(pipeline.apply(train.data), train.labels)
    test_eval = evaluator.evaluate(pipeline.apply(test.data), test.labels)
    logger.info(
        "RandomPatchCifar train %.2f%% test %.2f%% (%.1fs)",
        100 * train_eval.total_error,
        100 * test_eval.total_error,
        time.time() - start,
    )
    return pipeline, train_eval, test_eval


def run_random_patch_cifar_kernel(config: CifarConfig):
    """Same featurization, Gaussian-kernel ridge regression solver
    (RandomPatchCifarKernel.scala:33-76)."""
    start = time.time()
    train, test, _ = _load(config)
    filters, whitener = _sample_whitened_filters(train, config)
    labels = ClassLabelIndicatorsFromIntLabels(NUM_CLASSES)(train.labels)
    featurizer = _conv_featurizer(filters, whitener, config).and_then(
        StandardScaler(), train.data
    )
    pipeline = featurizer.and_then(
        KernelRidgeRegression(
            GaussianKernelGenerator(config.kernel_gamma),
            config.lam,
            config.block_size,
            config.num_epochs,
            checkpoint_path=config.checkpoint_path or None,
            checkpoint_every_blocks=config.checkpoint_every_blocks,
        ),
        train.data,
        labels,
    ).and_then(MaxClassifier())
    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    train_eval = evaluator.evaluate(pipeline.apply(train.data), train.labels)
    test_eval = evaluator.evaluate(pipeline.apply(test.data), test.labels)
    logger.info(
        "RandomPatchCifarKernel train %.2f%% test %.2f%% (%.1fs)",
        100 * train_eval.total_error,
        100 * test_eval.total_error,
        time.time() - start,
    )
    return pipeline, train_eval, test_eval


def run_random_patch_cifar_augmented(config: CifarConfig):
    """Random train crops; center/corner test crops (plus horizontal flips
    per ``config.horizontal_flips``) voted per image
    (RandomPatchCifarAugmented.scala:27-90)."""
    start = time.time()
    train, test, is_synthetic = _load(config)

    aug = config.augment_patch_size
    train_patcher = RandomPatcher(config.augment_patches, aug, aug, seed=config.seed)
    flips = config.horizontal_flips
    if flips is None:
        flips = not is_synthetic  # see CifarConfig comment
    test_patcher = CenterCornerPatcher(aug, aug, horizontal_flips=flips)

    train_images = train_patcher.batch_apply(train.data)
    train_label_ints = np.repeat(
        np.asarray(train.labels.array)[: train.labels.n], config.augment_patches
    )
    test_images = test_patcher.batch_apply(test.data)
    n_test = test.labels.n
    per_image = test_patcher.patches_per_image
    test_names = list(np.repeat(np.arange(n_test), per_image))

    filters, whitener = _sample_whitened_filters(
        LabeledData(np.asarray(train_images.array), train_label_ints), config
    )
    labels = ClassLabelIndicatorsFromIntLabels(NUM_CLASSES)(
        Dataset.of(train_label_ints)
    )

    conv = Convolver(
        jnp.asarray(filters, jnp.float32).reshape(len(filters), -1),
        img_x=aug,
        img_y=aug,
        img_channels=3,
        whitener=whitener,
        normalize_patches=True,
    )
    featurizer = (
        conv.to_pipeline()
        .and_then(SymmetricRectifier(alpha=config.alpha))
        .and_then(Pooler(config.pool_stride, config.pool_size, pool_function="sum"))
        .and_then(ImageVectorizer())
        .and_then(Cacher())
        .and_then(StandardScaler(), train_images)
    )
    # Keep raw scores (no MaxClassifier) so the evaluator can vote.
    pipeline = featurizer.and_then(
        BlockLeastSquaresEstimator(config.block_size, 1, config.lam),
        train_images,
        labels,
    )
    evaluator = AugmentedExamplesEvaluator(test_names, NUM_CLASSES)
    # Labels align with the augmented copies (one per patch).
    test_label_copies = np.repeat(
        np.asarray(test.labels.array)[:n_test], per_image
    )
    test_eval = evaluator.evaluate(
        pipeline.apply(test_images), Dataset.of(test_label_copies)
    )
    logger.info(
        "RandomPatchCifarAugmented test %.2f%% (%.1fs)",
        100 * test_eval.total_error,
        time.time() - start,
    )
    return pipeline, test_eval


RUNNERS = {
    "LinearPixels": run_linear_pixels,
    "RandomCifar": run_random_cifar,
    "RandomPatchCifar": run_random_patch_cifar,
    "RandomPatchCifarKernel": run_random_patch_cifar_kernel,
    "RandomPatchCifarAugmented": run_random_patch_cifar_augmented,
}


def main(argv=None, variant: str = "RandomPatchCifar"):
    parser = argparse.ArgumentParser(f"Cifar:{variant}")
    parser.add_argument("--trainLocation", default="")
    parser.add_argument("--testLocation", default="")
    parser.add_argument("--numFilters", type=int, default=100)
    parser.add_argument("--whitenerSize", type=int, default=1000)
    parser.add_argument("--patchSize", type=int, default=6)
    parser.add_argument("--poolSize", type=int, default=10)
    parser.add_argument("--poolStride", type=int, default=9)
    parser.add_argument("--alpha", type=float, default=0.25)
    parser.add_argument("--lambda", dest="lam", type=float, default=10.0)
    parser.add_argument("--gamma", type=float, default=5e-4)
    parser.add_argument("--blockSize", type=int, default=512)
    parser.add_argument("--numEpochs", type=int, default=1)
    parser.add_argument(
        "--checkpointPath", default="",
        help="kernel variant: mid-solver checkpoint/resume file",
    )
    parser.add_argument(
        "--checkpointEveryBlocks", type=int, default=25,
        help="kernel variant: block updates between checkpoint saves",
    )
    parser.add_argument(
        "--horizontalFlips", choices=["auto", "on", "off"], default="auto",
        help="augmented variant's test-crop flips (auto: on for real data)",
    )
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    config = CifarConfig(
        train_location=args.trainLocation,
        test_location=args.testLocation,
        num_filters=args.numFilters,
        whitener_size=args.whitenerSize,
        patch_size=args.patchSize,
        pool_size=args.poolSize,
        pool_stride=args.poolStride,
        alpha=args.alpha,
        lam=args.lam,
        kernel_gamma=args.gamma,
        block_size=args.blockSize,
        num_epochs=args.numEpochs,
        checkpoint_path=args.checkpointPath,
        checkpoint_every_blocks=args.checkpointEveryBlocks,
        horizontal_flips={"auto": None, "on": True, "off": False}[args.horizontalFlips],
        seed=args.seed,
    )
    results = RUNNERS[variant](config)
    test_eval = results[-1]
    print(f"TEST Error is {100 * test_eval.total_error:.2f}%")


if __name__ == "__main__":
    main()
