"""Example end-to-end pipelines (reference: pipelines/ — the acceptance workloads)."""
