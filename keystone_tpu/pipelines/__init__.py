"""Example end-to-end pipelines (reference: pipelines/ — the acceptance
workloads; see SURVEY.md §2.9).

Each module follows the reference skeleton: a Config dataclass, a
``run(config)`` returning (pipeline, metrics...), and a flag-parsing
``main``. Launch by name via ``python -m keystone_tpu.run <Name>``.

Modules are imported lazily (by run.py or by the user) so launching one
pipeline does not pay the import cost of all of them.
"""

__all__ = [
    "amazon_reviews",
    "cifar",
    "imagenet_sift_lcs_fv",
    "mnist_random_fft",
    "newsgroups",
    "stupid_backoff",
    "timit",
    "voc_sift_fisher",
]
