"""StupidBackoffPipeline: n-gram language model with stupid-backoff scoring
(reference: pipelines/nlp/StupidBackoffPipeline.scala:9-58).

Composition: Tokenizer → WordFrequencyEncoder → NGramsFeaturizer →
NGramsCounts → StupidBackoffEstimator.
"""

from __future__ import annotations

import argparse
import logging
import time
from dataclasses import dataclass

from keystone_tpu.data import Dataset
from keystone_tpu.data.loaders import synthetic_sentences
from keystone_tpu.ops.nlp import (
    NGramsCounts,
    NGramsFeaturizer,
    StupidBackoffEstimator,
    Tokenizer,
    WordFrequencyEncoder,
)

logger = logging.getLogger("keystone_tpu.pipelines.stupid_backoff")


@dataclass
class StupidBackoffConfig:
    train_location: str = ""
    n: int = 3
    alpha: float = 0.4
    seed: int = 0
    synthetic_n: int = 400


def run(config: StupidBackoffConfig):
    """Returns (model, word_encoder): the fitted StupidBackoffModel scoring
    encoded n-grams, plus the word→id encoder."""
    start = time.time()
    if config.train_location:
        with open(config.train_location) as f:
            text = Dataset.of([line.strip() for line in f if line.strip()])
    else:
        text = synthetic_sentences(config.synthetic_n, seed=config.seed)

    tokens = Tokenizer(r"\s+").batch_apply(text)
    word_encoder = WordFrequencyEncoder().fit(tokens)
    encoded = word_encoder.batch_apply(tokens)
    ngrams = NGramsFeaturizer(range(2, config.n + 1)).batch_apply(encoded)
    counts = NGramsCounts("default").batch_apply(ngrams)

    # WordFrequencyTransformer.unigram_counts is already index-keyed.
    model = StupidBackoffEstimator(word_encoder.unigram_counts, config.alpha).fit(
        counts
    )
    logger.info(
        "Trained stupid-backoff LM over %d ngrams in %.1f s",
        len(model.scores),
        time.time() - start,
    )
    return model, word_encoder


def main(argv=None):
    parser = argparse.ArgumentParser("StupidBackoffPipeline")
    parser.add_argument("--trainData", default="")
    parser.add_argument("--n", type=int, default=3)
    parser.add_argument("--alpha", type=float, default=0.4)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    config = StupidBackoffConfig(
        train_location=args.trainData, n=args.n, alpha=args.alpha
    )
    model, _ = run(config)
    print(f"Scored {len(model.scores)} ngrams")


if __name__ == "__main__":
    main()
