"""VOCSIFTFisher: dense SIFT → PCA → GMM Fisher vectors → block least squares,
evaluated by VOC mean average precision
(reference: pipelines/images/voc/VOCSIFTFisher.scala:23-105).

Composition: PixelScaler → GrayScaler → Cacher → SIFTExtractor →
ColumnPCAEstimator → GMMFisherVectorEstimator → FloatToDouble →
MatrixVectorizer → NormalizeRows → SignedHellingerMapper → NormalizeRows →
Cacher → BlockLeastSquares → MAP eval.
"""

from __future__ import annotations

import argparse
import logging
import time
from dataclasses import dataclass

import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.data.loaders import MultiLabeledImage, load_voc
from keystone_tpu.evaluation import MeanAveragePrecisionEvaluator
from keystone_tpu.ops.images.core import GrayScaler, PixelScaler
from keystone_tpu.ops.images.fisher import GMMFisherVectorEstimator
from keystone_tpu.ops.images.sift import SIFTExtractor
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.ops.learning.pca import ColumnPCAEstimator
from keystone_tpu.ops.stats import NormalizeRows, SignedHellingerMapper
from keystone_tpu.ops.util import (
    Cacher,
    ClassLabelIndicatorsFromIntArrayLabels,
    FloatToDouble,
    MatrixVectorizer,
)
from keystone_tpu.workflow import Pipeline, Transformer

logger = logging.getLogger("keystone_tpu.pipelines.voc")

NUM_CLASSES = 20  # VOC 2007 (reference: loaders/VOCLoader.scala:16-53)


@dataclass
class VOCConfig:
    train_location: str = ""
    train_labels: str = ""
    test_location: str = ""
    test_labels: str = ""
    lam: float = 0.5
    descriptor_dim: int = 80  # PCA dims (VOCSIFTFisher.scala:58)
    vocab_size: int = 16  # GMM centers (reference default 64)
    sift_scale_step: int = 1
    block_size: int = 4096
    seed: int = 0
    synthetic_n: int = 24
    synthetic_image_size: int = 48


class _MultiLabeledImageExtractor(Transformer):
    """MultiLabeledImage -> image (reference: LabeledImageExtractors.scala)."""

    def apply(self, x: MultiLabeledImage):
        return x.image


def synthetic_voc(n: int, seed: int, image_size: int = 48) -> Dataset:
    """Multi-labeled synthetic images with class-dependent textures."""
    rng = np.random.default_rng(seed)
    pat_rng = np.random.default_rng(99)
    freqs = pat_rng.uniform(0.2, 1.5, size=(NUM_CLASSES, 2))
    yy, xx = np.meshgrid(
        np.arange(image_size), np.arange(image_size), indexing="ij"
    )
    items = []
    for i in range(n):
        k = rng.integers(1, 3)
        classes = rng.choice(NUM_CLASSES, size=k, replace=False)
        img = np.zeros((image_size, image_size, 3))
        for c in classes:
            img += np.stack(
                [np.sin(freqs[c, 0] * xx + freqs[c, 1] * yy)] * 3, axis=-1
            )
        img = 127.5 + 60.0 * img / k + rng.normal(scale=20.0, size=img.shape)
        items.append(
            MultiLabeledImage(np.clip(img, 0, 255), np.sort(classes), f"img{i}")
        )
    return Dataset.of(items)


def build_featurizer(train_images: Dataset, config: VOCConfig) -> Pipeline:
    sift = SIFTExtractor(scale_step=config.sift_scale_step)
    prefix = (
        PixelScaler()
        .to_pipeline()
        .and_then(GrayScaler())
        .and_then(Cacher())
        .and_then(sift)
    )
    return (
        prefix.and_then(ColumnPCAEstimator(config.descriptor_dim), train_images)
        .and_then(
            GMMFisherVectorEstimator(config.vocab_size, gmm_seed=config.seed),
            train_images,
        )
        .and_then(FloatToDouble())
        .and_then(MatrixVectorizer())
        .and_then(NormalizeRows())
        .and_then(SignedHellingerMapper())
        .and_then(NormalizeRows())
        .and_then(Cacher())
    )


def run(config: VOCConfig):
    start = time.time()
    if config.train_location:
        train = load_voc(config.train_location, config.train_labels)
        test = load_voc(config.test_location, config.test_labels)
    else:
        train = synthetic_voc(
            config.synthetic_n, config.seed, config.synthetic_image_size
        )
        test = synthetic_voc(
            max(config.synthetic_n // 2, 8),
            config.seed + 1,
            config.synthetic_image_size,
        )

    extractor = _MultiLabeledImageExtractor()
    train_images = extractor.batch_apply(train)
    test_images = extractor.batch_apply(test)
    train_label_arrays = [item.labels for item in train.to_list()]
    test_label_arrays = [item.labels for item in test.to_list()]

    labels = ClassLabelIndicatorsFromIntArrayLabels(NUM_CLASSES).batch_apply(
        Dataset.of(train_label_arrays)
    )

    featurizer = build_featurizer(train_images, config)
    # No MaxClassifier: MAP evaluation consumes raw per-class scores.
    pipeline = featurizer.and_then(
        BlockLeastSquaresEstimator(config.block_size, 1, config.lam),
        train_images,
        labels,
    )

    evaluator = MeanAveragePrecisionEvaluator(NUM_CLASSES)
    aps = evaluator.evaluate(
        pipeline.apply(test_images), Dataset.of(test_label_arrays)
    )
    mean_ap = float(np.mean(np.asarray(aps)))
    logger.info("TEST APs: %s", np.round(np.asarray(aps), 3))
    logger.info("TEST Mean Average Precision: %.4f", mean_ap)
    logger.info("Pipeline took %.1f s", time.time() - start)
    return pipeline, aps, mean_ap


def main(argv=None):
    parser = argparse.ArgumentParser("VOCSIFTFisher")
    parser.add_argument("--trainLocation", default="")
    parser.add_argument("--trainLabels", default="")
    parser.add_argument("--testLocation", default="")
    parser.add_argument("--testLabels", default="")
    parser.add_argument("--lambda", dest="lam", type=float, default=0.5)
    parser.add_argument("--descDim", type=int, default=80)
    parser.add_argument("--vocabSize", type=int, default=16)
    parser.add_argument("--scaleStep", type=int, default=1)
    parser.add_argument("--blockSize", type=int, default=4096)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    config = VOCConfig(
        train_location=args.trainLocation,
        train_labels=args.trainLabels,
        test_location=args.testLocation,
        test_labels=args.testLabels,
        lam=args.lam,
        descriptor_dim=args.descDim,
        vocab_size=args.vocabSize,
        sift_scale_step=args.scaleStep,
        block_size=args.blockSize,
        seed=args.seed,
    )
    _, _, mean_ap = run(config)
    print(f"TEST Mean Average Precision is {mean_ap:.4f}")


if __name__ == "__main__":
    main()
