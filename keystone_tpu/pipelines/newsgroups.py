"""NewsgroupsPipeline: ngram term-frequency features + naive Bayes on
20-newsgroups (reference: pipelines/text/NewsgroupsPipeline.scala:25-72).

Composition: Trim → LowerCase → Tokenizer → NGramsFeaturizer(1..n) →
TermFrequency(log1p) → AllSparseFeatures → NaiveBayes → MaxClassifier.
"""

from __future__ import annotations

import argparse
import logging
import time
from dataclasses import dataclass

import numpy as np

from keystone_tpu.data.loaders import load_newsgroups, synthetic_documents
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.ops.learning.classifiers import NaiveBayesEstimator
from keystone_tpu.ops.nlp import LowerCase, NGramsFeaturizer, Tokenizer, Trim
from keystone_tpu.ops.sparse import AllSparseFeatures
from keystone_tpu.ops.stats import TermFrequency
from keystone_tpu.ops.util import MaxClassifier
from keystone_tpu.workflow import Pipeline

logger = logging.getLogger("keystone_tpu.pipelines.newsgroups")

NUM_CLASSES = 20


@dataclass
class NewsgroupsConfig:
    train_location: str = ""
    test_location: str = ""
    n_grams: int = 2
    seed: int = 0
    synthetic_n: int = 400
    synthetic_classes: int = NUM_CLASSES


def build_featurizer(config: NewsgroupsConfig) -> Pipeline:
    # log-scaled term frequency (NewsgroupsPipeline.scala:31: x => log(x + 1))
    return (
        Trim()
        .to_pipeline()
        .and_then(LowerCase())
        .and_then(Tokenizer())
        .and_then(NGramsFeaturizer(range(1, config.n_grams + 1)))
        .and_then(TermFrequency(weighting=lambda x: np.log1p(x)))
    )


def run(config: NewsgroupsConfig):
    start = time.time()
    if config.train_location:
        train = load_newsgroups(config.train_location)
        test = load_newsgroups(config.test_location)
        num_classes = NUM_CLASSES
    else:
        num_classes = config.synthetic_classes
        train = synthetic_documents(
            config.synthetic_n, num_classes, seed=config.seed
        )
        test = synthetic_documents(
            max(config.synthetic_n // 4, 64), num_classes, seed=config.seed + 1
        )

    featurizer = build_featurizer(config)
    pipeline = featurizer.and_then(AllSparseFeatures(), train.data).and_then(
        NaiveBayesEstimator(num_classes), train.data, train.labels
    ).and_then(MaxClassifier())

    evaluator = MulticlassClassifierEvaluator(num_classes)
    train_eval = evaluator.evaluate(pipeline.apply(train.data), train.labels)
    test_eval = evaluator.evaluate(pipeline.apply(test.data), test.labels)
    logger.info("TRAIN error %.2f%%", 100 * train_eval.total_error)
    logger.info("TEST error %.2f%%", 100 * test_eval.total_error)
    logger.info("Pipeline took %.1f s", time.time() - start)
    return pipeline, train_eval, test_eval


def main(argv=None):
    parser = argparse.ArgumentParser("NewsgroupsPipeline")
    parser.add_argument("--trainLocation", default="")
    parser.add_argument("--testLocation", default="")
    parser.add_argument("--nGrams", type=int, default=2)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    config = NewsgroupsConfig(
        train_location=args.trainLocation,
        test_location=args.testLocation,
        n_grams=args.nGrams,
    )
    _, train_eval, test_eval = run(config)
    print(f"TRAIN error is {100 * train_eval.total_error:.2f}%")
    print(f"TEST error is {100 * test_eval.total_error:.2f}%")


if __name__ == "__main__":
    main()
