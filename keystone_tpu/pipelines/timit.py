"""TimitPipeline: cosine random features + block least squares on TIMIT
(reference: pipelines/speech/TimitPipeline.scala:37-130).

Composition: gather(numCosines × CosineRandomFeatures(440→4096, γ,
gaussian|cauchy)) → VectorCombiner → BlockLeastSquares(4096, numEpochs, λ)
→ MaxClassifier.
"""

from __future__ import annotations

import argparse
import logging
import time
from dataclasses import dataclass

from keystone_tpu.data.loaders import TimitFeaturesDataLoader, synthetic_timit
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.ops.stats import CosineRandomFeatures
from keystone_tpu.ops.util import (
    ClassLabelIndicatorsFromIntLabels,
    MaxClassifier,
    VectorCombiner,
)
from keystone_tpu.workflow import Pipeline

logger = logging.getLogger("keystone_tpu.pipelines.timit")

NUM_CLASSES = TimitFeaturesDataLoader.num_classes  # 147
NUM_INPUT_FEATURES = TimitFeaturesDataLoader.num_features  # 440


@dataclass
class TimitConfig:
    train_data_location: str = ""
    train_labels_location: str = ""
    test_data_location: str = ""
    test_labels_location: str = ""
    num_parts: int = 512  # kept for flag parity; sharding is mesh-driven
    num_cosines: int = 50
    gamma: float = 0.05555
    rf_type: str = "gaussian"  # or "cauchy" (TimitPipeline.scala Distributions)
    block_size: int = 4096
    num_epochs: int = 5
    lam: float = 0.0
    seed: int = 123
    synthetic_n: int = 4096
    # Solver selection:
    #   "auto"      — cost-model-driven (LeastSquaresEstimator): the
    #                 optimizer picks among resident solvers and the
    #                 out-of-core streaming tier by analytic cost under an
    #                 HBM feasibility cut; past the memory wall the
    #                 StreamedFitFusionRule binds the cosine featurizer
    #                 into the fit with NO flag (the reference's defining
    #                 behavior, LeastSquaresEstimator.scala:59-84).
    #   "block"     — force BlockLeastSquares(block_size, epochs, λ), the
    #                 reference TimitPipeline's literal composition.
    #   "streaming" — force the out-of-core tier (the old --streaming).
    # All three fit the same centered model (streaming_ls centering).
    solver: str = "auto"
    # Back-compat alias: streaming=True == solver="streaming".
    streaming: bool = False


def build_featurizer(config: TimitConfig) -> Pipeline:
    """numCosines branches of 4096 random features each
    (TimitPipeline.scala:61-78: numCosineFeatures = 4096 per batch)."""
    branches = [
        CosineRandomFeatures(
            NUM_INPUT_FEATURES,
            config.block_size,
            config.gamma,
            seed=config.seed + i,
            cauchy=(config.rf_type == "cauchy"),
        ).to_pipeline()
        for i in range(config.num_cosines)
    ]
    return Pipeline.gather(branches).and_then(VectorCombiner())


def run(config: TimitConfig):
    start = time.time()
    if config.train_data_location:
        train = TimitFeaturesDataLoader(
            config.train_data_location, config.train_labels_location
        ).labeled
        test = TimitFeaturesDataLoader(
            config.test_data_location, config.test_labels_location
        ).labeled
    else:
        train = synthetic_timit(config.synthetic_n, seed=config.seed)
        test = synthetic_timit(max(config.synthetic_n // 4, 256), seed=config.seed + 1)
        # The reference default (numCosines=50 -> 204,800 features) is a
        # 2.2M-row cluster shape (TimitPipeline.scala:30); at the synthetic
        # demo's row count it is absurdly overparametrized and overflows a
        # single chip's HBM. Cap the demo's feature width at 8n; explicit
        # real-data runs keep whatever was asked for.
        max_branches = max(
            1, (8 * config.synthetic_n) // max(config.block_size, 1)
        )
        if config.num_cosines > max_branches:
            from dataclasses import replace

            logger.info(
                "synthetic demo: capping numCosines %d -> %d (d <= 8n)",
                config.num_cosines, max_branches,
            )
            config = replace(config, num_cosines=max_branches)

    labels = ClassLabelIndicatorsFromIntLabels(NUM_CLASSES)(train.labels)

    solver = "streaming" if config.streaming else config.solver
    if solver == "streaming":
        import jax.numpy as jnp

        from keystone_tpu.ops.learning.streaming_ls import (
            StreamingFeaturizedLeastSquares,
            cosine_bank_featurize,
        )

        rfs = [
            CosineRandomFeatures(
                NUM_INPUT_FEATURES, config.block_size, config.gamma,
                seed=config.seed + i, cauchy=(config.rf_type == "cauchy"),
            )
            for i in range(config.num_cosines)
        ]
        bank = cosine_bank_featurize(
            jnp.concatenate([rf.W for rf in rfs]),
            jnp.concatenate([rf.b for rf in rfs]),
        )
        est = StreamingFeaturizedLeastSquares(
            bank, d_feat=config.num_cosines * config.block_size,
            block_size=config.block_size, num_iter=config.num_epochs,
            lam=config.lam,
        )
        pipeline = est.with_data(train.data, labels).and_then(MaxClassifier())
    elif solver == "auto":
        # Cost-model-driven selection: at resident-friendly geometry this
        # picks a resident solver (BlockLS at the reference's shape); past
        # the HBM wall the streaming choice wins and the optimizer fuses
        # the cosine featurizer into the fit — no flag.
        from keystone_tpu.ops.learning.cost import LeastSquaresEstimator

        est = LeastSquaresEstimator(
            lam=config.lam,
            block_size=config.block_size,
            block_iters=config.num_epochs,
        )
        pipeline = build_featurizer(config).and_then(
            est, train.data, labels,
        ).and_then(MaxClassifier())
    else:
        pipeline = build_featurizer(config).and_then(
            BlockLeastSquaresEstimator(config.block_size, config.num_epochs, config.lam),
            train.data,
            labels,
        ).and_then(MaxClassifier())

    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    train_eval = evaluator.evaluate(pipeline.apply(train.data), train.labels)
    logger.info("TRAIN Error is %.2f%%", 100 * train_eval.total_error)
    test_eval = evaluator.evaluate(pipeline.apply(test.data), test.labels)
    logger.info("TEST Error is %.2f%%", 100 * test_eval.total_error)
    logger.info("Pipeline took %.1f s", time.time() - start)
    return pipeline, train_eval, test_eval


def main(argv=None):
    parser = argparse.ArgumentParser("Timit")
    parser.add_argument("--trainDataLocation", default="")
    parser.add_argument("--trainLabelsLocation", default="")
    parser.add_argument("--testDataLocation", default="")
    parser.add_argument("--testLabelsLocation", default="")
    parser.add_argument("--numParts", type=int, default=512)
    parser.add_argument("--numCosines", type=int, default=50)
    parser.add_argument("--gamma", type=float, default=0.05555)
    parser.add_argument("--rfType", default="gaussian", choices=["gaussian", "cauchy"])
    parser.add_argument("--blockSize", type=int, default=4096)
    parser.add_argument("--numEpochs", type=int, default=5)
    parser.add_argument("--lambda", dest="lam", type=float, default=0.0)
    parser.add_argument("--seed", type=int, default=123)
    parser.add_argument(
        "--streaming", action="store_true",
        help="force the out-of-core fit (equivalent to --solver streaming)",
    )
    parser.add_argument(
        "--solver", default="auto", choices=["auto", "block", "streaming"],
        help="auto = cost-model selection with HBM feasibility (default); "
        "block = reference-literal BlockLeastSquares",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    config = TimitConfig(
        train_data_location=args.trainDataLocation,
        train_labels_location=args.trainLabelsLocation,
        test_data_location=args.testDataLocation,
        test_labels_location=args.testLabelsLocation,
        num_parts=args.numParts,
        num_cosines=args.numCosines,
        gamma=args.gamma,
        rf_type=args.rfType,
        block_size=args.blockSize,
        num_epochs=args.numEpochs,
        lam=args.lam,
        seed=args.seed,
        solver=args.solver,
        streaming=args.streaming,
    )
    _, train_eval, test_eval = run(config)
    print(f"TRAIN Error is {100 * train_eval.total_error:.2f}%")
    print(f"TEST Error is {100 * test_eval.total_error:.2f}%")


if __name__ == "__main__":
    main()
