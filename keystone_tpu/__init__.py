"""keystone_tpu: a TPU-native ML pipeline framework.

A ground-up JAX/XLA re-design of the capabilities of KeystoneML
(reference: amplab/keystone — Scala/Spark): lazily-executed typed pipeline
DAGs of Transformers and Estimators, a whole-pipeline rule-based optimizer
with cross-pipeline state reuse, a library of featurization nodes and
distributed solvers, and example end-to-end workloads — with sharded
`jax.Array`s over a TPU device mesh in place of RDDs over a Spark cluster.
"""

from keystone_tpu.data import Dataset, LabeledData
from keystone_tpu.workflow import (
    Chainable,
    Estimator,
    FittedPipeline,
    Identity,
    LabelEstimator,
    Pipeline,
    PipelineDataset,
    PipelineDatum,
    PipelineEnv,
    Transformer,
    transformer,
)

__version__ = "0.1.0"

__all__ = [
    "Dataset",
    "LabeledData",
    "Chainable",
    "Estimator",
    "FittedPipeline",
    "Identity",
    "LabelEstimator",
    "Pipeline",
    "PipelineDataset",
    "PipelineDatum",
    "PipelineEnv",
    "Transformer",
    "transformer",
]
