"""keystone_tpu: a TPU-native ML pipeline framework.

A ground-up JAX/XLA re-design of the capabilities of KeystoneML
(reference: amplab/keystone — Scala/Spark): lazily-executed typed pipeline
DAGs of Transformers and Estimators, a whole-pipeline rule-based optimizer
with cross-pipeline state reuse, a library of featurization nodes and
distributed solvers, and example end-to-end workloads — with sharded
`jax.Array`s over a TPU device mesh in place of RDDs over a Spark cluster.
"""

import logging as _logging
import os as _os

import jax as _jax

# f32 means f32: TPU's out-of-the-box matmul default runs float32 operands
# through a single lossy bfloat16 pass, which silently corrupts the solver
# paths that CPU tests validate exactly (observed: finite-but-garbage
# Cholesky factors and diverging BCD sweeps on rank-deficient blocks; the
# triangular solves inside cho_solve/LU cannot take a per-op precision
# flag). bfloat16 compute stays an explicit choice via bf16 operands
# (feature layouts, Pallas compute_dtype) — those are unaffected by this
# default. A precision the host application configured before importing
# this package is respected; KEYSTONE_MATMUL_PRECISION overrides both.
if "KEYSTONE_MATMUL_PRECISION" in _os.environ:
    _jax.config.update(
        "jax_default_matmul_precision",
        _os.environ["KEYSTONE_MATMUL_PRECISION"],
    )
elif _jax.config.jax_default_matmul_precision is None:
    _jax.config.update("jax_default_matmul_precision", "float32")
    # Process-global side effect on host applications sharing this process:
    # say so once (suppress with KEYSTONE_MATMUL_PRECISION).
    _logging.getLogger("keystone_tpu").info(
        "keystone_tpu set jax_default_matmul_precision=float32 for solver "
        "accuracy on TPU; set KEYSTONE_MATMUL_PRECISION to override."
    )

from keystone_tpu.data import Dataset, LabeledData
from keystone_tpu.workflow import (
    Chainable,
    Estimator,
    FittedPipeline,
    Identity,
    LabelEstimator,
    Pipeline,
    PipelineDataset,
    PipelineDatum,
    PipelineEnv,
    Transformer,
    transformer,
)

__version__ = "0.1.0"

__all__ = [
    "Dataset",
    "LabeledData",
    "Chainable",
    "Estimator",
    "FittedPipeline",
    "Identity",
    "LabelEstimator",
    "Pipeline",
    "PipelineDataset",
    "PipelineDatum",
    "PipelineEnv",
    "Transformer",
    "transformer",
]
