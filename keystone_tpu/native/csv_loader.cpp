// Native host-side data plane: fast CSV float parsing + PNM decode.
//
// The reference ships a native tier for host-side work the JVM was too slow
// for (src/main/cpp/{VLFeat,EncEval}.cxx). On TPU the compute members of that
// tier live on-device (Pallas/XLA); the host-side member that remains is the
// data loader: CSV/text ingestion feeding the device. Exposed through ctypes
// (keystone_tpu/native/__init__.py).

#include <cstdlib>
#include <cstring>
#include <cctype>

extern "C" {

// Parse a buffer of comma/whitespace-separated doubles.
// Returns the number of values written to `out` (capped at max_vals).
// Writes the first row's column count to n_cols and the number of non-empty
// rows to n_rows so the caller can validate rectangular shape.
long ks_parse_csv(const char* buf, long len, double* out, long max_vals,
                  long* n_cols, long* n_rows) {
  const char* p = buf;
  const char* end = buf + len;
  long count = 0;
  long cols = 0;
  long rows = 0;
  long row_vals = 0;
  bool first_row = true;
  *n_cols = 0;

  while (p < end && count < max_vals) {
    // skip separators
    while (p < end && (*p == ',' || *p == ' ' || *p == '\t' || *p == '\r')) p++;
    if (p < end && *p == '\n') {
      if (row_vals > 0) {
        rows++;
        if (first_row) {
          *n_cols = cols;
          first_row = false;
        }
      }
      row_vals = 0;
      p++;
      continue;
    }
    if (p >= end) break;
    char* next = nullptr;
    double v = strtod(p, &next);
    if (next == p) {  // unparseable token: skip it
      while (p < end && *p != ',' && *p != '\n' && *p != ' ' && *p != '\t') p++;
      continue;
    }
    out[count++] = v;
    row_vals++;
    if (first_row) cols++;
    p = next;
  }
  if (row_vals > 0) {
    rows++;
    if (first_row) *n_cols = cols;
  }
  *n_rows = rows;
  return count;
}

// Decode binary PPM (P6) / PGM (P5) into float32 HWC, rescaled to [0, 255].
// Returns 0 on success; fills x_dim (height), y_dim (width), channels.
// maxval > 255 (2-byte samples) returns an error so the caller can fall back
// to a full decoder.
int ks_decode_pnm(const unsigned char* buf, long len, float* out, long max_vals,
                  long* x_dim, long* y_dim, long* channels) {
  if (len < 2 || buf[0] != 'P') return 1;
  int kind = buf[1] - '0';
  if (kind != 5 && kind != 6) return 2;
  long pos = 2;
  long vals[3];  // width, height, maxval
  int got = 0;
  while (got < 3 && pos < len) {
    // skip whitespace and comments
    while (pos < len && (isspace(buf[pos]) || buf[pos] == '#')) {
      if (buf[pos] == '#')
        while (pos < len && buf[pos] != '\n') pos++;
      else
        pos++;
    }
    long v = 0;
    bool any = false;
    while (pos < len && isdigit(buf[pos])) {
      v = v * 10 + (buf[pos] - '0');
      pos++;
      any = true;
    }
    if (!any) return 3;
    vals[got++] = v;
  }
  if (got < 3 || pos >= len) return 3;
  pos++;  // single whitespace after maxval
  long w = vals[0], h = vals[1], maxval = vals[2];
  if (maxval <= 0 || maxval > 255) return 6;  // 16-bit: let PIL handle it
  long c = (kind == 6) ? 3 : 1;
  if (h * w * c > max_vals) return 4;
  if (pos + h * w * c > len) return 5;
  float scale = 255.0f / (float)maxval;
  for (long i = 0; i < h * w * c; i++) out[i] = (float)buf[pos + i] * scale;
  *x_dim = h;
  *y_dim = w;
  *channels = c;
  return 0;
}

}  // extern "C"
