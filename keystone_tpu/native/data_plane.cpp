// Native host-side data plane, part 2: fixed-record binary decode (CIFAR
// family) and a threaded multi-buffer CSV parser.
//
// The reference reads 3073-byte CIFAR records on the driver
// (loaders/CifarLoader.scala:14-53) and parses CSVs through Spark's line
// RDDs; here the record deinterleave + planar->HWC uint8->float conversion
// and bulk CSV parsing are parallel native loops feeding the device.

#include <atomic>
#include <cstdint>
#include <thread>
#include <vector>

extern "C" {

// From csv_loader.cpp.
long ks_parse_csv(const char* buf, long len, double* out, long max_vals,
                  long* n_cols, long* n_rows);

// Deinterleave fixed-size records of [label_bytes | c*h*w planar uint8].
// Writes the LAST label byte per record (CIFAR-10: the only byte; CIFAR-100:
// the fine label) to labels_out and HWC float32 pixels to images_out.
void ks_split_records(const uint8_t* buf, long n_records, long label_bytes,
                      long channels, long height, long width,
                      int64_t* labels_out, float* images_out) {
  const long img_bytes = channels * height * width;
  const long rec = label_bytes + img_bytes;
  const long plane = height * width;

  long n_threads = (long)std::thread::hardware_concurrency();
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_records) n_threads = n_records;
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  const long chunk = (n_records + n_threads - 1) / n_threads;
  for (long t = 0; t < n_threads; ++t) {
    const long lo = t * chunk;
    const long hi = (lo + chunk < n_records) ? lo + chunk : n_records;
    if (lo >= hi) break;
    workers.emplace_back([=]() {
      for (long r = lo; r < hi; ++r) {
        const uint8_t* p = buf + r * rec;
        labels_out[r] = (int64_t)p[label_bytes - 1];
        const uint8_t* img = p + label_bytes;
        float* out = images_out + r * img_bytes;
        for (long c = 0; c < channels; ++c) {
          const uint8_t* pl = img + c * plane;
          for (long i = 0; i < plane; ++i) {
            out[i * channels + c] = (float)pl[i];
          }
        }
      }
    });
  }
  for (auto& w : workers) w.join();
}

// Parse n_bufs CSV byte buffers concurrently (one task per buffer, pulled
// from a shared counter by hardware_concurrency() threads). Per-buffer
// outputs mirror ks_parse_csv: value count, column count, row count.
void ks_parse_csv_many(const char** bufs, const long* lens, long n_bufs,
                       double** outs, const long* max_vals, long* counts,
                       long* n_cols, long* n_rows) {
  long n_threads = (long)std::thread::hardware_concurrency();
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_bufs) n_threads = n_bufs;
  std::atomic<long> next(0);
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  for (long t = 0; t < n_threads; ++t) {
    workers.emplace_back([&]() {
      for (;;) {
        const long i = next.fetch_add(1);
        if (i >= n_bufs) return;
        counts[i] = ks_parse_csv(bufs[i], lens[i], outs[i], max_vals[i],
                                 &n_cols[i], &n_rows[i]);
      }
    });
  }
  for (auto& w : workers) w.join();
}

}  // extern "C"

extern "C" {

// From csv_loader.cpp.
int ks_decode_pnm(const unsigned char* data, long len, float* out,
                  long max_vals, long* x, long* y, long* c);

// Decode n_bufs PNM buffers concurrently (thread pool over a shared counter).
// Per-buffer outputs mirror ks_decode_pnm; rcs[i] is the per-buffer return
// code (0 = ok).
void ks_decode_pnm_many(const char** bufs, const long* lens, long n_bufs,
                        float** outs, const long* max_vals, long* xs,
                        long* ys, long* cs, long* rcs) {
  long n_threads = (long)std::thread::hardware_concurrency();
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_bufs) n_threads = n_bufs;
  std::atomic<long> next(0);
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  for (long t = 0; t < n_threads; ++t) {
    workers.emplace_back([&]() {
      for (;;) {
        const long i = next.fetch_add(1);
        if (i >= n_bufs) return;
        rcs[i] = ks_decode_pnm(
            reinterpret_cast<const unsigned char*>(bufs[i]), lens[i],
            outs[i], max_vals[i], &xs[i], &ys[i], &cs[i]);
      }
    });
  }
  for (auto& w : workers) w.join();
}

}  // extern "C"
