"""Native host-side data plane (the analog of the reference's src/main/cpp
tier, loaded there via System.loadLibrary — utils/external/VLFeat.scala:4).

The C++ sources here are built on demand with g++ into a shared library inside
the package directory and bound via ctypes. Everything degrades gracefully:
if no compiler is available the pure-NumPy/PIL paths are used instead, so the
library never hard-fails at import.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libkeystone_native.so")
_SOURCES = [
    os.path.join(_DIR, "csv_loader.cpp"),
    os.path.join(_DIR, "data_plane.cpp"),
]

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-o", _LIB_PATH] + _SOURCES
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=120)
        return res.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        newest_src = max(os.path.getmtime(s) for s in _SOURCES)
        if not os.path.exists(_LIB_PATH) or os.path.getmtime(_LIB_PATH) < newest_src:
            if not _build():
                return None
        lib = ctypes.CDLL(_LIB_PATH)
        lib.ks_parse_csv.restype = ctypes.c_long
        lib.ks_parse_csv.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.ks_split_records.restype = None
        lib.ks_split_records.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.ks_parse_csv_many.restype = None
        lib.ks_parse_csv_many.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_long),
            ctypes.c_long,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
        ]
        # Bindings for symbols that may be absent from a stale .so are
        # guarded so get_lib keeps its degrade-gracefully contract.
        if hasattr(lib, "ks_decode_pnm_many"):
            lib.ks_decode_pnm_many.restype = None
            lib.ks_decode_pnm_many.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_long),
                ctypes.c_long,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long),
            ]
        lib.ks_decode_pnm.restype = ctypes.c_int
        lib.ks_decode_pnm.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
        ]
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def _csv_max_vals(text: bytes) -> int:
    """Upper bound on the value count of a CSV buffer: every value is
    preceded by a separator (incl. CR, which the parser skips) or starts the
    buffer."""
    return (
        text.count(b",")
        + text.count(b"\n")
        + text.count(b" ")
        + text.count(b"\t")
        + text.count(b"\r")
        + 2
    )


def parse_csv_floats(text: bytes) -> Tuple[np.ndarray, int, int]:
    """Parse a CSV byte buffer into (flat float64 values, num_columns,
    num_rows). Uses the native parser when available, else a NumPy fallback.
    Callers should validate values.size == num_rows * num_columns to reject
    ragged input."""
    lib = get_lib()
    if lib is not None:
        max_vals = _csv_max_vals(text)
        out = np.empty(max_vals, dtype=np.float64)
        ncols = ctypes.c_long(0)
        nrows = ctypes.c_long(0)
        n = lib.ks_parse_csv(
            text,
            len(text),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            max_vals,
            ctypes.byref(ncols),
            ctypes.byref(nrows),
        )
        return out[:n].copy(), int(ncols.value), int(nrows.value)
    # Fallback
    rows = [r for r in text.decode("utf-8", "ignore").splitlines() if r.strip()]
    vals = []
    ncols = 0
    for r in rows:
        parts = [p for p in r.replace(",", " ").split() if p]
        if not ncols:
            ncols = len(parts)
        vals.extend(float(p) for p in parts)
    return np.asarray(vals, dtype=np.float64), ncols, len(rows)


def decode_pnm(data: bytes) -> Optional[np.ndarray]:
    """Decode binary PPM/PGM bytes to a float32 (x, y, c) array via the
    native decoder; None if the library is unavailable or decoding fails."""
    lib = get_lib()
    if lib is None:
        return None
    max_vals = len(data) * 3
    out = np.empty(max_vals, dtype=np.float32)
    x = ctypes.c_long(0)
    y = ctypes.c_long(0)
    c = ctypes.c_long(0)
    rc = lib.ks_decode_pnm(
        data,
        len(data),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        max_vals,
        ctypes.byref(x),
        ctypes.byref(y),
        ctypes.byref(c),
    )
    if rc != 0:
        return None
    n = x.value * y.value * c.value
    return out[:n].copy().reshape(x.value, y.value, c.value)


def split_records(
    buf: bytes,
    label_bytes: int,
    channels: int,
    height: int,
    width: int,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Deinterleave CIFAR-style fixed records [label_bytes | planar pixels]
    into (int64 labels, float32 HWC images) with a threaded native loop;
    None when the native library is unavailable. The last label byte is used
    (CIFAR-10's only byte; CIFAR-100's fine label)."""
    if label_bytes < 1:
        raise ValueError("label_bytes must be >= 1")
    lib = get_lib()
    if lib is None:
        return None
    img_bytes = channels * height * width
    rec = label_bytes + img_bytes
    if len(buf) % rec != 0:
        raise ValueError(f"buffer not a multiple of record size {rec}")
    n = len(buf) // rec
    labels = np.empty(n, dtype=np.int64)
    images = np.empty((n, height, width, channels), dtype=np.float32)
    lib.ks_split_records(
        buf,
        n,
        label_bytes,
        channels,
        height,
        width,
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return labels, images


def parse_csv_floats_many(texts) -> Optional[list]:
    """Parse many CSV byte buffers concurrently via the native thread pool.
    Returns a list of (flat values, num_columns, num_rows) or None when the
    native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(texts)
    if n == 0:
        return []
    bufs = (ctypes.c_char_p * n)(*texts)
    lens = (ctypes.c_long * n)(*[len(t) for t in texts])
    max_vals_list = [_csv_max_vals(t) for t in texts]
    outs_np = [np.empty(m, dtype=np.float64) for m in max_vals_list]
    outs = (ctypes.POINTER(ctypes.c_double) * n)(
        *[o.ctypes.data_as(ctypes.POINTER(ctypes.c_double)) for o in outs_np]
    )
    max_vals = (ctypes.c_long * n)(*max_vals_list)
    counts = (ctypes.c_long * n)()
    ncols = (ctypes.c_long * n)()
    nrows = (ctypes.c_long * n)()
    lib.ks_parse_csv_many(bufs, lens, n, outs, max_vals, counts, ncols, nrows)
    return [
        (outs_np[i][: counts[i]].copy(), int(ncols[i]), int(nrows[i]))
        for i in range(n)
    ]


def decode_pnm_many(datas) -> Optional[list]:
    """Decode many binary PNM buffers concurrently via the native thread
    pool. Returns a list of float32 (h, w, c) arrays (None per item that
    failed to decode), or None when the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    if not hasattr(lib, "ks_decode_pnm_many"):
        return None
    n = len(datas)
    if n == 0:
        return []
    bufs = (ctypes.c_char_p * n)(*datas)
    lens = (ctypes.c_long * n)(*[len(d) for d in datas])
    max_vals_list = [len(d) * 3 for d in datas]
    outs_np = [np.empty(m, dtype=np.float32) for m in max_vals_list]
    outs = (ctypes.POINTER(ctypes.c_float) * n)(
        *[o.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for o in outs_np]
    )
    max_vals = (ctypes.c_long * n)(*max_vals_list)
    xs = (ctypes.c_long * n)()
    ys = (ctypes.c_long * n)()
    cs = (ctypes.c_long * n)()
    rcs = (ctypes.c_long * n)()
    lib.ks_decode_pnm_many(bufs, lens, n, outs, max_vals, xs, ys, cs, rcs)
    results = []
    for i in range(n):
        if rcs[i] != 0:
            results.append(None)
            continue
        count = xs[i] * ys[i] * cs[i]
        results.append(outs_np[i][:count].copy().reshape(xs[i], ys[i], cs[i]))
    return results
