"""Native host-side data plane (the analog of the reference's src/main/cpp
tier, loaded there via System.loadLibrary — utils/external/VLFeat.scala:4).

The C++ sources here are built on demand with g++ into a shared library inside
the package directory and bound via ctypes. Everything degrades gracefully:
if no compiler is available the pure-NumPy/PIL paths are used instead, so the
library never hard-fails at import.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libkeystone_native.so")
_SOURCES = [os.path.join(_DIR, "csv_loader.cpp")]

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB_PATH] + _SOURCES
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=120)
        return res.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        newest_src = max(os.path.getmtime(s) for s in _SOURCES)
        if not os.path.exists(_LIB_PATH) or os.path.getmtime(_LIB_PATH) < newest_src:
            if not _build():
                return None
        lib = ctypes.CDLL(_LIB_PATH)
        lib.ks_parse_csv.restype = ctypes.c_long
        lib.ks_parse_csv.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.ks_decode_pnm.restype = ctypes.c_int
        lib.ks_decode_pnm.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
        ]
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def parse_csv_floats(text: bytes) -> Tuple[np.ndarray, int, int]:
    """Parse a CSV byte buffer into (flat float64 values, num_columns,
    num_rows). Uses the native parser when available, else a NumPy fallback.
    Callers should validate values.size == num_rows * num_columns to reject
    ragged input."""
    lib = get_lib()
    if lib is not None:
        # Upper bound on value count: every value is preceded by a separator
        # or starts the buffer.
        max_vals = (
            text.count(b",")
            + text.count(b"\n")
            + text.count(b" ")
            + text.count(b"\t")
            + 2
        )
        out = np.empty(max_vals, dtype=np.float64)
        ncols = ctypes.c_long(0)
        nrows = ctypes.c_long(0)
        n = lib.ks_parse_csv(
            text,
            len(text),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            max_vals,
            ctypes.byref(ncols),
            ctypes.byref(nrows),
        )
        return out[:n].copy(), int(ncols.value), int(nrows.value)
    # Fallback
    rows = [r for r in text.decode("utf-8", "ignore").splitlines() if r.strip()]
    vals = []
    ncols = 0
    for r in rows:
        parts = [p for p in r.replace(",", " ").split() if p]
        if not ncols:
            ncols = len(parts)
        vals.extend(float(p) for p in parts)
    return np.asarray(vals, dtype=np.float64), ncols, len(rows)


def decode_pnm(data: bytes) -> Optional[np.ndarray]:
    """Decode binary PPM/PGM bytes to a float32 (x, y, c) array via the
    native decoder; None if the library is unavailable or decoding fails."""
    lib = get_lib()
    if lib is None:
        return None
    max_vals = len(data) * 3
    out = np.empty(max_vals, dtype=np.float32)
    x = ctypes.c_long(0)
    y = ctypes.c_long(0)
    c = ctypes.c_long(0)
    rc = lib.ks_decode_pnm(
        data,
        len(data),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        max_vals,
        ctypes.byref(x),
        ctypes.byref(y),
        ctypes.byref(c),
    )
    if rc != 0:
        return None
    n = x.value * y.value * c.value
    return out[:n].copy().reshape(x.value, y.value, c.value)
