"""Process-wide tracer: nested, thread-safe spans under one ``run_id``.

Design constraints, in priority order:

1. **Zero-cost when disabled.** Every hook in the hot paths (fold steps,
   runtime lane tasks, prefetch waits) funnels through module-level
   :func:`span` / :func:`event` / :func:`counter`, each guarded by ONE
   branch on the module-global ``_ACTIVE``. Disabled, :func:`span`
   returns a shared no-op context manager — no allocation beyond the
   caller's kwargs, no lock, no timestamps. The regression test in
   ``tests/test_obs.py`` pins the disabled per-hook cost.
2. **Thread-safe nesting.** Spans nest per thread (a thread-local
   stack); a span opened on a runtime IO worker records that worker's
   thread name and parents onto whatever span is open *on that thread*
   (cross-thread causality rides the shared ``run_id`` + lane names).
   Finished records append to one lock-guarded list.
3. **No jax.** The data-plane runtime imports this module from its IO
   workers; the one-thread-owns-JAX discipline must hold by
   construction here exactly as it does in ``data/runtime.py``.

Records are plain dicts (the JSONL event-log rows — see
``obs/export.py`` for the Chrome-trace projection):

  span   {"type": "span", "name", "ts_us", "dur_us", "tid", "thread",
          "span_id", "parent_id", "run_id", "args"}
  event  {"type": "event", "name", "ts_us", "tid", "thread", "run_id",
          "args"}  — instants (cost decisions, faults)
  count  {"type": "counter", "name", "ts_us", "value", "run_id"}
         — counter-track samples (queue depths, outstanding requests)
"""

from __future__ import annotations

import contextlib
import itertools
import logging
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

logger = logging.getLogger("keystone_tpu.obs.tracer")

__all__ = [
    "CostDecision",
    "CostOutcomeRef",
    "Span",
    "TailSampler",
    "Tracer",
    "active_tracer",
    "counter_track",
    "enabled",
    "event",
    "record_cost_decision",
    "span",
    "tracing",
    "tracing_from_env",
]

TRACE_ENV = "KEYSTONE_TRACE"
# Tail-sampling knobs for serving spans under a long-lived traced serve:
# head-sample rate (keep 1-in-round(1/rate)) and the slow threshold in
# milliseconds past which a request span is ALWAYS kept.
TRACE_SAMPLE_ENV = "KEYSTONE_TRACE_SAMPLE"
TRACE_SLOW_MS_ENV = "KEYSTONE_TRACE_SLOW_MS"


class _NoopSpan:
    """The shared disabled-path span: one instance for the whole
    process, so a disabled hook allocates nothing."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> None:
        """Attribute setter no-op (the enabled Span's ``set``)."""


_NOOP = _NoopSpan()

# THE one branch: every hook reads this module global. None = disabled.
_ACTIVE: Optional["Tracer"] = None
_ACTIVE_LOCK = threading.Lock()


def enabled() -> bool:
    """Whether a tracer is active (the guard hot paths may hoist when a
    hook's argument construction itself is worth skipping)."""
    return _ACTIVE is not None


def active_tracer() -> Optional["Tracer"]:
    return _ACTIVE


def span(name: str, **attrs) -> Any:
    """Open a span under the active tracer, or the shared no-op when
    tracing is disabled — the ONE hook hot paths call."""
    t = _ACTIVE
    if t is None:
        return _NOOP
    return t.span(name, **attrs)


def event(name: str, **attrs) -> None:
    """Record an instant event (no duration) under the active tracer."""
    t = _ACTIVE
    if t is not None:
        t.event(name, **attrs)


def counter_track(name: str, value: float) -> None:
    """Record one sample on a counter track (queue depth, outstanding
    requests) under the active tracer. Track names are free-form trace
    labels — a separate namespace from the registry's METRIC_* catalogue
    (which the metric-name lint rule polices)."""
    t = _ACTIVE
    if t is not None:
        t.counter_track(name, value)


class Span:
    """One open span: context manager handed out by :meth:`Tracer.span`.

    ``set(**attrs)`` adds attributes after open (e.g. a fold step's
    realized chunk count). Entering pushes onto the calling thread's
    stack (nesting/parent links); exiting pops and publishes the
    finished record. A span must exit on the thread that entered it —
    the stack is thread-local.
    """

    __slots__ = ("tracer", "name", "args", "span_id", "parent_id",
                 "_t0", "error")

    def __init__(self, tracer: "Tracer", name: str, args: Dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.args = args
        self.span_id: Optional[int] = None
        self.parent_id: Optional[int] = None
        self._t0 = 0.0
        self.error: Optional[str] = None

    def set(self, **attrs) -> None:
        self.args.update(attrs)

    def __enter__(self) -> "Span":
        self.tracer._open(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.perf_counter()
        if exc is not None:
            # The span carries its failure — a postmortem's flight
            # record names not just WHAT was in flight but what died.
            self.error = f"{type(exc).__name__}: {exc}"
        self.tracer._close(self, self._t0, t1)
        return False


class TailSampler:
    """Keep-if policy for high-volume serving spans, evaluated at span
    CLOSE (when the duration and outcome are known — the whole point of
    tail over head sampling):

      - ``flagged`` spans (errors, sheds, breaker-adjacent requests)
        are ALWAYS kept;
      - spans at least ``slow_s`` long are always kept (the tail the
        p99 is made of);
      - everything else is head-sampled at ``head_rate``, implemented
        as a deterministic keep-every-Nth (N = round(1/rate)) so a
        traced bench leg is reproducible — there is no RNG to seed.

    ``head_rate=1.0`` keeps everything (the default when no sampler is
    installed); ``head_rate=0.0`` keeps only flagged/slow spans.
    ``stats()`` reports kept/sampled-out counts per reason — the bound
    on tracing overhead under sustained load is auditable, not assumed.
    """

    __slots__ = ("head_rate", "slow_s", "_modulus", "_lock", "_seq",
                 "_kept", "_dropped")

    def __init__(self, head_rate: float = 0.01,
                 slow_s: Optional[float] = None):
        if not 0.0 <= head_rate <= 1.0:
            raise ValueError(f"head_rate must be in [0, 1], got {head_rate}")
        if slow_s is not None and slow_s <= 0:
            raise ValueError(f"slow_s must be > 0, got {slow_s}")
        self.head_rate = float(head_rate)
        self.slow_s = slow_s
        self._modulus = (
            max(int(round(1.0 / head_rate)), 1) if head_rate > 0 else 0
        )
        self._lock = threading.Lock()
        self._seq = 0
        self._kept: Dict[str, int] = {}
        self._dropped = 0

    def keep(self, dur_s: float, flagged: bool = False
             ) -> "tuple[bool, Optional[str]]":
        """(keep?, reason) — reason is ``flagged``/``slow``/``head``
        (None when sampled out)."""
        with self._lock:
            if flagged:
                reason = "flagged"
            elif self.slow_s is not None and dur_s >= self.slow_s:
                reason = "slow"
            else:
                self._seq += 1
                if self._modulus and (self._seq % self._modulus) == 0:
                    reason = "head"
                else:
                    self._dropped += 1
                    return False, None
            self._kept[reason] = self._kept.get(reason, 0) + 1
            return True, reason

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "kept": dict(self._kept),
                "kept_total": sum(self._kept.values()),
                "sampled_out": self._dropped,
                "head_rate": self.head_rate,
                "slow_s": self.slow_s,
            }


class Tracer:
    """Collects span/event/counter records for one traced run.

    ``run_id`` stamps every record, so one trace file is one causal
    record even when spans come from many threads (fold consumer,
    runtime IO workers, serving worker). Use through
    :func:`tracing` / the module-level hooks, not directly.

    ``serving_sampler``: an optional :class:`TailSampler` applied to the
    retroactive serving request spans (:meth:`add_serving_span`) — a
    long-lived traced serve keeps every slow/error/shed span but only a
    head sample of the healthy fast ones. Fit-path spans are never
    sampled (their volume is bounded by the fold, not the traffic).
    """

    def __init__(self, run_id: Optional[str] = None,
                 max_records: int = 1_000_000,
                 serving_sampler: Optional[TailSampler] = None):
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self.serving_sampler = serving_sampler
        # Map perf_counter to wall-clock microseconds once, so every
        # record's ts_us is an epoch time Perfetto renders as absolute.
        self._epoch_us_at_zero = (
            time.time_ns() // 1_000 - int(time.perf_counter() * 1e6)
        )
        self._lock = threading.Lock()
        # Bounded: a traced LONG-LIVED process (serve under sustained
        # load appends spans per request) must not grow memory without
        # bound until tracing() exit. At capacity the OLDEST records
        # roll off (the recent window is the postmortem-relevant one)
        # and the drop is counted + logged — never silent. A bounded
        # fit never comes near the default.
        self._max_records = int(max_records)
        self._records: "deque[Dict[str, Any]]" = deque(
            maxlen=self._max_records
        )
        self.dropped = 0
        self._ids = itertools.count(1)
        self._open_spans: Dict[int, Dict[str, Any]] = {}
        self._tls = threading.local()

    # -- record plumbing ---------------------------------------------------

    def _us(self, perf_t: float) -> int:
        return self._epoch_us_at_zero + int(perf_t * 1e6)

    def _append_locked(self, rec: Dict[str, Any]) -> None:
        """Append one record; caller holds ``_lock``. Counts (and logs
        once) when the bounded buffer starts rolling off old records."""
        if len(self._records) == self._max_records:
            if self.dropped == 0:
                logger.warning(
                    "trace buffer full (%d records): oldest records now "
                    "roll off — raise Tracer(max_records=...) to keep "
                    "the full run", self._max_records,
                )
            self.dropped += 1
        self._records.append(rec)

    def _stack(self) -> List[int]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def _open(self, sp: Span) -> None:
        st = self._stack()
        with self._lock:
            sp.span_id = next(self._ids)
        sp.parent_id = st[-1] if st else None
        st.append(sp.span_id)
        th = threading.current_thread()
        with self._lock:
            self._open_spans[sp.span_id] = {
                "name": sp.name, "span_id": sp.span_id,
                "parent_id": sp.parent_id, "thread": th.name,
            }

    def _close(self, sp: Span, t0: float, t1: float) -> None:
        st = self._stack()
        # Pop our own id (tolerate a corrupted stack rather than
        # poisoning the traced code path with an assertion).
        if st and st[-1] == sp.span_id:
            st.pop()
        elif sp.span_id in st:
            st.remove(sp.span_id)
        th = threading.current_thread()
        rec = {
            "type": "span",
            "name": sp.name,
            "ts_us": self._us(t0),
            "dur_us": max(int((t1 - t0) * 1e6), 0),
            "tid": th.ident,
            "thread": th.name,
            "span_id": sp.span_id,
            "parent_id": sp.parent_id,
            "run_id": self.run_id,
            "args": sp.args,
        }
        if sp.error is not None:
            rec["error"] = sp.error
        with self._lock:
            self._open_spans.pop(sp.span_id, None)
            self._append_locked(rec)
        from keystone_tpu.obs import flight

        flight.flight_note("span", sp.name, dur_us=rec["dur_us"],
                           thread=th.name, error=sp.error)

    # -- public recording API ----------------------------------------------

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, dict(attrs))

    def add_span(self, name: str, t0: float, t1: float, **attrs) -> int:
        """Record a span retroactively from perf_counter endpoints — the
        serving bridge: the micro-batcher knows a request's
        enqueue/complete times only after the fact, and its rolling
        ``RequestSpan``/``SpanLog`` stats must keep working unchanged.
        Returns the span id (the exemplar reference a histogram bucket
        can carry)."""
        th = threading.current_thread()
        with self._lock:
            sid = next(self._ids)
            self._append_locked({
                "type": "span", "name": name,
                "ts_us": self._us(t0),
                "dur_us": max(int((t1 - t0) * 1e6), 0),
                "tid": th.ident, "thread": th.name,
                "span_id": sid, "parent_id": None,
                "run_id": self.run_id, "args": dict(attrs),
            })
        return sid

    def add_serving_span(self, name: str, t0: float, t1: float,
                         flagged: bool = False, **attrs) -> Optional[int]:
        """The tail-sampled form of :meth:`add_span` for per-request
        serving spans: the keep-if policy runs HERE, at close, when
        duration and outcome are known. ``flagged`` marks spans the
        policy must never drop (errors, sheds, breaker-adjacent
        requests). Returns the span id when kept (→ the
        ``run_id/span_id`` exemplar ref), None when sampled out.
        No sampler installed = keep everything."""
        s = self.serving_sampler
        if s is not None:
            kept, reason = s.keep(t1 - t0, flagged=flagged)
            if not kept:
                return None
            if reason != "head":
                attrs["keep"] = reason
        return self.add_span(name, t0, t1, **attrs)

    def event(self, name: str, **attrs) -> Dict[str, Any]:
        """Record an instant event; returns the record dict (the handle
        :class:`CostOutcomeRef` mutates to back-annotate a decision with
        its measured outcome before the trace file is written)."""
        th = threading.current_thread()
        rec = {
            "type": "event", "name": name,
            "ts_us": self._us(time.perf_counter()),
            "tid": th.ident, "thread": th.name,
            "run_id": self.run_id, "args": dict(attrs),
        }
        with self._lock:
            self._append_locked(rec)
        return rec

    def counter_track(self, name: str, value: float) -> None:
        with self._lock:
            self._append_locked({
                "type": "counter", "name": name,
                "ts_us": self._us(time.perf_counter()),
                "value": float(value),
                "run_id": self.run_id,
            })

    # -- introspection -----------------------------------------------------

    @property
    def events(self) -> List[Dict[str, Any]]:
        """Snapshot of every record so far (finished spans + events +
        counter samples), in completion order."""
        with self._lock:
            return list(self._records)

    def inflight(self) -> List[Dict[str, Any]]:
        """Spans currently OPEN — what the flight recorder names at
        death."""
        with self._lock:
            return list(self._open_spans.values())

    def spans(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        return [
            r for r in self.events
            if r["type"] == "span" and (name is None or r["name"] == name)
        ]


# ---------------------------------------------------------------------------
# Cost-decision events
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class CostDecision:
    """One cost-model selection, as evidence: what was on the table,
    what the model predicted, what feasibility cut, and who won — the
    predicted-vs-measured discipline the replay tests
    (``tests/test_cost_replay.py``) audit against the trace."""

    decision: str                     # e.g. "least_squares_solver"
    winner: str                       # candidate label of the selection
    candidates: Sequence[Dict[str, Any]]  # [{label, cost, feasible, ...}]
    reason: str = "argmin"            # "argmin" | "least_resident_fallback"
    context: Dict[str, Any] = field(default_factory=dict)  # n/d/k/budget...

    def to_args(self) -> Dict[str, Any]:
        return {
            "decision": self.decision,
            "winner": self.winner,
            "reason": self.reason,
            "candidates": [dict(c) for c in self.candidates],
            # Top-level provenance shared by all six decision streams
            # (placement/engine.py): which weight family priced this.
            "weights_family": (self.context.get("weights") or {}).get(
                "family"),
            **{k: v for k, v in self.context.items()},
        }


class CostOutcomeRef:
    """Handle onto one recorded ``cost.decision`` event: whoever runs
    the priced work back-annotates the decision record with the
    MEASURED outcome (the executor stamps the winning fit's wall +
    span id — ``workflow/pipeline.py``), so predicted-vs-measured is
    one record with no join (``obs/calibrate.py``; ``bin/trace``'s
    decision table prints it per row). The mutation happens under the
    tracer lock, before the trace file is written at ``tracing()``
    exit; a stamp after exit mutates a dict nothing reads — harmless."""

    __slots__ = ("_tracer", "_record")

    def __init__(self, tracer: "Tracer", record: Dict[str, Any]):
        self._tracer = tracer
        self._record = record

    def stamp(self, measured_s: float, span_id: Optional[int] = None,
              **extra) -> None:
        if self._tracer is None or self._record is None:
            return  # ref crossed a pickle boundary: nothing to annotate
        outcome = {"measured_s": float(measured_s)}
        if span_id is not None:
            outcome["span_id"] = span_id
        outcome.update(extra)
        with self._tracer._lock:
            self._record.setdefault("args", {})["outcome"] = outcome

    def __getstate__(self):
        # A pending ref rides on the selected estimator, and estimators
        # get cloudpickled (FittedPipeline saves); the live tracer
        # (locks) must not be dragged along — a pickled ref drops its
        # annotation instead.
        return {}

    def __setstate__(self, state) -> None:
        self._tracer = None
        self._record = None


def record_cost_decision(decision: CostDecision) -> Optional[CostOutcomeRef]:
    """Emit a ``cost.decision`` instant event (and a flight-recorder
    note) for one selection. One branch when tracing is disabled.
    Returns a :class:`CostOutcomeRef` for the measured-outcome
    back-annotation, or None when no tracer is active."""
    t = _ACTIVE
    ref: Optional[CostOutcomeRef] = None
    if t is not None:
        ref = CostOutcomeRef(t, t.event("cost.decision", **decision.to_args()))
    from keystone_tpu.obs import flight

    flight.flight_note(
        "decision", decision.decision, winner=decision.winner,
        reason=decision.reason,
    )
    return ref


# ---------------------------------------------------------------------------
# Activation
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def tracing(directory: Optional[str] = None, run_id: Optional[str] = None,
            xla_profile: bool = False,
            serving_sampler: Optional[TailSampler] = None):
    """Activate tracing for the dynamic extent of the block.

    ``directory`` (optional): on exit the trace is written there —
    ``trace.json`` (Chrome-trace/Perfetto, load it at ui.perfetto.dev),
    ``events.jsonl`` (the compact event log ``bin/trace`` reads), and
    ``meta.json``. With no directory the records stay in-memory on the
    yielded :class:`Tracer` (the audit-test form).

    ``xla_profile=True`` additionally wraps the block in the
    jax.profiler trace (``utils.profiling.trace`` — the XLA
    device-timeline deep-dive leg of this plane) writing under
    ``directory/xla``; requires a directory. Imported lazily so this
    module stays jax-free.

    ``serving_sampler``: a :class:`TailSampler` for the per-request
    serving spans — a traced long-lived serve keeps every slow/error/
    shed span, head-samples the rest (docs/observability.md).

    Nested activation raises: one trace is one run's record.
    """
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            raise RuntimeError(
                "tracing is already active; one trace per run "
                "(nest work under the active tracer instead)"
            )
        t = Tracer(run_id=run_id, serving_sampler=serving_sampler)
        _ACTIVE = t
    xla_cm = contextlib.nullcontext()
    if xla_profile:
        if directory is None:
            raise ValueError("xla_profile=True needs a trace directory")
        from keystone_tpu.utils import profiling

        xla_cm = profiling.trace(os.path.join(directory, "xla"))
    try:
        with xla_cm:
            yield t
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = None
        if directory is not None:
            from keystone_tpu.obs.export import write_trace_dir

            write_trace_dir(directory, t)


def tracing_from_env():
    """The env-knob activation: ``KEYSTONE_TRACE=dir`` (what
    ``run.py --trace=dir`` sets) turns the wrapped block into a traced
    run writing to ``dir``; unset — or a tracer already active — yields
    a no-op context. This is what ``run.py`` wraps every pipeline and
    serve invocation in, so tracing any production entry point is one
    flag, zero code.

    ``KEYSTONE_TRACE_SAMPLE=<rate>`` (and optionally
    ``KEYSTONE_TRACE_SLOW_MS=<ms>``) installs a :class:`TailSampler`
    over the serving request spans — the knob a traced long-lived serve
    needs so its trace buffer holds hours of tail, not seconds of
    everything."""
    directory = os.environ.get(TRACE_ENV, "").strip()
    if not directory or _ACTIVE is not None:
        return contextlib.nullcontext()
    sampler = None
    rate = os.environ.get(TRACE_SAMPLE_ENV, "").strip()
    if rate:
        # Validate-at-parse with the error naming the VARIABLE (the
        # utils.faults env-knob discipline): a typo'd rate must not
        # surface as a bare float() error or an internal parameter
        # name the operator never set.
        from keystone_tpu.utils.faults import _env_number

        head_rate = _env_number(TRACE_SAMPLE_ENV, rate, float, 0.0)
        if head_rate > 1.0:
            raise ValueError(
                f"{TRACE_SAMPLE_ENV}={rate!r} must be a keep rate "
                "in [0, 1]"
            )
        slow_ms = os.environ.get(TRACE_SLOW_MS_ENV, "").strip()
        slow_s = None
        if slow_ms:
            slow_s = _env_number(TRACE_SLOW_MS_ENV, slow_ms, float, 0.0)
            slow_s = slow_s / 1e3 if slow_s > 0 else None
        sampler = TailSampler(head_rate=head_rate, slow_s=slow_s)
    return tracing(directory, serving_sampler=sampler)
