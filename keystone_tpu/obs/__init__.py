"""The unified run-trace + metrics plane (ISSUE 9 tentpole).

Until this package, the evidence for *why* the system did anything lived
in disconnected fragments: ``PhaseTimer`` blocks inside solvers, per-fit
``PrefetchStats``, per-server ``stats()`` dicts, and bench-row ``detail``
blobs — none of them correlated after the fact. This package is the one
causally-linked record:

  - :mod:`~keystone_tpu.obs.tracer` — a process-wide :class:`Tracer`
    with nested, thread-safe spans carrying one ``run_id`` and parent
    links, instrumented at the load-bearing seams (``Pipeline.fit``
    phases, optimizer rules, verifier pre-passes, cost-model decisions,
    fold chunk steps, data-plane runtime lane tasks, prefetch waits,
    checkpoint write-behind, serving requests). The whole plane is a
    **no-op guarded by one branch** when tracing is off — hooks cost one
    global read — and cheap when on (the ``observability_overhead``
    bench row holds the enabled cost to <=2% of the disk-streamed fold).
  - :mod:`~keystone_tpu.obs.metrics` — :class:`MetricsRegistry`
    (counters / gauges / histograms with a flat ``snapshot()``), the
    single store behind ``DataPlaneRuntime.stats()``, the serving
    breaker counters, and ``PrefetchStats`` site accounting. Every
    metric name comes from the parsed ``METRIC_*`` catalogue
    (``tools/lint.py``'s ``metric-name`` rule — dashboards cannot
    silently fork names).
  - :mod:`~keystone_tpu.obs.export` — Chrome-trace/Perfetto JSON
    exporter (one track per thread, counter tracks) plus a compact
    JSONL event log; ``tools/trace.py`` / ``bin/trace`` summarize it.
  - :mod:`~keystone_tpu.obs.flight` — the flight recorder: a bounded
    ring of recent events that chaos/fault paths (worker death, breaker
    opens, shard corruption, watchdog evictions) dump alongside the
    exception, so a postmortem names the spans in flight at death.
  - :mod:`~keystone_tpu.obs.calibrate` — the cost-model calibration
    plane (ISSUE 13): joins every ``cost.decision`` with the measured
    seconds of the work it priced, reports prediction error per engine
    and weight family, flags mis-routes with their regret, refits the
    weight families from production traces
    (``KEYSTONE_COST_WEIGHTS=calibrated:<artifact>``), and gates on
    drift (``bin/calibrate``).

Activation (docs/observability.md): ``KEYSTONE_TRACE=dir`` env knob,
``run.py --trace=dir``, or ``with obs.tracing(dir):`` in code. This
package imports no jax — the data-plane runtime (which must stay
jax-free) reports into it from its IO workers.
"""

from keystone_tpu.obs.calibrate import (
    calibration_report,
    drift_gate,
    join_decisions,
    load_calibration_artifact,
    refit,
    write_calibration_artifact,
)
from keystone_tpu.obs.export import (
    load_events,
    to_chrome_trace,
    validate_chrome_trace,
    write_trace_dir,
)
from keystone_tpu.obs.flight import (
    FlightRecorder,
    flight_note,
    flight_snapshot,
    render_flight_record,
)
from keystone_tpu.obs.live import LiveExporter, render_prometheus
from keystone_tpu.obs.metrics import (  # noqa: F401 — METRIC_* re-exported
    BucketedHistogram,
    MetricsRegistry,
)
from keystone_tpu.obs.metrics import __all__ as _metrics_all
from keystone_tpu.obs.metrics import *  # noqa: F401,F403 — the catalogue
from keystone_tpu.obs.slo import (
    STATE_BREACH,
    STATE_OK,
    STATE_WARN,
    SLOObjective,
    SLOTracker,
)
from keystone_tpu.obs.tracer import (
    CostDecision,
    CostOutcomeRef,
    Span,
    TailSampler,
    Tracer,
    active_tracer,
    counter_track,
    enabled,
    event,
    record_cost_decision,
    span,
    tracing,
    tracing_from_env,
)

__all__ = [
    "CostDecision",
    "CostOutcomeRef",
    "FlightRecorder",
    "LiveExporter",
    "MetricsRegistry",
    "STATE_BREACH",
    "STATE_OK",
    "STATE_WARN",
    "SLOObjective",
    "SLOTracker",
    "Span",
    "TailSampler",
    "Tracer",
    "active_tracer",
    "calibration_report",
    "counter_track",
    "drift_gate",
    "enabled",
    "event",
    "flight_note",
    "flight_snapshot",
    "join_decisions",
    "load_calibration_artifact",
    "load_events",
    "record_cost_decision",
    "refit",
    "write_calibration_artifact",
    "render_flight_record",
    "render_prometheus",
    "span",
    "to_chrome_trace",
    "tracing",
    "tracing_from_env",
    "validate_chrome_trace",
    "write_trace_dir",
] + list(_metrics_all)
