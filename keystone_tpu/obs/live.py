"""Live exporter: a periodic publisher for long-lived serving processes.

The PR-9 plane is postmortem-shaped: one causal trace per run, written
at ``tracing()`` exit. A serving process never exits — its signals must
be READABLE WHILE IT RUNS. This module is that door, two formats from
one collection pass:

  - **Prometheus text-format** over a stdlib HTTP endpoint
    (``GET /metrics``; ``/healthz`` liveness; ``/snapshot.json`` the
    raw JSON) — the scrape path.
  - **Atomic JSON snapshot files** (``live_metrics.json`` via
    ``data/durable.py::atomic_write_json`` — a reader sees the old
    snapshot or the complete new one, never a torn write) — for
    scrape-less environments; ``bin/slo`` renders SLO state from them.

One background publisher thread owns the cadence: every ``interval_s``
it evaluates the SLO tracker (idle decay happens even with zero
traffic), calls every collector, renders both formats, and bumps its
own ``exporter.publishes`` counter. The thread discipline is the
repo's standard one: the publisher and the HTTP server thread touch
NOTHING jax (the ``jax-off-thread`` lint rule walks them like any other
worker target), collector errors are counted + logged once — never
thread-fatal — and ``close()`` joins both threads (the ``thread-join``
contract).

Sources are late-bound callables (``server.stats``,
``runtime.stats``, a registry's ``snapshot``), so one exporter composes
the full picture — registry metrics + per-replica serving stats +
runtime lane stats + SLO states — without owning any of them.
"""

from __future__ import annotations

import http.server
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional

from keystone_tpu.obs.metrics import (
    METRIC_EXPORTER_ERRORS,
    METRIC_EXPORTER_PUBLISHES,
    METRIC_EXPORTER_PUBLISH_S,
    MetricsRegistry,
)

__all__ = ["LiveExporter", "render_prometheus"]

logger = logging.getLogger("keystone_tpu.obs.live")

SNAPSHOT_FILE = "live_metrics.json"

_PROM_PREFIX = "keystone"


def _prom_name(*parts: str) -> str:
    out = "_".join(p for p in parts if p)
    return "".join(
        c if (c.isalnum() or c == "_") else "_" for c in out
    ).strip("_")


def _split_registry_key(key: str) -> "tuple[str, Dict[str, str]]":
    """``name{k=v,...}.suffix`` (the registry snapshot key shape) →
    (``name_suffix``, labels)."""
    labels: Dict[str, str] = {}
    if "{" in key and "}" in key:
        head, rest = key.split("{", 1)
        inside, tail = rest.split("}", 1)
        for pair in inside.split(","):
            if "=" in pair:
                k, v = pair.split("=", 1)
                labels[k.strip()] = v.strip()
        key = head + tail
    return key, labels


def render_prometheus(doc: Mapping[str, Any]) -> str:
    """Project one collected snapshot document into Prometheus
    text-format. Numeric leaves only; nested dicts flatten into the
    metric name; registry-shaped keys (``name{k=v}.p99``) keep their
    labels as Prometheus labels. Strings/None are skipped — the JSON
    snapshot is the lossless view, this is the scrapeable one."""
    lines: List[str] = []

    def emit(name: str, labels: Dict[str, str], value: Any) -> None:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return
        if labels:
            lbl = ",".join(
                f'{_prom_name(k)}="{v}"' for k, v in sorted(labels.items())
            )
            lines.append(f"{name}{{{lbl}}} {float(value):g}")
        else:
            lines.append(f"{name} {float(value):g}")

    def walk(prefix: str, obj: Any, labels: Dict[str, str]) -> None:
        if isinstance(obj, Mapping):
            for k, v in obj.items():
                key, extra = _split_registry_key(str(k))
                walk(_prom_name(prefix, key), v, {**labels, **extra})
        elif isinstance(obj, (list, tuple)):
            return  # sequences (ledgers, transition logs) are JSON-only
        else:
            emit(prefix, labels, obj)

    for section, payload in doc.items():
        if section in ("ts", "seq"):
            emit(_prom_name(_PROM_PREFIX, "exporter", section), {}, payload)
            continue
        walk(_prom_name(_PROM_PREFIX, str(section)), payload, {})
    return "\n".join(lines) + "\n"


class _Handler(http.server.BaseHTTPRequestHandler):
    exporter: "LiveExporter"  # set on the server class per exporter

    def do_GET(self):  # noqa: N802 - stdlib handler name
        ex = self.server.exporter  # type: ignore[attr-defined]
        if self.path.startswith("/healthz"):
            body, ctype = b"ok\n", "text/plain"
        elif self.path.startswith("/snapshot.json"):
            body = json.dumps(ex.last_snapshot()).encode()
            ctype = "application/json"
        elif self.path == "/" or self.path.startswith("/metrics"):
            body = ex.last_prometheus().encode()
            ctype = "text/plain; version=0.0.4"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # noqa: D102 - silence per-scrape log
        pass


class LiveExporter:
    """Periodic publisher over late-bound stat sources (module
    docstring).

    ``sources``: ``{section: callable-or-registry}`` — each tick, every
    callable runs and its dict lands under ``section`` in the snapshot;
    a :class:`MetricsRegistry` contributes its ``snapshot()``.
    ``slo``: an :class:`~keystone_tpu.obs.slo.SLOTracker` — evaluated
    each tick (state decay under zero traffic) and rendered under the
    ``slo`` section. ``snapshot_dir``: atomic JSON snapshots land there.
    ``port``: serve HTTP on it (0 = ephemeral — read ``.port`` after
    construction); None disables the endpoint.
    """

    def __init__(
        self,
        sources: Optional[Mapping[str, Any]] = None,
        slo=None,
        snapshot_dir: Optional[str] = None,
        port: Optional[int] = None,
        interval_s: float = 1.0,
        host: str = "127.0.0.1",
    ):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self._sources: Dict[str, Callable[[], Any]] = {}
        for section, src in dict(sources or {}).items():
            if isinstance(src, MetricsRegistry):
                self._sources[section] = src.snapshot
            elif callable(src):
                self._sources[section] = src
            else:
                raise TypeError(
                    f"source {section!r} must be a callable or a "
                    f"MetricsRegistry, got {type(src).__name__}"
                )
        self._slo = slo
        self.snapshot_dir = snapshot_dir
        self.interval_s = float(interval_s)
        # The exporter's own accounting rides the same registry plane it
        # publishes, so "is the exporter alive" is itself scrapeable.
        self.metrics = MetricsRegistry()
        self._publishes = self.metrics.counter(METRIC_EXPORTER_PUBLISHES)
        self._errors = self.metrics.counter(METRIC_EXPORTER_ERRORS)
        self._publish_s = self.metrics.histogram(
            METRIC_EXPORTER_PUBLISH_S, maxlen=256
        )
        self._sources.setdefault("exporter", self.metrics.snapshot)

        self._lock = threading.Lock()
        self._doc: Dict[str, Any] = {}
        self._text = "# no publish yet\n"
        self._seq = 0
        self._error_logged = False
        self._stop = threading.Event()
        self._closed = False

        self._http = None
        self._http_thread = None
        self.port: Optional[int] = None
        if port is not None:
            self._http = http.server.ThreadingHTTPServer(
                (host, int(port)), _Handler
            )
            self._http.daemon_threads = True
            self._http.exporter = self  # type: ignore[attr-defined]
            self.port = self._http.server_address[1]
            self._http_thread = threading.Thread(
                target=self._http.serve_forever,
                name="keystone-obs-exporter-http", daemon=True,
            )
            self._http_thread.start()

        if snapshot_dir:
            os.makedirs(snapshot_dir, exist_ok=True)
        self._thread = threading.Thread(
            target=self._loop, name="keystone-obs-exporter", daemon=True
        )
        self._thread.start()

    # -- collection (publisher thread + publish_now callers) ---------------

    def _collect(self) -> Dict[str, Any]:
        doc: Dict[str, Any] = {"ts": time.time(), "seq": self._seq}
        if self._slo is not None:
            try:
                self._slo.evaluate()
                doc["slo"] = self._slo.verdict()
            except Exception as e:  # noqa: BLE001 — never thread-fatal
                self._note_error("slo", e)
        for section, fn in self._sources.items():
            try:
                doc[section] = fn()
            except Exception as e:  # noqa: BLE001 — never thread-fatal
                self._note_error(section, e)
        return doc

    def _note_error(self, section: str, exc: Exception) -> None:
        self._errors.add(1)
        if not self._error_logged:
            self._error_logged = True
            logger.warning(
                "live exporter: collector %r failed (%r) — counted on "
                "exporter.errors, further failures are silent",
                section, exc,
            )

    def publish_now(self) -> Dict[str, Any]:
        """One synchronous publish pass (collect → render → write);
        returns the snapshot document. The loop calls this every tick;
        tests and close() call it directly."""
        t0 = time.perf_counter()
        doc = self._collect()
        text = render_prometheus(doc)
        with self._lock:
            self._seq += 1
            self._doc = doc
            self._text = text
        if self.snapshot_dir:
            # Imported lazily: data/durable.py imports the obs package
            # at module scope, and a top-level import here would close
            # that cycle during package init.
            from keystone_tpu.data.durable import atomic_write_json

            try:
                atomic_write_json(
                    os.path.join(self.snapshot_dir, SNAPSHOT_FILE), doc
                )
            except OSError as e:
                self._note_error("snapshot_write", e)
        self._publishes.add(1)
        self._publish_s.observe(time.perf_counter() - t0)
        return doc

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.publish_now()
            except Exception as e:  # noqa: BLE001 — keep publishing
                self._note_error("publish", e)

    # -- reading -----------------------------------------------------------

    def last_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._doc)

    def last_prometheus(self) -> str:
        with self._lock:
            return self._text

    # -- shutdown ----------------------------------------------------------

    def close(self, timeout: float = 5.0) -> None:
        """Stop publishing: one final publish (the snapshot file ends
        current, not one interval stale), then both threads join.
        Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._thread.join(timeout=timeout)
        try:
            self.publish_now()
        except Exception:  # noqa: BLE001 — best-effort final write
            pass
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
            self._http_thread.join(timeout=timeout)

    def __enter__(self) -> "LiveExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
