"""Cost-model calibration plane (ISSUE 13 tentpole).

Every routing decision in the system — gram vs gather vs BCD engine,
resident vs compressed vs streamed tier — flows through
``ops/learning/cost.py``, whose TPU weight constants were fitted once,
offline. Meanwhile the obs plane records the *actual* cost of every
fold chunk, prefetch read, lane task and served batch, plus a
structured ``cost.decision`` audit event for every prediction. This
module is the feedback path between the two:

  - :func:`join_decisions` joins each ``cost.decision`` event with the
    measured seconds of the work it priced: the back-annotated
    ``outcome`` the executor stamps onto the decision record
    (``workflow/pipeline.py`` — span id + wall of the winning fit), or,
    for older traces, the span-window join over the work spans that
    followed it (``estimator.fit`` / ``fold.segment`` / the IO spans),
    matched by ``run_id`` and timestamps.
  - :func:`calibration_report` turns joined outcomes into the
    per-engine, per-weight-family prediction-error report: signed and
    absolute log-error summaries (log error = ln(measured/predicted)),
    the distributions on :class:`~keystone_tpu.obs.metrics.
    BucketedHistogram` (the ``calibration.error`` metric family), and
    the MIS-ROUTE table — decisions where a measured-faster feasible
    candidate lost, with the regret in seconds. Evidence discipline:
    a mis-route claim cites either a measured outcome of the losing
    engine at the SAME geometry elsewhere in the trace set, or the
    losing engine's calibrated estimate (its prediction corrected by
    that engine's own measured error ratio) — never the raw prediction
    the decision itself was (possibly wrongly) made from.
  - :func:`fit_weights` / :func:`refit` re-estimate the weight
    families from the joined outcomes — THE weight-fitting
    implementation (``scripts/fit_cost_weights.py`` drives it; the
    round-6 ad-hoc scrape is gone): (cpu, mem) by median-relative-error
    grid search under the ``max(cpu·flops, mem·bytes)`` form the
    selector evaluates, ``sparse_gather_overhead`` refit from the
    gather-engine rows given (cpu, mem), network PINNED from the base
    family (single-chip traces cannot observe it).
  - :func:`write_calibration_artifact` /
    :func:`load_calibration_artifact` persist the refit as a
    versioned, provenance-stamped JSON artifact (source run_ids, span
    counts, residuals, fit date — ``durable.atomic_write_json``) which
    ``cost.py`` loads via ``KEYSTONE_COST_WEIGHTS=calibrated:<path>``
    beside the built-in ``tpu`` / ``ec2`` families.
  - :func:`drift_gate` closes the loop: when fresh traces disagree
    with the active weights beyond the stated threshold (median
    absolute log error, default :data:`DEFAULT_DRIFT_THRESHOLD` — a 2x
    median miss), it publishes ``calibration.drift`` and emits a
    WARN-level flight note + log line, so a mis-predicting cost model
    is a DETECTED regression in ``bin/trace`` / ``bin/calibrate``
    output and the bench audit block, not a silent mis-route.

No jax at module level (the obs package contract); estimator
reconstruction for re-prediction imports the learning modules lazily.
"""

from __future__ import annotations

import logging
import math
import statistics
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from keystone_tpu.obs.metrics import (
    METRIC_CALIBRATION_DECISIONS,
    METRIC_CALIBRATION_DRIFT,
    METRIC_CALIBRATION_ERROR,
    METRIC_CALIBRATION_MISROUTES,
    METRIC_CALIBRATION_REGRET_S,
    MetricsRegistry,
)

logger = logging.getLogger("keystone_tpu.obs.calibrate")

__all__ = [
    "ARTIFACT_FORMAT",
    "ARTIFACT_VERSION",
    "DEFAULT_DRIFT_THRESHOLD",
    "DecisionOutcome",
    "calibration_report",
    "drift_gate",
    "estimator_for_label",
    "family_weights",
    "fit_weights",
    "join_decisions",
    "load_calibration_artifact",
    "predict_seconds",
    "refit",
    "write_calibration_artifact",
]

ARTIFACT_FORMAT = "keystone-cost-calibration"
ARTIFACT_VERSION = 1

# Drift threshold in ln units: a median |ln(measured/predicted)| past
# this is a detected regression (0.7 ≈ a 2x median miss — the bound the
# replay magnitude test holds the shipped TPU constants to on-chip).
DEFAULT_DRIFT_THRESHOLD = 0.7

# Decision kinds the calibrator prices. ``least_squares_solver`` is the
# production selector (cost.py); ``calibration_sweep`` is the
# fit-weights measurement harness (scripts/fit_cost_weights.py) which
# records one single-candidate decision per timed (engine, geometry)
# point so the refit path is IDENTICAL for sweeps and production runs;
# ``mesh_layout`` is the mesh-shape selector (cost.choose_mesh_layout)
# whose runners stamp the measured multichip fit wall onto the record.
# ``placement.zoo_page_in`` is the zoo's priced page fault
# (placement/engine.py price_page_in), stamped with the measured
# restore wall so refit can recover the paging overhead.
CALIBRATED_DECISIONS = (
    "least_squares_solver", "calibration_sweep", "mesh_layout",
    "placement.zoo_page_in",
)

# Work spans a decision's measured seconds may be joined from, by
# priority: the executor's fit bracket first (it IS the priced work),
# then the fold chunks (the dominant term of every streamed fit).
_FIT_SPAN = "estimator.fit"
_FOLD_SPAN = "fold.segment"
# Span families counted per decision window for provenance (the
# span_counts block the artifact records).
WORK_SPAN_NAMES = (
    _FIT_SPAN, _FOLD_SPAN, "prefetch.read", "runtime.task",
    "serving.batch",
)


@dataclass
class DecisionOutcome:
    """One ``cost.decision`` event joined with the measured seconds of
    the work it priced."""

    run_id: str
    decision: str                      # kind, e.g. "least_squares_solver"
    winner: str                        # candidate label of the selection
    reason: str
    predicted_s: Optional[float]       # the winner's RECORDED prediction
    measured_s: Optional[float]        # joined measurement (None: no join)
    span_id: Optional[int] = None      # the measured span, when stamped
    joined_via: Optional[str] = None   # "outcome" | "spans" | None
    # Measurement convention of the stamped wall (the bench VALID_TIMING
    # vocabulary): "min_of_N_warm" (the sweep harness — warm, dispatch
    # subtracted), "single_run_cold" (the executor's one production
    # fit — INCLUDES XLA compile), "spans" (window-joined), or None.
    timing: Optional[str] = None
    context: Dict[str, Any] = field(default_factory=dict)
    weights: Dict[str, Any] = field(default_factory=dict)  # as recorded
    candidates: List[Dict[str, Any]] = field(default_factory=list)
    span_counts: Dict[str, int] = field(default_factory=dict)

    def log_error(self, predicted: Optional[float] = None
                  ) -> Optional[float]:
        """ln(measured / predicted): positive = the model was optimistic
        (work ran slower than priced). None when either side is missing
        or non-positive (an infeasible winner has no prediction)."""
        p = self.predicted_s if predicted is None else predicted
        if p is None or self.measured_s is None:
            return None
        if p <= 0 or self.measured_s <= 0:
            return None
        return math.log(self.measured_s / p)


def _geometry(ctx: Dict[str, Any]) -> Tuple[int, int, int, float, int]:
    return (
        int(ctx.get("n", 0)), int(ctx.get("d", 0)), int(ctx.get("k", 1)),
        float(ctx.get("sparsity", 1.0)), int(ctx.get("machines", 1)),
    )


def _geometry_key(label: str, ctx: Dict[str, Any]) -> Tuple:
    n, d, k, s, m = _geometry(ctx)
    return label, n, d, k, round(s, 8), m


def join_decisions(
    records: Iterable[Dict[str, Any]],
    kinds: Sequence[str] = CALIBRATED_DECISIONS,
) -> List[DecisionOutcome]:
    """Join every ``cost.decision`` event with its measured outcome.

    Preferred evidence is the back-annotated ``outcome`` block the
    executor stamped onto the decision record (span id + wall of the
    winning fit). Decisions without one fall back to the span-window
    join: within the same ``run_id``, the work spans opening between
    this decision's timestamp and the next decision's (or the end of
    the trace) are the work it priced — measured seconds is the
    ``estimator.fit`` bracket when present, else the sum of the
    ``fold.segment`` chunks. Span counts per family are kept either way
    (the provenance block of the calibration artifact).
    """
    records = list(records)
    decisions = [
        r for r in records
        if r.get("type") == "event"
        and r.get("name") in ("cost.decision", "placement.decision")
        and (r.get("args") or {}).get("decision") in kinds
    ]
    spans_by_run: Dict[str, List[Dict[str, Any]]] = {}
    for r in records:
        if r.get("type") == "span" and r.get("name") in WORK_SPAN_NAMES:
            spans_by_run.setdefault(r.get("run_id", ""), []).append(r)
    # Decision windows are per run, in timestamp order.
    by_run: Dict[str, List[Dict[str, Any]]] = {}
    for ev in decisions:
        by_run.setdefault(ev.get("run_id", ""), []).append(ev)
    out: List[DecisionOutcome] = []
    for run_id, evs in by_run.items():
        evs.sort(key=lambda e: e.get("ts_us", 0))
        spans = sorted(
            spans_by_run.get(run_id, []), key=lambda s: s.get("ts_us", 0)
        )
        for i, ev in enumerate(evs):
            args = ev.get("args") or {}
            t0 = ev.get("ts_us", 0)
            t1 = evs[i + 1].get("ts_us") if i + 1 < len(evs) else None
            window = [
                s for s in spans
                if s.get("ts_us", 0) >= t0
                and (t1 is None or s.get("ts_us", 0) < t1)
            ]
            counts: Dict[str, int] = {}
            for s in window:
                counts[s["name"]] = counts.get(s["name"], 0) + 1
            cands = [dict(c) for c in args.get("candidates", [])]
            winner = args.get("winner", "?")
            predicted = next(
                (c.get("cost_s") for c in cands
                 if c.get("label") == winner), None,
            )
            outcome = args.get("outcome") or {}
            measured = outcome.get("measured_s")
            span_id = outcome.get("span_id")
            timing = outcome.get("timing")
            via: Optional[str] = "outcome" if measured is not None else None
            if measured is None:
                timing = "spans"
                fits = [s for s in window if s["name"] == _FIT_SPAN]
                folds = [s for s in window if s["name"] == _FOLD_SPAN]
                if fits:
                    measured = fits[0].get("dur_us", 0) / 1e6
                    span_id = fits[0].get("span_id")
                    via = "spans"
                elif folds:
                    measured = sum(
                        s.get("dur_us", 0) for s in folds
                    ) / 1e6
                    via = "spans"
            ctx = {
                k: v for k, v in args.items()
                if k not in ("decision", "winner", "reason", "candidates",
                             "outcome", "weights", "weights_family")
            }
            weights = dict(args.get("weights") or {})
            if "family" not in weights and args.get("weights_family"):
                weights["family"] = args["weights_family"]
            out.append(DecisionOutcome(
                run_id=run_id,
                decision=args.get("decision", "?"),
                winner=winner,
                reason=args.get("reason", "?"),
                predicted_s=predicted,
                measured_s=(
                    float(measured) if measured is not None else None
                ),
                span_id=span_id,
                joined_via=via,
                timing=(timing if measured is not None else None),
                context=ctx,
                weights=weights,
                candidates=cands,
                span_counts=counts,
            ))
    out.sort(key=lambda o: (o.run_id, o.decision))
    return out


# ---------------------------------------------------------------------------
# Weight families + candidate reconstruction
# ---------------------------------------------------------------------------


def family_weights(spec: Optional[str] = None) -> Dict[str, Any]:
    """Resolve a weight-family spec to its constants.

    ``spec``: None / ``"active"`` (whatever ``KEYSTONE_COST_WEIGHTS``
    selects right now), ``"tpu"``, ``"ec2"``, or
    ``"calibrated:<path>"`` (a refit artifact). Returns
    ``{"name", "cpu", "mem", "network", "sparse_gather_overhead",
    "srht_sketch_overhead", "countsketch_overhead",
    "zoo_page_overhead"}``.
    """
    from keystone_tpu.ops.learning import cost as cost_mod

    raw = (spec or "active").strip()
    low = raw.lower()
    if low == "active":
        cpu, mem, net = cost_mod.active_weights()
        return {
            "name": cost_mod.weights_family_name(),
            "cpu": cpu, "mem": mem, "network": net,
            "sparse_gather_overhead": cost_mod.sparse_gather_overhead(),
            "srht_sketch_overhead": cost_mod.srht_sketch_overhead(),
            "countsketch_overhead": cost_mod.countsketch_overhead(),
            "zoo_page_overhead": cost_mod.zoo_page_overhead(),
        }
    if low == "tpu":
        return {
            "name": "tpu",
            "cpu": cost_mod.TPU_CPU_WEIGHT,
            "mem": cost_mod.TPU_MEM_WEIGHT,
            "network": cost_mod.TPU_NETWORK_WEIGHT,
            "sparse_gather_overhead": cost_mod.TPU_SPARSE_GATHER_OVERHEAD,
            "srht_sketch_overhead": cost_mod.TPU_SRHT_SKETCH_OVERHEAD,
            "countsketch_overhead": cost_mod.TPU_COUNTSKETCH_OVERHEAD,
            "zoo_page_overhead": cost_mod.TPU_ZOO_PAGE_OVERHEAD,
        }
    if low == "ec2":
        return {
            "name": "ec2",
            "cpu": cost_mod.EC2_CPU_WEIGHT,
            "mem": cost_mod.EC2_MEM_WEIGHT,
            "network": cost_mod.EC2_NETWORK_WEIGHT,
            "sparse_gather_overhead": cost_mod.EC2_SPARSE_GATHER_OVERHEAD,
            "srht_sketch_overhead": cost_mod.EC2_SRHT_SKETCH_OVERHEAD,
            "countsketch_overhead": cost_mod.EC2_COUNTSKETCH_OVERHEAD,
            "zoo_page_overhead": cost_mod.EC2_ZOO_PAGE_OVERHEAD,
        }
    if low.startswith(cost_mod.CALIBRATED_PREFIX):
        art = load_calibration_artifact(
            raw[len(cost_mod.CALIBRATED_PREFIX):]
        )
        w = dict(art["weights"])
        w["name"] = "calibrated"
        return w
    raise ValueError(
        f"unknown weight-family spec {spec!r}: expected 'active', 'tpu', "
        f"'ec2' or 'calibrated:<path>'"
    )


def estimator_for_label(label: str):
    """Reconstruct the cost-model candidate a ``candidate_label`` names,
    at the constructor defaults ``LeastSquaresEstimator`` builds its
    candidate set with — the analytic ``cost()`` extractors are what the
    calibrator needs, not a fit-capable configuration. Returns None for
    labels this registry does not know (the caller counts skips; an
    unknown engine must not silently drop out of a report)."""
    name, _, qual = label.partition("[")
    quals = [q for q in qual.rstrip("]").split(",") if q] if qual else []
    if name == "DenseLBFGSwithL2":
        from keystone_tpu.ops.learning.lbfgs import DenseLBFGSwithL2

        return DenseLBFGSwithL2(lam=1e-4, num_iterations=20)
    if name == "SparseLBFGSwithL2":
        from keystone_tpu.ops.learning.lbfgs import SparseLBFGSwithL2

        solver = "gram" if "gram" in quals else "gather"
        compress = "int16_bf16" if "int16_bf16" in quals else None
        return SparseLBFGSwithL2(
            lam=1e-4, num_iterations=20, solver=solver, compress=compress,
        )
    if name == "BlockLeastSquaresEstimator":
        from keystone_tpu.ops.learning.block import (
            BlockLeastSquaresEstimator,
        )

        return BlockLeastSquaresEstimator(1000, 3, lam=1e-4)
    if name == "LinearMapEstimator":
        from keystone_tpu.ops.learning.linear import LinearMapEstimator

        return LinearMapEstimator(1e-4)
    if name == "SketchedLeastSquaresEstimator":
        from keystone_tpu.ops.learning.linear import (
            SketchedLeastSquaresEstimator,
        )

        return SketchedLeastSquaresEstimator(lam=1e-4)
    if name == "StreamingLeastSquaresChoice":
        from keystone_tpu.ops.learning.streaming_ls import (
            StreamingLeastSquaresChoice,
        )

        return StreamingLeastSquaresChoice(
            num_iter=3, lam=1e-4, block_size_hint=1024
        )
    if name == "SketchedLeastSquares":
        from keystone_tpu.ops.learning.sketch import SketchedLeastSquares

        return SketchedLeastSquares(lam=1e-4)
    if name == "IterativeHessianSketch":
        from keystone_tpu.ops.learning.sketch import IterativeHessianSketch

        compress = "int16_bf16" if "int16_bf16" in quals else None
        return IterativeHessianSketch(lam=1e-4, compress=compress)
    return None


def _cost_under(est, ctx: Dict[str, Any], cpu: float, mem: float,
                net: float, sparse_overhead: Optional[float],
                srht_overhead: Optional[float] = None,
                cs_overhead: Optional[float] = None) -> float:
    n, d, k, s, m = _geometry(ctx)
    from keystone_tpu.ops.learning.lbfgs import SparseLBFGSwithL2
    from keystone_tpu.ops.learning.sketch import (
        IterativeHessianSketch, SketchedLeastSquares,
    )

    if isinstance(est, SparseLBFGSwithL2):
        return est.cost(
            n, d, k, s, m, cpu, mem, net,
            sparse_overhead=sparse_overhead,
        )
    if isinstance(est, SketchedLeastSquares):
        return est.cost(
            n, d, k, s, m, cpu, mem, net,
            sketch_overhead=srht_overhead, gather_overhead=sparse_overhead,
        )
    if isinstance(est, IterativeHessianSketch):
        return est.cost(
            n, d, k, s, m, cpu, mem, net,
            sketch_overhead=cs_overhead, gather_overhead=sparse_overhead,
        )
    return est.cost(n, d, k, s, m, cpu, mem, net)


def predict_seconds(label: str, ctx: Dict[str, Any],
                    weights: Dict[str, Any]) -> Optional[float]:
    """Price one candidate at one recorded geometry under an arbitrary
    weight family — how the report re-evaluates a trace under weights
    it was NOT recorded with (drift A/B, refit validation). None when
    the label cannot be reconstructed."""
    est = estimator_for_label(label)
    if est is None:
        return None
    return _cost_under(
        est, ctx, float(weights["cpu"]), float(weights["mem"]),
        float(weights["network"]), weights.get("sparse_gather_overhead"),
        srht_overhead=weights.get("srht_sketch_overhead"),
        cs_overhead=weights.get("countsketch_overhead"),
    )


# ---------------------------------------------------------------------------
# The prediction-error report + mis-route table
# ---------------------------------------------------------------------------


def _median(vals: List[float]) -> Optional[float]:
    return statistics.median(vals) if vals else None


def calibration_report(
    records_or_outcomes,
    weights: Optional[Dict[str, Any]] = None,
    registry: Optional[MetricsRegistry] = None,
    kinds: Sequence[str] = CALIBRATED_DECISIONS,
) -> Dict[str, Any]:
    """The per-engine, per-weight-family prediction-error report.

    ``weights``: a :func:`family_weights` dict to RE-predict every
    candidate under (drift A/B against a family the trace was not
    recorded with); None evaluates the predictions as recorded.
    ``registry``: when given, the ``calibration.*`` metric family is
    published into it — the per-engine ``|log error|`` distributions on
    bucketed histograms plus decision/mis-route counters.
    """
    if records_or_outcomes and isinstance(records_or_outcomes[0], dict):
        outcomes = join_decisions(records_or_outcomes, kinds=kinds)
    else:
        outcomes = list(records_or_outcomes)

    fam_name = (weights or {}).get("name")
    if fam_name is None:
        # As-recorded evaluation: name the family the trace itself
        # carries (all-equal), else "mixed".
        seen = {
            tuple(sorted(o.weights.items()))
            for o in outcomes if o.weights
        }
        fam_name = "as-recorded" if len(seen) <= 1 else "mixed"

    per_engine: Dict[str, Dict[str, Any]] = {}
    errors: List[float] = []
    rows: List[Tuple[DecisionOutcome, float, float]] = []
    skipped_unknown = 0
    measured_by_geometry: Dict[Tuple, List[float]] = {}
    for o in outcomes:
        if o.measured_s is None:
            continue
        measured_by_geometry.setdefault(
            _geometry_key(o.winner, o.context), []
        ).append(o.measured_s)
        if weights is not None:
            predicted = predict_seconds(o.winner, o.context, weights)
            if predicted is None:
                # Not a solver-estimator label (e.g. a mesh_layout
                # decision): it cannot be RE-priced under an arbitrary
                # family, but a joined row with its recorded prediction
                # still belongs in the drift verdict — score it
                # as-recorded, count the skip only when even that is
                # missing. (fit_weights independently excludes these
                # rows from the regression.)
                predicted = o.predicted_s
                if predicted is None:
                    skipped_unknown += 1
                    continue
        else:
            predicted = o.predicted_s
        err = o.log_error(predicted)
        if err is None:
            continue
        rows.append((o, predicted, err))
        errors.append(err)

    for o, predicted, err in rows:
        eng = per_engine.setdefault(o.winner, {
            "count": 0, "_pred": [], "_meas": [], "_err": [],
        })
        eng["count"] += 1
        eng["_pred"].append(predicted)
        eng["_meas"].append(o.measured_s)
        eng["_err"].append(err)

    ratios: Dict[str, float] = {}
    for label, eng in per_engine.items():
        errs = eng.pop("_err")
        med = _median(errs)  # never None: the bucket was fed >= 1 row
        abs_errs = sorted(abs(e) for e in errs)
        eng["median_predicted_s"] = _median(eng.pop("_pred"))
        eng["median_measured_s"] = _median(eng.pop("_meas"))
        eng["median_log_error"] = med
        eng["median_abs_log_error"] = _median(abs_errs)
        eng["max_abs_log_error"] = abs_errs[-1]
        ratios[label] = math.exp(med)

    misroutes = _misroute_table(
        outcomes, weights, ratios, measured_by_geometry
    )
    med_abs = _median([abs(e) for e in errors])
    report = {
        "weights_family": fam_name,
        "weights": {
            k: v for k, v in (weights or {}).items() if k != "name"
        } or None,
        "num_decisions": len(outcomes),
        "num_measured": sum(
            1 for o in outcomes if o.measured_s is not None
        ),
        "num_scored": len(errors),
        # Measurement-convention mix of the scored rows: cold
        # single-run stamps INCLUDE XLA compile (the executor fits each
        # estimator once), so a report dominated by "single_run_cold"
        # rows scores model + compile, not the device-time claim the
        # constants make — the refit discipline prefers warm rows and
        # the drift verdict carries this mix so an operator can tell.
        "timings": _count_timings(rows),
        "skipped_unknown_engine": skipped_unknown,
        "run_ids": sorted({o.run_id for o in outcomes}),
        "span_counts": _sum_span_counts(outcomes),
        "per_engine": per_engine,
        "median_abs_log_error": med_abs,
        "median_log_error": _median(errors),
        "misroutes": misroutes,
        "total_regret_s": round(
            sum(m["regret_s"] for m in misroutes), 6
        ),
    }
    if registry is not None:
        _publish_metrics(report, rows, registry)
    return report


def _count_timings(rows) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for o, _predicted, _err in rows:
        key = o.timing or "unknown"
        counts[key] = counts.get(key, 0) + 1
    return counts


def _sum_span_counts(outcomes: List[DecisionOutcome]) -> Dict[str, int]:
    total: Dict[str, int] = {}
    for o in outcomes:
        for name, c in o.span_counts.items():
            total[name] = total.get(name, 0) + c
    return total


def _misroute_table(
    outcomes: List[DecisionOutcome],
    weights: Optional[Dict[str, Any]],
    ratios: Dict[str, float],
    measured_by_geometry: Dict[Tuple, List[float]],
) -> List[Dict[str, Any]]:
    """Decisions where a measured-faster feasible candidate lost.

    Evidence per claim, strongest first: a measured outcome of the
    losing engine at the SAME geometry elsewhere in the trace set
    (``evidence="measured"``), else the loser's prediction corrected by
    its engine's own measured error ratio (``evidence="calibrated"``).
    Candidates whose engine has no measured outcomes anywhere make no
    claim at all — a mis-route table must not be built from the very
    predictions under audit."""
    table: List[Dict[str, Any]] = []
    for idx, o in enumerate(outcomes):
        if o.measured_s is None:
            continue
        for c in o.candidates:
            label = c.get("label")
            if label == o.winner or not c.get("feasible"):
                continue
            key = _geometry_key(label, o.context)
            same_geom = measured_by_geometry.get(key)
            if same_geom:
                estimate = _median(same_geom)
                evidence = "measured"
            else:
                if weights is not None:
                    predicted = predict_seconds(label, o.context, weights)
                else:
                    predicted = c.get("cost_s")
                if predicted is None or label not in ratios:
                    continue
                estimate = predicted * ratios[label]
                evidence = "calibrated"
            if estimate is not None and estimate < o.measured_s:
                table.append({
                    "decision_index": idx,
                    "decision": o.decision,
                    "run_id": o.run_id,
                    "winner": o.winner,
                    "winner_measured_s": round(o.measured_s, 6),
                    "faster_candidate": label,
                    "faster_estimate_s": round(estimate, 6),
                    "evidence": evidence,
                    "regret_s": round(o.measured_s - estimate, 6),
                })
    table.sort(key=lambda m: m["regret_s"], reverse=True)
    return table


def _publish_metrics(report, rows, registry: MetricsRegistry) -> None:
    registry.counter(METRIC_CALIBRATION_DECISIONS).add(
        report["num_decisions"]
    )
    registry.counter(METRIC_CALIBRATION_MISROUTES).add(
        len(report["misroutes"])
    )
    registry.counter(METRIC_CALIBRATION_REGRET_S).add(
        report["total_regret_s"]
    )
    for o, _predicted, err in rows:
        registry.bucketed_histogram(
            METRIC_CALIBRATION_ERROR, engine=o.winner,
        ).observe(max(abs(err), 1e-9))


# ---------------------------------------------------------------------------
# Trace-driven refit — THE weight-fitting implementation
# ---------------------------------------------------------------------------


def fit_weights(
    outcomes: List[DecisionOutcome],
    base: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Re-estimate a weight family from measured outcomes.

    (cpu, mem) fit on the SEQUENTIAL-engine rows (dense LBFGS / block /
    exact / streaming — everything whose model has no random-access
    multiplier) under the ``max(cpu·flops, mem·bytes)`` form the
    selector evaluates: closed-form per-row medians seed a log-grid
    search minimizing the median relative error (the round-6 procedure,
    moved here from ``scripts/fit_cost_weights.py`` so there is exactly
    one implementation). ``sparse_gather_overhead`` refit from the
    gather-engine rows GIVEN (cpu, mem). The network weight is PINNED
    from ``base`` — single-chip traces cannot observe it. Gram-engine
    rows are evaluation-only (their model mixes the overhead factor
    with a capacity term; the report scores them, the fit does not
    regress on them). The sketched-engine overheads
    (``srht_sketch_overhead`` / ``countsketch_overhead``) refit from
    their engines' rows GIVEN the fitted (cpu, mem, gather overhead):
    each engine's model is AFFINE in its own overhead, so the per-row
    estimate is ``(measured − cost@0) / (cost@1 − cost@0)`` and the
    family takes the median. Row families without measurements keep
    ``base``'s constants, and the result says so (``fitted`` lists what
    was actually re-estimated — no silent caps)."""
    from keystone_tpu.ops.learning.lbfgs import SparseLBFGSwithL2
    from keystone_tpu.ops.learning.sketch import (
        IterativeHessianSketch, SketchedLeastSquares,
    )

    base = dict(base or family_weights("active"))
    dense_rows: List[Tuple[float, float, float]] = []  # f_cpu, f_mem, s
    gather_rows: List[Tuple[Any, DecisionOutcome]] = []
    srht_rows: List[Tuple[Any, DecisionOutcome]] = []
    cs_rows: List[Tuple[Any, DecisionOutcome]] = []
    zoo_rows: List[DecisionOutcome] = []
    for o in outcomes:
        if o.measured_s is None or o.measured_s <= 0:
            continue
        if o.decision == "placement.zoo_page_in":
            # Zoo page faults carry a tenant id as the winner label, not
            # an estimator name — intercepted here, BEFORE the registry
            # lookup treats them as unknown engines.
            zoo_rows.append(o)
            continue
        est = estimator_for_label(o.winner)
        if est is None:
            continue
        if isinstance(est, SparseLBFGSwithL2):
            if est.solver == "gather":
                gather_rows.append((est, o))
            continue
        if isinstance(est, SketchedLeastSquares):
            srht_rows.append((est, o))
            continue
        if isinstance(est, IterativeHessianSketch):
            cs_rows.append((est, o))
            continue
        f_cpu = _cost_under(est, o.context, 1.0, 0.0, 0.0, None)
        f_mem = _cost_under(est, o.context, 0.0, 1.0, 0.0, None)
        dense_rows.append((f_cpu, f_mem, o.measured_s))

    fitted: List[str] = []
    cpu_w, mem_w = float(base["cpu"]), float(base["mem"])
    if dense_rows:
        cpu_w, mem_w = _fit_max_form(dense_rows, anchor=(cpu_w, mem_w))
        fitted += ["cpu", "mem"]

    overhead = base.get("sparse_gather_overhead")
    if gather_rows:
        samples = []
        for est, o in gather_rows:
            unit = _cost_under(est, o.context, cpu_w, mem_w, 0.0, 1.0)
            if unit > 0:
                samples.append(o.measured_s / unit)
        if samples:
            overhead = _median(samples)
            fitted.append("sparse_gather_overhead")

    def _affine_overhead(rows, kwarg):
        # cost(ov) = c0 + ov·(c1 − c0) given (cpu, mem, gather), so each
        # measured row pins one overhead sample; non-positive samples
        # (the measured wall under the overhead-free floor — a
        # mis-joined or noise row) are dropped, not clamped into the
        # median.
        samples = []
        for est, o in rows:
            c0 = _cost_under(
                est, o.context, cpu_w, mem_w, 0.0, overhead,
                **{kwarg: 0.0},
            )
            c1 = _cost_under(
                est, o.context, cpu_w, mem_w, 0.0, overhead,
                **{kwarg: 1.0},
            )
            if c1 - c0 > 0:
                sample = (o.measured_s - c0) / (c1 - c0)
                if sample > 0:
                    samples.append(sample)
        return _median(samples)

    srht_ov = base.get("srht_sketch_overhead")
    if srht_rows:
        fit = _affine_overhead(srht_rows, "srht_overhead")
        if fit is not None:
            srht_ov = fit
            fitted.append("srht_sketch_overhead")
    cs_ov = base.get("countsketch_overhead")
    if cs_rows:
        fit = _affine_overhead(cs_rows, "cs_overhead")
        if fit is not None:
            cs_ov = fit
            fitted.append("countsketch_overhead")

    zoo_ov = base.get("zoo_page_overhead")
    if zoo_rows:
        # price_page_in is mem_w · overhead · resident_bytes, so each
        # measured page fault pins one overhead sample GIVEN the fitted
        # mem weight; the family takes the median.
        samples = []
        for o in zoo_rows:
            rb = next(
                (c.get("resident_bytes") for c in o.candidates
                 if c.get("label") == o.winner), None,
            )
            if rb is not None and float(rb) > 0 and mem_w > 0:
                sample = o.measured_s / (mem_w * float(rb))
                if sample > 0:
                    samples.append(sample)
        fit = _median(samples)
        if fit is not None:
            zoo_ov = fit
            fitted.append("zoo_page_overhead")

    return {
        "cpu": cpu_w,
        "mem": mem_w,
        "network": float(base["network"]),  # pinned, not fit
        "sparse_gather_overhead": (
            float(overhead) if overhead is not None else None
        ),
        "srht_sketch_overhead": (
            float(srht_ov) if srht_ov is not None else None
        ),
        "countsketch_overhead": (
            float(cs_ov) if cs_ov is not None else None
        ),
        "zoo_page_overhead": (
            float(zoo_ov) if zoo_ov is not None else None
        ),
        "fitted": fitted,
        "num_rows": {
            "sequential": len(dense_rows), "gather": len(gather_rows),
            "srht": len(srht_rows), "countsketch": len(cs_rows),
            "zoo_page": len(zoo_rows),
        },
    }


def _fit_max_form(
    rows: List[Tuple[float, float, float]],
    anchor: Optional[Tuple[float, float]] = None,
) -> Tuple[float, float]:
    """Median-relative-error fit of ``max(cpu·f_cpu, mem·f_mem)`` to the
    measured seconds: per-row closed forms seed a log grid (each row
    pins cpu OR mem exactly when its term dominates).

    ``anchor``: the base family's (cpu, mem). Under the max() form a
    small trace can leave one weight UNDER-determined (every row
    cpu-bound ⇒ any small-enough mem fits equally well) — among grid
    points within 25% of the best median error, the one closest to the
    anchor in log space wins, so a refit deviates from the shipped
    constants only as far as the measured evidence actually demands
    (the round-6 fit resolved the same degeneracy by hand, choosing mem
    jointly so measured pairwise orderings reproduce)."""

    def rel_err(cpu: float, mem: float) -> float:
        errs = [
            abs(max(cpu * fc, mem * fm) - s) / max(s, 1e-9)
            for fc, fm, s in rows
        ]
        return float(statistics.median(errs))

    cpu0 = statistics.median(
        [s / max(fc, 1e-9) for fc, _fm, s in rows]
    )
    mem0 = statistics.median(
        [s / max(fm, 1e-9) for _fc, fm, s in rows]
    )
    grid = [10.0 ** (e / 4.0) for e in range(-8, 9)]
    candidates = [(cpu0 * s0, mem0 * s1) for s0 in grid for s1 in grid]
    errs = [rel_err(*w) for w in candidates]
    best = min(errs)
    near = [
        w for w, e in zip(candidates, errs)
        if e <= best * 1.25 + 1e-12
    ]
    if anchor is None or anchor[0] <= 0 or anchor[1] <= 0:
        return near[0]

    def log_dist(w: Tuple[float, float]) -> float:
        return abs(math.log(w[0] / anchor[0])) + abs(
            math.log(w[1] / anchor[1])
        )

    return min(near, key=log_dist)


def refit(
    records: Iterable[Dict[str, Any]],
    out_path: Optional[str] = None,
    base: Optional[Dict[str, Any]] = None,
    kinds: Sequence[str] = CALIBRATED_DECISIONS,
) -> Dict[str, Any]:
    """Trace-driven refit: join → fit → (optionally) persist.

    Returns ``{"weights", "before", "after", "artifact_path",
    "outcomes"}`` where ``before``/``after`` are
    :func:`calibration_report` dicts under the base family and the
    refit weights respectively — the evidence a refit must present
    (median |log error| after ≤ before, on the very rows it was fit
    from) — and ``outcomes`` is the joined row list (so callers never
    re-join the trace set)."""
    records = list(records)
    outcomes = join_decisions(records, kinds=kinds)
    base = dict(base or family_weights("active"))
    weights = fit_weights(outcomes, base=base)
    # (Callers print orderings etc. from the returned outcomes — the
    # join over a large trace set runs once, here.)
    eval_weights = {
        "name": "refit",
        "cpu": weights["cpu"], "mem": weights["mem"],
        "network": weights["network"],
        "sparse_gather_overhead": weights["sparse_gather_overhead"],
        "srht_sketch_overhead": weights["srht_sketch_overhead"],
        "countsketch_overhead": weights["countsketch_overhead"],
        "zoo_page_overhead": weights["zoo_page_overhead"],
    }
    before = calibration_report(outcomes, weights=base, kinds=kinds)
    after = calibration_report(outcomes, weights=eval_weights, kinds=kinds)
    artifact_path = None
    if out_path is not None:
        provenance = {
            "base_family": base.get("name", "?"),
            "run_ids": after["run_ids"],
            "num_decisions": after["num_decisions"],
            "num_measured": after["num_measured"],
            "span_counts": after["span_counts"],
            "residuals": {
                "median_abs_log_error": after["median_abs_log_error"],
                "median_abs_log_error_before": (
                    before["median_abs_log_error"]
                ),
                "per_engine": {
                    label: eng["median_abs_log_error"]
                    for label, eng in after["per_engine"].items()
                },
            },
            "fitted": weights["fitted"],
            "num_rows": weights["num_rows"],
        }
        write_calibration_artifact(out_path, weights, provenance)
        artifact_path = out_path
    return {
        "weights": weights,
        "before": before,
        "after": after,
        "artifact_path": artifact_path,
        "outcomes": outcomes,
    }


# ---------------------------------------------------------------------------
# The calibration artifact
# ---------------------------------------------------------------------------


def write_calibration_artifact(
    path: str, weights: Dict[str, Any], provenance: Dict[str, Any],
) -> None:
    """Persist a refit as the versioned, provenance-stamped artifact
    ``KEYSTONE_COST_WEIGHTS=calibrated:<path>`` loads. Atomic
    (``durable.atomic_write_json``): a reader never sees a torn file."""
    from keystone_tpu.data.durable import atomic_write_json

    now = time.time()
    doc = {
        "format": ARTIFACT_FORMAT,
        "version": ARTIFACT_VERSION,
        "weights": {
            "cpu": float(weights["cpu"]),
            "mem": float(weights["mem"]),
            "network": float(weights["network"]),
            "sparse_gather_overhead": (
                float(weights["sparse_gather_overhead"])
                if weights.get("sparse_gather_overhead") is not None
                else None
            ),
            "srht_sketch_overhead": (
                float(weights["srht_sketch_overhead"])
                if weights.get("srht_sketch_overhead") is not None
                else None
            ),
            "countsketch_overhead": (
                float(weights["countsketch_overhead"])
                if weights.get("countsketch_overhead") is not None
                else None
            ),
            "zoo_page_overhead": (
                float(weights["zoo_page_overhead"])
                if weights.get("zoo_page_overhead") is not None
                else None
            ),
        },
        "provenance": {
            **provenance,
            "fit_unix_s": now,
            "fit_date": time.strftime(
                "%Y-%m-%d %H:%M:%S UTC", time.gmtime(now)
            ),
        },
    }
    atomic_write_json(path, doc)


def load_calibration_artifact(path: str) -> Dict[str, Any]:
    """Read + validate a calibration artifact. Raises ValueError naming
    the path on any malformed content — a weight family that cannot be
    parsed must fail loudly at selection time, not mis-price silently."""
    import json

    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise ValueError(
            f"calibration artifact {path!r} is unreadable: {e}"
        ) from e
    except json.JSONDecodeError as e:
        raise ValueError(
            f"calibration artifact {path!r} is not valid JSON: {e}"
        ) from e
    if not isinstance(doc, dict) or doc.get("format") != ARTIFACT_FORMAT:
        raise ValueError(
            f"calibration artifact {path!r}: format is not "
            f"{ARTIFACT_FORMAT!r}"
        )
    if doc.get("version") != ARTIFACT_VERSION:
        raise ValueError(
            f"calibration artifact {path!r}: version "
            f"{doc.get('version')!r} != supported {ARTIFACT_VERSION}"
        )
    weights = doc.get("weights")
    if not isinstance(weights, dict):
        raise ValueError(
            f"calibration artifact {path!r}: missing weights block"
        )
    for key in ("cpu", "mem", "network"):
        v = weights.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or not v > 0:
            raise ValueError(
                f"calibration artifact {path!r}: weights.{key} must be "
                f"a positive number, got {v!r}"
            )
    for opt_key in (
        "sparse_gather_overhead", "srht_sketch_overhead",
        "countsketch_overhead", "zoo_page_overhead",
    ):
        so = weights.get(opt_key)
        if so is not None and (
            not isinstance(so, (int, float)) or isinstance(so, bool)
            or not so > 0
        ):
            raise ValueError(
                f"calibration artifact {path!r}: "
                f"weights.{opt_key} must be a positive number "
                f"or null, got {so!r}"
            )
    return doc


# ---------------------------------------------------------------------------
# The drift gate
# ---------------------------------------------------------------------------


def drift_gate(
    report: Dict[str, Any],
    threshold: float = DEFAULT_DRIFT_THRESHOLD,
    registry: Optional[MetricsRegistry] = None,
) -> Dict[str, Any]:
    """State the drift verdict for one calibration report: median
    absolute log error past ``threshold`` is a DETECTED regression —
    published as ``calibration.drift``, flight-noted at WARN, and
    logged, so a mis-predicting cost model fails loudly everywhere the
    obs plane is read instead of silently mis-routing fits."""
    med = report.get("median_abs_log_error")
    worst_engine, worst = None, None
    for label, eng in (report.get("per_engine") or {}).items():
        e = eng.get("median_abs_log_error")
        if e is not None and (worst is None or e > worst):
            worst_engine, worst = label, e
    drifted = med is not None and med > threshold
    verdict = {
        "drifted": drifted,
        "median_abs_log_error": med,
        "threshold": threshold,
        "weights_family": report.get("weights_family"),
        "num_decisions": report.get("num_decisions"),
        "num_scored": report.get("num_scored"),
        "timings": report.get("timings"),
        "worst_engine": worst_engine,
        "worst_engine_median_abs_log_error": worst,
    }
    if registry is not None:
        registry.gauge(METRIC_CALIBRATION_DRIFT).set(1.0 if drifted else 0.0)
    if drifted:
        from keystone_tpu.obs import flight

        flight.flight_note(
            "warn", "calibration.drift",
            weights_family=report.get("weights_family"),
            median_abs_log_error=round(med, 4),
            threshold=threshold,
            worst_engine=worst_engine,
        )
        logger.warning(
            "cost-model drift detected: median |log error| %.3f > %.3f "
            "under the %r weights over %d measured decisions (worst "
            "engine: %s at %.3f) — refit with bin/calibrate --refit",
            med, threshold, report.get("weights_family"),
            report.get("num_scored", 0), worst_engine,
            worst if worst is not None else float("nan"),
        )
    return verdict
