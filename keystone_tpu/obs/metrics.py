"""Named, registered metrics: counters / gauges / histograms.

Before this module, operational counters were ad-hoc attributes: the
data-plane runtime's per-lane ``tasks/errors/busy_s``, the serving
breaker's ``completed/rejected/failed/breaker_opens``, the per-fit
``PrefetchStats`` site accounting. Each grew its own locking, its own
snapshot shape, and its own (unchecked) names. A :class:`MetricsRegistry`
replaces that plumbing: one get-or-create API, one flat ``snapshot()``
shape every ``stats()``/bench reader consumes, and every name drawn from
the ``METRIC_*`` catalogue below.

The catalogue is the contract: ``tools/lint.py``'s ``metric-name`` rule
PARSES (never imports) this module for ``METRIC_*`` assignments — the
same discipline as the fault-site registry — and rejects any
register/lookup site whose dotted name is not in it, so dashboards can't
silently fork names. Labels (``site=``, ``lane=``) carry the
per-instance dimension; snapshot keys render as ``name{k=v}``.

No jax, no numpy: the registry is imported by ``data/runtime.py``
(which must stay jax-free) and updated from IO worker threads.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "BucketedHistogram",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "METRIC_AUTOSCALE_BROWNOUT_LEVEL",
    "METRIC_AUTOSCALE_DECISIONS",
    "METRIC_AUTOSCALE_REPLICAS",
    "METRIC_AUTOSCALE_SCALE_DOWNS",
    "METRIC_AUTOSCALE_SCALE_UPS",
    "METRIC_CALIBRATION_DECISIONS",
    "METRIC_CALIBRATION_DRIFT",
    "METRIC_CALIBRATION_ERROR",
    "METRIC_CALIBRATION_MISROUTES",
    "METRIC_CALIBRATION_REGRET_S",
    "METRIC_EXPORTER_ERRORS",
    "METRIC_EXPORTER_PUBLISHES",
    "METRIC_EXPORTER_PUBLISH_S",
    "METRIC_LIFECYCLE_CANARY_PROMOTIONS",
    "METRIC_LIFECYCLE_PUBLISHED",
    "METRIC_LIFECYCLE_REJECTED",
    "METRIC_LIFECYCLE_ROLLBACKS",
    "METRIC_LIFECYCLE_STALENESS_S",
    "METRIC_PLACEMENT_DECISIONS",
    "METRIC_PLACEMENT_INFEASIBLE",
    "METRIC_PREFETCH_BACKOFF_S",
    "METRIC_PREFETCH_LOAD_S",
    "METRIC_PREFETCH_RETRIES",
    "METRIC_PREFETCH_SEGMENTS",
    "METRIC_PREFETCH_WAIT_S",
    "METRIC_RUNTIME_LANE_BUSY_S",
    "METRIC_RUNTIME_LANE_ERRORS",
    "METRIC_RUNTIME_LANE_QUEUED",
    "METRIC_RUNTIME_LANE_TASKS",
    "METRIC_SERVING_BREAKER_OPENS",
    "METRIC_SERVING_COMPLETED",
    "METRIC_SERVING_DEGRADED_REJECTED",
    "METRIC_SERVING_FAILED",
    "METRIC_SERVING_LATENCY_S",
    "METRIC_SERVING_QUEUE_DEPTH",
    "METRIC_SERVING_REJECTED",
    "METRIC_SITE_BUSY_S",
    "METRIC_SITE_WAIT_S",
    "METRIC_SLO_BUDGET_SPENT",
    "METRIC_SLO_BURN_FAST",
    "METRIC_SLO_BURN_SLOW",
    "METRIC_SLO_STATE",
    "METRIC_SLO_TRANSITIONS",
    "METRIC_TENANT_COLDSTART_FAILFAST",
    "METRIC_TENANT_COMPLETED",
    "METRIC_TENANT_FAILED",
    "METRIC_TENANT_OFFERED",
    "METRIC_TENANT_REJECTED",
    "METRIC_TRAINER_RESUMES",
    "METRIC_TRAINER_SEGMENTS_FIT",
    "METRIC_ZOO_DECISIONS",
    "METRIC_ZOO_PAGE_INS",
    "METRIC_ZOO_PAGE_OUTS",
    "METRIC_ZOO_QUARANTINED",
    "METRIC_ZOO_RESIDENTS",
]

# ---------------------------------------------------------------------------
# Metric catalogue — the ONLY names a register/lookup site may use
# (parsed, not imported, by tools/lint.py's metric-name rule; the docs
# table in docs/observability.md mirrors this list).
# ---------------------------------------------------------------------------

# Data-plane runtime, per lane (label: site=<lane>) — DataPlaneRuntime.stats()
METRIC_RUNTIME_LANE_TASKS = "runtime.lane.tasks"
METRIC_RUNTIME_LANE_ERRORS = "runtime.lane.errors"
METRIC_RUNTIME_LANE_BUSY_S = "runtime.lane.busy_s"
METRIC_RUNTIME_LANE_QUEUED = "runtime.lane.queued"

# Per-fit ingestion (PrefetchStats) — overlap + retry accounting
METRIC_PREFETCH_LOAD_S = "prefetch.load_s"
METRIC_PREFETCH_WAIT_S = "prefetch.wait_s"
METRIC_PREFETCH_SEGMENTS = "prefetch.segments"
METRIC_PREFETCH_RETRIES = "prefetch.retries"
METRIC_PREFETCH_BACKOFF_S = "prefetch.backoff_s"
# Per-site overlap accounting (label: site=read/verify/checkpoint/compute)
METRIC_SITE_BUSY_S = "overlap.site_busy_s"
METRIC_SITE_WAIT_S = "overlap.site_wait_s"

# Serving (MicroBatchServer) — the breaker/throughput counters stats() reads
METRIC_SERVING_COMPLETED = "serving.completed"
METRIC_SERVING_REJECTED = "serving.rejected"
METRIC_SERVING_FAILED = "serving.failed"
METRIC_SERVING_BREAKER_OPENS = "serving.breaker_opens"
METRIC_SERVING_DEGRADED_REJECTED = "serving.degraded_rejected"
METRIC_SERVING_LATENCY_S = "serving.latency_s"
METRIC_SERVING_QUEUE_DEPTH = "serving.queue_depth"

# Live SLO plane (obs/slo.py), per declared objective (label: objective=)
METRIC_SLO_BURN_FAST = "slo.burn_rate_fast"
METRIC_SLO_BURN_SLOW = "slo.burn_rate_slow"
METRIC_SLO_BUDGET_SPENT = "slo.budget_spent_fraction"
METRIC_SLO_STATE = "slo.state"  # 0=OK 1=WARN 2=BREACH
METRIC_SLO_TRANSITIONS = "slo.transitions"

# Live exporter (obs/live.py) — the publisher thread's own accounting
METRIC_EXPORTER_PUBLISHES = "exporter.publishes"
METRIC_EXPORTER_ERRORS = "exporter.errors"
METRIC_EXPORTER_PUBLISH_S = "exporter.publish_s"

# SLO-closed-loop autoscaler (serving/autoscale.py) — the control
# plane's own accounting, published into the serving plane's registry so
# the live exporter renders scale state beside the SLO verdict.
METRIC_AUTOSCALE_REPLICAS = "autoscale.replicas"
METRIC_AUTOSCALE_SCALE_UPS = "autoscale.scale_ups"
METRIC_AUTOSCALE_SCALE_DOWNS = "autoscale.scale_downs"
METRIC_AUTOSCALE_BROWNOUT_LEVEL = "autoscale.brownout_level"
METRIC_AUTOSCALE_DECISIONS = "autoscale.decisions"

# Cost-model calibration plane (obs/calibrate.py) — predicted-vs-measured
# audit of the cost.decision trail. calibration.error is the |log error|
# distribution per engine (label: engine=<candidate label>);
# calibration.drift is the gate verdict (1 = fresh traces disagree with
# the active weights past the stated threshold).
METRIC_CALIBRATION_ERROR = "calibration.error"
METRIC_CALIBRATION_DECISIONS = "calibration.decisions"
METRIC_CALIBRATION_MISROUTES = "calibration.misroutes"
METRIC_CALIBRATION_REGRET_S = "calibration.regret_s"
METRIC_CALIBRATION_DRIFT = "calibration.drift"

# Multi-tenant model zoo (serving/zoo.py) — residency/paging counters
# plus the per-tenant front-door accounting (label: tenant=<id>), so the
# live exporter renders every tenant's offered/completed/rejected/failed
# beside the plane counters and the per-tenant SLO verdicts.
METRIC_ZOO_RESIDENTS = "zoo.residents"
METRIC_ZOO_PAGE_INS = "zoo.page_ins"
METRIC_ZOO_PAGE_OUTS = "zoo.page_outs"
METRIC_ZOO_QUARANTINED = "zoo.quarantined"
METRIC_ZOO_DECISIONS = "zoo.decisions"
METRIC_TENANT_OFFERED = "tenant.offered"
METRIC_TENANT_COMPLETED = "tenant.completed"
METRIC_TENANT_REJECTED = "tenant.rejected"
METRIC_TENANT_FAILED = "tenant.failed"
METRIC_TENANT_COLDSTART_FAILFAST = "tenant.coldstart_failfast"

# Continuous-learning control plane (serving/lifecycle.py +
# learning/continuous.py) — the publication path's own accounting:
# candidates published/rejected at the validation gate, canary
# promotions vs rollbacks (canary OR post-promotion SLO-attributed),
# and the model-staleness clock (newest covered shard arrival -> first
# response served under the covering fingerprint). The trainer counters
# ride beside them: segments folded and checkpoint resumes.
METRIC_LIFECYCLE_PUBLISHED = "lifecycle.published"
METRIC_LIFECYCLE_REJECTED = "lifecycle.rejected"
METRIC_LIFECYCLE_ROLLBACKS = "lifecycle.rollbacks"
METRIC_LIFECYCLE_CANARY_PROMOTIONS = "lifecycle.canary_promotions"
METRIC_LIFECYCLE_STALENESS_S = "lifecycle.staleness_s"
METRIC_TRAINER_SEGMENTS_FIT = "trainer.segments_fit"
METRIC_TRAINER_RESUMES = "trainer.resumes"

# Global placement engine (placement/engine.py) — the unified
# placement.decision stream's own accounting: decisions audited, and
# candidates priced infeasible (the capacity cuts the planner replays).
METRIC_PLACEMENT_DECISIONS = "placement.decisions"
METRIC_PLACEMENT_INFEASIBLE = "placement.infeasible_candidates"


class Counter:
    """Monotonic-by-convention accumulator (float). ``set_()`` exists
    only for the attribute-compatibility shims that migrated legacy
    ``stats.load_s += dt`` call sites onto the registry."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def add(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def set_(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (queue depth, liveness)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


def _interp_percentile(vals: "List[float]", q: float) -> Optional[float]:
    """Linear-interpolation percentile over SORTED values (numpy's
    default convention): None when empty, the sample itself when
    single. The one implementation behind ``Histogram.percentile`` and
    ``Histogram.stats_snapshot`` — the empty/single-sample contract is
    pinned by tests and must not fork."""
    if not vals:
        return None
    if len(vals) == 1:
        return vals[0]
    pos = (q / 100.0) * (len(vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(vals) - 1)
    frac = pos - lo
    return vals[lo] * (1.0 - frac) + vals[hi] * frac


class Histogram:
    """Bounded-reservoir distribution: keeps the most recent ``maxlen``
    observations (the rolling-window convention the serving stats
    already used) plus lifetime count/sum. Percentiles are exact over
    the retained window, computed by linear interpolation (the same
    convention as numpy's default, so ``latency_percentiles`` agrees)."""

    __slots__ = ("_lock", "_window", "count", "total")

    def __init__(self, maxlen: int = 4096):
        self._lock = threading.Lock()
        self._window: "deque[float]" = deque(maxlen=maxlen)
        self.count = 0
        self.total = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._window.append(v)
            self.count += 1
            self.total += v

    def snapshot_values(self) -> list:
        with self._lock:
            return list(self._window)

    def percentile(self, q: float) -> Optional[float]:
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile q must be in [0, 100], got {q}")
        with self._lock:
            vals = sorted(self._window)
        return _interp_percentile(vals, q)

    def stats_snapshot(self) -> Dict[str, Any]:
        """count/sum/p50/p99 read under ONE lock acquisition, so a
        snapshot raced against concurrent ``observe()`` calls is a
        consistent point-in-time view (count can never read AHEAD of the
        window the percentiles were computed from)."""
        with self._lock:
            count, total = self.count, self.total
            vals = sorted(self._window)
        return {"count": count, "sum": total,
                "p50": _interp_percentile(vals, 50.0),
                "p99": _interp_percentile(vals, 99.0)}


class BucketedHistogram:
    """Mergeable log-bucketed distribution: fixed exponential buckets,
    O(1) memory for unbounded runs, EXACT cross-replica merge.

    This is the latency-metric store for long-lived serving processes.
    The 4096-sample ring (:class:`Histogram`) keeps only the most recent
    window, which silently biases a multi-hour serve's p99 toward the
    last few seconds; log buckets keep the WHOLE run at bounded memory
    and merge exactly across replicas (bucket counts add — there is no
    resampling step to lose tail mass in). The price is resolution: a
    percentile is reported as its bucket's geometric midpoint, so it is
    exact only to within one bucket width (``growth`` per bucket,
    default 8%/bucket — tests pin the merged-vs-concatenated bound).

    Contracts shared with the sample-ring class (PR-9 conventions,
    pinned in tests): an EMPTY histogram's ``percentile`` is ``None``
    (never a fabricated zero); a SINGLE sample IS every percentile
    (returned exactly — the observed min/max clamp makes the one-sample
    bucket estimate collapse to the sample itself); an out-of-range
    ``q`` raises ValueError naming the bound.

    ``observe(value, exemplar=...)`` optionally attaches a trace
    reference to the value's bucket (latest wins, one per bucket —
    bounded): the bucket→trace-id exemplar map that links a p99 breach
    to the offending request traces (:meth:`exemplars_at_or_above`).
    """

    # Shared bucket geometry: every instance merges with every other.
    _LO = 1e-6       # values at/below 1µs share the underflow bucket
    _GROWTH = 1.08   # ~8% relative resolution per bucket

    __slots__ = ("_lock", "_buckets", "_exemplars", "count", "total",
                 "_min", "_max")

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: Dict[int, int] = {}
        self._exemplars: Dict[int, str] = {}
        self.count = 0
        self.total = 0.0
        self._min = math.inf
        self._max = -math.inf

    @classmethod
    def bucket_index(cls, value: float) -> int:
        if value <= cls._LO:
            return 0
        return 1 + int(math.log(value / cls._LO) / math.log(cls._GROWTH))

    @classmethod
    def bucket_bounds(cls, index: int) -> Tuple[float, float]:
        """(lo, hi] value bounds of one bucket (lo == 0 for the
        underflow bucket)."""
        if index <= 0:
            return 0.0, cls._LO
        return (cls._LO * cls._GROWTH ** (index - 1),
                cls._LO * cls._GROWTH ** index)

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        v = float(value)
        # NaN would silently poison count/sum/percentiles; +/-inf would
        # escape bucket_index as a raw OverflowError — one named error.
        if not math.isfinite(v):
            raise ValueError(
                f"BucketedHistogram.observe: value must be finite, "
                f"got {v}"
            )
        idx = self.bucket_index(v)
        with self._lock:
            self._buckets[idx] = self._buckets.get(idx, 0) + 1
            self.count += 1
            self.total += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if exemplar is not None:
                self._exemplars[idx] = exemplar

    def merge(self, other: "BucketedHistogram") -> "BucketedHistogram":
        """Fold ``other``'s buckets into self (exact: counts add). The
        cross-replica aggregation step — merged percentiles equal the
        percentile of the concatenated observation stream to within one
        bucket width (property-tested)."""
        with other._lock:
            buckets = dict(other._buckets)
            exemplars = dict(other._exemplars)
            count, total = other.count, other.total
            mn, mx = other._min, other._max
        with self._lock:
            for idx, c in buckets.items():
                self._buckets[idx] = self._buckets.get(idx, 0) + c
            self._exemplars.update(exemplars)
            self.count += count
            self.total += total
            self._min = min(self._min, mn)
            self._max = max(self._max, mx)
        return self

    def state_dict(self) -> Dict[str, Any]:
        """JSON-safe serialized form for CROSS-PROCESS merge (ISSUE 20:
        the fleet router merges per-plane histograms scraped over
        ``/snapshot.json``). Bucket keys are stringified indices; the
        shared class-level geometry means :meth:`merge_state` on the
        receiving side is exactly :meth:`merge` — counts add, no
        resampling, the PR-10 exact-merge property preserved over the
        wire. Exemplars ride along (latest-wins on merge)."""
        with self._lock:
            return {
                "geometry": {"lo": self._LO, "growth": self._GROWTH},
                "count": self.count,
                "sum": self.total,
                "min": self._min if self.count else None,
                "max": self._max if self.count else None,
                "buckets": {str(i): c for i, c in self._buckets.items()},
                "exemplars": dict(self._exemplars),
            }

    @classmethod
    def from_state(cls, state: Dict[str, Any]) -> "BucketedHistogram":
        """Rebuild from :meth:`state_dict` (e.g. after a JSON round
        trip). Raises ValueError on a geometry mismatch — merging
        histograms bucketed under different geometries would silently
        misplace every count."""
        h = cls()
        h.merge_state(state)
        return h

    def merge_state(self, state: Dict[str, Any]) -> "BucketedHistogram":
        """Fold a serialized peer into self — the cross-process form of
        :meth:`merge`, with the same exactness (counts add)."""
        geo = state.get("geometry") or {}
        if (float(geo.get("lo", self._LO)) != self._LO
                or float(geo.get("growth", self._GROWTH)) != self._GROWTH):
            raise ValueError(
                f"histogram geometry mismatch: peer {geo} vs local "
                f"lo={self._LO} growth={self._GROWTH}"
            )
        buckets = {int(i): int(c)
                   for i, c in (state.get("buckets") or {}).items()}
        count = int(state.get("count", 0))
        total = float(state.get("sum", 0.0))
        mn = state.get("min")
        mx = state.get("max")
        with self._lock:
            for idx, c in buckets.items():
                self._buckets[idx] = self._buckets.get(idx, 0) + c
            for idx, ex in (state.get("exemplars") or {}).items():
                self._exemplars[int(idx)] = str(ex)
            self.count += count
            self.total += total
            if mn is not None:
                self._min = min(self._min, float(mn))
            if mx is not None:
                self._max = max(self._max, float(mx))
        return self

    def _percentile_locked(self, q: float) -> Optional[float]:
        if not self.count:
            return None
        # Nearest-rank walk over cumulative bucket counts; the estimate
        # is the bucket's geometric midpoint clamped into the OBSERVED
        # [min, max] — which makes a single-sample histogram return the
        # sample exactly (min == max == the value).
        rank = max(int(math.ceil((q / 100.0) * self.count)), 1)
        seen = 0
        for idx in sorted(self._buckets):
            seen += self._buckets[idx]
            if seen >= rank:
                lo, hi = self.bucket_bounds(idx)
                mid = math.sqrt(lo * hi) if lo > 0.0 else hi / 2.0
                return min(max(mid, self._min), self._max)
        return self._max  # pragma: no cover - rank <= count always hits

    def percentile(self, q: float) -> Optional[float]:
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile q must be in [0, 100], got {q}")
        with self._lock:
            return self._percentile_locked(q)

    def stats_snapshot(self) -> Dict[str, Any]:
        """count/sum/p50/p99 under ONE lock acquisition (the same
        consistent-view contract as :meth:`Histogram.stats_snapshot`)."""
        with self._lock:
            return {
                "count": self.count, "sum": self.total,
                "p50": self._percentile_locked(50.0),
                "p99": self._percentile_locked(99.0),
            }

    def exemplars_at_or_above(self, q: float, limit: int = 4) -> List[str]:
        """Trace references attached to the buckets at or above the
        q-th percentile's bucket (worst first) — the p99→trace link a
        breach investigation starts from."""
        with self._lock:
            p = self._percentile_locked(q)
            if p is None or not self._exemplars:
                return []
            cut = self.bucket_index(p)
            return [
                self._exemplars[idx]
                for idx in sorted(self._exemplars, reverse=True)
                if idx >= cut
            ][:limit]


class MetricsRegistry:
    """Get-or-create registry of named metrics.

    ``counter(name, **labels)`` / ``gauge(...)`` / ``histogram(...)``
    are both registration and lookup — the same call shape at the
    definition site and every reader, so there is nothing to keep in
    sync. A name re-used at a different type raises (one name, one
    meaning). ``snapshot()`` flattens everything to one dict —
    ``name`` or ``name{k=v,...}`` keys — which is the ONE shape
    ``stats()`` methods and bench rows read.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Any] = {}

    @staticmethod
    def _key(name: str, labels: Dict[str, Any]):
        return name, tuple(sorted((k, str(v)) for k, v in labels.items()))

    def _get_or_create(self, cls, name: str, labels, **kw):
        key = self._key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(**kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r}{labels or ''} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get_or_create(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get_or_create(Gauge, name, labels)

    def histogram(self, name: str, maxlen: int = 4096, **labels) -> Histogram:
        return self._get_or_create(Histogram, name, labels, maxlen=maxlen)

    def bucketed_histogram(self, name: str, **labels) -> BucketedHistogram:
        """The mergeable log-bucketed form — the right store for
        LONG-LIVED latency metrics (serving): O(1) memory over unbounded
        runs, exact cross-replica merge. Short-lived fit phases keep the
        exact sample-ring :meth:`histogram`."""
        return self._get_or_create(BucketedHistogram, name, labels)

    def labels_of(self, name: str) -> list:
        """The label-sets registered under ``name`` (e.g. every lane a
        runtime has created), as dicts."""
        with self._lock:
            return [
                dict(lbls) for (n, lbls) in self._metrics if n == name
            ]

    def values_by_label(self, name: str, label: str) -> Dict[str, float]:
        """``{label_value: metric_value}`` for one labeled counter/gauge
        family — the shape the per-site overlap dicts are built from."""
        out: Dict[str, float] = {}
        with self._lock:
            items = list(self._metrics.items())
        for (n, lbls), m in items:
            d = dict(lbls)
            if n == name and label in d and hasattr(m, "value"):
                out[d[label]] = m.value
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Flat dict of every registered metric. Counters/gauges map to
        their value; histograms (ring and bucketed) expand to ``.count``
        / ``.sum`` / ``.p50`` / ``.p99`` sub-keys. Safe against
        concurrent ``observe()``/``add()`` from worker threads: each
        histogram's four sub-keys come from ONE ``stats_snapshot()``
        lock acquisition, so the expanded values are mutually consistent
        and counters read monotonically across successive snapshots."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, Any] = {}
        for (name, lbls), m in items:
            key = name
            if lbls:
                key += "{" + ",".join(f"{k}={v}" for k, v in lbls) + "}"
            if isinstance(m, (Histogram, BucketedHistogram)):
                st = m.stats_snapshot()
                out[key + ".count"] = st["count"]
                out[key + ".sum"] = st["sum"]
                out[key + ".p50"] = st["p50"]
                out[key + ".p99"] = st["p99"]
            else:
                out[key] = m.value
        return out
