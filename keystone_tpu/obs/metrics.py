"""Named, registered metrics: counters / gauges / histograms.

Before this module, operational counters were ad-hoc attributes: the
data-plane runtime's per-lane ``tasks/errors/busy_s``, the serving
breaker's ``completed/rejected/failed/breaker_opens``, the per-fit
``PrefetchStats`` site accounting. Each grew its own locking, its own
snapshot shape, and its own (unchecked) names. A :class:`MetricsRegistry`
replaces that plumbing: one get-or-create API, one flat ``snapshot()``
shape every ``stats()``/bench reader consumes, and every name drawn from
the ``METRIC_*`` catalogue below.

The catalogue is the contract: ``tools/lint.py``'s ``metric-name`` rule
PARSES (never imports) this module for ``METRIC_*`` assignments — the
same discipline as the fault-site registry — and rejects any
register/lookup site whose dotted name is not in it, so dashboards can't
silently fork names. Labels (``site=``, ``lane=``) carry the
per-instance dimension; snapshot keys render as ``name{k=v}``.

No jax, no numpy: the registry is imported by ``data/runtime.py``
(which must stay jax-free) and updated from IO worker threads.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "METRIC_PREFETCH_BACKOFF_S",
    "METRIC_PREFETCH_LOAD_S",
    "METRIC_PREFETCH_RETRIES",
    "METRIC_PREFETCH_SEGMENTS",
    "METRIC_PREFETCH_WAIT_S",
    "METRIC_RUNTIME_LANE_BUSY_S",
    "METRIC_RUNTIME_LANE_ERRORS",
    "METRIC_RUNTIME_LANE_QUEUED",
    "METRIC_RUNTIME_LANE_TASKS",
    "METRIC_SERVING_BREAKER_OPENS",
    "METRIC_SERVING_COMPLETED",
    "METRIC_SERVING_DEGRADED_REJECTED",
    "METRIC_SERVING_FAILED",
    "METRIC_SERVING_LATENCY_S",
    "METRIC_SERVING_QUEUE_DEPTH",
    "METRIC_SERVING_REJECTED",
    "METRIC_SITE_BUSY_S",
    "METRIC_SITE_WAIT_S",
]

# ---------------------------------------------------------------------------
# Metric catalogue — the ONLY names a register/lookup site may use
# (parsed, not imported, by tools/lint.py's metric-name rule; the docs
# table in docs/observability.md mirrors this list).
# ---------------------------------------------------------------------------

# Data-plane runtime, per lane (label: site=<lane>) — DataPlaneRuntime.stats()
METRIC_RUNTIME_LANE_TASKS = "runtime.lane.tasks"
METRIC_RUNTIME_LANE_ERRORS = "runtime.lane.errors"
METRIC_RUNTIME_LANE_BUSY_S = "runtime.lane.busy_s"
METRIC_RUNTIME_LANE_QUEUED = "runtime.lane.queued"

# Per-fit ingestion (PrefetchStats) — overlap + retry accounting
METRIC_PREFETCH_LOAD_S = "prefetch.load_s"
METRIC_PREFETCH_WAIT_S = "prefetch.wait_s"
METRIC_PREFETCH_SEGMENTS = "prefetch.segments"
METRIC_PREFETCH_RETRIES = "prefetch.retries"
METRIC_PREFETCH_BACKOFF_S = "prefetch.backoff_s"
# Per-site overlap accounting (label: site=read/verify/checkpoint/compute)
METRIC_SITE_BUSY_S = "overlap.site_busy_s"
METRIC_SITE_WAIT_S = "overlap.site_wait_s"

# Serving (MicroBatchServer) — the breaker/throughput counters stats() reads
METRIC_SERVING_COMPLETED = "serving.completed"
METRIC_SERVING_REJECTED = "serving.rejected"
METRIC_SERVING_FAILED = "serving.failed"
METRIC_SERVING_BREAKER_OPENS = "serving.breaker_opens"
METRIC_SERVING_DEGRADED_REJECTED = "serving.degraded_rejected"
METRIC_SERVING_LATENCY_S = "serving.latency_s"
METRIC_SERVING_QUEUE_DEPTH = "serving.queue_depth"


class Counter:
    """Monotonic-by-convention accumulator (float). ``set_()`` exists
    only for the attribute-compatibility shims that migrated legacy
    ``stats.load_s += dt`` call sites onto the registry."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def add(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def set_(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (queue depth, liveness)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Bounded-reservoir distribution: keeps the most recent ``maxlen``
    observations (the rolling-window convention the serving stats
    already used) plus lifetime count/sum. Percentiles are exact over
    the retained window, computed by linear interpolation (the same
    convention as numpy's default, so ``latency_percentiles`` agrees)."""

    __slots__ = ("_lock", "_window", "count", "total")

    def __init__(self, maxlen: int = 4096):
        self._lock = threading.Lock()
        self._window: "deque[float]" = deque(maxlen=maxlen)
        self.count = 0
        self.total = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._window.append(v)
            self.count += 1
            self.total += v

    def snapshot_values(self) -> list:
        with self._lock:
            return list(self._window)

    def percentile(self, q: float) -> Optional[float]:
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile q must be in [0, 100], got {q}")
        with self._lock:
            vals = sorted(self._window)
        if not vals:
            return None
        if len(vals) == 1:
            return vals[0]
        pos = (q / 100.0) * (len(vals) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(vals) - 1)
        frac = pos - lo
        return vals[lo] * (1.0 - frac) + vals[hi] * frac


class MetricsRegistry:
    """Get-or-create registry of named metrics.

    ``counter(name, **labels)`` / ``gauge(...)`` / ``histogram(...)``
    are both registration and lookup — the same call shape at the
    definition site and every reader, so there is nothing to keep in
    sync. A name re-used at a different type raises (one name, one
    meaning). ``snapshot()`` flattens everything to one dict —
    ``name`` or ``name{k=v,...}`` keys — which is the ONE shape
    ``stats()`` methods and bench rows read.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Any] = {}

    @staticmethod
    def _key(name: str, labels: Dict[str, Any]):
        return name, tuple(sorted((k, str(v)) for k, v in labels.items()))

    def _get_or_create(self, cls, name: str, labels, **kw):
        key = self._key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(**kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r}{labels or ''} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get_or_create(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get_or_create(Gauge, name, labels)

    def histogram(self, name: str, maxlen: int = 4096, **labels) -> Histogram:
        return self._get_or_create(Histogram, name, labels, maxlen=maxlen)

    def labels_of(self, name: str) -> list:
        """The label-sets registered under ``name`` (e.g. every lane a
        runtime has created), as dicts."""
        with self._lock:
            return [
                dict(lbls) for (n, lbls) in self._metrics if n == name
            ]

    def values_by_label(self, name: str, label: str) -> Dict[str, float]:
        """``{label_value: metric_value}`` for one labeled counter/gauge
        family — the shape the per-site overlap dicts are built from."""
        out: Dict[str, float] = {}
        with self._lock:
            items = list(self._metrics.items())
        for (n, lbls), m in items:
            d = dict(lbls)
            if n == name and label in d and hasattr(m, "value"):
                out[d[label]] = m.value
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Flat dict of every registered metric. Counters/gauges map to
        their value; histograms expand to ``.count`` / ``.sum`` /
        ``.p50`` / ``.p99`` sub-keys."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, Any] = {}
        for (name, lbls), m in items:
            key = name
            if lbls:
                key += "{" + ",".join(f"{k}={v}" for k, v in lbls) + "}"
            if isinstance(m, Histogram):
                out[key + ".count"] = m.count
                out[key + ".sum"] = m.total
                out[key + ".p50"] = m.percentile(50.0)
                out[key + ".p99"] = m.percentile(99.0)
            else:
                out[key] = m.value
        return out
