"""Online SLO objectives: multi-window burn rates, an error-budget
ledger, and an OK/WARN/BREACH state machine for long-lived serving.

ROADMAP item 3's serving target is judged by LIVE signals ("a p99 SLO
gate, plus a chaos leg proving the SLO degrades gracefully") — but until
this module nothing in the codebase could state an SLO verdict while a
server was running: the degradation machinery (shed, breaker trip,
replica evict, swap) fired with no quantitative objective behind it and
no budget accounting after. This is the measured-policy layer over
those mechanisms, the same discipline KeystoneML applies to optimizer
choices (decisions justified by observed profiles):

  - An :class:`SLOObjective` declares what "good" means — a latency
    bound (``kind="latency"``: a completion is good iff it finished
    within ``threshold_s``) or availability (``kind="availability"``: a
    request is good iff it resolved with a result, not a shed/breaker
    reject/failure) — plus the ``target`` good fraction.
  - :class:`SLOTracker` consumes the per-request outcome stream
    (:meth:`SLOTracker.observe`, fed by the serving planes) into
    fixed-slot time windows (O(1) memory, the same bounded-state rule
    as the bucketed histograms) and computes FAST and SLOW window
    **burn rates**: ``bad_fraction / (1 - target)`` — 1.0 means budget
    is being spent exactly at the sustainable rate, N means N× too
    fast. Two windows so a one-tick blip neither pages (the slow window
    smooths it) nor hides (the fast window catches a real storm within
    seconds).
  - The per-objective state machine: **BREACH** when the fast burn
    reaches ``breach_burn``; it sticks (hysteresis) until the fast burn
    falls back under ``warn_burn``; **WARN** when either window burns
    above ``warn_burn``; **OK** otherwise. Every transition is traced
    as an instant event (``slo.transition``) under the active tracer,
    noted on the flight ring, and a transition INTO breach dumps the
    flight record (:func:`keystone_tpu.obs.flight.dump_flight_record`)
    — the postmortem starts AT the breach, not after the pager.
  - The **error-budget ledger**: one entry per state interval with the
    good/bad counts attributed to it, so a chaos kill's degraded window
    is accounted for — "the BREACH interval burned 312 of the run's 450
    allowed errors" is a ledger read, not archaeology.

States publish into a :class:`~keystone_tpu.obs.metrics.MetricsRegistry`
when one is provided (``slo.state`` / ``slo.burn_rate_fast`` / ... per
objective label) so the live exporter renders them beside the serving
counters — gauges refresh on :meth:`SLOTracker.evaluate` (the
exporter's tick), never on the per-request hot path. No jax, no numpy:
fed from serving worker callbacks.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

from keystone_tpu.obs import flight as flight_mod
from keystone_tpu.obs import tracer as tracer_mod
from keystone_tpu.obs.metrics import (
    METRIC_SLO_BUDGET_SPENT,
    METRIC_SLO_BURN_FAST,
    METRIC_SLO_BURN_SLOW,
    METRIC_SLO_STATE,
    METRIC_SLO_TRANSITIONS,
)

__all__ = [
    "SLOObjective",
    "SLOTracker",
    "STATE_BREACH",
    "STATE_OK",
    "STATE_WARN",
]

STATE_OK = "OK"
STATE_WARN = "WARN"
STATE_BREACH = "BREACH"
# Numeric projection for the registry gauge / Prometheus rendering.
_STATE_LEVEL = {STATE_OK: 0, STATE_WARN: 1, STATE_BREACH: 2}

# Slots per window: burn rates are computed over fixed time slots, so
# memory is O(slots) regardless of traffic, and an idle second ages out
# of the window without a timer thread.
_SLOTS_PER_WINDOW = 20


@dataclass(frozen=True)
class SLOObjective:
    """One declared objective. ``target`` is the GOOD fraction the SLO
    promises (0.99 = 1% error budget); ``threshold_s`` is the latency
    bound for ``kind="latency"`` (ignored for availability). The burn
    thresholds are in budget-rate units: 1.0 = spending exactly the
    sustainable rate."""

    name: str
    kind: str = "latency"  # "latency" | "availability"
    threshold_s: Optional[float] = None
    target: float = 0.99
    fast_window_s: float = 5.0
    slow_window_s: float = 60.0
    warn_burn: float = 1.0
    breach_burn: float = 6.0
    # A window with fewer events than this cannot ESCALATE the state:
    # one slow request in an otherwise-empty window is a 100% bad
    # fraction (burn = 1/budget — an instant page at serve start, seen
    # on the first cold batch of the chaos bench). De-escalation is
    # ungated — hysteresis still holds a breach while the raw fast burn
    # stays over warn_burn, and an idle window decays to OK.
    min_events: int = 10

    def __post_init__(self):
        if self.kind not in ("latency", "availability"):
            raise ValueError(
                f"SLOObjective kind must be 'latency' or 'availability', "
                f"got {self.kind!r}"
            )
        if self.kind == "latency" and (
            self.threshold_s is None or self.threshold_s <= 0
        ):
            raise ValueError(
                f"latency objective {self.name!r} needs threshold_s > 0"
            )
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"objective {self.name!r}: target must be in (0, 1) — "
                f"a target of 1.0 has zero error budget and every bad "
                f"event is an immediate breach; got {self.target}"
            )
        if self.fast_window_s <= 0 or self.slow_window_s < self.fast_window_s:
            raise ValueError(
                f"objective {self.name!r}: need 0 < fast_window_s "
                f"<= slow_window_s"
            )
        if self.breach_burn < self.warn_burn:
            raise ValueError(
                f"objective {self.name!r}: breach_burn < warn_burn would "
                "make WARN unreachable on the way down"
            )
        if self.min_events < 1:
            raise ValueError(
                f"objective {self.name!r}: min_events must be >= 1"
            )


class _Window:
    """Time-slotted (good, bad) counts covering ``window_s``, bounded to
    a fixed slot count — O(1) memory under unbounded traffic."""

    __slots__ = ("slot_s", "slots", "_ring")

    def __init__(self, window_s: float, slots: int = _SLOTS_PER_WINDOW):
        self.slot_s = window_s / slots
        self.slots = slots
        # (slot_index, good, bad) — mutated in place for the live slot.
        self._ring: "deque[List[float]]" = deque(maxlen=slots + 1)

    def add(self, now: float, good: int, bad: int) -> None:
        idx = int(now / self.slot_s)
        if self._ring and self._ring[-1][0] == idx:
            self._ring[-1][1] += good
            self._ring[-1][2] += bad
        else:
            self._ring.append([idx, good, bad])

    def totals(self, now: float) -> "tuple[int, int]":
        lo = int(now / self.slot_s) - self.slots
        good = bad = 0
        for idx, g, b in self._ring:
            if idx > lo:
                good += g
                bad += b
        return int(good), int(bad)


class _ObjectiveState:
    """Per-objective live state: windows, lifetime totals, the state
    machine, the transition log, and the budget ledger."""

    def __init__(self, objective: SLOObjective):
        self.obj = objective
        self.fast = _Window(objective.fast_window_s)
        self.slow = _Window(objective.slow_window_s)
        self.good_total = 0
        self.bad_total = 0
        self.state = STATE_OK
        self.transitions: List[Dict[str, Any]] = []
        # Budget ledger: one OPEN entry per state interval; counts are
        # attributed to the interval they arrived in.
        self.ledger: List[Dict[str, Any]] = [{
            "state": STATE_OK, "t_start": 0.0, "t_end": None,
            "good": 0, "bad": 0,
        }]

    def record(self, now: float, good: bool) -> None:
        g, b = (1, 0) if good else (0, 1)
        self.fast.add(now, g, b)
        self.slow.add(now, g, b)
        self.good_total += g
        self.bad_total += b
        cur = self.ledger[-1]
        cur["good"] += g
        cur["bad"] += b

    @staticmethod
    def _burn(totals: "tuple[int, int]", budget_frac: float) -> float:
        good, bad = totals
        n = good + bad
        if n == 0:
            return 0.0
        return (bad / n) / budget_frac

    def burns(self, now: float) -> "tuple[float, float]":
        budget = 1.0 - self.obj.target
        return (
            self._burn(self.fast.totals(now), budget),
            self._burn(self.slow.totals(now), budget),
        )

    def next_state(self, now: float, burn_fast: float,
                   burn_slow: float) -> str:
        obj = self.obj
        # min_events gates ESCALATION only: a 1-sample window has a
        # 0-or-100% bad fraction — noise, not a storm. De-escalation
        # stays on the raw burns (hysteresis below; an idle window
        # decays to 0 and clears).
        fast_n = sum(self.fast.totals(now))
        slow_n = sum(self.slow.totals(now))
        if fast_n >= obj.min_events and burn_fast >= obj.breach_burn:
            return STATE_BREACH
        if self.state == STATE_BREACH and burn_fast >= obj.warn_burn:
            # Hysteresis: a breach ends only when the fast window is
            # back UNDER the sustainable rate — not when it merely dips
            # below the page threshold (which would flap).
            return STATE_BREACH
        if (fast_n >= obj.min_events and burn_fast >= obj.warn_burn) or (
            slow_n >= obj.min_events and burn_slow >= obj.warn_burn
        ):
            return STATE_WARN
        return STATE_OK

    def budget_spent_fraction(self) -> float:
        """Share of the run's error budget consumed so far: observed bad
        fraction over the allowed bad fraction (can exceed 1.0 — budget
        overdrawn)."""
        n = self.good_total + self.bad_total
        if n == 0:
            return 0.0
        return (self.bad_total / n) / (1.0 - self.obj.target)


class SLOTracker:
    """Consume request outcomes, hold the per-objective burn-rate state
    machines, and publish verdicts (module docstring).

    ``metrics``: a :class:`MetricsRegistry` to publish per-objective
    gauges into (optional). ``clock``: injectable monotonic clock —
    the state machine is deterministic under a fake clock, which is how
    the unit tests drive OK→WARN→BREACH→OK without wall-time sleeps.
    Thread-safe: ``observe`` is called from serving worker threads and
    done-callbacks while ``verdict``/``evaluate`` run on exporter or
    bench threads.
    """

    def __init__(
        self,
        objectives: Sequence[SLOObjective],
        metrics=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        objectives = list(objectives)
        if not objectives:
            raise ValueError("SLOTracker needs at least one objective")
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self._clock = clock
        self._lock = threading.Lock()
        self._t0 = clock()
        self._objectives: Dict[str, _ObjectiveState] = {
            o.name: _ObjectiveState(o) for o in objectives
        }
        self._metrics = metrics
        if metrics is not None:
            for name in names:
                metrics.gauge(METRIC_SLO_STATE, objective=name)
                metrics.gauge(METRIC_SLO_BURN_FAST, objective=name)
                metrics.gauge(METRIC_SLO_BURN_SLOW, objective=name)
                metrics.gauge(METRIC_SLO_BUDGET_SPENT, objective=name)
                metrics.counter(METRIC_SLO_TRANSITIONS, objective=name)

    @property
    def objectives(self) -> List[SLOObjective]:
        return [st.obj for st in self._objectives.values()]

    # -- feeding -----------------------------------------------------------

    def observe(self, latency_s: Optional[float] = None,
                ok: bool = True) -> None:
        """Record one request outcome. ``ok=False`` (shed / breaker
        reject / failure / timeout) is a bad event for EVERY objective.
        ``ok=True`` with a latency feeds latency objectives
        (good iff within threshold) and availability objectives (good).
        Evaluates the state machines inline — transition latency is one
        request, not one exporter tick."""
        now = self._clock() - self._t0
        transitions = []
        with self._lock:
            for st in self._objectives.values():
                if ok and st.obj.kind == "latency":
                    if latency_s is None:
                        continue  # no latency measured: not a latency SLI
                    st.record(now, latency_s <= st.obj.threshold_s)
                else:
                    st.record(now, ok)
            # publish=False: the hot path detects transitions only;
            # registry gauge publishing rides the exporter's evaluate()
            # cadence, not every request (the tracker lock is contended
            # by every serving worker and done-callback).
            transitions = self._evaluate_locked(now, publish=False)
        self._emit(transitions)

    def evaluate(self) -> Dict[str, str]:
        """Re-run the state machines on the current clock (an idle
        window decays burn rates with no traffic) and return the
        per-objective states. The exporter calls this every tick."""
        now = self._clock() - self._t0
        with self._lock:
            transitions = self._evaluate_locked(now)
            states = {n: st.state for n, st in self._objectives.items()}
        self._emit(transitions)
        return states

    def _evaluate_locked(self, now: float,
                         publish: bool = True) -> List[Dict[str, Any]]:
        out = []
        for name, st in self._objectives.items():
            burn_fast, burn_slow = st.burns(now)
            nxt = st.next_state(now, burn_fast, burn_slow)
            if publish and self._metrics is not None:
                self._metrics.gauge(METRIC_SLO_STATE, objective=name).set(
                    _STATE_LEVEL[nxt]
                )
                self._metrics.gauge(
                    METRIC_SLO_BURN_FAST, objective=name
                ).set(burn_fast)
                self._metrics.gauge(
                    METRIC_SLO_BURN_SLOW, objective=name
                ).set(burn_slow)
                self._metrics.gauge(
                    METRIC_SLO_BUDGET_SPENT, objective=name
                ).set(st.budget_spent_fraction())
            if nxt == st.state:
                continue
            rec = {
                "objective": name, "from": st.state, "to": nxt,
                "t_s": round(now, 6),
                "burn_fast": round(burn_fast, 4),
                "burn_slow": round(burn_slow, 4),
                "budget_spent_fraction": round(
                    st.budget_spent_fraction(), 4
                ),
            }
            st.transitions.append(rec)
            st.ledger[-1]["t_end"] = now
            st.ledger.append({
                "state": nxt, "t_start": now, "t_end": None,
                "good": 0, "bad": 0,
            })
            st.state = nxt
            if self._metrics is not None:
                self._metrics.counter(
                    METRIC_SLO_TRANSITIONS, objective=name
                ).add(1)
            out.append(rec)
        return out

    def _emit(self, transitions: List[Dict[str, Any]]) -> None:
        """Trace + flight-record each transition OUTSIDE the tracker
        lock (the flight dump renders and logs — never under a lock the
        serving hot path contends)."""
        for rec in transitions:
            tracer_mod.event("slo.transition", **rec)
            flight_mod.flight_note(
                "slo", f"{rec['objective']}:{rec['from']}->{rec['to']}",
                burn_fast=rec["burn_fast"],
                budget_spent=rec["budget_spent_fraction"],
            )
            if rec["to"] == STATE_BREACH:
                # A breach IS a postmortem moment: dump the ring (recent
                # spans, faults, decisions, in-flight work) beside it.
                flight_mod.dump_flight_record(
                    f"SLO BREACH: objective {rec['objective']!r} "
                    f"burn_fast={rec['burn_fast']} "
                    f"(budget {rec['budget_spent_fraction']:.1%} spent)"
                )

    # -- reading -----------------------------------------------------------

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {n: st.state for n, st in self._objectives.items()}

    def burn_rates(self) -> Dict[str, "tuple[float, float]"]:
        """``{objective: (burn_fast, burn_slow)}`` on the current clock —
        the light read the autoscaler's tick consumes (``verdict()``
        builds the full transition/ledger copies; a control loop ticking
        several times a second only needs the burns)."""
        now = self._clock() - self._t0
        with self._lock:
            return {n: st.burns(now) for n, st in self._objectives.items()}

    def worst_state(self) -> str:
        states = self.states().values()
        for s in (STATE_BREACH, STATE_WARN):
            if s in states:
                return s
        return STATE_OK

    def verdict(self) -> Dict[str, Any]:
        """The SLO verdict block (what ``LoadReport`` and ``run.py
        serve`` publish): per objective — state, both burn rates,
        budget spent/remaining, lifetime good/bad, the transition log,
        and the budget ledger with per-interval counts (a degraded
        window's cost is a ledger read)."""
        now = self._clock() - self._t0
        with self._lock:
            objectives = {}
            for name, st in self._objectives.items():
                burn_fast, burn_slow = st.burns(now)
                spent = st.budget_spent_fraction()
                ledger = []
                for entry in st.ledger:
                    e = dict(entry)
                    e["t_start"] = round(e["t_start"], 6)
                    if e["t_end"] is not None:
                        e["t_end"] = round(e["t_end"], 6)
                    ledger.append(e)
                objectives[name] = {
                    "kind": st.obj.kind,
                    "threshold_s": st.obj.threshold_s,
                    "target": st.obj.target,
                    "state": st.state,
                    # Numeric projection: the Prometheus renderer skips
                    # strings, so this is the field an alert scrapes.
                    "state_level": _STATE_LEVEL[st.state],
                    "burn_fast": round(burn_fast, 4),
                    "burn_slow": round(burn_slow, 4),
                    "budget_spent_fraction": round(spent, 4),
                    "budget_remaining_fraction": round(1.0 - spent, 4),
                    "good_total": st.good_total,
                    "bad_total": st.bad_total,
                    "transitions": list(st.transitions),
                    "ledger": ledger,
                }
            worst = STATE_OK
            for o in objectives.values():
                if _STATE_LEVEL[o["state"]] > _STATE_LEVEL[worst]:
                    worst = o["state"]
        return {
            "state": worst,
            "state_level": _STATE_LEVEL[worst],
            "objectives": objectives,
        }
