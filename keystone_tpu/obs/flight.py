"""Flight recorder: the bounded ring of recent events a postmortem reads.

Chaos taught this repo that the exception alone rarely names the cause:
a serving worker dies and the interesting fact is which batch was in
flight and whether the breaker had been flapping; a ``ShardCorrupted``
surfaces consumer-side and the interesting fact is which segment reads
and checkpoint writes preceded it. The flight recorder keeps a bounded,
always-on ring of recent notes — span completions (when tracing is on),
cost decisions, fault-path events — and the fault paths
(``MicroBatchServer._worker_died``, breaker opens, shard-corruption
raises, replica watchdog evictions) dump it alongside the exception via
:func:`dump_flight_record`, so the log names the spans in flight at
death instead of just the stack.

Always-on is safe because the steady-state cost is zero: fault paths are
the only unconditional writers, and span notes fire only while a tracer
is active. No jax, no numpy (imported by the runtime's IO workers and
the serving worker)."""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "FlightRecorder",
    "default_flight_recorder",
    "dump_flight_record",
    "flight_note",
    "flight_snapshot",
    "render_flight_record",
]

logger = logging.getLogger("keystone_tpu.obs.flight")


class FlightRecorder:
    """Thread-safe bounded ring of ``(ts, kind, name, attrs)`` notes."""

    def __init__(self, maxlen: int = 256):
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def note(self, kind: str, name: str, **attrs) -> None:
        rec = {"ts": time.time(), "kind": kind, "name": name}
        if attrs:
            rec["attrs"] = {k: v for k, v in attrs.items() if v is not None}
        with self._lock:
            self._ring.append(rec)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_DEFAULT = FlightRecorder()


def default_flight_recorder() -> FlightRecorder:
    return _DEFAULT


def flight_note(kind: str, name: str, **attrs) -> None:
    """Append one note to the process flight ring (fault paths call this
    unconditionally; the tracer mirrors span completions here while
    active)."""
    _DEFAULT.note(kind, name, **attrs)


def flight_snapshot() -> List[Dict[str, Any]]:
    return _DEFAULT.snapshot()


def render_flight_record(limit: int = 25) -> str:
    """Human-readable postmortem block: the last ``limit`` ring notes
    (oldest first) plus every span currently OPEN on the active tracer —
    what was in flight at the moment of death."""
    lines: List[str] = []
    notes = _DEFAULT.snapshot()[-limit:]
    t_ref = notes[-1]["ts"] if notes else time.time()
    for rec in notes:
        attrs = rec.get("attrs") or {}
        suffix = " ".join(f"{k}={v}" for k, v in attrs.items())
        lines.append(
            f"  {rec['ts'] - t_ref:+8.3f}s [{rec['kind']}] {rec['name']}"
            + (f" {suffix}" if suffix else "")
        )
    from keystone_tpu.obs import tracer as tracer_mod

    t = tracer_mod.active_tracer()
    if t is not None:
        for sp in t.inflight():
            parent = sp.get("parent_id")
            lines.append(
                f"  IN FLIGHT: {sp['name']} (span {sp['span_id']}"
                + (f" < {parent}" if parent else "")
                + f", thread {sp['thread']})"
            )
    if not lines:
        return "flight record: (empty)"
    return "flight record (most recent last):\n" + "\n".join(lines)


def dump_flight_record(
    context: str, exc: Optional[BaseException] = None,
    log: Optional[logging.Logger] = None, limit: int = 25,
) -> str:
    """The fault-path hook: render the ring (+ in-flight spans), log it
    loudly with the failure context, note the dump itself, and return
    the rendered block (callers that can attach it to a report do).
    Never raises — a postmortem aid must not kill the path it serves."""
    try:
        rendered = render_flight_record(limit=limit)
        flight_note("dump", context, error=repr(exc) if exc else None)
        (log or logger).warning(
            "%s%s\n%s", context,
            f": {exc!r}" if exc is not None else "", rendered,
        )
        return rendered
    except Exception:  # pragma: no cover - last-resort guard
        return "flight record: (unavailable)"
