"""Flight recorder: the bounded ring of recent events a postmortem reads.

Chaos taught this repo that the exception alone rarely names the cause:
a serving worker dies and the interesting fact is which batch was in
flight and whether the breaker had been flapping; a ``ShardCorrupted``
surfaces consumer-side and the interesting fact is which segment reads
and checkpoint writes preceded it. The flight recorder keeps a bounded,
always-on ring of recent notes — span completions (when tracing is on),
cost decisions, fault-path events — and the fault paths
(``MicroBatchServer._worker_died``, breaker opens, shard-corruption
raises, replica watchdog evictions) dump it alongside the exception via
:func:`dump_flight_record`, so the log names the spans in flight at
death instead of just the stack.

Always-on is safe because the steady-state cost is zero: fault paths are
the only unconditional writers, and span notes fire only while a tracer
is active. No jax, no numpy (imported by the runtime's IO workers and
the serving worker)."""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "FlightRecorder",
    "default_flight_recorder",
    "dump_flight_record",
    "flight_note",
    "flight_snapshot",
    "render_flight_record",
    "set_dump_dir",
]

logger = logging.getLogger("keystone_tpu.obs.flight")

# Optional on-disk dumps: when a directory is configured (set_dump_dir()
# or the env knob), every dump_flight_record ALSO writes its rendered
# block to a UNIQUE file there. Uniqueness is load-bearing: two replicas
# dying in the same tick dump concurrently, and a timestamp-only name
# would let the second clobber the first — the postmortem of the death
# that explains the other one. pid + an atomic per-process sequence +
# O_EXCL creation make collisions structurally impossible.
DUMP_DIR_ENV = "KEYSTONE_FLIGHT_DUMPS"
_DUMP_DIR: Optional[str] = None
_DUMP_SEQ = itertools.count(1)


class FlightRecorder:
    """Thread-safe bounded ring of ``(ts, kind, name, attrs)`` notes."""

    def __init__(self, maxlen: int = 256):
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def note(self, kind: str, name: str, **attrs) -> None:
        rec = {"ts": time.time(), "kind": kind, "name": name}
        if attrs:
            rec["attrs"] = {k: v for k, v in attrs.items() if v is not None}
        with self._lock:
            self._ring.append(rec)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_DEFAULT = FlightRecorder()


def default_flight_recorder() -> FlightRecorder:
    return _DEFAULT


def flight_note(kind: str, name: str, **attrs) -> None:
    """Append one note to the process flight ring (fault paths call this
    unconditionally; the tracer mirrors span completions here while
    active)."""
    _DEFAULT.note(kind, name, **attrs)


def flight_snapshot() -> List[Dict[str, Any]]:
    return _DEFAULT.snapshot()


def render_flight_record(limit: int = 25) -> str:
    """Human-readable postmortem block: the last ``limit`` ring notes
    (oldest first) plus every span currently OPEN on the active tracer —
    what was in flight at the moment of death."""
    lines: List[str] = []
    notes = _DEFAULT.snapshot()[-limit:]
    t_ref = notes[-1]["ts"] if notes else time.time()
    for rec in notes:
        attrs = rec.get("attrs") or {}
        suffix = " ".join(f"{k}={v}" for k, v in attrs.items())
        lines.append(
            f"  {rec['ts'] - t_ref:+8.3f}s [{rec['kind']}] {rec['name']}"
            + (f" {suffix}" if suffix else "")
        )
    from keystone_tpu.obs import tracer as tracer_mod

    t = tracer_mod.active_tracer()
    if t is not None:
        for sp in t.inflight():
            parent = sp.get("parent_id")
            lines.append(
                f"  IN FLIGHT: {sp['name']} (span {sp['span_id']}"
                + (f" < {parent}" if parent else "")
                + f", thread {sp['thread']})"
            )
    if not lines:
        return "flight record: (empty)"
    return "flight record (most recent last):\n" + "\n".join(lines)


def set_dump_dir(directory: Optional[str]) -> None:
    """Configure (or clear, with None) the on-disk flight-dump
    directory; ``KEYSTONE_FLIGHT_DUMPS=dir`` is the env form."""
    global _DUMP_DIR
    _DUMP_DIR = directory


def _dump_dir() -> Optional[str]:
    return _DUMP_DIR or os.environ.get(DUMP_DIR_ENV, "").strip() or None


def _write_dump_file(context: str, exc: Optional[BaseException],
                     rendered: str) -> Optional[str]:
    """Write one dump to a UNIQUE file under the configured dump dir
    (None when no dir is configured). ``O_EXCL`` creation: concurrent
    dumps — two replicas dying in the same tick — can NEVER clobber
    each other; a (theoretical) name collision retries with the next
    sequence number instead of truncating an existing postmortem."""
    directory = _dump_dir()
    if not directory:
        return None
    # The file is an AUGMENTATION of the loud log line, never a
    # precondition: an unwritable dump dir / full disk must not
    # propagate into dump_flight_record's last-resort guard and
    # swallow the warning the dump exists to emit.
    try:
        os.makedirs(directory, exist_ok=True)
        body = (
            f"context: {context}\n"
            + (f"exception: {exc!r}\n" if exc is not None else "")
            + rendered + "\n"
        )
        for _ in range(8):
            name = (
                f"flight-{time.time_ns()}-{os.getpid()}"
                f"-{next(_DUMP_SEQ):06d}.txt"
            )
            path = os.path.join(directory, name)
            try:
                fd = os.open(
                    path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644
                )
            except FileExistsError:  # pragma: no cover - seq is unique
                continue
            with os.fdopen(fd, "w") as f:
                f.write(body)
            return path
    except OSError:
        return None
    return None  # pragma: no cover - 8 collisions cannot happen


def dump_flight_record(
    context: str, exc: Optional[BaseException] = None,
    log: Optional[logging.Logger] = None, limit: int = 25,
) -> str:
    """The fault-path hook: render the ring (+ in-flight spans), log it
    loudly with the failure context, note the dump itself, write it to
    a unique file when a dump directory is configured (set_dump_dir /
    ``KEYSTONE_FLIGHT_DUMPS``), and return the rendered block (callers
    that can attach it to a report do). Never raises — a postmortem aid
    must not kill the path it serves."""
    try:
        rendered = render_flight_record(limit=limit)
        flight_note("dump", context, error=repr(exc) if exc else None)
        path = _write_dump_file(context, exc, rendered)
        (log or logger).warning(
            "%s%s\n%s%s", context,
            f": {exc!r}" if exc is not None else "", rendered,
            f"\nflight dump written: {path}" if path else "",
        )
        return rendered
    except Exception:  # pragma: no cover - last-resort guard
        return "flight record: (unavailable)"
