"""Trace export: Chrome-trace/Perfetto JSON + compact JSONL event log.

The Chrome trace event format (the JSON array flavor inside a
``{"traceEvents": [...]}`` document) is what Perfetto's UI and
``chrome://tracing`` load directly:

  - one track per thread (``M`` thread-name metadata events; spans are
    ``X`` complete events with microsecond ``ts``/``dur``),
  - instant events (cost decisions, faults) as ``i`` events,
  - counter tracks (queue depths, outstanding requests) as ``C`` events.

``events.jsonl`` is the same record stream in this repo's own row shape
(one JSON object per line — see ``obs/tracer.py`` for the schema): the
compact log ``tools/trace.py`` / ``bin/trace`` summarize without parsing
the Chrome projection back apart.

``validate_chrome_trace`` is the schema gate the tests assert through:
it checks exactly the invariants the viewers rely on, so "the file
validates" is a testable claim, not a vibe.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional

__all__ = [
    "device_of_span_args",
    "load_events",
    "to_chrome_trace",
    "validate_chrome_trace",
    "write_trace_dir",
]

TRACE_JSON = "trace.json"
EVENTS_JSONL = "events.jsonl"
META_JSON = "meta.json"

# The subset of Chrome trace event phases this exporter emits.
_PHASES = {"X", "i", "C", "M"}

# Per-device read lanes the mesh ingestion plane submits on
# (data/prefetch.py ``mesh_read_lane``): ``read.d<k>`` owns device k's
# row shard, so its runtime.task spans ARE device-k evidence.
_DEVICE_LANE = re.compile(r"^read\.d(\d+)$")


def device_of_span_args(args: Dict[str, Any]) -> Optional[str]:
    """The device identity a span's args pin it to, or None.

    Two tag conventions feed this: an explicit ``device=`` attr (the
    mesh fold's ``fold.segment`` spans — ``data[0-7]`` for a dispatch
    covering the whole axis), and a ``lane=read.d<k>`` attr (the
    per-device ingestion lanes, genuinely device-local work)."""
    dev = args.get("device")
    if dev is not None:
        return str(dev)
    lane = args.get("lane")
    if isinstance(lane, str):
        m = _DEVICE_LANE.match(lane)
        if m:
            return m.group(1)
    return None


def _jsonable(v: Any) -> Any:
    """Args must survive json.dumps: coerce exotic leaves (numpy
    scalars, dtypes, tuples-as-keys never occur) to plain types."""
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    try:
        # numpy scalars expose item(); anything else degrades to str.
        return v.item()
    except AttributeError:
        return str(v)


def to_chrome_trace(records: Iterable[Dict[str, Any]],
                    run_id: Optional[str] = None) -> Dict[str, Any]:
    """Project tracer records (span/event/counter rows) into one
    Chrome-trace document. ``records`` is a :class:`~keystone_tpu.obs.
    tracer.Tracer`'s ``events`` list (or the rows read back from
    ``events.jsonl``)."""
    records = list(records)
    if run_id is None:
        for r in records:
            if "run_id" in r:
                run_id = r["run_id"]
                break
    events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": 1, "tid": 0,
        "args": {"name": f"keystone_tpu run {run_id or '?'}"},
    }]
    # Stable small tids per thread, in first-seen order; one thread-name
    # metadata event per track.
    tid_of: Dict[Any, int] = {}
    for r in records:
        raw = r.get("tid")
        if raw is None:
            continue
        if raw not in tid_of:
            tid_of[raw] = len(tid_of) + 1
            events.append({
                "name": "thread_name", "ph": "M", "pid": 1,
                "tid": tid_of[raw],
                "args": {"name": r.get("thread", f"thread-{raw}")},
            })
    # Synthetic device tracks: spans pinned to a device (explicit
    # ``device=`` attr, or a ``read.d<k>`` per-device ingestion lane)
    # render on their own ``device-<k>`` row so an 8-chip run reads as
    # 8 parallel tracks, not one interleaved thread. Numeric device ids
    # sort numerically so device-10 lands after device-9.
    dev_keys: List[str] = []
    for r in records:
        if r.get("type") != "span":
            continue
        dev = device_of_span_args(r.get("args") or {})
        if dev is not None and dev not in dev_keys:
            dev_keys.append(dev)
    dev_keys.sort(key=lambda s: (0, int(s)) if s.isdigit() else (1, s))
    dev_tid_of: Dict[str, int] = {}
    for dev in dev_keys:
        dev_tid_of[dev] = len(tid_of) + len(dev_tid_of) + 1
        events.append({
            "name": "thread_name", "ph": "M", "pid": 1,
            "tid": dev_tid_of[dev],
            "args": {"name": f"device-{dev}"},
        })
    for r in records:
        kind = r.get("type")
        if kind == "span":
            args = dict(_jsonable(r.get("args", {})))
            args["run_id"] = r.get("run_id")
            args["span_id"] = r.get("span_id")
            if r.get("parent_id") is not None:
                args["parent_id"] = r["parent_id"]
            if r.get("error") is not None:
                args["error"] = r["error"]
            dev = device_of_span_args(args)
            events.append({
                "name": r["name"], "ph": "X", "pid": 1,
                "tid": (
                    dev_tid_of[dev] if dev is not None
                    else tid_of.get(r.get("tid"), 0)
                ),
                "ts": int(r["ts_us"]), "dur": int(r["dur_us"]),
                "args": args,
            })
        elif kind == "event":
            args = dict(_jsonable(r.get("args", {})))
            args["run_id"] = r.get("run_id")
            events.append({
                "name": r["name"], "ph": "i", "pid": 1,
                "tid": tid_of.get(r.get("tid"), 0),
                "ts": int(r["ts_us"]), "s": "t",
                "args": args,
            })
        elif kind == "counter":
            events.append({
                "name": r["name"], "ph": "C", "pid": 1, "tid": 0,
                "ts": int(r["ts_us"]),
                "args": {"value": float(r["value"])},
            })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"run_id": run_id},
    }


def validate_chrome_trace(doc: Any) -> List[str]:
    """Schema check of a Chrome-trace document; returns violation
    strings (empty = valid). Checks the invariants the Perfetto /
    chrome://tracing loaders rely on: a ``traceEvents`` list whose every
    event carries a string ``name``, a known ``ph``, integer
    ``pid``/``tid``, a numeric non-negative ``ts`` (except metadata),
    a non-negative ``dur`` on complete (``X``) events, an ``args.name``
    on metadata events, and a numeric counter value on ``C`` events."""
    bad: List[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, not an object"]
    ev = doc.get("traceEvents")
    if not isinstance(ev, list):
        return ["traceEvents missing or not a list"]
    for i, e in enumerate(ev):
        where = f"traceEvents[{i}]"
        if not isinstance(e, dict):
            bad.append(f"{where}: not an object")
            continue
        name = e.get("name")
        if not isinstance(name, str) or not name:
            bad.append(f"{where}: missing/empty name")
        ph = e.get("ph")
        if ph not in _PHASES:
            bad.append(f"{where}: unknown phase {ph!r}")
            continue
        for key in ("pid", "tid"):
            if not isinstance(e.get(key), int):
                bad.append(f"{where}: {key} missing or not an int")
        if ph != "M":
            ts = e.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                bad.append(f"{where}: ts missing/negative")
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                bad.append(f"{where}: X event without non-negative dur")
        if ph == "M":
            args = e.get("args")
            if not (isinstance(args, dict)
                    and isinstance(args.get("name"), str)):
                bad.append(f"{where}: metadata event without args.name")
        if ph == "C":
            args = e.get("args")
            if not isinstance(args, dict) or not all(
                isinstance(v, (int, float)) and not isinstance(v, bool)
                for v in args.values()
            ) or not args:
                bad.append(f"{where}: counter event without numeric args")
        if ph == "i" and e.get("s") not in (None, "t", "p", "g"):
            bad.append(f"{where}: instant scope {e.get('s')!r} invalid")
    return bad


def write_trace_dir(directory: str, tracer) -> Dict[str, str]:
    """Write one trace directory: ``events.jsonl`` (compact rows),
    ``trace.json`` (Chrome trace), ``meta.json`` (run_id + counts).
    Returns the written paths keyed by role."""
    os.makedirs(directory, exist_ok=True)
    records = tracer.events
    jsonl_path = os.path.join(directory, EVENTS_JSONL)
    with open(jsonl_path, "w") as f:
        for r in records:
            f.write(json.dumps(_jsonable(r)) + "\n")
    doc = to_chrome_trace(records, run_id=tracer.run_id)
    trace_path = os.path.join(directory, TRACE_JSON)
    with open(trace_path, "w") as f:
        json.dump(doc, f)
    counts: Dict[str, int] = {}
    for r in records:
        counts[r.get("type", "?")] = counts.get(r.get("type", "?"), 0) + 1
    meta_path = os.path.join(directory, META_JSON)
    meta = {"run_id": tracer.run_id, "counts": counts}
    dropped = getattr(tracer, "dropped", 0)
    if dropped:
        # No silent caps: a bounded buffer that rolled off old records
        # says so in the trace it wrote.
        meta["dropped_records"] = dropped
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    return {"events": jsonl_path, "trace": trace_path, "meta": meta_path}


def load_events(directory: str) -> List[Dict[str, Any]]:
    """Read a trace directory's ``events.jsonl`` back into record rows
    (what ``tools/trace.py`` summarizes)."""
    path = os.path.join(directory, EVENTS_JSONL)
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
