"""Global placement: one audited scheduler over the calibrated cost
model (docs/placement.md).

:mod:`keystone_tpu.placement.engine` prices every resource decision —
solver/storage plan, mesh layout, image-ingest tier, replica count,
brownout rung, zoo residency/eviction — from the same weight families
and emits the unified ``placement.decision`` audit stream.
:mod:`keystone_tpu.placement.planner` replays a recorded trace through
that stream to answer capacity what-ifs (``bin/plan``).
"""

from keystone_tpu.placement.engine import (
    ALL_KINDS,
    KIND_BROWNOUT,
    KIND_IMAGE_TIER,
    KIND_LIFECYCLE,
    KIND_MESH,
    KIND_REPLICAS,
    KIND_SOLVER,
    KIND_ZOO_EVICT,
    KIND_ZOO_PAGE_IN,
    PLACEMENT_EVENT,
    PlacementChoice,
    PlacementEngine,
    active_family,
)
from keystone_tpu.placement.planner import CapacityPlanner, decision_rows

__all__ = [
    "ALL_KINDS",
    "KIND_BROWNOUT",
    "KIND_IMAGE_TIER",
    "KIND_LIFECYCLE",
    "KIND_MESH",
    "KIND_REPLICAS",
    "KIND_SOLVER",
    "KIND_ZOO_EVICT",
    "KIND_ZOO_PAGE_IN",
    "PLACEMENT_EVENT",
    "PlacementChoice",
    "PlacementEngine",
    "active_family",
    "CapacityPlanner",
    "decision_rows",
]
