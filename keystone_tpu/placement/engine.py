"""The global placement engine: one audited scheduler over the
calibrated cost model.

Every resource decision this repo makes — which solver/storage plan an
estimator runs (ops/learning/cost.py), which mesh layout a fit shards
over, which image-ingest tier a dataset lands in, how many serving
replicas stay up, which brownout rung the plane sheds at, which zoo
tenant pages in or is evicted — is a *placement* of work onto priced
resources.  Historically each site carried its own argmin and its own
audit shape; the engine folds them onto one template:

* a candidate is a dict with a ``label`` and a predicted ``cost_s``
  (``float("inf")`` marks infeasible) plus whatever site-specific
  fields make the audit legible (``resident_bytes``, ``host_ok``, …);
* the winner of a priced decision is the FIRST minimum —
  ``int(np.argmin)`` semantics — so adapting a legacy site preserves
  its recorded tie-breaks bit for bit;
* every decision, argmin-chosen (:meth:`PlacementEngine.decide`) or
  policy-chosen (:meth:`PlacementEngine.audit`, for sites like the
  autoscaler whose winner is a threshold policy that the engine prices
  for the record), emits one ``placement.decision`` instant event
  carrying ``candidates`` / ``winner`` / ``reason`` /
  ``weights_family`` — the same back-annotatable shape as
  ``cost.decision`` (obs/calibrate.py's ``join_decisions`` reads both
  event names and stamps measured outcomes onto either).

Decision kinds are namespaced ``placement.*`` strings (``KIND_*``
below), deliberately disjoint from the ``cost.decision`` kinds in
``obs.calibrate.CALIBRATED_DECISIONS``, so the calibration joiner can
never double-count a legacy row and its placement mirror as two
decisions of the same kind.

This module resolves the active weight family from the environment
without importing the cost model (the autoscaler watchdog thread and
the zoo page lane stamp provenance from here, and must not drag jax
onto control-plane threads); the pricing helpers that DO need the
weights (:meth:`PlacementEngine.price_page_in`) import cost lazily,
matching the zoo's existing inline-import discipline.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from keystone_tpu import obs

# The unified audit stream every placement decision lands on.
PLACEMENT_EVENT = "placement.decision"

# Decision kinds — namespaced so they can never collide with the
# cost.decision kinds calibrate.py already joins ("least_squares_solver",
# "calibration_sweep", "mesh_layout").
KIND_SOLVER = "placement.solver"
KIND_MESH = "placement.mesh_layout"
KIND_IMAGE_TIER = "placement.image_tier"
KIND_REPLICAS = "placement.replica_count"
KIND_BROWNOUT = "placement.brownout"
KIND_ZOO_EVICT = "placement.zoo_evict"
KIND_ZOO_PAGE_IN = "placement.zoo_page_in"
KIND_LIFECYCLE = "placement.lifecycle"

ALL_KINDS = (
    KIND_SOLVER,
    KIND_MESH,
    KIND_IMAGE_TIER,
    KIND_REPLICAS,
    KIND_BROWNOUT,
    KIND_ZOO_EVICT,
    KIND_ZOO_PAGE_IN,
    KIND_LIFECYCLE,
)

_INF = float("inf")


def active_family() -> str:
    """Name of the weight family ``KEYSTONE_COST_WEIGHTS`` selects.

    Mirrors ``cost.weights_family_name()`` — "tpu" (the default), "ec2",
    or "calibrated" — without importing the cost module (and therefore
    jax), so control-plane threads can stamp provenance cheaply.  An
    unparseable spec maps to "custom" rather than raising: provenance
    stamping must never take down a decision site.
    """
    raw = (os.environ.get("KEYSTONE_COST_WEIGHTS") or "").strip()
    if not raw:
        return "tpu"
    lowered = raw.lower()
    if lowered in ("tpu", "ec2"):
        return lowered
    if lowered.startswith("calibrated:"):
        return "calibrated"
    return "custom"


@dataclass(frozen=True)
class PlacementChoice:
    """What :meth:`PlacementEngine.decide` resolved: the winning
    candidate's index/label, the reason string recorded on the audit
    event, and the outcome ref a caller stamps measured seconds onto."""

    kind: str
    winner: str
    index: int
    reason: str
    ref: Optional[obs.CostOutcomeRef] = field(default=None, compare=False)


class PlacementEngine:
    """Prices candidates, picks (or records) a winner, and emits the
    unified ``placement.decision`` audit event.

    ``weights_family`` defaults to the env-resolved family; adapter
    sites that computed costs under explicitly-passed weights override
    it with "custom" to keep provenance honest.  ``metrics`` is an
    optional :class:`obs.MetricsRegistry` for the ``placement.*``
    counters in the metric catalogue.
    """

    def __init__(self, weights_family: Optional[str] = None,
                 metrics: Optional[Any] = None):
        self.weights_family = (
            weights_family if weights_family is not None else active_family()
        )
        self._metrics = metrics

    # ------------------------------------------------------------------
    # decisions

    def decide(self, kind: str, candidates: Sequence[Dict[str, Any]], *,
               context: Optional[Dict[str, Any]] = None,
               fallback: Optional[str] = None,
               reason: str = "argmin") -> PlacementChoice:
        """Pick the first-minimum ``cost_s`` candidate and audit it.

        ``cost_s`` of ``float("inf")`` (or ``None``) marks a candidate
        infeasible.  When every candidate is infeasible the engine
        applies ``fallback``: ``"least_resident"`` picks the smallest
        ``resident_bytes`` (first on ties — the legacy
        ``least_resident_fallback`` semantics of cost.py's optimizer);
        ``None`` raises ``ValueError`` (the legacy mesh/image-tier
        behaviour, where the caller owns the error message and raises
        before consulting the engine).
        """
        if not candidates:
            raise ValueError(f"{kind}: no candidates to place")
        costs = [self._cost_of(c) for c in candidates]
        if all(math.isinf(c) for c in costs):
            if fallback == "least_resident":
                index = min(
                    range(len(candidates)),
                    key=lambda i: float(candidates[i].get("resident_bytes", _INF)),
                )
                reason = "least_resident_fallback"
            else:
                labels = ", ".join(str(c.get("label")) for c in candidates)
                raise ValueError(f"{kind}: every candidate infeasible: {labels}")
        else:
            # First minimum — identical to int(np.argmin(costs)).
            index = min(range(len(costs)), key=costs.__getitem__)
        winner = str(candidates[index].get("label"))
        ref = self._emit(kind, winner, candidates, reason, context)
        return PlacementChoice(kind=kind, winner=winner, index=index,
                               reason=reason, ref=ref)

    def audit(self, kind: str, winner: str,
              candidates: Sequence[Dict[str, Any]], *, reason: str,
              context: Optional[Dict[str, Any]] = None
              ) -> Optional[obs.CostOutcomeRef]:
        """Record a policy-chosen winner on the unified stream.

        For sites whose choice is NOT a cost argmin (autoscaler
        thresholds, zoo eviction scoring, lifecycle gates): the policy
        keeps the wheel, the engine prices the candidates it considered
        and writes the same audit shape, so ``bin/trace --decisions``
        and the capacity planner see one stream.
        """
        return self._emit(kind, winner, candidates, reason, context)

    # ------------------------------------------------------------------
    # pricing helpers

    def price_page_in(self, resident_bytes: int) -> float:
        """Predicted seconds to page a zoo tenant's ``resident_bytes``
        back into residency under the active weight family:
        ``mem_weight * zoo_page_overhead() * bytes`` (decode + CRC +
        rebuild run at overhead x the sequential-touch rate).  Imports
        the cost model lazily — see the module docstring.
        """
        from keystone_tpu.ops.learning.cost import active_weights, zoo_page_overhead

        _, mem_w, _ = active_weights()
        return float(mem_w) * float(zoo_page_overhead()) * float(resident_bytes)

    @staticmethod
    def price_queue_residence(queue_depth: float, outstanding: float,
                              replicas: int, service_estimate_s: float) -> float:
        """Predicted seconds of queue residence at a candidate replica
        count: the work in flight divided across replicas, scaled by the
        per-request service estimate.  A deliberately simple M/M/c-shaped
        proxy — the autoscaler's audit pricing, not its trigger."""
        backlog = max(float(queue_depth), 0.0) + max(float(outstanding), 0.0)
        return float(service_estimate_s) * backlog / max(int(replicas), 1)

    # ------------------------------------------------------------------
    # internals

    @staticmethod
    def _cost_of(candidate: Dict[str, Any]) -> float:
        cost = candidate.get("cost_s")
        if cost is None:
            return _INF
        return float(cost)

    def _emit(self, kind: str, winner: str,
              candidates: Sequence[Dict[str, Any]], reason: str,
              context: Optional[Dict[str, Any]]) -> Optional[obs.CostOutcomeRef]:
        normalized = [self._normalize(c) for c in candidates]
        infeasible = sum(1 for c in normalized if not c.get("feasible", False))
        if self._metrics is not None:
            self._metrics.counter(obs.METRIC_PLACEMENT_DECISIONS).add()
            if infeasible:
                self._metrics.counter(
                    obs.METRIC_PLACEMENT_INFEASIBLE).add(infeasible)
        obs.flight_note(
            "placement", kind, winner=winner, reason=reason,
            candidates=len(normalized), family=self.weights_family,
        )
        tracer = obs.active_tracer()
        if tracer is None:
            return None
        record = tracer.event(
            PLACEMENT_EVENT,
            decision=kind,
            winner=winner,
            reason=reason,
            candidates=normalized,
            weights_family=self.weights_family,
            **dict(context or {}),
        )
        return obs.CostOutcomeRef(tracer, record)

    @staticmethod
    def _normalize(candidate: Dict[str, Any]) -> Dict[str, Any]:
        """Audit-shape a candidate: infeasible cost becomes ``None``
        (JSON-clean, matching ``cost.decision``), and ``feasible`` is
        derived from the cost when the site didn't set it explicitly."""
        out = dict(candidate)
        cost = out.get("cost_s")
        if cost is None:
            out.setdefault("feasible", False)
            return out
        cost = float(cost)
        if math.isinf(cost):
            out["cost_s"] = None
            out.setdefault("feasible", False)
        else:
            out["cost_s"] = cost
            out.setdefault("feasible", True)
        return out
