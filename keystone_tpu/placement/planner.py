"""Trace-driven capacity planner: replay a recorded trace through the
placement engine's audit stream and answer what-if questions before any
hardware moves.

The raw material is what ``obs.tracing`` already writes — the six
decision streams (``cost.decision``, ``placement.decision``,
``autoscale.decision``, ``zoo.decision``, ``lifecycle.decision``, plus
the mesh rows riding on ``cost.decision``) and the ``serving.batch``
spans.  Because every decision event records its full candidate table
(label / predicted ``cost_s`` / feasibility / ``resident_bytes``), the
planner can re-run the engine's first-minimum argmin over the RECORDED
candidates under perturbed constraints without re-pricing anything:

* ``traffic=2x`` scales the queueing model's offered load and reports
  the predicted p99 shift against the measured baseline;
* ``hbm=0.5x`` re-applies the feasibility cut (``resident_bytes``
  against the scaled ``hbm_budget_bytes`` each decision recorded) and
  re-argmins, reporting which winners flip;
* ``tenants=+1`` prices the added paging churn from the calibrated
  ``zoo_page_overhead`` family against the trace's measured page-ins;
* ``mesh=8x1`` compares the requested layout's recorded candidate cost
  against the recorded winner's.

Fidelity first: :meth:`CapacityPlanner.fidelity` replays every argmin
decision at 1x and checks the recorded winner reproduces bit for bit,
and compares predicted-vs-measured seconds on every stamped outcome —
the same ``|ln(pred/measured)|`` yardstick, and the same
``DEFAULT_DRIFT_THRESHOLD`` bound, as the calibration plane's drift
gate.  A planner whose 1x replay cannot reproduce the past has no
business predicting the future.

Every what-if row is self-auditing: it carries ``num_decisions``, the
``weights_family`` provenance string, a measured baseline in the same
dict, and an ``assumptions`` list naming the model's simplifications
(bench.py's ``_whatif_violations`` enforces the first three on any dict
that claims a prediction).
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

from keystone_tpu.obs.calibrate import DEFAULT_DRIFT_THRESHOLD

#: The event names the planner (and ``bin/trace --decisions``) merges
#: into one chronological stream.
DECISION_EVENT_NAMES = (
    "cost.decision",
    "placement.decision",
    "autoscale.decision",
    "zoo.decision",
    "lifecycle.decision",
)

_SERVING_SPAN = "serving.batch"
_INF = float("inf")
_EPS = 1e-9

# Queue-residence predictions saturate here: an occupancy model fed by
# discrete scale-action snapshots cannot resolve loads beyond ~100x.
_MAX_AMPLIFICATION = 100.0


def decision_rows(records: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Normalize every decision event in ``records`` into one
    chronological table: ``ts_us`` / ``stream`` / ``kind`` / ``winner``
    / ``reason`` / ``weights_family`` / ``candidates`` (+ the raw
    ``args`` for stream-specific fields).  This is the merged view
    ``bin/trace --decisions`` renders and the planner replays."""
    rows: List[Dict[str, Any]] = []
    for rec in records:
        if rec.get("type") != "event":
            continue
        name = rec.get("name")
        if name not in DECISION_EVENT_NAMES:
            continue
        args = rec.get("args") or {}
        if name in ("cost.decision", "placement.decision"):
            kind = args.get("decision")
            winner = args.get("winner")
            reason = args.get("reason")
        else:
            action = args.get("action")
            kind = f"{name.split('.')[0]}.{action}"
            winner = args.get("winner") or args.get("tenant") or action
            reason = args.get("reason")
        family = args.get("weights_family")
        if family is None:
            family = (args.get("weights") or {}).get("family")
        rows.append({
            "ts_us": int(rec.get("ts_us") or 0),
            "stream": name,
            "kind": kind,
            "winner": winner,
            "reason": reason,
            "weights_family": family,
            "candidates": list(args.get("candidates") or []),
            "args": args,
        })
    rows.sort(key=lambda r: r["ts_us"])
    return rows


def _percentile(sorted_vals: Sequence[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    pos = (len(sorted_vals) - 1) * q
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac)


def _abs_log_error(predicted: Optional[float],
                   measured: Optional[float]) -> Optional[float]:
    if predicted is None or measured is None:
        return None
    return abs(math.log(max(float(predicted), _EPS) /
                        max(float(measured), _EPS)))


def parse_whatif(spec: str) -> Tuple[str, Any]:
    """Parse one ``--whatif`` spec: ``traffic=2x`` | ``hbm=0.5x`` |
    ``tenants=+1`` | ``mesh=8x1``."""
    key, sep, val = spec.partition("=")
    key = key.strip().lower()
    val = val.strip()
    if not sep or not val:
        raise ValueError(f"what-if spec needs key=value, got {spec!r}")
    if key in ("traffic", "hbm"):
        return key, float(val[:-1] if val.lower().endswith("x") else val)
    if key == "tenants":
        return key, int(val.lstrip("+"))
    if key == "mesh":
        p, sep2, q = val.lower().partition("x")
        if not sep2:
            raise ValueError(f"mesh what-if wants PxQ (e.g. 8x1), got {val!r}")
        return key, f"mesh[data={int(p)},model={int(q)}]"
    raise ValueError(
        f"unknown what-if {key!r} (have: traffic, hbm, tenants, mesh)")


class CapacityPlanner:
    """Replays one recorded trace's decision streams; see the module
    docstring for the model and its honesty constraints."""

    def __init__(self, records: Sequence[Dict[str, Any]],
                 drift_threshold: float = DEFAULT_DRIFT_THRESHOLD):
        self.records = list(records)
        self.rows = decision_rows(self.records)
        self.drift_threshold = float(drift_threshold)
        self.batch_latencies_s = sorted(
            r["dur_us"] / 1e6 for r in self.records
            if r.get("type") == "span" and r.get("name") == _SERVING_SPAN
            and r.get("dur_us") is not None
        )
        # Occupancy snapshots ride on the autoscale stream's inputs
        # (replicas / queue_depth / outstanding at each action).
        self.occupancy = [
            {
                "ts_us": row["ts_us"],
                "replicas": int(inputs.get("replicas") or 0),
                "queue_depth": float(inputs.get("queue_depth") or 0.0),
                "outstanding": float(inputs.get("outstanding") or 0.0),
            }
            for row in self.rows if row["stream"] == "autoscale.decision"
            for inputs in [row["args"].get("inputs") or {}]
        ]

    # ------------------------------------------------------------------
    # provenance / baseline

    def weights_family(self) -> str:
        families = Counter(
            row["weights_family"] for row in self.rows
            if row["weights_family"])
        if not families:
            return "unknown"
        return families.most_common(1)[0][0]

    def baseline(self) -> Dict[str, Any]:
        lat = self.batch_latencies_s
        return {
            "num_decisions": len(self.rows),
            "weights_family": self.weights_family(),
            "num_batches": len(lat),
            "measured_p50_s": _percentile(lat, 0.50),
            "measured_p99_s": _percentile(lat, 0.99),
            "replicas_peak": max(
                (p["replicas"] for p in self.occupancy), default=0),
            "queue_peak": max(
                (p["queue_depth"] for p in self.occupancy), default=0.0),
            "outstanding_peak": max(
                (p["outstanding"] for p in self.occupancy), default=0.0),
        }

    # ------------------------------------------------------------------
    # 1x fidelity — the planner's admission ticket

    def fidelity(self) -> Dict[str, Any]:
        """Replay every recorded argmin decision over its RECORDED
        candidates and check the winner reproduces; compare predicted vs
        measured seconds wherever an outcome was stamped."""
        replayed = reproduced = 0
        mismatches: List[Dict[str, Any]] = []
        errors: List[float] = []
        for row in self.rows:
            if row["stream"] not in ("cost.decision", "placement.decision"):
                continue
            cands = row["candidates"]
            if cands and row["reason"] in ("argmin", "least_resident_fallback"):
                winner = self._re_argmin(cands)
                replayed += 1
                if winner == row["winner"]:
                    reproduced += 1
                else:
                    mismatches.append({
                        "kind": row["kind"], "recorded": row["winner"],
                        "replayed": winner,
                    })
            outcome = row["args"].get("outcome") or {}
            measured = outcome.get("measured_s")
            predicted = self._winner_cost(row)
            err = _abs_log_error(predicted, measured)
            if err is not None:
                errors.append(err)
        return {
            "num_decisions": len(self.rows),
            "num_replayed": replayed,
            "num_reproduced": reproduced,
            "mismatches": mismatches,
            "num_outcomes": len(errors),
            "max_abs_log_error": max(errors) if errors else None,
            "drift_threshold": self.drift_threshold,
            "weights_family": self.weights_family(),
        }

    # ------------------------------------------------------------------
    # the queueing model (traffic what-ifs)

    def predict_p99_s(self, traffic: float = 1.0) -> Optional[float]:
        """Predicted tail latency at ``traffic`` x the recorded offered
        load: per-batch service floor (measured p50) amplified by queue
        residence — backlog spread across the replicas the trace
        actually reached.  Deliberately coarse (see ``assumptions`` on
        every what-if row); its job is ranking what-ifs against a
        measured baseline inside the calibration plane's error bars,
        not nanosecond forecasting."""
        service = _percentile(self.batch_latencies_s, 0.50)
        if service is None:
            return None
        base = self.baseline()
        backlog = base["queue_peak"] + base["outstanding_peak"]
        replicas = max(base["replicas_peak"], 1)
        amplification = 1.0 + float(traffic) * backlog / replicas
        return service * min(amplification, _MAX_AMPLIFICATION)

    # ------------------------------------------------------------------
    # what-ifs

    def whatif(self, key: str, value: Any) -> Dict[str, Any]:
        if key == "traffic":
            return self.whatif_traffic(float(value))
        if key == "hbm":
            return self.whatif_hbm(float(value))
        if key == "tenants":
            return self.whatif_tenants(int(value))
        if key == "mesh":
            return self.whatif_mesh(str(value))
        raise ValueError(f"unknown what-if {key!r}")

    def whatif_traffic(self, multiplier: float) -> Dict[str, Any]:
        base = self.baseline()
        p99_1x = self.predict_p99_s(1.0)
        p99_m = self.predict_p99_s(multiplier)
        return {
            "whatif": f"traffic={multiplier:g}x",
            "num_decisions": base["num_decisions"],
            "weights_family": base["weights_family"],
            "measured_p99_s": base["measured_p99_s"],
            "predicted_p99_s": p99_m,
            "predicted_p99_1x_s": p99_1x,
            "abs_log_error_1x": _abs_log_error(p99_1x, base["measured_p99_s"]),
            "replicas_peak": base["replicas_peak"],
            "assumptions": [
                "offered load scales backlog linearly; replica count "
                "capped at the trace's recorded peak",
                "per-batch service floor = measured p50",
            ],
        }

    def whatif_hbm(self, scale: float) -> Dict[str, Any]:
        base = self.baseline()
        replayed = 0
        changed: List[Dict[str, Any]] = []
        for row in self.rows:
            if row["stream"] not in ("cost.decision", "placement.decision"):
                continue
            budget = row["args"].get("hbm_budget_bytes")
            cands = row["candidates"]
            if not cands or not budget:
                continue
            replayed += 1
            winner = self._re_argmin(cands, budget_bytes=float(budget) * scale)
            if winner != row["winner"]:
                changed.append({
                    "kind": row["kind"], "recorded": row["winner"],
                    "predicted": winner,
                })
        return {
            "whatif": f"hbm={scale:g}x",
            "num_decisions": base["num_decisions"],
            "weights_family": base["weights_family"],
            "measured_p99_s": base["measured_p99_s"],
            "measured_num_replayed": replayed,
            "whatif_changed_winners": len(changed),
            "changed": changed,
            "assumptions": [
                "recorded candidate costs held fixed; only the "
                "resident_bytes-vs-budget feasibility cut moves",
            ],
        }

    def whatif_tenants(self, extra: int) -> Dict[str, Any]:
        base = self.baseline()
        page_bytes: List[float] = []
        page_measured: List[float] = []
        for row in self.rows:
            if row["kind"] == "placement.zoo_page_in":
                for c in row["candidates"]:
                    if c.get("resident_bytes"):
                        page_bytes.append(float(c["resident_bytes"]))
                measured = (row["args"].get("outcome") or {}).get("measured_s")
                if measured:
                    page_measured.append(float(measured))
            elif row["kind"] == "zoo.page_in":
                inputs = row["args"].get("inputs") or {}
                if inputs.get("resident_bytes"):
                    page_bytes.append(float(inputs["resident_bytes"]))
                if inputs.get("page_in_s"):
                    page_measured.append(float(inputs["page_in_s"]))
        out: Dict[str, Any] = {
            "whatif": f"tenants=+{extra}",
            "num_decisions": base["num_decisions"],
            "weights_family": base["weights_family"],
            "measured_p99_s": base["measured_p99_s"],
            "num_page_ins": len(page_measured),
            "measured_page_in_p50_s": _percentile(sorted(page_measured), 0.50),
            "assumptions": [
                "each added tenant pages the trace's median tenant "
                "footprint per churn event",
            ],
        }
        if page_bytes:
            from keystone_tpu.placement.engine import PlacementEngine

            sorted_bytes = sorted(page_bytes)
            median_bytes = _percentile(sorted_bytes, 0.50)
            predicted = PlacementEngine().price_page_in(int(median_bytes))
            out["median_tenant_bytes"] = median_bytes
            out["predicted_page_in_s"] = predicted
            out["whatif_added_page_seconds"] = extra * predicted
        else:
            out["note"] = "no zoo paging in trace; nothing to price"
        return out

    def whatif_mesh(self, layout_label: str) -> Dict[str, Any]:
        base = self.baseline()
        ratios: List[float] = []
        recorded_winners: List[str] = []
        for row in self.rows:
            if row["kind"] not in ("mesh_layout", "placement.mesh_layout"):
                continue
            by_label = {c.get("label"): c for c in row["candidates"]}
            want = by_label.get(layout_label)
            won = by_label.get(row["winner"])
            if not want or not won:
                continue
            if want.get("cost_s") and won.get("cost_s"):
                ratios.append(float(want["cost_s"]) / float(won["cost_s"]))
                recorded_winners.append(row["winner"])
        out: Dict[str, Any] = {
            "whatif": f"mesh={layout_label}",
            "num_decisions": base["num_decisions"],
            "weights_family": base["weights_family"],
            "measured_p99_s": base["measured_p99_s"],
            "num_mesh_decisions": len(ratios),
            "assumptions": [
                "requested layout priced from the candidate table each "
                "mesh decision recorded",
            ],
        }
        if ratios:
            out["recorded_winner"] = Counter(
                recorded_winners).most_common(1)[0][0]
            out["whatif_slowdown_x"] = _percentile(sorted(ratios), 0.50)
        else:
            out["note"] = (
                f"no mesh decision in trace priced candidate {layout_label}")
        return out

    def plan(self, whatifs: Sequence[Tuple[str, Any]] = ()) -> Dict[str, Any]:
        return {
            "baseline": self.baseline(),
            "fidelity": self.fidelity(),
            "whatifs": [self.whatif(k, v) for k, v in whatifs],
        }

    # ------------------------------------------------------------------
    # internals

    @staticmethod
    def _re_argmin(candidates: Sequence[Dict[str, Any]],
                   budget_bytes: Optional[float] = None) -> Optional[str]:
        """The engine's first-minimum argmin over RECORDED candidates,
        optionally re-cutting feasibility at a perturbed device budget;
        all-infeasible falls back to least resident_bytes — the same
        deterministic resolution the live sites use."""
        costs = []
        for c in candidates:
            cost = c.get("cost_s")
            feasible = bool(c.get("feasible", cost is not None))
            if budget_bytes is not None and c.get("resident_bytes") is not None:
                feasible = feasible and float(c["resident_bytes"]) <= budget_bytes
            costs.append(float(cost) if (feasible and cost is not None)
                         else _INF)
        if not costs:
            return None
        if all(math.isinf(x) for x in costs):
            index = min(
                range(len(candidates)),
                key=lambda i: float(candidates[i].get("resident_bytes", _INF)),
            )
        else:
            index = min(range(len(costs)), key=costs.__getitem__)
        return candidates[index].get("label")

    @staticmethod
    def _winner_cost(row: Dict[str, Any]) -> Optional[float]:
        for c in row["candidates"]:
            if c.get("label") == row["winner"]:
                return c.get("cost_s")
        return None
