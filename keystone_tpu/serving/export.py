"""Export a FittedPipeline as an online-serving apply plan.

The offline world applies a fitted pipeline to whole datasets; serving
applies it to streams of single datums under a latency budget. The export
step does everything expensive ONCE, ahead of traffic:

  1. **Apply-only subgraph.** A :class:`FittedPipeline` is already the
     apply-only subgraph of the fitted DAG — every estimator was executed
     at ``fit()`` time and replaced by its fitted transformer. Export
     re-validates that invariant (``TransformerGraph.from_graph``) so a
     hand-built graph smuggling an ``EstimatorOperator`` or
     ``DelegatingOperator`` fails at export, not mid-request.
  2. **Optimizer reuse.** The existing whole-pipeline fusion passes
     (StageFusionRule, GatherFusionRule — workflow/fusion.py) run on the
     apply graph. Chains the offline fit never fused (the model node and
     anything downstream of it were DelegatingOperators during
     optimization) collapse here: the MNIST plan becomes ONE program —
     packed-FFT featurize → flat GEMM → argmax.
  3. **Weight pinning.** Operator device arrays are ``jax.device_put``
     onto the serving device so the warm path never re-uploads weights.
  4. **Bucketed pre-compilation.** The composed apply function is
     AOT-compiled at a fixed set of padding buckets (powers of two up to
     ``max_batch``), keyed by bucket shape. Warm-path requests NEVER
     trigger a trace: the micro-batcher pads each coalesced batch to the
     smallest bucket that fits and calls a pre-built executable. The
     ``trace_count`` counter makes that property testable.

Pipelines that do not compose to a pure array function (host stages,
multi-input combiners fusion could not collapse) still export: the plan
falls back to per-node batch execution (``compiled == False``) — slower,
but the batching/padding/shedding machinery above it is identical.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.workflow.graph import Graph, SinkId, SourceId
from keystone_tpu.workflow.pipeline import (
    FittedPipeline,
    TransformerGraph,
    compose_apply_fn,
)

__all__ = ["BatchInfo", "ExportedPlan", "export_plan", "plan_fingerprint"]


def plan_fingerprint(graph: Graph, item_shape, dtype,
                     buckets: Optional[Sequence[int]] = None) -> str:
    """Content fingerprint of a serving plan version: a CRC over every
    operator's type + state (weights included, via
    ``durable.fingerprint_token``'s shape/dtype/content-CRC triples)
    AND the graph wiring (per-node dependency lists, sources, sinks —
    the same operators composed in a different order are a different
    function) plus the request signature and the padding-bucket ladder. Buckets
    are part of the identity because they are part of the served bits:
    a plan exported with explicit ``buckets=[1, ...]`` serves singleton
    responses through XLA's batch-1 codepath — a ulp off every other
    batch size (see ``_default_buckets``) — so it must never share a
    fingerprint with the default-bucket export of the same weights.
    Computed ONCE at export (operator state is frozen for serving), it
    is the identity the replicated plane stamps on every response — the
    hot-swap bit-identity contract (docs/reliability.md) is stated per
    fingerprint: any response carrying fingerprint F is bit-identical
    to offline apply under the plan version that exported F, and no
    batch ever mixes versions."""
    import json
    import zlib

    from keystone_tpu.data.durable import fingerprint_token
    from keystone_tpu.workflow.fusion import fused_members

    def state_token(v):
        # Recurse into plain containers BEFORE delegating to
        # fingerprint_token: it degrades a dict/set to its bare type
        # name, which would let two plans differing only in (say) a
        # vocabulary dict share a fingerprint — voiding the
        # per-fingerprint bit-identity contract. Unordered containers
        # sort by token repr so the digest is iteration-order-free.
        if isinstance(v, dict):
            return {"dict": sorted(
                ([state_token(k), state_token(u)] for k, u in v.items()),
                key=repr,
            )}
        if isinstance(v, (set, frozenset)):
            return {"set": sorted((state_token(e) for e in v), key=repr)}
        if isinstance(v, (list, tuple)):
            return [state_token(e) for e in v]
        return fingerprint_token(v)

    ops = []
    for node in sorted(graph.nodes, key=repr):
        op = graph.get_operator(node)
        members = []
        for member in fused_members(op) + [op]:
            state = {
                k: state_token(v)
                for k, v in sorted(getattr(member, "__dict__", {}).items())
                if not k.startswith("_")
            }
            members.append([type(member).__name__, state])
        # The node's WIRING rides beside its operators: the same
        # operator multiset composed in a different order is a
        # different function, and must be a different fingerprint.
        ops.append([
            repr(node),
            [repr(d) for d in graph.get_dependencies(node)],
            members,
        ])
    token = json.dumps(
        {
            "item_shape": list(item_shape),
            "dtype": str(dtype),
            "buckets": list(buckets) if buckets is not None else None,
            "sources": sorted(repr(s) for s in graph.sources),
            "sinks": sorted(
                [repr(k), repr(v)]
                for k, v in graph.sink_dependencies.items()
            ),
            "ops": ops,
        },
        sort_keys=True, default=str,
    )
    return f"{zlib.crc32(token.encode()) & 0xFFFFFFFF:08x}"


def _default_buckets(max_batch: int) -> List[int]:
    """Powers of two up to (and including) max_batch, starting at TWO; a
    non-power-of-two max_batch becomes the final bucket so the full batch
    size is always reachable.

    Bucket 1 is deliberately absent: XLA lowers some kernels (CPU FFT
    among them, measured) through a different codepath at batch 1,
    producing last-ulp differences against every other batch size — one
    bucket-1 dispatch would break the served-vs-offline bit-identity
    contract. A singleton request pads to 2 (one wasted row) and stays
    bitwise faithful; pass explicit ``buckets`` to reclaim that row for
    a pipeline measured stable at batch 1."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if max_batch == 1:
        return [1]
    buckets = []
    b = 2
    while b < max_batch:
        buckets.append(b)
        b <<= 1
    buckets.append(max_batch)
    return buckets


def _pin_operator_arrays(graph: Graph) -> int:
    """Pin every operator's device arrays onto the default serving device
    (committed placement — the warm path never re-uploads weights).
    Conservative by design: only jax.Array attributes (and lists of them,
    the BlockLinearMapper.xs shape) are touched; host-side numpy state is
    left alone so host-path operators keep their numpy semantics. Returns
    the pinned byte count. Runs BEFORE the plan composes/captures any
    closures so the pinned arrays are the ones the program embeds."""
    from keystone_tpu.workflow.fusion import fused_members

    device = jax.devices()[0]
    pinned = 0
    seen = set()
    for node in graph.nodes:
        for op in fused_members(graph.get_operator(node)) + [
            graph.get_operator(node)
        ]:
            if id(op) in seen or not hasattr(op, "__dict__"):
                continue
            seen.add(id(op))
            for k, v in list(op.__dict__.items()):
                try:
                    if isinstance(v, jax.Array):
                        object.__setattr__(op, k, jax.device_put(v, device))
                        pinned += v.size * v.dtype.itemsize
                    elif isinstance(v, list) and v and all(
                        isinstance(a, jax.Array) for a in v
                    ):
                        object.__setattr__(
                            op, k, [jax.device_put(a, device) for a in v]
                        )
                        pinned += sum(a.size * a.dtype.itemsize for a in v)
                except Exception:
                    continue  # an unpinnable attr never blocks export
    return pinned


@dataclass(frozen=True)
class BatchInfo:
    """How one coalesced batch actually ran."""

    batch_size: int
    bucket: int
    pad_fraction: float


class ExportedPlan:
    """A fitted pipeline frozen for online serving.

    Thread contract: ``apply_batch`` is intended to be called from ONE
    thread (the micro-batcher's worker owns all device interaction —
    the same single-JAX-thread discipline as data/prefetch.py); the
    read-only metadata (buckets, trace_count) is safe to read anywhere.
    """

    def __init__(
        self,
        graph: Graph,
        source: SourceId,
        sink: SinkId,
        example: Any,
        max_batch: int = 256,
        buckets: Optional[Sequence[int]] = None,
        precompile: bool = True,
        pin_weights: bool = True,
    ):
        self.graph = graph
        self.source = source
        self.sink = sink
        ex = np.asarray(example)
        self.item_shape = tuple(ex.shape)
        self.dtype = jnp.asarray(ex).dtype
        self.max_batch = int(max_batch)
        self.buckets = sorted(set(
            int(b) for b in (buckets or _default_buckets(self.max_batch))
        ))
        if self.buckets[-1] != self.max_batch:
            raise ValueError(
                f"largest bucket {self.buckets[-1]} != max_batch "
                f"{self.max_batch} — the full batch size must be reachable"
            )
        self.pinned_bytes = _pin_operator_arrays(graph) if pin_weights else 0
        # Version identity, frozen at export (state never changes after):
        # the replicated plane stamps this on every response it serves.
        self.fingerprint = plan_fingerprint(
            graph, self.item_shape, self.dtype, self.buckets
        )

        self._trace_count = 0
        self._trace_lock = threading.Lock()
        composed = compose_apply_fn(graph, source, sink)
        self.compiled = composed is not None
        self._executables: Dict[int, Any] = {}
        if self.compiled:
            def counted(X):
                # Executes only while TRACING (the jitted body is python
                # once per shape) — the warm-path-never-traces test pin.
                with self._trace_lock:
                    self._trace_count += 1
                return composed(X)

            self._jit = jax.jit(counted)
            if precompile:
                self.warm()
        else:
            self._jit = None
            self._fallback = FittedPipeline(graph, source, sink)

    def warm(self) -> "ExportedPlan":
        """Ensure every padding bucket has its pre-built executable (AOT
        warm). A no-op for plans exported with ``precompile=True`` (the
        default — export already built them); for lazily-exported plans
        it backfills every bucket, which is how the replicated plane's
        hot-swap guarantees a new plan is warm at the SAME padding
        buckets *before* it is admitted to traffic — a swap must never
        convert live requests into trace time."""
        if self.compiled:
            for b in self.buckets:
                if b not in self._executables:
                    spec = jax.ShapeDtypeStruct(
                        (b,) + self.item_shape, self.dtype
                    )
                    self._executables[b] = self._jit.lower(spec).compile()
        return self

    @property
    def is_warm(self) -> bool:
        """Every bucket pre-compiled (vacuously true for eager plans)."""
        return not self.compiled or all(
            b in self._executables for b in self.buckets
        )

    @property
    def trace_count(self) -> int:
        return self._trace_count

    def bucket_for(self, m: int) -> int:
        """Smallest pre-compiled bucket that fits m rows."""
        if m < 1 or m > self.max_batch:
            raise ValueError(
                f"batch of {m} outside [1, max_batch={self.max_batch}]"
            )
        for b in self.buckets:
            if b >= m:
                return b
        return self.buckets[-1]  # unreachable given the checks above

    def _pad(self, X: np.ndarray, bucket: int) -> np.ndarray:
        if X.shape[0] == bucket:
            return X
        pad = np.zeros((bucket - X.shape[0],) + self.item_shape, X.dtype)
        return np.concatenate([X, pad], axis=0)

    def _eager_apply(self, Xp: np.ndarray, m: int) -> np.ndarray:
        """Per-node fallback for non-composable plans: the canonical
        FittedPipeline batch walk over the (re-fused) serving graph —
        not a re-implementation, so the two paths can't drift. ``n=m``
        marks the padding rows so row-masking operators keep them
        zeroed."""
        out = self._fallback.apply(Dataset(jnp.asarray(Xp), n=m))
        return np.asarray(out.array if isinstance(out, Dataset) else out)

    def apply_padded(self, Xp) -> np.ndarray:
        """Run one bucket-shaped batch (padding rows included) and return
        the full padded output as numpy (the conversion is the execution
        barrier)."""
        bucket = int(np.shape(Xp)[0])
        if self.compiled:
            executable = self._executables.get(bucket)
            Xd = jnp.asarray(Xp, self.dtype)
            if executable is not None:
                return np.asarray(executable(Xd))
            return np.asarray(self._jit(Xd))  # un-bucketed shape: traces
        return np.asarray(self._eager_apply(np.asarray(Xp), bucket))

    def apply_batch(self, items) -> np.ndarray:
        out, _ = self.apply_batch_info(items)
        return out

    def apply_batch_info(self, items):
        """Serve ``m`` datums: stack, pad to the smallest fitting bucket,
        run the pre-compiled program, mask the padding rows off the
        response. Returns ``(outputs[:m], BatchInfo)``."""
        X = np.stack([np.asarray(x) for x in items]).astype(
            np.dtype(self.dtype), copy=False
        )
        m = X.shape[0]
        bucket = self.bucket_for(m)
        if self.compiled:
            out = self.apply_padded(self._pad(X, bucket))
        else:
            out = self._eager_apply(self._pad(X, bucket), m)
        info = BatchInfo(
            batch_size=m, bucket=bucket, pad_fraction=(bucket - m) / bucket
        )
        return out[:m], info

    def measure_single_request_s(self, reps: int = 10) -> float:
        """Warm min-of-N wall of a bucket-1 request — the single-request
        device+dispatch time the serving bench's p99 acceptance gate is
        stated against."""
        import time

        x = np.zeros(self.item_shape, np.dtype(self.dtype))
        self.apply_batch([x])  # warm (pre-compiled, but page in everything)
        best = float("inf")
        for _ in range(max(reps, 1)):
            t0 = time.perf_counter()
            self.apply_batch([x])
            best = min(best, time.perf_counter() - t0)
        return best


def export_plan(
    fitted: FittedPipeline,
    example_input: Any,
    max_batch: int = 256,
    buckets: Optional[Sequence[int]] = None,
    precompile: bool = True,
    pin_weights: bool = True,
) -> ExportedPlan:
    """Freeze a :class:`FittedPipeline` into an :class:`ExportedPlan`.

    ``example_input`` fixes the per-request shape/dtype every bucket is
    compiled at (a single datum, e.g. one ``(784,)`` image row).

    NOTE: the plan's graph SHARES operator objects with ``fitted``, and
    ``pin_weights=True`` (the default) commits their device arrays to the
    serving device in place — export freezes the pipeline FOR serving.
    Keep using the same fitted object for placement-sensitive offline
    work on other devices only with ``pin_weights=False``.
    """
    if not isinstance(fitted, FittedPipeline):
        raise TypeError(
            f"export_plan needs a FittedPipeline (got {type(fitted).__name__});"
            " call .fit() first — serving never runs estimator fits"
        )
    # Re-validate the transformer-only invariant: estimator state must be
    # frozen (no fit_datasets operator can execute at request time).
    graph = TransformerGraph.from_graph(fitted.transformer_graph)

    # Static verification of the apply plan (workflow/verify.py): no
    # estimator state reachable at request time, and the whole chain must
    # typecheck from the example input's concrete signature — a shape or
    # dtype bug fails HERE with node coordinates, before any bucket is
    # AOT-compiled. KEYSTONE_VERIFY=off disables.
    from keystone_tpu.workflow.verify import verify_apply_graph

    verify_apply_graph(
        graph, fitted.source, fitted.sink, example=example_input,
        context="export_plan apply plan",
    )

    # Reuse the offline optimizer's fusion passes on the apply-only graph.
    # The fit-time optimization couldn't fuse across the (then-unfitted)
    # delegating nodes; here the model IS a transformer and the chain
    # collapses. Prefixes are empty: an exported plan materializes nothing
    # for cross-pipeline reuse — it exists to be a single program.
    from keystone_tpu.workflow.fusion import GatherFusionRule, StageFusionRule

    plan_graph: Graph = graph
    for rule in (StageFusionRule(), GatherFusionRule(), StageFusionRule()):
        plan_graph, _ = rule.apply(plan_graph, {})

    return ExportedPlan(
        plan_graph,
        fitted.source,
        fitted.sink,
        example_input,
        max_batch=max_batch,
        buckets=buckets,
        precompile=precompile,
        pin_weights=pin_weights,
    )
