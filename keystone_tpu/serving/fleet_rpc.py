"""Stdlib-socket RPC for the serving fleet: length-prefixed CRC-checked
frames with deadline propagation (docs/serving.md fleet section).

# lint: jax-clean-module

The fleet router process must be able to run WITHOUT jax (the planes own
all device work), so this module is deliberately stdlib + nothing: no
jax, no numpy requirement of its own (numpy objects travel opaquely
inside pickled payloads), no keystone imports beyond the jax-free fault
harness. The ``jax-clean-module`` lint rule (marker above) enforces
that this file never grows a jax import.

Frame format (network byte order)::

    +--------+----------------+----------------+----------------+
    | magic  | payload length | crc32(payload) | payload bytes  |
    | 4 B    | 4 B unsigned   | 4 B unsigned   | length B       |
    +--------+----------------+----------------+----------------+

``magic = b"KFR1"``. The payload is a pickled dict. The CRC is checked
on EVERY receive — a mismatch raises :class:`FrameCorrupted`, never
yields a corrupt object (the same never-serve-wrong-bits posture as the
zoo's per-tensor CRCs; the plan ship additionally carries per-tensor
CRCs so weight corruption is caught even when framing survives).

Deadline propagation: requests carry ``deadline_ms`` — the REMAINING
deadline budget at send time, recomputed by the router from the
caller's original deadline minus queueing elapsed. The plane enforces
it through its own admission (earliest-deadline shedding), so a request
that burned its budget queueing at the router is shed at the plane door
instead of executing dead work.

Fault site: every client send fires ``fleet.rpc.send``
(:mod:`keystone_tpu.utils.faults`) BEFORE any bytes hit the wire, so an
injected error is always safely retryable (at-most-once: once the frame
is written, the caller must NOT retry — the plane may have executed).
"""

from __future__ import annotations

import logging
import pickle
import socket
import struct
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from keystone_tpu.utils import faults

__all__ = [
    "FrameCorrupted",
    "RpcClient",
    "RpcServer",
    "recv_frame",
    "send_frame",
]

logger = logging.getLogger(__name__)

MAGIC = b"KFR1"
_HEADER = struct.Struct("!4sII")
#: Hard frame bound (64 MiB): a corrupt length field must not allocate
#: unbounded memory before the CRC check can reject the payload.
MAX_FRAME_BYTES = 64 * 1024 * 1024


class FrameCorrupted(RuntimeError):
    """A frame failed its magic/length/CRC check — the connection is
    poisoned and must be closed, never read past."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, obj: Any, fire_fault: bool = False) -> None:
    """Pickle ``obj`` and write one frame. ``fire_fault`` runs the
    ``fleet.rpc.send`` fault site BEFORE any bytes are written, so
    injected errors never leave a half-sent frame (and are therefore
    safely retryable by the client)."""
    if fire_fault:
        faults.maybe_fail(faults.SITE_FLEET_RPC_SEND)
    payload = pickle.dumps(obj, protocol=4)
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(
            f"frame payload {len(payload)} B exceeds {MAX_FRAME_BYTES} B"
        )
    header = _HEADER.pack(MAGIC, len(payload), zlib.crc32(payload))
    sock.sendall(header + payload)


def recv_frame(sock: socket.socket,
               timeout_s: Optional[float] = None) -> Any:
    """Read one frame; verify magic, length bound and CRC; unpickle.
    Raises :class:`FrameCorrupted` on any integrity failure,
    ``socket.timeout`` past ``timeout_s``, ``ConnectionError`` on EOF."""
    sock.settimeout(timeout_s)
    header = _recv_exact(sock, _HEADER.size)
    magic, length, crc = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameCorrupted(f"bad magic {magic!r}")
    if length > MAX_FRAME_BYTES:
        raise FrameCorrupted(f"frame length {length} exceeds bound")
    payload = _recv_exact(sock, length)
    if zlib.crc32(payload) != crc:
        raise FrameCorrupted(
            f"payload CRC mismatch ({length} B frame)"
        )
    return pickle.loads(payload)


class RpcServer:
    """Threaded request/response server over frames: one accept loop,
    one thread per connection, ``handler(dict) -> dict`` per request.

    The handler runs on the connection's thread; an exception inside it
    is converted into ``{"ok": False, "error": "handler_error", ...}``
    so a bad request never kills the connection loop. ``close()`` stops
    the accept loop, closes every live connection and joins all
    threads (lint's thread-join discipline)."""

    def __init__(self, handler: Callable[[Dict[str, Any]], Dict[str, Any]],
                 host: str = "127.0.0.1", port: int = 0):
        self._handler = handler
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._closed = threading.Event()
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []
        self._threads: List[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fleet-rpc-accept", daemon=True
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="fleet-rpc-conn", daemon=True,
            )
            with self._lock:
                self._conns.append(conn)
                self._threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                try:
                    req = recv_frame(conn, timeout_s=None)
                except (ConnectionError, OSError):
                    return
                except FrameCorrupted as e:
                    # Poisoned stream: reply once (best effort) and
                    # drop the connection — never resynchronize past a
                    # failed CRC.
                    try:
                        send_frame(conn, {"ok": False,
                                          "error": "frame_corrupted",
                                          "message": str(e)})
                    except OSError:
                        pass
                    return
                try:
                    resp = self._handler(req)
                except Exception as e:  # noqa: BLE001 — loud, conn survives
                    logger.warning("fleet rpc handler failed: %r", e)
                    resp = {"ok": False, "error": "handler_error",
                            "message": f"{type(e).__name__}: {e}"}
                try:
                    send_frame(conn, resp)
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self, timeout: float = 5.0) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns, threads = list(self._conns), list(self._threads)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self._accept_thread.join(timeout)
        for t in threads:
            t.join(timeout)

    def __enter__(self) -> "RpcServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RpcClient:
    """Pooled request/response client. Thread-safe: concurrent
    ``request()`` calls each borrow (or dial) a connection, so N router
    dispatcher threads drive N parallel in-flight requests to a plane.

    The ``fleet.rpc.send`` fault fires before any bytes are written, so
    ``send_retries`` bounded, paced retries are safe (at-most-once is
    preserved: a frame that hit the wire is NEVER resent — failures
    after the write surface to the caller as connection errors)."""

    def __init__(self, host: str, port: int,
                 connect_timeout_s: float = 5.0,
                 send_retries: int = 3,
                 retry_base_delay_s: float = 0.02):
        self.host, self.port = host, int(port)
        self.connect_timeout_s = float(connect_timeout_s)
        self.send_retries = int(send_retries)
        self.retry_base_delay_s = float(retry_base_delay_s)
        self._lock = threading.Lock()
        self._idle: List[socket.socket] = []
        self._closed = False

    def _dial(self) -> socket.socket:
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout_s
        )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _borrow(self) -> socket.socket:
        with self._lock:
            if self._closed:
                raise ConnectionError("client closed")
            if self._idle:
                return self._idle.pop()
        return self._dial()

    def _give_back(self, sock: socket.socket) -> None:
        with self._lock:
            if not self._closed and len(self._idle) < 32:
                self._idle.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def request(self, obj: Dict[str, Any],
                timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """One round trip. Pre-write failures (dial errors, injected
        ``fleet.rpc.send`` faults) retry up to ``send_retries`` times
        with paced exponential backoff; post-write failures raise
        immediately (at-most-once)."""
        attempt = 0
        while True:
            try:
                sock = self._borrow()
            except OSError as e:
                attempt += 1
                if attempt > self.send_retries:
                    raise ConnectionError(
                        f"dial {self.host}:{self.port} failed after "
                        f"{attempt} attempts: {e}"
                    ) from e
                time.sleep(self.retry_base_delay_s * (2 ** (attempt - 1)))
                continue
            wrote = False
            try:
                send_frame(sock, obj, fire_fault=True)
                wrote = True
                resp = recv_frame(sock, timeout_s=timeout_s)
            except Exception as e:
                try:
                    sock.close()
                except OSError:
                    pass
                if wrote:
                    raise
                # Injected send fault or stale pooled connection: the
                # frame never hit the wire, safe to retry (paced).
                attempt += 1
                if attempt > self.send_retries:
                    raise
                logger.debug("fleet rpc pre-write retry %d: %r", attempt, e)
                time.sleep(self.retry_base_delay_s * (2 ** (attempt - 1)))
                continue
            self._give_back(sock)
            return resp

    def close(self) -> None:
        with self._lock:
            self._closed = True
            idle, self._idle = self._idle, []
        for sock in idle:
            try:
                sock.close()
            except OSError:
                pass

    def __enter__(self) -> "RpcClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
