"""SLO-closed-loop autoscaler: measured capacity for the serving plane
(ROADMAP item 3 — "an operator still picks ``--replicas`` by hand").

KeystoneML left resource sizing entirely to the Spark operator; the
serving plane here can already replicate, fail over, hot-swap, and state
a live SLO verdict — this module closes the loop by making replica count
a MEASURED, self-correcting decision driven by the same burn-rate state
machine the verdict comes from:

  - **The control thread** (:class:`Autoscaler`) is watchdog-style:
    numpy-free, jax-off-thread, one bounded tick per interval. Each tick
    consumes the :class:`~keystone_tpu.obs.slo.SLOTracker` state machine
    (``evaluate()`` + the light ``burn_rates()`` read) plus the plane's
    queue-depth/occupancy signals
    (:meth:`~keystone_tpu.serving.replicas.ReplicatedServer.autoscale_signals`)
    and drives the zero-drop elasticity primitives:

      * sustained WARN/BREACH with a rising fast burn →
        :meth:`~ReplicatedServer.add_replica` (bounded by
        ``max_replicas``);
      * sustained OK with idle budget (near-zero queue depth, low
        per-replica occupancy) → :meth:`~ReplicatedServer.remove_replica`
        (bounded by ``min_replicas``).

  - **Hysteresis + cooldowns** match the SLO tracker's discipline: a
    pressure/idle signal must SUSTAIN for its window before any action,
    no two actions land inside ``cooldown_s``, and each action resets
    its sustain timer — so the controller cannot flap (pinned
    deterministically by the fake-clock unit suite).

  - **The brownout ladder** is the wall past ``max_replicas``: when
    scale-up is exhausted and burn keeps rising, the controller climbs
    :data:`~keystone_tpu.serving.replicas.BROWNOUT_STEPS` one named,
    reversible rung per cooldown (widen micro-batch deadlines → shed
    earliest-deadline more aggressively → reject new admissions with a
    fast-fail). Exit is strictly LIFO and gated on RELIEF (occupancy
    idle), NOT on the SLO returning to OK — at the ladder top every
    request is rejected and rejected requests keep the SLO in breach,
    so an OK-gated exit would deadlock the plane in full-reject forever.
    Scale-DOWN stays OK-gated (capacity leaves only when the SLO is
    genuinely healthy and idle).

  - **Every decision is auditable**: each action is a structured
    ``autoscale.decision`` instant event (mirroring ``cost.decision``:
    inputs, thresholds, action, reason), a flight-recorder note, a
    bounded in-memory decision log (``decision_log()`` — ``bin/slo``
    renders it beside the verdict table), and ``autoscale.*`` registry
    metrics the live exporter publishes.

Determinism: the clock is injectable and ``tick()`` is a plain method —
the unit tests drive the whole state machine under a fake clock with no
thread and no sleeps. ``start()``/``close()`` wrap the same tick in a
daemon thread for production use (``run.py serve --autoscale``).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from keystone_tpu import obs
from keystone_tpu.placement.engine import (
    KIND_BROWNOUT,
    KIND_REPLICAS,
    PlacementEngine,
)
from keystone_tpu.obs.metrics import (
    METRIC_AUTOSCALE_BROWNOUT_LEVEL,
    METRIC_AUTOSCALE_DECISIONS,
    METRIC_AUTOSCALE_REPLICAS,
    METRIC_AUTOSCALE_SCALE_DOWNS,
    METRIC_AUTOSCALE_SCALE_UPS,
)
from keystone_tpu.obs.slo import STATE_BREACH, STATE_OK, STATE_WARN
from .replicas import BROWNOUT_STEPS

__all__ = ["AutoscaleDecision", "Autoscaler"]

logger = logging.getLogger("keystone_tpu.serving")

_STATE_RANK = {STATE_OK: 0, STATE_WARN: 1, STATE_BREACH: 2}


@dataclass(frozen=True)
class AutoscaleDecision:
    """One control-loop action, as evidence — the elasticity analogue of
    :class:`~keystone_tpu.obs.tracer.CostDecision`: what the controller
    saw (inputs), what it was configured to do about it (thresholds),
    what it did (action/step), and why (reason). ``ok=False`` records an
    ATTEMPTED action that failed (e.g. a spawn past the restart budget)
    — a failed scale-up is part of the audit trail, not a silent no-op."""

    action: str                 # scale_up | scale_down | brownout_enter |
                                # brownout_exit
    reason: str
    t_s: float                  # controller-clock seconds since start
    ok: bool = True
    step: Optional[str] = None  # the brownout rung, for brownout actions
    inputs: Dict[str, Any] = field(default_factory=dict)
    thresholds: Dict[str, Any] = field(default_factory=dict)
    # The placement-engine audit fields (ISSUE 19): the candidate
    # replica counts / brownout rungs the controller had on the table,
    # the one it took, and the weight family that priced them — the
    # decision-event schema every stream shares.
    winner: Optional[str] = None
    candidates: Sequence[Dict[str, Any]] = field(default_factory=tuple)
    weights_family: Optional[str] = None

    def to_args(self) -> Dict[str, Any]:
        out = {
            "action": self.action,
            "reason": self.reason,
            "ok": self.ok,
            "t_s": self.t_s,
            "inputs": dict(self.inputs),
            "thresholds": dict(self.thresholds),
            "winner": self.winner if self.winner is not None else self.action,
            "candidates": [dict(c) for c in self.candidates],
            "weights_family": self.weights_family,
        }
        if self.step is not None:
            out["step"] = self.step
        return out


class Autoscaler:
    """Drive a :class:`~keystone_tpu.serving.replicas.ReplicatedServer`'s
    elasticity from its SLO tracker (module docstring).

    Knobs:

      - ``min_replicas`` / ``max_replicas``: the capacity bounds the
        controller never crosses.
      - ``tick_interval_s``: control-loop cadence (the thread's pace;
        ``tick()`` itself is cadence-free under test).
      - ``scale_up_sustain_s``: how long pressure (WARN/BREACH + rising
        fast burn) must hold continuously before a scale-up/brownout
        action.
      - ``scale_down_sustain_s``: how long idle (OK + low occupancy)
        must hold before a scale-down; relief (occupancy only) gates
        brownout exits on the same window.
      - ``cooldown_s``: minimum spacing between ANY two actions — the
        no-flapping guarantee the fake-clock suite pins.
      - ``idle_outstanding_per_replica`` / ``idle_queue_depth``: the
        idle-budget definition (occupancy at/below both = idle).
      - ``clock``: injectable monotonic clock (determinism under test).
      - ``metrics``: a registry for the ``autoscale.*`` gauges/counters
        (defaults to the server's own, so the live exporter renders
        them with the serving counters).
    """

    def __init__(
        self,
        server,
        slo,
        min_replicas: int = 1,
        max_replicas: int = 8,
        tick_interval_s: float = 0.25,
        scale_up_sustain_s: float = 1.0,
        scale_down_sustain_s: float = 5.0,
        cooldown_s: float = 2.0,
        idle_outstanding_per_replica: float = 0.5,
        idle_queue_depth: int = 1,
        clock: Callable[[], float] = time.monotonic,
        metrics=None,
        decision_log_len: int = 256,
        service_estimate_s: float = 0.05,
    ):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) < min_replicas "
                f"({min_replicas})"
            )
        if slo is None:
            raise ValueError(
                "Autoscaler needs an SLOTracker — the control loop IS "
                "the burn-rate state machine's consumer"
            )
        self.server = server
        self.slo = slo
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.tick_interval_s = float(tick_interval_s)
        self.scale_up_sustain_s = float(scale_up_sustain_s)
        self.scale_down_sustain_s = float(scale_down_sustain_s)
        self.cooldown_s = float(cooldown_s)
        self.idle_outstanding_per_replica = float(
            idle_outstanding_per_replica
        )
        self.idle_queue_depth = int(idle_queue_depth)
        # The queueing proxy's per-request service scale — used only to
        # PRICE replica-count candidates for the placement audit stream
        # (the triggers stay the burn-rate state machine's).
        self.service_estimate_s = float(service_estimate_s)
        self._clock = clock
        self._t0 = clock()

        self._lock = threading.Lock()
        self._decisions: "deque[Dict[str, Any]]" = deque(
            maxlen=decision_log_len
        )
        self.num_decisions = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.failed_scale_ups = 0
        self.failed_scale_downs = 0
        self.brownout_steps_entered = 0
        self.brownout_steps_exited = 0
        self.ticks = 0
        self.tick_errors = 0
        n0 = server.autoscale_signals()["replicas"]  # live, not evicted
        self.replicas_low = n0
        self.replicas_high = n0

        # Controller state (all touched only from tick() — one ticker at
        # a time, whether the thread or a test).
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._relief_since: Optional[float] = None
        self._last_burn_fast = 0.0
        self._last_action_t = -float("inf")

        reg = metrics if metrics is not None else getattr(
            server, "metrics", None
        )
        self._metrics = reg
        if reg is not None:
            self._g_replicas = reg.gauge(METRIC_AUTOSCALE_REPLICAS)
            self._g_brownout = reg.gauge(METRIC_AUTOSCALE_BROWNOUT_LEVEL)
            self._c_ups = reg.counter(METRIC_AUTOSCALE_SCALE_UPS)
            self._c_downs = reg.counter(METRIC_AUTOSCALE_SCALE_DOWNS)
            self._c_decisions = reg.counter(METRIC_AUTOSCALE_DECISIONS)
            self._g_replicas.set(n0)

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the control loop --------------------------------------------------

    def start(self) -> "Autoscaler":
        """Start the control thread (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return self
            self._thread = threading.Thread(
                target=self._loop,
                name="keystone-serving-autoscaler", daemon=True,
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — controller must survive
                # A control-loop crash must degrade to "no autoscaling",
                # never to a dead plane; count + log, keep ticking.
                self.tick_errors += 1
                logger.warning("autoscaler tick failed: %r", e)

    def close(self, timeout: float = 5.0) -> None:
        """Stop the control thread (joins it). Idempotent. The serving
        plane itself is NOT closed — the controller is an observer with
        actuators, not the plane's owner."""
        self._stop.set()
        if self._thread is not None:  # set once under _lock in start()
            self._thread.join(timeout=timeout)

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- one tick ----------------------------------------------------------

    def tick(self) -> Optional[Dict[str, Any]]:
        """Run one control-loop evaluation; returns the decision record
        when an action was taken (or attempted), else None. Deterministic
        under an injected clock — the whole state machine is pure in
        (clock, SLO window contents, plane signals)."""
        now = self._clock()
        self.ticks += 1

        states = self.slo.evaluate()
        worst = STATE_OK
        for s in states.values():
            if _STATE_RANK.get(s, 0) > _STATE_RANK[worst]:
                worst = s
        burns = self.slo.burn_rates()
        burn_fast = max(
            (b[0] for b in burns.values()), default=0.0
        )
        signals = self.server.autoscale_signals()
        replicas = signals["replicas"]
        self._observe_bounds(replicas)

        # Pressure: the SLO is WARN/BREACH and the fast burn is not
        # falling (a falling burn means the plane is recovering on its
        # own — adding capacity then would overshoot). BREACH counts as
        # pressure regardless of slope: the budget is burning too fast
        # to wait out a dip.
        rising = burn_fast >= self._last_burn_fast - 1e-9
        pressure = worst in (STATE_WARN, STATE_BREACH) and (
            rising or worst == STATE_BREACH
        )
        # Relief: the occupancy side is idle — queues empty, few
        # outstanding reservations per replica. Deliberately SLO-blind:
        # at the brownout ladder top every request is rejected and
        # rejections keep the SLO in breach, so an OK-gated exit would
        # wedge the plane in full-reject forever.
        relief = (
            signals["queue_depth"] <= self.idle_queue_depth
            and signals["outstanding"]
            <= self.idle_outstanding_per_replica * max(replicas, 1)
        )
        # Idle (the scale-DOWN gate): relief AND a healthy verdict —
        # capacity only leaves when the SLO is genuinely OK.
        idle = relief and worst == STATE_OK

        self._pressure_since = (
            (self._pressure_since if self._pressure_since is not None
             else now) if pressure else None
        )
        self._relief_since = (
            (self._relief_since if self._relief_since is not None
             else now) if relief else None
        )
        self._idle_since = (
            (self._idle_since if self._idle_since is not None
             else now) if idle else None
        )
        self._last_burn_fast = burn_fast

        in_cooldown = now - self._last_action_t < self.cooldown_s
        inputs = {
            "state": worst,
            "burn_fast": round(burn_fast, 4),
            "replicas": replicas,
            "queue_depth": signals["queue_depth"],
            "outstanding": signals["outstanding"],
            "brownout_level": signals["brownout_level"],
        }
        if in_cooldown:
            return None

        pressure_sustained = (
            self._pressure_since is not None
            and now - self._pressure_since >= self.scale_up_sustain_s
        )
        if pressure_sustained:
            if replicas < self.max_replicas:
                return self._act_scale_up(now, inputs)
            # Brownout degrades ADMISSION to shed load — entering a rung
            # while the occupancy side is already relieved would be
            # degrading against stale burn evidence (the fast window
            # outlives the storm), and at ladder-top-minus-one it would
            # oscillate against the relief exit below.
            if signals["brownout_level"] < len(BROWNOUT_STEPS) \
                    and not relief:
                return self._act_brownout_enter(now, inputs)
            # Ladder top AND max replicas: nothing left to degrade —
            # fall through, so sustained relief can still unwind the
            # ladder (at reject_admissions the SLO stays in breach from
            # the rejections themselves; pressure must not shadow the
            # only exit).
        if (
            signals["brownout_level"] > 0
            and self._relief_since is not None
            and now - self._relief_since >= self.scale_down_sustain_s
        ):
            return self._act_brownout_exit(now, inputs)
        if (
            self._idle_since is not None
            and now - self._idle_since >= self.scale_down_sustain_s
            and replicas > self.min_replicas
        ):
            return self._act_scale_down(now, inputs)
        return None

    # -- actions -----------------------------------------------------------

    def _act_scale_up(self, now, inputs):
        try:
            index = self.server.add_replica()
        except Exception as e:  # noqa: BLE001 — audited failure
            self.failed_scale_ups += 1
            return self._record(
                now, "scale_up", ok=False,
                reason=f"add_replica failed: {e!r}", inputs=inputs,
            )
        self.scale_ups += 1
        if self._metrics is not None:
            self._c_ups.add(1)
        return self._record(
            now, "scale_up",
            reason=(
                f"sustained {inputs['state']} with rising fast burn "
                f"{inputs['burn_fast']}x for >= "
                f"{self.scale_up_sustain_s:.3g}s"
            ),
            inputs={**inputs, "new_replica_index": index},
        )

    def _act_brownout_enter(self, now, inputs):
        step = self.server.enter_brownout_step()
        if step is None:
            return None
        self.brownout_steps_entered += 1
        return self._record(
            now, "brownout_enter", step=step,
            reason=(
                f"scale-up exhausted at max_replicas="
                f"{self.max_replicas} and burn still "
                f"{inputs['burn_fast']}x — degrading admission"
            ),
            inputs=inputs,
        )

    def _act_brownout_exit(self, now, inputs):
        step = self.server.exit_brownout_step()
        if step is None:
            return None
        self.brownout_steps_exited += 1
        return self._record(
            now, "brownout_exit", step=step,
            reason=(
                f"occupancy relief sustained >= "
                f"{self.scale_down_sustain_s:.3g}s (queue "
                f"{inputs['queue_depth']}, outstanding "
                f"{inputs['outstanding']}) — reverting LIFO"
            ),
            inputs=inputs,
        )

    def _act_scale_down(self, now, inputs):
        try:
            index = self.server.remove_replica()
        except Exception as e:  # noqa: BLE001 — audited failure
            self.failed_scale_downs += 1
            return self._record(
                now, "scale_down", ok=False,
                reason=f"remove_replica failed: {e!r}", inputs=inputs,
            )
        self.scale_downs += 1
        if self._metrics is not None:
            self._c_downs.add(1)
        return self._record(
            now, "scale_down",
            reason=(
                f"sustained OK with idle budget for >= "
                f"{self.scale_down_sustain_s:.3g}s (queue "
                f"{inputs['queue_depth']}, outstanding "
                f"{inputs['outstanding']})"
            ),
            inputs={**inputs, "removed_replica_index": index},
        )

    # -- recording ---------------------------------------------------------

    def _thresholds(self) -> Dict[str, Any]:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "scale_up_sustain_s": self.scale_up_sustain_s,
            "scale_down_sustain_s": self.scale_down_sustain_s,
            "cooldown_s": self.cooldown_s,
            "idle_outstanding_per_replica":
                self.idle_outstanding_per_replica,
            "idle_queue_depth": self.idle_queue_depth,
        }

    def _placement_decision(self, action, step, inputs):
        """The placement-engine view of one control action: the
        neighbouring replica counts (or brownout rungs) as priced
        candidates, and the policy's target as winner. Replica
        candidates carry the queue-residence proxy in seconds
        (``service_estimate_s``-scaled); feasibility is the capacity
        bounds the controller never crosses."""
        replicas = int(inputs.get("replicas") or 0)
        queue = float(inputs.get("queue_depth") or 0.0)
        outstanding = float(inputs.get("outstanding") or 0.0)
        if action in ("scale_up", "scale_down"):
            target = replicas + (1 if action == "scale_up" else -1)
            candidates = [
                {
                    "label": f"replicas={r}",
                    "cost_s": round(PlacementEngine.price_queue_residence(
                        queue, outstanding, r, self.service_estimate_s), 6),
                    "feasible": self.min_replicas <= r <= self.max_replicas,
                    "replicas": r,
                }
                for r in sorted({replicas - 1, replicas, replicas + 1})
                if r >= 1
            ]
            return KIND_REPLICAS, f"replicas={target}", candidates
        level = int(inputs.get("brownout_level") or 0)
        target = level + (1 if action == "brownout_enter" else -1)
        candidates = [
            {
                "label": f"brownout={lv}",
                "cost_s": None,
                "feasible": 0 <= lv <= len(BROWNOUT_STEPS),
                "brownout_level": lv,
                "step": step if lv == target else None,
            }
            for lv in sorted({level, target}) if lv >= 0
        ]
        return KIND_BROWNOUT, f"brownout={target}", candidates

    def _record(self, now, action, reason, ok=True, step=None,
                inputs=None) -> Dict[str, Any]:
        """Make the action auditable everywhere at once: the structured
        ``autoscale.decision`` trace event (the ``cost.decision``
        mirror) plus its ``placement.decision`` counterpart on the
        unified stream, a flight-recorder note, the bounded decision
        log, and the registry counters/gauges — then start the cooldown
        and reset the sustain timers (an action consumes its
        evidence)."""
        inputs = dict(inputs or {})
        engine = PlacementEngine(metrics=self._metrics)
        kind, winner, candidates = self._placement_decision(
            action, step, inputs
        )
        decision = AutoscaleDecision(
            action=action, reason=reason, ok=ok, step=step,
            t_s=round(now - self._t0, 6),
            inputs=inputs, thresholds=self._thresholds(),
            winner=winner, candidates=candidates,
            weights_family=engine.weights_family,
        )
        rec = decision.to_args()
        with self._lock:
            self._decisions.append(rec)
            self.num_decisions += 1
        obs.event("autoscale.decision", **rec)
        engine.audit(
            kind, winner, candidates, reason=reason,
            context={
                "action": action, "ok": ok, "t_s": rec["t_s"],
                "replicas": inputs.get("replicas"),
                "queue_depth": inputs.get("queue_depth"),
                "outstanding": inputs.get("outstanding"),
                "brownout_level": inputs.get("brownout_level"),
            },
        )
        obs.flight_note(
            "autoscale", f"{action}{f':{step}' if step else ''}",
            ok=ok, state=rec["inputs"].get("state"),
            burn_fast=rec["inputs"].get("burn_fast"),
            replicas=rec["inputs"].get("replicas"),
        )
        # One post-action read of the LIVE (non-evicted) count — the
        # same basis tick() scales on — feeds both the gauge and the
        # observed bounds; server.num_replicas would count evicted
        # members into the audit fields.
        live = self.server.autoscale_signals()["replicas"]
        if self._metrics is not None:
            self._c_decisions.add(1)
            self._g_replicas.set(live)
            self._g_brownout.set(self.server.brownout_level)
        self._last_action_t = now
        self._pressure_since = None
        self._idle_since = None
        self._relief_since = None
        self._observe_bounds(live)
        return rec

    def _observe_bounds(self, replicas: int) -> None:
        if replicas:
            self.replicas_low = min(self.replicas_low, replicas)
            self.replicas_high = max(self.replicas_high, replicas)

    # -- reading -----------------------------------------------------------

    def decision_log(self) -> List[Dict[str, Any]]:
        """The bounded in-memory audit trail (newest last)."""
        with self._lock:
            return list(self._decisions)

    def stats(self) -> Dict[str, Any]:
        """The autoscale summary block. Carries ``num_decisions`` and
        the ``min/max_replicas`` bounds in the SAME dict as the
        ``scale_ups``/``scale_downs`` claims — the bench ``make_row``
        audit rule requires exactly that, so this block drops into a
        row as-is."""
        with self._lock:
            decisions = list(self._decisions)
            out = {
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "replicas_low": self.replicas_low,
                "replicas_high": self.replicas_high,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "failed_scale_ups": self.failed_scale_ups,
                "failed_scale_downs": self.failed_scale_downs,
                "brownout_steps_entered": self.brownout_steps_entered,
                "brownout_steps_exited": self.brownout_steps_exited,
                "num_decisions": self.num_decisions,
                "ticks": self.ticks,
                "tick_errors": self.tick_errors,
                "cooldown_s": self.cooldown_s,
            }
        out["brownout_level"] = self.server.brownout_level
        out["brownout_steps"] = list(self.server.brownout_steps)
        out["replicas"] = self.server.autoscale_signals()["replicas"]
        out["decisions"] = decisions[-64:]
        return out
